"""QoS control-plane + observability surfaces: mon qos set/rm/ls,
qos_db map distribution (full + incremental codec), scheduler lane
eviction and O(1) backlog accounting, hot profile re-tagging,
dump_qos_stats, the MMgrReport qos tail, ceph_qos_* prometheus
families, and the qos_wait trace event."""

from __future__ import annotations

import json
import time

from ceph_tpu.msg.encoding import Decoder, Encoder
from ceph_tpu.osd.map_codec import (
    apply_incremental, decode_incremental, decode_osdmap, diff_osdmap,
    encode_incremental, encode_osdmap)
from ceph_tpu.osd.op_queue import ClassInfo, MClockQueue, ShardedOpQueue
from ceph_tpu.osd.osdmap import OSDMap


# -- qos_db distribution ------------------------------------------------------

def test_osdmap_codec_carries_qos_db():
    m = OSDMap(epoch=3)
    m.set_max_osd(2)
    m.qos_db = {"gold": {"reservation": 100.0, "weight": 1.0,
                         "limit": 0.0}}
    got = decode_osdmap(encode_osdmap(m))
    assert got.qos_db == m.qos_db
    # copy() duplicates the db (mon _mutate mutates the copy)
    c = m.copy()
    c.qos_db["silver"] = {"reservation": 0, "weight": 2, "limit": 0}
    assert "silver" not in m.qos_db


def test_incremental_carries_qos_db():
    old = OSDMap(epoch=5)
    old.set_max_osd(2)
    new = old.copy()
    new.epoch = 6
    new.qos_db = {"gold": {"reservation": 50.0, "weight": 1.0,
                           "limit": 0.0}}
    inc = diff_osdmap(old, new)
    assert "qos_db" in inc
    dec = decode_incremental(encode_incremental(inc))
    m = old.copy()
    apply_incremental(m, dec)
    assert m.epoch == 6 and m.qos_db == new.qos_db
    # removal distributes too
    newer = new.copy()
    newer.epoch = 7
    newer.qos_db = {}
    inc2 = decode_incremental(encode_incremental(
        diff_osdmap(new, newer)))
    apply_incremental(m, inc2)
    assert m.qos_db == {}


def test_mon_qos_commands(monkeypatch=None):
    from ceph_tpu.tools.vstart import MiniCluster
    cluster = MiniCluster(n_osds=1, ms_type="loopback").start()
    try:
        cluster.wait_for_osd_count(1)
        client = cluster.client(timeout=15.0)
        rc, out = client.mon_command(
            {"prefix": "qos set", "tenant": "gold",
             "reservation": 100, "weight": 5, "limit": 200})
        assert rc == 0, out
        # validation: weight must be positive, res <= limit
        rc, out = client.mon_command(
            {"prefix": "qos set", "tenant": "bad", "weight": 0})
        assert rc == -22
        rc, out = client.mon_command(
            {"prefix": "qos set", "tenant": "bad",
             "reservation": 10, "weight": 1, "limit": 5})
        assert rc == -22
        rc, out = client.mon_command({"prefix": "qos ls"})
        assert rc == 0
        db = json.loads(out)
        assert db == {"gold": {"reservation": 100.0, "weight": 5.0,
                               "limit": 200.0}}
        # the OSD folds the db into its scheduler on map push
        deadline = time.time() + 10
        osd = cluster.osds[0]
        while time.time() < deadline \
                and "gold" not in osd._qos_profiles_applied:
            time.sleep(0.05)
        assert osd._qos_profiles_applied == db
        d = osd.ctx.admin.execute("dump_qos_stats")
        assert d["profiles"] == db and d["queue"] == "mclock"
        rc, out = client.mon_command({"prefix": "qos rm",
                                      "tenant": "gold"})
        assert rc == 0
        rc, out = client.mon_command({"prefix": "qos rm",
                                      "tenant": "gold"})
        assert rc == -2
        rc, out = client.mon_command({"prefix": "qos ls"})
        assert json.loads(out) == {}
    finally:
        cluster.stop()


# -- scheduler state hygiene --------------------------------------------------

def test_idle_tenant_lane_eviction_and_rollup():
    q = MClockQueue({"client": ClassInfo(weight=100.0)},
                    client_template=ClassInfo(weight=10.0),
                    idle_timeout=5.0)
    for i in range(40):
        q.enqueue(f"client.t{i}", i, now=0.0)
    while q.dequeue(now=1.0) is not None:
        pass
    assert sum(1 for n in q.dump_qos()["classes"]
               if n.startswith("client.")) == 40
    # quiet period passes: the sweep drops every idle dynamic lane and
    # folds its accounting into the rollup
    q.prune(now=100.0)
    d = q.dump_qos()
    assert not any(n.startswith("client.") for n in d["classes"])
    assert d["evicted"]["classes"] == 40
    assert d["evicted"]["enqueued"] == 40
    assert sum(d["evicted"]["served"].values()) == 40
    # static classes never evict
    assert "client" in d["classes"]
    # a busy lane is never evicted: backlogged or recently active
    q.enqueue("client.busy", 1, now=200.0)
    q.prune(now=201.0)
    assert q.exact_backlog("client.busy") == 1


def test_eviction_sweep_triggers_from_enqueue_volume():
    q = MClockQueue(client_template=ClassInfo(weight=1.0),
                    idle_timeout=0.5)
    # one-shot clients arriving over virtual time: the periodic sweep
    # (every 256 dynamic enqueues) must keep the table bounded without
    # anyone calling prune() explicitly
    for i in range(4000):
        now = i * 0.01
        q.enqueue(f"client.one{i}", i, now=now)
        got = q.dequeue(now=now)
        assert got is not None
    lanes = sum(1 for n in q.dump_qos()["classes"]
                if n.startswith("client."))
    assert lanes < 600, lanes


def test_group_backlog_accounting_is_exact():
    q = MClockQueue({"client": ClassInfo(weight=1.0),
                     "subop": ClassInfo(weight=1.0)})
    q.enqueue("client", "a", now=0.0)
    q.enqueue("client.t1", "b", now=0.0)
    q.enqueue("client.t1", "c", now=0.0)
    q.enqueue("client.t2", "d", now=0.0)
    q.enqueue("subop", "e", now=0.0)
    assert q.class_backlog("client") == 4
    assert q.class_backlog("client.t1") == 2
    assert q.exact_backlog("client.t1") == 2
    assert q.class_backlog("subop") == 1
    served = 0
    while q.dequeue(now=10.0) is not None:
        served += 1
    assert served == 5
    assert q.class_backlog("client") == 0
    assert q.exact_backlog("client.t1") == 0
    # eviction keeps the group counters consistent
    q.enqueue("client.t9", "x", now=20.0)
    assert q.class_backlog("client") == 1
    q.dequeue(now=20.0)
    q.prune(now=1000.0)
    assert q.class_backlog("client") == 0


def test_profile_change_retags_existing_backlog():
    """`ceph qos set` on a backlogged tenant applies to the queued
    ops, not just future ones: imposing a limit moves the queued
    requests behind it immediately."""
    q = MClockQueue({"other": ClassInfo(weight=1.0)},
                    client_template=ClassInfo(weight=100.0))
    for i in range(20):
        q.enqueue("client.t", i, now=0.0)
    q.enqueue("other", "o", now=0.0)
    # heavily weighted: the tenant would drain first at frozen now
    name, *_ = q.dequeue(now=0.0)
    assert name == "client.t"
    # cap the tenant hard: remaining backlog re-tags behind the limit
    q.set_client_profiles({"client.t": ClassInfo(weight=100.0,
                                                 limit=1.0)})
    order = [q.dequeue(now=0.0)[0] for _ in range(2)]
    assert order[0] == "other", order


def test_star_args_handler_receives_served():
    """A handler hiding its arity behind *args still gets the dmclock
    (phase, wait) tuple — no silent loss of phase data."""
    got = []
    wq = ShardedOpQueue(lambda *a: got.append(a), n_shards=1, name="t")
    try:
        wq.enqueue(1, "client", "x")
        deadline = time.time() + 5
        while not got and time.time() < deadline:
            time.sleep(0.01)
        assert got and len(got[0]) == 3, got
        klass, item, (phase, wait) = got[0]
        assert klass == "client" and item == "x" and phase > 0
    finally:
        wq.shutdown()


def test_kwargs_handler_counts_as_two_positional():
    """`def h(klass, item, **kw)` must NOT be classified served-aware:
    calling it with a third positional would TypeError on every op and
    wedge the queue."""
    got = []

    def h(klass, item, **kw):
        got.append((klass, item))
    wq = ShardedOpQueue(h, n_shards=1, name="t")
    try:
        assert not wq._handler_takes_served
        wq.enqueue(1, "client", "x")
        deadline = time.time() + 5
        while not got and time.time() < deadline:
            time.sleep(0.01)
        assert got == [("client", "x")], got
    finally:
        wq.shutdown()


def test_sharded_dump_merges_and_idle_timeout_reload():
    done = []
    wq = ShardedOpQueue(lambda k, i: done.append(i), n_shards=2,
                        name="t", client_template=ClassInfo(weight=1.0))
    try:
        for i in range(40):
            wq.enqueue(i, f"client.t{i % 4}", i)
        deadline = time.time() + 5
        while len(done) < 40 and time.time() < deadline:
            time.sleep(0.01)
        assert len(done) == 40
        d = wq.dump_qos()
        assert d["shards"] == 2
        total = sum(sum(r["served"].values())
                    for n, r in d["classes"].items()
                    if n.startswith("client."))
        assert total == 40
        wq.set_idle_timeout(123.0)
        assert all(q.idle_timeout == 123.0 for q, _cv in wq._shards)
    finally:
        wq.shutdown()


# -- report + exporter surfaces ----------------------------------------------

def test_mgr_report_qos_tail_roundtrip():
    from ceph_tpu.mgr.daemon import MMgrReport
    qos = {"lanes": {"client.gold": {
        "backlog": 2, "served": {"reservation": 10, "weight": 3,
                                 "limit": 0}, "wait_sum_s": 0.5}},
        "evicted": {"classes": 1, "enqueued": 7, "wait_sum_s": 0.1,
                    "served": {"reservation": 0, "weight": 7,
                               "limit": 0}}}
    m = MMgrReport(osd_id=3, qos=qos)
    enc = Encoder()
    m.encode_payload(enc)
    got = MMgrReport.__new__(MMgrReport)
    got.decode_payload(Decoder(enc.tobytes()), 0)
    assert got.qos == qos and got.osd_id == 3


def test_prometheus_qos_families():
    import sys
    sys.path.insert(0, "tests")
    from test_kernel_telemetry import parse_exposition
    from ceph_tpu.mgr.modules.prometheus import Module

    class _QosMgr:
        class _Map:
            max_osd = 1
            epoch = 1
            osd_weight = [0x10000]

            def is_up(self, o):
                return True

            def exists(self, o):
                return True

        osdmap = _Map()

        def get(self, name):
            return {
                "health": {"status": "HEALTH_OK"},
                "pg_summary": {},
                "df": {"total_objects": 0, "total_bytes_used": 0},
                "counters": {},
                "perf_reports": {},
                "qos_feed": {0: {
                    "lanes": {"client.gold": {
                        "backlog": 4,
                        "served": {"reservation": 11, "weight": 2,
                                   "limit": 1},
                        "wait_sum_s": 1.25}},
                    "evicted": {"classes": 3,
                                "served": {"reservation": 0,
                                           "weight": 40, "limit": 0},
                                "wait_sum_s": 2.5}}},
            }[name]

        def get_store(self, key, default=None):
            return default

    mod = Module.__new__(Module)
    mod.mgr = _QosMgr()
    fams = parse_exposition(mod.scrape_text())
    for fam, typ in (("ceph_qos_served_total", "counter"),
                     ("ceph_qos_backlog", "gauge"),
                     ("ceph_qos_wait_seconds_total", "counter"),
                     ("ceph_qos_evicted_lanes_total", "counter")):
        assert fam in fams and fams[fam]["type"] == typ, fam
    served = {(s[1]["qos_class"], s[1]["phase"]): s[2]
              for s in fams["ceph_qos_served_total"]["samples"]}
    assert served[("client.gold", "reservation")] == 11.0
    # the evicted rollup keeps one-shot tenants' service in the totals
    assert served[("evicted", "weight")] == 40.0
    waits = {s[1]["qos_class"]: s[2]
             for s in fams["ceph_qos_wait_seconds_total"]["samples"]}
    assert waits["evicted"] == 2.5
    backlog = fams["ceph_qos_backlog"]["samples"][0]
    assert backlog[1]["ceph_daemon"] == "osd.0" and backlog[2] == 4.0


def test_qos_wait_trace_event_explains_throttled_op():
    from ceph_tpu.common import tracing
    from ceph_tpu.tools.vstart import MiniCluster
    cluster = MiniCluster(n_osds=1, ms_type="loopback").start()
    try:
        cluster.wait_for_osd_count(1)
        client = cluster.client(timeout=15.0)
        pool = cluster.create_pool(client, pg_num=4, size=1)
        io = client.open_ioctx(pool)
        with tracing.trace_ctx(name="qos write",
                               daemon="client") as tid:
            io.write_full("traced-obj", b"payload")
        rows = tracing.dump(tid)
        events = [r for r in rows if r.get("event", "").startswith(
            "qos_wait")]
        assert events, rows
        assert "phase=" in events[0]["event"]
        assert "class=client" in events[0]["event"]
    finally:
        cluster.stop()


def test_service_delay_independent_dump_fields():
    """dump_qos_stats shape: wait/backlog/profile fields present and
    JSON-serializable (the admin-socket contract)."""
    wq = ShardedOpQueue(lambda k, i: None, n_shards=1, name="t",
                        client_template=ClassInfo(weight=1.0))
    try:
        wq.enqueue(1, "client.x", "a")
        time.sleep(0.2)
        d = wq.dump_qos()
        json.dumps(d)
        row = d["classes"]["client.x"]
        assert {"backlog", "enqueued", "served", "wait_sum_s",
                "wait_max_s", "profile", "dynamic"} <= set(row)
    finally:
        wq.shutdown()
