"""CRUSH placement for ceph_tpu.

CRUSH computes data placement as a pure function of (map, rule, x) — no lookup
service on the data path (reference: src/crush/mapper.c:900 crush_do_rule; see
SURVEY.md §1 "placement is computed, not looked up").  That purity is what makes it a
TPU kernel: bulk remaps evaluate the same map over thousands-to-millions of
independent x values (SURVEY.md §3.4).

Modules
-------
hashfn      rjenkins1 32-bit hashes (scalar oracle + numpy batch).
ln_table    the 2^44*log2 fixed-point tables, generated from their defining math
            plus the frozen upstream quirks needed for bit-exact placements.
types       CrushMap / Bucket / Rule / tunables model.
builder     map construction (crush/builder.c analog) + convenience topologies.
mapper_ref  exact scalar mapping oracle (crush/mapper.c semantics).
mapper_jax  batched placement engine over x on TPU (ops.crush_kernel).
"""

from .types import (
    CRUSH_BUCKET_UNIFORM,
    CRUSH_BUCKET_LIST,
    CRUSH_BUCKET_TREE,
    CRUSH_BUCKET_STRAW,
    CRUSH_BUCKET_STRAW2,
    CRUSH_ITEM_NONE,
    CRUSH_ITEM_UNDEF,
    Bucket,
    CrushMap,
    Rule,
    RuleStep,
    Tunables,
    RULE_TAKE,
    RULE_CHOOSE_FIRSTN,
    RULE_CHOOSE_INDEP,
    RULE_CHOOSELEAF_FIRSTN,
    RULE_CHOOSELEAF_INDEP,
    RULE_EMIT,
)
from .hashfn import crush_hash32, crush_hash32_2, crush_hash32_3, crush_hash32_4, crush_hash32_5
from .mapper_ref import crush_do_rule, crush_ln
from .builder import build_flat_map, build_two_level_map

__all__ = [
    "CRUSH_BUCKET_UNIFORM", "CRUSH_BUCKET_LIST", "CRUSH_BUCKET_TREE",
    "CRUSH_BUCKET_STRAW", "CRUSH_BUCKET_STRAW2",
    "CRUSH_ITEM_NONE", "CRUSH_ITEM_UNDEF",
    "Bucket", "CrushMap", "Rule", "RuleStep", "Tunables",
    "RULE_TAKE", "RULE_CHOOSE_FIRSTN", "RULE_CHOOSE_INDEP",
    "RULE_CHOOSELEAF_FIRSTN", "RULE_CHOOSELEAF_INDEP", "RULE_EMIT",
    "crush_hash32", "crush_hash32_2", "crush_hash32_3", "crush_hash32_4",
    "crush_hash32_5", "crush_do_rule", "crush_ln",
    "build_flat_map", "build_two_level_map",
]
