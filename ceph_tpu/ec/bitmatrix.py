"""Bitmatrix (word-schedule) RAID-6 techniques: blaum_roth, liberation, liber8tion.

The reference's jerasure plugin implements these with per-word XOR schedules
(jerasure_schedule_encode/decode_lazy, ErasureCodeJerasure.cc:259-356).  The
TPU-native formulation: a bitmatrix code over w-bit words is a GF(2) matrix
applied to k*w packet rows — and {0,1} is the subfield of GF(2^8), so the very
same batched MXU kernel used for byte codes executes the schedule, with the
(m*w, k*w) 0/1 matrix as coefficients and chunks reshaped into w packet rows.
No schedule interpreter, no per-word loop.

Constructions:
  blaum_roth   exact: Q block j = multiply-by-x^j in GF(2)[x]/((x^p-1)/(x-1)),
               w = p-1, p prime > k (Blaum & Roth 1993, as in jerasure).
  liberation   rotation blocks Q_j = R^j plus one extra bit per nonzero j
               (Plank, "The RAID-6 Liberation Codes", w prime >= k).  The extra
               bit is placed by deterministic search at init to the first
               position making every 2-erasure pattern decodable — the defining
               liberation property; bit-for-bit identity with liberation.c is
               not claimed (the reference ships no source for it either: empty
               submodule, SURVEY.md §2.4).
  liber8tion   the w=8 member of the same family (m=2, w=8).

All three are RAID-6 (m=2) codes, matching the reference's classes
(ErasureCodeJerasure.h:192-253).
"""

from __future__ import annotations

import functools

import numpy as np

from ceph_tpu.gf.matrix import gf_invert_matrix
from ceph_tpu.ops.gf_kernel import ec_encode_ref

from .base import ErasureCode, SIMD_ALIGN


def _is_prime(n: int) -> bool:
    if n < 2:
        return False
    for d in range(2, int(n ** 0.5) + 1):
        if n % d == 0:
            return False
    return True


# ---------------------------------------------------------------------------
# constructions
# ---------------------------------------------------------------------------

def _poly_mod_x_pow(e: int, p: int) -> np.ndarray:
    """Coefficients of x^e mod h(x), h = x^(p-1) + ... + x + 1, over GF(2).
    Returns a (p-1,) 0/1 vector."""
    w = p - 1
    coeffs = np.zeros(e + 1, dtype=np.uint8)
    coeffs[e] = 1
    # reduce: x^(p-1) = sum_{i<p-1} x^i (mod 2)
    for d in range(e, w - 1, -1):
        if coeffs[d]:
            coeffs[d] = 0
            coeffs[d - w:d] ^= 1
    out = np.zeros(w, dtype=np.uint8)
    out[:min(w, coeffs.size)] = coeffs[:w]
    return out


def blaum_roth_bitmatrix(k: int, w: int) -> np.ndarray:
    """(2w, k*w) coding bitmatrix: P row = identities, Q block j = mult-by-x^j
    in the ring GF(2)[x]/((x^p-1)/(x-1)) with p = w+1 prime."""
    p = w + 1
    if not _is_prime(p):
        raise ValueError(f"blaum_roth requires w+1 prime, got w={w}")
    if k > w:
        raise ValueError(f"blaum_roth requires k <= w, got k={k} w={w}")
    mat = np.zeros((2 * w, k * w), dtype=np.uint8)
    for j in range(k):
        mat[:w, j * w:(j + 1) * w] = np.eye(w, dtype=np.uint8)
        for c in range(w):
            mat[w:, j * w + c] = _poly_mod_x_pow(c + j, p)
    return mat


def _rotation(w: int, shift: int) -> np.ndarray:
    """R^shift: ones at (r, c) with r = (c + shift) mod w."""
    m = np.zeros((w, w), dtype=np.uint8)
    for c in range(w):
        m[(c + shift) % w, c] = 1
    return m


def _invertible(m: np.ndarray) -> bool:
    return gf_invert_matrix(m) is not None


@functools.lru_cache(maxsize=None)
def liberation_bitmatrix(k: int, w: int) -> np.ndarray:
    """(2w, k*w) coding bitmatrix: P = identities; Q_j = R^j plus, for j > 0,
    one extra bit (the liberation minimal-density shape: k*w + k - 1 ones in Q).

    RAID-6 decodability reduces to pairwise conditions: losing {data_j, P}
    needs X_j invertible; losing {data_a, data_b} needs X_a xor X_b invertible
    (substitute d_b = s1 + d_a into the Q equation).  Extra bits are chosen by
    deterministic backtracking over those cheap w x w checks."""
    if w < k:
        raise ValueError(f"liberation requires w >= k, got k={k} w={w}")
    blocks = [_rotation(w, j) for j in range(k)]

    def ok(j: int, cand: np.ndarray) -> bool:
        if not _invertible(cand):
            return False
        return all(_invertible(cand ^ blocks[i]) for i in range(j))

    def candidates(base: np.ndarray):
        """Single extra bits first (odd w), then bit pairs (even w: R^a xor R^b
        is always singular — all-ones null vector — and a pair is needed)."""
        free = [(r, c) for r in range(w) for c in range(w) if not base[r, c]]
        for rc in free:
            yield (rc,)
        for i in range(len(free)):
            for j2 in range(i + 1, len(free)):
                yield (free[i], free[j2])

    def search(j: int) -> bool:
        if j == k:
            return True
        base = blocks[j].copy()
        for bits in candidates(base):
            cand = base.copy()
            for r, c in bits:
                cand[r, c] = 1
            if ok(j, cand):
                blocks[j] = cand
                if search(j + 1):
                    return True
                blocks[j] = base
        return False

    if k > 1 and not search(1):
        raise ValueError(f"no liberation extra-bit assignment for k={k} w={w}")
    mat = np.zeros((2 * w, k * w), dtype=np.uint8)
    for j in range(k):
        mat[:w, j * w:(j + 1) * w] = np.eye(w, dtype=np.uint8)
        mat[w:, j * w:(j + 1) * w] = blocks[j]
    return mat


# ---------------------------------------------------------------------------
# plugin classes
# ---------------------------------------------------------------------------

class BitmatrixCode(ErasureCode):
    """RAID-6 code defined by a (2w, k*w) GF(2) coding bitmatrix; chunks are
    reshaped into w packet rows and run through the byte-code kernel."""

    #: recovery matrices here are PACKET-level ((t*w, k*w) over GF(2)
    #: rows), incompatible with the base pattern table's (t, k) chunk
    #: geometry — decodes stay on the synchronous path
    supports_submit_decode = False

    TECHNIQUE = ""
    FIXED_W: int | None = None

    def parse(self, profile):
        super().parse(profile)
        self.m = 2
        self.technique = profile.get("technique", self.TECHNIQUE)
        self.w = (self.FIXED_W if self.FIXED_W is not None
                  else self.to_int("w", profile, self._default_w()))
        self.packetsize = self.to_int("packetsize", profile, 2048)

    def _default_w(self) -> int:
        return 7

    def _build_coding_bitmatrix(self) -> np.ndarray:
        raise NotImplementedError

    def _build_generator(self):
        """Full (k+m)*w x k*w GF(2) generator over packet rows."""
        coding = self._build_coding_bitmatrix()
        kw = self.k * self.w
        gen = np.zeros(((self.k + 2) * self.w, kw), dtype=np.uint8)
        gen[:kw] = np.eye(kw, dtype=np.uint8)
        gen[kw:] = coding
        return gen

    # generator here is packet-level; override the chunk-level entry points

    def init(self, profile):
        self.parse(profile)
        self._generator = np.asarray(self._build_generator(), dtype=np.uint8)
        self._encoder = None
        self._decode_cache.clear()

    def get_alignment(self) -> int:
        return self.k * self.w * SIMD_ALIGN

    def _sub_rows(self, chunk_indices) -> list[int]:
        return [c * self.w + r for c in chunk_indices for r in range(self.w)]

    def _split(self, data_chunks: np.ndarray) -> np.ndarray:
        """(S, n, B) -> (S, n*w, B/w) packet rows."""
        s, n, b = data_chunks.shape
        if b % self.w:
            raise ValueError(f"chunk size {b} not a multiple of w={self.w}")
        return data_chunks.reshape(s, n * self.w, b // self.w)

    def _join(self, packet_rows: np.ndarray) -> np.ndarray:
        s, nw, pb = packet_rows.shape
        return packet_rows.reshape(s, nw // self.w, pb * self.w)

    def _apply(self, mat: np.ndarray, rows: np.ndarray) -> np.ndarray:
        if self.runtime == "cpu":
            return ec_encode_ref(mat, rows)
        from ceph_tpu.ops.gf_kernel import ec_encode_jax
        return np.asarray(ec_encode_jax(mat, rows))

    def encode_chunks(self, data_chunks):
        rows = self._split(np.asarray(data_chunks, dtype=np.uint8))
        kw = self.k * self.w
        parity_rows = self._apply(self.generator[kw:], rows)
        return self._join(parity_rows)

    def decode_chunks(self, chosen, chunks, targets):
        rows = self._split(np.asarray(chunks, dtype=np.uint8))
        rmat = self._recovery(tuple(chosen), tuple(targets))
        rebuilt = self._apply(rmat, rows)
        return self._join(rebuilt)

    def _recovery(self, chosen: tuple, targets: tuple) -> np.ndarray:
        def build():
            from ceph_tpu.gf.matrix import recovery_matrix
            try:
                return recovery_matrix(self.generator,
                                       self._sub_rows(chosen),
                                       self._sub_rows(targets))
            except ValueError as e:
                raise IOError(str(e))
        return self._recovery_cached((chosen, targets), build)


class BlaumRoth(BitmatrixCode):
    TECHNIQUE = "blaum_roth"

    def _default_w(self) -> int:
        return 10  # w+1=11 prime, and w >= the default k=7

    def _build_coding_bitmatrix(self):
        return blaum_roth_bitmatrix(self.k, self.w)


class Liberation(BitmatrixCode):
    TECHNIQUE = "liberation"

    def _default_w(self) -> int:
        return 7

    def _build_coding_bitmatrix(self):
        if not _is_prime(self.w):
            raise ValueError(f"liberation requires prime w, got {self.w}")
        return liberation_bitmatrix(self.k, self.w)


class Liber8tion(BitmatrixCode):
    TECHNIQUE = "liber8tion"
    FIXED_W = 8

    def _build_coding_bitmatrix(self):
        return liberation_bitmatrix(self.k, 8)


TECHNIQUES = {
    "blaum_roth": BlaumRoth,
    "liberation": Liberation,
    "liber8tion": Liber8tion,
}
