"""straw2 draws without int64: u32/i32 limb arithmetic + magic division.

The baseline kernel (ops/crush_kernel.straw2_draws) is bit-exact but leans
on s64 arithmetic — 64-bit emulation on a 32-bit-lane TPU VPU multiplies
every op, and the s64 `//` with a runtime divisor lowers particularly
badly.  This module computes the *same* draws (validated exhaustively over
the full 16-bit hash domain against the s64 kernel) using only u32/i32
element ops plus the existing exact bf16 one-hot MXU table lookups:

  draw(x, id, r, w) = -floor(P / w),  P = 2^48 - crush_ln(u),  u 16-bit
  argmax(draw) == argmin(P // w)      (first index on ties, both sides)

* crush_ln runs in 8-bit limbs end to end: the RH/LH/LL table lookups are
  the same one-hot bf16 matmuls, the u64 wraparound product and the
  (LH+LL)>>4 recombination become byte-limb carry chains in i32.
* The division P//w is a Granlund-Montgomery magic multiply: divisors are
  per-*item* (a few hundred per bucket), so exact (magic, shift) pairs are
  precomputed host-side with arbitrary-precision ints, shifts rounded up
  to a whole limb so the kernel never bit-shifts across limbs.  The magic
  product runs in 16-bit limb partial products (u32-exact).
* Winner selection is a lexicographic argmin over the (hi, lo) u32 pair.

Semantics preserved from mapper.c: bucket_straw2_choose's strict `>` keeps
the first maximum (mapper.c:374-380) == first minimum of P//w; zero-weight
items never win (draw = S64_MIN, here Q = +inf).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ceph_tpu.ops.crush_kernel import (
    _ln_limb_operands, _onehot_rows, hash32_3)

_U32 = jnp.uint32
_I32 = jnp.int32

# dividends are < 2^49 (P <= 2^48 inclusive: u == 0 gives crush_ln == 0)
_NBITS = 49


@functools.lru_cache(maxsize=None)
def _magic_for(w: int) -> tuple[int, int]:
    """Exact magic (m, shift) with floor(P/w) == (P*m) >> shift for all
    P < 2^49 (classic round-up method; the error bound is checked, not
    assumed).  shift is then rounded up to a multiple of 16 by scaling m,
    so the kernel's "shift" is a pure limb selection."""
    assert w >= 1
    p = max(0, w.bit_length() - 1)
    while True:
        m = ((1 << (_NBITS + p)) // w) + 1
        err = m * w - (1 << (_NBITS + p))
        if 0 < err <= (1 << p):
            break
        p += 1
    shift = _NBITS + p
    pad = (16 - shift % 16) % 16
    m <<= pad
    shift += pad
    assert m < (1 << 66)
    return m, shift


def magic_tables(weights: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(..., 5) uint32 magic limbs (16-bit each) and (...,) int32 limb
    offset (shift // 16) for an array of 16.16 weights.  Zero weights get
    magic 0 (they are masked to +inf draws by the kernel)."""
    flat = np.asarray(weights, dtype=np.int64).ravel()
    limbs = np.zeros((flat.size, 5), dtype=np.uint32)
    offs = np.zeros(flat.size, dtype=np.int32)
    for i, w in enumerate(flat):
        if w <= 0:
            continue
        m, shift = _magic_for(int(w))
        if shift // 16 > 6:
            # the kernels' limb pick covers off in {4,5,6} (shift <= 112
            # needs product limbs 4..9, exactly what they compute); a
            # 16.16 weight would need to exceed ~2^47 to get here
            raise ValueError(
                f"weight {w:#x} too large for the straw2 magic divide")
        for j in range(5):
            limbs[i, j] = (m >> (16 * j)) & 0xFFFF
        offs[i] = shift // 16
    return (limbs.reshape(*np.shape(weights), 5),
            offs.reshape(np.shape(weights)))


def _crush_ln_p48(u):
    """P = 2^48 - crush_ln(u) for u in [0, 2^16), as (p_hi17, p_lo32) u32.

    Follows crush_ln (mapper.c:248-290) with byte-limb carry arithmetic
    instead of int64 recombination.
    """
    x = u.astype(_U32) + _U32(1)          # 1..2^16
    low17 = x & _U32(0x1FFFF)
    bitlen = _U32(32) - jax.lax.clz(low17 | _U32(1))
    bits = _U32(16) - bitlen
    needs_norm = (x & _U32(0x18000)) == 0
    xnorm = jnp.where(needs_norm, x << bits, x).astype(_I32)
    iexpon = jnp.where(needs_norm, _U32(15) - bits, _U32(15)).astype(_I32)
    idx1 = (xnorm.astype(_U32) >> 8) << 1
    k = ((idx1 - _U32(256)) >> 1).astype(_I32)
    rhlh_tab, ll_tab = _ln_limb_operands()
    rhlh = _onehot_rows(k, 129, rhlh_tab)          # (..., 13) f32 bytes
    # u64 wraparound product xnorm * RH, byte 6 (bits 48..55): partial
    # products c_j = xnorm * rh_j < 2^25 (exact in i32), byte-carry chain
    acc = jnp.zeros_like(xnorm)
    for j in range(7):
        c = xnorm * rhlh[..., j].astype(_I32)
        acc = (acc >> 8) + c
    idx2 = acc & _I32(0xFF)
    ll = _onehot_rows(idx2, 256, ll_tab)           # (..., 6) f32 bytes
    # T = LH + LL in bytes (t_j < 512), carry-normalize; V = T >> 4;
    # ln = (iexpon << 44) + V  — all staying in 8-bit limbs
    b = []
    carry = jnp.zeros_like(xnorm)
    for j in range(6):
        s = (rhlh[..., 7 + j].astype(_I32) + ll[..., j].astype(_I32)
             + carry)
        b.append(s & _I32(0xFF))
        carry = s >> 8
    b.append(carry)                                # b6 <= 1
    v = [((b[j] >> 4) | ((b[j + 1] & _I32(0xF)) << 4)) for j in range(6)]
    # add iexpon << 44 into byte 5 bits 4..7 (never carries: ln < 2^48)
    v[5] = v[5] + ((iexpon & _I32(0xF)) << 4)
    # ln bytes v0..v5; P = 2^48 - ln.  ln == 0 <=> all bytes zero.
    ln_lo = (v[0] | (v[1] << 8) | (v[2] << 16)).astype(_U32) \
        | (v[3].astype(_U32) << 24)
    ln_hi = (v[4] | (v[5] << 8)).astype(_U32)      # bits 32..47
    is_zero = (ln_lo == 0) & (ln_hi == 0)
    # two's complement over 48 bits: P = (~ln + 1) mod 2^48
    p_lo = (~ln_lo) + _U32(1)
    # ~ln_lo + 1 wraps (carries into hi) exactly when ln_lo == 0
    carry_in = jnp.where(ln_lo == 0, _U32(1), _U32(0))
    p_hi = ((~ln_hi) & _U32(0xFFFF)) + carry_in
    p_hi = p_hi & _U32(0x1FFFF)
    # ln == 0: P = 2^48 exactly (bit 48 set, rest zero)
    p_lo = jnp.where(is_zero, _U32(0), p_lo)
    p_hi = jnp.where(is_zero, _U32(0x10000), p_hi)
    return p_hi, p_lo


def magic_divide_planes(p_hi, p_lo, magic_planes, off):
    """(q_hi, q_lo) = floor(P / w) via the magic multiply — the ONE
    implementation shared by the XLA path and the Pallas kernels (a
    bit-exactness-critical algorithm must not exist twice).

    p_hi (...,) u32 17-bit, p_lo u32; magic_planes: list of 5 u32 arrays
    (16-bit limbs, broadcastable); off (...,) i32 in {4, 5, 6}
    (shift // 16 after limb rounding).  Product is 49 + ~66 bits ->
    10x16 limbs; Q < 2^49 -> limbs [off .. off+3].
    """
    a = [p_lo & _U32(0xFFFF), p_lo >> 16,
         p_hi & _U32(0xFFFF), p_hi >> 16]          # 4x16-bit, a3 <= 1
    # column accumulation: a naive sum of <= 4 full 16x16 products would
    # overflow u32, so each product contributes its lo half to column k
    # and its hi half to column k+1 (column sums then stay < 2^20)
    prod = []
    lo_carry = jnp.zeros_like(p_lo)
    for kcol in range(10):
        s = lo_carry
        for i in range(4):
            j = kcol - i
            if 0 <= j < 5:
                s = s + ((a[i] * magic_planes[j]) & _U32(0xFFFF))
            j2 = kcol - 1 - i
            if 0 <= j2 < 5:
                s = s + ((a[i] * magic_planes[j2]) >> 16)
        prod.append(s & _U32(0xFFFF))
        lo_carry = s >> 16
    # select limbs [off .. off+3] (off in {4,5,6})
    def pick(base):
        out = prod[4 + base]
        for o in (5, 6):
            if o + base < len(prod):
                out = jnp.where(off == o, prod[o + base], out)
        return out
    q0, q1, q2, q3 = pick(0), pick(1), pick(2), pick(3)
    q_lo = q0 | (q1 << 16)
    q_hi = q2 | (q3 << 16)
    return q_hi, q_lo


def _magic_divide(p_hi, p_lo, magic, off):
    """magic as a (..., 5) stacked array (the XLA-path layout)."""
    return magic_divide_planes(
        p_hi, p_lo, [magic[..., j] for j in range(5)], off)


def straw2_qvals(x, ids, r, weights, magic, off):
    """Per-item (q_hi, q_lo): P//w for each item; +inf for weight 0.

    x (...,) uint32; ids (S,) or (..., S); r scalar/(...,) uint32;
    weights broadcastable to ids' shape (only used for the ==0 mask);
    magic/off from magic_tables(weights).
    """
    u = hash32_3(x[..., None], ids, r[..., None] if jnp.ndim(r) else r) \
        & _U32(0xFFFF)
    p_hi, p_lo = _crush_ln_p48(u)
    q_hi, q_lo = _magic_divide(p_hi, p_lo, magic, off)
    wz = jnp.asarray(weights) <= 0
    q_hi = jnp.where(wz, _U32(0xFFFFFFFF), q_hi)
    q_lo = jnp.where(wz, _U32(0xFFFFFFFF), q_lo)
    return q_hi, q_lo


def argmin_lex(q_hi, q_lo):
    """First index of the lexicographic minimum along the last axis —
    the first-max-wins rule of bucket_straw2_choose on negated draws."""
    min_hi = jnp.min(q_hi, axis=-1, keepdims=True)
    on_hi = q_hi == min_hi
    lo_m = jnp.where(on_hi, q_lo, _U32(0xFFFFFFFF))
    min_lo = jnp.min(lo_m, axis=-1, keepdims=True)
    return jnp.argmax(on_hi & (lo_m == min_lo), axis=-1)


def straw2_choose_index_u32(x, ids, r, weights, magic, off):
    q_hi, q_lo = straw2_qvals(x, ids, r, weights, magic, off)
    return argmin_lex(q_hi, q_lo)


# ---------------------------------------------------------------------------
# approximate-filter + exact-verify winner selection
# ---------------------------------------------------------------------------
#
# The exact pipeline above prices every item at ~150 u32 ops.  But the
# winner is almost always obvious: a cheap f32 approximation of the draw
# with a *certified* error bound narrows each lane to a handful of
# candidate items; the exact pipeline then runs on just those K items, and
# a lax.cond falls back to the full exact column in the (measured: never
# at realistic weights) case where more than K items land inside the
# error band of the minimum — bit-exactness is unconditional.
#
# The ln error bound D is measured EXHAUSTIVELY: crush_ln's domain is
# exactly the 16-bit hash, so max|f32_approx - exact| over all 65536
# inputs is a fact, not an estimate (it also absorbs the frozen table
# deviations).  f32 evaluation is deterministic on device, so the bound
# holds at runtime.

_K = 4


@functools.lru_cache(maxsize=None)
def _ln_f32_error_bound() -> float:
    """max over all u in [0, 2^16) of |2^44*log2(u+1) - crush_ln(u)|,
    evaluated with the same f32 ops the approx path uses."""
    from ceph_tpu.ops.crush_kernel import crush_ln
    u = jnp.arange(65536, dtype=jnp.uint32)
    approx = _ln_f32(u)
    exact = crush_ln(u).astype(jnp.float32)
    return float(jnp.max(jnp.abs(approx - exact)))


def _ln_f32(u):
    xf = (u.astype(jnp.float32) + 1.0)
    return jnp.log2(xf) * np.float32(2.0 ** 44)


def straw2_choose_index_approx(x, ids, r, weights, magic, off):
    """Bit-exact straw2 winner via approx-filter + exact-verify.

    Shapes as straw2_choose_index_u32 (ids (..., S) broadcastable).
    """
    ids_b = jnp.broadcast_to(ids, (*x.shape, ids.shape[-1]))
    S = ids_b.shape[-1]
    w = jnp.asarray(weights)
    if S <= _K + 1:
        # tiny bucket: the exact pipeline on all items is already cheap
        q_hi, q_lo = straw2_qvals(x, ids_b, r, w, magic, off)
        return argmin_lex(q_hi, q_lo).astype(jnp.int32)
    wf = jnp.maximum(w.astype(jnp.float32), 1.0)
    u = hash32_3(x[..., None], ids_b,
                 r[..., None] if jnp.ndim(r) else r) & _U32(0xFFFF)
    D = np.float32(_ln_f32_error_bound())
    q_approx = (np.float32(2.0 ** 48) - _ln_f32(u)) / wf
    # margin: ln bound + f32 representation error of P (~2^25 safe) +
    # relative f32 division error + floor-tie quantization
    m = ((D + np.float32(2 ** 25)) / wf
         + q_approx * np.float32(2.0 ** -21) + np.float32(4.0))
    wz = jnp.asarray(w) <= 0
    big = np.float32(3.0e38)
    q_approx = jnp.where(wz, big, q_approx)
    m = jnp.where(wz, 0.0, m)
    lo = q_approx - m
    hi = q_approx + m
    min_hi = jnp.min(hi, axis=-1, keepdims=True)
    in_band = lo <= min_hi
    need_fallback = jnp.any(jnp.sum(in_band, axis=-1) > _K)

    # K smallest lower bounds always contain every in-band item when the
    # certificate holds
    _, cand = jax.lax.top_k(-lo, _K)                      # (..., K)

    def exact_on_candidates(_):
        c_ids = jnp.take_along_axis(ids_b, cand, axis=-1)
        c_w = jnp.take_along_axis(
            jnp.broadcast_to(w, ids_b.shape), cand, axis=-1)
        mg = jnp.broadcast_to(magic, (*ids_b.shape, 5))
        c_mg = jnp.take_along_axis(
            mg, cand[..., None], axis=-2)
        c_off = jnp.take_along_axis(
            jnp.broadcast_to(off, ids_b.shape), cand, axis=-1)
        qh, ql = straw2_qvals(x, c_ids, r, c_w, c_mg, c_off)
        # lexicographic min over (q_hi, q_lo, original index): the floor
        # tie rule is "first index wins" in ORIGINAL item order
        min_h = jnp.min(qh, axis=-1, keepdims=True)
        on_h = qh == min_h
        ql_m = jnp.where(on_h, ql, _U32(0xFFFFFFFF))
        min_l = jnp.min(ql_m, axis=-1, keepdims=True)
        on = on_h & (ql_m == min_l)
        idx_m = jnp.where(on, cand, jnp.int32(2 ** 31 - 1))
        return jnp.min(idx_m, axis=-1)

    def exact_full(_):
        q_hi, q_lo = straw2_qvals(x, ids_b, r, w, magic, off)
        return argmin_lex(q_hi, q_lo).astype(jnp.int32)

    return jax.lax.cond(need_fallback, exact_full, exact_on_candidates,
                        None)
