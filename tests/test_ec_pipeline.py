"""EC per-object write pipelining (ExtentCache reduced,
src/osd/ExtentCache.h:1-491): overlapping writes to one EC object ride ONE
rmw gather — later writes overlay in arrival order onto the gather's
projected content instead of serializing whole-object — and the final
content matches the sequential overlay exactly."""

from __future__ import annotations

import numpy as np
import pytest

from ceph_tpu.messages.osd_msgs import OP_WRITE, OP_WRITEFULL, OSDOpField
from ceph_tpu.tools.vstart import MiniCluster


@pytest.fixture
def cluster():
    c = MiniCluster(n_osds=3, ms_type="loopback").start()
    c.wait_for_osd_count(3)
    yield c
    c.stop()


def _counter(cluster, name: str) -> int:
    total = 0
    for osd in cluster.osds.values():
        total += osd.perf.dump().get(name, 0)
    return total


def test_overlapping_writes_one_gather(cluster):
    client = cluster.client()
    pool = cluster.create_pool(client, pg_num=1, pool_type="erasure",
                               k=2, m=1)
    io = client.open_ioctx(pool)
    base = bytes(16384)
    io.write_full("pipe", base)

    g0 = _counter(cluster, "ec_rmw_gather")
    expected = bytearray(base)
    comps = []
    # back-to-back burst: overlapping 1 KiB ranges, no waiting between
    # submissions — all are in flight together
    writes = [(i * 512, bytes([i + 1]) * 1024) for i in range(8)]
    for off, data in writes:
        expected[off:off + len(data)] = data
        comps.append(client.aio_operate(
            pool, "pipe", [OSDOpField(OP_WRITE, off, len(data), data)]))
    for c in comps:
        assert c.wait_for_complete(15), "pipelined write timed out"
        assert c.get_return_value() == 0
    assert io.read("pipe") == bytes(expected)

    gathers = _counter(cluster, "ec_rmw_gather") - g0
    pipelined = _counter(cluster, "ec_rmw_pipelined")
    # one gather serves the whole burst: strictly fewer gathers than
    # writes, and at least one write rode the pipeline
    assert gathers < len(writes), (gathers, pipelined)
    assert pipelined >= 1, (gathers, pipelined)


def test_pipelined_writefull_replaces_projected_base(cluster):
    client = cluster.client()
    pool = cluster.create_pool(client, pg_num=1, pool_type="erasure",
                               k=2, m=1)
    io = client.open_ioctx(pool)
    io.write_full("wf", b"A" * 8192)

    # partial (starts a gather), then WRITEFULL and another partial queue
    # behind it: ordering must hold — final = overlay(writefull, partial2)
    c1 = client.aio_operate(pool, "wf", [OSDOpField(
        OP_WRITE, 100, 4, b"BBBB")])
    c2 = client.aio_operate(pool, "wf", [OSDOpField(
        OP_WRITEFULL, 0, 2000, b"C" * 2000)])
    c3 = client.aio_operate(pool, "wf", [OSDOpField(
        OP_WRITE, 1990, 20, b"D" * 20)])
    for c in (c1, c2, c3):
        assert c.wait_for_complete(15)
        assert c.get_return_value() == 0
    expected = bytearray(b"C" * 2000)
    expected[1990:2010] = b"D" * 20
    assert io.read("wf") == bytes(expected)


def test_interleaved_objects_do_not_cross_pipeline(cluster):
    # writes to different oids must not share a pipeline or corrupt each
    # other's projected bases
    client = cluster.client()
    pool = cluster.create_pool(client, pg_num=2, pool_type="erasure",
                               k=2, m=1)
    io = client.open_ioctx(pool)
    rng = np.random.default_rng(11)
    bases = {}
    for o in range(4):
        bases[o] = bytearray(rng.integers(
            0, 256, 8192, dtype=np.uint8).tobytes())
        io.write_full(f"multi-{o}", bytes(bases[o]))
    comps = []
    for i in range(6):
        for o in range(4):
            off = 777 * i + o * 13
            data = bytes([16 * o + i + 1]) * 600
            bases[o][off:off + len(data)] = data
            comps.append(client.aio_operate(
                pool, f"multi-{o}",
                [OSDOpField(OP_WRITE, off, len(data), data)]))
    for c in comps:
        assert c.wait_for_complete(20)
        assert c.get_return_value() == 0
    for o in range(4):
        assert io.read(f"multi-{o}") == bytes(bases[o]), f"multi-{o}"


def test_burst_survives_repeat(cluster):
    # repeated bursts keep chaining correctly (projected base refreshes
    # from committed state between bursts)
    client = cluster.client()
    pool = cluster.create_pool(client, pg_num=1, pool_type="erasure",
                               k=2, m=1)
    io = client.open_ioctx(pool)
    expected = bytearray(4096)
    io.write_full("rep", bytes(expected))
    for round_ in range(3):
        comps = []
        for i in range(4):
            off = (997 * (round_ + 1) * (i + 1)) % 3000
            data = bytes([round_ * 40 + i + 1]) * 512
            expected[off:off + len(data)] = data
            comps.append(client.aio_operate(
                pool, "rep", [OSDOpField(OP_WRITE, off, len(data), data)]))
        for c in comps:
            assert c.wait_for_complete(15)
            assert c.get_return_value() == 0
    assert io.read("rep") == bytes(expected)
