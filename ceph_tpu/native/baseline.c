/* Single-core CPU baseline kernels: GF(2^8) erasure encode and scalar CRUSH.
 *
 * Purpose: an honest in-repo CPU yardstick for bench.py (BASELINE.md rows).
 * The GF encode uses the split-nibble table algorithm that ISA-L / jerasure's
 * SIMD paths use (reference semantics: src/erasure-code/isa/ErasureCodeIsa.cc
 * :118-130 ec_encode_data), expressed with GCC vector extensions so -O3
 * -march=native lowers the 16-entry table lookups to pshufb/vpshufb.  The
 * CRUSH side is a scalar straw2 crush_do_rule with the firstn/indep retry
 * ladders (reference semantics: src/crush/mapper.c:460-1105), ported from the
 * in-repo Python oracle (ceph_tpu/crush/mapper_ref.py) and cross-validated
 * against it in tests/test_native.py.
 *
 * Single-threaded by design: the baseline is "one CPU core".
 */

#include <stdint.h>
#include <stdlib.h>
#include <string.h>

/* ------------------------------------------------------------------ */
/* GF(2^8), polynomial 0x11d (the ISA-L / jerasure w=8 field)          */
/* ------------------------------------------------------------------ */

static uint8_t gf_mul_tab[256][256];
static int gf_ready = 0;

static void gf_init(void) {
    if (gf_ready) return;
    uint8_t exp[512];
    int log[256];
    int x = 1;
    for (int i = 0; i < 255; i++) {
        exp[i] = (uint8_t)x;
        log[x] = i;
        x <<= 1;
        if (x & 0x100) x ^= 0x11d;
    }
    for (int i = 255; i < 510; i++) exp[i] = exp[i - 255];
    log[0] = -1;
    for (int a = 0; a < 256; a++)
        for (int b = 0; b < 256; b++)
            gf_mul_tab[a][b] = (a && b) ? exp[log[a] + log[b]] : 0;
    gf_ready = 1;
}

typedef uint8_t v32 __attribute__((vector_size(32)));

/* Encode: parity[s][i][:] = xor_j mul(matrix[i][j], data[s][j][:]).
 * Layout: data (stripes, k, chunk) C-contiguous; parity (stripes, m, chunk).
 * Per 32-byte block the data vector is loaded once and folded into all m
 * accumulators (the ISA-L dataflow: read data once, write parity once). */
void ec_encode_c(const uint8_t *matrix, int k, int m,
                 const uint8_t *data, uint8_t *parity,
                 long stripes, long chunk) {
    gf_init();
    if (m > 32) return; /* bench configs are far below this */
    /* per (i, j): 32-byte lo/hi nibble product tables (16 entries, doubled
     * across both 128-bit lanes so vpshufb sees the table in each lane) */
    /* vector loads are aligned moves; malloc only guarantees 16 bytes */
    v32 *lo = aligned_alloc(32, (size_t)m * k * sizeof(v32));
    v32 *hi = aligned_alloc(32, (size_t)m * k * sizeof(v32));
    for (int i = 0; i < m; i++)
        for (int j = 0; j < k; j++) {
            uint8_t c = matrix[i * k + j];
            uint8_t tl[32], th[32];
            for (int n = 0; n < 16; n++) {
                tl[n] = gf_mul_tab[c][n];
                tl[n + 16] = tl[n];
                th[n] = gf_mul_tab[c][n << 4];
                th[n + 16] = th[n];
            }
            memcpy(&lo[i * k + j], tl, 32);
            memcpy(&hi[i * k + j], th, 32);
        }
    const v32 mask15 = {15,15,15,15,15,15,15,15,15,15,15,15,15,15,15,15,
                        15,15,15,15,15,15,15,15,15,15,15,15,15,15,15,15};
    long vchunk = chunk & ~31L;
    for (long s = 0; s < stripes; s++) {
        const uint8_t *dbase = data + s * k * chunk;
        uint8_t *pbase = parity + s * m * chunk;
        for (long off = 0; off < vchunk; off += 32) {
            v32 acc[32];
            for (int i = 0; i < m; i++) acc[i] = (v32){0};
            for (int j = 0; j < k; j++) {
                v32 d;
                memcpy(&d, dbase + j * chunk + off, 32);
                v32 dl = d & mask15;
                v32 dh = (d >> 4) & mask15;
                for (int i = 0; i < m; i++)
                    acc[i] ^= __builtin_shuffle(lo[i * k + j], dl)
                            ^ __builtin_shuffle(hi[i * k + j], dh);
            }
            for (int i = 0; i < m; i++)
                memcpy(pbase + i * chunk + off, &acc[i], 32);
        }
        for (long off = vchunk; off < chunk; off++) {  /* scalar tail */
            for (int i = 0; i < m; i++) {
                uint8_t a = 0;
                for (int j = 0; j < k; j++)
                    a ^= gf_mul_tab[matrix[i * k + j]][dbase[j * chunk + off]];
                pbase[i * chunk + off] = a;
            }
        }
    }
    free(lo);
    free(hi);
}

/* ------------------------------------------------------------------ */
/* rjenkins1 hash family (semantics: src/crush/hash.c)                 */
/* ------------------------------------------------------------------ */

#define HASH_SEED 1315423911u

#define MIX(a, b, c) do {                         \
    a = a - b; a = a - c; a = a ^ (c >> 13);      \
    b = b - c; b = b - a; b = b ^ (a << 8);       \
    c = c - a; c = c - b; c = c ^ (b >> 13);      \
    a = a - b; a = a - c; a = a ^ (c >> 12);      \
    b = b - c; b = b - a; b = b ^ (a << 16);      \
    c = c - a; c = c - b; c = c ^ (b >> 5);       \
    a = a - b; a = a - c; a = a ^ (c >> 3);       \
    b = b - c; b = b - a; b = b ^ (a << 10);      \
    c = c - a; c = c - b; c = c ^ (b >> 15);      \
} while (0)

static uint32_t hash32_2(uint32_t a, uint32_t b) {
    uint32_t hash = HASH_SEED ^ a ^ b;
    uint32_t x = 231232, y = 1232;
    MIX(a, b, hash);
    MIX(x, a, hash);
    MIX(b, y, hash);
    return hash;
}

static uint32_t hash32_3(uint32_t a, uint32_t b, uint32_t c) {
    uint32_t hash = HASH_SEED ^ a ^ b ^ c;
    uint32_t x = 231232, y = 1232;
    MIX(a, b, hash);
    MIX(c, x, hash);
    MIX(y, a, hash);
    MIX(b, x, hash);
    MIX(y, c, hash);
    return hash;
}

static uint32_t hash32_4(uint32_t a, uint32_t b, uint32_t c, uint32_t d) {
    uint32_t hash = HASH_SEED ^ a ^ b ^ c ^ d;
    uint32_t x = 231232, y = 1232;
    MIX(a, b, hash);
    MIX(c, d, hash);
    MIX(a, x, hash);
    MIX(y, b, hash);
    MIX(c, x, hash);
    MIX(y, d, hash);
    return hash;
}

/* ------------------------------------------------------------------ */
/* CRUSH map (compact blob-parsed form) and scalar do_rule             */
/* ------------------------------------------------------------------ */

#define ALG_UNIFORM 1
#define ALG_LIST 2
#define ALG_TREE 3
#define ALG_STRAW 4
#define ALG_STRAW2 5

#define ITEM_UNDEF 0x7ffffffe
#define ITEM_NONE  0x7fffffff

enum {
    OP_NOOP = 0, OP_TAKE = 1, OP_CHOOSE_FIRSTN = 2, OP_CHOOSE_INDEP = 3,
    OP_EMIT = 4, OP_CHOOSELEAF_FIRSTN = 6, OP_CHOOSELEAF_INDEP = 7,
    OP_SET_CHOOSE_TRIES = 8, OP_SET_CHOOSELEAF_TRIES = 9,
    OP_SET_CHOOSE_LOCAL_TRIES = 10, OP_SET_CHOOSE_LOCAL_FALLBACK_TRIES = 11,
    OP_SET_CHOOSELEAF_VARY_R = 12, OP_SET_CHOOSELEAF_STABLE = 13,
};

typedef struct {
    int present, id, type, alg, size;
    int32_t *items;
    int64_t *weights;      /* 16.16 fixed point */
    int64_t *sums;         /* list alg cumulative weights */
    /* tree alg: weights at binary-tree nodes, leaves at odd indices */
    int n_nodes;
    int64_t *node_weights;
    /* workspace (bucket_perm_choose) */
    uint32_t perm_x, perm_n;
    int32_t *perm;
} cbucket;

typedef struct { int op, a1, a2; } cstep;
typedef struct { int present, n_steps; cstep *steps; } crule;

typedef struct {
    int max_devices, n_buckets, n_rules;
    int64_t tun[7]; /* local_tries, local_fallback, total_tries,
                       descend_once, vary_r, stable, straw_calc */
    cbucket *buckets;
    crule *rules;
    uint64_t rh[129], lh[129], ll[256];
} cmap;

static cbucket *map_bucket(cmap *m, int id) {
    int idx = -1 - id;
    if (idx < 0 || idx >= m->n_buckets || !m->buckets[idx].present)
        return NULL;
    return &m->buckets[idx];
}

void *crush_init(const int64_t *blob) {
    const int64_t *p = blob;
    if (*p++ != 0xCB02) return NULL;
    cmap *m = calloc(1, sizeof(cmap));
    m->max_devices = (int)*p++;
    m->n_buckets = (int)*p++;
    m->n_rules = (int)*p++;
    for (int i = 0; i < 7; i++) m->tun[i] = *p++;
    m->buckets = calloc(m->n_buckets ? m->n_buckets : 1, sizeof(cbucket));
    for (int i = 0; i < m->n_buckets; i++) {
        cbucket *b = &m->buckets[i];
        b->present = (int)*p++;
        if (!b->present) continue;
        b->id = (int)*p++;
        b->type = (int)*p++;
        b->alg = (int)*p++;
        b->size = (int)*p++;
        b->items = malloc(sizeof(int32_t) * (b->size ? b->size : 1));
        b->weights = malloc(sizeof(int64_t) * (b->size ? b->size : 1));
        b->sums = malloc(sizeof(int64_t) * (b->size ? b->size : 1));
        b->perm = malloc(sizeof(int32_t) * (b->size ? b->size : 1));
        for (int j = 0; j < b->size; j++) b->items[j] = (int32_t)*p++;
        for (int j = 0; j < b->size; j++) b->weights[j] = *p++;
        int64_t acc = 0;
        for (int j = 0; j < b->size; j++) {
            acc += b->weights[j];
            b->sums[j] = acc;
        }
        if (b->alg == ALG_TREE) {
            b->n_nodes = (int)*p++;
            b->node_weights = malloc(
                sizeof(int64_t) * (b->n_nodes ? b->n_nodes : 1));
            for (int j = 0; j < b->n_nodes; j++) b->node_weights[j] = *p++;
        }
    }
    m->rules = calloc(m->n_rules ? m->n_rules : 1, sizeof(crule));
    for (int i = 0; i < m->n_rules; i++) {
        crule *r = &m->rules[i];
        r->present = (int)*p++;
        if (!r->present) continue;
        r->n_steps = (int)*p++;
        r->steps = malloc(sizeof(cstep) * (r->n_steps ? r->n_steps : 1));
        for (int j = 0; j < r->n_steps; j++) {
            r->steps[j].op = (int)*p++;
            r->steps[j].a1 = (int)*p++;
            r->steps[j].a2 = (int)*p++;
        }
    }
    for (int i = 0; i < 129; i++) m->rh[i] = (uint64_t)*p++;
    for (int i = 0; i < 129; i++) m->lh[i] = (uint64_t)*p++;
    for (int i = 0; i < 256; i++) m->ll[i] = (uint64_t)*p++;
    return m;
}

void crush_free(void *h) {
    cmap *m = h;
    if (!m) return;
    for (int i = 0; i < m->n_buckets; i++) {
        free(m->buckets[i].items);
        free(m->buckets[i].weights);
        free(m->buckets[i].sums);
        free(m->buckets[i].node_weights);
        free(m->buckets[i].perm);
    }
    for (int i = 0; i < m->n_rules; i++) free(m->rules[i].steps);
    free(m->buckets);
    free(m->rules);
    free(m);
}

/* 2^44 * log2(x+1), 48-bit fixed point (semantics: mapper.c:248-290) */
static int64_t crush_ln_c(cmap *m, uint32_t xin) {
    uint32_t x = xin + 1;
    int iexpon = 15;
    if (!(x & 0x18000)) {
        uint32_t t = x & 0x1ffff;
        int bl = 0;
        while (t >> bl) bl++;
        int bits = 16 - bl;
        x <<= bits;
        iexpon = 15 - bits;
    }
    uint32_t index1 = (x >> 8) << 1;
    int kk = ((int)index1 - 256) >> 1;
    uint64_t rh = m->rh[kk], lhv = m->lh[kk];
    uint64_t xl64 = ((uint64_t)x * rh) >> 48;
    uint64_t llv = m->ll[xl64 & 0xff];
    int64_t result = (int64_t)iexpon << 44;
    result += (int64_t)((lhv + llv) >> 4);
    return result;
}

static int32_t bucket_straw2_choose(cmap *m, cbucket *b, uint32_t x, uint32_t r) {
    int high = 0;
    int64_t high_draw = 0;
    for (int i = 0; i < b->size; i++) {
        int64_t draw;
        if (b->weights[i]) {
            uint32_t u = hash32_3(x, (uint32_t)b->items[i], r) & 0xffff;
            int64_t ln = crush_ln_c(m, u) - 0x1000000000000LL;
            draw = ln / b->weights[i];
        } else {
            draw = INT64_MIN;
        }
        if (i == 0 || draw > high_draw) {
            high = i;
            high_draw = draw;
        }
    }
    return b->items[high];
}

static int32_t bucket_perm_choose(cbucket *b, uint32_t x, uint32_t r) {
    int size = b->size;
    uint32_t pr = r % (uint32_t)size;
    if (b->perm_x != x || b->perm_n == 0) {
        b->perm_x = x;
        if (pr == 0) {
            int32_t s = (int32_t)(hash32_3(x, (uint32_t)b->id, 0) % (uint32_t)size);
            memset(b->perm, 0, sizeof(int32_t) * size);
            b->perm[0] = s;
            b->perm_n = 0xffff;
            return b->items[s];
        }
        for (int i = 0; i < size; i++) b->perm[i] = i;
        b->perm_n = 0;
    } else if (b->perm_n == 0xffff) {
        for (int i = 1; i < size; i++) b->perm[i] = i;
        b->perm[b->perm[0]] = 0;
        b->perm_n = 1;
    }
    for (uint32_t i = b->perm_n; i <= pr; i++) {
        if ((int)i < size - 1) {
            uint32_t j = hash32_3(x, (uint32_t)b->id, i) % (uint32_t)(size - i);
            if (j) {
                int32_t t = b->perm[i + j];
                b->perm[i + j] = b->perm[i];
                b->perm[i] = t;
            }
        }
        b->perm_n = i + 1;
    }
    return b->items[b->perm[pr]];
}

static int32_t bucket_list_choose(cbucket *b, uint32_t x, uint32_t r) {
    for (int i = b->size - 1; i >= 0; i--) {
        uint64_t w = hash32_4(x, (uint32_t)b->items[i], r, (uint32_t)b->id)
                     & 0xffff;
        w = (w * (uint64_t)b->sums[i]) >> 16;
        if ((int64_t)w < b->weights[i]) return b->items[i];
    }
    return b->items[0];
}

static int32_t bucket_straw_choose(cbucket *b, uint32_t x, uint32_t r) {
    /* legacy straw: straws array == weights slot in the blob */
    int high = 0;
    uint64_t high_draw = 0;
    for (int i = 0; i < b->size; i++) {
        uint64_t draw = (uint64_t)(hash32_3(x, (uint32_t)b->items[i], r)
                                   & 0xffff) * (uint64_t)b->weights[i];
        if (i == 0 || draw > high_draw) {
            high = i;
            high_draw = draw;
        }
    }
    return b->items[high];
}

static int32_t bucket_tree_choose(cbucket *b, uint32_t x, uint32_t r) {
    /* descend from the root (num_nodes/2) to a leaf (odd node); leaf i
       lives at node 2i+1 (mapper.c:195-222 semantics) */
    if (b->n_nodes < 2 || b->size == 0)
        return ITEM_NONE;  /* degenerate tree: terminal reject (callers
                              already guard size==0; belt and braces —
                              n=0 would loop forever below) */
    uint32_t n = (uint32_t)b->n_nodes >> 1;
    while (!(n & 1)) {
        uint64_t w = (uint64_t)b->node_weights[n];
        uint64_t t =
            ((uint64_t)hash32_4(x, n, r, (uint32_t)b->id) * w) >> 32;
        uint32_t half = (n & (~n + 1u)) >> 1;  /* 1 << (h-1) */
        uint32_t left = n - half;
        if (t < (uint64_t)b->node_weights[left]) n = left;
        else n += half;
    }
    return b->items[n >> 1];
}

static int32_t crush_bucket_choose(cmap *m, cbucket *b, uint32_t x, uint32_t r) {
    switch (b->alg) {
    case ALG_UNIFORM: return bucket_perm_choose(b, x, r);
    case ALG_LIST:    return bucket_list_choose(b, x, r);
    case ALG_TREE:    return bucket_tree_choose(b, x, r);
    case ALG_STRAW:   return bucket_straw_choose(b, x, r);
    case ALG_STRAW2:  return bucket_straw2_choose(m, b, x, r);
    default:          return ITEM_NONE; /* unknown alg: terminal reject */
    }
}

static int is_out(cmap *m, const uint32_t *weight, int nweight,
                  int32_t item, uint32_t x) {
    if (item >= nweight) return 1;
    uint32_t w = weight[item];
    if (w >= 0x10000) return 0;
    if (w == 0) return 1;
    if ((hash32_2(x, (uint32_t)item) & 0xffff) < w) return 0;
    return 1;
}

static int choose_firstn(cmap *m, cbucket *bucket, const uint32_t *weight,
                         int nweight, uint32_t x, int numrep, int type,
                         int32_t *out, int outpos, int out_size,
                         int tries, int recurse_tries, int local_retries,
                         int local_fallback_retries, int recurse_to_leaf,
                         int vary_r, int stable, int32_t *out2, int parent_r) {
    int rep;
    int count = out_size;
    for (rep = stable ? 0 : outpos; rep < numrep && count > 0; rep++) {
        int ftotal = 0;
        int skip_rep = 0;
        int32_t item = 0;
        int retry_descent = 1;
        while (retry_descent) {
            retry_descent = 0;
            cbucket *in = bucket;
            int flocal = 0;
            int retry_bucket = 1;
            while (retry_bucket) {
                retry_bucket = 0;
                uint32_t r = (uint32_t)(rep + parent_r + ftotal);
                int reject = 0, collide = 0;
                if (in->size == 0) {
                    reject = 1;
                } else {
                    if (local_fallback_retries > 0
                        && flocal >= (in->size >> 1)
                        && flocal > local_fallback_retries)
                        item = bucket_perm_choose(in, x, r);
                    else
                        item = crush_bucket_choose(m, in, x, r);
                    if (item >= m->max_devices) { skip_rep = 1; break; }
                    int itemtype = (item < 0)
                        ? (map_bucket(m, item) ? map_bucket(m, item)->type : -1)
                        : 0;
                    if (itemtype != type) {
                        if (item >= 0 || !map_bucket(m, item)) {
                            skip_rep = 1;
                            break;
                        }
                        in = map_bucket(m, item);
                        retry_bucket = 1;
                        continue;
                    }
                    for (int i = 0; i < outpos; i++)
                        if (out[i] == item) { collide = 1; break; }
                    if (!collide && recurse_to_leaf) {
                        if (item < 0) {
                            uint32_t sub_r = vary_r ? (r >> (vary_r - 1)) : 0;
                            int got = choose_firstn(
                                m, map_bucket(m, item), weight, nweight, x,
                                stable ? 1 : outpos + 1, 0,
                                out2, outpos, count,
                                recurse_tries, 0, local_retries,
                                local_fallback_retries, 0, vary_r, stable,
                                NULL, (int)sub_r);
                            if (got <= outpos) reject = 1;
                        } else {
                            out2[outpos] = item;
                        }
                    }
                    if (!reject && !collide && itemtype == 0)
                        reject = is_out(m, weight, nweight, item, x);
                }
                if (reject || collide) {
                    ftotal++;
                    flocal++;
                    if (collide && flocal <= local_retries)
                        retry_bucket = 1;
                    else if (local_fallback_retries > 0
                             && flocal <= in->size + local_fallback_retries)
                        retry_bucket = 1;
                    else if (ftotal < tries)
                        retry_descent = 1;
                    else
                        skip_rep = 1;
                }
            }
        }
        if (skip_rep) continue;
        out[outpos] = item;
        outpos++;
        count--;
    }
    return outpos;
}

static void choose_indep(cmap *m, cbucket *bucket, const uint32_t *weight,
                         int nweight, uint32_t x, int left, int numrep,
                         int type, int32_t *out, int outpos, int tries,
                         int recurse_tries, int recurse_to_leaf,
                         int32_t *out2, int parent_r) {
    int endpos = outpos + left;
    for (int rep = outpos; rep < endpos; rep++) {
        out[rep] = ITEM_UNDEF;
        if (out2) out2[rep] = ITEM_UNDEF;
    }
    for (int ftotal = 0; left > 0 && ftotal < tries; ftotal++) {
        for (int rep = outpos; rep < endpos; rep++) {
            if (out[rep] != ITEM_UNDEF) continue;
            cbucket *in = bucket;
            for (;;) {
                uint32_t r = (uint32_t)(rep + parent_r);
                if (in->alg == ALG_UNIFORM && in->size % numrep == 0)
                    r += (uint32_t)((numrep + 1) * ftotal);
                else
                    r += (uint32_t)(numrep * ftotal);
                if (in->size == 0) break;
                int32_t item = crush_bucket_choose(m, in, x, r);
                if (item >= m->max_devices) {
                    out[rep] = ITEM_NONE;
                    if (out2) out2[rep] = ITEM_NONE;
                    left--;
                    break;
                }
                int itemtype = (item < 0)
                    ? (map_bucket(m, item) ? map_bucket(m, item)->type : -1)
                    : 0;
                if (itemtype != type) {
                    if (item >= 0 || !map_bucket(m, item)) {
                        out[rep] = ITEM_NONE;
                        if (out2) out2[rep] = ITEM_NONE;
                        left--;
                        break;
                    }
                    in = map_bucket(m, item);
                    continue;
                }
                int collide = 0;
                for (int i = outpos; i < endpos; i++)
                    if (out[i] == item) { collide = 1; break; }
                if (collide) break;
                if (recurse_to_leaf) {
                    if (item < 0) {
                        choose_indep(m, map_bucket(m, item), weight, nweight,
                                     x, 1, numrep, 0, out2, rep,
                                     recurse_tries, 0, 0, NULL, (int)r);
                        if (out2[rep] == ITEM_NONE) break;
                    } else {
                        out2[rep] = item;
                    }
                }
                if (type == 0 && is_out(m, weight, nweight, item, x)) break;
                out[rep] = item;
                left--;
                break;
            }
        }
    }
    for (int rep = outpos; rep < endpos; rep++) {
        if (out[rep] == ITEM_UNDEF) out[rep] = ITEM_NONE;
        if (out2 && out2[rep] == ITEM_UNDEF) out2[rep] = ITEM_NONE;
    }
}

static void reset_work(cmap *m) {
    for (int i = 0; i < m->n_buckets; i++) {
        m->buckets[i].perm_x = 0;
        m->buckets[i].perm_n = 0;
    }
}

/* Returns number of results (out must hold result_max entries), or -1 on
 * result_max beyond the fixed working-set capacity — never a silent empty
 * answer for an over-large request. */
int crush_do_rule_c(void *h, int ruleno, uint32_t x, int32_t *out,
                    int result_max, const uint32_t *weight, int nweight) {
    cmap *m = h;
    int32_t w[64], o[64], c[64], o_sub[64], c_sub[64];
    if (result_max > 64) return -1;
    if (ruleno < 0 || ruleno >= m->n_rules || !m->rules[ruleno].present)
        return 0;
    crule *rule = &m->rules[ruleno];
    reset_work(m);

    int wsize = 0, nres = 0;

    int choose_tries = (int)m->tun[2] + 1;
    int choose_leaf_tries = 0;
    int local_retries = (int)m->tun[0];
    int local_fallback_retries = (int)m->tun[1];
    int vary_r = (int)m->tun[4];
    int stable = (int)m->tun[5];

    int32_t *wp = w, *op = o;

    for (int si = 0; si < rule->n_steps; si++) {
        cstep *st = &rule->steps[si];
        switch (st->op) {
        case OP_TAKE:
            if ((st->a1 >= 0 && st->a1 < m->max_devices)
                || map_bucket(m, st->a1)) {
                wp[0] = st->a1;
                wsize = 1;
            }
            break;
        case OP_SET_CHOOSE_TRIES:
            if (st->a1 > 0) choose_tries = st->a1;
            break;
        case OP_SET_CHOOSELEAF_TRIES:
            if (st->a1 > 0) choose_leaf_tries = st->a1;
            break;
        case OP_SET_CHOOSE_LOCAL_TRIES:
            if (st->a1 >= 0) local_retries = st->a1;
            break;
        case OP_SET_CHOOSE_LOCAL_FALLBACK_TRIES:
            if (st->a1 >= 0) local_fallback_retries = st->a1;
            break;
        case OP_SET_CHOOSELEAF_VARY_R:
            if (st->a1 >= 0) vary_r = st->a1;
            break;
        case OP_SET_CHOOSELEAF_STABLE:
            if (st->a1 >= 0) stable = st->a1;
            break;
        case OP_CHOOSE_FIRSTN:
        case OP_CHOOSELEAF_FIRSTN:
        case OP_CHOOSE_INDEP:
        case OP_CHOOSELEAF_INDEP: {
            if (wsize == 0) break;
            int firstn = (st->op == OP_CHOOSE_FIRSTN
                          || st->op == OP_CHOOSELEAF_FIRSTN);
            int recurse_to_leaf = (st->op == OP_CHOOSELEAF_FIRSTN
                                   || st->op == OP_CHOOSELEAF_INDEP);
            int osize = 0;
            for (int i = 0; i < wsize; i++) {
                int numrep = st->a1;
                if (numrep <= 0) {
                    numrep += result_max;
                    if (numrep <= 0) continue;
                }
                cbucket *bucket = map_bucket(m, wp[i]);
                if (!bucket) continue;
                int placed;
                if (firstn) {
                    int recurse_tries = choose_leaf_tries ? choose_leaf_tries
                        : (m->tun[3] ? 1 : choose_tries);
                    placed = choose_firstn(
                        m, bucket, weight, nweight, x, numrep, st->a2,
                        o_sub, 0, result_max - osize, choose_tries,
                        recurse_tries, local_retries, local_fallback_retries,
                        recurse_to_leaf, vary_r, stable, c_sub, 0);
                } else {
                    placed = numrep < result_max - osize
                        ? numrep : result_max - osize;
                    choose_indep(m, bucket, weight, nweight, x, placed,
                                 numrep, st->a2, o_sub, 0, choose_tries,
                                 choose_leaf_tries ? choose_leaf_tries : 1,
                                 recurse_to_leaf, c_sub, 0);
                }
                for (int j = 0; j < placed; j++) {
                    op[osize + j] = o_sub[j];
                    c[osize + j] = c_sub[j];
                }
                osize += placed;
            }
            if (recurse_to_leaf)
                for (int j = 0; j < osize; j++) op[j] = c[j];
            int32_t *t = wp; wp = op; op = t;
            wsize = osize;
            break;
        }
        case OP_EMIT:
            for (int i = 0; i < wsize && nres < result_max; i++)
                out[nres++] = wp[i];
            wsize = 0;
            break;
        default:
            break;
        }
    }
    return nres;
}

/* Batch driver: the ParallelPGMapper workload on one core.  out is
 * (nx, result_max) int32, NONE-padded.  Returns 0, or -1 on an over-large
 * result_max (mirrors crush_do_rule_c). */
int crush_batch_c(void *h, int ruleno, const uint32_t *xs, long nx,
                  int result_max, const uint32_t *weight, int nweight,
                  int32_t *out) {
    if (result_max > 64) return -1;
    for (long i = 0; i < nx; i++) {
        int32_t *row = out + i * result_max;
        int n = crush_do_rule_c(h, ruleno, xs[i], row, result_max,
                                weight, nweight);
        for (int j = n; j < result_max; j++) row[j] = ITEM_NONE;
    }
    return 0;
}
