"""Fused device-resident placement pipeline tail (raw -> up -> acting).

The batched mapper (crush.mapper_jax) computes raw CRUSH placements for
a whole pool in one device call, but the seed finished every PG
host-side: ``OSDMap._finish_pg_mapping`` (upmap -> up/state filter ->
primary affinity -> pg_temp/primary_temp) ran per PG per epoch, and the
PR 8 phase profiler attributed the mapping service's epoch cost to
exactly that ``host_tail``.  This module fuses the whole tail into ONE
jitted ladder over the PG axis:

    raw table (N, W) + pps seeds + dense epoch operands
        -> (up, up_primary, acting, acting_primary) for ALL N PGs

Semantics are the scalar oracle's, bit for bit (OSDMap.cc:2228-2445
via osd.osdmap._finish_pg_mapping):

  * ``pg_upmap`` rows replace the raw row wholesale when every entry
    exists and is not out; otherwise ``pg_upmap_items`` pairs apply
    SEQUENTIALLY (each pair sees the previous pair's rewrite, first
    occurrence of ``frm`` rewritten, ``to`` must be absent/exists/in);
  * up filtering keeps positions with NONE holes for erasure pools and
    stable-compacts for replicated ones;
  * primary affinity replays the hash coin-flip ladder with the pps
    seed (first winning position; default-affinity osds always win);
  * pg_temp replaces acting when present and non-empty; primary_temp
    overrides acting_primary, else the first non-NOSD member — unless
    acting equals up, which inherits up_primary.

Dense operand layout (built by OSDMap.dense_osd_vectors /
dense_pool_overrides): every per-PG table is NONE/NOSD padded to a
shared width ``W`` and pairs to ``P``, so pools (and daemons) sharing
one epoch's operand digest coalesce into one device call through
``ops.dispatch.submit_finish_ladder``; the per-OSD state/weight/
affinity vectors are captured operands, mesh-replicated on sharded
batches exactly like the CRUSH reweight vector.  Every step is
row-independent along the PG axis, so a mesh-sharded engine splits the
batch across devices with bit-identical results (the crush_kernel mesh
contract).

Output packing: one (N, 2*W + 4) int32 array per call —
``[up (W) | acting (W) | up_len | up_primary | acting_len |
acting_primary]`` — rows unpack to the oracle tuple with
``unpack_row``; padded cells are a deterministic NOSD fill, so two
packed rows are equal IFF their oracle tuples are, which is what lets
the mapping service diff whole epochs on device.
"""

from __future__ import annotations

import functools

import numpy as np

from ceph_tpu.crush.types import CRUSH_ITEM_NONE

NONE = CRUSH_ITEM_NONE          # 0x7FFFFFFF — raw-table hole
NOSD = -1                       # CEPH_NOSD — up/acting hole
_MAX_AFFINITY = 0x10000
_OSD_EXISTS = 1
_OSD_UP = 2


# ---------------------------------------------------------------------------
# the jitted ladder
# ---------------------------------------------------------------------------

def _ladder_impl(raw, pps, raw_len, up_rows, up_len, items, temp_rows,
                 temp_len, ptemp, state, weight, affinity, *,
                 erasure: bool):
    """See the module docstring.  All tables int32 except pps (uint32)
    and weight (int64); shapes: raw/up_rows/temp_rows (N, W), items
    (N, P, 2), the rest (N,) or (M,)."""
    import jax.numpy as jnp

    from ceph_tpu.ops.crush_kernel import hash32_2

    n, w = raw.shape
    m_osd = state.shape[0]
    iota = jnp.arange(w, dtype=jnp.int32)[None, :]

    def in_range(o):
        return (o >= 0) & (o < m_osd)

    def gather(vec, o):
        return vec[jnp.clip(o, 0, m_osd - 1)]

    def exists(o):
        return in_range(o) & ((gather(state, o) & _OSD_EXISTS) != 0)

    def is_up(o):
        return in_range(o) & ((gather(state, o) & _OSD_UP) != 0)

    def not_out(o):
        return in_range(o) & (gather(weight, o) != 0)

    # -- base row: the raw list _finish_from hands to _apply_upmap
    # (replicated compacts NONE holes first; erasure keeps positions)
    if erasure:
        base = raw
        base_len = raw_len
    else:
        keep0 = raw != NONE
        order0 = jnp.argsort(~keep0, axis=1, stable=True)
        base = jnp.take_along_axis(raw, order0, axis=1)
        base_len = jnp.sum(keep0, axis=1).astype(jnp.int32)
        base = jnp.where(iota < base_len[:, None], base, NONE)

    # -- pg_upmap_items: sequential pair rewrites (each pair sees the
    # previous pair's result — a static unroll over the pair axis).
    # Padded pairs are (-1, -1): -1 never appears in a raw row (cells
    # are osd ids or NONE), so pads can never match, while a genuine
    # NONE `frm` matches erasure holes exactly like list.index does.
    # Both scans mask to the ACTIVE row length: the scalar list simply
    # has no cells past it, and an unmasked NONE `frm` would match a
    # NONE pad cell on a hole-free row — writing `to` into the pad and
    # making a later pair's `to not in raw` check wrongly fail.
    wrow = base
    base_mask = iota < base_len[:, None]
    p_pairs = items.shape[1]
    for p in range(p_pairs):
        frm = items[:, p, 0]
        to = items[:, p, 1]
        match = base_mask & (wrow == frm[:, None])
        has = jnp.any(match, axis=1)
        to_in = jnp.any(base_mask & (wrow == to[:, None]), axis=1)
        cond = has & ~to_in & exists(to) & not_out(to)
        first = jnp.argmax(match, axis=1).astype(jnp.int32)
        wrow = jnp.where(cond[:, None] & (iota == first[:, None]),
                         to[:, None], wrow)

    # -- pg_upmap: wholesale replacement when present and every entry
    # exists and is in (OSDMap._apply_upmap's validity gate); an
    # invalid or absent entry falls through to the items result
    upmask = iota < up_len[:, None]
    ent_ok = ~upmask | (exists(up_rows) & not_out(up_rows))
    allok = jnp.all(ent_ok, axis=1) & (up_len > 0)
    row = jnp.where(allok[:, None], up_rows, wrow)
    row_len = jnp.where(allok, up_len, base_len)

    # -- raw -> up: drop nonexistent/down osds (NONE-positional for
    # erasure, stable compaction for replicated; OSDMap.cc:2275-2297)
    lenmask = iota < row_len[:, None]
    valid = lenmask & (row != NONE) & exists(row) & is_up(row)
    if erasure:
        up = jnp.where(lenmask, jnp.where(valid, row, NOSD), NOSD)
        up_len_o = row_len
    else:
        order = jnp.argsort(~valid, axis=1, stable=True)
        up = jnp.take_along_axis(row, order, axis=1)
        up_len_o = jnp.sum(valid, axis=1).astype(jnp.int32)
        up = jnp.where(iota < up_len_o[:, None], up, NOSD)
    up_real = up != NOSD
    has_any = jnp.any(up_real, axis=1)
    firstj = jnp.argmax(up_real, axis=1)
    first_val = jnp.take_along_axis(up, firstj[:, None], axis=1)[:, 0]
    up_primary = jnp.where(has_any, first_val, NOSD)

    # -- primary affinity (OSDMap.cc _apply_primary_affinity): skip
    # entirely when every member has default affinity; otherwise the
    # first member winning its coin flip (default always wins) takes
    # primary, falling back to the positional primary
    aff = jnp.where(in_range(up), gather(affinity, up),
                    _MAX_AFFINITY).astype(jnp.int32)
    non_default = up_real & (aff != _MAX_AFFINITY)
    default_all = ~jnp.any(non_default, axis=1)
    h = (hash32_2(pps[:, None], up.astype(jnp.uint32))
         >> jnp.uint32(16)).astype(jnp.int32)
    win = up_real & ((aff == _MAX_AFFINITY) | (h < aff))
    has_win = jnp.any(win, axis=1)
    wj = jnp.argmax(win, axis=1)
    wval = jnp.take_along_axis(up, wj[:, None], axis=1)[:, 0]
    prim = jnp.where(default_all, up_primary,
                     jnp.where(has_win, wval, up_primary))

    # -- temps (OSDMap.cc:2417-2445): pg_temp replaces acting when
    # present and non-empty; primary_temp overrides, else the first
    # non-NOSD member — with acting == up inheriting up_primary
    tset = temp_len > 0
    acting = jnp.where(tset[:, None], temp_rows, up)
    act_len = jnp.where(tset, temp_len, up_len_o)
    act_real = acting != NOSD
    act_has = jnp.any(act_real, axis=1)
    aj = jnp.argmax(act_real, axis=1)
    act_first = jnp.where(
        act_has, jnp.take_along_axis(acting, aj[:, None], axis=1)[:, 0],
        NOSD)
    same = (act_len == up_len_o) & jnp.all(acting == up, axis=1)
    ap = jnp.where(ptemp != NOSD, ptemp,
                   jnp.where(same, prim, act_first))

    return jnp.concatenate(
        [up, acting, up_len_o[:, None], prim[:, None],
         act_len[:, None], ap[:, None]], axis=1).astype(jnp.int32)


@functools.lru_cache(maxsize=2)
def _ladder_jit(erasure: bool):
    import jax
    return jax.jit(functools.partial(_ladder_impl, erasure=erasure))


# ---------------------------------------------------------------------------
# numpy host oracle (the engine's pg_finish fallback channel)
# ---------------------------------------------------------------------------

_CRUSH_HASH_SEED = 1315423911    # crush/hash.c crush_hash_seed


def _mix_np(a, b, c):
    a = a - b - c; a = a ^ (c >> np.uint32(13))
    b = b - c - a; b = b ^ (a << np.uint32(8))
    c = c - a - b; c = c ^ (b >> np.uint32(13))
    a = a - b - c; a = a ^ (c >> np.uint32(12))
    b = b - c - a; b = b ^ (a << np.uint32(16))
    c = c - a - b; c = c ^ (b >> np.uint32(5))
    a = a - b - c; a = a ^ (c >> np.uint32(3))
    b = b - c - a; b = b ^ (a << np.uint32(10))
    c = c - a - b; c = c ^ (b >> np.uint32(15))
    return a, b, c


def _hash32_2_np(a, b):
    """crush_hash32_2 elementwise on numpy uint32 — the affinity
    coin-flip hash, host-side (no jax import on this path: the device
    runtime being broken is exactly when this runs)."""
    a = np.asarray(a, dtype=np.uint32)
    b = np.asarray(b, dtype=np.uint32)
    a, b = np.broadcast_arrays(a, b)
    h = np.uint32(_CRUSH_HASH_SEED) ^ a ^ b
    x = np.full(h.shape, 231232, dtype=np.uint32)
    y = np.full(h.shape, 1232, dtype=np.uint32)
    a, b, h = _mix_np(a.copy(), b.copy(), h)
    x, a, h = _mix_np(x, a, h)
    b, y, h = _mix_np(b, y, h)
    return h


def ladder_ref(raw, pps, raw_len, up_rows, up_len, items, temp_rows,
               temp_len, ptemp, state, weight, affinity, *,
               erasure: bool) -> np.ndarray:
    """Numpy twin of ``_ladder_impl`` — the bit-exact host oracle the
    dispatch engine degrades the ``pg_finish`` channel to when the
    device path is out (and the unit tests' ground truth for the
    fused ladder).  Operand-for-operand and step-for-step the same
    pipeline; see ``_ladder_impl`` for the semantics commentary."""
    raw = np.asarray(raw, dtype=np.int32)
    pps = np.asarray(pps, dtype=np.uint32)
    raw_len = np.asarray(raw_len, dtype=np.int32)
    up_rows = np.asarray(up_rows, dtype=np.int32)
    up_len = np.asarray(up_len, dtype=np.int32)
    items = np.asarray(items, dtype=np.int32)
    temp_rows = np.asarray(temp_rows, dtype=np.int32)
    temp_len = np.asarray(temp_len, dtype=np.int32)
    ptemp = np.asarray(ptemp, dtype=np.int32)
    state = np.asarray(state, dtype=np.int32)
    weight = np.asarray(weight)
    affinity = np.asarray(affinity, dtype=np.int32)

    n, w = raw.shape
    m_osd = state.shape[0]
    iota = np.arange(w, dtype=np.int32)[None, :]

    def in_range(o):
        return (o >= 0) & (o < m_osd)

    def gather(vec, o):
        return vec[np.clip(o, 0, m_osd - 1)]

    def exists(o):
        return in_range(o) & ((gather(state, o) & _OSD_EXISTS) != 0)

    def is_up(o):
        return in_range(o) & ((gather(state, o) & _OSD_UP) != 0)

    def not_out(o):
        return in_range(o) & (gather(weight, o) != 0)

    if erasure:
        base = raw
        base_len = raw_len
    else:
        keep0 = raw != NONE
        order0 = np.argsort(~keep0, axis=1, kind="stable")
        base = np.take_along_axis(raw, order0, axis=1)
        base_len = np.sum(keep0, axis=1).astype(np.int32)
        base = np.where(iota < base_len[:, None], base, NONE)

    wrow = base
    base_mask = iota < base_len[:, None]
    for p in range(items.shape[1]):
        frm = items[:, p, 0]
        to = items[:, p, 1]
        match = base_mask & (wrow == frm[:, None])
        has = np.any(match, axis=1)
        to_in = np.any(base_mask & (wrow == to[:, None]), axis=1)
        cond = has & ~to_in & exists(to) & not_out(to)
        first = np.argmax(match, axis=1).astype(np.int32)
        wrow = np.where(cond[:, None] & (iota == first[:, None]),
                        to[:, None], wrow)

    upmask = iota < up_len[:, None]
    ent_ok = ~upmask | (exists(up_rows) & not_out(up_rows))
    allok = np.all(ent_ok, axis=1) & (up_len > 0)
    row = np.where(allok[:, None], up_rows, wrow)
    row_len = np.where(allok, up_len, base_len)

    lenmask = iota < row_len[:, None]
    valid = lenmask & (row != NONE) & exists(row) & is_up(row)
    if erasure:
        up = np.where(lenmask, np.where(valid, row, NOSD), NOSD)
        up_len_o = row_len
    else:
        order = np.argsort(~valid, axis=1, kind="stable")
        up = np.take_along_axis(row, order, axis=1)
        up_len_o = np.sum(valid, axis=1).astype(np.int32)
        up = np.where(iota < up_len_o[:, None], up, NOSD)
    up_real = up != NOSD
    has_any = np.any(up_real, axis=1)
    firstj = np.argmax(up_real, axis=1)
    first_val = np.take_along_axis(up, firstj[:, None], axis=1)[:, 0]
    up_primary = np.where(has_any, first_val, NOSD)

    aff = np.where(in_range(up), gather(affinity, up),
                   _MAX_AFFINITY).astype(np.int32)
    non_default = up_real & (aff != _MAX_AFFINITY)
    default_all = ~np.any(non_default, axis=1)
    h = (_hash32_2_np(pps[:, None], up.astype(np.uint32))
         >> np.uint32(16)).astype(np.int32)
    win = up_real & ((aff == _MAX_AFFINITY) | (h < aff))
    has_win = np.any(win, axis=1)
    wj = np.argmax(win, axis=1)
    wval = np.take_along_axis(up, wj[:, None], axis=1)[:, 0]
    prim = np.where(default_all, up_primary,
                    np.where(has_win, wval, up_primary))

    tset = temp_len > 0
    acting = np.where(tset[:, None], temp_rows, up)
    act_len = np.where(tset, temp_len, up_len_o)
    act_real = acting != NOSD
    act_has = np.any(act_real, axis=1)
    aj = np.argmax(act_real, axis=1)
    act_first = np.where(
        act_has, np.take_along_axis(acting, aj[:, None], axis=1)[:, 0],
        NOSD)
    same = (act_len == up_len_o) & np.all(acting == up, axis=1)
    ap = np.where(ptemp != NOSD, ptemp,
                  np.where(same, prim, act_first))

    return np.concatenate(
        [up, acting, up_len_o[:, None], prim[:, None],
         act_len[:, None], ap[:, None]], axis=1).astype(np.int32)


def ladder_cache_entries() -> int:
    """Compile-cache entries across the fused-ladder entry points — the
    dispatch profiler's retrace/compile probe differences this.  The
    factory call is cached and only builds the jit wrapper, never
    traces."""
    return sum(_ladder_jit(flag)._cache_size() for flag in (False, True))


def run_ladder(operands: "LadderOperands") -> np.ndarray:
    """Direct (engine-less) fused-ladder evaluation: one jitted device
    call, result materialized to host.  The PG axis pads up to a
    power-of-two bucket (all-zero rows compute garbage that is sliced
    off — the dispatch engine's shape-bucketing rule) so the jit cache
    is bounded by the bucket table, not the pg_num population.  The
    dispatch-engine path is ops.dispatch.submit_finish_ladder."""
    n = operands.raw.shape[0]
    bucket = 1 << max(0, (n - 1).bit_length())
    pad = bucket - n

    def padded(arr):
        if not pad:
            return arr
        return np.concatenate(
            [arr, np.zeros((pad,) + arr.shape[1:], dtype=arr.dtype)])

    fn = _ladder_jit(operands.erasure)
    out = fn(padded(operands.raw), padded(operands.pps),
             padded(operands.raw_len), padded(operands.up_rows),
             padded(operands.up_len), padded(operands.items),
             padded(operands.temp_rows), padded(operands.temp_len),
             padded(operands.ptemp), operands.state, operands.weight,
             operands.affinity)
    # analysis: allow[blocking] -- engine-less entry point: callers want the host table
    return np.asarray(out)[:n]


# ---------------------------------------------------------------------------
# dense operand bundle
# ---------------------------------------------------------------------------

class LadderOperands:
    """One pool's (or one what-if batch's) dense ladder operands.

    ``raw``/``pps``/``raw_len`` and the override tables have the PG
    leading axis (they coalesce/shard through the engine's data+aux
    channels); ``state``/``weight``/``affinity`` are the per-OSD
    vectors shared by every pool of the epoch (captured operands,
    mesh-replicated by the submit helper)."""

    __slots__ = ("raw", "pps", "raw_len", "up_rows", "up_len", "items",
                 "temp_rows", "temp_len", "ptemp", "state", "weight",
                 "affinity", "erasure", "width")

    def __init__(self, *, raw, pps, raw_len, up_rows, up_len, items,
                 temp_rows, temp_len, ptemp, state, weight, affinity,
                 erasure, width):
        self.raw = raw
        self.pps = pps
        self.raw_len = raw_len
        self.up_rows = up_rows
        self.up_len = up_len
        self.items = items
        self.temp_rows = temp_rows
        self.temp_len = temp_len
        self.ptemp = ptemp
        self.state = state
        self.weight = weight
        self.affinity = affinity
        self.erasure = bool(erasure)
        self.width = int(width)

    def aux(self) -> tuple:
        """The per-PG side arrays in submit_finish_ladder's aux order."""
        return (self.pps, self.raw_len, self.up_rows, self.up_len,
                self.items, self.temp_rows, self.temp_len, self.ptemp)


def pad_raw(raw: np.ndarray, width: int) -> np.ndarray:
    """(N, w) raw table NONE-padded to the shared ladder width."""
    raw = np.asarray(raw, dtype=np.int32)
    n, w = raw.shape
    if w == width:
        return raw
    out = np.full((n, width), NONE, dtype=np.int32)
    out[:, :w] = raw
    return out


def build_operands(m, pool_id: int, pool, raw: np.ndarray,
                   pps: np.ndarray, *, width: int, pairs: int,
                   vectors=None) -> LadderOperands:
    """Dense ladder operands for one pool at one epoch.  ``width`` and
    ``pairs`` are the epoch-shared table widths (so pools coalesce);
    ``vectors`` memoizes m.dense_osd_vectors() across pools."""
    n = int(pool.pg_num)
    raw_np = np.asarray(raw, dtype=np.int32)
    raw_w = raw_np.shape[1] if raw_np.ndim == 2 else 0
    if vectors is None:
        vectors = m.dense_osd_vectors()
    state, weight, affinity = vectors
    up_rows, up_len, items, temp_rows, temp_len, ptemp = \
        m.dense_pool_overrides(pool_id, n, width, pairs)
    return LadderOperands(
        raw=pad_raw(raw_np.reshape(n, raw_w), width),
        pps=np.asarray(pps, dtype=np.uint32),
        raw_len=np.full(n, raw_w, dtype=np.int32),
        up_rows=up_rows, up_len=up_len, items=items,
        temp_rows=temp_rows, temp_len=temp_len, ptemp=ptemp,
        state=state, weight=weight, affinity=affinity,
        erasure=pool.is_erasure(), width=width)


def pool_widths(m, pools=None) -> tuple[int, int]:
    """(width, pairs) shared by every pool of an epoch: W covers the
    widest of pool size / pg_upmap row / pg_temp row, P the longest
    pg_upmap_items pair list — each rounded up (P to a power of two,
    W's excess over the max size to a power of two) so the jit/bucket
    key space stays bounded under override churn."""
    if pools is None:
        pools = m.pools
    w = max((int(p.size) for p in pools.values()), default=1)
    w_need = w
    for (pid, _pg), lst in m.pg_upmap.items():
        if pid in pools:
            w_need = max(w_need, len(lst))
    for (pid, _pg), lst in m.pg_temp.items():
        if pid in pools:
            w_need = max(w_need, len(lst))
    if w_need > w:
        extra = w_need - w
        w += 1 << (extra - 1).bit_length() if extra > 1 else 1
    p = 1
    for (pid, _pg), lst in m.pg_upmap_items.items():
        if pid in pools:
            p = max(p, len(lst))
    if p > 1:
        p = 1 << (p - 1).bit_length()
    return max(w, 1), p


def unpack_row(row, width: int) -> tuple[list[int], int, list[int], int]:
    """One packed ladder row -> the oracle's (up, up_primary, acting,
    acting_primary) tuple."""
    lst = row.tolist() if hasattr(row, "tolist") else list(row)
    w = width
    up_len = lst[2 * w]
    act_len = lst[2 * w + 2]
    return (lst[:up_len], lst[2 * w + 1],
            lst[w:w + act_len], lst[2 * w + 3])


def normalize_packed(packed: np.ndarray, width: int,
                     to_width: int) -> np.ndarray:
    """Re-pad a packed table to a wider layout (NOSD fill) so two
    epochs built at different shared widths compare row-for-row."""
    if width == to_width:
        return packed
    n = packed.shape[0]
    out = np.full((n, 2 * to_width + 4), NOSD, dtype=np.int32)
    out[:, :width] = packed[:, :width]
    out[:, to_width:to_width + width] = packed[:, width:2 * width]
    out[:, 2 * to_width:] = packed[:, 2 * width:]
    return out
