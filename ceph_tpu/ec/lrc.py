"""LRC — Locally Repairable Code built by *layering* other plugins
(src/erasure-code/lrc/ErasureCodeLrc.cc analog).

The profile describes chunk positions with a `mapping` string and a
JSON `layers` list; each layer names the positions it sees ('D' = data
the layer encodes, 'c' = coding it produces, '_' = not in this layer)
and the sub-plugin profile that does the math:

    mapping=__DD__DD
    layers=[["_cDD_cDD", {"plugin": "jerasure", "k": "2", "m": "1"}],
            ["cDDDcDDD"? ...]]

Encode walks the layers in order: a layer reads the current values at
its 'D' positions and writes its 'c' positions (so later layers can
protect earlier layers' parities — exactly the reference's pyramid
construction).  Decode walks layers smallest-repair-first: any layer
whose surviving members suffice repairs its own missing positions
locally; iterate until stable (ErasureCodeLrc::_minimum_to_decode
layer-picking semantics).  Each layer's math is a registry sub-plugin,
recursively, so layer encodes are the same batched MXU matmuls.
"""

from __future__ import annotations

import json

import numpy as np

from .interface import ErasureCodeInterface, ErasureCodeProfile
from .registry import instance as registry_instance, register


class _Layer:
    def __init__(self, mapping: str, profile: dict):
        self.mapping = mapping
        self.data_pos = [i for i, ch in enumerate(mapping) if ch == "D"]
        self.coding_pos = [i for i, ch in enumerate(mapping) if ch == "c"]
        prof = dict(profile)
        prof.setdefault("k", str(len(self.data_pos)))
        prof.setdefault("m", str(len(self.coding_pos)))
        plugin = prof.pop("plugin", "jerasure")
        self.codec = registry_instance().factory(plugin, prof)
        if self.codec.get_data_chunk_count() != len(self.data_pos) \
                or self.codec.get_coding_chunk_count() \
                != len(self.coding_pos):
            raise ValueError(
                f"layer {mapping!r}: sub-plugin k/m do not match the "
                f"D/c counts")

    @property
    def members(self) -> list[int]:
        return self.data_pos + self.coding_pos


class ErasureCodeLrc(ErasureCodeInterface):
    """Interface-level plugin (not a matrix code itself: the layers are)."""

    supports_rmw_striping = False

    def __init__(self):
        self.mapping = ""
        self.layers: list[_Layer] = []
        self.runtime = "tpu"

    # -- init -----------------------------------------------------------------

    def init(self, profile: ErasureCodeProfile) -> None:
        self.mapping = profile.get("mapping", "")
        if not self.mapping:
            raise ValueError("lrc requires a mapping= string")
        layers = profile.get("layers", "")
        if isinstance(layers, str):
            layers = json.loads(layers) if layers else []
        if not layers:
            raise ValueError("lrc requires a layers= JSON list")
        self.runtime = profile.get("runtime", "tpu")
        self.layers = []
        for entry in layers:
            lmap, lprof = entry[0], (entry[1] if len(entry) > 1 else {})
            if len(lmap) != len(self.mapping):
                raise ValueError(
                    f"layer {lmap!r} length != mapping {self.mapping!r}")
            if isinstance(lprof, str):
                lprof = json.loads(lprof) if lprof else {}
            lprof = dict(lprof)
            lprof.setdefault("runtime", self.runtime)
            self.layers.append(_Layer(lmap, lprof))
        covered = {p for l in self.layers for p in l.members}
        if covered != set(range(len(self.mapping))):
            raise ValueError(
                f"layers cover {sorted(covered)}; mapping needs all of "
                f"0..{len(self.mapping) - 1}")

    # -- geometry -------------------------------------------------------------

    def get_chunk_count(self) -> int:
        return len(self.mapping)

    def get_data_chunk_count(self) -> int:
        return sum(1 for ch in self.mapping if ch == "D")

    def get_coding_chunk_count(self) -> int:
        return self.get_chunk_count() - self.get_data_chunk_count()

    def get_sub_chunk_count(self) -> int:
        return 1

    def get_chunk_size(self, stripe_width: int) -> int:
        k = self.get_data_chunk_count()
        # the chunk must be SIMD_ALIGN-aligned so every layer's stripe
        # (layer_k * chunk) re-pads to itself — otherwise layer parities
        # come out longer than the data chunks
        from .base import SIMD_ALIGN
        align = k * SIMD_ALIGN
        padded = (stripe_width + align - 1) // align * align
        return padded // k

    def get_chunk_mapping(self) -> list:
        return []

    # -- encode ---------------------------------------------------------------

    def _data_positions(self) -> list[int]:
        return [i for i, ch in enumerate(self.mapping) if ch == "D"]

    def encode(self, want_to_encode: set, data: bytes) -> dict:
        k = self.get_data_chunk_count()
        chunk = self.get_chunk_size(len(data))
        padded = np.zeros(k * chunk, dtype=np.uint8)
        padded[:len(data)] = np.frombuffer(data, dtype=np.uint8)
        split = padded.reshape(k, chunk)
        values: dict[int, np.ndarray] = {}
        for idx, pos in enumerate(self._data_positions()):
            values[pos] = split[idx]
        for layer in self.layers:
            stripe = b"".join(values[p].tobytes() for p in layer.data_pos)
            enc = layer.codec.encode(
                set(range(len(layer.members))), stripe)
            for ci, pos in enumerate(layer.coding_pos):
                values[pos] = np.frombuffer(
                    enc[len(layer.data_pos) + ci], dtype=np.uint8)
        return {i: values[i].tobytes() for i in want_to_encode}

    def encode_chunks(self, data_chunks):
        raise NotImplementedError("lrc encodes via its layers")

    # -- decode (layer-local repair first) ------------------------------------

    def minimum_to_decode(self, want_to_read: set, available: set) -> set:
        plan = self._repair_plan(set(want_to_read), set(available))
        if plan is None:
            raise IOError(
                f"lrc cannot decode {sorted(want_to_read - available)}")
        return plan

    def minimum_to_decode_with_cost(self, want_to_read: set,
                                    available: dict) -> tuple[set, int]:
        chosen = self.minimum_to_decode(set(want_to_read), set(available))
        return chosen, sum(available.get(i, 1) for i in chosen)

    def _repair_plan(self, want: set, available: set):
        """Chunks to read so that iterated layer-local repair reaches
        `want`; None if unrecoverable."""
        have = set(available)
        reads: set = set()
        progress = True
        while not want <= have and progress:
            progress = False
            # smallest layer first: local repair reads fewest chunks
            for layer in sorted(self.layers, key=lambda l: len(l.members)):
                members = set(layer.members)
                lost = members - have
                if not lost:
                    continue
                surviving = members & have
                try:
                    need = layer.codec.minimum_to_decode(
                        self._to_layer(layer, lost),
                        self._to_layer(layer, surviving))
                except IOError:
                    continue
                reads |= {layer.members[i] for i in need} & available
                have |= lost
                progress = True
        if want <= have:
            return (reads | (want & available))
        return None

    @staticmethod
    def _to_layer(layer: _Layer, positions: set) -> set:
        return {layer.members.index(p) for p in positions
                if p in layer.members}

    def decode(self, want_to_read: set, chunks: dict) -> dict:
        values = {i: np.frombuffer(v, dtype=np.uint8)
                  for i, v in chunks.items()}
        want = set(want_to_read)
        progress = True
        while not want <= set(values) and progress:
            progress = False
            for layer in sorted(self.layers, key=lambda l: len(l.members)):
                members = set(layer.members)
                lost = members - set(values)
                if not lost:
                    continue
                surviving = members & set(values)
                lchunks = {layer.members.index(p): values[p].tobytes()
                           for p in surviving}
                try:
                    got = layer.codec.decode(
                        self._to_layer(layer, lost), lchunks)
                except IOError:
                    continue
                for li, blob in got.items():
                    values[layer.members[li]] = np.frombuffer(
                        blob, dtype=np.uint8)
                progress = True
        missing = want - set(values)
        if missing:
            raise IOError(f"lrc cannot decode {sorted(missing)}")
        return {i: values[i].tobytes() for i in want}

    def decode_concat(self, chunks: dict) -> bytes:
        data_pos = self._data_positions()
        out = self.decode(set(data_pos), chunks)
        return b"".join(out[i] for i in data_pos)

    def create_rule(self, name: str, crush_map) -> int:
        from ceph_tpu.crush.builder import add_simple_rule
        return add_simple_rule(crush_map, -1, 0, "indep")


register("lrc", lambda profile: ErasureCodeLrc())
