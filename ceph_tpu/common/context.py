"""CephTpuContext — the per-process service locator (CephContext analog,
src/common/ceph_context.h).

Owns the config, the perf-counter collection, the admin socket, and the log
levels; daemons and libraries receive one context and hang their services off
it, exactly as every reference component takes a CephContext*.
"""

from __future__ import annotations

from .admin_socket import AdminSocket
from .config import Config
from .perf_counters import PerfCountersCollection


class CephTpuContext:
    def __init__(self, name: str = "client", admin_path: str | None = None,
                 *, process_index: int | None = None,
                 n_processes: int | None = None,
                 coordinator: str | None = None):
        """``process_index``/``n_processes``/``coordinator`` opt this
        context into the multi-controller deployment mode (SURVEY §5's
        two-plane design): jax.distributed initializes against the
        coordinator, the kernel mesh spans every process's devices
        (engines place their own flushes over the process-local
        submesh — the ICI domain), and ``messenger_stack_for`` routes
        control-plane traffic ici intra-process / tcp across."""
        self.name = name
        self.process_index = 0 if process_index is None else int(process_index)
        self.n_processes = 1 if not n_processes else int(n_processes)
        if self.n_processes > 1:
            from ceph_tpu.parallel.dcn import init_distributed
            init_distributed(coordinator, self.n_processes,
                             self.process_index)
        self.conf = Config()
        self.perf = PerfCountersCollection()
        self.admin = AdminSocket(admin_path)
        self.admin.register_command(
            "perf dump", lambda **kw: self.perf.dump(),
            "dump perf counters")
        self.admin.register_command(
            "config show", lambda **kw: self.conf.show(),
            "show effective config")
        self.admin.register_command(
            "config diff", lambda **kw: self.conf.diff(),
            "show non-default config")
        self.admin.register_command(
            "config set",
            lambda name, value, **kw: (self.conf.set(name, value), "ok")[1],
            "set a runtime option")
        self.admin.register_command(
            "config get",
            lambda name, **kw: {name: self.conf.get(name)},
            "get one option")
        from ceph_tpu.common import tracing
        tracing.configure_from_conf(self.conf)
        trace_dump = (lambda trace_id=None, **kw: tracing.dump(
            int(trace_id) if trace_id else None))
        # one command, one help string, one reference-style alias: both
        # spellings serve the span-structured rows (span_id /
        # parent_span_id / dur / attrs per row)
        self.admin.register_command(
            "dump_tracing", trace_dump,
            "span-structured cross-daemon trace timelines "
            "[trace_id]: time-ordered rows with span_id, "
            "parent_span_id, duration and attributes",
            aliases=("dump_traces",))
        self.admin.register_command(
            "dump_slow_traces", lambda **kw: tracing.slow_traces(),
            "completed traces retained by tail sampling (root span "
            "over tracing_slow_threshold)")
        from ceph_tpu.ops import telemetry
        telemetry.configure_from_conf(self.conf)
        # fault injection + degraded-mode visibility: the failpoint
        # registry is process-global (like the telemetry registry);
        # this context's config option and admin commands drive it
        from ceph_tpu.common import failpoint
        failpoint.configure_from_conf(self.conf)
        failpoint.register_admin(self.admin)
        self.admin.register_command(
            "dump_fault_stats", lambda **kw: self.fault_digest(),
            "device-runtime fault/degradation counters per dispatch "
            "engine: retries, host-oracle fallback batches/stripes, "
            "circuit-breaker opens/closes and per-channel states, "
            "background-probe outcomes, thread deaths/restarts")
        self.admin.register_command(
            "dump_kernel_stats", lambda **kw: telemetry.dump(),
            "device-kernel telemetry: latency/batch histograms, "
            "byte counters, jit retrace counts")
        #: lazily-built cross-op coalescing engine (ops.dispatch); one
        #: per context, like every other service hung off it.  The
        #: build is locked: two racing first callers splitting across
        #: two engines would break per-key submission-order delivery
        from ceph_tpu.common import lockdep
        self._dispatch = None
        self._decode_dispatch = None
        self._mapping_service = None
        self._kernel_mesh = None        # (knob_value, mesh-or-None)
        self._dispatch_lock = lockdep.make_lock(
            "CephTpuContext::dispatch_build")
        # knob flip rebuilds the mesh and swaps it into LIVE engines
        # (takes effect from their next flush)
        self.conf.add_observer(
            "kernel_mesh_devices", lambda _n, _v: self._remesh())
        self.admin.register_command(
            "dump_dispatch_stats",
            lambda **kw: {"encode": telemetry.dispatch_dump(),
                          "decode": telemetry.decode_dispatch_dump()},
            "dispatch-engine telemetry (encode + decode engines): "
            "coalesce factor, queue delay/depth, flush reasons, "
            "in-flight batches, mesh fan-out (devices per flush, "
            "sharded-flush count, mesh shape); decode adds "
            "erasure-pattern heterogeneity per call and "
            "pattern-table size")
        self.admin.register_command(
            "dump_mapping_stats",
            lambda **kw: telemetry.mapping_dump(),
            "shared PG-mapping-service telemetry: epoch-update "
            "latency, pools recomputed vs reused, changed-PG counts, "
            "epoch-skips, cache lookups vs scalar fallbacks, and the "
            "per-epoch device/delta/host-tail phase split")
        self.admin.register_command(
            "dump_pipeline_profile",
            lambda **kw: telemetry.pipeline_profile_dump(),
            "per-batch pipeline phase attribution for both dispatch "
            "engines: queue-wait/build/place/launch/compute/"
            "materialize/deliver histograms per kernel family, the "
            "compile ledger (first-call jit cost, separate from "
            "steady-state compute), device busy-seconds/utilization/"
            "shard-imbalance, a ring of recent per-batch records, and "
            "the mapping service's epoch phase split")

    def fault_digest(self) -> dict:
        """telemetry.fault_digest() with THIS context's engines'
        per-channel breaker maps overlaid.  The counter sinks are
        process-global (every in-process daemon shares them, which is
        what a per-process exporter wants), but ``breaker_states`` is
        keyed by channel only — daemon B re-closing a breaker there is
        last-writer-wins over daemon A's still-open one.  The shipped
        MMgrReport ``faults`` tail and the admin payload attribute
        degradation to ONE daemon, so they must read breaker ground
        truth from that daemon's own engines; a context that never
        built an engine has no breakers (and must not inherit another
        daemon's)."""
        from ceph_tpu.ops import telemetry
        digest = telemetry.fault_digest()
        with self._dispatch_lock:
            engines = {"encode": self._dispatch,
                       "decode": self._decode_dispatch}
        for key, eng in engines.items():
            digest[key]["breaker_states"] = (
                eng.breaker_states() if eng is not None else {})
        return digest

    def kernel_mesh(self):
        """The ("dp", "ec") device mesh this context's dispatch engines
        shard over, or None (knob ``kernel_mesh_devices`` = 1, a
        single-device backend, or jax unavailable).  Built lazily on
        first engine construction — a context that never touches a
        kernel never imports jax.  In the multi-controller deployment
        mode this is the GLOBAL mesh spanning every process; engines
        place their own flushes over its process-local submesh."""
        knob = int(self.conf.get("kernel_mesh_devices"))
        with self._dispatch_lock:
            cached = self._kernel_mesh
            if cached is not None and cached[0] == knob:
                return cached[1]
            mesh = None
            if knob != 1:
                try:
                    import jax
                    n = len(jax.devices())
                    if knob > 1:
                        n = min(knob, n)
                    if n > 1:
                        from ceph_tpu.parallel.mesh import make_mesh
                        # pure dp by default: the engine coalesce axis
                        # is stripes/PGs; an ec axis only pays when the
                        # codec's k+m divides it (factor_devices)
                        mesh = make_mesh(n)
                except Exception as e:
                    # loud, like the engine's placement failure path:
                    # an operator who asked for N devices must not
                    # silently run single-device with no diagnostic
                    from ceph_tpu.common.logging import dout
                    dout("context", 0, "%s: kernel mesh unavailable, "
                         "engines run single-device: %r", self.name, e)
                    mesh = None
            self._kernel_mesh = (knob, mesh)
            return mesh

    def _remesh(self) -> None:
        """kernel_mesh_devices observer: rebuild and swap into live
        engines (their next flush re-places; see engine.set_mesh)."""
        with self._dispatch_lock:
            self._kernel_mesh = None
            mesh = self.kernel_mesh()
            for eng in (self._dispatch, self._decode_dispatch):
                if eng is not None:
                    eng.set_mesh(mesh)

    def messenger_stack_for(self, peer_process: int) -> str:
        """Control-plane routing for the multi-controller deployment:
        device-buffer ici inside the process, tcp async across (the
        SURVEY §5 two-plane rule, parallel.dcn.pick_stack)."""
        from ceph_tpu.parallel.dcn import pick_stack
        return pick_stack(peer_process, self.process_index)

    def _build_engine(self, name: str, stats=None):
        """One coalescing engine wired to the shared knobs (both the
        encode and decode engines hot-reload through the same config
        observers)."""
        from ceph_tpu.ops.dispatch import DeviceDispatchEngine
        eng = DeviceDispatchEngine(
            max_stripes=int(self.conf.get(
                "kernel_coalesce_max_stripes")),
            max_delay_us=float(self.conf.get(
                "kernel_coalesce_max_delay_us")),
            max_in_flight=int(self.conf.get(
                "kernel_dispatch_depth")),
            name=name, stats=stats, mesh=self.kernel_mesh())
        self.conf.add_observer(
            "kernel_coalesce_max_stripes",
            lambda _n, v: setattr(eng, "max_stripes", int(v)))
        self.conf.add_observer(
            "kernel_coalesce_max_delay_us",
            lambda _n, v: setattr(eng, "max_delay_us", float(v)))
        # fault-domain knobs (retry ladder, breaker, supervision):
        # same construction-read + hot-reload-observer pattern
        for opt, attr, cast in (
                ("kernel_fault_max_retries", "fault_max_retries", int),
                ("kernel_fault_backoff_ms", "fault_backoff_ms", float),
                ("kernel_fault_backoff_max_ms",
                 "fault_backoff_max_ms", float),
                ("kernel_fault_breaker_threshold",
                 "breaker_threshold", int),
                ("kernel_fault_probe_interval", "probe_interval",
                 float),
                ("kernel_fault_thread_restarts", "thread_restarts",
                 int)):
            setattr(eng, attr, cast(self.conf.get(opt)))
            self.conf.add_observer(
                opt, lambda _n, v, a=attr, c=cast:
                setattr(eng, a, c(v)))
        return eng

    def dispatch_engine(self):
        """The context's device dispatch engine (built on first use so
        contexts that never touch a kernel spawn no threads).  The
        coalescing knobs hot-reload through config observers."""
        if self._dispatch is None:
            with self._dispatch_lock:
                if self._dispatch is not None:
                    return self._dispatch
                self._dispatch = self._build_engine(
                    f"{self.name}-dispatch")
        return self._dispatch

    def decode_dispatch_engine(self):
        """The decode-side twin: EC decodes (degraded reads, recovery
        pulls, rmw gathers) coalesce here, separately double-buffered
        from the write path so a recovery storm cannot queue behind —
        or starve — client encodes.  Feeds the decode stats sink
        (telemetry.decode_dispatch_stats / ceph_kernel_decode_*)."""
        if self._decode_dispatch is None:
            with self._dispatch_lock:
                if self._decode_dispatch is not None:
                    return self._decode_dispatch
                from ceph_tpu.ops import telemetry
                self._decode_dispatch = self._build_engine(
                    f"{self.name}-decode",
                    stats=telemetry.decode_dispatch_stats())
        return self._decode_dispatch

    def mapping_service(self):
        """The context's shared epoch-keyed PG mapping cache
        (osd.mapping.SharedPGMappingService) — one per context like
        the dispatch engines; N daemons hanging off one context
        advancing the same epoch share a single table build, and its
        per-pool remaps ride this context's dispatch engine."""
        if self._mapping_service is None:
            with self._dispatch_lock:
                if self._mapping_service is not None:
                    return self._mapping_service
                from ceph_tpu.osd.mapping import SharedPGMappingService
                self._mapping_service = SharedPGMappingService(self)
        return self._mapping_service


_default: CephTpuContext | None = None


def default_context() -> CephTpuContext:
    """Process-wide fallback context (g_ceph_context analog)."""
    global _default
    if _default is None:
        _default = CephTpuContext()
    return _default
