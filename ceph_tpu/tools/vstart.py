"""vstart-style in-process cluster harness (src/vstart.sh +
qa/standalone/ceph-helpers.sh analog).

Starts one mon and N osds in this process over the chosen messenger stack,
returns a handle with run_mon/run_osd/kill_osd/wait_for_clean-style helpers,
and a connected RadosClient factory — the surface the standalone QA tier
drives (SURVEY.md §4 tier 3).
"""

from __future__ import annotations

import threading
import time

from ceph_tpu.client import RadosClient
from ceph_tpu.mon import Monitor
from ceph_tpu.osd.daemon import OSDDaemon


class MiniCluster:
    _instances = 0

    def __init__(self, n_osds: int = 3, ms_type: str = "async",
                 store_type: str = "memstore", base_path: str = "",
                 heartbeats: bool = False, n_mons: int = 1,
                 auth_key=None, cephx: bool = False,
                 osd_conf: dict | None = None):
        # namespace loopback addresses per cluster: sequential tests reuse
        # names like "mon.0", and a timer from a dying daemon of the
        # previous cluster must never reach this one
        MiniCluster._instances += 1
        self._ns = f"c{MiniCluster._instances}."
        self.ms_type = ms_type
        self.store_type = store_type
        self.base_path = base_path
        self.heartbeats = heartbeats
        self.mons: dict[int, Monitor] = {}
        self.monmap: list[str] = []
        self.osds: dict[int, OSDDaemon] = {}
        self.clients: list[RadosClient] = []
        self._n_initial = n_osds
        self._n_mons = n_mons
        self.auth_key = auth_key
        #: startup config overrides applied to every OSD's context at
        #: construction (vstart.sh -o analog): knobs read before the
        #: first map lands (osd_op_queue, shard count, qos timeouts)
        self.osd_conf = dict(osd_conf or {})
        #: full cephx mode: per-entity keys + tickets (wire stacks).
        #: The seed keyring (mon keys + admin) is generated here — the
        #: `ceph-authtool` bootstrap step
        self.cephx = cephx
        self.keyring: dict[str, str] = {}
        if cephx:
            from ceph_tpu.auth.cephx import new_secret
            for i in range(n_mons):
                self.keyring[f"mon.{i}"] = new_secret()
            self.keyring["client.admin"] = new_secret()
        self.mgr = None
        self.mds = None
        self.fs_mds: list = []
        #: monotonic: a crashed daemon's loopback name is NEVER reused
        #: (len(fs_mds) would rebind a live daemon's address)
        self._fs_mds_seq = 0

    def _is_wire(self) -> bool:
        """TCP-style stacks bind host:port; loopback/ici bind names."""
        return self.ms_type not in ("loopback", "ici")

    @property
    def mon(self) -> Monitor:
        """A live monitor (prefer the leader — its map is freshest)."""
        for m in self.mons.values():
            if m.is_leader():
                return m
        return next(iter(self.mons.values()))

    @property
    def mon_host(self) -> str:
        return ",".join(self.monmap)

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "MiniCluster":
        # bind all mons first (TCP ports are ephemeral), then hand every
        # mon the complete monmap so elections can begin
        for i in range(self._n_mons):
            self.run_mon(i, defer_monmap=True)
        self.monmap = [self.mons[i].addr for i in range(self._n_mons)]
        for m in self.mons.values():
            m.set_monmap(self.monmap)
        for i in range(self._n_initial):
            self.run_osd(i)
        return self

    def run_mon(self, mon_id: int, defer_monmap: bool = False) -> Monitor:
        addr = ("127.0.0.1:0" if self._is_wire()
                else f"{self._ns}mon.{mon_id}")
        path = (f"{self.base_path}/mon.{mon_id}" if self.base_path else None)
        mon = Monitor(mon_id=mon_id, ms_type=self.ms_type, addr=addr,
                      store_path=path, auth_key=self.auth_key,
                      cephx_keyring=self.keyring if self.cephx else None)
        if defer_monmap:
            mon.init(monmap=[])   # bind only; set_monmap comes later
        else:
            # rejoin: reuse the recorded monmap slot (loopback addrs are
            # stable; TCP rejoin needs the same port, so record it)
            mon.init(monmap=[])
            if self.monmap:
                self.monmap[mon_id] = mon.addr
                monmap = list(self.monmap)
                mon.set_monmap(monmap)
                for other in self.mons.values():
                    other.monmap[mon_id] = mon.addr
        self.mons[mon_id] = mon
        return mon

    def kill_mon(self, mon_id: int) -> None:
        mon = self.mons.pop(mon_id)
        mon.shutdown()

    def add_mon(self, mon_id: int, timeout: float = 30.0) -> Monitor:
        """GROW the mon cluster at runtime (`ceph mon add` + probe):
        the new mon starts probing the existing quorum, the membership
        commits through paxos, and this returns once the joiner has
        entered the committed monmap and elections settled."""
        import json as _json
        import time as _time
        addr = ("127.0.0.1:0" if self._is_wire()
                else f"{self._ns}mon.{mon_id}")
        path = (f"{self.base_path}/mon.{mon_id}" if self.base_path
                else None)
        seeds = [m.addr for m in self.mons.values()]
        mon = Monitor(mon_id=mon_id, ms_type=self.ms_type, addr=addr,
                      store_path=path, auth_key=self.auth_key,
                      cephx_keyring=self.keyring if self.cephx else None)
        mon.init(probe=seeds)
        client = self.client(timeout=20.0)
        rc, out = client.mon_command({"prefix": "mon add",
                                      "id": mon_id, "addr": mon.addr})
        if rc != 0:
            mon.shutdown()
            raise RuntimeError(f"mon add failed: {out}")
        self.mons[mon_id] = mon
        while len(self.monmap) <= mon_id:
            self.monmap.append("")
        self.monmap[mon_id] = mon.addr
        deadline = _time.time() + timeout
        while _time.time() < deadline:
            if mon.elector is not None and not mon.elector.electing \
                    and mon.mon_id in (mon.quorum() or []):
                return mon
            _time.sleep(0.1)
        self.mons.pop(mon_id, None)
        mon.shutdown()
        raise TimeoutError(
            f"mon.{mon_id} did not join quorum: elector="
            f"{mon.elector is not None}, quorum={mon.quorum()}")

    def replace_mon(self, mon_id: int, timeout: float = 30.0) -> Monitor:
        """Kill a mon, WIPE its store, and rejoin it via probe +
        store-sync (the dead-mon-replacement flow: the fresh store pulls
        the paxos tail from the quorum before electing)."""
        import shutil
        import time as _time
        if mon_id in self.mons:
            self.kill_mon(mon_id)
        path = (f"{self.base_path}/mon.{mon_id}" if self.base_path
                else None)
        if path:
            shutil.rmtree(path, ignore_errors=True)
        addr = ("127.0.0.1:0" if self._is_wire()
                else f"{self._ns}mon.{mon_id}")
        seeds = [m.addr for m in self.mons.values()]
        mon = Monitor(mon_id=mon_id, ms_type=self.ms_type, addr=addr,
                      store_path=path, auth_key=self.auth_key,
                      cephx_keyring=self.keyring if self.cephx else None)
        mon.init(probe=seeds)
        if self._is_wire():
            # the wiped mon's new ephemeral port must replace the old
            # monmap entry before the probe can match it
            client = self.client(timeout=20.0)
            client.mon_command({"prefix": "mon add", "id": mon_id,
                                "addr": mon.addr})
        self.mons[mon_id] = mon
        if mon_id < len(self.monmap):
            self.monmap[mon_id] = mon.addr
        deadline = _time.time() + timeout
        while _time.time() < deadline:
            if mon.elector is not None and not mon.elector.electing:
                return mon
            _time.sleep(0.1)
        # clean up the half-joined mon: leaving it registered (and its
        # threads running) would let a later run_mon bind a SECOND
        # monitor over the same address/store
        self.mons.pop(mon_id, None)
        mon.shutdown()
        raise TimeoutError(f"replaced mon.{mon_id} did not rejoin")

    def run_mgr(self, mgr_id: int = 0):
        """Start a manager; OSDs started AFTERWARDS stream reports to
        the one the mon names active (restart existing ones to pick it
        up).  Additional mgr_ids are standbys the mon promotes when the
        active's session dies."""
        from ceph_tpu.mgr import MgrDaemon
        addr = ("127.0.0.1:0" if self._is_wire()
                else f"{self._ns}mgr.{mgr_id}")
        cephx = None
        if self.cephx:
            who = f"mgr.{mgr_id}"
            key = self.keyring.get(who) or self.provision_key(who)
            cephx = (who, key)
        mgr = MgrDaemon(self.mon_host, ms_type=self.ms_type,
                        addr=addr, auth_key=self.auth_key,
                        cephx=cephx, mgr_id=mgr_id)
        mgr.init()
        self.mgrs = getattr(self, "mgrs", {})
        self.mgrs[mgr_id] = mgr
        if mgr_id == 0 or self.mgr is None:
            self.mgr = mgr
        return mgr

    def kill_mgr(self, mgr_id: int = 0):
        mgr = self.mgrs.pop(mgr_id, None) if getattr(self, "mgrs", None) \
            else None
        if mgr is None:
            mgr, self.mgr = self.mgr, None
        if mgr is not None:
            if self.mgr is mgr:
                self.mgr = next(iter(getattr(self, "mgrs", {}).values()),
                                None)
            mgr.shutdown()

    def run_mds(self, metadata_pool: int, data_pool: int):
        """Start the metadata server over the given pools (the `fs new
        meta data` + ceph-mds step)."""
        from ceph_tpu.mds import MDSDaemon
        addr = ("127.0.0.1:0" if self._is_wire()
                else f"{self._ns}mds.0")
        cephx = None
        if self.cephx:
            key = self.keyring.get("mds.0") or self.provision_key("mds.0")
            cephx = ("mds.0", key)
        self.mds = MDSDaemon(self.mon_host, metadata_pool, data_pool,
                             ms_type=self.ms_type, addr=addr,
                             auth_key=self.auth_key, cephx=cephx)
        self.mds.init()
        return self.mds

    def run_fs_mds(self, n: int = 1):
        """FSMap mode: start n beaconing MDS daemons; the mon assigns
        ranks (up to max_mds), the rest idle as standbys.  Run `fs new`
        first."""
        from ceph_tpu.mds import MDSDaemon
        out = []
        for i in range(n):
            idx = self._fs_mds_seq
            self._fs_mds_seq += 1
            addr = ("127.0.0.1:0" if self._is_wire()
                    else f"{self._ns}mds.g{idx}")
            cephx = None
            if self.cephx:
                ent = f"mds.{idx}"
                key = self.keyring.get(ent) or self.provision_key(ent)
                cephx = (ent, key)
            d = MDSDaemon(self.mon_host, ms_type=self.ms_type,
                          addr=addr, auth_key=self.auth_key,
                          cephx=cephx)
            d.init_standby()
            self.fs_mds.append(d)
            out.append(d)
        return out

    def crash_fs_mds(self, d) -> None:
        """SIGKILL-style: no flush, no journal trim, no goodbye."""
        d._stop = True
        for t in (d._tick_timer, d._beacon_timer):
            if t:
                t.cancel()
        d.msgr.shutdown()
        d.objecter.shutdown()
        if d in self.fs_mds:
            self.fs_mds.remove(d)

    def kill_mds(self) -> None:
        mds = self.mds
        self.mds = None
        mds.shutdown()

    def provision_key(self, entity: str) -> str:
        """`ceph auth get-or-create` as admin; returns the secret."""
        admin = self.client()
        rc, out = admin.mon_command({"prefix": "auth get-or-create",
                                     "entity": entity})
        assert rc == 0, out
        rc, key = admin.mon_command({"prefix": "auth print-key",
                                     "entity": entity})
        assert rc == 0, key
        self.keyring[entity] = key
        return key

    def run_osd(self, osd_id: int) -> OSDDaemon:
        addr = (f"127.0.0.1:0" if self._is_wire()
                else f"{self._ns}osd.{osd_id}")
        path = (f"{self.base_path}/osd.{osd_id}" if self.base_path else "")
        cephx = None
        if self.cephx:
            ent = f"osd.{osd_id}"
            key = self.keyring.get(ent) or self.provision_key(ent)
            cephx = (ent, key)
        osd = OSDDaemon(osd_id, self.mon_host, store_type=self.store_type,
                        store_path=path, ms_type=self.ms_type, addr=addr,
                        heartbeats=self.heartbeats,
                        auth_key=self.auth_key, cephx=cephx,
                        mgr_addr=self.mgr.addr if self.mgr else None,
                        conf=self.osd_conf)
        osd.init()
        self.osds[osd_id] = osd
        return osd

    def kill_osd(self, osd_id: int) -> None:
        """Hard kill (Thrasher kill_osd analog)."""
        osd = self.osds.pop(osd_id)
        osd.shutdown()

    def client(self, timeout: float = 10.0) -> RadosClient:
        cephx = (("client.admin", self.keyring["client.admin"])
                 if self.cephx else None)
        c = RadosClient(self.mon_host, ms_type=self.ms_type,
                        timeout=timeout, auth_key=self.auth_key,
                        cephx=cephx)
        c.connect()
        self.clients.append(c)
        return c

    def client_as(self, entity: str, key: str,
                  timeout: float = 10.0) -> RadosClient:
        """A client with SPECIFIC cephx credentials (not admin)."""
        c = RadosClient(self.mon_host, ms_type=self.ms_type,
                        timeout=timeout, cephx=(entity, key))
        c.connect()
        self.clients.append(c)
        return c

    def stop(self) -> None:
        for c in self.clients:
            c.shutdown()
        if self.mds:
            self.mds.shutdown()
            self.mds = None
        for d in list(self.fs_mds):
            d.shutdown()
        self.fs_mds = []
        for osd in list(self.osds.values()):
            osd.shutdown()
        self.osds.clear()
        if self.mgr:
            self.mgr.shutdown()
        for mon in list(self.mons.values()):
            mon.shutdown()
        self.mons.clear()

    # -- helpers (ceph-helpers.sh analog) -------------------------------------

    def wait_for_epoch(self, epoch: int, timeout: float = 10.0) -> None:
        """All live daemons have seen at least `epoch`."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            if all(o.osdmap.epoch >= epoch for o in self.osds.values()):
                return
            time.sleep(0.02)
        raise TimeoutError(f"cluster did not reach epoch {epoch}")

    def wait_for_osd_count(self, n: int, timeout: float = 10.0) -> None:
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self.mon.status()["num_up_osds"] == n:
                return
            time.sleep(0.02)
        raise TimeoutError(f"never saw {n} up osds")

    def create_pool(self, client: RadosClient, *,
                    epoch_timeout: float = 10.0, **cmd) -> int:
        """``epoch_timeout``: a new pool's first map application can
        pay a cold jit trace+compile inside _handle_map (the fused
        placement ladder, when osdmap_mapping_min_pgs admits toy
        pools) — tens of seconds on a 1-core host; callers running
        fused-on-toy-pools setups pass a compile-sized timeout."""
        res, out = client.mon_command(
            dict({"prefix": "osd pool create"}, **cmd))
        assert res == 0, out
        pool_id = int(out.split()[1])
        epoch = self.mon.osdmap.epoch
        self.wait_for_epoch(epoch, timeout=epoch_timeout)
        client.wait_for_epoch(epoch)
        return pool_id


class ProcCluster:
    """Multi-PROCESS cluster harness: every mon/OSD is a separate OS
    process over the TCP stack (the reference's tier-3 QA model —
    vstart.sh spawns real daemons; qa/standalone/ceph-helpers.sh
    run_mon:437 / run_osd:596).  kill_osd(9) is real SIGKILL process
    death; the filestore survives for the restart.
    """

    def __init__(self, n_osds: int = 3, n_mons: int = 1,
                 base_path: str = "", auth_key: str = "",
                 ms_type: str = "async", jax_cpu_devices: int = 0):
        import tempfile
        self.n_osds = n_osds
        self.n_mons = n_mons
        self.base_path = base_path or tempfile.mkdtemp(prefix="proccluster-")
        self.auth_key = auth_key
        #: OSD messenger stack: "ici" = cross-process ici-wire (TCP
        #: control plane + device transfer data plane); OSD processes
        #: then pin a cpu backend with jax_cpu_devices local devices
        #: (the virtual-mesh tier; real deployments use the real chips)
        self.ms_type = ms_type
        self.jax_cpu_devices = jax_cpu_devices or (
            2 if ms_type == "ici" else 0)
        self.procs: dict[str, object] = {}   # "mon.0" / "osd.2" -> Popen
        self.mon_addrs: list[str] = []
        self.clients: list[RadosClient] = []

    @property
    def mon_host(self) -> str:
        return ",".join(self.mon_addrs)

    def _spawn(self, role: str, rid: int, extra: list[str]):
        import subprocess
        import sys
        cmd = [sys.executable, "-m", "ceph_tpu.tools.daemon_main",
               "--role", role, "--id", str(rid),
               "--store-path", f"{self.base_path}/{role}.{rid}"]
        if self.auth_key:
            cmd += ["--auth-key", self.auth_key]
        cmd += extra
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                stderr=subprocess.DEVNULL, text=True)
        # wait for the readiness line (bounded: a wedged daemon must
        # fail the harness, not hang it — including one that emits a
        # partial line), then keep the pipe drained so later daemon
        # output cannot fill the buffer and block it
        import os as _os
        import selectors
        fd = proc.stdout.fileno()
        _os.set_blocking(fd, False)
        sel = selectors.DefaultSelector()
        sel.register(proc.stdout, selectors.EVENT_READ)
        buf = b""
        deadline = time.time() + 60.0
        while b"\n" not in buf and time.time() < deadline:
            if sel.select(timeout=max(0.05, deadline - time.time())):
                chunk = _os.read(fd, 4096)
                if not chunk:
                    break
                buf += chunk
        sel.close()
        _os.set_blocking(fd, True)
        line = buf.split(b"\n", 1)[0].decode(errors="replace")
        if not line.startswith("ready"):
            proc.kill()
            raise RuntimeError(f"{role}.{rid} failed to start: {line!r}")
        threading.Thread(target=proc.stdout.read, daemon=True).start()
        #: the full ready line (rgw appends its bound HTTP address)
        proc.ready_line = line
        self.procs[f"{role}.{rid}"] = proc
        return proc

    def start(self) -> "ProcCluster":
        from ceph_tpu.common import free_port
        self.mon_addrs = [f"127.0.0.1:{free_port()}"
                          for _ in range(self.n_mons)]
        monmap = ",".join(self.mon_addrs)
        for i in range(self.n_mons):
            self._spawn("mon", i, ["--addr", self.mon_addrs[i],
                                   "--monmap", monmap])
        for i in range(self.n_osds):
            self.run_osd(i)
        return self

    def run_osd(self, osd_id: int):
        extra = ["--mon-host", self.mon_host, "--heartbeats"]
        if self.ms_type != "async":
            extra += ["--ms-type", self.ms_type]
        if self.jax_cpu_devices:
            extra += ["--jax-cpu-devices", str(self.jax_cpu_devices)]
        return self._spawn("osd", osd_id, extra)

    def kill_osd(self, osd_id: int) -> None:
        """SIGKILL — crash-grade process death (Thrasher kill_osd)."""
        proc = self.procs.pop(f"osd.{osd_id}")
        proc.kill()
        proc.wait(timeout=10)

    def run_rgw(self, pool: int, rgw_id: int = 0) -> str:
        """Spawn a radosgw process over `pool`; returns its HTTP
        address, read from the ready line — the daemon binds an
        ephemeral port itself, so there is no pick-then-bind race."""
        proc = self._spawn("rgw", rgw_id,
                           ["--mon-host", self.mon_host,
                            "--rgw-pool", str(pool)])
        parts = proc.ready_line.split()
        if len(parts) < 3:
            raise RuntimeError(
                f"rgw ready line carried no address: "
                f"{proc.ready_line!r}")
        return parts[2]

    def client(self, timeout: float = 20.0) -> RadosClient:
        c = RadosClient(self.mon_host, ms_type="async", timeout=timeout,
                        auth_key=self.auth_key.encode()
                        if self.auth_key else None)
        c.connect()
        self.clients.append(c)
        return c

    def wait_for_osd_count(self, n: int, timeout: float = 30.0) -> None:
        import json
        deadline = time.time() + timeout
        client = self.clients[0] if self.clients else self.client()
        while time.time() < deadline:
            try:
                rc, out = client.mon_command({"prefix": "status"})
                if rc == 0 and json.loads(out)["num_up_osds"] == n:
                    return
            except (TimeoutError, OSError, ValueError, KeyError):
                pass
            time.sleep(0.25)
        raise TimeoutError(f"never saw {n} up osds")

    def create_pool(self, client: RadosClient, **cmd) -> int:
        import json
        res, out = client.mon_command(
            dict({"prefix": "osd pool create"}, **cmd))
        assert res == 0, out
        pool_id = int(out.split()[1])
        rc, st = client.mon_command({"prefix": "status"})
        assert rc == 0, st
        client.wait_for_epoch(json.loads(st)["epoch"])
        return pool_id

    def stop(self) -> None:
        for c in self.clients:
            try:
                c.shutdown()
            except Exception:
                pass
        self.clients.clear()
        for name, proc in list(self.procs.items()):
            proc.terminate()
        for name, proc in list(self.procs.items()):
            try:
                proc.wait(timeout=10)
            except Exception:
                proc.kill()
        self.procs.clear()
