"""librbd-lite — block images striped over RADOS objects
(src/librbd/ analog: ImageRequest -> ObjectRequest over a striped
layout; header object + rbd_data.<id>.<objno> data objects).

An image is a fixed-size virtual block device: create/open/read/write
at arbitrary byte offsets, resize, stat, remove, plus snapshot
read-back riding the pool-snapshot machinery underneath.
"""

from __future__ import annotations

import json

from ceph_tpu.osdc.striper import StripeLayout, StripedObject


class Image:
    HEADER_FMT = "rbd_header.{name}"
    DATA_FMT = "rbd_data.{name}"

    def __init__(self, ioctx, name: str):
        self.io = ioctx
        self.name = name
        self._meta = None

    # -- lifecycle ------------------------------------------------------------

    @classmethod
    def create(cls, ioctx, name: str, size: int,
               order: int = 22, stripe_unit: int = 1 << 16,
               stripe_count: int = 4) -> "Image":
        """order = log2(object size), like rbd create --order."""
        header = cls.HEADER_FMT.format(name=name)
        exists = True
        try:
            ioctx.stat(header)
        except OSError:
            exists = False
        if exists:
            raise FileExistsError(f"image {name!r} exists")
        meta = {"size": size, "order": order,
                "stripe_unit": stripe_unit,
                "stripe_count": stripe_count}
        ioctx.write_full(header, json.dumps(meta).encode())
        img = cls(ioctx, name)
        img._meta = meta
        return img

    def _load(self) -> dict:
        if self._meta is None:
            blob = self.io.read(self.HEADER_FMT.format(name=self.name))
            self._meta = json.loads(blob.decode())
        return self._meta

    def _striped(self) -> StripedObject:
        m = self._load()
        layout = StripeLayout(stripe_unit=m["stripe_unit"],
                              stripe_count=m["stripe_count"],
                              object_size=1 << m["order"])
        return StripedObject(self.io, self.DATA_FMT.format(name=self.name),
                             layout)

    # -- I/O ------------------------------------------------------------------

    def stat(self) -> dict:
        m = self._load()
        return {"size": m["size"], "order": m["order"],
                "stripe_unit": m["stripe_unit"],
                "stripe_count": m["stripe_count"]}

    def write(self, data: bytes, offset: int = 0) -> int:
        m = self._load()
        if offset + len(data) > m["size"]:
            raise ValueError("write past end of image")
        self._striped().write(data, offset)
        return len(data)

    def read(self, offset: int = 0, length: int = 0) -> bytes:
        m = self._load()
        if length <= 0 or offset + length > m["size"]:
            length = max(0, m["size"] - offset)
        data = self._striped().read(offset, length)
        if len(data) < length:      # unwritten space reads as zeros
            data = data + bytes(length - len(data))
        return data

    def resize(self, new_size: int) -> None:
        m = self._load()
        if new_size < m["size"]:
            # shrink trims the discarded extent (real rbd semantics):
            # growing back later must read zeros, not stale payload
            self._striped().truncate(new_size)
        m["size"] = new_size
        self.io.write_full(self.HEADER_FMT.format(name=self.name),
                           json.dumps(m).encode())

    def remove(self) -> None:
        self._striped().remove()
        try:
            self.io.remove(self.HEADER_FMT.format(name=self.name))
        except OSError:
            pass
        self._meta = None


def list_images(ioctx, probe: list[str]) -> list[str]:
    """Images among candidate names (no pool listing primitive yet —
    the reference keeps an rbd_directory object; callers track names)."""
    out = []
    for name in probe:
        try:
            ioctx.stat(Image.HEADER_FMT.format(name=name))
            out.append(name)
        except OSError:
            continue
    return out
