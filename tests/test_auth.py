"""cephx-lite authentication + messenger hardening (VERDICT item 9):
keyed clusters work end to end, un-keyed and wrong-keyed peers are
rejected at the handshake, oversized frames are dropped, and reconnect
storms do not accumulate dead accepted connections.
"""

import socket
import struct
import time

import pytest

from ceph_tpu.msg.messenger import EntityName, Messenger
from ceph_tpu.tools.vstart import MiniCluster


def mk_messenger(name, key=None):
    m = Messenger.create(EntityName(*name), "async")
    if key is not None:
        m.set_auth(key)
    m.bind("127.0.0.1:0")
    m.start()
    return m


class Sink:
    def __init__(self):
        self.got = []

    def ms_dispatch(self, msg):
        self.got.append(msg)
        return True

    def ms_handle_reset(self, con):
        pass

    def ms_handle_remote_reset(self, con):
        pass


def test_keyed_peers_talk():
    a = mk_messenger(("osd", 1), key="sesame")
    b = mk_messenger(("osd", 2), key="sesame")
    sink = Sink()
    b.add_dispatcher_tail(sink)
    try:
        from ceph_tpu.messages import MOSDPing
        con = a.connect_to(b.my_addr, EntityName("osd", 2))
        con.send_message(MOSDPing(from_osd=1, op=MOSDPing.PING))
        deadline = time.time() + 5
        while not sink.got and time.time() < deadline:
            time.sleep(0.02)
        assert sink.got, "keyed peers failed to exchange a message"
    finally:
        a.shutdown()
        b.shutdown()


@pytest.mark.parametrize("bad_key", [None, "wrong"])
def test_unkeyed_or_wrong_key_peer_rejected(bad_key):
    server = mk_messenger(("mon", 0), key="sesame")
    attacker = mk_messenger(("osd", 9), key=bad_key)
    sink = Sink()
    server.add_dispatcher_tail(sink)
    try:
        from ceph_tpu.messages import MOSDPing
        con = attacker.connect_to(server.my_addr, EntityName("mon", 0))
        con.send_message(MOSDPing(from_osd=9, op=MOSDPing.PING))
        time.sleep(1.0)
        assert sink.got == [], "unauthenticated peer got through"
    finally:
        attacker.shutdown()
        server.shutdown()


def test_oversized_frame_rejected():
    server = mk_messenger(("mon", 0))
    sink = Sink()
    server.add_dispatcher_tail(sink)
    try:
        host, port = server.my_addr.rsplit(":", 1)
        s = socket.create_connection((host, int(port)), timeout=5)
        from ceph_tpu.msg.async_tcp import BANNER
        s.sendall(BANNER)
        s.recv(len(BANNER))
        me = b"client.99"
        s.sendall(struct.pack("<I", len(me)) + me)
        plen = struct.unpack("<I", s.recv(4))[0]
        s.recv(plen)
        # feature negotiation frame (supported, required)
        from ceph_tpu.msg.features import (
            FEAT_FRAME, FEATURE_BASE, SUPPORTED_FEATURES)
        s.sendall(FEAT_FRAME.pack(SUPPORTED_FEATURES, FEATURE_BASE))
        s.recv(FEAT_FRAME.size)
        s.sendall(bytes(17))          # auth: mode none + zero nonce
        s.recv(17)
        # claim a 1 GiB frame: the reader must drop the connection, not
        # try to buffer it
        s.sendall(struct.pack("<I", 1 << 30))
        s.sendall(b"x" * 4096)
        time.sleep(0.5)
        assert sink.got == []
    finally:
        s.close()
        server.shutdown()


def test_reconnect_storm_reaps_accepted_connections():
    server = mk_messenger(("mon", 0))
    try:
        for i in range(12):
            dialer = mk_messenger(("osd", 7))
            from ceph_tpu.messages import MOSDPing
            con = dialer.connect_to(server.my_addr,
                                    EntityName("mon", 0))
            con.send_message(MOSDPing(from_osd=7, op=MOSDPing.PING))
            time.sleep(0.05)
            dialer.shutdown()
        time.sleep(1.0)
        accepted = [k for k in server._conns if k.startswith("accepted:")]
        live = [k for k in accepted
                if server._conns[k].is_connected()]
        # at most the latest session may remain; the storm must not
        # accumulate one dead connection per reconnect
        assert len(accepted) <= 1, accepted
        assert len(live) <= 1
    finally:
        server.shutdown()


def test_authenticated_cluster_end_to_end():
    c = MiniCluster(n_osds=3, ms_type="async",
                    auth_key="cluster-secret").start()
    try:
        c.wait_for_osd_count(3)
        client = c.client()
        pool = c.create_pool(client, pg_num=4, size=3)
        io = client.open_ioctx(pool)
        io.write_full("sec", b"authenticated bytes")
        assert io.read("sec") == b"authenticated bytes"
        # an un-keyed client cannot even fetch a map
        from ceph_tpu.client.rados import RadosClient
        intruder = RadosClient(c.mon_host, ms_type="async", timeout=2.0)
        with pytest.raises(TimeoutError):
            intruder.connect()
        intruder.shutdown()
    finally:
        c.stop()
