"""Atomic compound transactions (os/ObjectStore.h:306 Transaction analog).

A Transaction is an ordered op list over (collection, object) targets.  It
encodes to bytes so primaries ship the identical transaction to replicas in
MOSDRepOp (the reference does exactly this: ECSubWrite/RepOp carry encoded
transactions).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ceph_tpu.msg.encoding import Decoder, Encoder

OP_TOUCH = 1
OP_WRITE = 2          # (off, data)
OP_ZERO = 3           # (off, length)
OP_TRUNCATE = 4       # (length)
OP_REMOVE = 5
OP_OMAP_SETKEYS = 6   # ({k: v})
OP_OMAP_RMKEYS = 7    # ([k])
OP_MKCOLL = 8
OP_RMCOLL = 9
OP_CLONE = 10         # (dest_oid)
OP_SETATTR = 11       # (name, value)
OP_COLL_MOVE = 12     # (dest = destination collection)

_OP_NAMES = {
    OP_TOUCH: "touch", OP_WRITE: "write", OP_ZERO: "zero",
    OP_TRUNCATE: "truncate", OP_REMOVE: "remove",
    OP_OMAP_SETKEYS: "omap_setkeys", OP_OMAP_RMKEYS: "omap_rmkeys",
    OP_MKCOLL: "mkcoll", OP_RMCOLL: "rmcoll", OP_CLONE: "clone",
    OP_SETATTR: "setattr", OP_COLL_MOVE: "coll_move",
}


@dataclass
class Op:
    op: int
    cid: str = ""
    oid: str = ""
    offset: int = 0
    length: int = 0
    data: bytes = b""
    keys: dict = field(default_factory=dict)
    rmkeys: list = field(default_factory=list)
    dest: str = ""
    name: str = ""

    def describe(self) -> str:
        return f"{_OP_NAMES.get(self.op, self.op)} {self.cid}/{self.oid}"


class Transaction:
    def __init__(self):
        self.ops: list[Op] = []

    def __len__(self):
        return len(self.ops)

    # -- builders (ObjectStore::Transaction API names) ------------------------

    def create_collection(self, cid: str) -> "Transaction":
        self.ops.append(Op(OP_MKCOLL, cid))
        return self

    def remove_collection(self, cid: str) -> "Transaction":
        self.ops.append(Op(OP_RMCOLL, cid))
        return self

    def touch(self, cid: str, oid: str) -> "Transaction":
        self.ops.append(Op(OP_TOUCH, cid, oid))
        return self

    def write(self, cid: str, oid: str, offset: int, data: bytes
              ) -> "Transaction":
        self.ops.append(Op(OP_WRITE, cid, oid, offset=offset,
                           length=len(data), data=bytes(data)))
        return self

    def zero(self, cid: str, oid: str, offset: int, length: int
             ) -> "Transaction":
        self.ops.append(Op(OP_ZERO, cid, oid, offset=offset, length=length))
        return self

    def truncate(self, cid: str, oid: str, length: int) -> "Transaction":
        self.ops.append(Op(OP_TRUNCATE, cid, oid, length=length))
        return self

    def remove(self, cid: str, oid: str) -> "Transaction":
        self.ops.append(Op(OP_REMOVE, cid, oid))
        return self

    def omap_setkeys(self, cid: str, oid: str, keys: dict) -> "Transaction":
        self.ops.append(Op(OP_OMAP_SETKEYS, cid, oid, keys=dict(keys)))
        return self

    def omap_rmkeys(self, cid: str, oid: str, keys: list) -> "Transaction":
        self.ops.append(Op(OP_OMAP_RMKEYS, cid, oid, rmkeys=list(keys)))
        return self

    def clone(self, cid: str, oid: str, dest: str) -> "Transaction":
        self.ops.append(Op(OP_CLONE, cid, oid, dest=dest))
        return self

    def setattr(self, cid: str, oid: str, name: str, value: bytes
                ) -> "Transaction":
        self.ops.append(Op(OP_SETATTR, cid, oid, name=name,
                           data=bytes(value)))
        return self

    def collection_move(self, cid: str, oid: str, dest_cid: str
                        ) -> "Transaction":
        """Move an object (data + attrs + omap) to another collection —
        the PG-split primitive (os/ObjectStore.h collection_move_rename /
        split_collection analog; missing source is a no-op so replayed
        split transactions stay idempotent)."""
        self.ops.append(Op(OP_COLL_MOVE, cid, oid, dest=dest_cid))
        return self

    def append(self, other: "Transaction") -> "Transaction":
        self.ops.extend(other.ops)
        return self

    # -- wire form ------------------------------------------------------------

    def encode(self) -> bytes:
        enc = Encoder()

        def enc_op(e: Encoder, op: Op):
            e.u8(op.op).str(op.cid).str(op.oid)
            e.u64(op.offset).u64(op.length).bytes(op.data)
            e.map(op.keys, lambda e2, k: e2.str(k),
                  lambda e2, v: e2.bytes(v))
            e.list(op.rmkeys, lambda e2, k: e2.str(k))
            e.str(op.dest).str(op.name)

        enc.versioned(1, 1, lambda e: e.list(self.ops, enc_op))
        return enc.tobytes()

    @staticmethod
    def decode(data: bytes) -> "Transaction":
        dec = Decoder(data)

        def dec_op(d: Decoder) -> Op:
            return Op(op=d.u8(), cid=d.str(), oid=d.str(), offset=d.u64(),
                      length=d.u64(), data=d.bytes(),
                      keys=d.map(lambda d2: d2.str(), lambda d2: d2.bytes()),
                      rmkeys=d.list(lambda d2: d2.str()),
                      dest=d.str(), name=d.str())

        t = Transaction()
        t.ops = dec.versioned(1, lambda d, v: d.list(dec_op))
        return t
