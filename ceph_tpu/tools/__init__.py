"""CLI tools and harnesses (reference layer 7: src/tools/, src/vstart.sh).

vstart          in-process MiniCluster harness
crush_test      crushtool --test analog (batched)
osdmap_test     osdmaptool --test-map-pgs analog
ec_benchmark    ceph_erasure_code_benchmark analog
profile_report  pipeline where-did-the-time-go table renderer
"""
