"""Monitor: the cluster-map authority (reference src/mon/).

Holds the authoritative OSDMap in a versioned durable store (the Paxos
store layout: one committed value per version), adjudicates failure reports
with a reporter quorum (mon/OSDMonitor.cc:2537 check_failure analog), runs the
command table ("osd pool create", "osd tree", ...), and broadcasts map epochs
to subscribers.  Single-mon deployment this round; the store and proposal path
are shaped so the Paxos collect/accept phases slot in front of commit.
"""

from .monitor import Monitor

__all__ = ["Monitor"]
