"""Monitor daemon: Paxos-replicated cluster-map authority.

Map mutations follow the reference's pending_inc pattern (OSDMonitor):
mutate a *copy* of the map, then commit it through Paxos — the committed
blob is what every monitor (leader and peons alike) applies in the
on_commit callback, so all quorum members converge on the identical map
bytes.  Leadership comes from the Elector (lowest reachable rank); peons
forward client commands to the leader (MForward, src/mon/Monitor.cc
forward_request_leader) and OSDs simply send their boot/failure reports
to every monitor (the leader executes, peons ignore — the reports are
idempotent and re-sent, so no relay machinery is needed for them).

Failure handling mirrors check_failure (mon/OSDMonitor.cc:2537): an osd
is marked down once `mon_osd_min_down_reporters` distinct reporters have
filed MOSDFailure against it.

Mutations run on a single worker thread, never on a messenger dispatch
thread: propose_and_wait blocks until the quorum accepts, and the
dispatch thread must stay free to process those very ACCEPT messages.
"""

from __future__ import annotations

import json
import queue
import threading
import time

from ceph_tpu.common.clog import (
    MLog, PRIO_INFO, PRIO_WARN, LogStore)
from ceph_tpu.common.context import CephTpuContext
from ceph_tpu.common.logging import dout
# top-level, not lazy: a STANDALONE mon process must have type 0x702 in
# the message registry before the first beacon frame arrives, or every
# beacon is dropped at decode and failover silently degrades
from ceph_tpu.mgr.daemon import MMgrBeacon
from ceph_tpu.crush.builder import add_simple_rule, make_bucket
from ceph_tpu.crush.types import CRUSH_BUCKET_STRAW2, CrushMap
from ceph_tpu.messages import (
    MPGStats,
    MMonCommand, MMonCommandAck, MOSDFailure, MOSDMapMsg)
from ceph_tpu.messages.osd_msgs import MOSDPing
from ceph_tpu.mon.elector import Elector, MMonElection
from ceph_tpu.mon.paxos import MMonPaxos, Paxos
from ceph_tpu.msg.message import Message, register_message
from ceph_tpu.msg.encoding import Encoder, Decoder
from ceph_tpu.msg.messenger import (
    ConnectionPolicy, Dispatcher, EntityName, Messenger)
from ceph_tpu.objectstore.kv import LogDB, MemDB
from ceph_tpu.osd.map_codec import decode_osdmap, encode_osdmap
from ceph_tpu.osd.osdmap import OSDMap, PGPool, POOL_TYPE_ERASURE


@register_message
class MOSDBoot(Message):
    """osd -> mon: I'm up at this address (messages/MOSDBoot.h analog)."""

    TYPE = 71

    def __init__(self, osd_id: int = 0, addr: str = ""):
        super().__init__()
        self.osd_id = osd_id
        self.addr = addr

    def encode_payload(self, enc: Encoder):
        enc.versioned(1, 1, lambda e: (e.s32(self.osd_id), e.str(self.addr)))

    def decode_payload(self, dec: Decoder, version: int):
        def body(d, v):
            self.osd_id = d.s32()
            self.addr = d.str()
        dec.versioned(1, body)


@register_message
class MMonSubscribe(Message):
    """client/osd -> mon: send me map updates (MMonSubscribe analog).
    v2: carries the subscriber's current epoch (the reference sub's
    `start`) so a renewal from an up-to-date subscriber costs nothing."""

    TYPE = 15

    def __init__(self, name: str = "", addr: str = "", epoch: int = 0):
        super().__init__()
        self.name = name
        self.addr = addr
        self.epoch = epoch

    def encode_payload(self, enc: Encoder):
        enc.versioned(2, 1, lambda e: (e.str(self.name), e.str(self.addr),
                                       e.u32(self.epoch)))

    def decode_payload(self, dec: Decoder, version: int):
        def body(d, v):
            self.name = d.str()
            self.addr = d.str()
            self.epoch = d.u32() if v >= 2 else 0
        dec.versioned(2, body)


@register_message
class MMonProbe(Message):
    """mon <-> mon bootstrap probing + store sync
    (messages/MMonProbe.h:22 + Monitor.cc:1186-1400 probe,
    :1560-1740 sync, reduced):

      PROBE      joiner -> any known mon: who is in the monmap?
      REPLY      member -> joiner: committed monmap + my paxos tail pos
      SYNC       joiner -> member: my store ends at `last_committed`,
                 ship me the tail
      SYNC_DATA  member -> joiner: paxos values (full snapshots) +
                 last_committed; the joiner installs them and only THEN
                 enters elections
    """

    TYPE = 67  # MSG_MON_PROBE

    PROBE = 1
    REPLY = 2
    SYNC = 3
    SYNC_DATA = 4

    def __init__(self, op: int = 0, rank: int = -1, addr: str = "",
                 mon_db: dict | None = None, last_committed: int = 0,
                 values: dict[int, bytes] | None = None):
        super().__init__()
        self.op = op
        self.rank = rank
        self.addr = addr
        self.mon_db = mon_db or {}
        self.last_committed = last_committed
        self.values = values or {}

    def encode_payload(self, enc: Encoder):
        enc.versioned(1, 1, lambda e: (
            e.u8(self.op), e.s32(self.rank), e.str(self.addr),
            e.bytes(json.dumps(self.mon_db).encode()),
            e.u64(self.last_committed),
            e.map(self.values, lambda e2, k: e2.u64(k),
                  lambda e2, v: e2.bytes(v))))

    def decode_payload(self, dec: Decoder, version: int):
        def body(d, v):
            self.op = d.u8()
            self.rank = d.s32()
            self.addr = d.str()
            self.mon_db = json.loads(d.bytes().decode() or "{}")
            self.last_committed = d.u64()
            self.values = d.map(lambda d2: d2.u64(),
                                lambda d2: d2.bytes())
        dec.versioned(1, body)


@register_message
class MMonForward(Message):
    """peon -> leader: relayed client command (messages/MForward.h)."""

    TYPE = 46  # MSG_FORWARD

    def __init__(self, fwd_tid: int = 0, cmd_tid: int = 0,
                 cmd_blob: bytes = b""):
        super().__init__()
        self.fwd_tid = fwd_tid
        self.cmd_tid = cmd_tid
        self.cmd_blob = cmd_blob   # json-encoded command dict

    def encode_payload(self, enc: Encoder):
        enc.versioned(1, 1, lambda e: (
            e.u64(self.fwd_tid), e.u64(self.cmd_tid),
            e.bytes(self.cmd_blob)))

    def decode_payload(self, dec: Decoder, version: int):
        def body(d, v):
            self.fwd_tid = d.u64()
            self.cmd_tid = d.u64()
            self.cmd_blob = d.bytes()
        dec.versioned(1, body)


@register_message
class MMonForwardAck(Message):
    TYPE = 47

    def __init__(self, fwd_tid: int = 0, result: int = 0,
                 output: str = ""):
        super().__init__()
        self.fwd_tid = fwd_tid
        self.result = result
        self.output = output

    def encode_payload(self, enc: Encoder):
        enc.versioned(1, 1, lambda e: (
            e.u64(self.fwd_tid), e.s32(self.result), e.str(self.output)))

    def decode_payload(self, dec: Decoder, version: int):
        def body(d, v):
            self.fwd_tid = d.u64()
            self.result = d.s32()
            self.output = d.str()
        dec.versioned(1, body)


@register_message
class MMDSBeacon(Message):
    """mds <-> mon liveness + rank assignment (messages/MMDSBeacon.h).
    mds -> mon: gid/addr/state/load every beacon interval.
    mon -> mds (ack): the rank this gid holds (-1 = standby)."""

    TYPE = 100  # MSG_MDS_BEACON

    def __init__(self, gid: int = 0, addr: str = "", state: str = "",
                 rank: int = -1, load: float = 0.0,
                 bal_rank: int = -1, bal_load: float = 0.0,
                 meta_pool: int = -1, data_pool: int = -1):
        super().__init__()
        self.gid = gid
        self.addr = addr
        self.state = state
        self.rank = rank
        self.load = load
        #: acks carry the balancer hint: least-loaded active rank
        self.bal_rank = bal_rank
        self.bal_load = bal_load
        #: acks also carry the fs pools, so an assigned rank can
        #: activate immediately without waiting on its own map
        #: subscription (a cross-channel dependency that stalls under
        #: load)
        self.meta_pool = meta_pool
        self.data_pool = data_pool

    def encode_payload(self, enc: Encoder):
        enc.versioned(2, 1, lambda e: (
            e.u64(self.gid), e.str(self.addr), e.str(self.state),
            e.s32(self.rank), e.f64(self.load),
            e.s32(self.bal_rank), e.f64(self.bal_load),
            e.s64(self.meta_pool), e.s64(self.data_pool)))

    def decode_payload(self, dec: Decoder, version: int):
        def body(d, v):
            self.gid = d.u64()
            self.addr = d.str()
            self.state = d.str()
            self.rank = d.s32()
            self.load = d.f64()
            if v >= 2:
                self.bal_rank = d.s32()
                self.bal_load = d.f64()
                self.meta_pool = d.s64()
                self.data_pool = d.s64()
        dec.versioned(2, body)


def _referenced_bucket_ids(crush) -> set:
    """Bucket/item ids that appear inside some bucket — i.e. everything
    but the root(s).  Shared by root detection and parent lookup."""
    return {it for b in crush.buckets if b is not None for it in b.items}


class Monitor(Dispatcher):
    TICK_INTERVAL = 0.25

    def __init__(self, ctx: CephTpuContext | None = None, mon_id: int = 0,
                 store_path: str | None = None, ms_type: str = "async",
                 addr: str = "127.0.0.1:0", auth_key=None,
                 cephx_keyring: dict | None = None,
                 cephx_rotation: float = 3600.0):
        self.ctx = ctx or CephTpuContext(f"mon.{mon_id}")
        self.mon_id = mon_id
        self.name = EntityName("mon", mon_id)
        self.db = LogDB(store_path) if store_path else MemDB()
        self.osdmap = OSDMap()
        from ceph_tpu.common.lockdep import make_lock
        self._lock = make_lock(f"Monitor::lock({mon_id})")
        #: failure reports: failed_osd -> {reporter: (report_time,
        #: failed_for)} — report_time expires stale reports, failed_for
        #: is the reporter's observed silence when it filed
        self._failure_reports: dict[int, dict[int, tuple[float, float]]] = {}
        #: subscriber name -> (addr, entity)
        #: subscriber -> (addr, entity, session connection): pushes
        #: ride the session the subscriber authenticated
        self._subs: dict[str, tuple] = {}
        #: epoch -> encoded OSDMap::Incremental (each mon rebuilds this
        #: deterministically at commit; trimmed to INC_HISTORY)
        self._inc_history: dict[int, bytes] = {}
        #: latest MPGStats per reporting OSD (PG_DEGRADED health feed)
        self._pg_stats: dict[int, dict] = {}
        #: mds gid -> (last beacon time, addr, load) — mon-local
        #: liveness (the FSMap itself is paxos state on the map)
        self._mds_beacons: dict[int, tuple[float, str, float]] = {}
        #: mgr name -> (time, addr, con, available, modules) — mon-local
        #: liveness feeding the MgrMap (MgrMonitor beacon table)
        self._mgr_beacons: dict[str, tuple] = {}
        #: central cluster log (LogMonitor analog): every mon persists
        #: the fanned-out MLog stream and serves `ceph log last`
        self.logstore = LogStore(self.db)
        self._clog_seq = 0
        self._mgr_logged_active: str | None = None
        self._health_log_status: str | None = None
        self._health_log_last = 0.0
        #: when this mon started watching beacons as leader: a gid we
        #: have NEVER heard from is only dead once a full grace has
        #: passed since then (a freshly-elected/restarted leader must
        #: not fail every healthy rank on its first tick)
        self._mds_watch_since: float | None = None
        self._osd_addrs: dict[int, str] = {}
        #: rank -> address.  Runtime membership (`mon add/rm`) keeps
        #: this in lockstep with the committed mon_db; `mon rm` leaves
        #: rank holes, hence a dict rather than a list
        self.monmap: dict[int, str] = {}
        #: committed monmap epoch this mon has reconfigured to
        self.monmap_epoch = 0
        #: probing mode (Monitor.cc bootstrap/probe): seed addrs we ask
        #: for the authoritative monmap until we find ourselves in it
        self._probe_addrs: list[str] = []
        self._probe_synced = False
        self._pending_join: dict | None = None
        #: rank -> addr of members removed by `mon rm` (in-flight
        #: fan-outs — notably their own removal COMMIT — still reach them)
        self._retired_mons: dict[int, str] = {}
        self.elector: Elector | None = None
        self.paxos: Paxos | None = None
        self._tick_timer: threading.Timer | None = None
        self._work_q: queue.Queue = queue.Queue()
        self._worker: threading.Thread | None = None
        self._fwd_tid = 0
        #: fwd_tid -> (client connection, client tid)
        self._fwd_waiting: dict[int, tuple] = {}
        self._stop = False
        self.msgr = Messenger.create(self.name, ms_type)
        self.msgr.set_auth(auth_key)
        self.msgr.set_policy("client", ConnectionPolicy.lossy_client())
        self.msgr.set_policy("osd", ConnectionPolicy.stateful_server())
        self.msgr.set_policy("mon", ConnectionPolicy.stateful_peer())
        #: per-entity cephx: the seed keyring (mon keys + client.admin)
        #: bootstraps auth before the first map commit; after that the
        #: paxos-replicated auth_db is authoritative
        self._cephx_seed = dict(cephx_keyring or {})
        self.cephx_rotation = cephx_rotation
        if cephx_keyring is not None:
            from ceph_tpu.auth.cephx import TicketKeyring
            from ceph_tpu.auth.handshake import CephxConfig
            self.msgr.set_auth_cephx(CephxConfig(
                entity=f"mon.{mon_id}",
                key=self._cephx_seed.get(f"mon.{mon_id}", ""),
                keyring=TicketKeyring(self._self_ticket),
                auth_lookup=self._auth_lookup))
        self.msgr.add_dispatcher_tail(self)
        self._addr = addr
        self.ctx.admin.register_command(
            "mon status", lambda **kw: self.status(), "cluster status")

    # -- lifecycle ------------------------------------------------------------

    def init(self, monmap: list[str] | None = None,
             probe: list[str] | None = None) -> None:
        """probe: addresses of an EXISTING cluster to join instead of
        forming a quorum from a static monmap (Monitor.cc bootstrap/
        probe).  The mon stays out of elections until a probe reply
        shows its rank in the committed monmap; a wiped store is
        re-synced from the quorum's paxos tail first."""
        if isinstance(self.db, LogDB):
            self.db.open()
        self.msgr.bind(self._addr)
        self.msgr.start()
        self._worker = threading.Thread(target=self._work_loop, daemon=True)
        self._worker.start()
        if probe:
            self._probe_addrs = list(probe)
            self._schedule_tick()
            self._send_probes()
        elif monmap:
            self.set_monmap(monmap)
        elif monmap is None and not self.monmap:
            # single-mon convenience: I am the whole quorum
            # (monmap=[] defers: caller will set_monmap once every mon
            # in the cluster has bound its address)
            self.set_monmap([self.addr])

    def set_monmap(self, addrs) -> None:
        """Fix the monitor cluster membership and start electing.
        Must run after init() (our own address must be known).
        addrs: list (ranks 0..n-1) or rank->addr dict."""
        if isinstance(addrs, dict):
            self.monmap = {int(r): a for r, a in addrs.items() if a}
        else:
            # empty entries are rank-hole padding (a list monmap after
            # `mon rm`/sparse add): a phantom rank would inflate the
            # election majority with a peer that can never ack
            self.monmap = {r: a for r, a in enumerate(addrs) if a}
        self.elector = Elector(self.mon_id, sorted(self.monmap),
                               self._send_mon,
                               self._on_election_win, self._on_election_lose)
        self.paxos = Paxos(self.mon_id, self.db, self._send_mon,
                           self._on_paxos_commit, self._request_election)
        self.paxos.on_active = self._on_paxos_active
        # restore the last committed map (mon store = Paxos store)
        if self.paxos.last_committed > 0:
            blob = self.paxos.get(self.paxos.last_committed)
            if blob:
                self.osdmap = decode_osdmap(blob)
        self._schedule_tick()
        self.elector.start()

    def shutdown(self) -> None:
        self._stop = True
        if self._tick_timer:
            self._tick_timer.cancel()
        self._work_q.put(None)
        self.msgr.shutdown()
        if isinstance(self.db, LogDB):
            self.db.close()

    @property
    def addr(self) -> str:
        return self.msgr.my_addr

    def is_leader(self) -> bool:
        # snapshot: _maybe_reconfigure nulls self.elector (removed
        # mon) from the dispatch thread while tick/command threads run
        # this — a re-read between check and use races to None
        e = self.elector
        return (e is not None and e.leader == self.mon_id
                and not e.electing)

    def quorum(self) -> list[int]:
        e = self.elector
        return list(e.quorum) if e else []

    # -- mon-to-mon plumbing --------------------------------------------------

    def _send_mon(self, rank: int, msg) -> None:
        addr = self.monmap.get(rank) or self._retired_mons.get(rank)
        if addr is None:
            return
        con = self.msgr.connect_to(addr, EntityName("mon", rank))
        con.send_message(msg)

    # -- runtime membership (Monitor.cc probe/sync + MonmapMonitor) -----------

    #: values shipped per store sync: each is a full map snapshot, so
    #: the tail only needs to cover realistic election-window lag
    SYNC_TAIL = 50

    def _clog(self, prio: int, fmt: str, *args) -> None:
        """Mon-originated cluster-log entry: persist locally, fan to
        peer mons (LogMonitor logging its own events)."""
        from ceph_tpu.common.clog import make_entry
        with self._lock:
            self._clog_seq += 1
            ent = make_entry(self._clog_seq, prio,
                             (fmt % args) if args else fmt)
        name = f"mon.{self.mon_id}"
        self.logstore.append(name, [ent])
        for r in list(self.monmap):
            if r != self.mon_id:
                self._send_mon(r, MLog(name=name, entries=[ent]))

    def _check_health_transition(self) -> None:
        """Leader: log HEALTH_OK <-> HEALTH_WARN transitions (the
        reference's health-to-clog bridge)."""
        now = time.time()
        if now - self._health_log_last < 2.0:
            return
        self._health_log_last = now
        try:
            rep = self._health_report()
        except Exception:
            return
        status = rep["status"]
        if status == self._health_log_status:
            return
        prev = self._health_log_status
        self._health_log_status = status
        if prev is None and status == "HEALTH_OK":
            return      # boot into OK is not a transition
        detail = "; ".join(c.get("summary", c.get("check", ""))
                           for c in rep.get("checks", [])) or "all clear"
        self._clog(PRIO_WARN if status != "HEALTH_OK" else PRIO_INFO,
                   "health %s -> %s (%s)", prev or "?", status, detail)

    def _maybe_seed_mon_db(self) -> None:
        """Self-healing monmap seed: bootstrap normally commits it, but
        an OSD-boot mutation queued ahead of the bootstrap work item
        can commit first, making bootstrap's last_committed guard skip
        — the leader re-seeds from the static config whenever the map
        lacks a monmap."""
        if self.osdmap.mon_db or not self.monmap:
            return
        mons = {str(r): a for r, a in self.monmap.items()}

        def fn(m: OSDMap):
            if m.mon_db:
                return False
            m.mon_db = {"epoch": 1, "mons": mons}
        self._work_q.put(("mgr_map", fn, None))

    _addr_fix_last = 0.0

    def _maybe_fix_my_addr(self) -> None:
        """A restarted mon can come back on a fresh ephemeral port
        while the committed monmap still names its old one — re-commit
        the entry through the ordinary `mon add` path so every consumer
        of the map finds the live address again."""
        db = self.osdmap.mon_db
        e = self.elector
        if not db or e is None or e.electing:
            return
        mine = db.get("mons", {}).get(str(self.mon_id))
        if mine is None or mine == self.addr:
            return
        now = time.time()
        if now - self._addr_fix_last < 2.0:
            return
        self._addr_fix_last = now
        cmd = {"prefix": "mon add", "id": self.mon_id,
               "addr": self.addr}
        if self.is_leader():
            self._work_q.put(("cmd", cmd, None))
        elif e.leader is not None:
            self._send_mon(e.leader,
                           MMonCommand(tid=0, cmd=cmd))

    def _current_mon_db(self) -> dict:
        """The committed monmap, or one synthesized from the static
        config (clusters bootstrapped before mon_db existed)."""
        if self.osdmap.mon_db:
            return self.osdmap.mon_db
        return {"epoch": 0, "mons": {str(r): a
                                     for r, a in self.monmap.items()}}

    def _stored_lc(self) -> int:
        lc = self.db.get("paxos", "last_committed")
        return int(lc.decode()) if lc else 0

    def _send_probes(self) -> None:
        self._probe_last = time.time()
        for a in self._probe_addrs:
            try:
                con = self.msgr.connect_to(a, EntityName("mon", 0))
                con.send_message(MMonProbe(
                    op=MMonProbe.PROBE, rank=self.mon_id,
                    addr=self.addr))
            except OSError:
                continue

    def _handle_probe(self, msg: MMonProbe) -> None:
        if msg.op == MMonProbe.PROBE:
            # member side: hand the joiner the authoritative monmap and
            # my paxos position (any member may answer, like the
            # reference's probe)
            msg.connection.send_message(MMonProbe(
                op=MMonProbe.REPLY, rank=self.mon_id, addr=self.addr,
                mon_db=self._current_mon_db(),
                last_committed=self._stored_lc()))
            return
        if msg.op == MMonProbe.SYNC:
            values: dict[int, bytes] = {}
            lc = self._stored_lc()
            lo = max(msg.last_committed + 1, lc - self.SYNC_TAIL + 1, 1)
            for v in range(lo, lc + 1):
                blob = self.db.get("paxos", f"v_{v}")
                if blob is not None:
                    values[v] = blob
            msg.connection.send_message(MMonProbe(
                op=MMonProbe.SYNC_DATA, rank=self.mon_id,
                addr=self.addr, last_committed=lc, values=values))
            return
        if self.elector is not None or not self._probe_addrs:
            return      # only an un-joined prober consumes replies
        if msg.op == MMonProbe.REPLY:
            mons = {int(r): a for r, a in
                    msg.mon_db.get("mons", {}).items()}
            if mons.get(self.mon_id) != self.addr:
                return  # not (yet) a member: keep probing for mon add
            self._pending_join = msg.mon_db
            if self._stored_lc() < msg.last_committed \
                    and not self._probe_synced:
                # wiped/fresh store: pull the paxos tail BEFORE
                # electing (a rank-0 joiner winning with an empty
                # store would roll the cluster back)
                msg.connection.send_message(MMonProbe(
                    op=MMonProbe.SYNC, rank=self.mon_id,
                    addr=self.addr,
                    last_committed=self._stored_lc()))
                return
            self._finish_join(msg.mon_db)
            return
        if msg.op == MMonProbe.SYNC_DATA:
            t = self.db.get_transaction()
            for v in sorted(msg.values):
                t.set("paxos", f"v_{v}", msg.values[v])
            t.set("paxos", "last_committed",
                  str(msg.last_committed).encode())
            self.db.submit_transaction(t)
            self._probe_synced = True
            dout("mon", 1, "mon.%d store-synced to v%d (%d values)",
                 self.mon_id, msg.last_committed, len(msg.values))
            join = getattr(self, "_pending_join", None)
            if join:
                self._finish_join(join)

    def _finish_join(self, mon_db: dict) -> None:
        dout("mon", 1, "mon.%d joining: monmap e%d %s", self.mon_id,
             mon_db.get("epoch", 0), mon_db.get("mons"))
        self._probe_addrs = []
        self._probe_synced = False
        self.monmap_epoch = int(mon_db.get("epoch", 0))
        self.set_monmap({int(r): a
                         for r, a in mon_db.get("mons", {}).items()})

    def _maybe_reconfigure(self, mon_db: dict) -> None:
        """A committed monmap with a newer epoch reconfigures this
        member: update peers, resize the elector, re-elect.  A mon that
        finds itself REMOVED goes quiet (the reference's removed mon
        shuts down; ours parks so the operator can stop it)."""
        if not mon_db or int(mon_db.get("epoch", 0)) <= self.monmap_epoch:
            return
        mons = {int(r): a for r, a in mon_db.get("mons", {}).items()}
        self.monmap_epoch = int(mon_db.get("epoch", 0))
        if mons == self.monmap:
            return
        # keep removed members dialable: the COMMIT carrying their own
        # removal fans out AFTER this reconfigure runs on the leader —
        # dropping the address here would strand them in the old map
        for r, a in self.monmap.items():
            if r not in mons:
                self._retired_mons[r] = a
        self.monmap = mons
        if self.mon_id not in mons:
            dout("mon", 1, "mon.%d removed from monmap e%d — going "
                 "quiet", self.mon_id, self.monmap_epoch)
            self.elector = None
            self.paxos = None
            return
        dout("mon", 1, "mon.%d monmap e%d -> members %s", self.mon_id,
             self.monmap_epoch, sorted(mons))
        if self.is_leader():
            self._clog(PRIO_INFO, "monmap e%d: members %s",
                       self.monmap_epoch, sorted(mons))
        if self.elector is not None:
            self.elector.set_ranks(sorted(mons))
            self._request_election()

    def _request_election(self) -> None:
        # one election at a time: restarting every liveness tick would
        # bump the epoch faster than peers can ack and never converge
        e = self.elector
        if e and not self._stop and not e.electing:
            dout("mon", 5, "mon.%d calling new election", self.mon_id)
            e.start()

    def _on_election_win(self, epoch: int, quorum: list[int]) -> None:
        dout("mon", 5, "mon.%d won election epoch %d quorum %s",
             self.mon_id, epoch, quorum)
        self._mds_watch_since = None    # fresh grace for every rank
        p = self.paxos
        if p is not None:
            p.leader_init(epoch, quorum)

    def _on_election_lose(self, epoch: int, leader: int,
                          quorum: list[int]) -> None:
        dout("mon", 5, "mon.%d peon of mon.%d epoch %d", self.mon_id,
             leader, epoch)
        p = self.paxos
        if p is not None:
            p.peon_init(epoch, leader, quorum)

    def _on_paxos_active(self) -> None:
        """Leader finished the collect phase.  Bootstrap the very first
        map if the store is empty (must not block the calling thread)."""
        p = self.paxos
        if p is not None and p.last_committed == 0:
            self._work_q.put(("bootstrap", None, None))

    #: incremental history depth (the mon's map trimming: subscribers
    #: gapped further back than this get a full map)
    INC_HISTORY = 500

    def _on_paxos_commit(self, version: int, blob: bytes) -> None:
        """Every quorum member applies committed maps identically, and
        each builds the SAME incremental locally (deterministic diff of
        consecutive committed maps) — no extra paxos state needed."""
        from ceph_tpu.osd.map_codec import diff_osdmap, encode_incremental
        newmap = decode_osdmap(blob)
        with self._lock:
            if newmap.epoch <= self.osdmap.epoch:
                return
            old = self.osdmap
            self.osdmap = newmap
            inc_blob = None
            if newmap.epoch == old.epoch + 1 and old.epoch > 0:
                inc_blob = encode_incremental(diff_osdmap(old, newmap))
                self._inc_history[newmap.epoch] = inc_blob
                for e in list(self._inc_history):
                    if e <= newmap.epoch - self.INC_HISTORY:
                        del self._inc_history[e]
            subs = list(self._subs.values())
        self._maybe_reconfigure(newmap.mon_db)
        if inc_blob is not None:
            # normal churn: O(delta) bytes per subscriber per epoch
            msg = MOSDMapMsg(epoch=newmap.epoch,
                             incs=[(newmap.epoch, inc_blob)])
        else:
            # never fan the paxos value out: it carries the auth keys
            msg = MOSDMapMsg(epoch=newmap.epoch,
                             map_blob=encode_osdmap(newmap))
        for sub in subs:
            sub[2].send_message(msg)

    def _schedule_tick(self) -> None:
        if self._stop:
            return
        if self._tick_timer is not None:
            # idempotent: a joiner schedules during probing and again
            # via set_monmap on join — never run two timer chains
            self._tick_timer.cancel()
        self._tick_timer = threading.Timer(self.TICK_INTERVAL, self._tick)
        self._tick_timer.daemon = True
        self._tick_timer.start()

    _probe_last = 0.0

    def _tick(self) -> None:
        try:
            e, p = self.elector, self.paxos
            if self._probe_addrs and e is None:
                if time.time() - self._probe_last > 1.0:
                    self._send_probes()
            if e:
                e.tick()
            if p:
                p.tick()
            if self.is_leader() and self.osdmap.fs_db:
                self._check_mds_failures()
            if self.is_leader():
                self._maybe_rotate_service_keys()
                self._check_mgr_map()
                self._check_health_transition()
                self._maybe_seed_mon_db()
            self._maybe_fix_my_addr()
        finally:
            self._schedule_tick()

    MGR_SUB_GRACE = 12.0

    def _live_mgr_subs(self) -> dict:
        """mgr.* subscriptions whose session is up AND recently
        renewed (subscribers renew every ~5 s)."""
        now = time.time()
        with self._lock:
            return {n: s[0] for n, s in self._subs.items()
                    if n.startswith("mgr.")
                    and not getattr(s[2], "_down", False)
                    and now - (s[3] if len(s) > 3 else now)
                    < self.MGR_SUB_GRACE}

    #: beacons renew every ~5 s: the grace spans two-plus periods so a
    #: single starved timer tick (1-core hosts) never demotes a healthy
    #: active; matches MGR_SUB_GRACE so the two liveness sources agree
    MGR_BEACON_GRACE = 12.0

    def _live_mgrs(self) -> dict[str, dict]:
        """name -> {addr, modules} for every mgr whose beacon is fresh
        and whose session is up (a SIGKILLed mgr's dead connection
        drops it instantly, without waiting out the grace).  Plain
        mgr.* subscriptions count as beacons too, so an older mgr that
        never beacons still registers — reusing the last-known module
        list, never wiping it (a map whose only change is modules
        flapping to [] would churn paxos epochs for nothing)."""
        now = time.time()
        out: dict[str, dict] = {}
        with self._lock:
            for n, b in self._mgr_beacons.items():
                if not getattr(b[2], "_down", False) and b[3] \
                        and now - b[0] < self.MGR_BEACON_GRACE:
                    out[n] = {"addr": b[1], "modules": b[4]}
            known = {n: b[4] for n, b in self._mgr_beacons.items()}
        for n, addr in self._live_mgr_subs().items():
            out.setdefault(n, {"addr": addr,
                               "modules": known.get(n, [])})
        return out

    def _check_mgr_map(self) -> None:
        """Publish/maintain the MgrMap (MgrMonitor.cc:47-120 reduced):
        keep the current active while its beacon lives; promote the
        first live standby when it dies; list the rest as standbys.
        OSDs and clients learn the change through their map
        subscription; a promoted standby sees itself named and loads
        its module set (see MgrDaemon._check_activation)."""
        live = self._live_mgrs()
        cur = self.osdmap.mgr_db
        if not live and not cur:
            return
        desired: dict = {}
        if live:
            cur_name = (cur or {}).get("active_name")
            if cur_name in live \
                    and live[cur_name]["addr"] == cur.get("addr"):
                name = cur_name          # incumbent keeps the role
            else:
                name = sorted(live)[0]   # promotion
            desired = {
                "active_name": name,
                "addr": live[name]["addr"],
                "modules": live[name]["modules"],
                "standbys": [{"name": n, "addr": live[n]["addr"]}
                             for n in sorted(live) if n != name],
            }

        if self.osdmap.mgr_db == desired:
            return
        old_active = (cur or {}).get("active_name")
        new_active = desired.get("active_name")

        def fn(m: OSDMap, desired=desired):
            if m.mgr_db == desired:
                return False
            m.mgr_db = desired

        def log_after():
            # runs after the mutation: log only a transition that
            # actually COMMITTED, deduped against the last logged
            # active (pending paxos rounds re-enqueue this every tick)
            if self.osdmap.mgr_db != desired \
                    or old_active == new_active \
                    or self._mgr_logged_active == new_active:
                return
            self._mgr_logged_active = new_active
            if new_active is None:
                self._clog(PRIO_WARN, "no active mgr (last was %s)",
                           old_active)
            else:
                self._clog(PRIO_INFO, "mgr %s is now active%s",
                           new_active,
                           f" (was {old_active})" if old_active else "")
        self._work_q.put(("mgr_map", (fn, log_after), None))

    def _maybe_rotate_service_keys(self) -> None:
        """Leader: advance stale service-key generations (KeyServer
        rotation) through paxos so every mon grants/validates alike."""
        svc = self.osdmap.auth_db.get("__svc__")
        if not svc:
            return
        now = time.time()
        stale = any(now - s.get("rotated_at", 0) >= self.cephx_rotation
                    for s in svc.values())
        if not stale:
            return

        def fn(m: OSDMap):
            return self._keyserver(m.auth_db).maybe_rotate() or False
        self._work_q.put(("rotate_keys", fn, None))

    # -- FSMap / MDS cluster (MDSMonitor analog) ------------------------------

    MDS_BEACON_GRACE = 6.0

    def _check_mds_failures(self) -> None:
        """Leader tick: a rank whose gid stopped beaconing is failed;
        promote a standby into it (MDSMonitor::maybe_replace_gid)."""
        now = time.time()
        if self._mds_watch_since is None:
            self._mds_watch_since = now
        fs = self.osdmap.fs_db
        dead = []
        for rank, ent in fs.get("ranks", {}).items():
            seen = self._mds_beacons.get(ent["gid"])
            t0 = seen[0] if seen is not None else self._mds_watch_since
            if now - t0 > self.MDS_BEACON_GRACE:
                dead.append((rank, ent["gid"]))
        if not dead:
            return
        self._work_q.put(("mds_failover", dead, None))

    def _do_mds_failover(self, dead: list) -> None:
        def fn(m: OSDMap):
            fs = m.fs_db
            if not fs:
                return False
            changed = False
            for rank, gid in dead:
                ent = fs.get("ranks", {}).get(rank)
                if ent is None or ent["gid"] != gid:
                    continue    # already replaced
                del fs["ranks"][rank]
                changed = True
                if fs.get("standbys"):
                    nxt = fs["standbys"].pop(0)
                    fs["ranks"][rank] = nxt
                    dout("mon", 1, "fsmap: rank %s failed (gid %d), "
                         "promoting gid %d", rank, gid, nxt["gid"])
                else:
                    dout("mon", 1, "fsmap: rank %s failed (gid %d), "
                         "no standby", rank, gid)
            return changed     # False = no paxos round for a stale item
        self._mutate(fn)

    def _do_mds_beacon(self, msg) -> None:
        """Worker-thread half: FSMap mutations for a new/boot gid."""
        def fn(m: OSDMap):
            fs = m.fs_db
            if not fs:
                return False
            ranks = fs.setdefault("ranks", {})
            standbys = fs.setdefault("standbys", [])
            known = {e["gid"] for e in ranks.values()} | \
                    {e["gid"] for e in standbys}
            if msg.gid in known:
                return False
            ent = {"gid": msg.gid, "addr": msg.addr}
            for r in range(int(fs.get("max_mds", 1))):
                if str(r) not in ranks:
                    ranks[str(r)] = ent
                    dout("mon", 2, "fsmap: gid %d -> rank %d",
                         msg.gid, r)
                    return None
            standbys.append(ent)
            return None
        self._mutate(fn)

    def _beacon_ack(self, msg) -> None:
        fs = self.osdmap.fs_db
        rank = -1
        bal_rank, bal_load = -1, 0.0
        with self._lock:
            for r, ent in fs.get("ranks", {}).items():
                if ent["gid"] == msg.gid:
                    rank = int(r)
                load = self._mds_beacons.get(ent["gid"], (0, "", 0.0))[2]
                if bal_rank < 0 or load < bal_load:
                    bal_rank, bal_load = int(r), load
        msg.connection.send_message(MMDSBeacon(
            gid=msg.gid, addr=msg.addr, state="ack", rank=rank,
            bal_rank=bal_rank, bal_load=bal_load,
            meta_pool=fs.get("metadata_pool", -1) if fs else -1,
            data_pool=fs.get("data_pool", -1) if fs else -1))

    # -- the mutation path (worker thread only) -------------------------------

    def _work_loop(self) -> None:
        while True:
            item = self._work_q.get()
            if item is None:
                return
            kind, payload, reply_to = item
            try:
                if kind == "bootstrap":
                    self._do_bootstrap()
                elif kind == "cmd":
                    out, res = self.handle_command(payload)
                    if reply_to is not None:
                        con, tid, fwd = reply_to
                        if fwd is None:
                            con.send_message(MMonCommandAck(
                                tid=tid, result=res, output=out))
                        else:
                            con.send_message(MMonForwardAck(
                                fwd_tid=fwd, result=res, output=out))
                elif kind == "boot":
                    self._do_boot(payload)
                elif kind == "failure":
                    self._do_failure(payload)
                elif kind == "mds_beacon":
                    self._do_mds_beacon(payload)
                elif kind == "mds_failover":
                    self._do_mds_failover(payload)
                elif kind in ("rotate_keys", "mgr_map"):
                    if isinstance(payload, tuple):
                        fn, after = payload
                        self._mutate(fn)
                        after()
                    else:
                        self._mutate(payload)
            except Exception:
                from ceph_tpu.common.logging import get_logger
                get_logger("mon").exception("mon.%d work item failed",
                                            self.mon_id)

    def _mutate(self, fn) -> bool:
        """Run fn on a copy of the map; commit through Paxos on change.
        fn returns False for a no-op.  Worker thread only."""
        if not self.is_leader():
            return False
        with self._lock:
            m = decode_osdmap(encode_osdmap(self.osdmap, with_auth=True))
        if fn(m) is False:
            return True  # nothing to do
        m.epoch += 1
        # the paxos value is mon-internal: it is the ONE encoding that
        # carries the auth key table (peons/restarts restore it from
        # here); every client/OSD-facing broadcast re-encodes stripped
        blob = encode_osdmap(m, with_auth=True)
        p = self.paxos
        if p is None:      # removed from the monmap mid-command
            return False
        return p.propose_and_wait(blob)

    def _auth_lookup(self, entity: str):
        """Entity secret for the handshake: the committed auth_db once
        it exists, the static seed keyring before bootstrap (the
        reference's mon keyring file)."""
        db = self.osdmap.auth_db
        if db:
            key = db.get(entity)
            return key if isinstance(key, str) else None
        return self._cephx_seed.get(entity)

    def _self_ticket(self, service: str):
        """The mon dials services too (map pushes): it grants itself a
        ticket from its own key server."""
        svc_state = self.osdmap.auth_db.get("__svc__")
        if svc_state is None:
            return None
        ks = self._keyserver({"__svc__": svc_state})
        if service not in ks.SERVICES:
            return None
        return ks.grant(service, f"mon.{self.mon_id}")

    def _keyserver(self, auth_db: dict):
        from ceph_tpu.auth.cephx import KeyServer
        return KeyServer(auth_db.setdefault("__svc__", {}),
                         rotation_period=self.cephx_rotation)

    def _do_bootstrap(self) -> None:
        p = self.paxos
        if p is None or p.last_committed > 0:
            return

        def fn(m: OSDMap):
            m.crush = CrushMap()
            m.crush.add_bucket(
                make_bucket(-1, CRUSH_BUCKET_STRAW2, 2, [], []))
            # seed the committed monmap from the static boot config so
            # `mon add/rm` has a base to mutate and probing joiners get
            # an authoritative member set
            m.mon_db = {"epoch": 1,
                        "mons": {str(r): a
                                 for r, a in self.monmap.items()}}
            if self._cephx_seed:
                # commit the seed + fresh rotating service keys
                m.auth_db.update(self._cephx_seed)
                ks = self._keyserver(m.auth_db)
                for svc in ks.SERVICES:
                    ks._svc(svc)
        self._mutate(fn)

    # -- dispatch -------------------------------------------------------------

    def ms_dispatch(self, msg) -> bool:
        if self._stop:
            return True  # stopping mon answers nothing (zombie guard)
        if isinstance(msg, MMonProbe):
            self._handle_probe(msg)
            return True
        if isinstance(msg, MMonElection):
            e = self.elector
            if e:
                e.handle(msg)
            return True
        if isinstance(msg, MMonPaxos):
            p = self.paxos
            if p:
                p.handle(msg)
            return True
        if isinstance(msg, MMonCommand):
            self._handle_command_msg(msg)
            return True
        if isinstance(msg, MMonForward):
            # only a fellow mon may forward (it attests the original
            # caller's identity inside the blob; a client sending this
            # directly could forge any identity)
            if self._cephx_seed:
                ent = getattr(msg.connection, "auth_entity", None)
                if not (ent or "").startswith("mon."):
                    return True
            import json
            cmd = json.loads(msg.cmd_blob.decode())
            self._work_q.put(("cmd", cmd,
                              (msg.connection, msg.cmd_tid, msg.fwd_tid)))
            return True
        if isinstance(msg, MMonForwardAck):
            with self._lock:
                waiting = self._fwd_waiting.pop(msg.fwd_tid, None)
            if waiting is not None:
                con, tid = waiting
                con.send_message(MMonCommandAck(
                    tid=tid, result=msg.result, output=msg.output))
            return True
        if isinstance(msg, MOSDBoot):
            self._work_q.put(("boot", msg, None))
            return True
        if isinstance(msg, MMonSubscribe):
            with self._lock:
                entity = (msg.connection.peer_name
                          or EntityName.parse(msg.name))
                # map pushes ride the SUBSCRIBER'S OWN connection (the
                # session it authenticated): dialing its listener back
                # would need credentials no one holds for "client"
                # targets, and a fake push must be impossible anyway
                self._subs[msg.name] = (msg.addr, entity,
                                        msg.connection, time.time())
                epoch = self.osdmap.epoch
                reply = None
                if epoch > 0 and epoch > msg.epoch:
                    # catch the subscriber up with deltas when its gap
                    # is covered by history; full map otherwise
                    wanted = range(msg.epoch + 1, epoch + 1)
                    if msg.epoch > 0 and all(
                            e in self._inc_history for e in wanted):
                        reply = MOSDMapMsg(
                            epoch=epoch,
                            incs=[(e, self._inc_history[e])
                                  for e in wanted])
                    else:
                        reply = MOSDMapMsg(
                            epoch=epoch,
                            map_blob=encode_osdmap(self.osdmap))
                # (renewal from a current subscriber: nothing to send)
            if reply is not None:
                msg.connection.send_message(reply)
            return True
        if isinstance(msg, MPGStats):
            with self._lock:
                self._pg_stats[msg.osd_id] = {
                    "states": dict(msg.states),
                    "degraded_objects": msg.degraded_objects,
                    "received": time.time()}
            return True
        if isinstance(msg, MOSDFailure):
            self._work_q.put(("failure", msg, None))
            return True
        if isinstance(msg, MMDSBeacon):
            with self._lock:
                self._mds_beacons[msg.gid] = (time.time(), msg.addr,
                                              msg.load)
                fs = self.osdmap.fs_db
                known = bool(fs) and any(
                    e["gid"] == msg.gid
                    for e in list(fs.get("ranks", {}).values())
                    + fs.get("standbys", []))
            if fs and not known and self.is_leader():
                self._work_q.put(("mds_beacon", msg, None))
            self._beacon_ack(msg)
            return True
        if isinstance(msg, MOSDPing):
            return True  # mon liveness probe, nothing to do
        if isinstance(msg, MMgrBeacon):
            with self._lock:
                self._mgr_beacons[msg.name] = (
                    time.time(), msg.addr, msg.connection,
                    msg.available, list(msg.modules))
            return True
        if isinstance(msg, MLog):
            self.logstore.append(msg.name, msg.entries)
            return True
        return False

    def _handle_command_msg(self, msg: MMonCommand) -> None:
        # the AUTHENTICATED identity comes from the connection's cephx
        # handshake, never from the command body (strip spoof attempts)
        msg.cmd.pop("_auth_entity", None)
        ent = getattr(msg.connection, "auth_entity", None)
        if ent is not None:
            msg.cmd["_auth_entity"] = ent
        if self.is_leader():
            self._work_q.put(("cmd", msg.cmd,
                              (msg.connection, msg.tid, None)))
            return
        # peon: forward to the leader (MForward)
        e = self.elector
        leader = e.leader if e else None
        if leader is None or leader == self.mon_id:
            msg.connection.send_message(MMonCommandAck(
                tid=msg.tid, result=-11, output="no quorum"))
            return
        import json
        with self._lock:
            self._fwd_tid += 1
            fwd = self._fwd_tid
            self._fwd_waiting[fwd] = (msg.connection, msg.tid)
        self._send_mon(leader, MMonForward(
            fwd_tid=fwd, cmd_tid=msg.tid,
            cmd_blob=json.dumps(msg.cmd).encode()))

    # -- osd lifecycle (worker thread) ----------------------------------------

    def _do_boot(self, msg: MOSDBoot) -> None:
        def fn(m: OSDMap):
            osd = msg.osd_id
            if (osd < m.max_osd and m.is_up(osd)
                    and osd < len(m.osd_addrs)
                    and m.osd_addrs[osd] == msg.addr):
                return False  # dup boot (osd sends to every mon)
            if osd >= m.max_osd:
                m.set_max_osd(osd + 1)
            newly_known = not m.exists(osd)
            was_down = m.exists(osd) and not m.is_up(osd)
            m.mark_up(osd, weight=m.osd_weight[osd] or 0x10000)
            m.osd_addrs[osd] = msg.addr
            if was_down:
                # a marked-down osd that boots right back was laggy, not
                # dead: fold this episode into the decaying laggy history
                # that check_failure uses to extend the grace
                # (OSDMonitor::prepare_boot xinfo update)
                xi = m.get_xinfo(osd)
                if xi.down_stamp > 0:
                    w = float(self.ctx.conf.get("mon_osd_laggy_weight"))
                    cap = float(self.ctx.conf.get(
                        "mon_osd_laggy_max_interval"))
                    interval = min(time.time() - xi.down_stamp, cap)
                    xi.laggy_interval = (
                        w * interval + (1 - w) * xi.laggy_interval)
                    xi.laggy_probability = w + (1 - w) * xi.laggy_probability
            if newly_known:
                self._crush_add_osd(m, osd, 0x10000)
        with self._lock:
            self._osd_addrs[msg.osd_id] = msg.addr
            self._failure_reports.pop(msg.osd_id, None)
        was_up = self.osdmap.is_up(msg.osd_id)
        if self._mutate(fn) and not was_up \
                and self.osdmap.is_up(msg.osd_id):
            self._clog(PRIO_INFO, "osd.%d boot (%s)", msg.osd_id,
                       msg.addr)

    def _crush_add_osd(self, m: OSDMap, osd: int, weight: int) -> None:
        """Attach a booting osd to the map's hierarchy (the default
        crush-location hook: straight under the root for flat maps, in
        a fresh sibling bucket when the root holds buckets — so an
        operator map injected via setcrushmap keeps its failure-domain
        shape instead of gaining stray devices on a hardcoded -1)."""
        crush = m.crush
        referenced = _referenced_bucket_ids(crush)
        root = next((b for b in crush.buckets
                     if b is not None and b.id not in referenced), None)
        if root is None:
            # boot raced the bootstrap commit: create the root here
            crush.add_bucket(
                make_bucket(-1, CRUSH_BUCKET_STRAW2, 2, [], []))
            root = crush.bucket(-1)
        child_buckets = [crush.bucket(it) for it in root.items if it < 0]
        if child_buckets:
            # hierarchical map: wrap the device in its own bucket of
            # the same type as the root's children (host-per-osd)
            proto = child_buckets[0]
            nb = make_bucket(crush.next_bucket_id(), proto.alg,
                             proto.type, [osd], [weight])
            crush.add_bucket(nb)
            names = m.crush_names.get("items")
            if isinstance(names, dict):
                names[str(nb.id)] = f"osd-{osd}-host"
            root.items.append(nb.id)
            root.item_weights.append(nb.weight)
            root.weight += nb.weight
        else:
            root.items.append(osd)
            root.item_weights.append(weight)
            root.weight += weight
        crush.max_devices = max(crush.max_devices, osd + 1)

    def _reporter_subtree(self, osd: int) -> int:
        """The failure-domain key a reporter counts under: its immediate
        parent bucket in the crush hierarchy (host level for two-level
        maps — mon_osd_reporter_subtree_level semantics), or the osd id
        itself on flat maps where the parent is the root."""
        return self._reporter_subtrees([osd])[osd]

    def _reporter_subtrees(self, osds) -> dict[int, int]:
        """Resolve many reporters in one pass over the bucket array
        (peers re-file reports every heartbeat tick; per-reporter scans
        would be O(reporters x buckets) per report)."""
        crush = self.osdmap.crush
        referenced = _referenced_bucket_ids(crush)
        out = {o: o for o in osds}
        want = set(osds)
        for b in crush.buckets:
            if b is None or b.id not in referenced:
                continue
            for o in want & set(b.items):
                out[o] = b.id
        return out

    def _failure_grace(self, osd: int, now: float) -> float:
        """Adaptive grace (OSDMonitor::check_failure, OSDMonitor.cc:
        2548-2572): an osd with a history of being marked down and
        booting right back — laggy, not dead — earns extra grace
        proportional to that history, decayed by time since last down."""
        import math
        grace = float(self.ctx.conf.get("osd_heartbeat_grace"))
        if not int(self.ctx.conf.get("mon_osd_adjust_heartbeat_grace")):
            return grace
        xi = self.osdmap.get_xinfo(osd)
        if xi.laggy_probability > 0 and xi.laggy_interval > 0:
            halflife = float(self.ctx.conf.get("mon_osd_laggy_halflife"))
            decay = math.exp(math.log(0.5) / halflife
                             * max(now - xi.down_stamp, 0.0))
            grace += decay * xi.laggy_interval * xi.laggy_probability
        return grace

    def _do_failure(self, msg: MOSDFailure) -> None:
        need = int(self.ctx.conf.get("mon_osd_min_down_reporters"))
        now = time.time()
        with self._lock:
            if msg.alive:
                # reporter heard from the peer again: retract its report
                # (OSDMonitor::process_failure FLAG_ALIVE path)
                reports = self._failure_reports.get(msg.failed_osd)
                if reports:
                    reports.pop(msg.reporter, None)
                    if not reports:
                        self._failure_reports.pop(msg.failed_osd, None)
                return
            if not self.osdmap.is_up(msg.failed_osd):
                return
            reports = self._failure_reports.setdefault(msg.failed_osd, {})
            reports[msg.reporter] = (now, msg.failed_for)
            # a report is only a live witness while its reporter is still
            # up and it is fresh — a reporter that died after filing can
            # never retract, and peers re-file every heartbeat tick, so
            # anything older than a few grace periods is stale
            # (check_failure cancels reports from down reporters)
            expiry = 2 * float(self.ctx.conf.get("osd_heartbeat_grace"))
            for r in [r for r, (t, _ff) in reports.items()
                      if not self.osdmap.is_up(r) or now - t > expiry]:
                del reports[r]
            if not reports:
                self._failure_reports.pop(msg.failed_osd, None)
                return
            # reporters must span distinct failure domains
            # (mon_osd_reporter_subtree_level: two osds on one host are
            # one witness) and the peer must have been unreachable for
            # the full — possibly laggy-extended — grace
            subtrees = set(self._reporter_subtrees(list(reports)).values())
            failed_for = max(ff for _t, ff in reports.values())
            if (len(subtrees) < need
                    or failed_for < self._failure_grace(msg.failed_osd, now)):
                return
            self._failure_reports.pop(msg.failed_osd, None)

        def fn(m: OSDMap):
            if not m.is_up(msg.failed_osd):
                return False
            m.mark_down(msg.failed_osd)
        if self._mutate(fn) and not self.osdmap.is_up(msg.failed_osd):
            self._clog(PRIO_WARN,
                       "osd.%d marked down (%d reporters from %d "
                       "subtrees, failed for %.1fs)", msg.failed_osd,
                       len(reports), len(subtrees), failed_for)

    # -- command table (MonCommands.h analog; worker thread) ------------------

    #: with cephx identities, these need client.admin (minimal caps
    #: floor; the reference's MonCap grammar is richer)
    ADMIN_ONLY = ("auth get-or-create", "auth del", "auth ls",
                  "auth get", "auth print-key", "config set",
                  "config rm", "osd setcrushmap",
                  "mon add", "mon rm")

    def handle_command(self, cmd: dict) -> tuple[str, int]:
        import json
        prefix = cmd.get("prefix", "")
        ent = cmd.get("_auth_entity")
        if ent is not None and ent != "client.admin" \
                and not ent.startswith("mon.") \
                and prefix in self.ADMIN_ONLY:
            # mon.* passes: a restarted mon re-commits its own address
            # through `mon add` (_maybe_fix_my_addr)
            return f"entity {ent!r} not authorized for {prefix!r}", -13
        try:
            if prefix == "auth get-ticket":
                return self._cmd_auth_get_ticket(cmd)
            if prefix == "auth rotating":
                return self._cmd_auth_rotating(cmd)
            if prefix == "status":
                return json.dumps(self.status()), 0
            if prefix in ("health", "health detail"):
                return json.dumps(self._health_report(
                    detail=(prefix == "health detail"
                            or cmd.get("detail")))), 0
            if prefix == "config set":
                return self._cmd_config_set(cmd)
            if prefix == "config get":
                return self._cmd_config_get(cmd)
            if prefix == "config rm":
                return self._cmd_config_rm(cmd)
            if prefix == "config dump":
                return json.dumps(self.osdmap.config_db), 0
            if prefix in ("config-key set", "config-key get",
                          "config-key rm", "config-key dump"):
                return self._cmd_config_key(prefix, cmd)
            if prefix == "auth get-or-create":
                return self._cmd_auth_get_or_create(cmd)
            if prefix in ("auth get", "auth print-key"):
                ent = str(cmd["entity"])
                key = self.osdmap.auth_db.get(ent)
                if not isinstance(key, str):
                    return f"no key for {ent!r}", -2
                if prefix == "auth print-key":
                    return key, 0
                return self._keyring(ent, key), 0
            if prefix == "auth ls":
                return json.dumps(sorted(
                    e for e, v in self.osdmap.auth_db.items()
                    if isinstance(v, str))), 0   # not the key server
            if prefix == "auth del":
                ent = str(cmd["entity"])
                if ent not in self.osdmap.auth_db:
                    return f"no key for {ent!r}", -2

                def fn(m: OSDMap):
                    if ent not in m.auth_db:
                        return False
                    del m.auth_db[ent]
                if not self._mutate(fn):
                    return "commit failed", -11
                return "removed", 0
            if prefix == "fs new":
                return self._cmd_fs_new(cmd)
            if prefix == "fs status":
                fs = dict(self.osdmap.fs_db)
                now = time.time()
                with self._lock:
                    fs["beacons"] = {
                        str(g): round(now - t[0], 2)
                        for g, t in self._mds_beacons.items()}
                return json.dumps(fs), 0
            if prefix == "fs set":
                if str(cmd.get("var")) != "max_mds":
                    return "only max_mds is settable", -22
                n = int(cmd["val"])
                if n < 1:
                    return "max_mds must be >= 1", -22

                def fn(m: OSDMap):
                    if not m.fs_db:
                        return False
                    m.fs_db["max_mds"] = n
                    # grow: promote standbys into the new ranks now
                    ranks = m.fs_db.setdefault("ranks", {})
                    sb = m.fs_db.setdefault("standbys", [])
                    for r in range(n):
                        if str(r) not in ranks and sb:
                            ranks[str(r)] = sb.pop(0)
                if not self._mutate(fn):
                    return "commit failed", -11
                return json.dumps({"max_mds": n}), 0
            if prefix == "quorum_status":
                e = self.elector
                return json.dumps({
                    "quorum": self.quorum(),
                    "leader": e.leader if e else None,
                    "election_epoch": e.epoch if e else 0}), 0
            if prefix == "log last":
                n = int(cmd.get("num", 100))
                return json.dumps(self.logstore.last(
                    n, channel=cmd.get("channel"),
                    min_prio=int(cmd.get("level", 0)))), 0
            if prefix == "log":
                # operator-injected entry (`ceph log "..."`), fanned
                # like any daemon's
                self._clog(PRIO_INFO, "%s",
                           str(cmd.get("message", "")))
                return "{}", 0
            if prefix == "mon dump":
                db = self._current_mon_db()
                return json.dumps({"epoch": db.get("epoch", 0),
                                   "mons": db.get("mons", {}),
                                   "quorum": self.quorum()}), 0
            if prefix == "mon add":
                return self._cmd_mon_add(cmd)
            if prefix == "mon rm":
                return self._cmd_mon_rm(cmd)
            if prefix == "mgr dump":
                # active mgr discovery (MgrMonitor::dump reduced): the
                # mgr's map subscription carries its dialable address;
                # clients re-target mgr-tier commands (pg dump, iostat)
                # at it, like the reference's mgr command routing
                if self.osdmap.mgr_db:
                    return json.dumps(self.osdmap.mgr_db), 0
                mgrs = self._live_mgr_subs()
                if not mgrs:
                    return json.dumps({"addr": ""}), 0
                name = sorted(mgrs)[0]
                return json.dumps({"active_name": name,
                                   "addr": mgrs[name]}), 0
            if prefix == "osd pool create":
                return self._cmd_pool_create(cmd)
            if prefix == "osd pool set":
                return self._cmd_pool_set(cmd)
            if prefix == "osd tree":
                return json.dumps(self._cmd_tree()), 0
            if prefix == "osd reweight":
                w = float(cmd["weight"])
                if not 0.0 <= w <= 1.0:
                    return "weight must be in [0, 1]", -22
                return self._cmd_osd_weight(int(cmd["id"]),
                                            int(w * 0x10000))
            if prefix == "osd reweight-by-utilization":
                from ceph_tpu.balancer import reweight_by_utilization
                plan = reweight_by_utilization(
                    self.osdmap, oload=int(cmd.get("oload", 120)))

                def fn(m: OSDMap):
                    for o, w in plan:
                        m.osd_weight[o] = int(w * 0x10000)
                if plan and not self._mutate(fn):
                    return "commit failed", -11
                return json.dumps({"reweighted": [
                    {"osd": o, "weight": w} for o, w in plan]}), 0
            if prefix == "osd out":
                return self._cmd_osd_weight(int(cmd["id"]), 0)
            if prefix == "osd in":
                return self._cmd_osd_weight(int(cmd["id"]), 0x10000)
            if prefix == "osd down":
                osd = int(cmd["id"])
                if not self.osdmap.exists(osd):
                    return f"osd.{osd} does not exist", -2

                def fn(m: OSDMap):
                    if not m.is_up(osd):
                        return False
                    m.mark_down(osd)
                if not self._mutate(fn):
                    return "commit failed", -11
                return "marked down", 0
            if prefix == "osd pool mksnap":
                pool_id = int(cmd["pool"])
                name = str(cmd["snap"])

                def fn(m: OSDMap):
                    p = m.pools[pool_id]
                    p.snap_seq += 1
                    p.snaps[p.snap_seq] = name
                if not self._mutate(fn):
                    return "commit failed", -11
                # epoch rides the reply so clients can barrier on map
                # propagation before trusting snapshot isolation
                return json.dumps(
                    {"snapid": self.osdmap.pools[pool_id].snap_seq,
                     "epoch": self.osdmap.epoch}), 0
            if prefix == "osd pool rmsnap":
                pool_id = int(cmd["pool"])
                name = str(cmd["snap"])
                if name not in self.osdmap.pools[pool_id].snaps.values():
                    return f"snap {name!r} does not exist", -2

                def fn(m: OSDMap):
                    p = m.pools[pool_id]
                    sid = next((s for s, n in p.snaps.items()
                                if n == name), None)
                    if sid is None:
                        return False
                    del p.snaps[sid]
                if not self._mutate(fn):
                    return "commit failed", -11
                return "removed", 0
            if prefix == "osd pg-upmap-items":
                pool_id, ps = (int(x) for x in
                               str(cmd["pgid"]).split("."))
                flat = [int(x) for x in cmd["id_pairs"]]
                if len(flat) % 2:
                    return "id_pairs must be from,to pairs", -22
                pairs = [(flat[i], flat[i + 1])
                         for i in range(0, len(flat), 2)]
                if pool_id not in self.osdmap.pools:
                    return f"pool {pool_id} does not exist", -2
                if ps >= self.osdmap.pools[pool_id].pg_num:
                    return f"pg {pool_id}.{ps} does not exist", -2
                if not all(self.osdmap.exists(t) for _f, t in pairs):
                    return "destination osd does not exist", -2

                def fn(m: OSDMap):
                    if pairs:
                        m.pg_upmap_items[(pool_id, ps)] = pairs
                    else:
                        m.pg_upmap_items.pop((pool_id, ps), None)
                if not self._mutate(fn):
                    return "commit failed", -11
                return json.dumps({"pgid": f"{pool_id}.{ps}",
                                   "pairs": pairs}), 0
            if prefix == "osd rm-pg-upmap-items":
                pool_id, ps = (int(x) for x in
                               str(cmd["pgid"]).split("."))
                if (pool_id, ps) not in self.osdmap.pg_upmap_items:
                    return "no upmap items for pg", -2

                def fn(m: OSDMap):
                    m.pg_upmap_items.pop((pool_id, ps), None)
                if not self._mutate(fn):
                    return "commit failed", -11
                return "removed", 0
            if prefix == "osd tier add":
                base, cache = int(cmd["pool"]), int(cmd["tierpool"])
                if base not in self.osdmap.pools \
                        or cache not in self.osdmap.pools:
                    return "no such pool", -2
                if base == cache:
                    return "a pool cannot be a tier of itself", -22
                if self.osdmap.pools[cache].tier_of >= 0:
                    return "tier pool already a tier", -22
                if self.osdmap.pools[base].tier_of >= 0:
                    return "base pool is itself a tier (no chains)", -22
                if any(p.tier_of == cache
                       for p in self.osdmap.pools.values()):
                    return "tier pool has tiers of its own", -22
                if self.osdmap.pools[cache].is_erasure():
                    return "cache pool must be replicated", -22

                def fn(m: OSDMap):
                    m.pools[cache].tier_of = base
                if not self._mutate(fn):
                    return "commit failed", -11
                return f"pool {cache} is now a tier of {base}", 0
            if prefix == "osd tier cache-mode":
                cache = int(cmd["pool"])
                mode = str(cmd["mode"])
                if mode not in ("none", "writeback"):
                    return f"unknown cache mode {mode!r}", -22
                if self.osdmap.pools[cache].tier_of < 0:
                    return "pool is not a tier", -22

                def fn(m: OSDMap):
                    m.pools[cache].cache_mode = \
                        "" if mode == "none" else mode
                if not self._mutate(fn):
                    return "commit failed", -11
                return f"cache-mode {mode}", 0
            if prefix == "osd tier set-overlay":
                base, cache = int(cmd["pool"]), int(cmd["overlaypool"])
                if self.osdmap.pools[cache].tier_of != base:
                    return "overlay pool is not a tier of pool", -22

                def fn(m: OSDMap):
                    m.pools[base].read_tier = cache
                    m.pools[base].write_tier = cache
                if not self._mutate(fn):
                    return "commit failed", -11
                return json.dumps({"epoch": self.osdmap.epoch}), 0
            if prefix == "osd tier remove-overlay":
                base = int(cmd["pool"])

                def fn(m: OSDMap):
                    m.pools[base].read_tier = -1
                    m.pools[base].write_tier = -1
                if not self._mutate(fn):
                    return "commit failed", -11
                return json.dumps({"epoch": self.osdmap.epoch}), 0
            if prefix == "osd tier remove":
                base, cache = int(cmd["pool"]), int(cmd["tierpool"])
                if self.osdmap.pools[cache].tier_of != base:
                    return "pool is not a tier of base", -22
                if self.osdmap.pools[base].write_tier == cache \
                        or self.osdmap.pools[base].read_tier == cache:
                    return "remove the overlay first", -16

                def fn(m: OSDMap):
                    m.pools[cache].tier_of = -1
                    m.pools[cache].cache_mode = ""
                if not self._mutate(fn):
                    return "commit failed", -11
                return "tier removed", 0
            if prefix == "qos set":
                # per-tenant dmclock profile -> the replicated qos_db
                # (every OSD folds it into its scheduler on the next
                # map push; `ceph qos set tenant=gold reservation=100
                # weight=10 limit=0`)
                from ceph_tpu.qos.dmclock import QosProfile
                tenant = str(cmd["tenant"])
                if not tenant:
                    return "empty tenant", -22
                prof = QosProfile(
                    reservation=float(cmd.get("reservation", 0.0)),
                    weight=float(cmd.get("weight", 1.0)),
                    limit=float(cmd.get("limit", 0.0)))
                try:
                    prof.validate()
                except ValueError as e:
                    return str(e), -22

                def fn(m: OSDMap):
                    m.qos_db[tenant] = prof.to_dict()
                if not self._mutate(fn):
                    return "commit failed", -11
                return json.dumps({"tenant": tenant,
                                   **prof.to_dict(),
                                   "epoch": self.osdmap.epoch}), 0
            if prefix == "qos rm":
                tenant = str(cmd["tenant"])
                if tenant not in self.osdmap.qos_db:
                    return f"no qos profile for {tenant!r}", -2

                def fn(m: OSDMap):
                    m.qos_db.pop(tenant, None)
                if not self._mutate(fn):
                    return "commit failed", -11
                return f"qos profile for {tenant} removed", 0
            if prefix == "qos ls":
                return json.dumps(self.osdmap.qos_db), 0
            if prefix == "qos slo set":
                # per-tenant SLO objectives -> the replicated slo_db
                # (the mgr slo module evaluates them as burn rates;
                # `ceph qos slo set tenant=gold
                # reservation_attainment=0.9 p99_latency_s=0.05
                # device_share=0.5`)
                from ceph_tpu.qos.dmclock import SloObjective
                tenant = str(cmd["tenant"])
                if not tenant:
                    return "empty tenant", -22
                slo = SloObjective(
                    reservation_attainment=float(
                        cmd.get("reservation_attainment", 0.0)),
                    p99_latency_s=float(cmd.get("p99_latency_s", 0.0)),
                    device_share=float(cmd.get("device_share", 0.0)))
                try:
                    slo.validate()
                except ValueError as e:
                    return str(e), -22

                def fn(m: OSDMap):
                    m.slo_db[tenant] = slo.to_dict()
                if not self._mutate(fn):
                    return "commit failed", -11
                return json.dumps({"tenant": tenant,
                                   **slo.to_dict(),
                                   "epoch": self.osdmap.epoch}), 0
            if prefix == "qos slo rm":
                tenant = str(cmd["tenant"])
                if tenant not in self.osdmap.slo_db:
                    return f"no slo for {tenant!r}", -2

                def fn(m: OSDMap):
                    m.slo_db.pop(tenant, None)
                if not self._mutate(fn):
                    return "commit failed", -11
                return f"slo for {tenant} removed", 0
            if prefix == "qos slo ls":
                return json.dumps(self.osdmap.slo_db), 0
            if prefix == "osd getmap":
                return json.dumps({"epoch": self.osdmap.epoch}), 0
            if prefix == "osd getcrushmap":
                import base64
                from ceph_tpu.msg.encoding import Encoder
                from ceph_tpu.osd.map_codec import encode_crush
                e = Encoder()
                encode_crush(self.osdmap.crush, e)
                return json.dumps({
                    "epoch": self.osdmap.epoch,
                    "names": self.osdmap.crush_names,
                    "crush_b64":
                        base64.b64encode(e.tobytes()).decode()}), 0
            if prefix == "osd setcrushmap":
                import base64
                from ceph_tpu.msg.encoding import Decoder
                from ceph_tpu.osd.map_codec import decode_crush
                blob = base64.b64decode(cmd["crush_b64"])
                try:
                    crush = decode_crush(Decoder(blob))
                except Exception as e:
                    return f"cannot decode crush map: {e}", -22
                # every pool's rule must survive (OSDMonitor
                # prepare_newcrush validation)
                for pid, p in self.osdmap.pools.items():
                    r = (crush.rules[p.crush_rule]
                         if 0 <= p.crush_rule < crush.max_rules
                         else None)
                    if r is None:
                        return (f"pool {pid} references rule "
                                f"{p.crush_rule} absent from new map"), -22
                if crush.max_devices > self.osdmap.max_osd:
                    return (f"crush map addresses {crush.max_devices} "
                            f"devices but max_osd is "
                            f"{self.osdmap.max_osd}"), -22

                names = cmd.get("names") or {}

                def fn(m: OSDMap):
                    m.crush = crush
                    m.crush_names = names
                if not self._mutate(fn):
                    return "commit failed", -11
                return json.dumps({"epoch": self.osdmap.epoch}), 0
            return f"unknown command {prefix!r}", -22
        except (KeyError, ValueError, IndexError) as e:
            return f"command failed: {e}", -22

    def _cmd_auth_get_ticket(self, cmd) -> tuple[str, int]:
        """Ticket grant (CephxServiceHandler): the caller's cephx
        identity gets a ticket for one service — unless the entity has
        been deleted, which is how `auth del` cuts future access."""
        ent = cmd.get("_auth_entity")
        if ent is None:
            return "no authenticated identity on this connection", -13
        db = self.osdmap.auth_db
        if (db.get(ent) is None or not isinstance(db.get(ent), str)) \
                and self._cephx_seed.get(ent) is None:
            return f"entity {ent!r} unknown or revoked", -13
        service = str(cmd.get("service", ""))
        svc_state = self.osdmap.auth_db.get("__svc__")
        if svc_state is None:
            return "cephx key server not initialized", -22
        ks = self._keyserver({"__svc__": svc_state})
        if service not in ks.SERVICES:
            return f"unknown service {service!r}", -22
        from ceph_tpu.auth.cephx import ticket_to_json
        return ticket_to_json(ks.grant(service, ent)), 0

    def _cmd_auth_rotating(self, cmd) -> tuple[str, int]:
        """Rotating service keys for a service DAEMON (its validation
        material).  Only daemons of that service (or admin) may fetch."""
        import json
        ent = cmd.get("_auth_entity")
        service = str(cmd.get("service", ""))
        if ent is not None and ent != "client.admin" \
                and not ent.startswith(service + "."):
            return f"entity {ent!r} may not read {service!r} keys", -13
        svc_state = self.osdmap.auth_db.get("__svc__")
        if svc_state is None:
            return "cephx key server not initialized", -22
        ks = self._keyserver({"__svc__": svc_state})
        if service not in ks.SERVICES:
            return f"unknown service {service!r}", -22
        return json.dumps(ks.rotating_keys(service)), 0

    def _cmd_fs_new(self, cmd) -> tuple[str, int]:
        """`ceph fs new <name> <metadata_pool> <data_pool>`
        (MDSMonitor's filesystem creation)."""
        import json
        name = str(cmd.get("fs_name", "cephfs"))
        meta = int(cmd["metadata"])
        data = int(cmd["data"])
        if meta not in self.osdmap.pools or data not in self.osdmap.pools:
            return "metadata/data pool does not exist", -2
        if self.osdmap.fs_db:
            return f"filesystem {self.osdmap.fs_db['name']!r} exists", -17

        def fn(m: OSDMap):
            if m.fs_db:
                return False
            m.fs_db = {"name": name, "max_mds": 1,
                       "metadata_pool": meta, "data_pool": data,
                       "ranks": {}, "standbys": []}
        if not self._mutate(fn):
            return "commit failed", -11
        return json.dumps({"fs_name": name}), 0

    def _cmd_pool_create(self, cmd) -> tuple[str, int]:
        result: list[int] = []

        def fn(m: OSDMap):
            pool_id = max(m.pools, default=0) + 1
            pg_num = int(cmd.get("pg_num",
                                 self.ctx.conf.get("osd_pool_default_pg_num")))
            ptype = (POOL_TYPE_ERASURE if cmd.get("pool_type") == "erasure"
                     else 1)
            profile = {}
            if ptype == POOL_TYPE_ERASURE:
                profile = {"plugin": cmd.get("plugin", "jerasure"),
                           "k": str(cmd.get("k", 4)),
                           "m": str(cmd.get("m", 2))}
                # plugin-specific keys ride through (shec's c, lrc's
                # mapping/layers, jerasure/isa techniques); non-string
                # values must be JSON, not python repr
                for key in ("technique", "c", "mapping", "layers"):
                    if key in cmd:
                        v = cmd[key]
                        profile[key] = (v if isinstance(v, str)
                                        else json.dumps(v))
                if profile["plugin"] in ("jerasure", "isa"):
                    profile.setdefault("technique", "reed_sol_van")
                # validate the profile NOW (reference: OSDMonitor
                # get_erasure_code at pool create) and take the true
                # chunk geometry from the codec — lrc's width comes from
                # its mapping, not k+m
                from ceph_tpu.ec import registry_instance
                codec = registry_instance().factory(
                    profile["plugin"], dict(profile))
                size = codec.get_chunk_count()
                data_chunks = codec.get_data_chunk_count()
                rule = add_simple_rule(m.crush, -1, 0, "indep")
            else:
                rule = add_simple_rule(m.crush, -1, 0, "firstn")
                size = int(cmd.get("size",
                                   self.ctx.conf.get("osd_pool_default_size")))
            if "min_size" in cmd:
                min_size = int(cmd["min_size"])
            elif ptype == POOL_TYPE_ERASURE:
                # k+1, not k: an EC write acked at exactly k live shards
                # has zero redundancy margin — one more store loss is
                # data loss (the thrasher caught this; real deployments
                # default min_size = k+1 for the same reason)
                min_size = min(data_chunks + 1, size)
            else:
                min_size = max(1, size - 1)
            m.pools[pool_id] = PGPool(
                pool_id=pool_id, type=ptype, size=size,
                min_size=min_size,
                crush_rule=rule, pg_num=pg_num, ec_profile=profile)
            result.append(pool_id)
        if not self._mutate(fn):
            return "commit failed", -11
        return f"pool {result[0]} created", 0

    # -- auth key table (mon/AuthMonitor analog) ------------------------------

    @staticmethod
    def _keyring(entity: str, key: str) -> str:
        """The keyring file shape `ceph auth get` emits."""
        return f"[{entity}]\n\tkey = {key}\n"

    def _cmd_auth_get_or_create(self, cmd) -> tuple[str, int]:
        """Issue (or return the existing) key for an entity — the
        AuthMonitor's create-or-fetch flow.  Keys are random per entity
        and replicate through Paxos with the map."""
        import base64
        import os as _os
        ent = str(cmd["entity"])
        existing = self.osdmap.auth_db.get(ent)
        if existing is not None:
            return self._keyring(ent, existing), 0
        newkey = base64.b64encode(_os.urandom(16)).decode()

        def fn(m: OSDMap):
            # another proposer may have won the race; keep the winner
            m.auth_db.setdefault(ent, newkey)
        if not self._mutate(fn):
            return "commit failed", -11
        return self._keyring(ent, self.osdmap.auth_db[ent]), 0

    # -- central config-db (mon/ConfigMonitor.h:13 analog) --------------------

    def _cmd_config_set(self, cmd) -> tuple[str, int]:
        import json
        who = str(cmd.get("who", "global"))
        name = str(cmd["name"])
        value = str(cmd["value"])
        # reject unknown option names up front (the reference's config
        # set does): a typo silently persisted-but-never-applied is the
        # worst operator experience
        from ceph_tpu.common.config import OPTIONS
        if name not in OPTIONS:
            return f"unknown config option {name!r}", -22
        try:
            OPTIONS[name].cast(value)
        except (ValueError, TypeError):
            return (f"invalid value {value!r} for {name!r} "
                    f"({OPTIONS[name].type})"), -22

        def fn(m: OSDMap):
            sec = m.config_db.setdefault(who, {})
            if sec.get(name) == value:
                return False
            sec[name] = value
        if not self._mutate(fn):
            return "commit failed", -11
        return json.dumps({"epoch": self.osdmap.epoch}), 0

    def _cmd_mon_add(self, cmd) -> tuple[str, int]:
        """`ceph mon add <id> <addr>` (MonmapMonitor::preprocess_join
        reduced): commit the grown monmap; every member reconfigures on
        the commit, and the probing joiner finds itself in the REPLY."""
        import json
        rank = int(cmd["id"])
        addr = str(cmd["addr"])
        base = self._current_mon_db()
        mons = dict(base.get("mons", {}))
        if mons.get(str(rank)) == addr:
            return json.dumps({"epoch": base.get("epoch", 0)}), 0

        def fn(m: OSDMap):
            db = m.mon_db or self._current_mon_db()
            ms = dict(db.get("mons", {}))
            if ms.get(str(rank)) == addr:
                return False
            ms[str(rank)] = addr
            m.mon_db = {"epoch": int(db.get("epoch", 0)) + 1,
                        "mons": ms}
        if not self._mutate(fn):
            return "commit failed", -11
        return json.dumps({"epoch": self.osdmap.mon_db.get("epoch", 0),
                           "mons": self.osdmap.mon_db.get("mons")}), 0

    def _cmd_mon_rm(self, cmd) -> tuple[str, int]:
        import json
        rank = int(cmd["id"])
        base = self._current_mon_db()
        if str(rank) not in base.get("mons", {}):
            return f"mon.{rank} not in monmap", -2
        if len(base.get("mons", {})) <= 1:
            return "refusing to remove the last monitor", -22

        def fn(m: OSDMap):
            db = m.mon_db or self._current_mon_db()
            ms = dict(db.get("mons", {}))
            if ms.pop(str(rank), None) is None:
                return False
            m.mon_db = {"epoch": int(db.get("epoch", 0)) + 1,
                        "mons": ms}
        if not self._mutate(fn):
            return "commit failed", -11
        return json.dumps({"epoch": self.osdmap.mon_db.get("epoch", 0),
                           "mons": self.osdmap.mon_db.get("mons")}), 0

    def _cmd_config_key(self, prefix: str, cmd) -> tuple[str, int]:
        """Arbitrary KV through paxos (mon/ConfigKeyService analog):
        free-form keys, unlike `config set`'s option registry — the mgr
        module store (module config, enabled-module list) lives here,
        which is what lets a promoted standby find it."""
        import json
        KV = "__kv__"
        if prefix == "config-key dump":
            return json.dumps(self.osdmap.config_db.get(KV, {})), 0
        key = str(cmd["key"])
        if prefix == "config-key get":
            sec = self.osdmap.config_db.get(KV, {})
            if key not in sec:
                return f"no such key {key!r}", -2
            return sec[key], 0
        if prefix == "config-key set":
            value = str(cmd.get("value", ""))

            def fn(m: OSDMap):
                sec = m.config_db.setdefault(KV, {})
                if sec.get(key) == value:
                    return False
                sec[key] = value
            if not self._mutate(fn):
                return "commit failed", -11
            return json.dumps({"epoch": self.osdmap.epoch}), 0
        # config-key rm
        def fn(m: OSDMap):
            sec = m.config_db.get(KV, {})
            if key not in sec:
                return False
            del sec[key]
            if not sec:
                m.config_db.pop(KV, None)
        if not self._mutate(fn):
            return "commit failed", -11
        return json.dumps({"epoch": self.osdmap.epoch}), 0

    def _cmd_config_get(self, cmd) -> tuple[str, int]:
        import json
        who = str(cmd.get("who", "global"))
        sec = self.osdmap.config_db.get(who, {})
        if "name" in cmd:
            name = str(cmd["name"])
            if name not in sec:
                return f"no config {name!r} for {who!r}", -2
            return str(sec[name]), 0
        return json.dumps(sec), 0

    def _cmd_config_rm(self, cmd) -> tuple[str, int]:
        import json
        who = str(cmd.get("who", "global"))
        name = str(cmd["name"])

        def fn(m: OSDMap):
            sec = m.config_db.get(who, {})
            if name not in sec:
                return False
            del sec[name]
            if not sec:
                m.config_db.pop(who, None)
        if not self._mutate(fn):
            return "commit failed", -11
        return json.dumps({"epoch": self.osdmap.epoch}), 0

    # -- health framework (mon/HealthMonitor.h:22 analog) ---------------------

    #: pg-stat reports older than this are ignored (the sender is dead
    #: or wedged; OSD_DOWN covers it)
    PG_STATS_STALE = 30.0

    def _health_report(self, detail: bool = False) -> dict:
        import time as _time
        m = self.osdmap
        checks = []

        def check(name, summary, details, **extra):
            c = {"check": name, "summary": summary, **extra}
            if detail:
                c["detail"] = details
            checks.append(c)

        down = [o for o in range(m.max_osd)
                if m.exists(o) and not m.is_up(o)]
        if down:
            check("OSD_DOWN", f"{len(down)} osds down",
                  [f"osd.{o} is down" for o in down], osds=down)
        out_osds = [o for o in range(m.max_osd)
                    if m.exists(o) and m.is_out(o)]
        if out_osds:
            check("OSD_OUT", f"{len(out_osds)} osds out",
                  [f"osd.{o} is out" for o in out_osds], osds=out_osds)
        # MON_DOWN: monmap members absent from the current quorum
        e = self.elector
        if e is not None and self.monmap:
            q = set(self.quorum())
            missing = [r for r in sorted(self.monmap) if r not in q]
            if missing and not e.electing:
                check("MON_DOWN",
                      f"{len(missing)} mons down",
                      [f"mon.{r} is not in quorum" for r in missing],
                      mons=missing)
        if e is None or e.electing:
            check("MON_QUORUM_AT_RISK", "election in progress",
                  [f"last quorum {self.quorum()}"],
                  last_quorum=self.quorum())
        # PG_DEGRADED from the MPGStats feed (primaries report)
        now = _time.time()
        with self._lock:
            stats = {o: st for o, st in self._pg_stats.items()
                     if now - st["received"] < self.PG_STATS_STALE
                     and m.exists(o) and m.is_up(o)}
        not_active = {}
        degraded_objects = 0
        for o, st in stats.items():
            degraded_objects += st["degraded_objects"]
            for state, n in st["states"].items():
                if state != "active" and n:
                    not_active[state] = not_active.get(state, 0) + n
        if not_active or degraded_objects:
            total = sum(not_active.values())
            check("PG_DEGRADED",
                  f"{total} pgs not active; "
                  f"{degraded_objects} objects degraded",
                  [f"{n} pgs {state}" for state, n in
                   sorted(not_active.items())]
                  + [f"osd.{o}: {st['degraded_objects']} degraded objects"
                     for o, st in sorted(stats.items())
                     if st["degraded_objects"]],
                  pgs_not_active=total,
                  degraded_objects=degraded_objects)
        return {"status": "HEALTH_OK" if not checks else "HEALTH_WARN",
                "checks": checks}

    def _cmd_pool_set(self, cmd) -> tuple[str, int]:
        pool_id = int(cmd["pool"])
        pool = self.osdmap.pools.get(pool_id)
        if pool is None:
            return f"pool {pool_id} does not exist", -2
        var = cmd["var"]
        # pg_num / pgp_num changes gate PG splits (OSDMonitor.cc pg_num
        # handling): pg_num may only grow (children split from parents on
        # the OSDs), and pgp_num — the placement seed modulus — may never
        # exceed pg_num (children must exist before they can move)
        if var == "pg_num":
            new = int(cmd["val"])
            if new < pool.pg_num:
                return (f"pg_num {new} < current {pool.pg_num}: "
                        "shrinking is not supported", -22)
        elif var == "pgp_num":
            new = int(cmd["val"])
            if new > pool.pg_num:
                return f"pgp_num {new} > pg_num {pool.pg_num}", -22
            if new < pool.pgp_num:
                return (f"pgp_num {new} < current {pool.pgp_num}: "
                        "shrinking is not supported", -22)

        def fn(m: OSDMap):
            p = m.pools[pool_id]
            # coerce by the field's current type (int/float/str knobs)
            cur = getattr(p, var)
            cast = type(cur) if cur is not None else int
            setattr(p, var,
                    cast(cmd["val"]) if cast is not bool
                    else cmd["val"] in ("1", "true", "True"))
        if not self._mutate(fn):
            return "commit failed", -11
        return json.dumps({"epoch": self.osdmap.epoch}), 0

    def _cmd_osd_weight(self, osd: int, weight: int) -> tuple[str, int]:
        if not (0 <= osd < self.osdmap.max_osd):
            return f"osd.{osd} does not exist", -2

        def fn(m: OSDMap):
            m.osd_weight[osd] = weight
        if not self._mutate(fn):
            return "commit failed", -11
        return f"osd.{osd} weight {weight:#x}", 0

    def _cmd_tree(self) -> dict:
        m = self.osdmap
        return {
            "epoch": m.epoch,
            "osds": [
                {"id": o, "up": m.is_up(o), "exists": m.exists(o),
                 "weight": m.osd_weight[o] / 0x10000}
                for o in range(m.max_osd)],
        }

    def status(self) -> dict:
        with self._lock:
            m = self.osdmap
            e = self.elector
            return {
                "epoch": m.epoch,
                "quorum": self.quorum(),
                "leader": e.leader if e else None,
                "num_osds": sum(1 for o in range(m.max_osd) if m.exists(o)),
                "num_up_osds": sum(1 for o in range(m.max_osd)
                                   if m.is_up(o)),
                "pools": {p: {"pg_num": pool.pg_num, "size": pool.size,
                              "type": pool.type}
                          for p, pool in m.pools.items()},
            }
