"""Daemon process entry point — the ceph-osd / ceph-mon `main()` analog.

Each daemon runs as its own OS process over the TCP messenger stack
(`python -m ceph_tpu.tools.daemon_main --role osd --id 2 ...`), the
reference's deployment model (src/ceph_osd.cc, src/ceph_mon.cc; spawned
by vstart.sh / qa/standalone/ceph-helpers.sh run_mon:437 run_osd:596).
The process stays up until SIGTERM/SIGINT; SIGKILL models crash-death
(the thrasher's kill mode) with the store surviving on disk.

The mon's listen address must be pre-agreed (it IS the cluster's
bootstrap identity), so `--addr` takes an explicit host:port; OSDs bind
an ephemeral port and advertise it through MOSDBoot as usual.
"""

from __future__ import annotations

import argparse
import signal
import sys
import os
import threading


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ceph-tpu-daemon")
    p.add_argument("--role", required=True,
                   choices=["mon", "osd", "mgr", "mds", "rgw"])
    p.add_argument("--id", type=int, default=0)
    p.add_argument("--addr", default="127.0.0.1:0",
                   help="bind address (mons need an agreed host:port)")
    p.add_argument("--mon-host", default="",
                   help="comma-separated mon addresses")
    p.add_argument("--monmap", default="",
                   help="mon only: comma-separated monmap (all mons)")
    p.add_argument("--ms-type", default="async",
                   help="messenger stack; 'ici' selects the cross-"
                        "process ici-wire stack (TCP control plane + "
                        "device transfer data plane)")
    p.add_argument("--jax-cpu-devices", type=int, default=0,
                   help="force the cpu platform with N local devices "
                        "BEFORE jax initializes (the virtual-mesh test "
                        "tier; production uses the real backend)")
    p.add_argument("--store-type", default="filestore")
    p.add_argument("--store-path", default="")
    p.add_argument("--auth-key", default="")
    p.add_argument("--heartbeats", action="store_true")
    p.add_argument("--metadata-pool", type=int, default=1)
    p.add_argument("--data-pool", type=int, default=2)
    p.add_argument("--rgw-pool", type=int, default=1,
                   help="rgw only: backing pool id")
    p.add_argument("--rgw-access", default="",
                   help="rgw only: explicit S3 access key (with "
                        "--rgw-secret; else derived from --auth-key)")
    p.add_argument("--rgw-secret", default="")
    p.add_argument("--rgw-port", type=int, default=0,
                   help="rgw only: HTTP listen port (0 = ephemeral; "
                        "the bound address prints on the ready line)")
    args = p.parse_args(argv)
    auth_key = args.auth_key.encode() if args.auth_key else None
    if args.jax_cpu_devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count="
            f"{args.jax_cpu_devices}").strip()
        import jax
        jax.config.update("jax_platforms", "cpu")
    ms_type = "ici-wire" if args.ms_type == "ici" else args.ms_type

    if args.role == "mon":
        from ceph_tpu.mon import Monitor
        d = Monitor(mon_id=args.id, ms_type="async", addr=args.addr,
                    store_path=args.store_path or None, auth_key=auth_key)
        d.init(monmap=[])
        monmap = (args.monmap or args.addr).split(",")
        if args.id >= len(monmap):
            print(f"error: --id {args.id} outside the {len(monmap)}-entry "
                  "monmap (pass --monmap with every mon's address)",
                  file=sys.stderr)
            return 2
        # substitute my own resolved addr (port 0 binds resolve late)
        monmap[args.id] = d.addr
        d.set_monmap(monmap)
    elif args.role == "osd":
        from ceph_tpu.osd.daemon import OSDDaemon
        d = OSDDaemon(args.id, args.mon_host, store_type=args.store_type,
                      store_path=args.store_path, ms_type=ms_type,
                      addr=args.addr, heartbeats=args.heartbeats,
                      auth_key=auth_key)
        d.init()
    elif args.role == "mgr":
        from ceph_tpu.mgr import MgrDaemon
        d = MgrDaemon(args.mon_host, ms_type="async", addr=args.addr,
                      auth_key=auth_key)
        d.init()
    elif args.role == "rgw":
        # the radosgw daemon shell: a RadosClient into the backing
        # pool + the S3 REST frontend; S3 credentials derive from the
        # cluster key (provision_from_cephx), so every rgw in the
        # cluster serves the same access/secret pair
        from ceph_tpu.client import RadosClient
        from ceph_tpu.rgw_rest import RgwRestServer
        if not auth_key and not (args.rgw_access and args.rgw_secret):
            print("error: an rgw needs credentials — pass --auth-key "
                  "(S3 keys derive from it) or --rgw-access/"
                  "--rgw-secret; an empty key table would 403 every "
                  "request", file=sys.stderr)
            return 2
        rc = RadosClient(args.mon_host, ms_type="async",
                         auth_key=auth_key)
        rc.connect()
        d = RgwRestServer(rc.open_ioctx(args.rgw_pool),
                          addr=f"127.0.0.1:{args.rgw_port}")
        if args.rgw_access and args.rgw_secret:
            d.add_key(args.rgw_access, args.rgw_secret)
        if auth_key:
            d.provision_from_cephx(auth_key)
        d.start()
    else:
        from ceph_tpu.mds import MDSDaemon
        d = MDSDaemon(args.mon_host, args.metadata_pool, args.data_pool,
                      ms_type="async", addr=args.addr, auth_key=auth_key)
        d.init()

    stop = threading.Event()

    def on_signal(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)
    # readiness marker for the spawning harness (rgw appends its bound
    # HTTP address — the operator's endpoint)
    extra_info = f" {d.addr}" if args.role == "rgw" else ""
    sys.stdout.write(f"ready {args.role}.{args.id}{extra_info}\n")
    sys.stdout.flush()
    stop.wait()
    d.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
