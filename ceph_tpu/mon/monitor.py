"""Monitor daemon.

Map mutations follow the reference's pending_inc pattern (OSDMonitor): mutate a
pending copy, commit it as epoch+1 to the versioned store, then broadcast to
subscribers.  Failure handling mirrors check_failure (mon/OSDMonitor.cc:2537):
an osd is marked down once `mon_osd_min_down_reporters` distinct reporters
have filed MOSDFailure against it.
"""

from __future__ import annotations

import threading
import time

from ceph_tpu.common.context import CephTpuContext
from ceph_tpu.crush.builder import add_simple_rule, make_bucket
from ceph_tpu.crush.types import CRUSH_BUCKET_STRAW2, CrushMap
from ceph_tpu.messages import (
    MMonCommand, MMonCommandAck, MOSDFailure, MOSDMapMsg)
from ceph_tpu.messages.osd_msgs import MOSDPing
from ceph_tpu.msg.message import Message, register_message
from ceph_tpu.msg.encoding import Encoder, Decoder
from ceph_tpu.msg.messenger import (
    ConnectionPolicy, Dispatcher, EntityName, Messenger)
from ceph_tpu.objectstore.kv import LogDB, MemDB
from ceph_tpu.osd.map_codec import decode_osdmap, encode_osdmap
from ceph_tpu.osd.osdmap import OSDMap, PGPool, POOL_TYPE_ERASURE


@register_message
class MOSDBoot(Message):
    """osd -> mon: I'm up at this address (messages/MOSDBoot.h analog)."""

    TYPE = 71

    def __init__(self, osd_id: int = 0, addr: str = ""):
        super().__init__()
        self.osd_id = osd_id
        self.addr = addr

    def encode_payload(self, enc: Encoder):
        enc.versioned(1, 1, lambda e: (e.s32(self.osd_id), e.str(self.addr)))

    def decode_payload(self, dec: Decoder, version: int):
        def body(d, v):
            self.osd_id = d.s32()
            self.addr = d.str()
        dec.versioned(1, body)


@register_message
class MMonSubscribe(Message):
    """client/osd -> mon: send me map updates (MMonSubscribe analog)."""

    TYPE = 15

    def __init__(self, name: str = "", addr: str = ""):
        super().__init__()
        self.name = name
        self.addr = addr

    def encode_payload(self, enc: Encoder):
        enc.versioned(1, 1, lambda e: (e.str(self.name), e.str(self.addr)))

    def decode_payload(self, dec: Decoder, version: int):
        def body(d, v):
            self.name = d.str()
            self.addr = d.str()
        dec.versioned(1, body)


class Monitor(Dispatcher):
    def __init__(self, ctx: CephTpuContext | None = None, mon_id: int = 0,
                 store_path: str | None = None, ms_type: str = "async",
                 addr: str = "127.0.0.1:0"):
        self.ctx = ctx or CephTpuContext(f"mon.{mon_id}")
        self.mon_id = mon_id
        self.name = EntityName("mon", mon_id)
        self.db = LogDB(store_path) if store_path else MemDB()
        self.osdmap = OSDMap()
        self._lock = threading.RLock()
        #: failure reports: failed_osd -> {reporter: report_time}
        self._failure_reports: dict[int, dict[int, float]] = {}
        #: subscriber name -> (addr, entity)
        self._subs: dict[str, tuple[str, EntityName]] = {}
        self._osd_addrs: dict[int, str] = {}
        self.msgr = Messenger.create(self.name, ms_type)
        self.msgr.set_policy("client", ConnectionPolicy.lossy_client())
        self.msgr.set_policy("osd", ConnectionPolicy.stateful_server())
        self.msgr.add_dispatcher_tail(self)
        self._addr = addr
        self.ctx.admin.register_command(
            "mon status", lambda **kw: self.status(), "cluster status")

    # -- lifecycle ------------------------------------------------------------

    def init(self) -> None:
        if isinstance(self.db, LogDB):
            self.db.open()
        self._load_or_bootstrap()
        self.msgr.bind(self._addr)
        self.msgr.start()

    def shutdown(self) -> None:
        self.msgr.shutdown()
        if isinstance(self.db, LogDB):
            self.db.close()

    @property
    def addr(self) -> str:
        return self.msgr.my_addr

    def _load_or_bootstrap(self) -> None:
        last = self.db.get("osdmap", "last_committed")
        if last is not None:
            blob = self.db.get("osdmap", f"full_{int(last.decode())}")
            self.osdmap = decode_osdmap(blob)
            return
        # bootstrap: empty map with a root bucket and a default rule
        m = OSDMap(epoch=0, crush=CrushMap())
        m.crush.add_bucket(
            make_bucket(-1, CRUSH_BUCKET_STRAW2, 2, [], []))
        self.osdmap = m
        self._commit(m)  # epoch 1

    # -- the pending_inc commit path ------------------------------------------

    def _commit(self, newmap: OSDMap) -> None:
        """Versioned commit (Paxos store layout: one value per version)."""
        with self._lock:
            newmap.epoch += 1
            blob = encode_osdmap(newmap)
            t = self.db.get_transaction()
            t.set("osdmap", f"full_{newmap.epoch}", blob)
            t.set("osdmap", "last_committed", str(newmap.epoch).encode())
            self.db.submit_transaction(t)
            self.osdmap = newmap
            subs = list(self._subs.values())
        for addr, entity in subs:
            con = self.msgr.connect_to(addr, entity)
            con.send_message(MOSDMapMsg(epoch=newmap.epoch, map_blob=blob))

    # -- dispatch -------------------------------------------------------------

    def ms_dispatch(self, msg) -> bool:
        if isinstance(msg, MMonCommand):
            out, result = self.handle_command(msg.cmd)
            msg.connection.send_message(
                MMonCommandAck(tid=msg.tid, result=result, output=out))
            return True
        if isinstance(msg, MOSDBoot):
            self._handle_boot(msg)
            return True
        if isinstance(msg, MMonSubscribe):
            with self._lock:
                entity = (msg.connection.peer_name
                          or EntityName.parse(msg.name))
                self._subs[msg.name] = (msg.addr, entity)
                epoch, blob = self.osdmap.epoch, encode_osdmap(self.osdmap)
            con = self.msgr.connect_to(msg.addr, entity)
            con.send_message(MOSDMapMsg(epoch=epoch, map_blob=blob))
            return True
        if isinstance(msg, MOSDFailure):
            self._handle_failure(msg)
            return True
        if isinstance(msg, MOSDPing):
            return True  # mon liveness probe, nothing to do
        return False

    # -- osd lifecycle --------------------------------------------------------

    def _handle_boot(self, msg: MOSDBoot) -> None:
        with self._lock:
            m = self.osdmap
            osd = msg.osd_id
            if osd >= m.max_osd:
                m.set_max_osd(osd + 1)
            newly_known = not m.exists(osd)
            m.mark_up(osd, weight=m.osd_weight[osd] or 0x10000)
            m.osd_addrs[osd] = msg.addr
            if newly_known:
                self._crush_add_osd(m, osd, 0x10000)
            self._osd_addrs[osd] = msg.addr
            self._failure_reports.pop(osd, None)
            self._commit(m)

    def _crush_add_osd(self, m: OSDMap, osd: int, weight: int) -> None:
        root = m.crush.bucket(-1)
        root.items.append(osd)
        root.item_weights.append(weight)
        root.weight += weight
        m.crush.max_devices = max(m.crush.max_devices, osd + 1)

    def _handle_failure(self, msg: MOSDFailure) -> None:
        need = int(self.ctx.conf.get("mon_osd_min_down_reporters"))
        with self._lock:
            if not self.osdmap.is_up(msg.failed_osd):
                return
            reports = self._failure_reports.setdefault(msg.failed_osd, {})
            reports[msg.reporter] = time.time()
            if len(reports) < need:
                return
            # quorum of reporters: mark down (check_failure analog)
            m = self.osdmap
            m.mark_down(msg.failed_osd)
            self._failure_reports.pop(msg.failed_osd, None)
            self._commit(m)

    # -- command table (MonCommands.h analog) ---------------------------------

    def handle_command(self, cmd: dict) -> tuple[str, int]:
        import json
        prefix = cmd.get("prefix", "")
        try:
            if prefix == "status":
                return json.dumps(self.status()), 0
            if prefix == "osd pool create":
                return self._cmd_pool_create(cmd)
            if prefix == "osd pool set":
                return self._cmd_pool_set(cmd)
            if prefix == "osd tree":
                return json.dumps(self._cmd_tree()), 0
            if prefix == "osd out":
                return self._cmd_osd_weight(int(cmd["id"]), 0)
            if prefix == "osd in":
                return self._cmd_osd_weight(int(cmd["id"]), 0x10000)
            if prefix == "osd down":
                with self._lock:
                    m = self.osdmap
                    osd = int(cmd["id"])
                    if not m.exists(osd):
                        return f"osd.{osd} does not exist", -2
                    m.mark_down(osd)
                    self._commit(m)
                return "marked down", 0
            if prefix == "osd getmap":
                return json.dumps({"epoch": self.osdmap.epoch}), 0
            return f"unknown command {prefix!r}", -22
        except (KeyError, ValueError, IndexError) as e:
            return f"command failed: {e}", -22

    def _cmd_pool_create(self, cmd) -> tuple[str, int]:
        with self._lock:
            m = self.osdmap
            pool_id = max(m.pools, default=0) + 1
            pg_num = int(cmd.get("pg_num",
                                 self.ctx.conf.get("osd_pool_default_pg_num")))
            ptype = (POOL_TYPE_ERASURE if cmd.get("pool_type") == "erasure"
                     else 1)
            profile = {}
            if ptype == POOL_TYPE_ERASURE:
                k = int(cmd.get("k", 4))
                ec_m = int(cmd.get("m", 2))
                profile = {"plugin": cmd.get("plugin", "jerasure"),
                           "technique": cmd.get("technique", "reed_sol_van"),
                           "k": str(k), "m": str(ec_m)}
                rule = add_simple_rule(m.crush, -1, 0, "indep")
                size = k + ec_m
            else:
                rule = add_simple_rule(m.crush, -1, 0, "firstn")
                size = int(cmd.get("size",
                                   self.ctx.conf.get("osd_pool_default_size")))
            m.pools[pool_id] = PGPool(
                pool_id=pool_id, type=ptype, size=size,
                min_size=max(1, size - 1) if ptype != POOL_TYPE_ERASURE
                else int(cmd.get("k", 4)),
                crush_rule=rule, pg_num=pg_num, ec_profile=profile)
            self._commit(m)
            return f"pool {pool_id} created", 0

    def _cmd_pool_set(self, cmd) -> tuple[str, int]:
        with self._lock:
            m = self.osdmap
            pool = m.pools[int(cmd["pool"])]
            setattr(pool, cmd["var"], int(cmd["val"]))
            self._commit(m)
            return "set", 0

    def _cmd_osd_weight(self, osd: int, weight: int) -> tuple[str, int]:
        with self._lock:
            m = self.osdmap
            if not (0 <= osd < m.max_osd):
                return f"osd.{osd} does not exist", -2
            m.osd_weight[osd] = weight
            self._commit(m)
            return f"osd.{osd} weight {weight:#x}", 0

    def _cmd_tree(self) -> dict:
        m = self.osdmap
        return {
            "epoch": m.epoch,
            "osds": [
                {"id": o, "up": m.is_up(o), "exists": m.exists(o),
                 "weight": m.osd_weight[o] / 0x10000}
                for o in range(m.max_osd)],
        }

    def status(self) -> dict:
        with self._lock:
            m = self.osdmap
            return {
                "epoch": m.epoch,
                "num_osds": sum(1 for o in range(m.max_osd) if m.exists(o)),
                "num_up_osds": sum(1 for o in range(m.max_osd)
                                   if m.is_up(o)),
                "pools": {p: {"pg_num": pool.pg_num, "size": pool.size,
                              "type": pool.type}
                          for p, pool in m.pools.items()},
            }
