"""Wire encoding for CrushMap and OSDMap (OSDMap::encode / CrushWrapper::encode
analog) using the versioned binary codec, so maps distribute over MOSDMapMsg
and persist in the mon store exactly like any other wire struct."""

from __future__ import annotations

from ceph_tpu.crush.types import (
    Bucket, ChooseArg, CrushMap, Rule, RuleStep, Tunables)
from ceph_tpu.msg.encoding import Decoder, Encoder

from .osdmap import OSDMap, OSDXInfo, PGPool


# -- crush ------------------------------------------------------------------

def encode_crush(m: CrushMap, enc: Encoder) -> None:
    def body(e: Encoder):
        t = m.tunables
        for v in (t.choose_local_tries, t.choose_local_fallback_tries,
                  t.choose_total_tries, t.chooseleaf_descend_once,
                  t.chooseleaf_vary_r, t.chooseleaf_stable,
                  t.straw_calc_version):
            e.u32(v)
        e.u32(m.max_devices)

        def enc_bucket(e2: Encoder, b: Bucket | None):
            if b is None:
                e2.u8(0)
                return
            e2.u8(1)
            e2.s32(b.id).u32(b.type).u8(b.alg).u8(b.hash).u32(b.weight)
            e2.list(b.items, lambda e3, v: e3.s32(v))
            e2.list(b.item_weights, lambda e3, v: e3.u32(v))
            e2.u32(b.item_weight)
            e2.list(b.sum_weights, lambda e3, v: e3.u32(v))
            e2.list(b.straws, lambda e3, v: e3.u64(v))
            e2.list(b.node_weights, lambda e3, v: e3.u32(v))

        e.list(m.buckets, enc_bucket)

        def enc_rule(e2: Encoder, r: Rule | None):
            if r is None:
                e2.u8(0)
                return
            e2.u8(1)
            e2.u32(r.ruleset).u32(r.type).u32(r.min_size).u32(r.max_size)
            e2.list(r.steps, lambda e3, s: (e3.u32(s.op), e3.s32(s.arg1),
                                            e3.s32(s.arg2)))

        e.list(m.rules, enc_rule)

        def enc_choose_args(e2: Encoder, d: dict):
            def enc_arg(e3: Encoder, a: ChooseArg):
                if a.ids is None:
                    e3.u8(0)
                else:
                    e3.u8(1)
                    e3.list(a.ids, lambda e4, v: e4.s32(v))
                if a.weight_set is None:
                    e3.u8(0)
                else:
                    e3.u8(1)
                    e3.list(a.weight_set,
                            lambda e4, ws: e4.list(ws, lambda e5, v: e5.u32(v)))

            e2.map(d, lambda e3, k: e3.u32(k), enc_arg)

        # choose_args ids are s64 in the reference (CrushWrapper.h:72);
        # v1 encoded them as strings, hence the struct version bump
        e.map(m.choose_args, lambda e2, k: e2.s64(int(k)), enc_choose_args)
        # v3: device-class shadow table (CrushWrapper class_bucket)
        e.map(m.class_bucket,
              lambda e2, k: (e2.s32(k[0]), e2.str(k[1])),
              lambda e2, v: e2.s32(v))

    enc.versioned(3, 1, body)


def decode_crush(dec: Decoder) -> CrushMap:
    def body(d: Decoder, version: int) -> CrushMap:
        t = Tunables(
            choose_local_tries=d.u32(),
            choose_local_fallback_tries=d.u32(),
            choose_total_tries=d.u32(),
            chooseleaf_descend_once=d.u32(),
            chooseleaf_vary_r=d.u32(),
            chooseleaf_stable=d.u32(),
            straw_calc_version=d.u32(),
        )
        max_devices = d.u32()

        def dec_bucket(d2: Decoder) -> Bucket | None:
            if not d2.u8():
                return None
            b = Bucket(id=d2.s32(), type=d2.u32(), alg=d2.u8(),
                       hash=d2.u8(), weight=d2.u32())
            b.items = d2.list(lambda d3: d3.s32())
            b.item_weights = d2.list(lambda d3: d3.u32())
            b.item_weight = d2.u32()
            b.sum_weights = d2.list(lambda d3: d3.u32())
            b.straws = d2.list(lambda d3: d3.u64())
            b.node_weights = d2.list(lambda d3: d3.u32())
            return b

        buckets = d.list(dec_bucket)

        def dec_rule(d2: Decoder) -> Rule | None:
            if not d2.u8():
                return None
            r = Rule(ruleset=d2.u32(), type=d2.u32(), min_size=d2.u32(),
                     max_size=d2.u32())
            r.steps = d2.list(
                lambda d3: RuleStep(op=d3.u32(), arg1=d3.s32(), arg2=d3.s32()))
            return r

        rules = d.list(dec_rule)

        def dec_choose_args(d2: Decoder) -> dict:
            def dec_arg(d3: Decoder) -> ChooseArg:
                ids = d3.list(lambda d4: d4.s32()) if d3.u8() else None
                ws = (d3.list(lambda d4: d4.list(lambda d5: d5.u32()))
                      if d3.u8() else None)
                return ChooseArg(ids=ids, weight_set=ws)

            return d2.map(lambda d3: d3.u32(), dec_arg)

        if version >= 2:
            choose_args = d.map(lambda d2: d2.s64(), dec_choose_args)
        else:  # v1 stores persisted before the s64 key change
            raw = d.map(lambda d2: d2.str(), dec_choose_args)
            choose_args = {
                int(k) if k.lstrip("-").isdigit() else k: v
                for k, v in raw.items()}
        class_bucket = {}
        if version >= 3:
            class_bucket = d.map(lambda d2: (d2.s32(), d2.str()),
                                 lambda d2: d2.s32())
        m = CrushMap(buckets=buckets, rules=rules, max_devices=max_devices,
                     tunables=t, choose_args=choose_args,
                     class_bucket=class_bucket)
        return m

    return dec.versioned(3, body)


# -- osdmap -----------------------------------------------------------------

# ONE pool/pgid codec serves the full map AND the incremental: a field
# added to one but not the other would make delta-built maps silently
# diverge from backfilled ones.

def _enc_pool(e2: Encoder, p: PGPool) -> None:
    e2.s64(p.pool_id).u8(p.type).u32(p.size).u32(p.min_size)
    e2.u32(p.crush_rule).u32(p.pg_num).u32(p.pgp_num)
    e2.map(p.ec_profile, lambda e3, k: e3.str(k),
           lambda e3, v: e3.str(str(v)))
    e2.u64(p.snap_seq)
    e2.map(p.snaps, lambda e3, k: e3.u64(k), lambda e3, v: e3.str(v))
    # v5: cache-tier fields (pg_pool_t tier_of/read_tier/...)
    e2.s64(p.tier_of).s64(p.read_tier).s64(p.write_tier)
    e2.str(p.cache_mode)
    e2.u64(p.target_max_objects)
    e2.f64(p.cache_min_flush_age)
    # v13: per-pool objectstore compression (pg_pool_t compression opts)
    e2.str(p.compression_mode)
    e2.str(p.compression_algorithm)


def _dec_pool(d2: Decoder, version: int = 999) -> PGPool:
    p = PGPool(pool_id=d2.s64(), type=d2.u8(), size=d2.u32(),
               min_size=d2.u32(), crush_rule=d2.u32(),
               pg_num=d2.u32(), pgp_num=d2.u32(),
               ec_profile=d2.map(lambda d3: d3.str(),
                                 lambda d3: d3.str()))
    if version >= 2:
        p.snap_seq = d2.u64()
        p.snaps = d2.map(lambda d3: d3.u64(), lambda d3: d3.str())
    if version >= 5:
        p.tier_of = d2.s64()
        p.read_tier = d2.s64()
        p.write_tier = d2.s64()
        p.cache_mode = d2.str()
        p.target_max_objects = d2.u64()
        p.cache_min_flush_age = d2.f64()
    if version >= 13:
        p.compression_mode = d2.str()
        p.compression_algorithm = d2.str()
    return p


def _enc_pgid(e2: Encoder, k) -> None:
    e2.s64(k[0])
    e2.u32(k[1])


def _dec_pgid(d2: Decoder):
    return (d2.s64(), d2.u32())


def encode_osdmap(m: OSDMap, *, with_auth: bool = False) -> bytes:
    """with_auth gates the AuthMonitor key table: ONLY the mon-internal
    paxos value / mon store carries it (reference: auth key material
    lives in the AuthMonitor's own paxos service, never in the OSDMap
    clients subscribe to).  Every broadcast path — MOSDMapMsg fan-out,
    subscription replies, OSD maybe_share_map — uses the default
    stripped form, so no client ever sees another entity's secret."""
    enc = Encoder()

    def body(e: Encoder):
        e.u32(m.epoch).u32(m.max_osd)
        encode_crush(m.crush, e)
        e.list(m.osd_state, lambda e2, v: e2.u8(v))
        e.list(m.osd_weight, lambda e2, v: e2.u32(v))
        e.list(m.osd_primary_affinity, lambda e2, v: e2.u32(v))
        e.list(m.osd_addrs, lambda e2, v: e2.str(v))

        e.map(m.pools, lambda e2, k: e2.s64(k), _enc_pool)

        e.map(m.pg_upmap, _enc_pgid,
              lambda e2, v: e2.list(v, lambda e3, o: e3.s32(o)))
        e.map(m.pg_upmap_items, _enc_pgid,
              lambda e2, v: e2.list(v, lambda e3, p: (e3.s32(p[0]),
                                                      e3.s32(p[1]))))
        e.map(m.pg_temp, _enc_pgid,
              lambda e2, v: e2.list(v, lambda e3, o: e3.s32(o)))
        e.map(m.primary_temp, _enc_pgid, lambda e2, v: e2.s32(v))
        # v3: CRUSH name tables ride the map (the reference's binary
        # crush carries type/name/rule maps; CrushWrapper name_map)
        import json as _json
        e.bytes(_json.dumps(m.crush_names).encode()
                if m.crush_names else b"")
        # v4: osd_xinfo laggy history (osd_xinfo_t vector)
        e.list(m.osd_xinfo, lambda e2, x: (
            e2.f64(x.down_stamp), e2.f64(x.laggy_probability),
            e2.f64(x.laggy_interval)))
        # v6: central config-db (ConfigMonitor key space)
        e.bytes(_json.dumps(m.config_db).encode() if m.config_db
                else b"")
        # v7: auth key table (AuthMonitor key space) — mon-internal only
        e.bytes(_json.dumps(m.auth_db).encode()
                if (with_auth and m.auth_db) else b"")
        # v8: FSMap (MDSMonitor FSMap) — public, clients route by it
        e.bytes(_json.dumps(m.fs_db).encode() if m.fs_db else b"")
        # v9: active-mgr record (MgrMap) — OSDs/clients re-target by it
        e.bytes(_json.dumps(m.mgr_db).encode() if m.mgr_db else b"")
        # v10: monitor membership (MonMap) — mon add/rm rides paxos
        e.bytes(_json.dumps(m.mon_db).encode() if m.mon_db else b"")
        # v11: per-tenant QoS profiles (dmclock ClientInfo distribution,
        # `ceph qos set/rm/ls`) — every OSD schedules from the same db
        e.bytes(_json.dumps(m.qos_db).encode() if m.qos_db else b"")
        # v12: per-tenant SLO objectives (`ceph qos slo set/rm/ls`) —
        # the mgr slo module's burn-rate engine reads them off the map
        e.bytes(_json.dumps(m.slo_db).encode() if m.slo_db else b"")

    enc.versioned(13, 1, body)
    return enc.tobytes()


# -- incremental osdmap (OSDMap::Incremental, src/osd/OSDMap.h:353) ---------
#
# The mon publishes DELTAS for normal churn: an incremental carries only
# what changed between epoch-1 and epoch, daemons apply them in sequence,
# and full maps ship only to gapped/backfilling subscribers.  A 10k-OSD
# map is ~hundreds of KB; marking one osd down is tens of bytes.
#
# Layout choice vs the reference: pg_temp/primary_temp/upmap changes
# carry the full new value per KEY (remove = empty), pools ship whole
# per changed pool id, and a changed CRUSH ships whole (as in the
# reference — crush deltas aren't worth the complexity).  The small
# JSON side-tables (config/fs/crush-names) ship whole when changed.

_SENTINEL = object()


def diff_osdmap(old: OSDMap, new: OSDMap) -> dict:
    """Compute the incremental old -> new (epochs must be adjacent or
    at least ordered; the inc is tagged with new.epoch)."""
    import json as _json
    inc: dict = {"epoch": new.epoch}
    if new.max_osd != old.max_osd:
        inc["max_osd"] = new.max_osd
    for field_, name in (("osd_state", "state"),
                        ("osd_weight", "weight"),
                        ("osd_primary_affinity", "affinity"),
                        ("osd_addrs", "addrs")):
        ov, nv = getattr(old, field_), getattr(new, field_)
        changes = {i: nv[i] for i in range(len(nv))
                   if i >= len(ov) or ov[i] != nv[i]}
        if changes:
            inc[name] = changes
    pools = {}
    for pid, p in new.pools.items():
        if pid not in old.pools or old.pools[pid] != p:
            pools[pid] = p
    gone = [pid for pid in old.pools if pid not in new.pools]
    if pools:
        inc["pools"] = pools
    if gone:
        inc["old_pools"] = gone
    for attr in ("pg_temp", "primary_temp", "pg_upmap",
                 "pg_upmap_items"):
        ov, nv = getattr(old, attr), getattr(new, attr)
        changes = {k: v for k, v in nv.items()
                   if ov.get(k, _SENTINEL) != v}
        removes = [k for k in ov if k not in nv]
        if changes or removes:
            inc[attr] = (changes, removes)
    if old.osd_xinfo != new.osd_xinfo:
        xch = {i: new.osd_xinfo[i] for i in range(len(new.osd_xinfo))
               if i >= len(old.osd_xinfo)
               or old.osd_xinfo[i] != new.osd_xinfo[i]}
        if xch:
            inc["xinfo"] = xch
    # whole-structure deltas: compare structurally (dataclass equality)
    # first — encoding runs only when the crush map actually changed, not
    # on every epoch commit under the mon lock
    if old.crush is not new.crush and old.crush != new.crush:
        enc_new = Encoder()
        encode_crush(new.crush, enc_new)
        inc["crush"] = enc_new.tobytes()
    for attr in ("config_db", "fs_db", "crush_names",
                 "mgr_db", "mon_db", "qos_db", "slo_db"):
        if getattr(old, attr) != getattr(new, attr):
            inc[attr] = _json.dumps(getattr(new, attr))
    return inc


def apply_incremental(m: OSDMap, inc: dict) -> None:
    """Apply one decoded incremental IN PLACE (OSD::handle_osd_map's
    apply_incremental).  inc['epoch'] must be m.epoch + 1."""
    import json as _json
    if inc["epoch"] != m.epoch + 1:
        raise ValueError(
            f"incremental {inc['epoch']} onto map {m.epoch}")
    if "max_osd" in inc:
        m.set_max_osd(inc["max_osd"])
    for name, attr in (("state", "osd_state"), ("weight", "osd_weight"),
                       ("affinity", "osd_primary_affinity"),
                       ("addrs", "osd_addrs")):
        vec = getattr(m, attr)
        for i, v in inc.get(name, {}).items():
            while len(vec) <= i:
                vec.append(0 if attr != "osd_addrs" else "")
            vec[i] = v
    for pid, p in inc.get("pools", {}).items():
        m.pools[pid] = p
    for pid in inc.get("old_pools", []):
        m.pools.pop(pid, None)
    for attr in ("pg_temp", "primary_temp", "pg_upmap",
                 "pg_upmap_items"):
        if attr in inc:
            changes, removes = inc[attr]
            d = getattr(m, attr)
            d.update(changes)
            for k in removes:
                d.pop(k, None)
    for i, x in inc.get("xinfo", {}).items():
        while len(m.osd_xinfo) <= i:
            m.osd_xinfo.append(OSDXInfo())
        m.osd_xinfo[i] = x
    if "crush" in inc:
        m.crush = decode_crush(Decoder(inc["crush"]))
    for attr in ("config_db", "fs_db", "crush_names",
                 "mgr_db", "mon_db", "qos_db", "slo_db"):
        if attr in inc:
            setattr(m, attr, _json.loads(inc[attr]))
    m.epoch = inc["epoch"]


def encode_incremental(inc: dict) -> bytes:
    enc = Encoder()

    def body(e: Encoder):
        e.u32(inc["epoch"])
        e.s32(inc.get("max_osd", -1))
        for name in ("state", "weight", "affinity"):
            e.map(inc.get(name, {}), lambda e2, k: e2.u32(k),
                  lambda e2, v: e2.u64(v))
        e.map(inc.get("addrs", {}), lambda e2, k: e2.u32(k),
              lambda e2, v: e2.str(v))
        e.map(inc.get("pools", {}), lambda e2, k: e2.s64(k), _enc_pool)
        e.list(inc.get("old_pools", []), lambda e2, v: e2.s64(v))
        for attr, enc_v in (
                ("pg_temp", lambda e2, v: e2.list(
                    v, lambda e3, o: e3.s32(o))),
                ("primary_temp", lambda e2, v: e2.s32(v)),
                ("pg_upmap", lambda e2, v: e2.list(
                    v, lambda e3, o: e3.s32(o))),
                ("pg_upmap_items", lambda e2, v: e2.list(
                    v, lambda e3, p: (e3.s32(p[0]), e3.s32(p[1]))))):
            changes, removes = inc.get(attr, ({}, []))
            e.map(changes, _enc_pgid, enc_v)
            e.list(removes, _enc_pgid)
        e.map(inc.get("xinfo", {}), lambda e2, k: e2.u32(k),
              lambda e2, x: (e2.f64(x.down_stamp),
                             e2.f64(x.laggy_probability),
                             e2.f64(x.laggy_interval)))
        e.bytes(inc.get("crush", b""))
        for attr in ("config_db", "fs_db", "crush_names",
                     "mgr_db", "mon_db", "qos_db",
                     "slo_db"):  # mon_db: v2; qos: v3; slo: v4
            has = attr in inc
            e.u8(1 if has else 0)
            if has:
                e.bytes(inc[attr].encode())

    enc.versioned(4, 1, body)
    return enc.tobytes()


def decode_incremental(data: bytes) -> dict:
    dec = Decoder(data)

    def body(d: Decoder, version: int) -> dict:
        inc: dict = {"epoch": d.u32()}
        mo = d.s32()
        if mo >= 0:
            inc["max_osd"] = mo
        for name in ("state", "weight", "affinity"):
            ch = d.map(lambda d2: d2.u32(), lambda d2: d2.u64())
            if ch:
                inc[name] = ch
        ch = d.map(lambda d2: d2.u32(), lambda d2: d2.str())
        if ch:
            inc["addrs"] = ch
        pools = d.map(lambda d2: d2.s64(), _dec_pool)
        if pools:
            inc["pools"] = pools
        old_pools = d.list(lambda d2: d2.s64())
        if old_pools:
            inc["old_pools"] = old_pools
        for attr, dec_v in (
                ("pg_temp", lambda d2: d2.list(lambda d3: d3.s32())),
                ("primary_temp", lambda d2: d2.s32()),
                ("pg_upmap", lambda d2: d2.list(lambda d3: d3.s32())),
                ("pg_upmap_items", lambda d2: d2.list(
                    lambda d3: (d3.s32(), d3.s32())))):
            changes = d.map(_dec_pgid, dec_v)
            removes = d.list(_dec_pgid)
            if changes or removes:
                inc[attr] = (changes, removes)
        xinfo = d.map(lambda d2: d2.u32(),
                      lambda d2: OSDXInfo(down_stamp=d2.f64(),
                                          laggy_probability=d2.f64(),
                                          laggy_interval=d2.f64()))
        if xinfo:
            inc["xinfo"] = xinfo
        crush = d.bytes()
        if crush:
            inc["crush"] = crush
        side = ["config_db", "fs_db", "crush_names", "mgr_db"]
        if version >= 2:
            side.append("mon_db")
        if version >= 3:
            side.append("qos_db")
        if version >= 4:
            side.append("slo_db")
        for attr in side:
            if d.u8():
                inc[attr] = d.bytes().decode()
        return inc

    return dec.versioned(1, body)


def advance_map(cur: OSDMap, msg) -> tuple[OSDMap | None, bool]:
    """Apply an MOSDMapMsg (full or incremental) to the current map:
    returns (new map | None, gapped).  gapped=True means the deltas
    don't connect to our epoch — the caller re-subscribes with its
    epoch and the mon backfills (OSD::handle_osd_map's request_full)."""
    if msg.map_blob:
        new = decode_osdmap(msg.map_blob)
        return (new, False) if new.epoch > cur.epoch else (None, False)
    if not msg.incs:
        return None, False
    incs = [(e, b) for e, b in msg.incs if e > cur.epoch]
    if not incs:
        return None, False
    if incs[0][0] != cur.epoch + 1 or cur.epoch == 0:
        return None, True
    new = cur.copy()
    for _e, b in incs:
        apply_incremental(new, decode_incremental(b))
    return new, False


def decode_osdmap(data: bytes) -> OSDMap:
    dec = Decoder(data)

    def body(d: Decoder, version: int) -> OSDMap:
        epoch = d.u32()
        max_osd = d.u32()
        crush = decode_crush(d)
        osd_state = d.list(lambda d2: d2.u8())
        osd_weight = d.list(lambda d2: d2.u32())
        affinity = d.list(lambda d2: d2.u32())
        osd_addrs = d.list(lambda d2: d2.str())

        pools = d.map(lambda d2: d2.s64(),
                      lambda d2: _dec_pool(d2, version))
        pg_upmap = d.map(_dec_pgid, lambda d2: d2.list(lambda d3: d3.s32()))
        pg_upmap_items = d.map(
            _dec_pgid,
            lambda d2: d2.list(lambda d3: (d3.s32(), d3.s32())))
        pg_temp = d.map(_dec_pgid, lambda d2: d2.list(lambda d3: d3.s32()))
        primary_temp = d.map(_dec_pgid, lambda d2: d2.s32())
        crush_names = {}
        if version >= 3:
            import json as _json
            blob = d.bytes()
            if blob:
                crush_names = _json.loads(blob.decode())
        xinfo = []
        if version >= 4:
            xinfo = d.list(lambda d2: OSDXInfo(
                down_stamp=d2.f64(), laggy_probability=d2.f64(),
                laggy_interval=d2.f64()))
        while len(xinfo) < max_osd:
            xinfo.append(OSDXInfo())
        config_db = {}
        auth_db = {}
        fs_db = {}
        mgr_db = {}
        mon_db = {}
        qos_db = {}
        slo_db = {}
        if version >= 6:
            import json as _json
            blob = d.bytes()
            if blob:
                config_db = _json.loads(blob.decode())
            if version >= 7:
                blob = d.bytes()
                if blob:
                    auth_db = _json.loads(blob.decode())
            if version >= 8:
                blob = d.bytes()
                if blob:
                    fs_db = _json.loads(blob.decode())
            if version >= 9:
                blob = d.bytes()
                if blob:
                    mgr_db = _json.loads(blob.decode())
            if version >= 10:
                blob = d.bytes()
                if blob:
                    mon_db = _json.loads(blob.decode())
            if version >= 11:
                blob = d.bytes()
                if blob:
                    qos_db = _json.loads(blob.decode())
            if version >= 12:
                blob = d.bytes()
                if blob:
                    slo_db = _json.loads(blob.decode())
        return OSDMap(epoch=epoch, crush=crush, max_osd=max_osd,
                      config_db=config_db, auth_db=auth_db, fs_db=fs_db,
                      mgr_db=mgr_db, mon_db=mon_db, qos_db=qos_db,
                      slo_db=slo_db,
                      crush_names=crush_names, osd_xinfo=xinfo,
                      osd_state=osd_state, osd_weight=osd_weight,
                      osd_primary_affinity=affinity, osd_addrs=osd_addrs,
                      pools=pools,
                      pg_upmap=pg_upmap, pg_upmap_items=pg_upmap_items,
                      pg_temp=pg_temp, primary_temp=primary_temp)

    return dec.versioned(1, body)
