"""Device kernels (JAX/XLA, with Pallas variants for the hot paths).

gf_kernel       batched GF(2^8) matrix-vector products: erasure encode/decode.
crush_kernel    rjenkins1 hashes, crush_ln, straw2 selection — batched over inputs.
telemetry       stdlib-only kernel stats registry the entry points feed.

The kernel exports resolve lazily (PEP 562): importing this package —
or ceph_tpu.ops.telemetry, which the mgr's prometheus scraper and every
CephTpuContext do — must not pull in jax/pallas.
"""

__all__ = ["ec_encode_ref", "ec_encode_jax", "make_encoder"]


def __getattr__(name):
    if name in __all__:
        from ceph_tpu.ops import gf_kernel
        return getattr(gf_kernel, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
