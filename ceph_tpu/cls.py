"""Object classes — in-OSD stored procedures (src/cls/ + ClassHandler
analog).

A class method runs AT THE PRIMARY inside the op pipeline with direct
store access, the way the reference dlopens cls_*.so plugins into the
OSD.  Here classes register python handlers:

    @register_cls("lock", "acquire")
    def acquire(ctx, inp: bytes) -> bytes: ...

ctx gives read/write/omap access to the target object; mutations ride
the SAME replicated transaction/log entry as any write.  Built-ins
mirror reference classes: cls_lock (advisory locks), cls_version
(object version counters), cls_numops (atomic arithmetic).
"""

from __future__ import annotations

import json
import threading

_REGISTRY: dict[tuple[str, str], object] = {}
# analysis: allow[bare-lock] -- import-time cls-method registry lock; leaf
_LOCK = threading.Lock()


def register_cls(cls_name: str, method: str):
    def deco(fn):
        with _LOCK:
            _REGISTRY[(cls_name, method)] = fn
        return fn
    return deco


def lookup(cls_name: str, method: str):
    with _LOCK:
        return _REGISTRY.get((cls_name, method))


class ClsContext:
    """What a class method sees: the target object through the store,
    plus a transaction its mutations are appended to."""

    def __init__(self, store, txn, cid: str, oid: str):
        self._store = store
        self.txn = txn
        self.cid = cid
        self.oid = oid
        self.mutated = False

    def read(self) -> bytes:
        try:
            return self._store.read(self.cid, self.oid)
        except KeyError:
            return b""

    def write_full(self, data: bytes) -> None:
        self.txn.truncate(self.cid, self.oid, 0)
        self.txn.write(self.cid, self.oid, 0, data)
        self.mutated = True

    def omap_get(self) -> dict:
        try:
            return self._store.omap_get(self.cid, self.oid)
        except KeyError:
            return {}

    def omap_set(self, keys: dict) -> None:
        self.txn.touch(self.cid, self.oid)
        self.txn.omap_setkeys(self.cid, self.oid, keys)
        self.mutated = True

    def omap_rm(self, keys: list) -> None:
        self.txn.omap_rmkeys(self.cid, self.oid, keys)
        self.mutated = True


# -- built-in classes (cls_lock / cls_version / cls_numops analogs) ----------

@register_cls("lock", "lock")
def _cls_lock(ctx: ClsContext, inp: bytes) -> bytes:
    req = json.loads(inp.decode())
    omap = ctx.omap_get()
    holder = omap.get(b"lock.holder" if False else "lock.holder")
    if holder and holder.decode() != req["owner"]:
        raise PermissionError(f"locked by {holder.decode()}")
    ctx.omap_set({"lock.holder": req["owner"].encode()})
    return b"{}"


@register_cls("lock", "unlock")
def _cls_unlock(ctx: ClsContext, inp: bytes) -> bytes:
    req = json.loads(inp.decode())
    omap = ctx.omap_get()
    holder = omap.get("lock.holder")
    if holder is None:
        return b"{}"
    if holder.decode() != req["owner"]:
        raise PermissionError(f"locked by {holder.decode()}")
    ctx.omap_rm(["lock.holder"])
    return b"{}"


@register_cls("lock", "info")
def _cls_lock_info(ctx: ClsContext, inp: bytes) -> bytes:
    holder = ctx.omap_get().get("lock.holder")
    return json.dumps(
        {"holder": holder.decode() if holder else None}).encode()


@register_cls("version", "bump")
def _cls_version_bump(ctx: ClsContext, inp: bytes) -> bytes:
    cur = int(ctx.omap_get().get("ver", b"0"))
    ctx.omap_set({"ver": str(cur + 1).encode()})
    return json.dumps({"ver": cur + 1}).encode()


@register_cls("version", "read")
def _cls_version_read(ctx: ClsContext, inp: bytes) -> bytes:
    return json.dumps(
        {"ver": int(ctx.omap_get().get("ver", b"0"))}).encode()


@register_cls("numops", "add")
def _cls_numops_add(ctx: ClsContext, inp: bytes) -> bytes:
    req = json.loads(inp.decode())
    cur = int(ctx.omap_get().get(req["key"], b"0"))
    val = cur + int(req["val"])
    ctx.omap_set({req["key"]: str(val).encode()})
    return json.dumps({"value": val}).encode()
