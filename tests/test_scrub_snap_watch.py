"""Scrub, pool snapshots, and watch/notify (VERDICT round-1 item 7:
the PrimaryLogPG feature tier)."""

import threading
import time

import pytest

from ceph_tpu.client.rados import ceph_str_hash_rjenkins
from ceph_tpu.objectstore import Transaction
from ceph_tpu.osd.osdmap import pg_to_pgid
from ceph_tpu.tools.vstart import MiniCluster


@pytest.fixture()
def cluster():
    c = MiniCluster(n_osds=3, ms_type="loopback").start()
    c.wait_for_osd_count(3)
    try:
        yield c
    finally:
        c.stop()


def _pg_of(cluster, pool, oid):
    m = cluster.mon.osdmap
    pg = pg_to_pgid(ceph_str_hash_rjenkins(oid), m.pools[pool].pg_num)
    up, primary, _a, _ap = m.pg_to_up_acting_osds(pool, pg)
    return pg, up, primary


class TestScrub:
    def test_clean_pg_scrubs_clean(self, cluster):
        client = cluster.client()
        pool = cluster.create_pool(client, pg_num=4, size=3)
        io = client.open_ioctx(pool)
        io.write_full("s1", b"spotless" * 100)
        time.sleep(0.3)
        pg, up, primary = _pg_of(cluster, pool, "s1")
        rep = cluster.osds[primary].scrub_pg((pool, pg))
        assert rep["inconsistent"] == []
        assert rep["checked"] >= 1

    def test_replica_corruption_found_and_repaired(self, cluster):
        client = cluster.client()
        pool = cluster.create_pool(client, pg_num=4, size=3)
        io = client.open_ioctx(pool)
        io.write_full("sc", b"truth" * 200)
        time.sleep(0.3)
        pg, up, primary = _pg_of(cluster, pool, "sc")
        victim_id = next(o for o in up if o != primary)
        victim = cluster.osds[victim_id]
        cid = f"{pool}.{pg}"
        t = (Transaction().truncate(cid, "sc", 0)
             .write(cid, "sc", 0, b"lies" * 200))
        victim.store.apply_transaction(t)
        rep = cluster.osds[primary].scrub_pg((pool, pg))
        assert "sc" in rep["inconsistent"]
        deadline = time.time() + 10
        while time.time() < deadline:
            if victim.store.read(cid, "sc") == b"truth" * 200:
                break
            time.sleep(0.1)
        assert victim.store.read(cid, "sc") == b"truth" * 200

    def test_primary_outlier_repulls_from_replicas(self, cluster):
        client = cluster.client()
        pool = cluster.create_pool(client, pg_num=4, size=3)
        io = client.open_ioctx(pool)
        io.write_full("pc", b"quorum" * 150)
        time.sleep(0.3)
        pg, up, primary = _pg_of(cluster, pool, "pc")
        prim = cluster.osds[primary]
        cid = f"{pool}.{pg}"
        t = (Transaction().truncate(cid, "pc", 0)
             .write(cid, "pc", 0, b"drifted"))
        prim.store.apply_transaction(t)
        rep = prim.scrub_pg((pool, pg))
        assert "pc" in rep["inconsistent"]
        deadline = time.time() + 10
        while time.time() < deadline:
            if prim.store.read(cid, "pc") == b"quorum" * 150:
                break
            time.sleep(0.1)
        assert prim.store.read(cid, "pc") == b"quorum" * 150
        assert io.read("pc") == b"quorum" * 150


class TestSnapshots:
    def test_snapshot_preserves_point_in_time(self, cluster):
        client = cluster.client()
        pool = cluster.create_pool(client, pg_num=4, size=3)
        io = client.open_ioctx(pool)
        io.write_full("obj", b"version-one")
        res, out = client.mon_command(
            {"prefix": "osd pool mksnap", "pool": str(pool),
             "snap": "snap1"})
        assert res == 0, out
        import json
        snap1 = json.loads(out)["snapid"]
        cluster.wait_for_epoch(cluster.mon.osdmap.epoch)
        client.wait_for_epoch(cluster.mon.osdmap.epoch)
        io.write_full("obj", b"version-two")
        assert io.read("obj") == b"version-two"
        assert io.read("obj", snapid=snap1) == b"version-one"

    def test_two_snapshots_layer(self, cluster):
        client = cluster.client()
        pool = cluster.create_pool(client, pg_num=4, size=3)
        io = client.open_ioctx(pool)
        import json

        def mksnap(name):
            res, out = client.mon_command(
                {"prefix": "osd pool mksnap", "pool": str(pool),
                 "snap": name})
            assert res == 0, out
            cluster.wait_for_epoch(cluster.mon.osdmap.epoch)
            client.wait_for_epoch(cluster.mon.osdmap.epoch)
            return json.loads(out)["snapid"]

        io.write_full("o", b"A")
        s1 = mksnap("s1")
        io.write_full("o", b"B")
        s2 = mksnap("s2")
        io.write_full("o", b"C")
        assert io.read("o") == b"C"
        assert io.read("o", snapid=s2) == b"B"
        assert io.read("o", snapid=s1) == b"A"

    def test_object_created_after_snap_absent_at_snap(self, cluster):
        client = cluster.client()
        pool = cluster.create_pool(client, pg_num=4, size=3)
        io = client.open_ioctx(pool)
        import json
        res, out = client.mon_command(
            {"prefix": "osd pool mksnap", "pool": str(pool),
             "snap": "early"})
        snapid = json.loads(out)["snapid"]
        cluster.wait_for_epoch(cluster.mon.osdmap.epoch)
        client.wait_for_epoch(cluster.mon.osdmap.epoch)
        io.write_full("late", b"born after the snapshot")
        with pytest.raises(OSError):
            io.read("late", snapid=snapid)

    def test_delete_preserves_snapshot(self, cluster):
        client = cluster.client()
        pool = cluster.create_pool(client, pg_num=4, size=3)
        io = client.open_ioctx(pool)
        import json
        io.write_full("gone", b"still reachable via snap")
        res, out = client.mon_command(
            {"prefix": "osd pool mksnap", "pool": str(pool),
             "snap": "keep"})
        snapid = json.loads(out)["snapid"]
        cluster.wait_for_epoch(cluster.mon.osdmap.epoch)
        client.wait_for_epoch(cluster.mon.osdmap.epoch)
        io.remove("gone")
        with pytest.raises(OSError):
            io.read("gone")
        assert io.read("gone", snapid=snapid) \
            == b"still reachable via snap"


class TestWatchNotify:
    def test_notify_reaches_watcher(self, cluster):
        c1 = cluster.client()
        c2 = cluster.client()
        pool = cluster.create_pool(c1, pg_num=4, size=3)
        c2.wait_for_epoch(cluster.mon.osdmap.epoch)
        io1 = c1.open_ioctx(pool)
        io2 = c2.open_ioctx(pool)
        io1.write_full("w", b"watched")
        got = []
        ev = threading.Event()

        def cb(payload):
            got.append(payload)
            ev.set()

        io2.watch("w", cb)
        io1.notify("w", b"ping!")     # returns once the watcher acked
        assert ev.wait(5)
        assert got == [b"ping!"]

    def test_notify_without_watchers_returns(self, cluster):
        c1 = cluster.client()
        pool = cluster.create_pool(c1, pg_num=4, size=3)
        io = c1.open_ioctx(pool)
        io.write_full("nw", b"x")
        io.notify("nw", b"anyone?")   # must not hang

    def test_unwatch_stops_notifies(self, cluster):
        c1 = cluster.client()
        c2 = cluster.client()
        pool = cluster.create_pool(c1, pg_num=4, size=3)
        c2.wait_for_epoch(cluster.mon.osdmap.epoch)
        io1 = c1.open_ioctx(pool)
        io2 = c2.open_ioctx(pool)
        io1.write_full("uw", b"x")
        got = []
        io2.watch("uw", got.append)
        io2.unwatch("uw")
        io1.notify("uw", b"silence")
        time.sleep(0.3)
        assert got == []
