"""Typed configuration registry with observers.

The reference keeps one declarative option table (src/common/options.cc, 7510
lines of Option{name, type, level, default, description, flags}) consumed by
md_config_t (common/config.h:152-223) with observer-based hot reload
(common/config_obs.h).  Sources are layered: compiled defaults < config file <
mon config-db < env < CLI < runtime `config set`.  This module mirrors that:
a declarative OPTIONS table, a Config object with layered sources, and
observers notified on runtime changes.
"""

from __future__ import annotations

import json
import threading
from dataclasses import dataclass, field

OPT_INT = "int"
OPT_STR = "str"
OPT_BOOL = "bool"
OPT_FLOAT = "float"

LEVEL_BASIC = "basic"
LEVEL_ADVANCED = "advanced"
LEVEL_DEV = "dev"

_CASTS = {
    OPT_INT: int,
    OPT_FLOAT: float,
    OPT_STR: str,
    OPT_BOOL: lambda v: (v if isinstance(v, bool)
                         else str(v).lower() in ("true", "1", "yes", "on")),
}


@dataclass(frozen=True)
class Option:
    name: str
    type: str
    default: object
    description: str = ""
    level: str = LEVEL_ADVANCED
    runtime: bool = True      # changeable without restart (flag RUNTIME)
    see_also: tuple = ()

    def cast(self, value):
        try:
            return _CASTS[self.type](value)
        except (TypeError, ValueError):
            raise ValueError(
                f"option {self.name}: {value!r} is not a valid {self.type}")


#: The central option table (options.cc analog).  Components register theirs
#: at import via register_options().
OPTIONS: dict[str, Option] = {}


def register_options(opts: list[Option]) -> None:
    for o in opts:
        if o.name in OPTIONS and OPTIONS[o.name] != o:
            raise ValueError(f"conflicting re-registration of {o.name}")
        OPTIONS[o.name] = o


register_options([
    Option("erasure_code_plugins", OPT_STR, "jerasure isa",
           "plugins preloaded at init (options.cc:2197 analog)"),
    Option("erasure_code_runtime", OPT_STR, "tpu",
           "default EC execution runtime: tpu | cpu"),
    Option("crush_backend", OPT_STR, "tpu",
           "bulk placement backend: tpu (BatchMapper) | scalar"),
    Option("osdmap_mapping_min_pgs", OPT_INT, 1024,
           "pools with fewer PGs than this rebuild their cached raw "
           "tables with the scalar rule engine instead of a device "
           "call (per-call dispatch + jit-compile overhead dominates "
           "tiny pools); the epoch cache, incremental invalidation "
           "and delta detection are identical either way"),
    Option("osdmap_mapping_fused", OPT_BOOL, True,
           "fuse the post-CRUSH placement pipeline tail (upmap -> "
           "up/state filter -> primary affinity -> pg_temp/"
           "primary_temp) into one device ladder per epoch "
           "(ops.placement_kernel): the mapping service publishes "
           "packed (up, acting, primaries) tables next to the raw "
           "ones, reads become row slices, and epoch deltas diff the "
           "fused outputs on device; off (or crush_backend=scalar) = "
           "the per-PG host pipeline tail of PR 5"),
    Option("osdmap_mapping_shared", OPT_BOOL, True,
           "serve PG->OSD mappings from the context's shared "
           "epoch-keyed mapping cache (osd.mapping."
           "SharedPGMappingService): OSD map consumption becomes "
           "O(changed PGs + local PGs), client op targeting and the "
           "balancer read cached raw placements; off = every consumer "
           "runs the scalar pg_to_up_acting_osds pipeline per PG"),
    Option("osd_pool_default_size", OPT_INT, 3, "replicas per object"),
    Option("mds_dentry_lease_ttl", OPT_FLOAT, 10.0,
           "seconds a client may trust a leased dentry+attrs without "
           "re-asking the MDS (client dcache, MClientLease analog)"),
    Option("osd_pool_default_min_size", OPT_INT, 2,
           "min replicas to serve IO"),
    Option("osd_pool_default_pg_num", OPT_INT, 32, "pgs per new pool"),
    Option("osd_heartbeat_interval", OPT_FLOAT, 1.0,
           "seconds between peer pings (osd_heartbeat_interval analog)"),
    Option("osd_heartbeat_grace", OPT_FLOAT, 6.0,
           "seconds without ping before reporting failure"),
    Option("mon_osd_min_down_reporters", OPT_INT, 2,
           "distinct reporters before the mon marks an osd down"),
    Option("mon_osd_adjust_heartbeat_grace", OPT_INT, 1,
           "scale the mark-down grace by the target's laggy history "
           "(OSDMonitor.cc:2548-2572 analog)"),
    Option("mon_osd_laggy_halflife", OPT_FLOAT, 3600.0,
           "seconds for laggy history to decay by half"),
    Option("mon_osd_laggy_weight", OPT_FLOAT, 0.3,
           "weight of the newest laggy interval in the decaying average"),
    Option("mon_osd_laggy_max_interval", OPT_FLOAT, 300.0,
           "cap on a single recorded laggy interval (seconds)"),
    Option("osd_op_complaint_time", OPT_FLOAT, 30.0,
           "age after which an in-flight op is a slow request"),
    Option("osd_map_renew_interval", OPT_FLOAT, 2.0,
           "seconds between mon map-subscription renewals"),
    Option("osd_op_queue", OPT_STR, "mclock",
           "op scheduler: mclock (sharded QoS queue) | direct"),
    Option("osd_op_num_shards", OPT_INT, 2,
           "op queue shards (ops shard by pgid; per-PG order kept)"),
    Option("osd_mclock_per_client", OPT_INT, 1,
           "tag client ops per client id (dmclock client-class QoS) "
           "instead of one aggregate client class"),
    Option("osd_mclock_client_reservation", OPT_FLOAT, 0.0,
           "per-client guaranteed ops/s (dmclock reservation; 0 = none)"),
    Option("osd_mclock_client_weight", OPT_FLOAT, 100.0,
           "per-client share of excess capacity (dmclock weight)"),
    Option("osd_mclock_client_limit", OPT_FLOAT, 0.0,
           "per-client ops/s cap (dmclock limit; 0 = unlimited)"),
    Option("osd_op_queue_max_client_backlog", OPT_INT, 512,
           "client ops queued per shard before dispatch backpressure "
           "blocks the intake (peer/recovery classes are never gated)"),
    Option("osd_qos_tenant_lanes", OPT_BOOL, True,
           "schedule client ops by the MOSDOp's authenticated tenant "
           "tag (client.<tenant> dmclock lanes with per-tenant "
           "profiles from the OSDMap qos_db); off = per-client-id "
           "lanes only, tenant tags ignored"),
    Option("osd_qos_idle_client_timeout", OPT_FLOAT, 60.0,
           "seconds a dynamic per-client/per-tenant dmclock lane may "
           "sit idle (empty queue, no enqueues) before the scheduler "
           "evicts its state — bounds the lane table under millions "
           "of one-shot clients; served/wait totals fold into the "
           "dump_qos_stats evicted rollup"),
    Option("osd_max_backfills", OPT_INT, 1,
           "PGs an osd recovers concurrently (reservation slots)"),
    Option("osd_recovery_max_active", OPT_INT, 3,
           "in-flight object pulls per recovering PG"),
    Option("osd_client_message_size_cap", OPT_INT, 256 << 20,
           "bytes of op payloads queued in the sharded op queue before "
           "dispatch threads block (front-door backpressure)"),
    Option("tracing_sample_rate", OPT_FLOAT, 0.0,
           "head-sampling probability for client ops (0 = trace only "
           "explicitly opened traces; 1 = trace everything)"),
    Option("tracing_slow_threshold", OPT_FLOAT, 0.5,
           "root-span seconds at/above which a completed trace is "
           "promoted into the slow-trace ring (tail retention) instead "
           "of aging out with the rest"),
    Option("tracing_slow_ring", OPT_INT, 64,
           "completed slow traces retained per process"),
    Option("kernel_coalesce_max_stripes", OPT_INT, 2048,
           "stripes per coalesced device call: the dispatch engine "
           "stacks concurrent EC/CRUSH requests on the batch axis and "
           "flushes when the batch reaches this many rows"),
    Option("kernel_coalesce_max_delay_us", OPT_FLOAT, 250.0,
           "microseconds a queued kernel request may wait for "
           "coalescing company while the pipeline is busy; an idle "
           "engine always flushes immediately, so single-op latency "
           "never pays this"),
    Option("kernel_dispatch_depth", OPT_INT, 2,
           "device calls in flight per dispatch engine (2 = double "
           "buffering: h2d of batch N+1 overlaps compute of batch N)"),
    Option("kernel_mesh_devices", OPT_INT, 0,
           "devices the dispatch engines shard each coalesced batch "
           "over (the stripe/PG axis splits across a dp x ec device "
           "mesh): 0 = all local devices, 1 = single-device (exact "
           "pre-mesh engine behavior), N = the first N devices; "
           "ignored when the backend exposes one device"),
    Option("osd_ec_dispatch_async", OPT_BOOL, True,
           "submit EC write encodes through the dispatch engine and "
           "run transaction-build + shard fan-out in the completion "
           "continuation, letting concurrent client writes share one "
           "device call; off = encode synchronously per op"),
    Option("osd_ec_decode_async", OPT_BOOL, True,
           "submit EC decodes (degraded reads, recovery pulls, rmw "
           "gathers) through the decode dispatch engine and finish "
           "reply/push/overlay in the completion continuation; "
           "concurrent decodes coalesce into one device call even "
           "with different erasure patterns (heterogeneous-matrix "
           "batched kernel); off = decode synchronously per gather"),
    Option("kernel_failpoints", OPT_STR, "",
           "armed device-runtime failpoints (common/failpoint.py): "
           "'name=mode[;name=mode...]' where name is a boundary site "
           "optionally channel-qualified (dispatch.launch:ec_encode) "
           "and mode is always|prob:P|oneshot|nth:K|off; empty "
           "disarms everything; the failpoint set/clear/ls admin "
           "commands drive the same registry"),
    Option("kernel_fault_max_retries", OPT_INT, 2,
           "device re-attempts per coalesced batch after a transient "
           "device failure before the batch fails over to the host "
           "oracle (or fans its error); each retry waits an "
           "exponentially growing jittered backoff"),
    Option("kernel_fault_backoff_ms", OPT_FLOAT, 5.0,
           "base retry backoff in milliseconds: attempt i waits "
           "base * 2^i scaled by uniform jitter in [0.5, 1.0)"),
    Option("kernel_fault_backoff_max_ms", OPT_FLOAT, 200.0,
           "cap on a single retry backoff wait"),
    Option("kernel_fault_breaker_threshold", OPT_INT, 3,
           "consecutive device-path batch failures (retries "
           "exhausted) on one kernel channel before its circuit "
           "breaker opens and batches route through the bit-exact "
           "host oracle while a background probe retries the device"),
    Option("kernel_fault_probe_interval", OPT_FLOAT, 0.5,
           "seconds between background device-path probes while a "
           "channel breaker is open; a successful probe closes the "
           "breaker and traffic returns to the device"),
    Option("kernel_fault_thread_restarts", OPT_INT, 4,
           "times a dead dispatch/completion thread is restarted "
           "per engine (in-flight batches re-fan to the replacement); "
           "past the budget the engine is wedged: every waiter gets "
           "a loud EngineWedgedError and flush() raises"),
    Option("osd_scrub_batched", OPT_BOOL, True,
           "compute scrub-map digests as one coalesced device batch "
           "per PG through the scrub_digest dispatch channel (crc32 + "
           "GF shard digest over stacked object/omap rows); off = the "
           "seed's per-object host shard_crc loop (always the "
           "fallback when the channel degrades)"),
    Option("osd_scrub_chunk_timeout", OPT_FLOAT, 15.0,
           "seconds a scrubbing primary waits for replica scrub maps "
           "per gather round; peers the osdmap marks down are "
           "recorded as missing immediately instead of waited out"),
    Option("osd_scrub_retry_backoff_ms", OPT_FLOAT, 150.0,
           "backoff before the single MOSDScrub re-request to a "
           "replica that never answered the first gather round; a "
           "peer still silent after the retry lands in the report's "
           "missing_peers and the PG is never reported clean"),
    Option("osd_scrub_verify_repairs", OPT_BOOL, True,
           "re-fetch each repaired copy's digest (a follow-up scrub "
           "of just the repaired oids) before counting it repaired; "
           "repairs that never verify surface as repair_unverified"),
    Option("osd_scrub_verify_timeout", OPT_FLOAT, 6.0,
           "seconds to keep re-checking a pending repair (pushes and "
           "recovery pulls apply asynchronously) before reporting it "
           "repair_unverified"),
    Option("osd_scrub_background_weight", OPT_FLOAT, 1.0,
           "dmclock weight of the background_best_effort class scrub "
           "ops schedule in: background integrity shares only excess "
           "capacity, so a full-cluster deep scrub cannot starve "
           "tenant reservations"),
    Option("osd_scrub_background_limit", OPT_FLOAT, 0.0,
           "ops/s cap on the background_best_effort class (0 = "
           "unlimited — weight-arbitrated only)"),
    Option("osd_scrub_cost", OPT_INT, 4,
           "dmclock cost units one scrub map-build CHUNK charges (the "
           "delta its background tag advances by): a chunk's bulk "
           "read + digest batch is still a few small-op service "
           "times, and without cost scaling the per-op scheduler "
           "would hand the background class cost-times its weight's "
           "worth of worker-seconds"),
    Option("osd_scrub_chunk_objects", OPT_INT, 16,
           "store objects per scrub map-build chunk (chunky scrub): "
           "each background lane op reads+digests at most this many "
           "objects, so scrub's non-preemptive service quantum stays "
           "small-op sized and a tenant op never waits out a "
           "whole-PG map build"),
    Option("osd_scrub_sleep", OPT_FLOAT, 0.004,
           "seconds between scrub map-build chunks (the reference's "
           "osd_scrub_sleep, implemented as a delayed requeue so "
           "neither a shard worker nor an engine thread parks): "
           "paces the storm's python-side work so continuous deep "
           "scrub rides the excess instead of contending for the "
           "serving threads; 0 = no pacing"),
    Option("osd_scrub_auto_interval", OPT_FLOAT, 0.0,
           "seconds between automatic full deep-scrub sweeps "
           "(scrub_all_pgs) this osd starts for the PGs it leads; "
           "0 disables the continuous driver (manual/admin scrubs "
           "only)"),
    Option("client_resend_backoff_ms", OPT_FLOAT, 25.0,
           "base backoff in milliseconds before an Objecter resend "
           "of an already-resent in-flight op (map-change/stale-epoch "
           "retargeting): resend i of one op waits ~base * 2^(i-1) "
           "with uniform jitter; the FIRST resend is immediate, so a "
           "single map change never delays an op"),
    Option("client_resend_backoff_max_ms", OPT_FLOAT, 2000.0,
           "cap on a single client resend backoff wait"),
    Option("kernel_profile_ring", OPT_INT, 256,
           "recent per-batch pipeline-profile records retained per "
           "dispatch engine (the dump_pipeline_profile ring); "
           "aggregated phase histograms are unbounded-time regardless"),
    Option("kernel_fence_for_timing", OPT_BOOL, False,
           "fence (block_until_ready) each instrumented device kernel "
           "call so telemetry latency samples are real device time; "
           "serializes the dispatch pipeline, so keep off on hot paths"),
    Option("kernel_tenant_ledger_enabled", OPT_BOOL, True,
           "apportion each coalesced batch's device busy integral "
           "(compute x devices) to its requests' cost_tags by stripe "
           "share and accumulate the per-tenant x engine x channel "
           "device-time ledger (dump_tenant_usage / the MMgrReport "
           "tenant_usage tail / ceph_tenant_* prometheus families); "
           "measurement-only — scheduling never reads it"),
    Option("kernel_tenant_ledger_max_tenants", OPT_INT, 1024,
           "distinct tenants the device-time ledger tracks before new "
           "tenants fold into the _overflow bucket (a tenant-name "
           "flood cannot grow the table without bound; overflow work "
           "stays counted, so conservation holds)"),
    Option("mgr_slo_fast_window_s", OPT_FLOAT, 300.0,
           "fast burn-rate window of the mgr slo module: QOS_SLO_BURN "
           "fires only while the fast AND slow windows both burn at "
           ">= 1.0, and clears once the fast window recovers"),
    Option("mgr_slo_slow_window_s", OPT_FLOAT, 3600.0,
           "slow burn-rate window of the mgr slo module (the "
           "sustained-violation proof; see mgr_slo_fast_window_s)"),
    Option("mgr_slo_max_samples", OPT_INT, 2048,
           "rolling counter samples the mgr slo module retains for "
           "windowed burn evaluation (also time-bounded by the slow "
           "window)"),
    Option("bluestore_batched_csum", OPT_BOOL, True,
           "settle each bluestore transaction batch's write-time "
           "block checksums as ONE coalesced device digest through "
           "the bluestore_data dispatch channel (the scrub digest "
           "kernel's crc32 column over stored payloads); off = the "
           "seed's inline scalar zlib.crc32 per block (always the "
           "fallback when the channel degrades)"),
    Option("bluestore_batched_csum_min", OPT_INT, 4,
           "minimum pending blocks before a commit's checksum batch "
           "rides the device; smaller batches take the scalar path "
           "(a one-block digest is cheaper on the host)"),
    Option("bluestore_data_timeout", OPT_FLOAT, 30.0,
           "seconds a bluestore commit or batched read waits on its "
           "bluestore_data digest future before falling back to "
           "scalar crc32 (generous: the engine's own retry/breaker "
           "ladder resolves failures far sooner)"),
    Option("bluestore_batched_read_verify", OPT_BOOL, True,
           "verify wide reads' block checksums as one bluestore_data "
           "digest call instead of per-block scalar crc32; any "
           "engine failure falls back to the scalar per-block path — "
           "reads never lose verification, only batching"),
    Option("bluestore_batched_read_min", OPT_INT, 8,
           "minimum checksummed blocks a read must cover before its "
           "verification batches to the device"),
    Option("bluestore_compression_mode", OPT_STR, "none",
           "default objectstore block compression mode when a pool "
           "sets none: none | aggressive | force (per-pool "
           "compression_mode overrides; passive is not carried — "
           "client hints do not exist in this stack)"),
    Option("bluestore_compression_algorithm", OPT_STR, "tpu_bitplane",
           "default compressor plugin for block compression "
           "(compressor registry name: tpu_bitplane | zlib | lzma)"),
    Option("bluestore_compression_required_ratio", OPT_FLOAT, 0.875,
           "a compressed block is kept only if stored_size <= "
           "block_size * ratio; otherwise it is stored raw "
           "(compress_rejected)"),
    Option("bluestore_compression_verify", OPT_BOOL, True,
           "round-trip every compressed block (decompress and "
           "compare byte-identical) before committing it; a "
           "mismatch stores the block raw and counts "
           "compress_roundtrip_failures"),
    Option("log_level", OPT_INT, 1, "default subsystem log level"),
    Option("ms_type", OPT_STR, "async",
           "messenger implementation: async | loopback"),
    Option("objectstore", OPT_STR, "memstore",
           "object store backend: memstore | filestore | bluestore"),
])


class Config:
    """Layered config with observers (md_config_t analog)."""

    #: source precedence, low to high (config.h "sources" semantics)
    SOURCES = ("default", "file", "mon", "env", "cli", "runtime")

    def __init__(self, options: dict[str, Option] | None = None):
        self._options = options if options is not None else OPTIONS
        # analysis: allow[bare-lock] -- config underpins lockdep's own enable gate (g_lockdep reads conf) -- bare avoids a bootstrap cycle; leaf around layer dicts
        self._lock = threading.RLock()
        self._values: dict[str, dict[str, object]] = {}  # name -> src -> val
        self._observers: dict[str, list] = {}            # name -> callbacks

    def get(self, name: str):
        with self._lock:
            opt = self._lookup(name)
            layers = self._values.get(name, {})
            for src in reversed(self.SOURCES):
                if src in layers:
                    return layers[src]
            return opt.default

    def set(self, name: str, value, source: str = "runtime") -> None:
        if source not in self.SOURCES:
            raise ValueError(f"unknown config source {source!r}")
        with self._lock:
            opt = self._lookup(name)
            if source == "runtime" and not opt.runtime:
                raise ValueError(
                    f"option {name} cannot change at runtime (STARTUP flag)")
            old = self.get(name)
            self._values.setdefault(name, {})[source] = opt.cast(value)
            new = self.get(name)
            observers = list(self._observers.get(name, []))
        if new != old:
            for cb in observers:
                cb(name, new)

    def rm(self, name: str, source: str) -> None:
        """Retract a layer's value (the mon config-db analog of
        `ceph config rm`); observers fire if the effective value moves."""
        with self._lock:
            self._lookup(name)
            old = self.get(name)
            layers = self._values.get(name, {})
            layers.pop(source, None)
            new = self.get(name)
            observers = list(self._observers.get(name, []))
        if new != old:
            for cb in observers:
                cb(name, new)

    def load_file(self, path: str) -> None:
        """JSON config file (the ceph.conf layer)."""
        with open(path) as f:
            for k, v in json.load(f).items():
                self.set(k, v, source="file")

    def add_observer(self, name: str, callback) -> None:
        """callback(name, new_value) on effective-value change
        (config_obs.h analog)."""
        with self._lock:
            self._lookup(name)
            self._observers.setdefault(name, []).append(callback)

    def show(self) -> dict:
        """Effective config (admin `config show`)."""
        with self._lock:
            return {name: self.get(name) for name in sorted(self._options)}

    def diff(self) -> dict:
        """Only values differing from defaults (admin `config diff`)."""
        with self._lock:
            return {name: self.get(name) for name in sorted(self._values)
                    if self.get(name) != self._options[name].default}

    def _lookup(self, name: str) -> Option:
        if name not in self._options:
            raise KeyError(f"unknown config option {name!r}")
        return self._options[name]
