"""Runtime lock-order checking (src/common/lockdep.{h,cc},
Mutex.h:44-53).

Every named DebugRLock registers edges in one global lock-order graph:
acquiring B while holding A records A->B.  If a later acquire would
add an edge that closes a cycle (B held, taking A), the reference
aborts the process; here we raise LockOrderError with both
acquisition backtraces, which the thrasher/tests turn into failures.

Zero-cost by default: make_lock() hands out plain threading.RLock
unless lockdep is enabled (enable() in tests, or CEPH_TPU_LOCKDEP=1 —
g_lockdep config gate).
"""

from __future__ import annotations

import os
import threading
import traceback

_registry_lock = threading.Lock()
#: name -> set of names acquired while it was held (the order graph)
_follows: dict[str, set[str]] = {}
#: (a, b) -> formatted stack where a->b was first recorded
_edge_sites: dict[tuple[str, str], str] = {}
_enabled = os.environ.get("CEPH_TPU_LOCKDEP", "") not in ("", "0")

_held = threading.local()


class LockOrderError(RuntimeError):
    pass


#: every detected violation (also raised); daemon threads may swallow
#: the exception, so CI asserts this list is empty after a workload
violations: list[str] = []


def enable(on: bool = True) -> None:
    global _enabled
    _enabled = on


def enabled() -> bool:
    return _enabled


def reset() -> None:
    with _registry_lock:
        _follows.clear()
        _edge_sites.clear()
        violations.clear()


def _reaches(src: str, dst: str) -> bool:
    """Is dst reachable from src in the order graph?  (lockdep.cc
    does_follow DFS)."""
    seen = set()
    stack = [src]
    while stack:
        n = stack.pop()
        if n == dst:
            return True
        if n in seen:
            continue
        seen.add(n)
        stack.extend(_follows.get(n, ()))
    return False


class DebugRLock:
    """Drop-in RLock recording ordering (Mutex with lockdep=true)."""

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.RLock()

    def _check_order(self) -> None:
        held = getattr(_held, "stack", None)
        if not held:
            return
        if self.name in held:       # re-entrant acquire: no new edge
            return
        with _registry_lock:
            for h in held:
                if _reaches(self.name, h):
                    site = _edge_sites.get((self.name, h), "  (unknown)")
                    msg = (
                        f"lock order violation: acquiring {self.name!r} "
                        f"while holding {h!r}, but {h!r} was previously "
                        f"acquired while {self.name!r} was held; first "
                        f"recorded at:\n{site}")
                    violations.append(msg)
                    raise LockOrderError(msg)
                edge = (h, self.name)
                if edge not in _edge_sites:
                    _follows.setdefault(h, set()).add(self.name)
                    _edge_sites[edge] = "".join(
                        traceback.format_stack(limit=8)[:-2])

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if _enabled:
            self._check_order()
        got = self._lock.acquire(blocking, timeout)
        if got:
            stack = getattr(_held, "stack", None)
            if stack is None:
                stack = _held.stack = []
            stack.append(self.name)
        return got

    def release(self) -> None:
        self._lock.release()
        stack = getattr(_held, "stack", None)
        if stack:
            # remove the most recent entry for this lock name
            for i in range(len(stack) - 1, -1, -1):
                if stack[i] == self.name:
                    del stack[i]
                    break

    __enter__ = acquire

    def __exit__(self, *exc) -> None:
        self.release()

    # threading.Condition protocol: a Condition wrapping a DebugRLock
    # calls these around wait().  They delegate straight to the inner
    # RLock — the held-stack entry goes stale for the duration of the
    # wait, which is harmless (the thread is blocked and acquires
    # nothing until _acquire_restore returns), and re-acquiring after a
    # wait is a continuation of the original hold, not a new edge.

    def _is_owned(self):
        return self._lock._is_owned()

    def _release_save(self):
        return self._lock._release_save()

    def _acquire_restore(self, state) -> None:
        self._lock._acquire_restore(state)


def make_lock(name: str):
    """Factory the daemons use: plain RLock in production, DebugRLock
    under lockdep (Mutex(name) with g_lockdep)."""
    return DebugRLock(name) if _enabled else threading.RLock()


def make_condition(name: str, lock=None) -> threading.Condition:
    """Condition-variable factory (Cond + Mutex(name) in the
    reference).  Under lockdep the condition's lock is a named
    DebugRLock, so every `with cv:` records order edges like any other
    mutex; wait() releases/re-acquires through the Condition protocol
    above.  ``lock`` lets callers share one named lock between a mutex
    and its condition."""
    if lock is None:
        lock = make_lock(name)
    return threading.Condition(lock)


def export_graph() -> dict:
    """Snapshot the runtime order graph for offline union with the
    static analyzer (`python -m ceph_tpu.analysis --runtime-graph`).
    Shape: {"edges": [{"a": .., "b": .., "site": ..}, ...]} where a->b
    means b was acquired while a was held."""
    with _registry_lock:
        return {"edges": [
            {"a": a, "b": b, "site": _edge_sites.get((a, b), "")}
            for a, follows in sorted(_follows.items())
            for b in sorted(follows)]}
