"""Multi-chip sharding tests on the virtual 8-device CPU mesh."""

import sys
from pathlib import Path

import numpy as np
import jax
import jax.numpy as jnp

from ceph_tpu.gf.matrix import gen_cauchy1_matrix
from ceph_tpu.ops.gf_kernel import ec_encode_ref
from ceph_tpu.parallel import factor_devices, make_mesh, sharded_encode
from ceph_tpu.parallel.sharded import make_cluster_step

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def test_factor_devices():
    assert factor_devices(8, ec_divides=12) == (2, 4)
    assert factor_devices(1) == (1, 1)
    assert factor_devices(7) == (7, 1)
    assert factor_devices(4, ec_divides=12) == (1, 4)


def test_sharded_encode_matches_oracle():
    k, m = 8, 4
    mesh = make_mesh(8, ec_divides=k + m)
    gen = gen_cauchy1_matrix(k, m)
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (16, k, 128), dtype=np.uint8)
    parity = np.asarray(sharded_encode(mesh, gen[k:], data))
    np.testing.assert_array_equal(parity, ec_encode_ref(gen[k:], data))


def test_cluster_step_end_to_end():
    k, m = 8, 4
    mesh = make_mesh(8, ec_divides=k + m)
    gen = gen_cauchy1_matrix(k, m)
    rng = np.random.default_rng(1)
    n_osds = 32
    ids = np.arange(n_osds, dtype=np.int32)
    weights = np.full(n_osds, 0x10000, dtype=np.int64)
    reweight = np.full(n_osds, 0x10000, dtype=np.int64)
    step = make_cluster_step(mesh, gen, ids, weights, reweight,
                             numrep=3, erasures=(1, 9))
    xs = jnp.asarray(rng.integers(0, 2**32, (32,), dtype=np.uint32))
    data = jnp.asarray(rng.integers(0, 256, (8, k, 64), dtype=np.uint8))
    out = step(xs, data)
    assert int(out["mismatches"]) == 0
    assert int(np.asarray(out["utilization"]).sum()) == 32 * 3
    # rebuilt chunks equal the originals they stand in for
    full = np.concatenate([np.asarray(data), np.asarray(out["parity"])], axis=1)
    np.testing.assert_array_equal(np.asarray(out["rebuilt"]),
                                  full[:, [1, 9], :])
    # placements are valid distinct devices
    p = np.asarray(out["placements"])
    assert ((p >= 0) & (p < n_osds)).all()
    for row in p:
        assert len(set(row.tolist())) == 3


def test_graft_entry_single_chip():
    import __graft_entry__ as ge
    fn, args = ge.entry()
    placements, parity = jax.jit(fn)(*args)
    assert placements.shape == (256, 3)
    assert parity.shape == (32, 4, 512)


def test_graft_entry_dryrun_multichip():
    import __graft_entry__ as ge
    ge.dryrun_multichip(8)
