"""Leveled, per-subsystem logging (src/common/dout.h:121 analog).

The reference gates ``dout(level)`` per subsystem (~90 subsystems in
common/subsys.h) with runtime-changeable levels.  Here each subsystem is a
python logger under the "ceph_tpu" root with an integer gather level: a
message logs when msg_level <= subsystem level (reference convention — higher
level means more verbose).
"""

from __future__ import annotations

import logging
import sys
import threading

# analysis: allow[bare-lock] -- import-time leaf lock on the dout hot path
_lock = threading.Lock()
_levels: dict[str, int] = {}
_DEFAULT_LEVEL = 1

SUBSYSTEMS = [
    "osd", "mon", "mgr", "ms", "crush", "ec", "objectstore", "client",
    "journal", "heartbeat", "paxos", "pg", "tools",
]

_root = logging.getLogger("ceph_tpu")
if not _root.handlers:
    h = logging.StreamHandler(sys.stderr)
    h.setFormatter(logging.Formatter(
        "%(asctime)s %(name)s %(levelname).1s %(message)s"))
    _root.addHandler(h)
    _root.setLevel(logging.DEBUG)
    _root.propagate = False


def get_logger(subsys: str) -> logging.Logger:
    return logging.getLogger(f"ceph_tpu.{subsys}")


def set_subsys_level(subsys: str, level: int) -> None:
    """Runtime level change (`config set debug_<subsys>` analog)."""
    with _lock:
        _levels[subsys] = level


def get_subsys_level(subsys: str) -> int:
    with _lock:
        return _levels.get(subsys, _DEFAULT_LEVEL)


def dout(subsys: str, level: int, msg: str, *args) -> None:
    """Gated debug output (dout/ldout semantics: emit iff level <= subsystem
    verbosity)."""
    if level <= get_subsys_level(subsys):
        get_logger(subsys).debug(msg, *args)
