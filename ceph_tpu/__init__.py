"""ceph_tpu — a TPU-native distributed-storage framework with the capabilities of Ceph.

Reference: sdpeters/ceph (nautilus-era), studied structurally in SURVEY.md. This is a
from-scratch, TPU-first design (JAX/XLA/Pallas for the numeric data path, Python/C++ for
the runtime shell), not a port.

Subpackages
-----------
gf        GF(2^8) algebra: tables, matrix generators, inversion (numpy oracle).
ops       JAX/Pallas device kernels: batched erasure encode/decode, rjenkins hash,
          crush_ln, straw2 selection.
ec        Erasure-code plugin framework mirroring the reference contract
          (src/erasure-code/ErasureCodeInterface.h:170-462): profiles, registry,
          chunk/stripe math, TPU + CPU-oracle plugins.
crush     CRUSH placement: map model, exact scalar oracle (crush/mapper.c semantics),
          batched JAX mapper for bulk PG remaps.
"""

# CRUSH straw2 fixed-point math needs 64-bit integers (crush/mapper.c uses __s64/__u64
# throughout); enable x64 before any jax array is created.  All kernels in this package
# use explicit dtypes, so the global default-dtype change is inert for them.
from jax import config as _jax_config

_jax_config.update("jax_enable_x64", True)

__version__ = "0.1.0"
