"""Batched GF(2^8) erasure-code kernels.

The reference's hot loop is ``ec_encode_data(blocksize, k, m, tbls, data, coding)``
(ISA-L, called from src/erasure-code/isa/ErasureCodeIsa.cc:118-130) — a GF(2^8)
matrix-vector product applied independently to every byte column of a stripe, which the
OSD invokes per 4-64 KiB stripe in a loop (src/osd/ECUtil.cc:120-159).  Here that whole
loop is one batched device call.

TPU-first design (not a translation): GF(2^8) multiplication by a constant is linear
over GF(2) in the bits of the input, so the coding matrix becomes a 0/1 matrix W of
shape (k*32, m*8) (see ceph_tpu.gf.tables.nibble_bit_table) and encoding becomes

    parity_bits = one_hot(nibbles(data)) @ W  (mod 2)

— a single (S*B, k*32) x (k*32, m*8) matrix multiply that runs on the MXU, followed by
a bit-pack.  No gathers, no scalar loops, static shapes; XLA fuses the nibble one-hot
expansion and the bit-pack into the matmul's prologue/epilogue.

Decode is the same kernel with a host-side inverted sub-matrix (tiny, k x k), exactly
mirroring the reference's decode structure (ErasureCodeIsa.cc:150-310).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ceph_tpu.gf.tables import mul_table, nibble_bit_table


# ---------------------------------------------------------------------------
# numpy oracle — ground truth for bit-exactness tests and the CPU plugin
# ---------------------------------------------------------------------------

def ec_encode_ref(coeff: np.ndarray, data: np.ndarray) -> np.ndarray:
    """Reference GF(2^8) encode on host.

    coeff : (m, k) uint8 coding matrix
    data  : (..., k, B) uint8 data chunks
    returns (..., m, B) uint8 parity chunks
    """
    coeff = np.asarray(coeff, dtype=np.uint8)
    data = np.asarray(data, dtype=np.uint8)
    mt = mul_table()
    # prods[..., i, j, b] = coeff[i, j] * data[..., j, b]
    prods = mt[coeff[..., :, :, None], data[..., None, :, :]]
    return np.bitwise_xor.reduce(prods, axis=-2)


# ---------------------------------------------------------------------------
# JAX kernel
# ---------------------------------------------------------------------------

_BIT_WEIGHTS = np.arange(8, dtype=np.int32)

# Byte-rows of the one-hot matmul processed per tile.  The one-hot expansion is k*32
# values per source byte, so an unbounded batch would inflate HBM ~64x (observed: a
# 128 MiB encode tried to materialize 24 GiB).  Tiling keeps the expansion resident in
# VMEM-scale working sets while the batch dimension streams.
_TILE_ROWS = 1 << 15


def _encode_tile(w_bits: jax.Array, x: jax.Array, k: int, m: int,
                 dot_dtype) -> jax.Array:
    """x: (T, k) uint8 byte rows -> (T, m) uint8 parity bytes."""
    t = x.shape[0]
    nib = jnp.concatenate([x & 0xF, (x >> 4) + 16], axis=-1)  # (T, 2k) in [0,32)
    # One-hot against the 32 nibble rows of each data chunk.  Row layout of w_bits is
    # (j, p, n): rows j*32..j*32+15 are chunk j's low-nibble values, +16..+31 high.
    # The lo column one-hot occupies positions 0..15 and the (biased) hi column 16..31,
    # so their sum is chunk j's combined 32-slot indicator with exactly two ones.
    iota = jnp.arange(32, dtype=nib.dtype)
    oh = (nib[:, :, None] == iota[None, None, :]).astype(dot_dtype)  # (T, 2k, 32)
    oh = (oh[:, :k, :] + oh[:, k:, :]).reshape(t, k * 32)
    acc = jax.lax.dot_general(
        oh, w_bits.astype(dot_dtype),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32 if dot_dtype == jnp.bfloat16 else jnp.int32,
    )
    bits = acc.astype(jnp.int32) & 1  # (T, m*8)
    return jnp.sum(bits.reshape(t, m, 8) << _BIT_WEIGHTS, axis=-1).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("k", "m", "dot_dtype"))
def _encode_impl(w_bits: jax.Array, data: jax.Array, *, k: int, m: int,
                 dot_dtype: jnp.dtype) -> jax.Array:
    """data: (S, k, B) uint8 -> parity (S, m, B) uint8."""
    s, _, b = data.shape
    x = jnp.transpose(data, (0, 2, 1)).reshape(s * b, k)  # (SB, k)
    rows = s * b
    if rows <= _TILE_ROWS:
        packed = _encode_tile(w_bits, x, k, m, dot_dtype)
    else:
        pad = (-rows) % _TILE_ROWS
        if pad:
            x = jnp.concatenate([x, jnp.zeros((pad, k), dtype=x.dtype)])
        tiles = x.reshape(-1, _TILE_ROWS, k)
        packed = jax.lax.map(
            lambda xt: _encode_tile(w_bits, xt, k, m, dot_dtype), tiles
        ).reshape(-1, m)[:rows]
    return jnp.transpose(packed.reshape(s, b, m), (0, 2, 1)).astype(jnp.uint8)


def ec_encode_jax(coeff: np.ndarray, data, dot_dtype=jnp.bfloat16) -> jax.Array:
    """One-shot encode (builds the bit table each call; use make_encoder for reuse)."""
    coeff = np.asarray(coeff, dtype=np.uint8)
    m, k = coeff.shape
    w = jnp.asarray(nibble_bit_table(coeff))
    data = jnp.asarray(data, dtype=jnp.uint8)
    squeeze = data.ndim == 2
    if squeeze:
        data = data[None]
    out = _encode_impl(w, data, k=k, m=m, dot_dtype=dot_dtype)
    return out[0] if squeeze else out


def make_encoder(coeff: np.ndarray, dot_dtype=jnp.bfloat16):
    """Return a jitted encode(data (S,k,B) uint8) -> (S,m,B) with the table resident."""
    coeff = np.asarray(coeff, dtype=np.uint8)
    m, k = coeff.shape
    w = jax.device_put(jnp.asarray(nibble_bit_table(coeff)))

    def encode(data):
        return _encode_impl(w, jnp.asarray(data, dtype=jnp.uint8),
                            k=k, m=m, dot_dtype=dot_dtype)

    return encode
