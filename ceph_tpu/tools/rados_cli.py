"""`rados` command-line tool (src/tools/rados/rados.cc analog): direct
object operations against a pool — the lowest-level operator surface.

    python -m ceph_tpu.tools.rados_cli --mon <host> -p <pool> <command>

Commands (the rados verbs they mirror):
    put OBJ FILE | get OBJ FILE | rm OBJ
    ls | stat OBJ
    listomapvals OBJ | setomapval OBJ KEY VALUE | rmomapkey OBJ KEY
    df                 (per-pool usage from the mgr's aggregates)
    bench ...          -> use ceph_tpu.tools.rados_bench (obj_bencher)
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="rados")
    p.add_argument("--mon", required=True, help="mon host(s)")
    p.add_argument("-p", "--pool", type=int, required=True)
    p.add_argument("--ms-type", default="async")
    p.add_argument("--auth-key", default="",
                   help="cluster shared key (authenticated clusters)")
    p.add_argument("words", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)
    if not args.words:
        p.error("missing command")

    from ceph_tpu.client import RadosClient
    client = RadosClient(args.mon, ms_type=args.ms_type,
                         auth_key=args.auth_key.encode()
                         if args.auth_key else None)
    client.connect()
    io = client.open_ioctx(args.pool)
    w = args.words
    try:
        cmd = w[0]
        if cmd == "put":
            with open(w[2], "rb") as f:
                io.write_full(w[1], f.read())
            return 0
        if cmd == "get":
            st = io.stat(w[1])
            data = io.read(w[1], st["size"])
            if w[2] == "-":
                sys.stdout.buffer.write(data)
            else:
                with open(w[2], "wb") as f:
                    f.write(data)
            return 0
        if cmd == "rm":
            io.remove(w[1])
            return 0
        if cmd == "ls":
            for oid in sorted(io.list_objects()):
                print(oid)
            return 0
        if cmd == "stat":
            st = io.stat(w[1])
            print(f"{w[1]} size {st['size']}")
            return 0
        if cmd == "listomapvals":
            for k, v in sorted(io.get_omap(w[1]).items()):
                print(f"{k}\t{v!r}")
            return 0
        if cmd == "setomapval":
            io.set_omap(w[1], {w[2]: w[3].encode()})
            return 0
        if cmd == "rmomapkey":
            io.rm_omap_keys(w[1], [w[2]])
            return 0
        if cmd == "df":
            import json
            res, out = client.mgr_command({"prefix": "pg dump"})
            if res != 0:
                print(f"rados: mgr unavailable: {out}", file=sys.stderr)
                return 1
            dump = json.loads(out)
            per_pool: dict[int, list[int]] = {}
            for row in dump["pg_stats"]:
                pid = int(row["pgid"].split(".")[0])
                agg = per_pool.setdefault(pid, [0, 0, 0])
                agg[0] += 1
                agg[1] += int(row.get("num_objects", 0))
                agg[2] += int(row.get("bytes", 0))
            print("POOL\tPGS\tOBJECTS\tBYTES")
            for pid in sorted(per_pool):
                pgs, objs, byts = per_pool[pid]
                print(f"{pid}\t{pgs}\t{objs}\t{byts}")
            return 0
        raise SystemExit(f"unknown rados command {cmd!r}")
    except IndexError:
        print(f"rados: missing operand for {w[0]!r}", file=sys.stderr)
        return 2
    except OSError as e:
        print(f"rados: {e}", file=sys.stderr)
        return 1
    finally:
        client.shutdown()


if __name__ == "__main__":
    sys.exit(main())
