"""RGW depth tier: S3 object versioning (delete markers, versionId ops,
suspended null versions, ListObjectVersions), lifecycle expiration with a
test clock (rgw_lc.cc analog), and canned-ACL enforcement on the REST
path (rgw_acl.cc reduced) — real HTTP with SigV4 against a MiniCluster."""

from __future__ import annotations

import hashlib
import http.client
import re
import time

import pytest

from ceph_tpu.rgw_rest import RgwRestServer, sign_request
from ceph_tpu.tools.vstart import MiniCluster

AUTH_KEY = b"rgw-version-secret"


class S3Client:
    def __init__(self, addr: str, access: str | None,
                 secret: str | None = None):
        self.host, port = addr.rsplit(":", 1)
        self.port = int(port)
        self.access = access
        self.secret = secret

    def request(self, method: str, path: str, query: str = "",
                body: bytes = b"", headers_extra: dict | None = None):
        payload_sha = hashlib.sha256(body).hexdigest()
        amzdate = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
        headers = {"Host": f"{self.host}:{self.port}",
                   "x-amz-date": amzdate,
                   "x-amz-content-sha256": payload_sha}
        if self.access is not None:
            headers["Authorization"] = sign_request(
                method, path, query,
                {"host": headers["Host"], "x-amz-date": amzdate,
                 "x-amz-content-sha256": payload_sha},
                payload_sha, self.access, self.secret)
        headers.update(headers_extra or {})
        conn = http.client.HTTPConnection(self.host, self.port, timeout=30)
        conn.request(method, path + (f"?{query}" if query else ""),
                     body=body, headers=headers)
        resp = conn.getresponse()
        data = resp.read()
        out = (resp.status, data, dict(resp.getheaders()))
        conn.close()
        return out


class FakeClock:
    def __init__(self):
        self.t = 1_700_000_000.0

    def __call__(self):
        return self.t


@pytest.fixture(scope="module")
def rig():
    c = MiniCluster(n_osds=3, auth_key=AUTH_KEY).start()
    c.wait_for_osd_count(3)
    client = c.client()
    pool = c.create_pool(client, pg_num=8, size=2)
    io = client.open_ioctx(pool)
    clock = FakeClock()
    srv = RgwRestServer(io, max_skew=None, clock=clock).start()
    access, secret = srv.provision_from_cephx(AUTH_KEY)
    srv.add_key("AKOTHERUSER000000000", "other-secret")
    yield {
        "owner": S3Client(srv.addr, access, secret),
        "other": S3Client(srv.addr, "AKOTHERUSER000000000",
                          "other-secret"),
        "anon": S3Client(srv.addr, None),
        "srv": srv, "clock": clock,
    }
    srv.shutdown()
    c.stop()


# -- versioning --------------------------------------------------------------

def test_versioned_put_get_delete_cycle(rig):
    s3 = rig["owner"]
    assert s3.request("PUT", "/ver")[0] == 200
    # default state: no Status element
    st, body, _ = s3.request("GET", "/ver", "versioning")
    assert st == 200 and b"<Status>" not in body
    st, _, _ = s3.request(
        "PUT", "/ver", "versioning",
        body=b"<VersioningConfiguration><Status>Enabled</Status>"
             b"</VersioningConfiguration>")
    assert st == 200
    st, body, _ = s3.request("GET", "/ver", "versioning")
    assert b"<Status>Enabled</Status>" in body

    st, _, h1 = s3.request("PUT", "/ver/doc", body=b"v1 content")
    assert st == 200
    v1 = h1["x-amz-version-id"]
    rig["clock"].t += 1
    st, _, h2 = s3.request("PUT", "/ver/doc", body=b"v2 content")
    v2 = h2["x-amz-version-id"]
    assert v1 != v2

    # latest wins; explicit versionId reaches back
    assert s3.request("GET", "/ver/doc")[1] == b"v2 content"
    st, got, gh = s3.request("GET", "/ver/doc", f"versionId={v1}")
    assert st == 200 and got == b"v1 content"
    assert gh["x-amz-version-id"] == v1

    # unversioned DELETE lays a delete marker; GET now 404s
    rig["clock"].t += 1
    st, _, dh = s3.request("DELETE", "/ver/doc")
    assert st == 204 and dh.get("x-amz-delete-marker") == "true"
    marker_vid = dh["x-amz-version-id"]
    assert s3.request("GET", "/ver/doc")[0] == 404
    # old versions still reachable
    assert s3.request("GET", "/ver/doc",
                      f"versionId={v2}")[1] == b"v2 content"

    # removing the marker by versionId restores the object (S3 undelete)
    st, _, _ = s3.request("DELETE", "/ver/doc",
                          f"versionId={marker_vid}")
    assert st == 204
    assert s3.request("GET", "/ver/doc")[1] == b"v2 content"

    # permanently removing v2 repoints current to v1
    assert s3.request("DELETE", "/ver/doc", f"versionId={v2}")[0] == 204
    assert s3.request("GET", "/ver/doc")[1] == b"v1 content"


def test_list_versions_markers_and_pagination(rig):
    s3 = rig["owner"]
    assert s3.request("PUT", "/lv")[0] == 200
    s3.request("PUT", "/lv", "versioning",
               body=b"<VersioningConfiguration><Status>Enabled</Status>"
                    b"</VersioningConfiguration>")
    for i in range(3):
        rig["clock"].t += 1
        s3.request("PUT", "/lv/a", body=f"a{i}".encode())
    rig["clock"].t += 1
    s3.request("PUT", "/lv/b", body=b"b0")
    rig["clock"].t += 1
    s3.request("DELETE", "/lv/a")    # marker on a

    st, body, _ = s3.request("GET", "/lv", "versions")
    assert st == 200
    text = body.decode()
    assert text.count("<Version>") == 4        # 3x a + 1x b
    assert text.count("<DeleteMarker>") == 1
    # newest 'a' row is the marker and IsLatest
    first = re.search(r"<(Version|DeleteMarker)>.*?</\1>", text, re.S)
    assert first.group(1) == "DeleteMarker"
    assert "<IsLatest>true</IsLatest>" in first.group(0)

    # pagination walks every row exactly once
    seen = 0
    km = vm = ""
    for _ in range(10):
        q = "versions&max-keys=2" + (
            f"&key-marker={km}&version-id-marker={vm}" if km else "")
        st, body, _ = s3.request("GET", "/lv", q)
        text = body.decode()
        seen += len(re.findall(r"<(?:Version|DeleteMarker)>", text))
        m = re.search(r"<NextKeyMarker>(.*?)</NextKeyMarker>", text)
        if not m:
            break
        km = m.group(1)
        vm = re.search(r"<NextVersionIdMarker>(.*?)"
                       r"</NextVersionIdMarker>", text).group(1)
    assert seen == 5


def test_suspended_null_versions(rig):
    s3 = rig["owner"]
    assert s3.request("PUT", "/susp")[0] == 200
    s3.request("PUT", "/susp", "versioning",
               body=b"<VersioningConfiguration><Status>Enabled</Status>"
                    b"</VersioningConfiguration>")
    rig["clock"].t += 1
    st, _, h = s3.request("PUT", "/susp/o", body=b"real-version")
    real_vid = h["x-amz-version-id"]
    s3.request("PUT", "/susp", "versioning",
               body=b"<VersioningConfiguration><Status>Suspended</Status>"
                    b"</VersioningConfiguration>")
    # suspended puts write THE null version, replacing each other
    rig["clock"].t += 1
    st, _, h = s3.request("PUT", "/susp/o", body=b"null-1")
    assert h["x-amz-version-id"] == "null"
    rig["clock"].t += 1
    s3.request("PUT", "/susp/o", body=b"null-2")
    assert s3.request("GET", "/susp/o")[1] == b"null-2"
    # the Enabled-era version survives
    assert s3.request("GET", "/susp/o",
                      f"versionId={real_vid}")[1] == b"real-version"
    st, body, _ = s3.request("GET", "/susp", "versions")
    assert body.decode().count("<Version>") == 2   # null + real


# -- lifecycle ---------------------------------------------------------------

LC_XML = (b"<LifecycleConfiguration><Rule><ID>exp</ID>"
          b"<Prefix>logs/</Prefix><Status>Enabled</Status>"
          b"<Expiration><Days>7</Days></Expiration></Rule>"
          b"<Rule><ID>nc</ID><Prefix></Prefix><Status>Enabled</Status>"
          b"<NoncurrentVersionExpiration><NoncurrentDays>3"
          b"</NoncurrentDays></NoncurrentVersionExpiration></Rule>"
          b"</LifecycleConfiguration>")


def test_lifecycle_roundtrip_and_expiration(rig):
    s3, srv, clock = rig["owner"], rig["srv"], rig["clock"]
    assert s3.request("PUT", "/lc")[0] == 200
    assert s3.request("GET", "/lc", "lifecycle")[0] == 404
    assert s3.request("PUT", "/lc", "lifecycle", body=LC_XML)[0] == 200
    st, body, _ = s3.request("GET", "/lc", "lifecycle")
    assert st == 200 and b"<Days>7</Days>" in body

    s3.request("PUT", "/lc/logs/old.log", body=b"ancient")
    s3.request("PUT", "/lc/logs/new.log", body=b"recent")
    s3.request("PUT", "/lc/keep.txt", body=b"not under prefix")
    # age only old.log past 7 days: rewrite new.log later
    clock.t += 8 * 86400
    s3.request("PUT", "/lc/logs/new.log", body=b"recent-again")
    stats = srv.gateway.lifecycle_pass()
    assert stats["expired"] == 1, stats
    assert s3.request("GET", "/lc/logs/old.log")[0] == 404
    assert s3.request("GET", "/lc/logs/new.log")[0] == 200
    assert s3.request("GET", "/lc/keep.txt")[0] == 200

    st, _, _ = s3.request("DELETE", "/lc", "lifecycle")
    assert st == 204
    assert s3.request("GET", "/lc", "lifecycle")[0] == 404


def test_lifecycle_versioned_noncurrent_expiry(rig):
    s3, srv, clock = rig["owner"], rig["srv"], rig["clock"]
    assert s3.request("PUT", "/lcv")[0] == 200
    s3.request("PUT", "/lcv", "versioning",
               body=b"<VersioningConfiguration><Status>Enabled</Status>"
                    b"</VersioningConfiguration>")
    s3.request("PUT", "/lcv", "lifecycle", body=LC_XML)
    s3.request("PUT", "/lcv/doc", body=b"gen1")
    clock.t += 1
    s3.request("PUT", "/lcv/doc", body=b"gen2")
    clock.t += 4 * 86400       # gen1 is now >3 days noncurrent
    s3.request("PUT", "/lcv/doc", body=b"gen3")
    stats = srv.gateway.lifecycle_pass()
    assert stats["noncurrent_removed"] >= 1, stats
    st, body, _ = s3.request("GET", "/lcv", "versions&prefix=doc")
    text = body.decode()
    assert "gen1" not in text   # sanity (content not listed anyway)
    assert text.count("<Version>") == 2       # gen2 + gen3 survive
    assert s3.request("GET", "/lcv/doc")[1] == b"gen3"

    # expiration of a CURRENT object in a versioned bucket lays a marker
    clock.t += 8 * 86400
    s3.request("PUT", "/lcv/logs/x", body=b"expire me")
    clock.t += 8 * 86400
    stats = srv.gateway.lifecycle_pass()
    assert stats["expired"] >= 1
    assert s3.request("GET", "/lcv/logs/x")[0] == 404
    st, body, _ = s3.request("GET", "/lcv", "versions&prefix=logs/x")
    assert b"<DeleteMarker>" in body          # data survives as version


# -- ACLs --------------------------------------------------------------------

def test_canned_acl_enforcement(rig):
    owner, other, anon = rig["owner"], rig["other"], rig["anon"]
    assert owner.request("PUT", "/private-b")[0] == 200
    owner.request("PUT", "/private-b/secret.txt", body=b"mine")

    # private: non-owner and anonymous both denied
    assert other.request("GET", "/private-b/secret.txt")[0] == 403
    assert anon.request("GET", "/private-b/secret.txt")[0] == 403
    assert owner.request("GET", "/private-b/secret.txt")[0] == 200

    # public-read: everyone reads, nobody but owner writes
    assert owner.request("PUT", "/pub-b", headers_extra={
        "x-amz-acl": "public-read"})[0] == 200
    owner.request("PUT", "/pub-b/page.html", body=b"<html/>")
    assert anon.request("GET", "/pub-b/page.html")[1] == b"<html/>"
    assert other.request("GET", "/pub-b/page.html")[0] == 200
    assert anon.request("PUT", "/pub-b/inject", body=b"x")[0] == 403
    assert other.request("PUT", "/pub-b/inject", body=b"x")[0] == 403

    # authenticated-read: signed users read, anonymous denied
    assert owner.request("PUT", "/auth-b", headers_extra={
        "x-amz-acl": "authenticated-read"})[0] == 200
    owner.request("PUT", "/auth-b/o", body=b"data")
    assert other.request("GET", "/auth-b/o")[0] == 200
    assert anon.request("GET", "/auth-b/o")[0] == 403

    # public-read-write: anyone writes
    assert owner.request("PUT", "/prw-b", headers_extra={
        "x-amz-acl": "public-read-write"})[0] == 200
    assert anon.request("PUT", "/prw-b/drop.txt", body=b"anon")[0] == 200
    assert anon.request("GET", "/prw-b/drop.txt")[1] == b"anon"

    # ACL flip via PUT ?acl, owner-only
    assert other.request("PUT", "/private-b", "acl", headers_extra={
        "x-amz-acl": "public-read"})[0] == 403
    assert owner.request("PUT", "/private-b", "acl", headers_extra={
        "x-amz-acl": "public-read"})[0] == 200
    assert anon.request("GET", "/private-b/secret.txt")[0] == 200
    st, body, _ = owner.request("GET", "/private-b", "acl")
    # the canned ACL reads back as its expanded grant list (real S3
    # AccessControlPolicy shape): AllUsers READ + owner FULL_CONTROL
    assert st == 200 and b"AllUsers" in body and b">READ<" in body \
        and b"FULL_CONTROL" in body

    # bucket config stays owner-only: versioning flip by other = denied
    assert other.request(
        "PUT", "/private-b", "versioning",
        body=b"<VersioningConfiguration><Status>Enabled</Status>"
             b"</VersioningConfiguration>")[0] == 403
    # anonymous bucket creation denied
    assert anon.request("PUT", "/anon-b")[0] == 403


def test_preversioning_object_survives_as_null_version(rig):
    # S3: an object written BEFORE versioning was enabled remains
    # addressable as versionId=null after versioned ops bury it
    s3 = rig["owner"]
    assert s3.request("PUT", "/pv")[0] == 200
    s3.request("PUT", "/pv/relic", body=b"pre-versioning")
    s3.request("PUT", "/pv", "versioning",
               body=b"<VersioningConfiguration><Status>Enabled</Status>"
                    b"</VersioningConfiguration>")
    rig["clock"].t += 1
    s3.request("PUT", "/pv/relic", body=b"versioned-gen")
    assert s3.request("GET", "/pv/relic")[1] == b"versioned-gen"
    st, got, _ = s3.request("GET", "/pv/relic", "versionId=null")
    assert st == 200 and got == b"pre-versioning"
    # marker over it also preserves the null version
    s3.request("PUT", "/pv/relic2-pre", body=b"keepme")
    # (relic2-pre was created AFTER enabling; use a fresh pre-versioned
    # object in a second bucket for the delete-marker variant)
    assert s3.request("PUT", "/pv2")[0] == 200
    s3.request("PUT", "/pv2/x", body=b"old")
    s3.request("PUT", "/pv2", "versioning",
               body=b"<VersioningConfiguration><Status>Enabled</Status>"
                    b"</VersioningConfiguration>")
    rig["clock"].t += 1
    s3.request("DELETE", "/pv2/x")
    assert s3.request("GET", "/pv2/x")[0] == 404
    assert s3.request("GET", "/pv2/x", "versionId=null")[1] == b"old"


def test_at_sign_keys_and_control_char_rejection(rig):
    # "@" is a legal S3 key char and must not collide with internal
    # version/data separators; C0 control chars are rejected
    s3 = rig["owner"]
    assert s3.request("PUT", "/atb")[0] == 200
    s3.request("PUT", "/atb", "versioning",
               body=b"<VersioningConfiguration><Status>Suspended</Status>"
                    b"</VersioningConfiguration>")
    s3.request("PUT", "/atb/k@null", body=b"at-key-object")
    rig["clock"].t += 1
    s3.request("PUT", "/atb/k", body=b"plain-k")
    assert s3.request("GET", "/atb/k@null")[1] == b"at-key-object"
    assert s3.request("GET", "/atb/k")[1] == b"plain-k"
    st, _, _ = s3.request("PUT", "/atb/bad%00key", body=b"x")
    assert st == 400


def test_bucket_subresource_delete_does_not_delete_bucket(rig):
    s3 = rig["owner"]
    assert s3.request("PUT", "/subres")[0] == 200
    assert s3.request("DELETE", "/subres", "versioning")[0] == 400
    assert s3.request("DELETE", "/subres", "acl")[0] == 400
    # bucket still exists
    assert s3.request("GET", "/subres")[0] == 200
