"""Mgr introspection depth (DaemonServer / mgr-module analogs):
`pg dump` and `pg ls` built from the per-PG records in MMgrReport v2,
the iostat rate module, and balancer status — each checked against the
OSDs' own truth on a live cluster."""

from __future__ import annotations

import time

from ceph_tpu.mgr import MMgrReport
from ceph_tpu.msg.encoding import Decoder, Encoder
from ceph_tpu.tools.vstart import MiniCluster


def _wait_pg_rows(mgr, want_pgs: int, timeout: float = 15.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        dump = mgr.pg_dump()
        if dump["num_pgs"] >= want_pgs \
                and all(r["state"] == "active"
                        for r in dump["pg_stats"]):
            return dump
        time.sleep(0.2)
    return mgr.pg_dump()


def test_mgr_report_v2_roundtrip_and_v1_compat():
    # v2 round-trip carries pg_stats; a v1 payload (no pg_stats field)
    # still decodes — rolling-upgrade shape
    rep = MMgrReport(osd_id=3, counters={"op_w": 7},
                     pg_states={"active": 2}, num_objects=5,
                     bytes_used=1024,
                     pg_stats={"1.0": {"state": "active", "up": [0, 1],
                                       "num_objects": 4, "bytes": 99,
                                       "missing": 0, "log_size": 6,
                                       "log_head": (3, 6),
                                       "log_tail": (1, 1)}})
    enc = Encoder()
    rep.encode_payload(enc)
    back = MMgrReport()
    back.decode_payload(Decoder(enc.tobytes()), 0)
    assert back.pg_stats["1.0"]["log_head"] == (3, 6)
    assert back.pg_stats["1.0"]["up"] == [0, 1]

    # hand-build a v1 body: same fields minus pg_stats
    v1 = Encoder()
    v1.versioned(1, 1, lambda e: (
        e.s32(9),
        e.map({"op_w": 1}, lambda e2, k: e2.str(k),
              lambda e2, v: e2.u64(v)),
        e.map({"active": 1}, lambda e2, k: e2.str(k),
              lambda e2, v: e2.u32(v)),
        e.u64(2), e.u64(3)))
    old = MMgrReport()
    old.decode_payload(Decoder(v1.tobytes()), 0)
    assert old.osd_id == 9 and old.pg_stats == {}


def test_pg_dump_matches_osd_truth():
    c = MiniCluster(n_osds=3, ms_type="loopback").start()
    try:
        c.run_mgr()
        for oid in list(c.osds):
            c.kill_osd(oid)
            c.run_osd(oid)
        c.wait_for_osd_count(3)
        client = c.client(timeout=20.0)
        pool = c.create_pool(client, pg_num=8, size=2)
        io = client.open_ioctx(pool)
        for i in range(24):
            io.write_full(f"obj-{i}", b"x" * (100 + i))
        dump = _wait_pg_rows(c.mgr, 8)
        rows = {r["pgid"]: r for r in dump["pg_stats"]
                if r["pgid"].startswith(f"{pool}.")}
        assert len(rows) == 8, sorted(rows)

        # cross-check each row against the reporting OSD's own PG
        total_objs = 0
        for pgid_s, row in rows.items():
            pgid = tuple(int(x) for x in pgid_s.split("."))
            osd = c.osds[row["reported_by"]]
            pg = osd.pgs[pgid]
            assert row["state"] == "active"
            assert row["up"] == list(pg.up), (pgid_s, row)
            assert row["log_head"] == tuple(pg.log.head)
            assert row["log_size"] == len(pg.log.entries)
            total_objs += row["num_objects"]
        assert total_objs == 24, total_objs

        # pg ls filters
        ls = c.mgr.pg_ls(pool=pool)
        assert len(ls) == 8
        assert c.mgr.pg_ls(pool=pool, states=["inactive"]) == []
        assert len(c.mgr.pg_ls(pool=pool, states=["active"])) == 8
    finally:
        c.stop()


def test_iostat_and_balancer_status():
    c = MiniCluster(n_osds=3, ms_type="loopback").start()
    try:
        c.run_mgr()
        for oid in list(c.osds):
            c.kill_osd(oid)
            c.run_osd(oid)
        c.wait_for_osd_count(3)
        client = c.client(timeout=20.0)
        pool = c.create_pool(client, pg_num=8, size=2)
        io = client.open_ioctx(pool)
        # sustained writes across two report intervals so rates show
        deadline = time.time() + 12
        i = 0
        while time.time() < deadline:
            io.write_full(f"w-{i % 50}", b"io" * 100)
            i += 1
            st = c.mgr.iostat()
            if st["osds"] and st["total_wr_ops_s"] > 0:
                break
            time.sleep(0.05)
        st = c.mgr.iostat()
        assert st["total_wr_ops_s"] > 0, st
        assert all(v["interval_s"] > 0 for v in st["osds"].values())
        # `ceph df` routes through the mgr tier like pg dump
        import json as _json
        rc, out = client.mgr_command({"prefix": "df"})
        assert rc == 0
        d = _json.loads(out)
        assert d["total_objects"] >= 1 and d["per_osd"]

        bs = c.mgr.balancer_status()
        assert bs["mode"] == "upmap"
        assert pool in bs["pool_spread"]
        lo = bs["pool_spread"][pool]["min"]
        hi = bs["pool_spread"][pool]["max"]
        assert 0 <= lo <= hi
        c.mgr.balance_plan()
        assert "commands" in c.mgr.balancer_status()["last_optimize"]
    finally:
        c.stop()


def test_mgr_command_routing_and_telemetry():
    # client discovers the active mgr via the mon (`mgr dump`) and
    # re-targets mgr-tier commands at it, like the reference routing
    c = MiniCluster(n_osds=3, ms_type="loopback").start()
    try:
        c.run_mgr()
        for oid in list(c.osds):
            c.kill_osd(oid)
            c.run_osd(oid)
        c.wait_for_osd_count(3)
        client = c.client(timeout=20.0)
        pool = c.create_pool(client, pg_num=4, size=2)
        io = client.open_ioctx(pool)
        for i in range(8):
            io.write_full(f"t-{i}", b"telemetry" * 10)
        import json as _json
        deadline = time.time() + 15
        while time.time() < deadline:
            rc, out = client.mon_command({"prefix": "mgr dump"})
            if rc == 0 and _json.loads(out).get("addr"):
                break
            time.sleep(0.3)
        rc, out = client.mgr_command({"prefix": "pg dump"})
        assert rc == 0, out
        dump = _json.loads(out)
        assert dump["num_pgs"] >= 0 and "pg_stats" in dump
        rc, out = client.mgr_command({"prefix": "balancer status"})
        assert rc == 0 and _json.loads(out)["mode"] == "upmap"
        rc, out = client.mgr_command({"prefix": "telemetry show"})
        assert rc == 0, out
        rep = _json.loads(out)
        assert rep["osd"]["count"] == 3
        assert rep["health"] in ("HEALTH_OK", "HEALTH_WARN")
        # no object names anywhere in the anonymized payload
        assert "t-0" not in out
        rc, out = client.mgr_command({"prefix": "bogus"})
        assert rc == -22
    finally:
        c.stop()


def test_mgr_standby_failover():
    # MgrMap reduced: the mon publishes the active mgr in the map; when
    # it dies, a standby is promoted and OSD reports + client commands
    # re-target without restarts
    c = MiniCluster(n_osds=2, ms_type="async").start()
    try:
        c.run_mgr(0)
        c.run_mgr(1)            # standby
        for oid in list(c.osds):
            c.kill_osd(oid)
            c.run_osd(oid)
        c.wait_for_osd_count(2)
        client = c.client(timeout=20.0)
        pool = c.create_pool(client, pg_num=4, size=2)
        io = client.open_ioctx(pool)
        import json as _json
        # active published in the map and serving reports
        deadline = time.time() + 20
        while time.time() < deadline:
            io.write_full("fo", b"x")
            rc, out = client.mon_command({"prefix": "mgr dump"})
            if rc == 0 and _json.loads(out).get("active_name") == "mgr.0" \
                    and c.mgrs[0].reports:
                break
            time.sleep(0.3)
        assert c.mgrs[0].reports, "active mgr never got reports"

        standby = c.mgrs[1]
        c.kill_mgr(0)
        # the mon must promote mgr.1 and OSD reports must land there
        deadline = time.time() + 30
        while time.time() < deadline:
            io.write_full("fo2", b"y")
            rc, out = client.mon_command({"prefix": "mgr dump"})
            if rc == 0 and _json.loads(out).get("active_name") == "mgr.1" \
                    and standby.reports:
                break
            time.sleep(0.5)
        rc, out = client.mon_command({"prefix": "mgr dump"})
        assert _json.loads(out).get("active_name") == "mgr.1", out
        assert standby.reports, "standby never received OSD reports"
        # and mgr-tier commands flow to the new active
        rc, out = client.mgr_command({"prefix": "pg dump"})
        assert rc == 0, out
    finally:
        c.stop()
