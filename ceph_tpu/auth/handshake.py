"""Handshake-side cephx logic shared by the wire messenger stacks.

Wire auth modes (one byte in the connection handshake):

  AUTH_NONE          no authentication
  AUTH_CEPHX         legacy shared-cluster-key HMAC challenge
  AUTH_CEPHX_TICKET  principal -> service: present a mon-granted ticket,
                     prove possession of its derived session key
  AUTH_CEPHX_ENTITY  principal -> mon: prove possession of the entity's
                     own secret (the mon holds every entity's key)

The effective mode of a connection is the INITIATOR's mode; the
acceptor adapts (it learns the mode before any credential bytes).  Both
directions authenticate: the acceptor proves it holds the same session
key (ticket mode) or the same entity secret (entity mode) — a fake mon
or fake OSD fails the reverse proof.
"""

from __future__ import annotations

import hashlib
import hmac

from ceph_tpu.auth.cephx import Ticket, validate_ticket

AUTH_NONE = 0
AUTH_CEPHX = 1
AUTH_CEPHX_TICKET = 2
AUTH_CEPHX_ENTITY = 3


class CephxConfig:
    """Per-messenger cephx configuration (set_auth_cephx)."""

    def __init__(self, entity: str = "", key: str | bytes = "",
                 keyring=None, service: str | None = None,
                 rotating=None, auth_lookup=None,
                 required: bool = True):
        self.entity = entity
        self.key = key.decode() if isinstance(key, bytes) else key
        #: TicketKeyring — initiator-side tickets for peer services
        self.keyring = keyring
        #: my service name + rotating-keys provider — acceptor side
        self.service = service
        self.rotating = rotating
        #: mon only: entity -> secret (the AuthMonitor table)
        self.auth_lookup = auth_lookup
        self.required = required

    def initiator_mode(self, peer_type: str) -> int:
        if peer_type == "mon":
            # to a mon: entity-secret proof (the mon knows every key)
            return AUTH_CEPHX_ENTITY if self.key else AUTH_NONE
        if self.keyring is not None:
            # to a service: mon-granted ticket (the mon itself carries
            # a self-granted one — it owns the key server)
            return AUTH_CEPHX_TICKET
        return AUTH_NONE

    def acceptor_mode(self) -> int:
        if self.auth_lookup is not None:
            return AUTH_CEPHX_ENTITY
        if self.rotating is not None:
            return AUTH_CEPHX_TICKET
        return AUTH_NONE


def proof(key: bytes, nonce: bytes, name: str) -> bytes:
    return hmac.new(key, nonce + name.encode(), hashlib.sha256).digest()


def entity_proof(secret: str, nonce: bytes, name: str) -> bytes:
    return proof(secret.encode(), nonce, name)


def ticket_for(cfg: CephxConfig, peer_type: str) -> Ticket | None:
    """Called from messenger threads: must never block on a mon round
    trip (the reply would need the very thread it blocks)."""
    if cfg.keyring is None:
        return None
    return cfg.keyring.get_nowait(peer_type)


def accept_ticket(cfg: CephxConfig,
                  blob: bytes) -> tuple[str, bytes] | None:
    """Acceptor: validate a presented ticket; returns (auth entity,
    session key) or None.  The AUTH identity comes from the ticket
    (e.g. "client.admin"), distinct from the transport-level messenger
    name (e.g. "client.4821") — exactly the reference's entity-name vs
    entity-instance split."""
    if cfg.rotating is None or cfg.service is None:
        return None
    return validate_ticket(blob, cfg.service, cfg.rotating())
