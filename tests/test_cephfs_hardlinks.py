"""CephFS hardlinks (CDentry.h:77-90 remote dentries + backtrace
re-homing): link() across directories, nlink accounting, unlinking the
primary re-homes the inode, data survives until the last link, journal
replay across an MDS crash, and cross-rank export of a directory
holding remote dentries."""

from __future__ import annotations

import time

import pytest

from ceph_tpu.cephfs import CephFS
from ceph_tpu.tools.vstart import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(n_osds=3, ms_type="loopback").start()
    c.wait_for_osd_count(3)
    client = c.client(timeout=20.0)
    meta = c.create_pool(client, pg_num=4, size=2)
    data = c.create_pool(client, pg_num=8, size=2)
    c.run_mds(meta, data)
    c._fs_pools = (meta, data)
    yield c
    c.stop()


@pytest.fixture
def fs(cluster):
    f = CephFS(cluster.mon_host, cluster.mds.addr, ms_type="loopback")
    f.mount()
    yield f
    f.unmount()


def test_link_across_directories_and_nlink(fs):
    fs.mkdir("/hl")
    fs.mkdir("/hl/a")
    fs.mkdir("/hl/b")
    with fs.open("/hl/a/orig.txt", "w") as f:
        f.write(b"one inode, two names")
    assert fs.stat("/hl/a/orig.txt")["nlink"] == 1
    inode = fs.link("/hl/a/orig.txt", "/hl/b/alias.txt")
    assert inode["nlink"] == 2
    # both names resolve to the SAME inode and data
    sa = fs.stat("/hl/a/orig.txt")
    sb = fs.stat("/hl/b/alias.txt")
    assert sa["ino"] == sb["ino"]
    assert sa["nlink"] == sb["nlink"] == 2
    with fs.open("/hl/b/alias.txt") as f:
        assert f.read() == b"one inode, two names"
    # a write through one name is visible through the other
    with fs.open("/hl/b/alias.txt", "w") as f:
        f.write(b"rewritten via alias!")
    with fs.open("/hl/a/orig.txt") as f:
        assert f.read() == b"rewritten via alias!"
    # directories cannot be hardlinked; duplicate names refused
    with pytest.raises(OSError):
        fs.link("/hl/a", "/hl/b/dir-link")
    with pytest.raises(OSError):
        fs.link("/hl/a/orig.txt", "/hl/b/alias.txt")
    # readdir shows both dentries
    assert "alias.txt" in fs.listdir("/hl/b")


def test_unlink_primary_rehomes_inode(fs):
    fs.mkdir("/rh")
    fs.mkdir("/rh/d1")
    fs.mkdir("/rh/d2")
    with fs.open("/rh/d1/primary", "w") as f:
        f.write(b"survives the primary unlink")
    fs.link("/rh/d1/primary", "/rh/d2/second")
    fs.link("/rh/d1/primary", "/rh/d2/third")
    assert fs.stat("/rh/d2/third")["nlink"] == 3
    # unlink the PRIMARY: the inode re-homes to a remote dentry
    fs.unlink("/rh/d1/primary")
    with pytest.raises(OSError):
        fs.stat("/rh/d1/primary")
    assert fs.stat("/rh/d2/second")["nlink"] == 2
    with fs.open("/rh/d2/second") as f:
        assert f.read() == b"survives the primary unlink"
    # drop the re-homed primary too: the LAST link still serves
    fs.unlink("/rh/d2/second")
    assert fs.stat("/rh/d2/third")["nlink"] == 1
    with fs.open("/rh/d2/third") as f:
        assert f.read() == b"survives the primary unlink"
    # last unlink drops inode + data
    ino = fs.stat("/rh/d2/third")["ino"]
    fs.unlink("/rh/d2/third")
    with pytest.raises(OSError):
        fs.stat("/rh/d2/third")
    from ceph_tpu.cephfs import _data_name
    from ceph_tpu.osdc.striper import StripedObject
    from ceph_tpu.cephfs import _LAYOUT
    assert StripedObject(fs.data_io, _data_name(ino),
                         _LAYOUT).size() == 0


def test_rename_of_remote_dentry_keeps_primary(fs):
    fs.mkdir("/rn")
    fs.mkdir("/rn/x")
    fs.mkdir("/rn/y")
    with fs.open("/rn/x/base", "w") as f:
        f.write(b"rename me by alias")
    fs.link("/rn/x/base", "/rn/y/alias")
    fs.rename("/rn/y/alias", "/rn/y/alias2")
    assert fs.stat("/rn/y/alias2")["nlink"] == 2
    # the primary is untouched: unlinking the renamed alias leaves it
    fs.unlink("/rn/y/alias2")
    assert fs.stat("/rn/x/base")["nlink"] == 1
    with fs.open("/rn/x/base") as f:
        assert f.read() == b"rename me by alias"


def test_rename_of_primary_keeps_link_accounting(fs):
    """Renaming the PRIMARY dentry (same dir or across dirs) moves the
    name only — it removes no link, so the re-home machinery must not
    fire and later unlinks must still resolve correctly."""
    fs.mkdir("/rp")
    fs.mkdir("/rp/d")
    with fs.open("/rp/d/a", "w") as f:
        f.write(b"primary rename")
    fs.link("/rp/d/a", "/rp/d/alias")
    # same-directory rename of the primary
    fs.rename("/rp/d/a", "/rp/d/b")
    assert fs.stat("/rp/d/b")["nlink"] == 2
    assert fs.stat("/rp/d/alias")["nlink"] == 2
    # unlink the renamed primary: re-home onto the alias, data intact
    fs.unlink("/rp/d/b")
    assert fs.stat("/rp/d/alias")["nlink"] == 1
    with fs.open("/rp/d/alias") as f:
        assert f.read() == b"primary rename"
    # and the last unlink really removes it
    fs.unlink("/rp/d/alias")
    with pytest.raises(OSError):
        fs.stat("/rp/d/alias")


def test_hardlinks_survive_mds_crash_replay(cluster, fs):
    fs.mkdir("/dur2")
    fs.mkdir("/dur2/p")
    fs.mkdir("/dur2/q")
    with fs.open("/dur2/p/file", "w") as f:
        f.write(b"journaled linkage")
    fs.link("/dur2/p/file", "/dur2/q/linked")
    fs.unlink("/dur2/p/file")     # re-home journaled too
    # crash + restart (suppress the flush so the JOURNAL must carry
    # the remote-link records)
    cluster.mds._flush_dirty = lambda: None
    cluster.mds.journal.trim = lambda *a, **k: None
    cluster.kill_mds()
    cluster.run_mds(*cluster._fs_pools)
    f2 = CephFS(cluster.mon_host, cluster.mds.addr, ms_type="loopback")
    f2.mount()
    try:
        st = f2.stat("/dur2/q/linked")
        assert st["nlink"] == 1
        with f2.open("/dur2/q/linked") as fh:
            assert fh.read() == b"journaled linkage"
    finally:
        f2.unmount()


def test_remote_dentries_cross_rank_export():
    c = MiniCluster(n_osds=3, ms_type="loopback").start()
    try:
        c.wait_for_osd_count(3)
        client = c.client(timeout=20.0)
        meta = c.create_pool(client, pg_num=4, size=2)
        data = c.create_pool(client, pg_num=8, size=2)
        rc, out = client.mon_command({
            "prefix": "fs new", "fs_name": "cephfs",
            "metadata": meta, "data": data})
        assert rc == 0, out
        rc, out = client.mon_command({"prefix": "fs set",
                                      "var": "max_mds", "val": 2})
        assert rc == 0, out
        c.run_fs_mds(2)
        deadline = time.time() + 15
        while time.time() < deadline:
            if len((client.osdmap.fs_db or {}).get("ranks", {})) == 2:
                break
            time.sleep(0.1)
        fs = CephFS(c.mon_host, ms_type="loopback", client_id=601)
        fs.mount()
        try:
            fs.mkdir("/exp")
            fs.mkdir("/exp/inner")
            fs.mkdir("/keep")
            with fs.open("/keep/target", "w") as f:
                f.write(b"primary stays on rank 0")
            fs.link("/keep/target", "/exp/inner/remote-name")
            # export the subtree HOLDING the remote dentry to rank 1;
            # the primary's home dir stays behind
            fs.export_dir("/exp", 1)
            st = fs.stat("/exp/inner/remote-name")
            assert st["nlink"] == 2
            with fs.open("/exp/inner/remote-name") as f:
                assert f.read() == b"primary stays on rank 0"
            # and the linkage still works both ways after the export
            fs.unlink("/keep/target")
            st = fs.stat("/exp/inner/remote-name")
            assert st["nlink"] == 1
            with fs.open("/exp/inner/remote-name") as f:
                assert f.read() == b"primary stays on rank 0"
        finally:
            fs.unmount()
    finally:
        c.stop()
