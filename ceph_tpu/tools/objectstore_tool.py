"""ceph-objectstore-tool analog: offline surgery on one OSD's store.

The reference tool (src/tools/ceph_objectstore_tool.cc) opens a stopped
OSD's ObjectStore and supports listing, PG info/log dumps, object byte
get/set, and PG export/import — the disaster-recovery path for moving a
PG off a dead OSD.  Same operation set here over the ObjectStore API:

    --op list                           collections + objects
    --op info     --pgid P.S            decoded pg info
    --op log      --pgid P.S            decoded pg log entries
    --op export   --pgid P.S --file F   PG -> portable blob
    --op import   --file F              blob -> this store
    --op get-bytes --pgid P.S --oid O   object data to stdout
    --op rm-object --pgid P.S --oid O

Usage: python -m ceph_tpu.tools.objectstore_tool --data-path PATH \
          --type filestore|bluestore|memstore --op ...
"""

from __future__ import annotations

import argparse
import json
import sys

from ceph_tpu.msg.encoding import Decoder, Encoder
from ceph_tpu.objectstore import Transaction, create_objectstore
from ceph_tpu.osd.pg import PG


def _pg_cid(pgid: tuple[int, int]) -> str:
    return f"{pgid[0]}.{pgid[1]}"


def op_list(store) -> dict:
    return {cid: store.list_objects(cid)
            for cid in sorted(store.list_collections())}


def op_info(store, pgid) -> dict:
    meta = store.omap_get(_pg_cid(pgid), PG.PGMETA)
    blob = meta.get("info")
    if blob is None:
        raise KeyError(f"pg {_pg_cid(pgid)} has no info")
    info = PG.decode_info(blob)
    return {"pgid": list(info.pgid), "last_update": list(info.last_update),
            "last_complete": list(info.last_complete),
            "last_epoch_started": info.last_epoch_started,
            "past_up": info.past_up}


def op_log(store, pgid) -> list[dict]:
    meta = store.omap_get(_pg_cid(pgid), PG.PGMETA)
    entries = []
    for key in sorted(k for k in meta if k.startswith("log.")):
        e = PG.decode_entry(meta[key])
        entries.append({"version": list(e.version), "op": e.op,
                        "oid": e.oid})
    return entries


def op_export(store, pgid, path: str) -> dict:
    """Portable PG image: pgmeta omap + every object's data/omap/attrs
    (the reference's export writes a typed section stream)."""
    cid = _pg_cid(pgid)
    if cid not in store.list_collections():
        raise KeyError(f"pg {cid} not in store")
    e = Encoder()

    def body(enc: Encoder):
        enc.s64(pgid[0]).u32(pgid[1])
        meta = store.omap_get(cid, PG.PGMETA)
        enc.map(meta, lambda e2, k: e2.str(k), lambda e2, v: e2.bytes(v))
        oids = [o for o in store.list_objects(cid) if o != PG.PGMETA]
        def enc_obj(e2: Encoder, oid: str):
            e2.str(oid)
            e2.bytes(store.read(cid, oid))
            e2.map(store.omap_get(cid, oid), lambda e3, k: e3.str(k),
                   lambda e3, v: e3.bytes(v))
            attrs = {}
            for name in ("_v",):
                v = store.getattr(cid, oid, name)
                if v is not None:
                    attrs[name] = v
            e2.map(attrs, lambda e3, k: e3.str(k), lambda e3, v: e3.bytes(v))
        enc.list(oids, enc_obj)

    e.versioned(1, 1, body)
    blob = e.tobytes()
    with open(path, "wb") as f:
        f.write(blob)
    return {"pgid": _pg_cid(pgid), "bytes": len(blob)}


def op_import(store, path: str) -> dict:
    with open(path, "rb") as f:
        blob = f.read()
    d = Decoder(blob)

    def body(dd: Decoder, version: int):
        pgid = (dd.s64(), dd.u32())
        cid = _pg_cid(pgid)
        meta = dd.map(lambda d2: d2.str(), lambda d2: d2.bytes())
        t = Transaction()
        if cid in store.list_collections():
            raise ValueError(f"pg {cid} already present (remove first)")
        t.create_collection(cid)
        t.touch(cid, PG.PGMETA)
        t.omap_setkeys(cid, PG.PGMETA, meta)
        n = dd.u32()
        for _ in range(n):
            oid = dd.str()
            data = dd.bytes()
            omap = dd.map(lambda d2: d2.str(), lambda d2: d2.bytes())
            attrs = dd.map(lambda d2: d2.str(), lambda d2: d2.bytes())
            t.write(cid, oid, 0, data)
            if omap:
                t.omap_setkeys(cid, oid, omap)
            for name, val in attrs.items():
                t.setattr(cid, oid, name, val)
        store.apply_transaction(t)
        return {"pgid": cid, "objects": n}

    return d.versioned(1, body)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ceph-objectstore-tool")
    ap.add_argument("--data-path", required=True)
    ap.add_argument("--type", default="filestore",
                    choices=["memstore", "filestore", "bluestore"])
    ap.add_argument("--op", required=True,
                    choices=["list", "info", "log", "export", "import",
                             "get-bytes", "rm-object"])
    ap.add_argument("--pgid")
    ap.add_argument("--oid")
    ap.add_argument("--file")
    args = ap.parse_args(argv)

    store = create_objectstore(args.type, args.data_path)
    store.mount()
    try:
        pgid = None
        if args.pgid:
            p, s = args.pgid.split(".")
            pgid = (int(p), int(s))
        if args.op == "list":
            print(json.dumps(op_list(store), indent=1))
        elif args.op == "info":
            print(json.dumps(op_info(store, pgid), indent=1))
        elif args.op == "log":
            print(json.dumps(op_log(store, pgid), indent=1))
        elif args.op == "export":
            print(json.dumps(op_export(store, pgid, args.file)))
        elif args.op == "import":
            print(json.dumps(op_import(store, args.file)))
        elif args.op == "get-bytes":
            sys.stdout.buffer.write(store.read(_pg_cid(pgid), args.oid))
        elif args.op == "rm-object":
            store.apply_transaction(
                Transaction().remove(_pg_cid(pgid), args.oid))
            print(json.dumps({"removed": args.oid}))
        return 0
    finally:
        store.umount()


if __name__ == "__main__":
    raise SystemExit(main())
