"""Unit tests for the pure cap-issue and lock-state machines
(Locker.cc / flock.cc observable-behaviour analogs, no I/O)."""

from ceph_tpu.mds.caps import (
    ALL, BUFFER, CACHE, RD, WANT_READ, WANT_WRITE, WR, CapTable, caps_str)
from ceph_tpu.mds.flock import (
    EOF, F_RDLCK, F_UNLCK, F_WRLCK, LockState, fcntl_range)


# -- caps -------------------------------------------------------------------

def test_lone_writer_gets_everything():
    t = CapTable()
    granted, revokes = t.open_want(7, 1, WANT_WRITE)
    assert granted == WANT_WRITE and revokes == []
    assert caps_str(granted) == "rwcb"


def test_shared_readers_keep_cache():
    t = CapTable()
    g1, r1 = t.open_want(7, 1, WANT_READ)
    g2, r2 = t.open_want(7, 2, WANT_READ)
    assert g1 == RD | CACHE and g2 == RD | CACHE
    assert r1 == [] and r2 == []


def test_writer_joining_reader_forces_sync():
    t = CapTable()
    t.open_want(7, 1, WANT_READ)
    granted, revokes = t.open_want(7, 2, WANT_WRITE)
    # reader must drop CACHE first: grant parks until the ack
    # (seq 1 was the reader's own grant stamp; the revoke bumps to 2)
    assert granted is None
    assert revokes == [(1, RD, 2)]
    assert t.ack(7, 1, 2)
    granted, revokes = t.open_want(7, 2, WANT_WRITE)
    assert granted == (RD | WR) and revokes == []
    assert t.issued(7, 1) == RD


def test_reader_joining_buffered_writer_flushes_it():
    t = CapTable()
    t.open_want(7, 1, WANT_WRITE)          # lone writer: rwcb
    granted, revokes = t.open_want(7, 2, WANT_READ)
    assert granted is None
    assert revokes == [(1, RD | WR, 2)]    # drop cache+buffer -> flush
    assert t.ack(7, 1, 2)
    granted, _ = t.open_want(7, 2, WANT_READ)
    assert granted == RD                    # sync read while writer live
    assert t.issued(7, 1) == RD | WR


def test_release_upgrades_remaining_lone_writer():
    t = CapTable()
    t.open_want(7, 1, WANT_WRITE)
    _, rv = t.open_want(7, 2, WANT_READ)
    t.ack(7, 1, rv[0][2])
    t.open_want(7, 2, WANT_READ)
    grants = t.release(7, 2)
    # buffer/cache handed back, with a fresh ordering seq
    assert [(c, caps) for c, caps, _s in grants] == [(1, WANT_WRITE)]
    assert t.issued(7, 1) == WANT_WRITE


def test_stale_ack_ignored_and_force_drop():
    t = CapTable()
    t.open_want(7, 1, WANT_WRITE)
    _, rv = t.open_want(7, 2, WANT_READ)
    assert not t.ack(7, 1, 99)             # wrong seq
    assert t.pending_revokes(7, exclude=2)
    t.force_drop(7, 1)                     # dead session eviction
    assert not t.pending_revokes(7, exclude=2)
    granted, _ = t.open_want(7, 2, WANT_READ)
    assert granted == WANT_READ            # now the lone holder


def test_recall_buffer_for_stat():
    t = CapTable()
    t.open_want(7, 1, WANT_WRITE)
    revokes = t.recall(7, BUFFER)
    assert revokes == [(1, RD | WR | CACHE, 2)]
    assert t.pending_revokes(7)
    t.ack(7, 1, 2)
    assert not t.pending_revokes(7)
    assert t.recall(7, BUFFER) == []       # idempotent once dropped


def test_drop_client_touches_inos():
    t = CapTable()
    t.open_want(1, 5, WANT_WRITE)
    t.open_want(2, 5, WANT_READ)
    assert sorted(t.drop_client(5)) == [1, 2]
    assert t.holders(1) == {}


# -- posix ranges -----------------------------------------------------------

def test_posix_split_and_merge():
    s = LockState()
    assert s.posix_set(1, "p1", F_WRLCK, *fcntl_range(0, 10))
    # same owner re-locks the middle shared: 3 segments
    assert s.posix_set(1, "p1", F_RDLCK, *fcntl_range(4, 2))
    segs = sorted(((lk.start, lk.end, lk.type) for lk in s.posix))
    assert segs == [(0, 4, F_WRLCK), (4, 6, F_RDLCK), (6, 10, F_WRLCK)]
    # unlock punches a hole
    assert s.posix_set(1, "p1", F_UNLCK, *fcntl_range(2, 6))
    segs = sorted(((lk.start, lk.end, lk.type) for lk in s.posix))
    assert segs == [(0, 2, F_WRLCK), (8, 10, F_WRLCK)]


def test_posix_conflicts():
    s = LockState()
    s.posix_set(1, "a", F_WRLCK, *fcntl_range(0, 10))
    assert not s.posix_set(2, "b", F_RDLCK, *fcntl_range(5, 1))
    assert s.posix_set(2, "b", F_RDLCK, *fcntl_range(10, 5))
    # shared locks coexist; a writer is blocked by either
    s.posix_set(1, "a", F_UNLCK, *fcntl_range(0, 10))
    assert s.posix_set(1, "a", F_RDLCK, *fcntl_range(0, 5))
    assert s.posix_set(2, "b", F_RDLCK, *fcntl_range(0, 5))
    assert not s.posix_set(3, "c", F_WRLCK, *fcntl_range(0, 1))
    got = s.getlk(3, "c", F_WRLCK, *fcntl_range(0, 1))
    assert got is not None and got["type"] == F_RDLCK


def test_len0_means_to_eof():
    s = LockState()
    s.posix_set(1, "a", F_WRLCK, *fcntl_range(100, 0))
    assert not s.posix_set(2, "b", F_WRLCK, *fcntl_range(10 ** 9, 1))
    assert s.posix_set(2, "b", F_WRLCK, *fcntl_range(0, 100))
    got = s.getlk(2, "b", F_WRLCK, *fcntl_range(100, 1))
    assert got["len"] == 0                 # EOF lock reports len 0


def test_flock_upgrade_and_handle_scope():
    s = LockState()
    assert s.flock_set(1, "h1", F_RDLCK)
    assert s.flock_set(2, "h2", F_RDLCK)   # shared coexists
    assert not s.flock_set(1, "h1", F_WRLCK)  # upgrade blocked by h2
    assert s.flock_set(2, "h2", F_UNLCK)   # handle close -> unlock
    assert s.flock_set(1, "h1", F_WRLCK)   # now upgrades (replaces)
    assert len(s.flock) == 1 and s.flock[0].type == F_WRLCK


def test_drop_client_clears_both_families():
    s = LockState()
    s.posix_set(1, "a", F_WRLCK, *fcntl_range(0, 10))
    s.flock_set(1, "h", F_WRLCK)
    assert s.drop_client(1)
    assert s.empty()
