"""CephFS client (src/client/Client.cc + ceph-fuse surface, lite).

Path operations go to the MDS over MClientRequest/MClientReply; file
DATA never touches the MDS — it stripes straight into the data pool
via the Striper, named by inode number, and the client reports the new
size back with a setattr (standing in for the reference's size-tracking
client caps).

    fs = CephFS(mon_addr, mds_addr); fs.mount()
    fs.mkdir("/a"); f = fs.open("/a/hello", "w"); f.write(b"hi"); f.close()
    fs.listdir("/a"); fs.stat("/a/hello"); fs.rename(...); fs.unlink(...)
"""

from __future__ import annotations

import threading

from ceph_tpu.client.rados import RadosClient
from ceph_tpu.mds.server import MClientReply, MClientRequest
from ceph_tpu.msg.messenger import (
    ConnectionPolicy, Dispatcher, EntityName, Messenger)
from ceph_tpu.osdc.striper import StripeLayout, StripedObject


class CephFS(Dispatcher):
    def __init__(self, mon_addr: str, mds_addr: str,
                 ms_type: str = "async", timeout: float = 10.0,
                 auth_key=None, client_id: int | None = None):
        self.mds_addr = mds_addr
        self.timeout = timeout
        self.rados = RadosClient(mon_addr, ms_type=ms_type,
                                 auth_key=auth_key)
        cid = client_id if client_id is not None else self.rados.client_id
        self.name = EntityName("client", 10000 + cid)
        self.msgr = Messenger.create(self.name, ms_type)
        self.msgr.set_auth(auth_key)
        self.msgr.set_policy("mds", ConnectionPolicy.stateful_peer())
        self.msgr.add_dispatcher_tail(self)
        self._lock = threading.Lock()
        self._next_tid = 1
        self._waiters: dict[int, tuple[threading.Event, list]] = {}
        self._data_pool: int | None = None

    # -- lifecycle ------------------------------------------------------------

    def mount(self) -> None:
        self.rados.connect()
        if _is_tcp(self.msgr):
            self.msgr.bind("127.0.0.1:0")
        else:
            self.msgr.bind(f"fsclient.{self.name.id}")
        self.msgr.start()
        st = self._request("statfs", {})
        self._data_pool = st["data_pool"]
        self.data_io = self.rados.open_ioctx(self._data_pool)

    def unmount(self) -> None:
        self.msgr.shutdown()
        self.rados.shutdown()

    # -- mds rpc --------------------------------------------------------------

    def ms_dispatch(self, msg) -> bool:
        if isinstance(msg, MClientReply):
            with self._lock:
                w = self._waiters.pop(msg.tid, None)
            if w is not None:
                w[1].append(msg)
                w[0].set()
            return True
        return False

    def _request(self, op: str, args: dict) -> dict:
        with self._lock:
            tid = self._next_tid
            self._next_tid += 1
            ev: tuple[threading.Event, list] = (threading.Event(), [])
            self._waiters[tid] = ev
        con = self.msgr.connect_to(self.mds_addr, EntityName("mds", 0))
        con.send_message(MClientRequest(tid=tid, op=op, args=args))
        if not ev[0].wait(self.timeout):
            with self._lock:
                self._waiters.pop(tid, None)
            raise TimeoutError(f"mds request {op} timed out")
        reply = ev[1][0]
        if reply.result < 0:
            raise OSError(-reply.result, f"{op} {args} failed")
        return reply.out

    # -- namespace ------------------------------------------------------------

    def mkdir(self, path: str, mode: int = 0o755) -> None:
        self._request("mkdir", {"path": path, "mode": mode})

    def listdir(self, path: str) -> dict:
        return self._request("readdir", {"path": path})["entries"]

    def stat(self, path: str) -> dict:
        return self._request("lookup", {"path": path})["inode"]

    def unlink(self, path: str) -> None:
        out = self._request("unlink", {"path": path})
        # purge the file's striped data (the reference defers this to
        # the MDS purge queue; the client is the data-pool actor here)
        StripedObject(self.data_io, _data_name(out["ino"]),
                      _LAYOUT).remove()

    def rmdir(self, path: str) -> None:
        self._request("rmdir", {"path": path})

    def rename(self, src: str, dst: str) -> None:
        self._request("rename", {"src": src, "dst": dst})

    # -- file i/o -------------------------------------------------------------

    def open(self, path: str, flags: str = "r") -> "File":
        if "w" in flags or "a" in flags:
            out = self._request("create", {"path": path})
        else:
            out = {"inode": self._request(
                "lookup", {"path": path})["inode"]}
        return File(self, out["inode"], append="a" in flags,
                    truncate="w" in flags)


_LAYOUT = StripeLayout(stripe_unit=1 << 16, stripe_count=4,
                       object_size=1 << 22)


def _data_name(ino: int) -> str:
    return f"{ino:x}"


def _is_tcp(msgr) -> bool:
    return msgr.is_wire


class File:
    """Open file handle: striped data I/O + size writeback on close."""

    def __init__(self, fs: CephFS, inode: dict, append: bool = False,
                 truncate: bool = False):
        self.fs = fs
        self.inode = inode
        self.obj = StripedObject(fs.data_io, _data_name(inode["ino"]),
                                 _LAYOUT)
        if truncate and inode.get("size", 0) > 0:
            self.obj.truncate(0)
            self._set_size(0)
        self.pos = inode.get("size", 0) if append else 0
        self._dirty = False

    def _set_size(self, size: int) -> None:
        import time as _t
        self.inode = self.fs._request(
            "setattr", {"ino": self.inode["ino"], "size": size,
                        "mtime": _t.time()})["inode"]

    def write(self, data: bytes) -> int:
        self.obj.write(data, offset=self.pos)
        self.pos += len(data)
        self._dirty = True
        return len(data)

    def read(self, length: int = 0) -> bytes:
        size = self.inode.get("size", 0)
        if length <= 0:
            length = max(0, size - self.pos)
        length = min(length, max(0, size - self.pos))
        data = self.obj.read(self.pos, length)
        self.pos += len(data)
        return data

    def seek(self, pos: int) -> None:
        self.pos = pos

    def close(self) -> None:
        if self._dirty:
            self._set_size(max(self.pos, self.inode.get("size", 0)))
        self._dirty = False

    def __enter__(self) -> "File":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
