"""Thread run-loop exception-swallowing lint (check family
``thread-except``).

A daemon/engine thread's run-loop is the LAST handler its work will
ever see: an ``except`` that catches ``BaseException`` (or a bare
``except:``) and drops the exception on the floor turns a dead batch
into an invisible one — waiters park forever behind futures nobody
will resolve, and the thrasher reads it as a hang, not a failure.
PR 11's supervised engine formalized the contract: a run-loop handler
that catches everything must DELIVER the exception somewhere — fan it
to the waiting futures (``exc = e`` / ``_deliver(None, e)``), hand it
to the supervisor, or re-``raise``.

Roots: every function reachable as a thread body — ``target=`` of a
``threading.Thread(...)`` construction, and ``run`` methods of
``Thread`` subclasses.  The lint flags, in any function reachable from
a root through the best-effort call graph, an ``except`` handler that

* catches ``BaseException`` explicitly, is a bare ``except:``, or
  names it inside a tuple, AND
* neither ``raise``s in its body NOR binds the exception
  (``as e``) and references that name (the static proxy for
  "delivered it to a waiter or the supervisor").

Handlers catching ``Exception`` or narrower are NOT flagged — absorbing
expected errors is normal; it is the catch-everything-and-vanish shape
(which also eats ``InjectedThreadDeath`` and ``KeyboardInterrupt``)
that must prove delivery.
"""

from __future__ import annotations

import ast

from ceph_tpu.analysis import Finding
from ceph_tpu.analysis.core import TreeIndex, name_chain

#: reachability bound, same rationale as the blocking check
MAX_DEPTH = 6


def _thread_roots(index: TreeIndex):
    """Functions that run as a thread body: Thread(target=...) args and
    run() methods of Thread subclasses."""
    roots = []
    for fi in index.all_functions():
        for cs in fi.call_sites:
            node = cs.node
            if not isinstance(node, ast.Call):
                continue
            chain = name_chain(node.func)
            if not chain or chain[-1] != "Thread":
                continue
            for kw in node.keywords:
                if kw.arg != "target":
                    continue
                target = None
                ach = name_chain(kw.value)
                if isinstance(kw.value, ast.Lambda):
                    target = fi.nested.get(
                        f"<lambda@{kw.value.lineno}:"
                        f"{kw.value.col_offset}>")
                elif ach:
                    spec = None
                    if len(ach) == 1:
                        spec = ("name", ach[0])
                    elif ach[0] in ("self", "cls") and len(ach) == 2:
                        spec = ("self", ach[1])
                    if spec:
                        target = index.resolve_call(fi, spec)
                if target is not None:
                    roots.append(target)
    for mod in index.modules.values():
        for ci in mod.classes.values():
            if any(b and b[-1] == "Thread" for b in ci.bases):
                run = ci.methods.get("run")
                if run is not None:
                    roots.append(run)
    return roots


def _reachable(index: TreeIndex, roots):
    out = {}
    frontier = [(fn, 0) for fn in roots]
    for fn, _d in frontier:
        out.setdefault(fn, 0)
    while frontier:
        nxt = []
        for fn, d in frontier:
            if d >= MAX_DEPTH:
                continue
            for cs in fn.call_sites:
                g = index.resolve_call(fn, cs.spec)
                if g is not None and g not in out:
                    out[g] = d + 1
                    nxt.append((g, d + 1))
        frontier = nxt
    return out


def _catches_base(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True                      # bare except:
    if isinstance(t, ast.Tuple):
        return any(_catches_base_expr(e) for e in t.elts)
    return _catches_base_expr(t)


def _catches_base_expr(node) -> bool:
    chain = name_chain(node)
    return bool(chain) and chain[-1] == "BaseException"


def _delivers(handler: ast.ExceptHandler) -> bool:
    """True when the handler body re-raises or references its bound
    exception name (the static proxy for delivering it to a waiter,
    the log, or the supervisor)."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if (handler.name and isinstance(node, ast.Name)
                and node.id == handler.name
                and isinstance(node.ctx, ast.Load)):
            return True
    return False


def check(index: TreeIndex):
    reach = _reachable(index, _thread_roots(index))
    findings = []
    seen = set()
    for fn in sorted(reach, key=lambda f: f.qualname):
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Try):
                continue
            for handler in node.handlers:
                if not _catches_base(handler) or _delivers(handler):
                    continue
                key = (fn.module.relpath, handler.lineno)
                if key in seen:
                    continue
                seen.add(key)
                what = ("bare except:" if handler.type is None
                        else "except BaseException")
                findings.append(Finding(
                    "thread-except", fn.module.relpath,
                    handler.lineno, "swallow",
                    f"{what} in thread run-loop path {fn.qualname} "
                    f"neither re-raises nor uses the caught "
                    f"exception — a swallowed loop error strands "
                    f"every waiter behind it (deliver it to a "
                    f"future/supervisor or re-raise)"))
    return findings
