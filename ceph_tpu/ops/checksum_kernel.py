"""Batched object-integrity digests — the deep-scrub checksum kernel.

Deep scrub is a checksum workload: every object's payload and omap
blob hashes into the (size, data_crc, omap_crc) scrub-map triple
(`osd/ec_util.shard_crc`, the reference's chunky-scrub digests in
src/osd/PGBackend::be_deep_scrub).  The seed computed those digests
one object at a time on the host; this module turns a whole PG's
digests into ONE batched device call riding the dispatch engine,
exactly the treatment PRs 3-11 gave encode/decode/CRUSH/placement.

Variable object sizes are the obstacle: a CRC over row[:L] with L
varying per row defeats naive batching (per-byte masking serializes
the hot loop on selects).  Two linearity facts remove the lengths from
the device kernel entirely:

* **crc32 zero-padding is invertible.**  The crc register update for a
  ZERO byte is a GF(2)-linear map Z of the 32 register bits (the table
  lookup of a linear function of the register is linear).  So the
  register over row[:L] relates to the register over the zero-padded
  fixed width W by r_true = Z^-(W-L) r_padded: the kernel runs a
  mask-free fixed-width slicing-by-4 table scan over the padded batch
  — every row identical shape, no per-byte selects — and a per-row
  32x32 GF(2) matrix-vector epilogue (matrices gathered from an aux
  operand the submitter builds from the lengths) strips the padding's
  effect exactly.

* **GF(2^8) Horner trailing zeros are a multiplier.**  The GF shard
  digest is a 4-lane Horner evaluation d = alpha*d ^ byte over the
  byte stream (lane l takes bytes l, l+4, ...); t trailing zero steps
  multiply the lane state by alpha^t, undone by a gathered alpha^-t.

Both digests share one scan (4 bytes per step), so a PG's whole
object population digests in a single kernel launch.  The host oracle
(`scrub_digest_ref`) is the literal per-row `shard_crc` loop — the
seed's path, and the bit-exactness ground truth the property tests
pin; it doubles as the channel's breaker fallback.

Like every kernel module, jax only enters through the jitted entry
point — the oracle and the operand builders are numpy/zlib only, so
the OSD's scalar fallback path never imports the device stack.
"""

from __future__ import annotations

import functools
import zlib

import numpy as np

from ceph_tpu.ops import telemetry

#: crc32 (zlib/ISO-HDLC) reflected polynomial; the repo's shard_crc is
#: zlib.crc32 — the Castagnoli polynomial of the reference's hardware
#: crc32c is an implementation detail of the integrity attr (see
#: osd/ec_util.py), the detection semantics are identical
_CRC_POLY = 0xEDB88320
_CRC_INIT = 0xFFFFFFFF

#: GF(2^8) Horner evaluation point for the shard digest (alpha = x)
_GF_ALPHA = 2

#: minimum padded row width (pow2, multiple of the 4-byte scan step)
MIN_WIDTH = 8

#: rows wider than this take the scalar host path: the scan runs
#: W/4 sequential steps, and a multi-MB object would trade one long
#: device program for a loop the host does in microseconds
MAX_WIDTH = 1 << 18


# ---------------------------------------------------------------------------
# host oracle — ground truth for bit-exactness tests and the breaker fallback
# ---------------------------------------------------------------------------

def gf_digest_ref(row: np.ndarray) -> int:
    """4-lane GF(2^8) Horner digest of one row, packed little-endian:
    lane l evaluates bytes row[l::4] at alpha (the literal per-byte
    loop — the definition the batched kernel must reproduce)."""
    from ceph_tpu.gf.tables import mul_table
    mt = mul_table()
    alpha_row = mt[_GF_ALPHA]
    packed = 0
    for lane in range(4):
        d = 0
        for b in row[lane::4].tolist():
            d = int(alpha_row[d]) ^ int(b)
        packed |= d << (8 * lane)
    return packed


def scrub_digest_ref(batch, lengths, *_aux) -> np.ndarray:
    """Bit-exact host oracle: per row i, col 0 is ``shard_crc`` of
    row[:L_i] (the seed's scalar scrub loop, literally) and col 1 the
    packed GF Horner digest.  Extra aux operands (the device path's
    unpad matrices) are accepted and ignored so the engine's fallback
    ladder can call this with the full aux tuple."""
    # analysis: allow[blocking] -- host oracle: inputs are host numpy by contract (fallback/verification path)
    batch = np.asarray(batch, dtype=np.uint8)
    lengths = np.asarray(lengths, dtype=np.int64)
    out = np.zeros((batch.shape[0], 2), dtype=np.uint32)
    for i in range(batch.shape[0]):
        row = batch[i, : int(lengths[i])]
        out[i, 0] = zlib.crc32(row.tobytes()) & 0xFFFFFFFF
        out[i, 1] = gf_digest_ref(row)
    return out


# ---------------------------------------------------------------------------
# table prep (host, cached)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=1)
def _crc_tables() -> np.ndarray:
    """(4, 256) uint32 slicing-by-4 tables; row 0 is the classic
    byte-at-a-time table."""
    t0 = np.zeros(256, dtype=np.uint32)
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ (_CRC_POLY if c & 1 else 0)
        t0[i] = c
    tabs = [t0]
    for _ in range(3):
        prev = tabs[-1]
        tabs.append(((prev >> np.uint32(8)) ^ t0[prev & 0xFF])
                    .astype(np.uint32))
    return np.stack(tabs)


def _apply_cols(cols: np.ndarray, vals: np.ndarray) -> np.ndarray:
    """GF(2) matrix (32 uint32 columns) applied to uint32 value(s):
    out = XOR of columns selected by the set bits of each value."""
    vals = np.asarray(vals, dtype=np.uint32)
    out = np.zeros_like(vals)
    for j in range(32):
        bit = (vals >> np.uint32(j)) & np.uint32(1)
        out ^= cols[j] * bit
    return out


@functools.lru_cache(maxsize=1)
def _zero_cols() -> np.ndarray:
    """Columns of Z, the crc-register update for one ZERO byte:
    Z(c) = (c >> 8) ^ T0[c & 0xFF] — linear because T0 is the crc map
    of the byte, itself linear over GF(2)."""
    t0 = _crc_tables()[0]
    cols = np.zeros(32, dtype=np.uint32)
    for j in range(32):
        c = np.uint32(1 << j)
        cols[j] = (c >> np.uint32(8)) ^ t0[int(c) & 0xFF]
    return cols


@functools.lru_cache(maxsize=1)
def _zero_inv_cols() -> np.ndarray:
    """Z^-1 columns via GF(2) Gaussian elimination (Z is invertible:
    the crc register after a zero byte determines the register
    before)."""
    n = 32
    cols = _zero_cols()
    m = np.zeros((n, 2 * n), dtype=np.uint8)
    for j in range(n):
        for i in range(n):
            m[i, j] = (int(cols[j]) >> i) & 1
        m[j, n + j] = 1
    for col in range(n):
        piv = next(r for r in range(col, n) if m[r, col])
        if piv != col:
            m[[col, piv]] = m[[piv, col]]
        for r in range(n):
            if r != col and m[r, col]:
                m[r] ^= m[col]
    inv = np.zeros(n, dtype=np.uint32)
    for j in range(n):
        v = 0
        for i in range(n):
            if m[i, n + j]:
                v |= 1 << i
        inv[j] = v
    return inv


@functools.lru_cache(maxsize=4096)
def _unpad_cols(k: int) -> np.ndarray:
    """Columns of Z^-k (square-and-multiply over the composition
    _apply_cols): strips k trailing zero bytes from a crc register."""
    if k == 0:
        return (np.uint32(1) << np.arange(32, dtype=np.uint32))
    half = _unpad_cols(k // 2)
    sq = _apply_cols(half, half)
    if k % 2:
        return _apply_cols(_zero_inv_cols(), sq)
    return sq


#: widest padded width whose full Z^-k table is precomputed (one
#: compose per entry: ~0.1 ms each, so ~0.4 s once per process at the
#: cap); wider batches build only the DISTINCT pad counts they need
#: via square-and-multiply (_unpad_cols, O(log k) composes, memoized)
#: — an O(width) build at MAX_WIDTH would stall the submitting thread
#: for tens of seconds
_TABLE_WIDTH_MAX = 4096


@functools.lru_cache(maxsize=16)
def _unpad_table(width: int) -> np.ndarray:
    """(width + 1, 32) uint32: Z^-k columns for every pad count a
    batch of this width can need — built once per width (iterating
    Z^-1 composition), so the per-call operand build is one numpy
    gather instead of a per-row python loop (the scrub hot path runs
    hundreds of chunks a second; per-row python there is measurable
    GIL theft from the serving threads)."""
    out = np.zeros((width + 1, 32), dtype=np.uint32)
    out[0] = _unpad_cols(0)
    zinv = _zero_inv_cols()
    for k in range(1, width + 1):
        out[k] = _apply_cols(zinv, out[k - 1])
    return out


@functools.lru_cache(maxsize=1)
def _gf_alpha_row() -> np.ndarray:
    from ceph_tpu.gf.tables import mul_table
    return np.ascontiguousarray(mul_table()[_GF_ALPHA])


@functools.lru_cache(maxsize=1)
def _gf_alpha_inv() -> int:
    row = _gf_alpha_row()
    return int(np.nonzero(row == 1)[0][0])


@functools.lru_cache(maxsize=32)
def _gf_inv_pows(n: int) -> np.ndarray:
    """(n + 1,) uint8: alpha^-t for t in 0..n (undoes t trailing zero
    Horner steps on one lane)."""
    from ceph_tpu.gf.tables import mul_table
    mt = mul_table()
    inv = _gf_alpha_inv()
    out = np.zeros(n + 1, dtype=np.uint8)
    out[0] = 1
    for t in range(1, n + 1):
        out[t] = mt[int(out[t - 1]), inv]
    return out


def digest_operands(lengths, width: int):
    """The per-row epilogue operands for a padded batch of ``width``:
    (mats (S, 32) uint32 — Z^-(W-L) columns per row; invp (S, 4)
    uint8 — alpha^-t per GF lane).  Submitters build these host-side
    from the lengths; they ride the engine's aux channel in lockstep
    with the data rows."""
    lengths = np.asarray(lengths, dtype=np.int64)
    pads = width - lengths
    if width <= _TABLE_WIDTH_MAX:
        mats = _unpad_table(width)[pads]
    else:
        # wide rows: only the distinct pad counts this batch needs,
        # each O(log k) via the memoized square-and-multiply
        lut = {int(k): _unpad_cols(int(k)) for k in np.unique(pads)}
        mats = np.stack([lut[int(k)] for k in pads])
    steps = width // 4
    pows = _gf_inv_pows(steps)
    lanes = np.arange(4, dtype=np.int64)[None, :]
    # lane l holds ceil((L - l) / 4) real bytes; the rest of its
    # width/4 Horner steps consumed padding zeros
    n_real = np.clip(-(-(lengths[:, None] - lanes) // 4), 0, steps)
    invp = pows[(steps - n_real).astype(np.int64)]
    return mats, invp.astype(np.uint8)


def row_width(max_len: int) -> int:
    """Shared pow-2 padded width for a digest batch (>= MIN_WIDTH so
    the 4-byte scan step always divides it): concurrent scrubs bucket
    their rows to the same widths, so different PGs coalesce."""
    if max_len <= MIN_WIDTH:
        return MIN_WIDTH
    return 1 << (int(max_len) - 1).bit_length()


# ---------------------------------------------------------------------------
# the jitted kernel
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=1)
def _jit_digest():
    """Build (and cache) the jitted fixed-width digest entry point.
    jax imports live inside so the oracle path never pulls it in."""
    import jax
    import jax.numpy as jnp
    from ceph_tpu.gf.tables import mul_table

    tabs_host = _crc_tables()
    alpha_host = _gf_alpha_row()
    mt_host = mul_table()

    @functools.partial(jax.jit, static_argnames=("w",))
    def digest(data, mats, invp, *, w):
        tabs = jnp.asarray(tabs_host)
        alpha = jnp.asarray(alpha_host)
        mt = jnp.asarray(mt_host)
        s = data.shape[0]
        u8, u32 = jnp.uint32(0xFF), jnp.uint32
        words = jnp.transpose(
            data.reshape(s, w // 4, 4).astype(jnp.uint32), (1, 0, 2))

        def step(carry, wb):
            crc, g = carry
            x = crc ^ (wb[:, 0] | (wb[:, 1] << u32(8))
                       | (wb[:, 2] << u32(16)) | (wb[:, 3] << u32(24)))
            crc = (tabs[3][x & u8] ^ tabs[2][(x >> u32(8)) & u8]
                   ^ tabs[1][(x >> u32(16)) & u8]
                   ^ tabs[0][(x >> u32(24)) & u8])
            g = alpha[g] ^ wb.astype(jnp.uint8)
            return (crc, g), None

        init = (jnp.full((s,), _CRC_INIT, dtype=jnp.uint32),
                jnp.zeros((s, 4), dtype=jnp.uint8))
        (crc, g), _ = jax.lax.scan(step, init, words)
        # epilogue: strip the zero padding's effect — Z^-(W-L) per row
        # (gathered matrix columns), alpha^-t per GF lane
        true = jnp.zeros((s,), dtype=jnp.uint32)
        for j in range(32):
            bit = (crc >> u32(j)) & u32(1)
            true = true ^ (mats[:, j] * bit)
        crc_final = true ^ u32(_CRC_INIT)
        lanes = mt[g.astype(jnp.int32), invp.astype(jnp.int32)]
        lanes = lanes.astype(jnp.uint32)
        gf = (lanes[:, 0] | (lanes[:, 1] << u32(8))
              | (lanes[:, 2] << u32(16)) | (lanes[:, 3] << u32(24)))
        return jnp.stack([crc_final, gf], axis=1)

    return digest


def digest_jit_entries() -> int:
    """Compile-cache entry count for the digest entry point (the
    telemetry retrace counter differences this around each call)."""
    try:
        return _jit_digest()._cache_size()
    except Exception:
        return 0


def _digest_batched(kname: str, data, mats, invp):
    import jax.numpy as jnp
    data = jnp.asarray(np.asarray(data, dtype=np.uint8))
    mats = jnp.asarray(np.asarray(mats, dtype=np.uint32))
    invp = jnp.asarray(np.asarray(invp, dtype=np.uint8))
    s, w = data.shape
    return telemetry.timed_kernel(
        kname,
        lambda: _jit_digest()(data, mats, invp, w=int(w)),
        batch=int(s), bytes_in=int(s) * int(w) + mats.nbytes + invp.nbytes,
        bytes_out=int(s) * 8,
        cache_entries=digest_jit_entries,
        signature=(kname, int(s), int(w)))


def scrub_digest_batched(data, mats, invp):
    """One batched device digest call: data (S, W) uint8 zero-padded
    rows, mats/invp from ``digest_operands``.  Returns (S, 2) uint32 —
    col 0 crc32 (== shard_crc of the unpadded row), col 1 the packed
    GF Horner digest — bit-exact vs ``scrub_digest_ref``."""
    return _digest_batched("scrub_digest", data, mats, invp)


def bluestore_digest_batched(data, mats, invp):
    """The objectstore flavor of the batched digest: identical math
    through the SAME jitted entry point (equal-width store and scrub
    batches share one compiled executable — one checksum definition for
    both), but accounted under its own telemetry family so the
    ``ceph_kernel_bluestore_data_*`` histograms track the write/read
    hot path separately from background scrub."""
    return _digest_batched("bluestore_data", data, mats, invp)
