"""CephFS client (src/client/Client.cc + ceph-fuse surface, lite).

Path operations go to the MDS over MClientRequest/MClientReply; file
DATA never touches the MDS — it stripes straight into the data pool via
the Striper, named by inode number.

Coherence rides client capabilities (Client.cc's cap handling against
mds/Locker.cc, reduced to the same observable contract):

  * the client opens a SESSION with the MDS (MClientSession) and renews
    it on a timer; a client that dies is evicted and its caps/locks
    evaporate server-side
  * open() asks for cap bits (rd / rd|wr|cache|buffer); the MDS grants
    what the sharing situation allows
  * holding BUFFER, writes are buffered locally (dirty extents + size)
    and flushed lazily; holding CACHE, attrs are trusted from cache
  * an MClientCaps revoke makes the client FLUSH dirty data before
    acking — that ordering is what makes a second client's stat/read
    see the first client's buffered writes (POSIX coherence)
  * without CACHE (sync mode: mixed readers+writers), every read
    refreshes attrs from the MDS and every write reports its size —
    exactly the reference's synchronous-I/O lock state

File locks (flock / fcntl ranges) are MDS-arbitrated via setlk/getlk/
flock ops; blocking requests park server-side until the conflicting
lock drops.

    fs = CephFS(mon_addr, mds_addr); fs.mount()
    fs.mkdir("/a"); f = fs.open("/a/hello", "w"); f.write(b"hi"); f.close()
    fs.listdir("/a"); fs.stat("/a/hello"); fs.rename(...); fs.unlink(...)
"""

from __future__ import annotations

import threading
import time

from ceph_tpu.client.rados import RadosClient
from ceph_tpu.mds.caps import BUFFER, CACHE, WANT_READ, WANT_WRITE, WR
from ceph_tpu.mds.flock import F_RDLCK, F_UNLCK, F_WRLCK
from ceph_tpu.mds.server import (
    MClientLease,
    MClientCaps, MClientReply, MClientRequest, MClientSession)
from ceph_tpu.msg.messenger import (
    ConnectionPolicy, Dispatcher, EntityName, Messenger)
from ceph_tpu.osdc.striper import StripeLayout, StripedObject

#: dirty buffered bytes per inode before a forced writeback
MAX_DIRTY = 4 << 20


class _CapState:
    """Per-inode client cap state (Client::Inode + CapSnap, lite)."""

    __slots__ = ("ino", "caps", "inode", "attr_fresh", "size", "mtime",
                 "dirty", "dirty_bytes", "nopen", "wb_lock", "rank",
                 "inflight")

    def __init__(self, ino: int):
        self.ino = ino
        self.caps = 0
        self.rank = 0       # authoritative mds rank for this ino
        self.inode: dict = {}
        self.attr_fresh = False
        self.size = 0
        self.mtime = 0.0
        self.dirty: list[tuple[int, bytes]] = []   # buffered writes
        self.dirty_bytes = 0
        self.nopen = 0
        #: direct RADOS writes in flight under WR (Client::get_caps /
        #: put_caps references): a WR revoke ack waits for these to
        #: drain, so mksnap can never complete mid-write
        self.inflight = 0
        #: serializes writebacks so two flushers can never reorder
        #: overlapping extents (older batch landing over a newer one)
        # analysis: allow[bare-lock] -- client-writeback leaf lock; CephFS client hierarchy conversion deferred with its subsystem
        self.wb_lock = threading.Lock()


class CephFS(Dispatcher):
    def __init__(self, mon_addr: str, mds_addr: str | None = None,
                 ms_type: str = "async", timeout: float = 10.0,
                 auth_key=None, client_id: int | None = None,
                 cephx: tuple[str, str] | None = None):
        #: None = resolve the active MDS from the mon's FSMap (and
        #: fail over to its successor when it dies)
        self.mds_addr = mds_addr
        self._auto_mds = mds_addr is None
        self.timeout = timeout
        self.rados = RadosClient(mon_addr, ms_type=ms_type,
                                 auth_key=auth_key, cephx=cephx)
        cid = client_id if client_id is not None else self.rados.client_id
        self.client_id = cid
        self.name = EntityName("client", 10000 + cid)
        self.msgr = Messenger.create(self.name, ms_type)
        self.msgr.set_auth(auth_key)
        if cephx is not None:
            from ceph_tpu.auth.cephx import TicketKeyring
            from ceph_tpu.auth.handshake import CephxConfig
            self.msgr.set_auth_cephx(CephxConfig(
                entity=cephx[0], key=cephx[1],
                keyring=TicketKeyring(self.rados._fetch_ticket)))
        self.msgr.set_policy("mds", ConnectionPolicy.stateful_peer())
        self.msgr.add_dispatcher_tail(self)
        # analysis: allow[bare-lock] -- client session RLock, held across FS ops by design; CephFS lockdep pass deferred
        self._lock = threading.RLock()
        self._next_tid = 1
        self._waiters: dict[int, tuple[threading.Event, list]] = {}
        self._data_pool: int | None = None
        self._caps: dict[int, _CapState] = {}
        #: serializes open vs last-close so a concurrent open can never
        #: interleave with a cap_release and orphan its cap state.
        #: Deliberately client-wide (the reference holds client_lock
        #: across whole ops too): an open parked behind another
        #: client's revoke stalls this mount's other opens for up to
        #: revoke_grace — bounded, rare, and safe; a per-ino scope
        #: can't exclude the close because open learns the ino only
        #: from the reply
        # analysis: allow[bare-lock] -- objectcacher leaf lock; CephFS lockdep pass deferred
        self._oc_lock = threading.Lock()
        self._next_fh = 1
        #: last known ino per opened path (open-timeout cancel guard)
        self._path_ino: dict[str, int] = {}
        #: leased dentry cache (Client.cc dcache): normpath ->
        #: (expiry, inode dict).  Served by stat() without an MDS
        #: round-trip; dropped on MClientLease revokes, on our own
        #: mutations, and at expiry
        self._lease_cache: dict[str, tuple[float, dict]] = {}
        #: path -> time of its last revoke/drop: a lookup REPLY that
        #: raced an already-processed revoke must not reinstall the
        #: lease (the _cap_seq_seen idea, per path)
        self._lease_dropped_at: dict[str, float] = {}
        #: highest cap seq processed per ino — survives missing cap
        #: state, so an open reply racing an already-processed revoke
        #: never reinstalls the stale (higher) grant
        self._cap_seq_seen: dict[int, int] = {}
        #: osdmap epoch this client must reach before direct RADOS data
        #: writes (Client::set_cap_epoch_barrier): rides open replies
        #: and cap messages; bumped by the MDS at mksnap so post-snap
        #: writes carry the new pool snap_seq and the OSD clones
        self._osd_epoch_barrier = 0
        #: signaled when an in-flight direct write drains (revoke acks
        #: for WR wait on it)
        # analysis: allow[bare-lock] -- condition deliberately shares the client RLock above
        self._inflight_cv = threading.Condition(self._lock)
        #: multi-active routing: cached rank addrs, opened sessions,
        #: and last-known authoritative rank per path
        self._rank_addr: dict[int, str] = {}
        self._have_session: set[int] = set()
        self._path_rank: dict[str, int] = {}
        self._renew_timer: threading.Timer | None = None
        self._stop = False
        self._evicted = False

    # -- lifecycle ------------------------------------------------------------

    def mount(self) -> None:
        self.rados.connect()
        if self._auto_mds:
            self.mds_addr = self._resolve_mds()
        self._rank_addr[0] = self.mds_addr
        if _is_tcp(self.msgr):
            self.msgr.bind("127.0.0.1:0")
        else:
            self.msgr.bind(f"fsclient.{self.name.id}")
        self.msgr.start()
        self._ensure_session(0)
        st = self._request("statfs", {})
        self._data_pool = st["data_pool"]
        self.data_io = self.rados.open_ioctx(self._data_pool)
        self._schedule_renew()

    def _addr_of(self, rank: int) -> str:
        addr = self._rank_addr.get(rank)
        if addr is None:
            addr = self._resolve_mds(rank=rank)
            self._rank_addr[rank] = addr
        return addr

    def _ensure_session(self, rank: int) -> None:
        if rank in self._have_session:
            return
        self._session("request_open", rank=rank)
        self._have_session.add(rank)

    def _resolve_mds(self, rank: int = 0, timeout: float = 20.0,
                     not_addr: str | None = None) -> str:
        """Active MDS address for a rank, from the FSMap the mon
        publishes on the cluster map.  With not_addr, prefer a
        DIFFERENT address (the one that just timed out is probably the
        dead daemon still listed while the mon's grace runs); fall back
        to it only once the wait expires."""
        deadline = time.time() + timeout
        last = None
        while time.time() < deadline:
            fs = self.rados.osdmap.fs_db
            ent = (fs or {}).get("ranks", {}).get(str(rank))
            if ent:
                last = ent["addr"]
                if not_addr is None or last != not_addr:
                    return last
            time.sleep(0.1)
        if last is not None:
            return last     # unchanged: the MDS may just be slow
        raise TimeoutError(f"no active mds rank {rank} in fsmap")

    def _failover(self, rank: int = 0) -> bool:
        """An MDS request timed out: find the rank's (possibly new)
        daemon, re-open our session there, and reassert the caps we
        hold under that rank (Client::handle_mds_map reconnect)."""
        try:
            new = self._resolve_mds(rank=rank,
                                    not_addr=self._rank_addr.get(rank))
            self._rank_addr[rank] = new
            if rank == 0:
                self.mds_addr = new
            self._have_session.discard(rank)
            self._ensure_session(rank)
            with self._lock:
                entries = [{"ino": st.ino, "caps": st.caps,
                            "size": st.size, "mtime": st.mtime}
                           for st in self._caps.values()
                           if st.caps and st.rank == rank]
                # the new rank's seq generation starts fresh: stale
                # high-water marks would silently drop its grants.
                # Clear for EVERY ino homed on this rank — including
                # fully-revoked ones (caps==0) we don't reassert
                for st in self._caps.values():
                    if st.rank == rank:
                        self._cap_seq_seen.pop(st.ino, None)
            if entries:
                self._request("cap_reassert", {"caps": entries},
                              _retry=False, rank=rank)
            return True
        except (OSError, TimeoutError):
            return False

    def unmount(self) -> None:
        self._stop = True
        if self._renew_timer:
            self._renew_timer.cancel()
        with self._lock:
            states = list(self._caps.values())
        for st in states:
            try:
                self._flush_state(st)
            except (OSError, TimeoutError):
                # teardown is best-effort; per-file errors were the
                # owner's to observe via fsync/close
                pass
        for rank in list(self._have_session):
            try:
                self._session("request_close", rank=rank)
            except (OSError, TimeoutError):
                pass
        self.msgr.shutdown()
        self.rados.shutdown()

    def _schedule_renew(self) -> None:
        if self._stop:
            return
        self._renew_timer = threading.Timer(2.0, self._renew)
        self._renew_timer.daemon = True
        self._renew_timer.start()

    def _renew(self) -> None:
        try:
            now = time.time()
            with self._lock:
                # sweep expired lease entries (a tree walk statting
                # each dir once would otherwise grow the cache forever)
                for k in [k for k, (exp, _i) in
                          self._lease_cache.items() if exp <= now]:
                    del self._lease_cache[k]
                for k in [k for k, t in
                          self._lease_dropped_at.items()
                          if now - t > 60.0]:
                    del self._lease_dropped_at[k]
            for rank in list(self._have_session):
                try:
                    con = self.msgr.connect_to(self._addr_of(rank),
                                               EntityName("mds", 0))
                    con.send_message(MClientSession(
                        op="renew", client=self.client_id))
                except (OSError, TimeoutError):
                    # one dead rank must not starve the OTHER ranks'
                    # renewals (they would evict a healthy client)
                    continue
        finally:
            self._schedule_renew()

    # -- mds rpc --------------------------------------------------------------

    def ms_dispatch(self, msg) -> bool:
        if isinstance(msg, MClientReply) or (
                isinstance(msg, MClientSession)
                and msg.op in ("open_ack", "close_ack")):
            with self._lock:
                w = self._waiters.pop(msg.tid, None)
            if w is not None:
                w[1].append(msg)
                w[0].set()
            return True
        if isinstance(msg, MClientSession):
            if msg.op == "evicted":
                # the MDS killed our session (we stalled past a revoke
                # grace): caps are void, buffered data is dead — the
                # reference blocklists the client; ops now fail until
                # a remount
                with self._lock:
                    self._evicted = True
                    for st in self._caps.values():
                        st.caps = 0
                        st.dirty.clear()
                        st.dirty_bytes = 0
                    self._caps.clear()
                    self._cap_seq_seen.clear()
            return True
        if isinstance(msg, MClientCaps):
            self._handle_caps(msg)
            return True
        if isinstance(msg, MClientLease):
            if msg.op == "revoke":
                # a mutation (or new writer) voided the dentry: drop
                # the cached entry and any descendants cached under it
                self._lease_drop(msg.path, prefix=True)
            return True
        return False

    def _alloc_tid(self):
        with self._lock:
            tid = self._next_tid
            self._next_tid += 1
            ev: tuple[threading.Event, list] = (threading.Event(), [])
            self._waiters[tid] = ev
        return tid, ev

    def _session(self, op: str, rank: int = 0) -> None:
        tid, ev = self._alloc_tid()
        con = self.msgr.connect_to(self._addr_of(rank),
                                   EntityName("mds", 0))
        con.send_message(MClientSession(tid=tid, op=op,
                                        client=self.client_id))
        if not ev[0].wait(self.timeout):
            with self._lock:
                self._waiters.pop(tid, None)
            raise TimeoutError(f"mds session {op} timed out")

    @staticmethod
    def _normpath(path: str) -> str:
        return "/" + "/".join(p for p in path.split("/") if p)

    def _start_rank(self, op: str, args: dict) -> int:
        if "path" in args:
            return self._path_rank.get(self._normpath(args["path"]), 0)
        if "ino" in args:
            st = self._caps.get(args["ino"])
            if st is not None:
                return st.rank
        return 0

    def _request(self, op: str, args: dict,
                 timeout: float | None = None,
                 _retry: bool = True, rank: int | None = None) -> dict:
        """MDS RPC with multi-active routing: start at the last-known
        authoritative rank and follow 'forward' replies (a request that
        lands on the wrong rank after a subtree export is redirected,
        like the reference's MClientRequestForward)."""
        if self._evicted:
            raise OSError(108, "session evicted by mds (remount)")
        args = dict(args)
        args.setdefault("client", self.client_id)
        if rank is None:
            rank = self._start_rank(op, args)
        hops = 0
        while True:
            self._ensure_session(rank)
            tid, ev = self._alloc_tid()
            con = self.msgr.connect_to(self._addr_of(rank),
                                       EntityName("mds", 0))
            con.send_message(MClientRequest(tid=tid, op=op, args=args))
            if not ev[0].wait(self.timeout if timeout is None
                              else timeout):
                with self._lock:
                    self._waiters.pop(tid, None)
                if self._auto_mds and _retry and not self._stop \
                        and self._failover(rank):
                    _retry = False
                    continue
                raise TimeoutError(f"mds request {op} timed out")
            reply = ev[1][0]
            fwd = reply.out.get("forward") if reply.result == 0 else None
            if fwd is not None:
                hops += 1
                if hops > 4:
                    raise OSError(40, f"{op}: mds forward loop")
                rank = int(fwd)
                continue
            if reply.result < 0:
                raise OSError(-reply.result, f"{op} {args} failed")
            # remember who answered: path hints + the ino's home rank
            with self._lock:
                if "path" in args:
                    self._path_rank[self._normpath(args["path"])] = rank
                if "ino" in args:
                    st = self._caps.get(args["ino"])
                    if st is not None:
                        st.rank = rank
            return reply.out

    # -- capability handling ---------------------------------------------------

    def _state(self, ino: int) -> _CapState:
        st = self._caps.get(ino)
        if st is None:
            st = self._caps[ino] = _CapState(ino)
        return st

    def _handle_caps(self, msg: MClientCaps) -> None:
        """Async cap traffic from the MDS (revoke/grant).  Revoke order:
        downgrade the caps FIRST (under the lock — a racing write then
        takes the sync path), flush whatever was buffered up to that
        point, and only then ack.  A write therefore either lands in
        the flushed buffer or runs synchronously after the downgrade —
        never invisibly in between."""
        size = -1
        mtime = 0.0
        need_flush = False
        with self._lock:
            self._osd_epoch_barrier = max(
                self._osd_epoch_barrier,
                getattr(msg, "epoch_barrier", 0))
            st = self._caps.get(msg.ino)
            if msg.seq:
                self._cap_seq_seen[msg.ino] = max(
                    self._cap_seq_seen.get(msg.ino, 0), msg.seq)
            if msg.op == "grant":
                if st is not None and msg.seq >= \
                        self._cap_seq_seen.get(msg.ino, 0):
                    st.caps = msg.caps
                return
            if msg.op == "invalidated":
                # the inode was unlinked under us: caps are void and
                # buffered data has nowhere to go — drop it; subsequent
                # ops on live handles surface ENOENT.  The server-side
                # seq generation died with the grant, so forget ours.
                if st is not None:
                    st.caps = 0
                    st.dirty.clear()
                    st.dirty_bytes = 0
                    st.attr_fresh = False
                self._cap_seq_seen.pop(msg.ino, None)
                return
            if msg.op != "revoke":
                return
            if st is not None:
                lost = st.caps & ~msg.caps
                st.caps = msg.caps
                if lost & CACHE:
                    st.attr_fresh = False
                need_flush = bool(lost & BUFFER)
                if lost & WR:
                    # drain in-flight direct writes BEFORE acking: the
                    # MDS treats our ack as "this client writes no
                    # more", and mksnap's pool snapshot happens right
                    # after — an op still in flight would race it.
                    # Writers time out, so the drain is bounded.
                    while st.inflight > 0:
                        self._inflight_cv.wait(timeout=1.0)
        if st is not None and need_flush:
            self._writeback(st)
            with self._lock:
                size, mtime = st.size, st.mtime
        # ack over the connection the revoke came in on: with multiple
        # active ranks, only the sender knows this revoke's seq
        msg.connection.send_message(MClientCaps(
            op="ack", ino=msg.ino, seq=msg.seq, client=self.client_id,
            size=size, mtime=mtime))

    def _install_grant(self, ino: int, out: dict) -> None:
        """Install a caps+barrier reply (open / cap_want) under the
        lock: the grant lands ONLY if no newer revoke was processed
        since the server stamped it, and the epoch barrier merges
        grow-only."""
        with self._lock:
            st = self._caps.get(ino)
            if st is not None and out.get("cap_seq", 0) >= \
                    self._cap_seq_seen.get(ino, 0):
                st.caps = out["caps"]
            self._osd_epoch_barrier = max(
                self._osd_epoch_barrier,
                out.get("epoch_barrier", 0))

    def _pre_data_write(self, st: _CapState) -> None:
        """Gate a DIRECT RADOS data write (sync-mode write/truncate)
        and take an in-flight reference (Client::get_caps):

        1. re-acquire WR if it was recalled (mksnap's freeze strips WR
           from every holder — the round-trip here is what hands us the
           post-snapshot epoch barrier),
        2. wait for our osdmap to reach the barrier, so the op's
           SnapContext stamp carries the new pool snap_seq and the OSD
           copy-on-writes the pre-snapshot data, and
        3. atomically (WR still held + barrier reached) bump
           st.inflight — a WR revoke ack then WAITS for the write to
           drain, so mksnap can never complete around an op in flight.

        The caller MUST pair with _post_data_write in a finally.
        Buffered (Fb) flushes do NOT re-acquire WR — flushing under a
        revoke is legal and precedes the snapshot by construction —
        they only honor the barrier (see _writeback)."""
        while True:
            self._wait_epoch_barrier()
            with self._lock:
                if (st.caps & WR) and self.rados.osdmap.epoch >= \
                        self._osd_epoch_barrier:
                    st.inflight += 1
                    return
                need_caps = not (st.caps & WR)
            if need_caps:
                out = self._request("cap_want", {"ino": st.ino,
                                                 "wanted": WANT_WRITE},
                                    rank=st.rank)
                self._install_grant(st.ino, out)
                if not (st.caps & WR):
                    time.sleep(0.01)   # mixed-mode revoke still settling
            # else: the barrier moved under us — loop and wait again

    def _post_data_write(self, st: _CapState) -> None:
        with self._lock:
            st.inflight -= 1
            if st.inflight <= 0:
                self._inflight_cv.notify_all()

    def _wait_epoch_barrier(self) -> None:
        barrier = self._osd_epoch_barrier
        if barrier and self.rados.osdmap.epoch < barrier:
            self.rados.wait_for_epoch(barrier)

    def _writeback(self, st: _CapState) -> None:
        """Write buffered extents to RADOS (data only — the size rides
        the cap ack or an explicit setattr).  The dirty list is SWAPPED
        out under the client lock, so concurrent writes land on the new
        list (flushed by the next writeback, never lost); wb_lock keeps
        two flushers from landing overlapping batches out of order."""
        with st.wb_lock:
            # barrier BEFORE the swap: a failed wait (mon unreachable)
            # must leave the dirty list intact for the next flusher,
            # not silently drop it
            self._wait_epoch_barrier()
            with self._lock:
                extents = st.dirty
                st.dirty = []
                st.dirty_bytes = 0
            if not extents:
                return
            obj = StripedObject(self.data_io, _data_name(st.ino),
                                _LAYOUT)
            for off, data in extents:
                obj.write(data, offset=off)

    def _flush_state(self, st: _CapState) -> None:
        """Full writeback + synchronous size/mtime report (close/fsync
        path — Client::_flush + check_caps)."""
        if not st.dirty and st.size <= st.inode.get("size", 0):
            return
        self._writeback(st)
        # a failed size report MUST surface (fsync/close return the
        # error in POSIX — swallowing it would report success for
        # writes another client can never see)
        inode = self._request(
            "setattr", {"ino": st.ino, "size": st.size,
                        "grow": True,
                        "mtime": st.mtime or time.time()})["inode"]
        self._apply_inode(st, inode)

    def _apply_inode(self, st: _CapState, inode: dict) -> None:
        """Install server-reported attrs under the lock; a buffered
        write racing this keeps its (larger) local size."""
        with self._lock:
            st.inode = inode
            st.size = max(inode.get("size", 0),
                          st.size if st.dirty else 0)
            st.attr_fresh = True

    def _refresh_attrs(self, st: _CapState) -> None:
        """Sync mode (no CACHE): ask the MDS for the truth."""
        self._apply_inode(
            st, self._request("getattr", {"ino": st.ino})["inode"])

    # -- namespace ------------------------------------------------------------

    def mkdir(self, path: str, mode: int = 0o755) -> None:
        self._request("mkdir", {"path": path, "mode": mode})

    def listdir(self, path: str) -> dict:
        return self._request("readdir", {"path": path})["entries"]

    def _lease_get(self, norm: str) -> dict | None:
        with self._lock:
            ent = self._lease_cache.get(norm)
            if ent is None:
                return None
            if ent[0] < time.time():
                del self._lease_cache[norm]
                return None
            return dict(ent[1])

    def _lease_drop(self, path: str, prefix: bool = False) -> None:
        norm = self._normpath(path)
        now = time.time()
        with self._lock:
            self._lease_cache.pop(norm, None)
            self._lease_dropped_at[norm] = now
            if prefix:
                # a directory moved/vanished: every cached descendant
                # path string is void
                pre = norm.rstrip("/") + "/"
                for k in [k for k in self._lease_cache
                          if k.startswith(pre)]:
                    del self._lease_cache[k]
                    self._lease_dropped_at[k] = now

    def stat(self, path: str) -> dict:
        norm = self._normpath(path)
        inode = self._lease_get(norm)
        if inode is None:
            t0 = time.time()
            out = self._request("lookup", {"path": path})
            inode = out["inode"]
            ttl = out.get("lease", 0)
            if ttl:
                with self._lock:
                    # install ONLY if no revoke landed since we asked
                    if self._lease_dropped_at.get(norm, 0.0) < t0:
                        self._lease_cache[norm] = (time.time() + ttl,
                                                   dict(inode))
        # our OWN buffered size is more recent than the MDS's answer
        # (the MDS only recalls OTHER clients' buffers for a stat)
        with self._lock:
            st = self._caps.get(inode.get("ino"))
            if st is not None and st.caps & BUFFER:
                inode["size"] = max(inode.get("size", 0), st.size)
                inode["mtime"] = max(inode.get("mtime", 0.0), st.mtime)
        return inode

    def unlink(self, path: str) -> None:
        self._lease_drop(path)
        out = self._request("unlink", {"path": path})
        if not out.get("removed", True):
            return   # hardlinks remain: the inode (and data) live on
        with self._lock:
            self._caps.pop(out["ino"], None)
            self._cap_seq_seen.pop(out["ino"], None)
        # purge the file's striped data (the reference defers this to
        # the MDS purge queue; the client is the data-pool actor here)
        StripedObject(self.data_io, _data_name(out["ino"]),
                      _LAYOUT).remove()

    def link(self, src: str, dst: str) -> dict:
        """Hardlink: a second name for an existing file (POSIX link(2);
        MDS-side remote dentries).  Returns the inode (nlink bumped)."""
        self._lease_drop(src)    # nlink changed
        self._lease_drop(dst)
        return self._request("link", {"src": src, "dst": dst})["inode"]

    def rmdir(self, path: str) -> None:
        self._lease_drop(path, prefix=True)
        self._request("rmdir", {"path": path})

    def rename(self, src: str, dst: str) -> None:
        self._lease_drop(src, prefix=True)
        self._lease_drop(dst, prefix=True)
        self._request("rename", {"src": src, "dst": dst})

    def export_dir(self, path: str, to_rank: int) -> dict:
        """Delegate a subtree to another active MDS rank (the manual
        `setfattr ceph.dir.pin` / Migrator export_dir surface)."""
        out = self._request("export_dir", {"path": path,
                                           "to": to_rank},
                            timeout=60.0)
        # our path hints under that subtree are stale now
        norm = self._normpath(path)
        with self._lock:
            for p in list(self._path_rank):
                if p == norm or p.startswith(norm + "/"):
                    self._path_rank[p] = to_rank
        return out

    # -- snapshots (mkdir .snap analog, explicit verbs) -----------------------

    def mksnap(self, path: str, name: str) -> int:
        """Snapshot the directory subtree at `path` (the reference's
        `mkdir dir/.snap/name`).  Returns the pool snapid backing it."""
        return self._request("mksnap", {"path": path,
                                        "snap": name})["snapid"]

    def rmsnap(self, path: str, name: str) -> None:
        self._request("rmsnap", {"path": path, "snap": name})

    def listsnaps(self, path: str) -> dict:
        return self._request("lssnap", {"path": path})["snaps"]

    # -- quotas (ceph.quota vxattr surface) -----------------------------------

    def set_quota(self, path: str, max_bytes: int = 0,
                  max_files: int = 0) -> None:
        """setfattr ceph.quota.max_bytes/max_files analog; 0 clears.
        Enforcement is MDS-side at create and size-report time, so
        buffered writers can overshoot until their flush — the same
        approximate enforcement the reference documents."""
        self._lease_drop(path)   # our own cached attrs are stale now
        self._request("setquota", {"path": path, "max_bytes": max_bytes,
                                   "max_files": max_files})

    def get_quota(self, path: str) -> dict:
        return self._request("getquota", {"path": path})

    # -- file i/o -------------------------------------------------------------

    def open(self, path: str, flags: str = "r"):
        if "/.snap/" in self._normpath(path):
            if "w" in flags or "a" in flags:
                raise OSError(30, "snapshots are read-only")  # EROFS
            out = self._request("open", {"path": path,
                                         "wanted": WANT_READ,
                                         "create": False})
            return SnapFile(self, out["inode"], out["snapid"])
        writing = "w" in flags or "a" in flags
        wanted = WANT_WRITE if writing else WANT_READ
        with self._oc_lock:
            try:
                out = self._request("open", {"path": path,
                                             "wanted": wanted,
                                             "create": writing})
            except TimeoutError:
                # withdraw the server-side wanted/grant registration our
                # abandoned open may have left (else the ino is stuck in
                # sync mode) — but never while we hold live handles on
                # it, whose grant a release would wrongly drop
                known = self._path_ino.get(path)
                st0 = self._caps.get(known) if known is not None else None
                if st0 is None or st0.nopen <= 0:
                    try:
                        self._request("open_cancel", {"path": path},
                                      timeout=5.0)
                    except (OSError, TimeoutError):
                        pass
                raise
            ino = out["inode"]["ino"]
            self._path_ino[path] = ino
            with self._lock:
                st = self._state(ino)
                self._install_grant(ino, out)
                st.inode = out["inode"]
                st.attr_fresh = True
                if not st.dirty:
                    st.size = out["inode"].get("size", 0)
                    st.mtime = out["inode"].get("mtime", 0.0)
                st.rank = self._path_rank.get(self._normpath(path), 0)
                st.nopen += 1
                fh = self._next_fh
                self._next_fh += 1
        f = File(self, st, fh, append="a" in flags, writable=writing)
        if "w" in flags and st.size > 0:
            f.truncate(0)
        return f

    def _close_file(self, st: _CapState) -> None:
        flush_err = None
        try:
            self._flush_state(st)
        except (OSError, TimeoutError) as e:
            flush_err = e       # surface AFTER the handle bookkeeping
        with self._oc_lock:
            with self._lock:
                st.nopen -= 1
                last = st.nopen <= 0
                if last:
                    self._caps.pop(st.ino, None)
                    # the release ends this grant's seq generation
                    self._cap_seq_seen.pop(st.ino, None)
            if last:
                try:
                    self._request("cap_release", {"ino": st.ino})
                except (OSError, TimeoutError):
                    pass
        if flush_err is not None:
            raise flush_err


_LAYOUT = StripeLayout(stripe_unit=1 << 16, stripe_count=4,
                       object_size=1 << 22)


def _data_name(ino: int) -> str:
    return f"{ino:x}"


def _is_tcp(msgr) -> bool:
    return msgr.is_wire


class SnapFile:
    """Read-only handle on a file inside a directory snapshot: attrs
    come frozen from the snapshot record, data from pool-snapshot reads
    at the snapshot's snapid — no capabilities involved, the content is
    immutable by construction."""

    def __init__(self, fs: "CephFS", inode: dict, snapid: int):
        self.fs = fs
        self._inode = dict(inode)
        self.snapid = snapid
        self.obj = StripedObject(fs.data_io, _data_name(inode["ino"]),
                                 _LAYOUT)
        self.pos = 0

    @property
    def inode(self) -> dict:
        return dict(self._inode)

    def read(self, length: int = 0) -> bytes:
        size = self._inode.get("size", 0)
        if length <= 0 or self.pos + length > size:
            length = max(0, size - self.pos)
        if length <= 0:
            # frozen EOF: StripedObject's length<=0 fallback would
            # substitute the CURRENT size and read past the snapshot
            return b""
        data = self.obj.read(self.pos, length, snapid=self.snapid)
        if len(data) < length:
            data += bytes(length - len(data))
        self.pos += length
        return bytes(data)

    def seek(self, pos: int) -> None:
        self.pos = pos

    def write(self, data: bytes) -> int:
        raise OSError(30, "snapshots are read-only")   # EROFS

    def truncate(self, size: int) -> None:
        raise OSError(30, "snapshots are read-only")

    def close(self) -> None:
        pass

    def __enter__(self) -> "SnapFile":
        return self

    def __exit__(self, *exc) -> None:
        pass


class File:
    """Open file handle: cap-gated striped data I/O.

    With BUFFER: writes buffer locally and flush on close / revoke /
    high-water.  With CACHE: attrs trusted from cache.  Without either
    (sync mode), writes hit RADOS + report size immediately and reads
    refresh attrs from the MDS — two clients mixing reads and writes
    therefore always see POSIX-coherent data.
    """

    def __init__(self, fs: CephFS, state: _CapState, fh: int,
                 append: bool = False, writable: bool = False):
        self.fs = fs
        self.state = state
        self.fh = fh
        self.writable = writable
        self.obj = StripedObject(fs.data_io, _data_name(state.ino),
                                 _LAYOUT)
        self.pos = state.size if append else 0
        self._closed = False
        self._flocked = False
        self._lockfed = False

    @property
    def inode(self) -> dict:
        return self.state.inode

    def truncate(self, size: int) -> None:
        # truncate is always SYNCHRONOUS to the MDS (plain, shrinking
        # setattr) — a buffered size report is grow-only and could
        # never undo the old length
        if not self.writable:
            raise OSError(9, "file not open for writing")  # EBADF
        st = self.state
        self.fs._pre_data_write(st)
        try:
            self.obj.truncate(size)
        finally:
            self.fs._post_data_write(st)
        with self.fs._lock:
            # clip straddling extents to the new size (dropping them
            # whole would lose their in-range bytes)
            clipped = []
            for o, d in st.dirty:
                if o >= size:
                    continue
                clipped.append((o, d[:size - o] if o + len(d) > size
                                else d))
            st.dirty = clipped
            st.dirty_bytes = sum(len(d) for _o, d in clipped)
            st.size = size
            st.mtime = time.time()
        self.fs._apply_inode(st, self.fs._request(
            "setattr", {"ino": st.ino, "size": size,
                        "mtime": st.mtime})["inode"])

    def write(self, data: bytes) -> int:
        if not self.writable:
            raise OSError(9, "file not open for writing")  # EBADF
        st = self.state
        with self.fs._lock:
            buffered = bool(st.caps & BUFFER)
            if buffered:
                st.dirty.append((self.pos, bytes(data)))
                st.dirty_bytes += len(data)
                st.size = max(st.size, self.pos + len(data))
                st.mtime = time.time()
        if buffered:
            if st.dirty_bytes > MAX_DIRTY:
                self.fs._flush_state(st)
        else:
            # sync mode: data through, size reported immediately
            # (grow-only: the MDS keeps the max across all writers)
            self.fs._pre_data_write(st)
            try:
                self.obj.write(data, offset=self.pos)
            finally:
                self.fs._post_data_write(st)
            self.fs._apply_inode(st, self.fs._request(
                "setattr", {"ino": st.ino, "size": self.pos + len(data),
                            "grow": True,
                            "mtime": time.time()})["inode"])
        self.pos += len(data)
        return len(data)

    def read(self, length: int = 0) -> bytes:
        st = self.state
        if not st.caps & CACHE or not st.attr_fresh:
            self.fs._refresh_attrs(st)
        # wb_lock excludes an in-flight writeback (whose extents are in
        # neither st.dirty nor RADOS yet); under it, an extent is
        # either in the snapshot (overlaid below, newest wins) or was
        # fully written back before our RADOS read started
        with st.wb_lock:
            with self.fs._lock:
                size = st.size
                dirty = list(st.dirty)
            if length <= 0:
                length = max(0, size - self.pos)
            length = min(length, max(0, size - self.pos))
            data = bytearray(self.obj.read(self.pos, length))
        if len(data) < length:      # unwritten space reads as zeros
            data += bytes(length - len(data))
        # overlay this client's own buffered writes
        for off, blob in dirty:
            lo = max(off, self.pos)
            hi = min(off + len(blob), self.pos + length)
            if lo < hi:
                data[lo - self.pos:hi - self.pos] = \
                    blob[lo - off:hi - off]
        self.pos += length
        return bytes(data)

    def seek(self, pos: int) -> None:
        self.pos = pos

    def fsync(self) -> None:
        self.fs._flush_state(self.state)

    # -- locks ----------------------------------------------------------------

    def lockf(self, ltype: int, start: int = 0, length: int = 0,
              wait: bool = False) -> None:
        """fcntl byte-range lock (F_SETLK / F_SETLKW with wait=True).
        Owner scope is the CLIENT (posix: process-wide)."""
        self.fs._request(
            "setlk", {"ino": self.state.ino,
                      "owner": f"p{self.fs.client_id}",
                      "type": ltype, "start": start, "len": length,
                      "wait": wait},
            timeout=300.0 if wait else None)
        if ltype != F_UNLCK:
            self._lockfed = True

    def getlk(self, ltype: int, start: int = 0,
              length: int = 0) -> dict | None:
        return self.fs._request(
            "getlk", {"ino": self.state.ino,
                      "owner": f"p{self.fs.client_id}",
                      "type": ltype, "start": start,
                      "len": length})["lock"]

    def flock(self, ltype: int, wait: bool = False) -> None:
        """BSD flock; owner scope is THIS handle."""
        self.fs._request(
            "flock", {"ino": self.state.ino,
                      "owner": f"h{self.fs.client_id}.{self.fh}",
                      "type": ltype, "wait": wait},
            timeout=300.0 if wait else None)
        self._flocked = ltype != F_UNLCK

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._flocked:
            # a handle-scoped flock dies with the handle
            try:
                self.fs._request(
                    "flock", {"ino": self.state.ino,
                              "owner":
                              f"h{self.fs.client_id}.{self.fh}",
                              "type": F_UNLCK})
            except (OSError, TimeoutError):
                pass
        if self._lockfed:
            # POSIX: closing ANY descriptor of the file drops the
            # process's fcntl locks on it (whole-file unlock)
            try:
                self.fs._request(
                    "setlk", {"ino": self.state.ino,
                              "owner": f"p{self.fs.client_id}",
                              "type": F_UNLCK, "start": 0, "len": 0})
            except (OSError, TimeoutError):
                pass
        self.fs._close_file(self.state)

    def __enter__(self) -> "File":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


__all__ = ["CephFS", "File", "F_RDLCK", "F_WRLCK", "F_UNLCK",
           "WANT_READ", "WANT_WRITE", "BUFFER", "CACHE", "WR"]
