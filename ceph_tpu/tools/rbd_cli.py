"""`rbd` command-line tool (src/tools/rbd/ analog): image lifecycle,
snapshots, COW clones, object-map-aware du/diff and export/import —
the operator surface over ceph_tpu.rbd's librbd-lite.

    python -m ceph_tpu.tools.rbd_cli --mon <host> -p <pool> <command>

Commands (the rbd verbs they mirror):
    create NAME --size BYTES [--order N] [--features f1,f2]
    ls | info NAME | rm NAME | resize NAME --size BYTES
    snap create|rm|protect|unprotect|rollback NAME@SNAP
    snap ls NAME
    clone PARENT@SNAP CHILD           (COW; parent snap must be protected)
    flatten NAME | children PARENT@SNAP
    du NAME [--snap S] | diff NAME [--from-snap A] [--to-snap B]
    export NAME FILE | import FILE NAME
"""

from __future__ import annotations

import argparse
import json
import sys


def _split_at(spec: str) -> tuple[str, str]:
    if "@" not in spec:
        raise SystemExit(f"expected IMAGE@SNAP, got {spec!r}")
    name, snap = spec.split("@", 1)
    return name, snap


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="rbd")
    p.add_argument("--mon", required=True, help="mon host(s)")
    p.add_argument("-p", "--pool", type=int, required=True)
    p.add_argument("--ms-type", default="async")
    p.add_argument("--auth-key", default="",
                   help="cluster shared key (authenticated clusters)")
    p.add_argument("words", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)
    if not args.words:
        p.error("missing command")

    from ceph_tpu.client import RadosClient
    from ceph_tpu.rbd import Image, list_images
    client = RadosClient(args.mon, ms_type=args.ms_type,
                         auth_key=args.auth_key.encode()
                         if args.auth_key else None)
    client.connect()
    io = client.open_ioctx(args.pool)
    w = args.words
    try:
        cmd = w[0]
        if cmd == "create":
            sub = argparse.ArgumentParser(prog="rbd create")
            sub.add_argument("name")
            sub.add_argument("--size", type=int, required=True)
            sub.add_argument("--order", type=int, default=22)
            sub.add_argument("--features", default="")
            a = sub.parse_args(w[1:])
            feats = [f for f in a.features.split(",") if f]
            Image.create(io, a.name, size=a.size, order=a.order,
                         features=feats)
            return 0
        if cmd == "ls":
            for n in list_images(io):
                print(n)
            return 0
        if cmd == "info":
            img = Image(io, w[1])
            st = img.stat()
            st["features"] = img.features()
            parent = img._parent()
            if parent is not None:
                pi, ps, ov = parent
                st["parent"] = f"{pi.name}@{ps} (overlap {ov})"
            print(json.dumps(st, indent=1))
            return 0
        if cmd == "rm":
            Image(io, w[1]).remove()
            return 0
        if cmd == "resize":
            sub = argparse.ArgumentParser(prog="rbd resize")
            sub.add_argument("name")
            sub.add_argument("--size", type=int, required=True)
            a = sub.parse_args(w[1:])
            Image(io, a.name).resize(a.size)
            return 0
        if cmd == "snap":
            verb = w[1]
            if verb == "ls":
                for s, ent in Image(io, w[2]).snap_list().items():
                    flag = " (protected)" if ent.get("protected") else ""
                    print(f"{s}\tsize {ent['size']}{flag}")
                return 0
            name, snap = _split_at(w[2])
            img = Image(io, name)
            if verb == "create":
                img.snap_create(snap)
            elif verb == "rm":
                img.snap_remove(snap)
            elif verb == "protect":
                img.snap_protect(snap)
            elif verb == "unprotect":
                img.snap_unprotect(snap)
            elif verb == "rollback":
                img.snap_rollback(snap)
            else:
                raise SystemExit(f"unknown snap verb {verb!r}")
            return 0
        if cmd == "clone":
            pname, psnap = _split_at(w[1])
            Image(io, pname).clone(w[2], psnap)
            return 0
        if cmd == "flatten":
            n = Image(io, w[1]).flatten()
            print(f"flattened: {n} objects materialized")
            return 0
        if cmd == "children":
            pname, psnap = _split_at(w[1])
            for c in Image(io, pname).list_children(psnap):
                print(c)
            return 0
        if cmd == "du":
            sub = argparse.ArgumentParser(prog="rbd du")
            sub.add_argument("name")
            sub.add_argument("--snap", default=None)
            a = sub.parse_args(w[1:])
            print(json.dumps(Image(io, a.name).du(snap=a.snap)))
            return 0
        if cmd == "diff":
            sub = argparse.ArgumentParser(prog="rbd diff")
            sub.add_argument("name")
            sub.add_argument("--from-snap", default=None)
            sub.add_argument("--to-snap", default=None)
            a = sub.parse_args(w[1:])
            for off, ln, exists in Image(io, a.name).diff(
                    from_snap=a.from_snap, to_snap=a.to_snap):
                print(f"{off}\t{ln}\t{'data' if exists else 'zero'}")
            return 0
        if cmd == "export":
            img = Image(io, w[1])
            data = img.read(0, img.stat()["size"])
            with open(w[2], "wb") as f:
                f.write(data)
            print(f"exported {len(data)} bytes")
            return 0
        if cmd == "import":
            with open(w[1], "rb") as f:
                data = f.read()
            img = Image.create(io, w[2], size=len(data))
            if data.rstrip(b"\x00"):
                img.write(data, 0)
            print(f"imported {len(data)} bytes")
            return 0
        raise SystemExit(f"unknown rbd command {cmd!r}")
    except IndexError:
        print(f"rbd: missing operand for {w[0]!r}", file=sys.stderr)
        return 2
    except (OSError, KeyError, FileExistsError) as e:
        print(f"rbd: {e}", file=sys.stderr)
        return 1
    finally:
        client.shutdown()


if __name__ == "__main__":
    sys.exit(main())
