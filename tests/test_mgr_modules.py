"""Mgr module framework + multi-mgr failover (pybind/mgr/mgr_module.py
+ MgrMonitor.cc analogs): modules load by name from mon-persisted
config, module config/state lives mon-side (config-key), the MgrMap
names an active and standbys, killing the active promotes a standby
that still answers pg dump, and pg_autoscaler grows a filling pool's
pg_num autonomously."""

from __future__ import annotations

import json
import time

import pytest

from ceph_tpu.mgr import MgrDaemon, ModuleHost
from ceph_tpu.tools.vstart import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(n_osds=3, ms_type="loopback").start()
    c.wait_for_osd_count(3)
    client = c.client(timeout=20.0)
    # a pool with data, so pg dump has rows to serve
    pool = c.create_pool(client, pg_num=8, size=2)
    io = client.open_ioctx(pool)
    io.write_full("seed", b"mgr-module-test")
    yield c
    c.stop()


def _wait(pred, timeout=45.0, interval=0.1):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def test_module_framework_load_enable_disable(cluster):
    mgr = cluster.run_mgr(0)
    client = cluster.client(timeout=20.0)
    try:
        # the mon names it active; always-on modules load
        assert _wait(lambda: mgr.is_active)
        assert _wait(lambda: set(ModuleHost.ALWAYS_ON)
                     <= set(mgr.host.modules))
        # enable-by-name persists in the MON config-key store
        out, rc = mgr._handle_command({"prefix": "mgr module enable",
                                       "module": "pg_autoscaler"})
        assert rc == 0, out
        assert "pg_autoscaler" in mgr.host.modules
        rc2, raw = client.mon_command({"prefix": "config-key get",
                                       "key": "mgr/modules"})
        assert rc2 == 0 and "pg_autoscaler" in json.loads(raw)
        # module ls names enabled + available
        out, rc = mgr._handle_command({"prefix": "mgr module ls"})
        ls = json.loads(out)
        assert "pg_autoscaler" in ls["loaded_modules"]
        assert "prometheus" in ls["available_modules"]
        # a bogus module is refused, not crashed on
        _out, rc = mgr._handle_command({"prefix": "mgr module enable",
                                        "module": "nope"})
        assert rc == -2
        # module commands route through the host's prefix table
        out, rc = mgr._handle_command(
            {"prefix": "osd pool autoscale-status"})
        assert rc == 0 and "pools" in json.loads(out)
        # always-on modules cannot be disabled; others can
        _out, rc = mgr._handle_command({"prefix": "mgr module disable",
                                        "module": "balancer"})
        assert rc == -22
        out, rc = mgr._handle_command({"prefix": "mgr module disable",
                                       "module": "pg_autoscaler"})
        assert rc == 0
        assert "pg_autoscaler" not in mgr.host.modules
    finally:
        cluster.kill_mgr(0)


def test_standby_promotion_on_active_death(cluster):
    client = cluster.client(timeout=20.0)
    mgr0 = cluster.run_mgr(0)
    assert _wait(lambda: mgr0.is_active)
    mgr1 = cluster.run_mgr(1)
    try:
        # the MgrMap names mgr.0 active with mgr.1 standby
        def map_settled():
            db = client.osdmap.mgr_db or {}
            return (db.get("active_name") == "mgr.0"
                    and [s["name"] for s in db.get("standbys", [])]
                    == ["mgr.1"])
        # generous: late in a full-suite run the 1-core host is slow
        assert _wait(map_settled, timeout=60.0), client.osdmap.mgr_db
        assert not mgr1.is_active
        # module unload runs on the worker queue after the demotion
        # flag flips — wait for it to drain instead of racing it
        assert _wait(lambda: not mgr1.host.modules), mgr1.host.modules
        # kill the active: the mon promotes the standby, which loads
        # the module set and starts answering
        cluster.kill_mgr(0)
        assert _wait(lambda: (client.osdmap.mgr_db or {})
                     .get("active_name") == "mgr.1", timeout=60.0), \
            client.osdmap.mgr_db
        assert _wait(lambda: mgr1.is_active)
        assert _wait(lambda: set(ModuleHost.ALWAYS_ON)
                     <= set(mgr1.host.modules))
        # OSDs re-target reports at the promoted mgr: pg dump refills
        assert _wait(lambda: mgr1.pg_dump()["num_pgs"] > 0,
                     timeout=30.0)
        # and the mgr command tier answers through the new active
        res, out = client.mgr_command({"prefix": "iostat"})
        assert res == 0
    finally:
        cluster.kill_mgr(1)


def test_pg_autoscaler_grows_filling_pool(cluster):
    client = cluster.client(timeout=20.0)
    pool = cluster.create_pool(client, pg_num=2, size=2)
    io = client.open_ioctx(pool)
    for i in range(24):
        io.write_full(f"fill-{i}", b"x" * 4096)
    mgr = cluster.run_mgr(0)
    try:
        # the whole autonomous chain — OSD stat reports -> mgr host
        # tick (5 s timer) -> maybe_scale -> mon `osd pool set pg_num`
        # -> map propagation -> PG splits -> client map refresh — is a
        # stack of independent timers that all slip together under
        # full-suite load on a 1-core host.  One generous wall-clock
        # DEADLINE for the whole chain, polled against, instead of
        # per-step timeouts sized for an idle machine; the poll
        # interval is coarse so the wait itself does not eat the core
        # the timers need.
        deadline = time.time() + 150.0
        left = lambda: max(5.0, deadline - time.time())  # noqa: E731
        assert _wait(lambda: mgr.is_active, timeout=left(),
                     interval=0.25)
        # configure a small budget through the module-option store
        # (mon-side config-key), then enable the module — from here on
        # everything is autonomous: host tick -> maybe_scale -> mon
        # `osd pool set pg_num` -> PG splits on the OSDs
        mgr.set_store("mgr/pg_autoscaler/target_pgs_per_osd", 8)
        mgr.set_store("mgr/pg_autoscaler/sleep_interval", 1.0)
        out, rc = mgr._handle_command({"prefix": "mgr module enable",
                                       "module": "pg_autoscaler"})
        assert rc == 0, out
        # wait for the report feed, then for the autonomous growth
        assert _wait(lambda: mgr.pg_dump()["num_pgs"] > 0,
                     timeout=left(), interval=0.25)
        assert _wait(
            lambda: client.osdmap.pools.get(pool) is not None
            and client.osdmap.pools[pool].pg_num > 2,
            timeout=left(), interval=0.25), \
            f"pg_num still {client.osdmap.pools[pool].pg_num}"
        # growth may land in steps; poll until the full target, not
        # just past the first split
        _wait(lambda: client.osdmap.pools[pool].pg_num >= 8,
              timeout=left(), interval=0.25)
        grown = client.osdmap.pools[pool].pg_num
        assert grown >= 8
        # autoscale-status reports what it did
        out, rc = mgr._handle_command(
            {"prefix": "osd pool autoscale-status"})
        rows = {r["pool"]: r for r in json.loads(out)["pools"]}
        assert rows[pool]["pg_num"] >= 8 or \
            rows[pool]["action"] == "grown"
        # data stays reachable across the splits
        assert io.read("fill-0", 16) == b"x" * 16
    finally:
        cluster.kill_mgr(0)
