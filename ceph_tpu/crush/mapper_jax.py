"""Batched CRUSH rule evaluation on device.

One call evaluates a rule for N inputs at once — the TPU-native replacement for
ParallelPGMapper's thread-pool fan-out (src/osd/OSDMapMapping.h:17) and the
CrushTester loop (src/crush/CrushTester.cc:472-560).  Bit-exactness contract:
for any straw2 map with modern tunables, results equal the scalar oracle
(ceph_tpu.crush.mapper_ref, itself written against src/crush/mapper.c) exactly.

Shape of the implementation:
  * the rule program (TAKE/CHOOSE*/EMIT/SET_*) is interpreted in Python — it is
    static per map epoch, exactly like the reference (mapper.c:900-1105);
  * each CHOOSE step runs the whole batch through masked lax.while_loop retry
    ladders: descent through the hierarchy, the firstn collision/reject ladder
    (mapper.c:460-648) with chooseleaf recursion (vary_r/stable semantics), and
    the breadth-first positionally-stable indep pass (mapper.c:655-843);
  * per-lane state is (current bucket, ftotal, active); every draw is a
    straw2 argmax over a gathered bucket row (ops.crush_kernel.straw2_draws).

Working-set values are per-lane (a lane's chosen hosts differ), so multi-step
rules like "take root / choose firstn 0 host / choose firstn 1 osd / emit"
gather per-lane start buckets at each step.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ceph_tpu.ops.crush_kernel import hash32_4, is_out

from .compile import CompiledCrushMap, compile_map
from .types import (
    CRUSH_BUCKET_TREE,
    CRUSH_BUCKET_UNIFORM,
    CRUSH_ITEM_NONE,
    RULE_CHOOSE_FIRSTN,
    RULE_CHOOSE_INDEP,
    RULE_CHOOSELEAF_FIRSTN,
    RULE_CHOOSELEAF_INDEP,
    RULE_EMIT,
    RULE_SET_CHOOSE_TRIES,
    RULE_SET_CHOOSELEAF_TRIES,
    RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
    RULE_SET_CHOOSE_LOCAL_TRIES,
    RULE_SET_CHOOSELEAF_STABLE,
    RULE_SET_CHOOSELEAF_VARY_R,
    RULE_TAKE,
    CrushMap,
)

NONE = jnp.int32(CRUSH_ITEM_NONE)


class _Arrays:
    """Device-resident compiled map."""

    def __init__(self, c: CompiledCrushMap):
        self.bucket_id = jnp.asarray(c.bucket_id)
        self.bucket_type = jnp.asarray(c.bucket_type)
        self.bucket_size = jnp.asarray(c.bucket_size)
        self.bucket_alg = jnp.asarray(c.bucket_alg)
        self.items = jnp.asarray(c.items)
        self.weights = jnp.asarray(c.weights)
        self.n_nodes = jnp.asarray(c.n_nodes)
        self.node_weights = jnp.asarray(c.node_weights)
        self.has_tree = c.has_tree
        self.has_uniform = c.has_uniform
        self.max_uniform_size = c.max_uniform_size
        self.n_buckets = c.n_buckets
        self.max_devices = c.max_devices


def _straw2_draws_per_row(x, items_row, r, w_row):
    """Like ops.crush_kernel.straw2_draws but ids/weights differ per lane."""
    from ceph_tpu.crush.types import S64_MIN
    from ceph_tpu.ops.crush_kernel import _LN_2_48, crush_ln, hash32_3
    u = hash32_3(x[:, None], items_row, r[:, None]) & jnp.uint32(0xFFFF)
    ln = crush_ln(u) - _LN_2_48
    w = w_row.astype(jnp.int64)
    draw = -((-ln) // jnp.maximum(w, 1))
    return jnp.where(w > 0, draw, jnp.int64(S64_MIN))


def _tree_winner(a: _Arrays, cur: jax.Array, x: jax.Array,
                 r: jax.Array) -> jax.Array:
    """Tree-bucket winner: weighted binary descent from the root node
    (num_nodes/2) to a leaf (odd node; leaf i at node 2i+1), semantics of
    mapper.c:195-222.  Lanes whose bucket is not a tree terminate at node 1
    immediately; the caller selects them out by alg."""
    is_tree = a.bucket_alg[cur] == jnp.int32(CRUSH_BUCKET_TREE)
    n0 = (a.n_nodes[cur] >> 1).astype(jnp.uint32)
    n0 = jnp.where(is_tree & (n0 > 0), n0, jnp.uint32(1))
    bid = a.bucket_id[cur].astype(jnp.uint32)

    def cond(n):
        return jnp.any((n & 1) == 0)

    def body(n):
        live = (n & 1) == 0
        rows = a.node_weights[cur]                 # (N, T)
        safe = jnp.minimum(n, jnp.uint32(rows.shape[1] - 1)).astype(jnp.int32)
        w = jnp.take_along_axis(rows, safe[:, None], axis=1)[:, 0]
        h = hash32_4(x, n, r, bid).astype(jnp.uint64)
        t = (h * w.astype(jnp.uint64)) >> jnp.uint64(32)
        half = (n & (~n + jnp.uint32(1))) >> 1     # 1 << (h-1)
        left = n - half
        lsafe = jnp.minimum(
            left, jnp.uint32(rows.shape[1] - 1)).astype(jnp.int32)
        lw = jnp.take_along_axis(rows, lsafe[:, None], axis=1)[:, 0]
        nxt = jnp.where(t < lw.astype(jnp.uint64), left, n + half)
        return jnp.where(live, nxt, n)

    n = jax.lax.while_loop(cond, body, n0)
    leaf = (n >> 1).astype(jnp.int32)
    leaf = jnp.minimum(leaf, jnp.int32(a.items.shape[1] - 1))
    return jnp.take_along_axis(a.items[cur], leaf[:, None], axis=1)[:, 0]


def _uniform_winner(a: _Arrays, cur: jax.Array, x: jax.Array,
                    r: jax.Array) -> jax.Array:
    """Uniform-bucket winner (bucket_perm_choose, mapper.c:73-138): the
    permutation is a pure function of (x, bucket id) — each lane
    recomputes the Fisher-Yates prefix up to pr = r % size instead of
    consulting the reference's sequential perm cache, which is what
    makes uniform batchable at all.  Lanes whose bucket is not uniform
    compute garbage the caller selects away by alg."""
    from ceph_tpu.ops.crush_kernel import hash32_3
    size = jnp.maximum(a.bucket_size[cur], 1)          # (N,)
    pr = (r.astype(jnp.uint32)
          % size.astype(jnp.uint32)).astype(jnp.int32)
    bid = a.bucket_id[cur].astype(jnp.uint32)
    # loop bound: the largest UNIFORM bucket, not the map-wide widest
    # bucket (a straw2 root with hundreds of hosts would otherwise
    # multiply this loop's masked work for nothing)
    s_max = min(a.items.shape[1], max(a.max_uniform_size, 1))
    n = cur.shape[0]
    cols = jnp.arange(s_max, dtype=jnp.int32)[None, :]  # (1, S)
    perm0 = jnp.broadcast_to(cols, (n, s_max)).astype(jnp.int32)

    def body(p, perm):
        p32 = jnp.int32(p)
        # swap only while building the prefix (p <= pr) and while a
        # swap can matter (p < size-1); i == 0 swaps in place (no-op)
        live = (p32 <= pr) & (p32 < size - 1)
        span = jnp.maximum(size - p32, 1).astype(jnp.uint32)
        i = (hash32_3(x, bid, jnp.uint32(p))
             % span).astype(jnp.int32)              # (N,)
        idx = p32 + i
        val_p = perm[:, p]
        val_i = jnp.take_along_axis(perm, idx[:, None], axis=1)[:, 0]
        at_p = cols == p32
        at_i = cols == idx[:, None]
        swapped = jnp.where(at_i, val_p[:, None], perm)
        swapped = jnp.where(at_p, val_i[:, None], swapped)
        return jnp.where(live[:, None], swapped, perm)

    perm = jax.lax.fori_loop(0, s_max, body, perm0)
    s = jnp.take_along_axis(perm, pr[:, None], axis=1)[:, 0]
    return jnp.take_along_axis(a.items[cur], s[:, None], axis=1)[:, 0]


def _winner(a: _Arrays, cur: jax.Array, x: jax.Array, r: jax.Array) -> jax.Array:
    """Winner of bucket index ``cur`` for each lane: straw2 argmax (first max
    wins, mapper.c:361-384; choose_args overrides are scalar-path only),
    tree descent for tree buckets, or the recomputed uniform permutation
    — when the map contains those algs at all."""
    items_row = a.items[cur]                      # (N, S)
    w_row = a.weights[cur]                        # (N, S) — padding weight 0
    d = _straw2_draws_per_row(x, items_row, r, w_row)
    pos = jnp.argmax(d, axis=-1)
    out = jnp.take_along_axis(items_row, pos[:, None], axis=1)[:, 0]
    if a.has_tree:
        tw = _tree_winner(a, cur, x, r)
        out = jnp.where(
            a.bucket_alg[cur] == jnp.int32(CRUSH_BUCKET_TREE), tw, out)
    if a.has_uniform:
        uw = _uniform_winner(a, cur, x, r)
        out = jnp.where(
            a.bucket_alg[cur] == jnp.int32(CRUSH_BUCKET_UNIFORM),
            uw, out)
    return out


def _widx(a: _Arrays, item: jax.Array) -> jax.Array:
    """Bucket index of a (negative) item, clipped for safe gathering."""
    return jnp.clip(-1 - item, 0, a.n_buckets - 1)


def _wtype(a: _Arrays, item: jax.Array) -> jax.Array:
    """Type of an item: devices are 0, buckets their bucket_type."""
    return jnp.where(item < 0, a.bucket_type[_widx(a, item)], 0)


def _descend(a: _Arrays, x, start, r, want_type, active,
             ftotal=None, numrep: int = 0):
    """One full descent: from per-lane ``start`` bucket, draw and follow
    sub-buckets until an item of ``want_type`` (or a terminal failure).

    With ftotal/numrep given (the INDEP path), ``r`` is the BASE
    (rep + parent_r) and the retry offset is recomputed PER BUCKET on
    the way down: uniform buckets whose size divides numrep use
    (numrep+1)*ftotal instead of numrep*ftotal (mapper.c:720-728's
    "be careful" — without it the same permutation slot repeats on
    every retry and the position wedges).

    Returns (item, fail_perm, fail_retry):
      item       winner of want_type where neither failure flag is set
      fail_perm  skip_rep conditions — out-of-range device, wrong-type device,
                 unresolvable bucket (mapper.c:540-556 / 744-760)
      fail_retry empty bucket on the path (reject; mapper.c:533-537)
    """
    def cond(s):
        return jnp.any(s[3])

    def body(s):
        item, perm, retry, live, cur, rlast = s
        empty = a.bucket_size[cur] == 0
        if ftotal is None:
            rr = r
        else:
            mult = jnp.int32(numrep)
            if a.has_uniform and numrep > 0:
                special = ((a.bucket_alg[cur]
                            == jnp.int32(CRUSH_BUCKET_UNIFORM))
                           & (a.bucket_size[cur] % numrep == 0))
                mult = jnp.where(special, mult + 1, mult)
            rr = r + mult * ftotal
        win = _winner(a, cur, x, rr)
        wt = _wtype(a, win)
        oob = (win >= 0) & (win >= a.max_devices)
        reached = ~empty & ~oob & (wt == want_type)
        is_sub = win < 0
        new_perm = live & ~empty & ~reached & (oob | ~is_sub)
        new_retry = live & empty
        descend = live & ~empty & ~reached & ~new_perm
        item = jnp.where(live & reached, win, item)
        perm = perm | new_perm
        retry = retry | new_retry
        # the r actually used at the level that produced the winner:
        # the indep chooseleaf recursion inherits it as parent_r
        rlast = jnp.where(live, jnp.broadcast_to(rr, rlast.shape),
                          rlast)
        cur = jnp.where(descend, _widx(a, win), cur)
        live = descend
        return item, perm, retry, live, cur, rlast

    item0 = jnp.full_like(start, CRUSH_ITEM_NONE)
    f = jnp.zeros_like(active)
    r0 = jnp.broadcast_to(jnp.asarray(r, jnp.int32),
                          start.shape).astype(jnp.int32)
    out = jax.lax.while_loop(
        cond, body, (item0, f, f, active, start, r0))
    return out[0], out[1], out[2], out[5]


def _leaf_firstn(a: _Arrays, x, host_item, sub_r, leaf_out, rep, tries,
                 reweight, active):
    """chooseleaf recursion (stable tunable): choose 1 device inside
    ``host_item`` with r = sub_r + ftotal, colliding against leaves of earlier
    reps (out2 scoping, mapper.c:580-596).  Returns (leaf, ok)."""
    start = _widx(a, host_item)

    def cond(s):
        return jnp.any(s[2])

    def body(s):
        leaf, ftotal, live = s
        r = sub_r + ftotal
        item, perm, retry, _rl = _descend(a, x, start, r, 0, live)
        got = live & ~perm & ~retry
        collide = jnp.zeros_like(live)
        if rep > 0:
            collide = jnp.any(leaf_out[:, :rep] == item[:, None], axis=1)
        rejected = is_out(reweight, item, x)
        bad = collide | rejected | ~got
        leaf = jnp.where(live & got & ~bad, item, leaf)
        placed = live & got & ~bad
        ftotal = jnp.where(live & ~placed, ftotal + 1, ftotal)
        live = live & ~placed & ~perm & (ftotal < tries)
        return leaf, ftotal, live

    leaf0 = jnp.full_like(host_item, CRUSH_ITEM_NONE)
    leaf, _, _ = jax.lax.while_loop(
        cond, body, (leaf0, jnp.zeros_like(host_item), active))
    return leaf, leaf != NONE


def _choose_firstn(a: _Arrays, x, start, numrep, want_type, tries,
                   recurse_tries, vary_r, recurse_to_leaf, reweight, active):
    """Batched crush_choose_firstn (mapper.c:460-648), modern tunables.

    Returns (out, leaf_out): (N, numrep) int32, CRUSH_ITEM_NONE holes where a
    rep was abandoned (the scalar result is the NONE-compacted row).
    """
    n = x.shape[0]
    out = jnp.full((n, numrep), NONE, dtype=jnp.int32)
    leaf_out = jnp.full((n, numrep), NONE, dtype=jnp.int32)

    for rep in range(numrep):
        def cond(s):
            return jnp.any(s[3])

        def body(s, rep=rep):
            sel, leaf_sel, ftotal, live = s
            r = rep + ftotal
            item, perm, retry, _rl = _descend(a, x, start, r, want_type,
                                              live)
            got = live & ~perm & ~retry
            collide = jnp.any(out == item[:, None], axis=1) if numrep > 1 \
                else jnp.zeros_like(live)
            reject = jnp.zeros_like(live)
            leaf = jnp.full_like(item, CRUSH_ITEM_NONE)
            if recurse_to_leaf:
                # sub_r = vary_r ? r >> (vary_r-1) : 0 (mapper.c:578)
                sub_r = (r >> (vary_r - 1)) if vary_r else jnp.zeros_like(r)
                leaf, leaf_ok = _leaf_firstn(
                    a, x, item, sub_r, leaf_out, rep, recurse_tries,
                    reweight, got & ~collide)
                reject = got & ~collide & ~leaf_ok
            if want_type == 0:
                reject = reject | (got & is_out(reweight, item, x))
            bad = collide | reject | retry | ~got
            placed = live & ~perm & ~bad
            sel = jnp.where(placed, item, sel)
            if recurse_to_leaf:
                leaf_sel = jnp.where(placed, leaf, leaf_sel)
            ftotal = jnp.where(live & ~perm & bad, ftotal + 1, ftotal)
            live = live & ~perm & bad & (ftotal < tries)
            return sel, leaf_sel, ftotal, live

        sel0 = jnp.full((n,), NONE, dtype=jnp.int32)
        sel, leaf_sel, _, _ = jax.lax.while_loop(
            cond, body,
            (sel0, sel0, jnp.zeros((n,), jnp.int32), active))
        out = out.at[:, rep].set(sel)
        leaf_out = leaf_out.at[:, rep].set(leaf_sel)
    return out, leaf_out


def _leaf_indep(a: _Arrays, x, host_item, rep: int, parent_r, numrep_mult,
                tries, reweight, active):
    """indep chooseleaf recursion: positionally stable single-device pick at
    position ``rep``: r = rep + parent_r + numrep*ftotal with the parent's
    numrep as multiplier (the oracle's recursion wiring, mapper.c:794-806).
    Terminal (oob/wrong-type) failures are permanent, like the C break that
    leaves CRUSH_ITEM_NONE."""
    start = _widx(a, host_item)

    def cond(s):
        return jnp.any(s[2])

    def body(s):
        leaf, ftotal, live = s
        item, perm, retry, _rl = _descend(a, x, start, rep + parent_r,
                                          0, live, ftotal=ftotal,
                                          numrep=numrep_mult)
        got = live & ~perm & ~retry
        rejected = is_out(reweight, item, x)
        placed = got & ~rejected
        leaf = jnp.where(placed, item, leaf)
        ftotal = ftotal + 1
        live = live & ~placed & ~perm & (ftotal < tries)
        return leaf, ftotal, live

    leaf0 = jnp.full_like(host_item, CRUSH_ITEM_NONE)
    leaf, _, _ = jax.lax.while_loop(
        cond, body, (leaf0, jnp.zeros_like(host_item), active))
    return leaf, leaf != NONE


def _choose_indep(a: _Arrays, x, start, left, numrep_mult, want_type, tries,
                  recurse_tries, recurse_to_leaf, reweight, active):
    """Batched crush_choose_indep (mapper.c:655-843): breadth-first over
    ``left`` positions, r = rep + numrep*ftotal with the *step's* numrep as
    multiplier even when left < numrep; failures leave CRUSH_ITEM_NONE."""
    n = x.shape[0]
    out = jnp.full((n, left), NONE, dtype=jnp.int32)
    leaf_out = jnp.full((n, left), NONE, dtype=jnp.int32)
    undef = jnp.broadcast_to(active[:, None], (n, left)) & True

    def cond(s):
        out, leaf_out, undef, ftotal = s
        return jnp.any(undef) & (ftotal < tries)

    def body(s):
        out, leaf_out, undef, ftotal = s
        for rep in range(left):
            live = undef[:, rep]
            base = jnp.full((n,), rep, jnp.int32)
            item, perm, retry, host_r = _descend(
                a, x, start, base, want_type, live,
                ftotal=ftotal, numrep=numrep_mult)
            got = live & ~perm & ~retry
            collide = jnp.any(out == item[:, None], axis=1)
            reject = jnp.zeros_like(live)
            leaf = jnp.full_like(item, CRUSH_ITEM_NONE)
            if recurse_to_leaf:
                leaf, leaf_ok = _leaf_indep(
                    a, x, item, rep, host_r, numrep_mult,
                    recurse_tries, reweight, got & ~collide)
                reject = got & ~collide & ~leaf_ok
            if want_type == 0:
                reject = reject | (got & is_out(reweight, item, x))
            placed = got & ~collide & ~reject
            out = out.at[:, rep].set(jnp.where(placed, item, out[:, rep]))
            if recurse_to_leaf:
                leaf_out = leaf_out.at[:, rep].set(
                    jnp.where(placed, leaf, leaf_out[:, rep]))
            # perm: terminal failure, position stays NONE (mapper.c:744-760)
            undef = undef.at[:, rep].set(live & ~placed & ~perm)
        return out, leaf_out, undef, ftotal + 1

    out, leaf_out, _, _ = jax.lax.while_loop(
        cond, body, (out, leaf_out, undef, jnp.int32(0)))
    return out, leaf_out


def _compact_rows(rows: jax.Array) -> jax.Array:
    """Stable-compact NONE holes to the end of each row (firstn semantics:
    the scalar result is the dense prefix).  jnp.argsort is stable."""
    order = jnp.argsort(rows == NONE, axis=1)
    return jnp.take_along_axis(rows, order, axis=1)


class BatchMapper:
    """Batched crush_do_rule over a compiled map.

    >>> bm = BatchMapper(crush_map)
    >>> out = bm.do_rule(ruleno, xs, result_max, reweight)   # (N, result_max)

    firstn rules return NONE-compacted rows (dense prefix, NONE tail); indep
    rules return positionally-stable rows with NONE holes — matching the
    scalar crush_do_rule's list semantics in both cases.
    """

    def __init__(self, m: CrushMap, compiled: CompiledCrushMap | None = None):
        self.map = m
        self.compiled = compiled or compile_map(m)
        self.arrays = _Arrays(self.compiled)
        self._jit_cache: dict = {}
        self._fast_cache: dict = {}

    def _fastpath(self, ruleno: int):
        """Fused two-level kernel if the rule fits (crush.fastpath)."""
        if ruleno not in self._fast_cache:
            from . import fastpath
            fr = fastpath.detect(self.map, ruleno)
            self._fast_cache[ruleno] = (
                fastpath.FastMapper(fr) if fr is not None else None)
        return self._fast_cache[ruleno]

    def _jit_entries(self) -> int:
        """Compile-cache entries across every jitted rule evaluator —
        the telemetry retrace counter differences this per call."""
        return sum(f._cache_size() for f in self._jit_cache.values())

    def _fast_sharded_fn(self, fast, ruleno: int, result_max: int, xs):
        """The shard_map-wrapped fast path for a mesh-sharded batch:
        the Pallas column kernels are opaque custom calls GSPMD cannot
        split, so each device runs the full fused ladder on its local
        rows (row-independent by the oracle-equivalence contract) with
        the reweight vector replicated — PR 7's XLA-only routing guard
        for sharded fastpath batches, lifted.  Returns the jit-cache
        KEY, or None when the batch is not row-sharded (or
        single-device)."""
        from ceph_tpu.ops.gf_kernel import _multi_device, _row_sharding
        if not _multi_device(xs):
            return None
        sh = _row_sharding(xs)
        if sh is None:
            return None
        key = ("fast_sh", ruleno, result_max, sh)
        if key not in self._jit_cache:
            from ceph_tpu.ops.gf_kernel import build_sharded_rows_fn
            self._jit_cache[key] = build_sharded_rows_fn(
                functools.partial(fast.run, result_max=result_max),
                sh, n_replicated=1)
        return key

    def do_rule(self, ruleno: int, xs, result_max: int, reweight) -> jax.Array:
        xs = jnp.asarray(xs, dtype=jnp.uint32)
        reweight = jnp.asarray(reweight, dtype=jnp.int64)
        if (ruleno < 0 or ruleno >= self.map.max_rules
                or self.map.rules[ruleno] is None):
            # crush_do_rule returns empty for unknown rules (mapper.c:902-904)
            return jnp.full((xs.shape[0], result_max), NONE, dtype=jnp.int32)
        fast = self._fastpath(ruleno)
        if fast is not None:
            key = None
            if fast._pallas is not None:
                key = self._fast_sharded_fn(fast, ruleno, result_max, xs)
            if key is None:
                key = ("fast", ruleno, result_max)
                if key not in self._jit_cache:
                    self._jit_cache[key] = jax.jit(
                        functools.partial(fast.run,
                                          result_max=result_max))
        else:
            key = (ruleno, result_max)
            if key not in self._jit_cache:
                self._jit_cache[key] = jax.jit(
                    functools.partial(self._run, ruleno, result_max))
        fn = self._jit_cache[key]
        n = xs.shape[0]
        from ceph_tpu.ops import telemetry
        return telemetry.timed_kernel(
            "crush_map",
            lambda: fn(xs, reweight),
            batch=n, bytes_in=n * 4 + reweight.shape[0] * 8,
            bytes_out=n * result_max * 4,
            cache_entries=self._jit_entries,
            signature=("crush", id(self), key, n))

    # -- the rule interpreter (mapper.c:900-1105) -----------------------------

    def _run(self, ruleno: int, result_max: int, xs, reweight):
        a = self.arrays
        rule = self.map.rules[ruleno]
        n = xs.shape[0]
        t = self.map.tunables

        choose_tries = self.compiled.tunables_tries
        choose_leaf_tries = 0
        vary_r = t.chooseleaf_vary_r
        # working set: per-lane item ids, NONE-padded; starts empty
        w = jnp.full((n, result_max), NONE, dtype=jnp.int32)
        wsize = 0
        results = []

        for step in rule.steps:
            if step.op == RULE_TAKE:
                # validate like the reference (mapper.c:941-948): unknown
                # bucket / device -> the take is ignored
                ok = (0 <= step.arg1 < self.map.max_devices or
                      self.map.bucket(step.arg1) is not None)
                if ok:
                    w = w.at[:, 0].set(jnp.int32(step.arg1))
                    wsize = 1
            elif step.op == RULE_SET_CHOOSE_TRIES:
                if step.arg1 > 0:
                    choose_tries = step.arg1
            elif step.op == RULE_SET_CHOOSELEAF_TRIES:
                if step.arg1 > 0:
                    choose_leaf_tries = step.arg1
            elif step.op in (RULE_SET_CHOOSE_LOCAL_TRIES,
                             RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES):
                if step.arg1 > 0:
                    raise ValueError(
                        "legacy local-retry tunables are scalar-only")
            elif step.op == RULE_SET_CHOOSELEAF_VARY_R:
                if step.arg1 >= 0:
                    vary_r = step.arg1
            elif step.op == RULE_SET_CHOOSELEAF_STABLE:
                if step.arg1 >= 0 and step.arg1 != 1:
                    raise ValueError("batched mapper requires stable=1")
            elif step.op in (RULE_CHOOSE_FIRSTN, RULE_CHOOSELEAF_FIRSTN,
                             RULE_CHOOSE_INDEP, RULE_CHOOSELEAF_INDEP):
                if wsize == 0:
                    continue
                firstn = step.op in (RULE_CHOOSE_FIRSTN, RULE_CHOOSELEAF_FIRSTN)
                leafy = step.op in (RULE_CHOOSELEAF_FIRSTN,
                                    RULE_CHOOSELEAF_INDEP)
                # numrep <= 0 means result_max + numrep (mapper.c:1009-1014)
                numrep = step.arg1
                if numrep <= 0:
                    numrep += result_max
                    if numrep <= 0:
                        continue
                if firstn:
                    recurse = (choose_leaf_tries or
                               (1 if t.chooseleaf_descend_once
                                else choose_tries))
                else:
                    recurse = choose_leaf_tries if choose_leaf_tries else 1
                outs = []
                for i in range(wsize):
                    src = w[:, i]
                    active = src != NONE
                    start = _widx(a, src)
                    # a TAKE of a device id (src >= 0) is degenerate; treat
                    # as inactive like the reference's type check would
                    active = active & (src < 0)
                    if firstn:
                        # all numrep reps are attempted (count limiting in the
                        # reference only caps kept successes — equivalent to
                        # post-compaction truncation)
                        o, leaf = _choose_firstn(
                            a, xs, start, numrep, step.arg2, choose_tries,
                            recurse, vary_r, leafy, reweight, active)
                    else:
                        o, leaf = _choose_indep(
                            a, xs, start, min(numrep, result_max), numrep,
                            step.arg2, choose_tries, recurse,
                            leafy, reweight, active)
                    outs.append(leaf if leafy else o)
                new_w = jnp.concatenate(outs, axis=1)[:, :result_max]
                if firstn:
                    new_w = _compact_rows(new_w)
                w = jnp.full((n, result_max), NONE, dtype=jnp.int32)
                w = w.at[:, :new_w.shape[1]].set(new_w)
                wsize = new_w.shape[1]
            elif step.op == RULE_EMIT:
                results.append(w[:, :wsize])
                w = jnp.full((n, result_max), NONE, dtype=jnp.int32)
                wsize = 0
        if not results:
            return jnp.full((n, result_max), NONE, dtype=jnp.int32)
        res = jnp.concatenate(results, axis=1)[:, :result_max]
        pad = result_max - res.shape[1]
        if pad > 0:
            res = jnp.concatenate(
                [res, jnp.full((n, pad), NONE, dtype=jnp.int32)], axis=1)
        return res
