"""rbd-mirror — journal-based cross-cluster image replication
(src/tools/rbd_mirror/ + librbd/Journal.h analog).

A journaled primary image appends every mutation to its per-image
Journaler before applying (rbd.Image._journal_event).  The mirror
daemon tails that journal from a second cluster and replays events onto
a demoted (non-primary) copy of the image:

* the replay position is persisted ON THE MIRROR cluster after every
  applied event (`rbd_mirror.<image>` omap — the journal client
  position rbd-mirror registers), so a daemon crash mid-replay resumes
  exactly where it stopped; events carry absolute offsets/states, so
  an event re-applied across the crash window is idempotent
* the mirror image is created on first contact and demoted — writes to
  it are refused until promotion
* failover = demote the old primary (or it is simply dead), promote the
  mirror copy (Image.promote), point clients at it; failback runs the
  same machinery the other way
* after a full replay the daemon trims the primary journal up to the
  mirrored position (the journal client expiry that bounds journal
  growth in the reference)
"""

from __future__ import annotations

import binascii
import json

from ceph_tpu.osdc.journaler import Journaler
from ceph_tpu.rbd import FEATURE_JOURNALING, Image


class MirrorDaemon:
    """Replays journaled images from a primary ioctx to a mirror ioctx."""

    STATE_FMT = "rbd_mirror.{name}"

    def __init__(self, src_ioctx, dst_ioctx, trim: bool = True):
        self.src = src_ioctx
        self.dst = dst_ioctx
        self.trim = trim

    # -- position bookkeeping (on the MIRROR cluster) -------------------------

    def _position(self, name: str) -> int:
        try:
            omap = self.dst.get_omap(self.STATE_FMT.format(name=name))
        except OSError:
            return 0
        return int(omap.get("pos", b"0").decode())

    def _save_position(self, name: str, pos: int) -> None:
        self.dst.set_omap(self.STATE_FMT.format(name=name),
                          {"pos": str(pos).encode()})

    # -- resync flag (rbd mirror image resync analog) -------------------------

    def needs_resync(self, name: str) -> bool:
        try:
            omap = self.dst.get_omap(self.STATE_FMT.format(name=name))
        except OSError:
            return False
        return omap.get("needs_resync", b"0") == b"1"

    def _mark_resync(self, name: str) -> None:
        self.dst.set_omap(self.STATE_FMT.format(name=name),
                          {"needs_resync": b"1"})

    def resync_image(self, name: str) -> None:
        """Re-bootstrap the mirror copy from the primary and clear the
        resync flag (rbd mirror image resync).  A true re-bootstrap:
        the mirror's snapshots and data are discarded, the primary's
        snapshot HISTORY is rebuilt in snapid order (content at each
        snap copied, then snapped), and finally the head content lands.
        The journal position snapshots BEFORE the copy: events appended
        during it replay afterwards (idempotent), events before it are
        superseded by the copied state."""
        src_img = Image(self.src, name)
        dst_img = self._mirror_image(name, src_img)
        if dst_img.is_primary():
            raise OSError(16, f"cannot resync promoted image {name!r}")
        j = Journaler(self.src, Image.JOURNAL_FMT.format(name=name))
        j.open()
        pos = j.write_pos

        def copy_state(size: int, snap: str | None) -> None:
            # zero slate first: truncating to 0 discards stale mirror
            # bytes, so skipped all-zero chunks really read back zero
            dst_img.mirror_apply({"op": "resize", "size": 0})
            dst_img.mirror_apply({"op": "resize", "size": size})
            step = 1 << 22
            for off in range(0, size, step):
                chunk = src_img.read(off, min(step, size - off),
                                     snap=snap)
                if chunk.rstrip(b"\x00"):
                    dst_img.mirror_apply({
                        "op": "write", "off": off,
                        "data": binascii.hexlify(chunk).decode()})

        for snap in list(dst_img.snap_list()):
            dst_img.mirror_apply({"op": "snap_remove", "snap": snap})
        for snap, ent in sorted(src_img.snap_list().items(),
                                key=lambda kv: kv[1]["snapid"]):
            copy_state(ent["size"], snap)
            dst_img.mirror_apply({"op": "snap_create", "snap": snap})
        copy_state(src_img.stat()["size"], None)
        self.dst.set_omap(self.STATE_FMT.format(name=name),
                          {"pos": str(pos).encode(),
                           "needs_resync": b"0"})

    # -- replay ---------------------------------------------------------------

    def _mirror_image(self, name: str, src_img: Image) -> Image:
        try:
            st = src_img.stat()
            # created demoted AND journaled in one header write: no
            # primary window for a crash to leave open, and a later
            # promotion journals its own writes so failback
            # (MirrorDaemon(dst, src)) replicates them straight back
            return Image.create(self.dst, name, size=st["size"],
                                order=st["order"],
                                stripe_unit=st["stripe_unit"],
                                stripe_count=st["stripe_count"],
                                primary=False,
                                features=[FEATURE_JOURNALING])
        except FileExistsError:
            return Image(self.dst, name)

    def replay_image(self, name: str, max_events: int | None = None) -> int:
        """Tail one image's journal; returns events applied.
        ``max_events`` exists for crash-window tests."""
        src_img = Image(self.src, name)
        if FEATURE_JOURNALING not in src_img.features():
            return 0
        dst_img = self._mirror_image(name, src_img)
        if dst_img.is_primary():
            # split-brain guard: never replay onto a promoted image
            # (rbd-mirror refuses and flags the pair for resync)
            return 0
        if self.needs_resync(name):
            # a poison event already wedged this image: replay stays
            # paused until the operator (or a caller) runs resync_image
            return 0
        j = Journaler(self.src, Image.JOURNAL_FMT.format(name=name))
        j.open()
        start = self._position(name)
        applied = 0

        class _Stop(Exception):
            pass

        def apply(payload: bytes, end_pos: int) -> None:
            nonlocal applied
            if max_events is not None and applied >= max_events:
                raise _Stop()
            try:
                dst_img.mirror_apply(json.loads(payload.decode()))
            except (KeyError, ValueError):
                # a deterministic semantic failure (e.g. rollback to a
                # snapshot the mirror never received — one taken before
                # journaling was enabled): retrying can never converge.
                # Flag the image for resync and pause ITS replay; the
                # sweep must keep serving every other image (the
                # reference marks the pair split-brained the same way)
                self._mark_resync(name)
                raise _Stop()
            # position AFTER apply: a crash between the two re-applies
            # this (idempotent) event instead of skipping it
            self._save_position(name, end_pos)
            applied += 1

        try:
            j.replay(apply, start_pos=start)
        except _Stop:
            pass
        if self.trim and applied and max_events is None:
            j.trim(upto=self._position(name))
        return applied

    def run_once(self, images: list[str] | None = None) -> dict[str, int]:
        """One replay sweep over the pool's journaled images."""
        from ceph_tpu.rbd import list_images
        out = {}
        for name in images or list_images(self.src):
            out[name] = self.replay_image(name)
        return out


def promote(ioctx, name: str) -> None:
    """Failover: make the mirror copy writable (rbd mirror image promote)."""
    Image(ioctx, name).promote()


def demote(ioctx, name: str) -> None:
    """Make an image a replication target (rbd mirror image demote)."""
    Image(ioctx, name).demote()
