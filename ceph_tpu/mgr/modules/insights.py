"""Insights module — cluster-wide slow-trace and slow-op aggregation
(src/pybind/mgr/insights reduced to the observability tier this repo
needs).

Every daemon ships its tail-sampled slow traces (completed span trees
whose root crossed ``tracing_slow_threshold``) and its historic
slow-op digests in MMgrReport v4; this module merges them across the
cluster, ranks the slowest, and serves three mgr commands:

  * ``tracing ls``        — slowest retained traces cluster-wide
  * ``tracing show <id>`` — one trace's stitched span TREE (rows from
                            every reporting daemon merged by span_id)
  * ``slow_ops``          — slowest completed ops across all daemons

The in-process MiniCluster shares one tracing table so every daemon
reports the same ring (merged here by trace_id); multi-process daemons
each ship only their own spans and the merge stitches the cross-daemon
tree, exactly like zipkin collectors joining on trace id.
"""

from __future__ import annotations

import json

from ceph_tpu.mgr.module import MgrModule


class Module(MgrModule):
    NAME = "insights"
    COMMANDS = [
        {"prefix": "tracing ls",
         "help": "slowest tail-retained traces across all daemons"},
        {"prefix": "tracing show",
         "help": "render one trace's stitched span tree "
                 "(trace_id=<id>)"},
        {"prefix": "slow_ops",
         "help": "slowest completed ops across all daemons"},
    ]

    # -- aggregation ----------------------------------------------------------

    def _feed(self) -> dict:
        return self.get("insights_feed")

    def traces(self) -> dict[int, dict]:
        """trace_id -> merged digest: rows unioned across reporting
        daemons (dedup by (kind, span_id, event, t)), root metadata
        from the richest report."""
        merged: dict[int, dict] = {}
        seen: dict[int, set] = {}
        for osd, feed in sorted(self._feed().items()):
            for digest in feed.get("slow_traces", []):
                tid = digest.get("trace_id")
                if tid is None:
                    continue
                cur = merged.get(tid)
                if cur is None:
                    cur = {"trace_id": tid,
                           "root": digest.get("root"),
                           "daemon": digest.get("daemon"),
                           "duration": digest.get("duration", 0.0),
                           "completed_at": digest.get("completed_at"),
                           "reported_by": [],
                           "rows": []}
                    merged[tid] = cur
                    seen[tid] = set()
                cur["reported_by"].append(osd)
                cur["duration"] = max(cur["duration"],
                                      digest.get("duration", 0.0))
                for r in digest.get("rows", []):
                    key = (r.get("kind"), r.get("span_id"),
                           r.get("event"), r.get("t"))
                    if key in seen[tid]:
                        continue
                    seen[tid].add(key)
                    cur["rows"].append(r)
        for cur in merged.values():
            cur["rows"].sort(key=lambda r: r.get("t", 0.0))
        return merged

    def tracing_ls(self, limit: int = 20) -> list[dict]:
        ranked = sorted(self.traces().values(),
                        key=lambda tr: -tr["duration"])[:limit]
        return [{"trace_id": tr["trace_id"], "root": tr["root"],
                 "daemon": tr["daemon"],
                 "duration": tr["duration"],
                 "n_rows": len(tr["rows"]),
                 "reported_by": tr["reported_by"]}
                for tr in ranked]

    def tracing_show(self, trace_id: int) -> dict | None:
        from ceph_tpu.common.tracing import tree_from_rows
        tr = self.traces().get(trace_id)
        if tr is None:
            return None
        return {"trace_id": trace_id, "duration": tr["duration"],
                "reported_by": tr["reported_by"],
                "tree": tree_from_rows(tr["rows"])}

    def slow_ops(self, limit: int = 20) -> list[dict]:
        ops = []
        for _osd, feed in sorted(self._feed().items()):
            ops.extend(feed.get("slow_ops", []))
        # in-process daemons never collide (per-daemon trackers), but a
        # re-reported digest from consecutive reports must not rank twice
        uniq = {(o.get("daemon"), o.get("description"),
                 o.get("initiated_at")): o for o in ops}
        return sorted(uniq.values(),
                      key=lambda o: -o.get("duration", 0.0))[:limit]

    # -- command tier ---------------------------------------------------------

    def handle_command(self, cmd: dict) -> tuple[str, int]:
        prefix = cmd.get("prefix", "")
        if prefix == "tracing ls":
            limit = int(cmd.get("limit", 20))
            return json.dumps({"traces": self.tracing_ls(limit)}), 0
        if prefix == "tracing show":
            raw = cmd.get("trace_id")
            if raw is None:
                return "tracing show needs trace_id=<id>", -22
            out = self.tracing_show(int(raw))
            if out is None:
                return f"no retained trace {raw}", -2
            return json.dumps(out), 0
        if prefix == "slow_ops":
            limit = int(cmd.get("limit", 20))
            return json.dumps({"ops": self.slow_ops(limit)}), 0
        return f"module {self.NAME} has no command {prefix!r}", -22
