"""Cross-daemon trace propagation over the REAL TCP messengers (not
loopback): a traced EC write must yield ONE stitched span tree whose
shard sub-spans parent (transitively) under the primary's dispatch
span, with device spans attached where the encode ran."""

from __future__ import annotations

import pytest

from ceph_tpu.common import tracing
from ceph_tpu.tools.vstart import MiniCluster


@pytest.fixture(autouse=True)
def _clean_tracing():
    tracing.reset()
    yield
    tracing.reset()


def _ancestor_ids(spans: dict, row: dict) -> set:
    out = set()
    cur = row
    while cur["parent_span_id"] and cur["parent_span_id"] in spans:
        cur = spans[cur["parent_span_id"]]
        out.add(cur["span_id"])
    return out


def test_ec_write_stitches_one_span_tree_over_tcp():
    c = MiniCluster(n_osds=4, ms_type="async").start()
    try:
        c.wait_for_osd_count(4)
        client = c.client(timeout=20.0)
        pool = c.create_pool(client, pg_num=1, pool_type="erasure",
                             k=2, m=1)
        io = client.open_ioctx(pool)
        io.write_full("warm", b"w" * 4096)     # peering settled

        with tracing.trace_ctx(name="ec write", daemon="client") as tid:
            io.write_full("traced-tcp", b"T" * 8192)

        rows = tracing.dump(tid)
        assert rows, "no span rows recorded"
        spans = {r["span_id"]: r for r in rows if r["kind"] == "span"}

        # ONE tree: a single root (the client's trace_ctx span), and
        # every other span's parent resolves inside the trace
        roots = [r for r in spans.values() if not r["parent_span_id"]]
        assert len(roots) == 1 and roots[0]["event"] == "ec write", roots
        for r in spans.values():
            if r["parent_span_id"]:
                assert r["parent_span_id"] in spans, \
                    f"orphan span {r} — tree is torn"

        # the tree spans client + >= k+m osd daemons
        daemons = {r["daemon"] for r in spans.values()}
        assert any(d.startswith("client.") for d in daemons), daemons
        assert len({d for d in daemons if d.startswith("osd.")}) >= 3

        # the primary's rx dispatch span for the client op...
        rx_op = [r for r in spans.values()
                 if r["event"] == "rx MOSDOp"
                 and r["daemon"].startswith("osd.")]
        assert rx_op, "no primary dispatch span"
        prim_ids = {r["span_id"] for r in rx_op}

        # ...is an ancestor of every shard sub-op dispatch span
        shard_rx = [r for r in spans.values()
                    if r["event"] == "rx MOSDECSubOpWrite"]
        assert len(shard_rx) >= 2, spans
        for r in shard_rx:
            assert _ancestor_ids(spans, r) & prim_ids, \
                f"shard span {r} not under the primary's dispatch"

        # device span attached under the primary with h2d/compute
        # events and the retrace attribute
        dev = [r for r in spans.values()
               if r["event"] == "device ec_encode"]
        assert dev, "no device span on the traced write"
        assert _ancestor_ids(spans, dev[0]) & prim_ids
        assert "retrace" in dev[0]["attrs"]
        dev_events = [r["event"] for r in rows if r["kind"] == "event"
                      and r["span_id"] == dev[0]["span_id"]]
        assert any(e.startswith("h2d ") for e in dev_events), dev_events
        assert any(e.startswith("compute ") for e in dev_events)

        # objectstore commit spans sit inside the tree too
        assert any(r["event"] == "objectstore commit"
                   for r in spans.values()), daemons

        # the client's rx of the reply closes the round trip after the
        # first osd rx of the op
        t_op = min(r["t"] for r in rows if r["event"] == "rx MOSDOp")
        t_reply = max(r["t"] for r in rows
                      if "rx MOSDOpReply" in r["event"])
        assert t_reply >= t_op

        # an untraced op afterwards records nothing into this trace
        io.write_full("untraced", b"u")
        assert len(tracing.dump(tid)) == len(rows)
    finally:
        c.stop()
