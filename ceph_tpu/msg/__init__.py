"""Cluster communication (reference layer 2: src/msg/ + src/messages/).

Entity-addressed, policy-governed, typed-message transport:

  encoding     versioned binary encode/decode (bufferlist + denc analog)
  message      Message base + type registry (154-type catalog analog;
               ceph_tpu.messages holds the concrete types)
  messenger    Messenger/Connection/Dispatcher/Policy abstraction
               (msg/Messenger.h:120, msg/Policy.h)
  async_tcp    asyncio TCP stack with banner handshake + length-prefixed
               crc-checked frames (AsyncMessenger/ProtocolV1 analog)
  loopback     in-process stack for unit tests (testmsgr analog)

The TPU data plane (shard fan-out over ICI) lives in ceph_tpu.parallel as XLA
collectives; this layer carries the control plane and host<->host data path,
standing where posix/rdma/dpdk stacks stand in the reference (SURVEY.md §5).
"""

from .encoding import Encoder, Decoder
from .message import Message, register_message
from .messenger import (
    ConnectionPolicy, Dispatcher, EntityName, Messenger)

__all__ = [
    "Encoder", "Decoder", "Message", "register_message",
    "Messenger", "Dispatcher", "EntityName", "ConnectionPolicy",
]
