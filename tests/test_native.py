"""Cross-validation of the native (C) single-core baseline kernels against
the in-repo oracles: GF(2^8) encode vs the numpy reference, and the scalar C
crush_do_rule vs crush.mapper_ref across map shapes, weights, and rule modes.

These guarantee bench.py's vs_baseline denominators compute the same math the
TPU kernels do."""

from __future__ import annotations

import numpy as np
import pytest

from ceph_tpu.crush import build_flat_map, build_two_level_map
from ceph_tpu.crush.builder import add_simple_rule
from ceph_tpu.crush.mapper_ref import crush_do_rule
from ceph_tpu.native import CrushBaseline, ec_encode_native
from ceph_tpu.ops.gf_kernel import ec_encode_ref


# -- GF encode ---------------------------------------------------------------

@pytest.mark.parametrize("k,m,chunk", [(2, 1, 64), (4, 2, 4096),
                                       (8, 4, 4096), (10, 4, 1000),
                                       (8, 3, 33)])
def test_ec_encode_c_matches_numpy_oracle(k, m, chunk):
    rng = np.random.default_rng(k * 100 + m)
    matrix = rng.integers(0, 256, (m, k), dtype=np.uint8)
    data = rng.integers(0, 256, (7, k, chunk), dtype=np.uint8)
    got = ec_encode_native(matrix, data)
    want = ec_encode_ref(matrix, data)
    assert (got == want).all()


def test_ec_encode_c_special_coefficients():
    # identity / zero coefficients exercise the c==0 / c==1 table rows
    matrix = np.array([[0, 1, 2, 255], [1, 0, 128, 3]], dtype=np.uint8)
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (3, 4, 256), dtype=np.uint8)
    assert (ec_encode_native(matrix, data) == ec_encode_ref(matrix, data)).all()


# -- CRUSH -------------------------------------------------------------------

def _compare(m, rid, xs, numrep, weights):
    cb = CrushBaseline(m)
    try:
        for x in xs:
            want = crush_do_rule(m, rid, x, numrep, weights)
            got = cb.do_rule(rid, x, numrep, weights)
            assert got == want, (x, got, want)
    finally:
        cb.close()


def test_crush_c_flat_firstn_uniform():
    m, _root, rid = build_flat_map(32)
    weights = [0x10000] * 32
    _compare(m, rid, range(512), 3, weights)


def test_crush_c_flat_indep():
    m, _root, _rid = build_flat_map(24)
    weights = [0x10000] * 24
    _compare(m, 1, range(512), 6, weights)


def test_crush_c_two_level_chooseleaf():
    m, _root, rid = build_two_level_map(8, 4)
    weights = [0x10000] * 32
    _compare(m, rid, range(512), 3, weights)


def test_crush_c_nonuniform_weights_and_reweight():
    rng = np.random.default_rng(7)
    m, _root, rid = build_two_level_map(6, 5)
    # skew the straw2 item weights inside each host bucket
    for b in m.buckets:
        if b is not None and b.type == 1:
            b.item_weights = [int(w) for w in
                              rng.integers(0x4000, 0x30000, b.size)]
    weights = [int(w) for w in rng.integers(0, 0x10001, 30)]  # reweights
    weights[3] = 0  # one fully out
    _compare(m, rid, range(256), 3, weights)


def test_crush_c_indep_two_level():
    m, _root, _rid = build_two_level_map(8, 4)
    rid = add_simple_rule(m, -1, 1, "indep")
    weights = [0x10000] * 32
    _compare(m, rid, range(256), 4, weights)


def test_crush_c_batch_matches_scalar():
    m, _root, rid = build_two_level_map(10, 4)
    weights = np.full(40, 0x10000, dtype=np.uint32)
    xs = np.arange(200, dtype=np.uint32)
    cb = CrushBaseline(m)
    try:
        batch = cb.do_rule_batch(rid, xs, 3, weights)
        for i, x in enumerate(xs):
            want = crush_do_rule(m, rid, int(x), 3, list(weights))
            got = [int(v) for v in batch[i] if v != 0x7FFFFFFF]
            assert got == want
    finally:
        cb.close()


def test_crush_c_tree_buckets():
    # tree host buckets under a straw2 root, and a pure tree root: the C
    # descent (bucket_tree_choose) must match the oracle's mapper.c:195-222
    from ceph_tpu.crush.types import CRUSH_BUCKET_TREE
    m, _root, rid = build_two_level_map(8, 4, host_alg=CRUSH_BUCKET_TREE)
    _compare(m, rid, range(256), 3, [0x10000] * 32)

    rng = np.random.default_rng(3)
    weights = [int(w) for w in rng.integers(0x4000, 0x30000, 19)]
    from ceph_tpu.crush import build_flat_map as _bfm
    m2, _root2, rid2 = _bfm(19, weights=weights, alg=CRUSH_BUCKET_TREE)
    rw = [int(w) for w in rng.integers(0, 0x10001, 19)]
    _compare(m2, rid2, range(256), 3, rw)


def test_crush_c_result_max_guard_raises():
    # result_max beyond the fixed 64-slot working set must be a loud error,
    # never a silent empty result
    m, _root, rid = build_flat_map(8)
    cb = CrushBaseline(m)
    try:
        with pytest.raises(ValueError):
            cb.do_rule(rid, 1, 65, [0x10000] * 8)
        with pytest.raises(ValueError):
            cb.do_rule_batch(rid, np.arange(4, dtype=np.uint32), 65,
                             np.full(8, 0x10000, dtype=np.uint32))
    finally:
        cb.close()
