"""Insights module — cluster-wide slow-trace and slow-op aggregation
(src/pybind/mgr/insights reduced to the observability tier this repo
needs).

Every daemon ships its tail-sampled slow traces (completed span trees
whose root crossed ``tracing_slow_threshold``), its historic slow-op
digests, and its pipeline-profile phase digest in MMgrReport v4; this
module merges them across the cluster, ranks the slowest, and serves
five mgr commands:

  * ``tracing ls``        — slowest retained traces cluster-wide
  * ``tracing show <id>`` — one trace's stitched span TREE (rows from
                            every reporting daemon merged by span_id)
  * ``slow_ops``          — slowest completed ops across all daemons
  * ``profile phases``    — cluster-wide where-did-the-time-go: phase
                            seconds/shares per engine × kernel family,
                            compile ledger, mapping epoch split
  * ``profile top``       — top-N (engine, kernel, phase) stalls by
                            cluster-total seconds

The in-process MiniCluster shares one tracing table so every daemon
reports the same ring (merged here by trace_id); multi-process daemons
each ship only their own spans and the merge stitches the cross-daemon
tree, exactly like zipkin collectors joining on trace id.  Profile
digests merge by SUMMING phase seconds across daemons (multi-process
daemons have distinct telemetry registries, so engine pipelines are
distinct), with one dedup rule mirroring the tracing/slow-op merges:
daemons shipping a byte-identical digest are reading ONE shared
process-global registry (the in-process MiniCluster topology), so
they contribute once, with every reporter listed — otherwise an
N-daemon in-process cluster would inflate every total N-fold.
"""

from __future__ import annotations

import json

from ceph_tpu.mgr.module import MgrModule


class Module(MgrModule):
    NAME = "insights"
    COMMANDS = [
        {"prefix": "tracing ls",
         "help": "slowest tail-retained traces across all daemons"},
        {"prefix": "tracing show",
         "help": "render one trace's stitched span tree "
                 "(trace_id=<id>)"},
        {"prefix": "slow_ops",
         "help": "slowest completed ops across all daemons"},
        {"prefix": "profile phases",
         "help": "cluster-wide pipeline phase attribution per engine "
                 "and kernel family (seconds + shares, compile "
                 "ledger, mapping epoch split)"},
        {"prefix": "profile top",
         "help": "top-N (engine, kernel, phase) stalls by "
                 "cluster-total seconds (limit=<n>)"},
        {"prefix": "integrity",
         "help": "cluster-wide background-integrity rollup: per-osd "
                 "deep-scrub counters (objects checked, batched vs "
                 "scalar digests, inconsistencies found, repairs "
                 "verified/unverified, missing-peer scrubs) and the "
                 "cluster totals"},
    ]

    # -- aggregation ----------------------------------------------------------

    def _feed(self) -> dict:
        return self.get("insights_feed")

    def traces(self) -> dict[int, dict]:
        """trace_id -> merged digest: rows unioned across reporting
        daemons (dedup by (kind, span_id, event, t)), root metadata
        from the richest report."""
        merged: dict[int, dict] = {}
        seen: dict[int, set] = {}
        for osd, feed in sorted(self._feed().items()):
            for digest in feed.get("slow_traces", []):
                tid = digest.get("trace_id")
                if tid is None:
                    continue
                cur = merged.get(tid)
                if cur is None:
                    cur = {"trace_id": tid,
                           "root": digest.get("root"),
                           "daemon": digest.get("daemon"),
                           "duration": digest.get("duration", 0.0),
                           "completed_at": digest.get("completed_at"),
                           "reported_by": [],
                           "rows": []}
                    merged[tid] = cur
                    seen[tid] = set()
                cur["reported_by"].append(osd)
                cur["duration"] = max(cur["duration"],
                                      digest.get("duration", 0.0))
                for r in digest.get("rows", []):
                    key = (r.get("kind"), r.get("span_id"),
                           r.get("event"), r.get("t"))
                    if key in seen[tid]:
                        continue
                    seen[tid].add(key)
                    cur["rows"].append(r)
        for cur in merged.values():
            cur["rows"].sort(key=lambda r: r.get("t", 0.0))
        return merged

    def tracing_ls(self, limit: int = 20) -> list[dict]:
        ranked = sorted(self.traces().values(),
                        key=lambda tr: -tr["duration"])[:limit]
        return [{"trace_id": tr["trace_id"], "root": tr["root"],
                 "daemon": tr["daemon"],
                 "duration": tr["duration"],
                 "n_rows": len(tr["rows"]),
                 "reported_by": tr["reported_by"]}
                for tr in ranked]

    def tracing_show(self, trace_id: int) -> dict | None:
        from ceph_tpu.common.tracing import tree_from_rows
        tr = self.traces().get(trace_id)
        if tr is None:
            return None
        return {"trace_id": trace_id, "duration": tr["duration"],
                "reported_by": tr["reported_by"],
                "tree": tree_from_rows(tr["rows"])}

    def slow_ops(self, limit: int = 20) -> list[dict]:
        ops = []
        for _osd, feed in sorted(self._feed().items()):
            ops.extend(feed.get("slow_ops", []))
        # in-process daemons never collide (per-daemon trackers), but a
        # re-reported digest from consecutive reports must not rank twice
        uniq = {(o.get("daemon"), o.get("description"),
                 o.get("initiated_at")): o for o in ops}
        return sorted(uniq.values(),
                      key=lambda o: -o.get("duration", 0.0))[:limit]

    # -- pipeline-profile aggregation -----------------------------------------

    def profile_phases(self) -> dict:
        """Cluster-merged where-did-the-time-go: per engine × kernel
        family, phase seconds summed across every reporting daemon
        (shares recomputed over the merged totals), the compile
        ledger, utilization per daemon, and the mapping epoch split."""
        engines: dict = {}
        compile_: dict = {}
        util: dict = {}
        mapping = {"seconds": {}, "epochs": 0}
        # dedup byte-identical digests (shared in-process registry —
        # see module docstring): one contribution, every reporter
        by_digest: dict = {}
        for osd, feed in sorted(self._feed().items()):
            prof = feed.get("profile") or {}
            if not prof:
                continue
            key = json.dumps(prof, sort_keys=True)
            entry = by_digest.setdefault(key, (prof, []))
            entry[1].append(osd)
        for prof, osds in by_digest.values():
            for engine in ("encode", "decode"):
                d = prof.get(engine) or {}
                for kernel, row in (d.get("kernels") or {}).items():
                    cur = engines.setdefault(engine, {}).setdefault(
                        kernel, {"seconds": {}, "batches": 0,
                                 "reported_by": []})
                    for ph, s in (row.get("seconds") or {}).items():
                        cur["seconds"][ph] = \
                            cur["seconds"].get(ph, 0.0) + s
                    cur["batches"] += row.get("batches", 0)
                    cur["reported_by"].extend(osds)
                for kernel, c in (d.get("compile") or {}).items():
                    cc = compile_.setdefault(engine, {}).setdefault(
                        kernel, {"seconds": 0.0, "events": 0,
                                 "reported_by": []})
                    cc["seconds"] += c.get("seconds", 0.0)
                    cc["events"] += c.get("events", 0)
                    cc["reported_by"].extend(osds)
                if d:
                    for o in osds:   # gauges, not sums: safe to
                        # repeat for every daemon sharing the digest
                        util.setdefault(engine, {})[f"osd.{o}"] = {
                            "busy_seconds": d.get("busy_seconds", 0.0),
                            "utilization": d.get("utilization", 0.0),
                            "devices_seen": d.get("devices_seen", 1)}
            mp = prof.get("mapping") or {}
            for ph, s in (mp.get("seconds") or {}).items():
                mapping["seconds"][ph] = \
                    mapping["seconds"].get(ph, 0.0) + s
            mapping["epochs"] += mp.get("epochs", 0)
        for per in engines.values():
            for cur in per.values():
                total = sum(cur["seconds"].values())
                cur["share"] = {
                    ph: (round(s / total, 4) if total else 0.0)
                    for ph, s in cur["seconds"].items()}
        return {"engines": engines, "compile": compile_,
                "utilization": util, "mapping": mapping}

    def profile_top(self, limit: int = 10) -> list[dict]:
        """Ranked (engine, kernel, phase) rows by cluster-total
        seconds — the top stalls.  Compile cost ranks too, as its own
        ``compile`` phase row, so a retrace storm surfaces next to a
        queue-wait stall instead of hiding in a separate ledger."""
        merged = self.profile_phases()
        rows = []
        for engine, per in merged["engines"].items():
            for kernel, cur in per.items():
                total = sum(cur["seconds"].values())
                for ph, s in cur["seconds"].items():
                    rows.append({
                        "engine": engine, "kernel": kernel,
                        "phase": ph, "seconds": round(s, 6),
                        "share": (round(s / total, 4) if total
                                  else 0.0),
                        "reported_by": cur["reported_by"]})
        for engine, per in merged["compile"].items():
            for kernel, c in per.items():
                rows.append({
                    "engine": engine, "kernel": kernel,
                    "phase": "compile",
                    "seconds": round(c["seconds"], 6),
                    "share": None,
                    "events": c["events"],
                    "reported_by": c["reported_by"]})
        rows.sort(key=lambda r: -r["seconds"])
        return rows[:limit]

    # -- background integrity -------------------------------------------------

    def integrity(self) -> dict:
        """Cluster-wide scrub rollup from the MMgrReport v5 scrub
        tail: per-daemon counters plus summed totals.  The headline
        invariant the operator watches: ``repair_unverified`` stays 0
        — every repair the scrub path fired had its digest re-fetched
        and matched."""
        try:
            feed = self.get("scrub_feed")
        except Exception:
            feed = {}
        totals: dict = {}
        per_osd = {}
        for osd, entry in sorted(feed.items()):
            per_osd[f"osd.{osd}"] = dict(entry)
            for k, v in entry.items():
                if isinstance(v, (int, float)):
                    totals[k] = totals.get(k, 0) + v
        return {"totals": totals, "per_osd": per_osd}

    # -- command tier ---------------------------------------------------------

    def handle_command(self, cmd: dict) -> tuple[str, int]:
        prefix = cmd.get("prefix", "")
        if prefix == "tracing ls":
            limit = int(cmd.get("limit", 20))
            return json.dumps({"traces": self.tracing_ls(limit)}), 0
        if prefix == "tracing show":
            raw = cmd.get("trace_id")
            if raw is None:
                return "tracing show needs trace_id=<id>", -22
            out = self.tracing_show(int(raw))
            if out is None:
                return f"no retained trace {raw}", -2
            return json.dumps(out), 0
        if prefix == "slow_ops":
            limit = int(cmd.get("limit", 20))
            return json.dumps({"ops": self.slow_ops(limit)}), 0
        if prefix == "profile phases":
            return json.dumps(self.profile_phases()), 0
        if prefix == "profile top":
            limit = int(cmd.get("limit", 10))
            return json.dumps({"stalls": self.profile_top(limit)}), 0
        if prefix == "integrity":
            return json.dumps(self.integrity()), 0
        return f"module {self.NAME} has no command {prefix!r}", -22
