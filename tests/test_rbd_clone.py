"""RBD COW clone layering (librbd/image/CloneRequest.cc:80-220 +
io/CopyupRequest.cc:120-260 analogs): protect -> clone -> read-through
-> copy-up on first write -> flatten severs; children bookkeeping gates
unprotect; thin provisioning proven by pool object counts."""

from __future__ import annotations

import pytest

from ceph_tpu.rbd import FEATURE_OBJECT_MAP, Image
from ceph_tpu.tools.vstart import MiniCluster

MiB = 1 << 20


@pytest.fixture(scope="module")
def rig():
    c = MiniCluster(n_osds=3, ms_type="loopback").start()
    c.wait_for_osd_count(3)
    client = c.client(timeout=20.0)
    pool = c.create_pool(client, pg_num=8, size=2)
    yield {"cluster": c, "client": client, "pool": pool,
           "io": client.open_ioctx(pool)}
    c.stop()


def _pool_objects(rig) -> int:
    n = 0
    for osd in rig["cluster"].osds.values():
        for cid in osd.store.list_collections():
            if cid.startswith(f"{rig['pool']}."):
                n += sum(1 for _ in osd.store.list_objects(cid))
    return n


def test_clone_requires_protection(rig):
    img = Image.create(rig["io"], "golden0", size=1 * MiB, order=18)
    img.write(b"base", 0)
    img.snap_create("s")
    with pytest.raises(OSError):
        img.clone("never", "s")
    img.snap_protect("s")
    assert img.snap_is_protected("s")
    c = img.clone("ok-child", "s")
    assert c.read(0, 4) == b"base"


def test_ten_clones_share_golden_objects(rig):
    """Thin provisioning: 10 clones of a written golden image add only
    header/metadata objects to the pool — none of the parent's data
    objects are copied until someone writes."""
    io = rig["io"]
    img = Image.create(io, "golden", size=8 * MiB, order=20,
                       stripe_unit=1 << 16, stripe_count=2)
    img.write(b"G" * (2 * MiB), 0)          # a few data objects
    img.write(b"tail", 6 * MiB)
    img.snap_create("base")
    img.snap_protect("base")
    before = _pool_objects(rig)
    clones = [img.clone(f"child-{i}", "base") for i in range(10)]
    added = _pool_objects(rig) - before
    # each clone adds its header (x2 replicas) plus shared registry
    # objects — NO data objects (the golden image's 2 MiB of data
    # would be ~4 objects x 2 replicas x 10 clones if copied)
    assert added <= 10 * 2 + 6, added
    # every clone reads the golden content through the parent
    for c in clones:
        assert c.read(0, 8) == b"G" * 8
        assert c.read(6 * MiB, 4) == b"tail"
        assert c.read(7 * MiB, 4) == b"\x00" * 4   # sparse stays sparse
    assert sorted(img.list_children("base")) == sorted(
        f"child-{i}" for i in range(10))


def test_copyup_touches_only_written_objects(rig):
    io = rig["io"]
    img = Image.create(io, "golden2", size=8 * MiB, order=20,
                       stripe_unit=1 << 16, stripe_count=2)
    img.write(b"A" * (4 * MiB), 0)
    img.snap_create("base")
    img.snap_protect("base")
    child = img.clone("cow-child", "base")
    before = _pool_objects(rig)
    # one small write: exactly the touched object(s) copy up
    child.write(b"child!", 100)
    added = _pool_objects(rig) - before
    # the write covers ONE 1 MiB object (order=20): copy-up creates
    # that object (replicated size=2 counts it twice) plus the striped
    # size-meta object — not the 4 MiB of parent data
    assert added <= 6, added
    # read after copy-up: child part + parent-backed remainder intact
    assert child.read(100, 6) == b"child!"
    assert child.read(0, 100) == b"A" * 100      # same object, copied up
    assert child.read(2 * MiB, 8) == b"A" * 8    # still parent-backed
    # the PARENT snapshot is untouched
    assert img.read(100, 6, snap="base") == b"A" * 6
    assert img.read(0, 8) == b"A" * 8


def test_unprotect_refused_while_children_then_flatten(rig):
    io = rig["io"]
    img = Image.create(io, "golden3", size=2 * MiB, order=19)
    img.write(b"golden-three", 0)
    img.snap_create("base")
    img.snap_protect("base")
    child = img.clone("flat-child", "base")
    with pytest.raises(OSError):
        img.snap_unprotect("base")
    with pytest.raises(OSError):
        img.snap_remove("base")
    copied = child.flatten()
    assert copied >= 1
    # severed: content survives parent snapshot removal
    assert img.list_children("base") == []
    img.snap_unprotect("base")
    img.snap_remove("base")
    assert child.read(0, 12) == b"golden-three"
    # child can re-write freely (no parent anymore)
    child.write(b"post-flatten", 0)
    assert child.read(0, 12) == b"post-flatten"


def test_clone_remove_deregisters_child(rig):
    io = rig["io"]
    img = Image.create(io, "golden4", size=1 * MiB, order=18)
    img.write(b"x" * 4096, 0)
    img.snap_create("s")
    img.snap_protect("s")
    c = img.clone("doomed-child", "s")
    assert img.list_children("s") == ["doomed-child"]
    c.remove()
    assert img.list_children("s") == []
    img.snap_unprotect("s")     # now allowed


def test_child_snap_view_survives_flatten_and_shrink(rig):
    """A child snapshot freezes its parent record: flatten (which
    severs only the HEAD link) and head shrink (which clamps only the
    HEAD overlap) must not change what the snap reads — and the child
    stays registered (unprotect refused) while such a snap exists."""
    io = rig["io"]
    img = Image.create(io, "golden6", size=4 * MiB, order=20)
    img.write(b"Q" * (2 * MiB), 0)
    img.snap_create("base")
    img.snap_protect("base")
    child = img.clone("frozen-child", "base")
    child.write(b"c1", 0)
    child.snap_create("cs")          # parent-backed beyond object 0
    child.flatten()
    # the pre-flatten snap still reads parent-backed ranges
    assert child.read(1 * MiB + 16, 4, snap="cs") == b"Q" * 4
    assert child.read(0, 2, snap="cs") == b"c1"
    # flatten kept the child registered: a snap still references the
    # parent, so unprotect stays refused
    assert img.list_children("base") == ["frozen-child"]
    with pytest.raises(OSError):
        img.snap_unprotect("base")
    # head shrink must not retroactively truncate the snap's view
    child.resize(1 * MiB)
    assert child.read(1 * MiB + 16, 4, snap="cs") == b"Q" * 4
    # removing the last parent-referencing snap releases the parent
    child.snap_remove("cs")
    assert img.list_children("base") == []
    img.snap_unprotect("base")


def test_flatten_maintains_object_map(rig):
    """Flatten's materialized objects must land in the object map, or
    fast-diff/export-diff silently drop them."""
    io = rig["io"]
    img = Image.create(io, "golden7", size=2 * MiB, order=19,
                       features=[FEATURE_OBJECT_MAP])
    img.write(b"OMDATA" * 100, 0)
    img.snap_create("base")
    img.snap_protect("base")
    child = img.clone("om-flat-child", "base")
    child.flatten()
    blob = child.export_diff()
    fresh = Image.create(io, "om-flat-restore", size=2 * MiB, order=19)
    fresh.import_diff(blob)
    assert fresh.read(0, 12) == b"OMDATA" * 2
    img.snap_unprotect("base")


def test_clone_snapshot_and_object_map(rig):
    """Clone with inherited object map: snapshots on the CHILD freeze
    its copied-up state; reads at the child snap still fall through to
    the parent for untouched objects."""
    io = rig["io"]
    img = Image.create(io, "golden5", size=4 * MiB, order=20,
                       features=[FEATURE_OBJECT_MAP])
    img.write(b"P" * (1 * MiB), 0)
    img.snap_create("base")
    img.snap_protect("base")
    child = img.clone("snap-child", "base")
    child.write(b"c1", 0)                    # copy-up object 0
    child.snap_create("cs")
    child.write(b"c2", 0)
    assert child.read(0, 2) == b"c2"
    assert child.read(0, 2, snap="cs") == b"c1"
    # untouched range at the child snap: parent content
    assert child.read(512 * 1024, 4, snap="cs") == b"P" * 4
