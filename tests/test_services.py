"""Services tier on RADOS (VERDICT missing item 10): striper, rbd-lite
block images, in-OSD object classes (cls), rgw-lite buckets, and the
compressor plugin registry."""

import json

import pytest

from ceph_tpu.osdc.striper import StripeLayout, StripedObject
from ceph_tpu.tools.vstart import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(n_osds=3, ms_type="loopback").start()
    c.wait_for_osd_count(3)
    try:
        yield c
    finally:
        c.stop()


@pytest.fixture(scope="module")
def io(cluster):
    client = cluster.client(timeout=15.0)
    pool = cluster.create_pool(client, pg_num=8, size=3)
    return client.open_ioctx(pool)


class TestStriper:
    def test_extent_math(self):
        lay = StripeLayout(stripe_unit=16, stripe_count=2, object_size=32)
        # 2 su per object; su 0->obj0, su 1->obj1, su 2->obj0(second),
        # su 3->obj1(second), su 4->obj2...
        assert lay.extents(0, 16) == [(0, 0, 16)]
        assert lay.extents(16, 16) == [(1, 0, 16)]
        assert lay.extents(32, 16) == [(0, 16, 16)]
        assert lay.extents(64, 16) == [(2, 0, 16)]
        assert lay.extents(8, 16) == [(0, 8, 8), (1, 0, 8)]

    def test_striped_object_roundtrip(self, io):
        so = StripedObject(io, "big",
                           StripeLayout(stripe_unit=1024,
                                        stripe_count=3,
                                        object_size=4096))
        payload = bytes(range(256)) * 64      # 16 KiB over many objects
        so.write(payload)
        assert so.size() == len(payload)
        assert so.read() == payload
        assert so.read(5000, 1000) == payload[5000:6000]
        so.write(b"#" * 100, offset=2000)
        want = payload[:2000] + b"#" * 100 + payload[2100:]
        assert so.read() == want
        so.remove()
        assert so.size() == 0


class TestRbd:
    def test_image_lifecycle(self, io):
        from ceph_tpu.rbd import Image, list_images
        img = Image.create(io, "disk0", size=1 << 20, order=16)
        assert img.stat()["size"] == 1 << 20
        img.write(b"bootsector" * 51, offset=0)
        img.write(b"data-at-512k", offset=512 * 1024)
        assert img.read(0, 510) == (b"bootsector" * 51)
        assert img.read(512 * 1024, 12) == b"data-at-512k"
        # unwritten space reads as zeros
        assert img.read(900 * 1024, 64) == bytes(64)
        with pytest.raises(ValueError):
            img.write(b"x", offset=1 << 20)
        img.resize(2 << 20)
        img.write(b"grown", offset=(1 << 20) + 5)
        assert img.read((1 << 20) + 5, 5) == b"grown"
        assert list_images(io, ["disk0", "nope"]) == ["disk0"]
        img.remove()
        assert list_images(io, ["disk0"]) == []


class TestCls:
    def test_lock_class(self, io):
        io.write_full("locked", b"x")
        out = io.execute("locked", "lock", "lock",
                         json.dumps({"owner": "alice"}).encode())
        assert out == b"{}"
        info = json.loads(io.execute("locked", "lock", "info"))
        assert info["holder"] == "alice"
        # contention -> EACCES
        with pytest.raises(OSError):
            io.execute("locked", "lock", "lock",
                       json.dumps({"owner": "bob"}).encode())
        io.execute("locked", "lock", "unlock",
                   json.dumps({"owner": "alice"}).encode())
        assert json.loads(io.execute("locked", "lock",
                                     "info"))["holder"] is None

    def test_numops_and_version(self, io):
        io.write_full("ctr", b"")
        for want in (5, 8):
            out = json.loads(io.execute(
                "ctr", "numops", "add",
                json.dumps({"key": "hits", "val": 5 if want == 5
                            else 3}).encode()))
            assert out["value"] == want
        v1 = json.loads(io.execute("ctr", "version", "bump"))["ver"]
        v2 = json.loads(io.execute("ctr", "version", "bump"))["ver"]
        assert (v1, v2) == (1, 2)
        # cls mutations replicate: read the omap through the data path
        omap = io.get_omap("ctr")
        assert omap["hits"] == b"8"

    def test_unknown_class_errors(self, io):
        io.write_full("u", b"x")
        with pytest.raises(OSError):
            io.execute("u", "no_such", "method")


class TestRgw:
    def test_bucket_object_lifecycle(self, io):
        from ceph_tpu.rgw_lite import Bucket
        b = Bucket(io, "photos", compression="zlib").create()
        assert b.exists()
        body = b"jpegjpegjpeg" * 500
        b.put("2026/cat.jpg", body, metadata={"content-type":
                                              "image/jpeg"})
        b.put("2026/dog.jpg", b"woof")
        b.put("notes.txt", b"hello")
        assert b.get("2026/cat.jpg") == body
        head = b.head("2026/cat.jpg")
        assert head["size"] == len(body)
        assert head["stored"] < len(body)      # compression worked
        assert head["meta"]["content-type"] == "image/jpeg"
        assert b.list() == ["2026/cat.jpg", "2026/dog.jpg", "notes.txt"]
        assert b.list(prefix="2026/") == ["2026/cat.jpg", "2026/dog.jpg"]
        b.delete_object("2026/dog.jpg")
        assert b.list(prefix="2026/") == ["2026/cat.jpg"]
        with pytest.raises(OSError):
            b.delete()                         # not empty
        for k in b.list():
            b.delete_object(k)
        b.delete()
        assert not b.exists()


class TestCompressor:
    def test_registry_roundtrip(self):
        from ceph_tpu import compressor
        data = b"compressible " * 1000
        for name in compressor.names():
            c = compressor.create(name)
            assert c.decompress(c.compress(data)) == data
        with pytest.raises(KeyError):
            compressor.create("snappy")

    def test_custom_plugin_registration(self):
        from ceph_tpu import compressor

        class Rot13(compressor.Compressor):
            name = "rot13"

            def compress(self, data):
                return bytes((b + 13) % 256 for b in data)

            def decompress(self, data):
                return bytes((b - 13) % 256 for b in data)

        compressor.register("rot13", Rot13)
        c = compressor.create("rot13")
        assert c.decompress(c.compress(b"abc")) == b"abc"


class TestMgrAndCli:
    def test_mgr_aggregates_reports(self):
        c = MiniCluster(n_osds=3, ms_type="loopback").start()
        try:
            c.run_mgr()
            # restart osds so they pick up the mgr address
            for i in list(c.osds):
                c.kill_osd(i)
                c.run_osd(i)
            c.wait_for_osd_count(3)
            client = c.client(timeout=15.0)
            pool = c.create_pool(client, pg_num=8, size=3)
            io = client.open_ioctx(pool)
            for i in range(6):
                io.write_full(f"m{i}", b"x" * 500)
            import time as _t
            deadline = _t.time() + 10
            while _t.time() < deadline:
                df = c.mgr.df()
                if len(df["per_osd"]) == 3 and df["total_objects"] > 0:
                    break
                _t.sleep(0.2)
            df = c.mgr.df()
            assert len(df["per_osd"]) == 3
            assert df["total_objects"] >= 6   # replicas count per-osd
            assert c.mgr.pg_summary().get("active", 0) > 0
            assert c.mgr.health()["status"] in ("HEALTH_OK",
                                                "HEALTH_WARN")
            ctrs = c.mgr.counters()
            assert any(v.get("op_w", 0) > 0 for v in ctrs.values())
        finally:
            c.stop()

    def test_ceph_cli_parses_and_runs(self):
        from ceph_tpu.tools.ceph_cli import main, parse_command
        cmd = parse_command(["osd", "pool", "create", "pg_num=8",
                             "size=3"])
        assert cmd == {"prefix": "osd pool create", "pg_num": "8",
                       "size": "3"}
        assert parse_command(["osd", "out", "3"]) == {
            "prefix": "osd out", "id": "3"}
        c = MiniCluster(n_osds=3, ms_type="async").start()
        try:
            c.wait_for_osd_count(3)
            rc = main(["-m", c.mon_host, "status"])
            assert rc == 0
            rc = main(["-m", c.mon_host, "osd", "pool", "create",
                       "pg_num=4", "size=2"])
            assert rc == 0
        finally:
            c.stop()


class TestIciStack:
    """The device mesh as a messenger stack (SURVEY §5): EC shard bulk
    payloads ride cross-device placement while the daemons run the same
    code path as on tcp/loopback."""

    def test_ec_over_ici_mesh(self):
        from ceph_tpu.msg.ici import IciTransport
        t = IciTransport.instance()
        before = (t.transfers, t.bytes_staged)
        c = MiniCluster(n_osds=4, ms_type="ici").start()
        try:
            c.wait_for_osd_count(4)
            client = c.client(timeout=15.0)
            pool = c.create_pool(client, pg_num=4,
                                 pool_type="erasure", k=2, m=2)
            io = client.open_ioctx(pool)
            payload = bytes(range(256)) * 128     # 32 KiB
            io.write_full("mesh-obj", payload)
            assert io.read("mesh-obj") == payload
            # partial rmw over the mesh too
            io.write("mesh-obj", b"Z" * 5000, offset=3000)
            want = payload[:3000] + b"Z" * 5000 + payload[8000:]
            assert io.read("mesh-obj") == want
            # replicated pool bulk recovery pushes also ride the mesh
            rep = c.create_pool(client, pg_num=4, size=3)
            io2 = client.open_ioctx(rep)
            io2.write_full("r", b"replicated-over-ici" * 200)
            assert io2.read("r") == b"replicated-over-ici" * 200
        finally:
            c.stop()
        after = (t.transfers, t.bytes_staged)
        assert after[0] > before[0], "no payload rode the device mesh"
        assert after[1] > before[1]

    def test_bulk_payload_lands_on_peer_device(self):
        import jax
        from ceph_tpu.msg.ici import IciTransport
        t = IciTransport.instance()
        from ceph_tpu.msg.messenger import EntityName
        if len(jax.devices()) < 2:
            import pytest as _pytest
            _pytest.skip("single-device backend")
        token = t.stage(b"x" * 4096, EntityName("osd", 1))
        entry = t._bufs[int.from_bytes(token[5:], "little")]
        assert entry["buf"].devices() == {jax.devices()[1]}
        assert t.redeem(token) == b"x" * 4096


class TestRbdAdvanced:
    """rbd_directory, exclusive lock, snapshots, clone — the librbd
    feature tier over the lite image."""

    def test_directory_listing(self, io):
        from ceph_tpu.rbd import Image, list_images
        a = Image.create(io, "dir-a", size=1 << 16, order=16)
        b = Image.create(io, "dir-b", size=1 << 16, order=16)
        assert list_images(io) == ["dir-a", "dir-b"]
        a.remove()
        assert list_images(io) == ["dir-b"]
        b.remove()
        assert list_images(io) == []

    def test_exclusive_lock(self, io):
        import pytest
        from ceph_tpu.rbd import Image
        img = Image.create(io, "locked-img", size=1 << 16, order=16)
        img.lock_acquire("writer-1")
        img.write(b"mine", 0)   # owner writes fine
        # a second handle must be refused
        other = Image(io, "locked-img")
        with pytest.raises(OSError) as ei:
            other.write(b"stolen", 0)
        assert ei.value.errno == 16
        with pytest.raises(OSError):
            other.resize(1 << 17)
        # lock break lets the second handle take over
        other.break_lock()
        other.lock_acquire("writer-2")
        other.write(b"taken", 0)
        assert img.read(0, 5) == b"taken"
        other.lock_release()
        img.remove()

    def test_snapshots_and_clone(self, io):
        import pytest
        from ceph_tpu.rbd import Image
        img = Image.create(io, "snappy", size=1 << 16, order=16)
        img.write(b"version-one", 0)
        img.snap_create("v1")
        img.write(b"VERSION-TWO", 0)
        assert img.read(0, 11) == b"VERSION-TWO"
        assert img.read(0, 11, snap="v1") == b"version-one"
        assert "v1" in img.snap_list()
        # COW clone from the protected snapshot sees v1 content; its
        # writes copy-up and never touch the parent
        img.snap_protect("v1")
        c = img.clone("snappy-clone", "v1")
        assert c.read(0, 11) == b"version-one"
        c.write(b"clone-write", 0)
        assert img.read(0, 11, snap="v1") == b"version-one"
        # rollback restores v1 on the source
        img.snap_rollback("v1")
        assert img.read(0, 11) == b"version-one"
        # protected + child: removal refused until flatten + unprotect
        with pytest.raises(OSError):
            img.snap_remove("v1")
        with pytest.raises(OSError):
            img.snap_unprotect("v1")
        c.flatten()
        img.snap_unprotect("v1")
        img.snap_remove("v1")
        with pytest.raises(KeyError):
            img.read(0, 4, snap="v1")
        c.remove()
        img.remove()


class TestRbdReviewRegressions:
    def test_lock_enforced_against_prior_writer(self, io):
        """A handle that wrote before the lock existed must be refused
        after another owner acquires it (no stale positive cache)."""
        import pytest
        from ceph_tpu.rbd import Image
        img = Image.create(io, "cache-img", size=1 << 16, order=16)
        img.write(b"pre-lock", 0)     # writes while unlocked
        other = Image(io, "cache-img")
        other.lock_acquire("B")
        with pytest.raises(OSError):
            img.write(b"post-lock", 0)
        other.lock_release()
        img.write(b"unlocked-again", 0)
        img.remove()

    def test_remove_refuses_with_snapshots(self, io):
        import pytest
        from ceph_tpu.rbd import Image
        img = Image.create(io, "snapped", size=1 << 16, order=16)
        img.write(b"x", 0)
        img.snap_create("keep")
        with pytest.raises(OSError):
            img.remove()
        img.snap_remove("keep")
        img.remove()

    def test_rm_omap_keys_with_newline_in_key(self, io):
        io.write_full("omapped", b"")
        io.set_omap("omapped", {"a\nb": b"1", "a": b"2", "b": b"3"})
        io.rm_omap_keys("omapped", ["a\nb"])
        assert io.get_omap("omapped") == {"a": b"2", "b": b"3"}

    def test_list_images_merges_probe_hits(self, io):
        import json as _json
        from ceph_tpu.rbd import Image, list_images
        # legacy image: header exists, no directory entry
        io.write_full(Image.HEADER_FMT.format(name="legacy"),
                      _json.dumps({"size": 16, "order": 16,
                                   "stripe_unit": 1 << 16,
                                   "stripe_count": 4,
                                   "snaps": {}}).encode())
        img = Image.create(io, "modern", size=1 << 16, order=16)
        assert list_images(io, probe=["legacy"]) == ["legacy", "modern"]
        img.remove()


def test_populate_classes_idempotent():
    from ceph_tpu.crush import build_two_level_map
    from ceph_tpu.crush.classes import populate_classes
    m, _root, _rid = build_two_level_map(4, 2)
    dc = {i: ("ssd" if i % 2 else "hdd") for i in range(8)}
    populate_classes(m, dc)
    n_buckets = sum(1 for b in m.buckets if b is not None)
    table = dict(m.class_bucket)
    populate_classes(m, dc)   # refresh must not clone shadows-of-shadows
    assert sum(1 for b in m.buckets if b is not None) == n_buckets
    assert set(table) == set(m.class_bucket)
