"""Experiment 3: fused Pallas GF(2) bit-matmul encode kernels.

Per grid step: load a block of stripes (SB, k, B) uint8, expand bits on
sublanes, lane-split into G groups stacked on the contraction sublanes
(block-diagonal W fills all 128 MXU output lanes), one int8 matmul,
pack parity bits, store (SB, m, B) uint8. No HBM intermediates.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ceph_tpu.ops.gf_kernel import ec_encode_ref
from ceph_tpu.gf.matrix import gen_cauchy1_matrix
from bench import chained_seconds_per_step
from exp_gf import bit_matrix, K, M, CHUNK, STRIPES


def _expand_bits(d, k, B):
    """(k, B) uint8 -> (k*8, B) int8 bit planes (row j*8+t = bit t of chunk j)."""
    d32 = d.astype(jnp.int32)
    rep = jnp.repeat(d32, 8, axis=0)                      # (k*8, B)
    shifts = jnp.tile(jnp.arange(8, dtype=jnp.int32), k)[:, None]
    return ((rep >> shifts) & 1).astype(jnp.int8)


def _kernel_blk(d_ref, w_ref, out_ref, *, k, m, g, B, sb, dot_dtype):
    # d_ref (sb, k, B) uint8; w_ref (g*k*8, g*m*8) int8; out (sb, m, B) uint8
    Bg = B // g
    outs = []
    for s in range(sb):
        bits = _expand_bits(d_ref[s], k, B)               # (k8, B) int8
        groups = [bits[:, i * Bg:(i + 1) * Bg] for i in range(g)]
        bits4 = jnp.concatenate(groups, axis=0)           # (g*k8, Bg)
        acc = jax.lax.dot_general(
            w_ref[...].T.astype(dot_dtype), bits4.astype(dot_dtype),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32 if dot_dtype == jnp.int8 else jnp.float32,
        )                                                  # (g*m8, Bg)
        pb = acc.astype(jnp.int32) & 1
        pb = pb.reshape(g, m, 8, Bg)
        bw = jnp.arange(8, dtype=jnp.int32)[None, None, :, None]
        packed = jnp.sum(pb << bw, axis=2, dtype=jnp.int32)  # (g, m, Bg)
        par = jnp.concatenate([packed[i] for i in range(g)], axis=1)  # (m, B)
        outs.append(par)
    out_ref[...] = jnp.stack(outs).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("k", "m", "g", "sb", "dot"))
def enc_pallas(wblk, data, *, k, m, g, sb, dot):
    s, _, B = data.shape
    dot_dtype = jnp.int8 if dot == "int8" else jnp.bfloat16
    return pl.pallas_call(
        functools.partial(_kernel_blk, k=k, m=m, g=g, B=B, sb=sb,
                          dot_dtype=dot_dtype),
        grid=(s // sb,),
        in_specs=[
            pl.BlockSpec((sb, k, B), lambda i: (i, jnp.int32(0), jnp.int32(0))),
            pl.BlockSpec((g * k * 8, g * m * 8),
                         lambda i: (jnp.int32(0), jnp.int32(0)),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((sb, m, B),
                               lambda i: (i, jnp.int32(0), jnp.int32(0))),
        out_shape=jax.ShapeDtypeStruct((s, m, B), jnp.uint8),
    )(data, wblk)


def main():
    gen = gen_cauchy1_matrix(K, M)
    coding = gen[K:]
    rng = np.random.default_rng(0)
    data_np = rng.integers(0, 256, (STRIPES, K, CHUNK), dtype=np.uint8)
    data = jnp.asarray(data_np)
    data_bytes = STRIPES * K * CHUNK
    ref = ec_encode_ref(coding, data_np[:8])
    wb = bit_matrix(coding)

    def wblk_of(g):
        w = np.zeros((g * K * 8, g * M * 8), dtype=np.int8)
        for i in range(g):
            w[i * K * 8:(i + 1) * K * 8, i * M * 8:(i + 1) * M * 8] = wb
        return jnp.asarray(w)

    variants = {}
    for g in (4, 2, 1):
        for sb in (1, 4, 8):
            for dot in ("int8", "bf16"):
                variants[f"pl_g{g}_sb{sb}_{dot}"] = functools.partial(
                    lambda d, g, sb, dot, w: enc_pallas(w, d, k=K, m=M, g=g, sb=sb, dot=dot),
                    g=g, sb=sb, dot=dot, w=wblk_of(g))

    for name, fn in variants.items():
        try:
            out = np.asarray(fn(data[:8]))
            ok = np.array_equal(out, ref)

            def step(d, fn=fn):
                p = fn(d)
                return d.at[0, 0, 0].set(p[0, 0, 0] ^ jnp.uint8(1))

            t = chained_seconds_per_step(step, data)
            print(f"{name}: {'OK ' if ok else 'BAD'} {data_bytes / t / 1e9:8.2f} GB/s")
        except Exception as e:
            print(f"{name}: FAILED {type(e).__name__}: {str(e)[:160]}")


if __name__ == "__main__":
    main()
