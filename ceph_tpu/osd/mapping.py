"""Bulk PG -> OSD mapping on device (OSDMapMapping / ParallelPGMapper analog)
and the shared, epoch-keyed PG mapping service.

The reference computes the full PG->OSD table with a thread pool over pgid
batches (src/osd/OSDMapMapping.h:17 ParallelPGMapper, used by the mgr balancer
and OSDMonitor).  Here the whole pool maps in one device call: the pps seeds
are a vectorized stable_mod + rjenkins hash, and placement is the batched rule
engine (ceph_tpu.crush.mapper_jax.BatchMapper).

Post-CRUSH overrides (upmap, primary affinity, temps) are sparse per-PG state
and apply host-side on the dense result — the same split the reference uses
(its mapping cache also stores raw CRUSH output and applies overrides on read).

Three layers:

* ``OSDMapMapping`` — the per-epoch table builder.  ``update()`` is now
  INCREMENTAL: each pool carries a signature (crush content, rule, size,
  pg_num/pgp_num, the reweights of the OSDs its rule can actually reach) and
  only pools whose signature moved recompute; untouched pools reuse their raw
  tables.  One BatchMapper is cached per crush-map identity, so
  unchanged-crush epochs skip the mapper rebuild entirely.  Remaps submit
  through the context's dispatch engine (ops.dispatch.submit_do_rule) when
  one is supplied: pools sharing a rule — and daemons sharing a context —
  coalesce into one device call, and the double-buffered pipeline overlaps
  pool N+1's h2d with pool N's compute.

* ``SharedPGMappingService`` — one instance per CephTpuContext
  (``ctx.mapping_service()``), the epoch-keyed cache every mapping consumer
  reads: OSD map consumption (daemon._scan_pgs), client op targeting
  (client.rados), the balancer, and the offline tools.  On a new epoch it
  updates the mapping, diffs old-vs-new raw tables ON DEVICE, and derives the
  exact changed-PG delta (candidates from the device diff + override/osd-state
  diffs, then filtered through the host-side pipeline tail) so map consumption
  is O(changed PGs + local PGs) instead of O(cluster PGs).  A burst of epochs
  coalesces: while one update runs, later maps queue and only the NEWEST is
  computed (epoch-skip).  Reads are epoch- and identity-checked — a reader
  holding a different map object or epoch falls back to the scalar oracle, so
  the scalar ``pg_to_up_acting_osds`` remains the source of truth.

Contract (same as the reference's mapping cache): maps are immutable once
published — advance by building a NEW OSDMap with a higher epoch (OSDMap.copy
+ mutate), never by mutating a map the service has already seen.
"""

from __future__ import annotations

import threading
import time
from collections import deque

import numpy as np

from ceph_tpu.common import lockdep
from ceph_tpu.crush.types import CRUSH_ITEM_NONE, CrushMap
from ceph_tpu.ops import telemetry

from .osdmap import MAX_AFFINITY, OSDMap, PGPool

__all__ = ["OSDMapMapping", "SharedPGMappingService", "MapUpdate",
           "pps_batch", "crush_signature", "rule_devices"]


def pps_batch(pool: PGPool, pgids: np.ndarray) -> np.ndarray:
    """Vectorized raw_pg_to_pps over pg ids (osd_types.cc:1505-1521)."""
    import jax.numpy as jnp

    from ceph_tpu.ops.crush_kernel import hash32_2
    ps = np.asarray(pgids, dtype=np.uint32)
    bmask = pool.pgp_num_mask
    low = ps & bmask
    stable = np.where(low < pool.pgp_num, low, ps & (bmask >> 1))
    return np.asarray(hash32_2(jnp.asarray(stable),
                               jnp.uint32(pool.pool_id & 0xFFFFFFFF)))


def pps_batch_scalar(pool: PGPool, pgids: np.ndarray) -> np.ndarray:
    """Scalar-backend twin of pps_batch (no jax import)."""
    return np.asarray([pool.raw_pg_to_pps(int(pg)) for pg in pgids],
                      dtype=np.uint32)


def crush_signature(crush: CrushMap) -> int:
    """Content hash of everything placement reads from the crush map:
    bucket structure/weights, rules, tunables, choose_args.  O(map
    size) per epoch — noise next to one pool remap — and it is what
    lets unchanged-crush epochs reuse both the compiled BatchMapper
    and every pool's raw table."""
    buckets = tuple(
        (b.id, b.type, b.alg, b.hash, tuple(b.items),
         tuple(b.item_weights), b.weight)
        for b in crush.buckets if b is not None)
    rules = tuple(
        (i, tuple((s.op, s.arg1, s.arg2) for s in r.steps))
        for i, r in enumerate(crush.rules) if r is not None)
    t = crush.tunables
    tun = (t.choose_local_tries, t.choose_local_fallback_tries,
           t.choose_total_tries, t.chooseleaf_descend_once,
           t.chooseleaf_vary_r, t.chooseleaf_stable, t.straw_calc_version)
    return hash((crush.max_devices, buckets, rules, tun,
                 repr(crush.choose_args)))


def rule_devices(crush: CrushMap, ruleno: int) -> tuple[int, ...]:
    """Devices reachable from a rule's take roots — the OSDs whose
    reweight can change this rule's raw output.  Sorted tuple."""
    rule = crush.rules[ruleno] if 0 <= ruleno < len(crush.rules) else None
    if rule is None:
        return ()
    from ceph_tpu.crush.types import RULE_TAKE
    devs: set[int] = set()
    stack = [s.arg1 for s in rule.steps if s.op == RULE_TAKE]
    seen: set[int] = set()
    while stack:
        item = stack.pop()
        if item >= 0:
            devs.add(item)
            continue
        if item in seen:
            continue
        seen.add(item)
        b = crush.bucket(item)
        if b is not None:
            stack.extend(b.items)
    return tuple(sorted(devs))


def _changed_rows(old: np.ndarray, new: np.ndarray,
                  mesh=None) -> np.ndarray:
    """Row indices where the two (pg_num, size) raw tables differ.
    The elementwise compare + row reduce runs on device; only the
    boolean row mask comes back to host.  With a ``mesh`` (the
    context's kernel mesh) and a PG axis the mesh size divides —
    pg_num is a power of two in practice — both tables split their PG
    axis across the mesh, so the epoch diff fans out with the rest of
    the mapping pipeline instead of serializing on one chip."""
    if old.shape != new.shape:
        return np.arange(new.shape[0])
    if new.size == 0:
        return np.zeros(0, dtype=np.int64)
    try:
        import jax.numpy as jnp
        if (mesh is not None and getattr(mesh, "size", 1) > 1
                and old.shape[0] % mesh.size == 0):
            # single sharded placement straight from host (jnp.asarray
            # first would pay an extra default-device transfer)
            import jax
            from jax.sharding import NamedSharding, PartitionSpec
            spec = NamedSharding(
                mesh, PartitionSpec(tuple(mesh.axis_names), None))
            o, n = jax.device_put(old, spec), jax.device_put(new, spec)
        else:
            o, n = jnp.asarray(old), jnp.asarray(new)
        mask = np.asarray(jnp.any(o != n, axis=1))
    except Exception:   # scalar backend / no device: host diff
        mask = (old != new).any(axis=1)
    return np.flatnonzero(mask)


def pool_signatures(m: OSDMap, reach: dict | None = None
                    ) -> tuple[int, dict[int, tuple]]:
    """(crush_sig, {pool_id: signature}) — the per-pool placement
    signature covering everything the RAW table depends on: crush
    content, rule, size/pg_num/pgp_num/type, and the reweights of the
    rule's reachable OSDs.  Two maps with equal signatures produce
    bit-identical raw tables.  ``reach`` is an optional
    (crush_sig, rule) -> devices memo shared across calls."""
    csig = crush_signature(m.crush)
    if reach is None:
        reach = {}
    sigs: dict[int, tuple] = {}
    w = m.osd_weight
    for pool_id, pool in m.pools.items():
        if (pool.crush_rule < 0 or pool.crush_rule >= m.crush.max_rules
                or m.crush.rules[pool.crush_rule] is None):
            sigs[pool_id] = ("invalid", pool.pg_num)
            continue
        devs = reach.get((csig, pool.crush_rule))
        if devs is None:
            devs = rule_devices(m.crush, pool.crush_rule)
            reach[(csig, pool.crush_rule)] = devs
        wsig = hash(tuple(w[o] if 0 <= o < len(w) else 0 for o in devs))
        sigs[pool_id] = (csig, pool.crush_rule, pool.size, pool.pg_num,
                        pool.pgp_num, pool.type, wsig)
    return csig, sigs


def scalar_rows(crush: CrushMap, ruleno: int, xs, numrep: int,
                weights) -> np.ndarray:
    """(len(xs), numrep) raw table via the scalar rule engine,
    CRUSH_ITEM_NONE-padded — the pure-python twin of a batched
    do_rule call (small pools, scalar backend, offline tools)."""
    from ceph_tpu.crush.mapper_ref import crush_do_rule
    w = [int(x) for x in weights]
    out = np.full((len(xs), numrep), CRUSH_ITEM_NONE, dtype=np.int32)
    for i, x in enumerate(xs):
        row = crush_do_rule(crush, ruleno, int(x), numrep, w)
        out[i, :len(row)] = row[:numrep]
    return out


def _vec(lst: list, n: int, fill: int = 0) -> np.ndarray:
    out = np.full(n, fill, dtype=np.int64)
    out[:len(lst)] = lst[:n] if len(lst) > n else lst
    return out


def _pool_override_digests(m: OSDMap) -> dict[int, int]:
    """Per-pool content digest of the four override dicts — part of
    the fused-table signature, so override-only churn recomputes just
    the touched pool's ladder."""
    acc: dict[int, list] = {}
    for attr in ("pg_upmap", "pg_upmap_items", "pg_temp",
                 "primary_temp"):
        d = getattr(m, attr)
        for (pid, pg), v in d.items():
            if isinstance(v, list):
                v = tuple(tuple(e) if isinstance(e, (list, tuple))
                          else e for e in v)
            acc.setdefault(pid, []).append((attr, pg, v))
    return {pid: hash(tuple(sorted(entries)))
            for pid, entries in acc.items()}


def _tail_equal(a: OSDMap, b: OSDMap) -> bool:
    """True when two maps agree on every PIPELINE-TAIL input (state,
    weights, affinity, overrides) — the gate for serving one map's
    fused rows to another object of the same epoch.  The raw-table
    signature already matched; this covers what it deliberately does
    not."""
    return (a.max_osd == b.max_osd
            and a.osd_state == b.osd_state
            and a.osd_weight == b.osd_weight
            and a.osd_primary_affinity == b.osd_primary_affinity
            and a.pg_upmap == b.pg_upmap
            and a.pg_upmap_items == b.pg_upmap_items
            and a.pg_temp == b.pg_temp
            and a.primary_temp == b.primary_temp)


def _finish_from(m: OSDMap, pool: PGPool, pool_id: int, pg: int,
                 raw_tab: dict, pps_tab: dict
                 ) -> tuple[list[int], int, list[int], int]:
    """Pipeline tail (upmap -> up -> affinity -> temps) over a cached
    raw row — the scalar oracle the fused device ladder
    (ops.placement_kernel) is bit-exact against, and the fallback when
    fused tables are unavailable."""
    raw = [int(o) for o in raw_tab[pool_id][pg]]
    if not pool.is_erasure():
        raw = [o for o in raw if o != CRUSH_ITEM_NONE]
    pps_arr = pps_tab.get(pool_id)
    pps = int(pps_arr[pg]) if pps_arr is not None else None
    return m._finish_pg_mapping(pool, (pool_id, pg), raw, pps)


class _Tables:
    """One epoch's published tables: the map object they were built
    from (identity IS the primary cache key — see module contract),
    the raw placements, the pps seeds, the per-pool signatures, and —
    when the fused device ladder ran — the packed
    (up, up_primary, acting, acting_primary) tables plus their shared
    width and tail signatures.

    ``bound`` / ``rejected`` memoize OTHER map objects of the same
    epoch that have been content-checked against the signatures —
    N daemons on one context each decode their own copy of a published
    epoch, and equal signatures mean bit-identical raw tables, so
    copies bind once and read the shared tables from then on.
    ``tail_bound`` additionally memoizes copies whose PIPELINE-TAIL
    inputs matched too (the raw signature deliberately excludes
    state/affinity/overrides): only those may read the fused rows —
    everyone else gets the host tail against their OWN map."""

    __slots__ = ("osdmap", "raw", "pps", "sigs", "epoch", "bound",
                 "rejected", "fused", "fused_w", "tail_sigs",
                 "tail_bound")

    def __init__(self, osdmap, raw, pps, sigs, epoch, fused=None,
                 fused_w=None, tail_sigs=None):
        self.osdmap = osdmap
        self.raw = raw
        self.pps = pps
        self.sigs = sigs
        self.epoch = epoch
        self.fused = fused if fused is not None else {}
        self.fused_w = fused_w if fused_w is not None else {}
        self.tail_sigs = tail_sigs if tail_sigs is not None else {}
        # id -> weakref (OSDMap is an eq-dataclass, hence unhashable;
        # membership verifies the ref still IS the object, so a reused
        # id after GC can never alias)
        self.bound: dict[int, object] = {}
        self.rejected: dict[int, object] = {}
        self.tail_bound: dict[int, object] = {}

    @staticmethod
    def _has(memo: dict, osdmap) -> bool:
        r = memo.get(id(osdmap))
        return r is not None and r() is osdmap

    @staticmethod
    def _memo(memo: dict, osdmap) -> None:
        import weakref
        dead = [k for k, r in memo.items() if r() is None]
        for k in dead:
            del memo[k]
        memo[id(osdmap)] = weakref.ref(osdmap)


class _UpdateInfo:
    __slots__ = ("prev", "recomputed", "reused")

    def __init__(self, prev, recomputed, reused):
        self.prev = prev
        self.recomputed = recomputed
        self.reused = reused


class MapUpdate:
    """What a consumer gets back from update_to(): the epochs it
    covers and the exact changed-PG list — or full=True when the
    delta chain cannot serve the caller's from_epoch (first map, or a
    reader older than the retained delta log), meaning: rescan
    everything, but still read the mappings from the cache."""

    __slots__ = ("epoch_from", "epoch_to", "changed", "full")

    def __init__(self, epoch_from, epoch_to, changed, full):
        self.epoch_from = epoch_from
        self.epoch_to = epoch_to
        self.changed = changed
        self.full = full

    def __repr__(self):
        return (f"MapUpdate({self.epoch_from}->{self.epoch_to}, "
                f"{'full' if self.full else len(self.changed)})")


class OSDMapMapping:
    """Full-map PG->OSD cache, updated per epoch (OSDMapMapping.h:324-332).

    ``update()`` recomputes only pools whose placement inputs changed
    since the cached epoch; see the module docstring.  ``backend``
    mirrors the ``crush_backend`` option: "tpu" uses the batched
    device mapper, "scalar" the pure-python oracle (slow, but it keeps
    the incremental reuse and exists for hosts without a device)."""

    def __init__(self, osdmap: OSDMap | None = None, *,
                 backend: str = "tpu", min_device_pgs: int = 0,
                 fused: bool = True):
        self.osdmap = osdmap
        #: pools below this pg_num rebuild with the scalar rule engine
        #: (device dispatch + compile overhead dominates tiny pools);
        #: the osdmap_mapping_min_pgs option
        self.min_device_pgs = min_device_pgs
        #: fuse the post-CRUSH pipeline tail on device (the
        #: osdmap_mapping_fused option): publish packed
        #: (up, acting, primaries) tables next to the raw ones.
        #: Ignored on the scalar backend.
        self.fused = fused
        #: one BatchMapper per crush-map identity (content signature),
        #: kept across update() calls so unchanged-crush epochs skip
        #: the compile_map/mapper rebuild
        self._mappers: dict[int, object] = {}
        self._raw: dict[int, np.ndarray] = {}    # pool -> (pg_num, size) raw
        self._pps: dict[int, np.ndarray] = {}    # pool -> (pg_num,) pps seeds
        self._sigs: dict[int, tuple] = {}        # pool -> placement signature
        self._fused: dict[int, np.ndarray] = {}  # pool -> packed ladder rows
        self._fused_w: dict[int, int] = {}       # pool -> packed width
        self._tail_sigs: dict[int, tuple] = {}   # pool -> tail signature
        self._reach: dict[tuple, tuple] = {}     # (crush_sig, rule) -> devs
        self.epoch = -1
        self.backend = backend

    def mapper_for(self, crush: CrushMap, csig: int | None = None):
        """The cached BatchMapper for this crush content (built on
        miss).  Offline tools share the production mapper path here."""
        if csig is None:
            csig = crush_signature(crush)
        bm = self._mappers.get(csig)
        if bm is None:
            from ceph_tpu.crush.mapper_jax import BatchMapper
            bm = BatchMapper(crush)
            self._mappers[csig] = bm
            # bound: the tool path (place() with per-run crush maps)
            # must not accumulate compiled programs for process life
            while len(self._mappers) > 4:
                self._mappers.pop(next(iter(self._mappers)))
        return bm

    def update(self, osdmap: OSDMap | None = None,
               engine=None) -> _UpdateInfo:
        """Advance the cache to ``osdmap`` (default: the constructor's
        map re-read — the seed-compatible full path).  Recomputes only
        signature-changed pools; with ``engine`` the per-pool remaps
        ride the dispatch engine (submit-all, then collect)."""
        m = osdmap if osdmap is not None else self.osdmap
        if m is None:
            raise ValueError("OSDMapMapping.update: no osdmap")
        # prev pairs the CURRENT tables with the map they were built
        # from; nothing on self is reassigned until the commit point
        # below, so a mid-update exception (device error, future
        # timeout) leaves the old state fully consistent and the next
        # successful update diffs against the right old map
        prev = _Tables(self.osdmap if self.epoch >= 0 else None,
                       self._raw, self._pps, self._sigs, self.epoch,
                       fused=self._fused, fused_w=self._fused_w,
                       tail_sigs=self._tail_sigs)
        # drop reachability memos of dead crush content before reuse
        csig, sigs = pool_signatures(m, self._reach)
        self._reach = {k: v for k, v in self._reach.items()
                       if k[0] == csig}
        weights = np.zeros(max(m.max_osd, 1), dtype=np.int64)
        weights[:len(m.osd_weight)] = m.osd_weight
        raw: dict[int, np.ndarray] = {}
        pps_t: dict[int, np.ndarray] = {}
        recomputed: list[int] = []
        reused: list[int] = []
        futures: list[tuple[int, object]] = []
        bm = None
        for pool_id, pool in m.pools.items():
            sig = sigs[pool_id]
            invalid = sig[0] == "invalid"
            if prev.sigs.get(pool_id) == sig and pool_id in prev.raw:
                raw[pool_id] = prev.raw[pool_id]
                if pool_id in prev.pps:
                    pps_t[pool_id] = prev.pps[pool_id]
                reused.append(pool_id)
                continue
            recomputed.append(pool_id)
            if invalid:
                # invalid rule -> empty raw, matching _pg_to_raw_osds's []
                raw[pool_id] = np.zeros((pool.pg_num, 0), dtype=np.int32)
                continue
            pgids = np.arange(pool.pg_num, dtype=np.uint32)
            # pps seeds depend ONLY on (pool_id, pg_num, pgp_num) —
            # reweight/crush churn recomputes the raw table but may
            # reuse the seeds (noticeable per epoch on slow hosts)
            old_pool = (prev.osdmap.pools.get(pool_id)
                        if prev.osdmap is not None else None)
            pps = (prev.pps.get(pool_id)
                   if (old_pool is not None
                       and old_pool.pg_num == pool.pg_num
                       and old_pool.pgp_num == pool.pgp_num)
                   else None)
            if (self.backend == "scalar"
                    or pool.pg_num < self.min_device_pgs):
                if pps is None:
                    pps = pps_batch_scalar(pool, pgids)
                pps_t[pool_id] = pps
                raw[pool_id] = scalar_rows(m.crush, pool.crush_rule,
                                           pps, pool.size, weights)
                continue
            if pps is None:
                pps = pps_batch(pool, pgids)
            pps_t[pool_id] = pps
            if bm is None:
                # mapper_for reuses the compiled mapper across epochs
                # for unchanged crush content (and bounds the dict for
                # the tool path)
                bm = self.mapper_for(m.crush, csig)
            if engine is not None:
                from ceph_tpu.ops.dispatch import BACKGROUND_BEST_EFFORT
                from ceph_tpu.ops.dispatch import submit_do_rule
                futures.append((pool_id, submit_do_rule(
                    engine, bm, pool.crush_rule, pps, pool.size,
                    weights,
                    cost_tag=("system", BACKGROUND_BEST_EFFORT))))
            else:
                raw[pool_id] = np.asarray(bm.do_rule(
                    pool.crush_rule, pps, pool.size, weights))
        for pool_id, fut in futures:
            raw[pool_id] = np.asarray(fut.result(timeout=120.0))
        fused: dict[int, np.ndarray] = {}
        fused_w: dict[int, int] = {}
        tail_sigs: dict[int, tuple] = {}
        if self.fused and self.backend != "scalar":
            try:
                self._build_fused(m, sigs, raw, pps_t, prev, engine,
                                  fused, fused_w, tail_sigs)
            except Exception as e:
                from ceph_tpu.common.logging import dout
                dout("mapping", 0, "fused placement ladder failed, "
                     "serving host pipeline tail: %r", e)
                fused, fused_w, tail_sigs = {}, {}, {}
        self.osdmap = m
        self._raw, self._pps, self._sigs = raw, pps_t, sigs
        self._fused, self._fused_w = fused, fused_w
        self._tail_sigs = tail_sigs
        self.epoch = m.epoch
        return _UpdateInfo(prev, recomputed, reused)

    def _build_fused(self, m: OSDMap, sigs: dict, raw: dict,
                     pps_t: dict, prev: _Tables, engine,
                     fused: dict, fused_w: dict,
                     tail_sigs: dict) -> None:
        """Run the device ladder for every pool whose TAIL signature
        moved (raw signature + osd state/weight/affinity digest +
        per-pool override digest); unchanged pools alias their packed
        tables forward.  With an ``engine`` the per-pool ladders
        submit through submit_finish_ladder (pools sharing the epoch
        digest and widths coalesce into one device call, mesh-sharded
        on the PG axis); without one, each pool runs a direct jitted
        call at its own pow-2 bucket (pool pg_nums are powers of two
        in practice, so the bucket set — and the jit cache — stays
        stable under whichever subset recomputes each epoch).

        Maps below ``min_device_pgs`` TOTAL PGs skip the fused build
        entirely (same policy as the raw-table rebuild: per-call
        dispatch + jit-compile overhead dominates toy maps, and the
        host tail is already cheap there); engine-less services and
        dedicated tests default the floor to 0."""
        if sum(int(p.pg_num) for p in m.pools.values()) \
                < self.min_device_pgs:
            return
        from ceph_tpu.ops import placement_kernel as pk
        width, pairs = pk.pool_widths(m)
        vectors = m.dense_osd_vectors()
        state, weight, affinity = vectors
        epoch_digest = (hash(state.tobytes()), hash(weight.tobytes()),
                        hash(affinity.tobytes()), width, pairs)
        ov = _pool_override_digests(m)
        jobs: list[tuple[int, object]] = []
        for pool_id, pool in m.pools.items():
            if pool_id not in raw:
                continue
            tsig = (sigs[pool_id], epoch_digest, ov.get(pool_id))
            tail_sigs[pool_id] = tsig
            if (prev.tail_sigs.get(pool_id) == tsig
                    and pool_id in prev.fused
                    and raw.get(pool_id) is prev.raw.get(pool_id)):
                fused[pool_id] = prev.fused[pool_id]
                fused_w[pool_id] = prev.fused_w[pool_id]
                continue
            pps = pps_t.get(pool_id)
            if pps is None:
                # invalid-rule pools skip the remap, but the ladder
                # still needs the affinity seed (it is what
                # _finish_pg_mapping would compute per read)
                pgids = np.arange(pool.pg_num, dtype=np.uint32)
                pps = pps_batch(pool, pgids)
                pps_t[pool_id] = pps
            jobs.append((pool_id, pk.build_operands(
                m, pool_id, pool, raw[pool_id], pps, width=width,
                pairs=pairs, vectors=vectors)))
        if not jobs:
            return
        if engine is not None:
            from ceph_tpu.ops.dispatch import BACKGROUND_BEST_EFFORT
            from ceph_tpu.ops.dispatch import submit_finish_ladder
            futs = [(pid, submit_finish_ladder(
                engine, op,
                cost_tag=("system", BACKGROUND_BEST_EFFORT)))
                    for pid, op in jobs]
            for pid, fut in futs:
                fused[pid] = np.asarray(fut.result(timeout=120.0))
                fused_w[pid] = width
        else:
            # per-pool direct calls, NOT a concatenated group: pool
            # pg_nums are powers of two in practice, so each pool hits
            # one stable jit bucket, while a concatenated batch of
            # whichever subset recomputed this epoch walks a different
            # pow2 bucket per churn kind and recompiles on toy hosts
            for pid, op in jobs:
                fused[pid] = pk.run_ladder(op)
                fused_w[pid] = width

    def fused_complete(self) -> bool:
        """True when every pool of the cached map has a packed fused
        table — the gate for device-diff deltas and the
        fused-vs-fallback epoch counters."""
        return (self.osdmap is not None
                and all(pid in self._fused for pid in self.osdmap.pools))

    def get_raw(self, pool_id: int) -> np.ndarray:
        """(pg_num, size) int32 raw CRUSH output, CRUSH_ITEM_NONE holes."""
        return self._raw[pool_id]

    def get(self, pool_id: int, pgid: int
            ) -> tuple[list[int], int, list[int], int]:
        """Full pipeline for one PG: a fused-table row read when the
        device ladder ran, the host tail over the cached raw placement
        otherwise."""
        f = self._fused.get(pool_id)
        if f is not None and 0 <= pgid < f.shape[0]:
            from ceph_tpu.ops import placement_kernel as pk
            return pk.unpack_row(f[pgid], self._fused_w[pool_id])
        return _finish_from(self.osdmap, self.osdmap.pools[pool_id],
                            pool_id, pgid, self._raw, self._pps)

    def pg_counts(self, pool_id: int) -> np.ndarray:
        """Per-OSD PG count histogram for a pool (balancer input)."""
        raw = self._raw[pool_id]
        valid = raw[(raw != CRUSH_ITEM_NONE) & (raw >= 0)]
        return np.bincount(valid, minlength=self.osdmap.max_osd)


class SharedPGMappingService:
    """The epoch-keyed shared mapping cache (one per CephTpuContext).

    See the module docstring for the design.  Thread contract: any
    number of concurrent update_to()/lookup() callers; one update
    computes at a time, later targets queue with only the newest kept
    (epoch-skip), waiters return as soon as the cache reaches their
    epoch."""

    #: delta-log entries retained (epoch transitions a lagging reader
    #: can still be served incrementally)
    DELTA_LOG = 64

    #: packed fused tables at/below this many elements diff with one
    #: vectorized numpy compare instead of a device call — per-call
    #: dispatch overhead dominates tiny tables, exactly the
    #: osdmap_mapping_min_pgs rationale (1M elements ~ a 100k-PG pool
    #: at width 3, where the device/mesh diff starts paying)
    FUSED_DIFF_HOST_MAX = 1 << 20

    def __init__(self, ctx=None, backend: str | None = None,
                 fused: bool | None = None):
        self._cv = lockdep.make_condition("SharedPGMappingService::cv")
        self._ctx = ctx
        #: explicit backend override (tests / engine-less tools);
        #: None = follow the context's crush_backend option
        self._backend_override = backend
        #: explicit fused-ladder override (tests / bench A-B runs);
        #: None = follow the osdmap_mapping_fused option
        self._fused_override = fused
        self._mapping: OSDMapMapping | None = None
        self._tables: dict[int, _Tables] = {}     # current + previous epoch
        self._deltas: deque = deque(maxlen=self.DELTA_LOG)
        self._pending: OSDMap | None = None
        self._updating = False
        #: the service's published epoch — MONOTONIC, unlike the inner
        #: mapping's (a warm() against an older map rebuilds tables
        #: without regressing this, so update_to waiters can rely on
        #: "epoch only moves forward")
        self._epoch = -1
        #: False after a warm() installed tables outside the online
        #: epoch sequence: the NEXT online update's delta would be
        #: computed against those tables, so it must not be logged
        self._chain_valid = True
        self.stats = telemetry.mapping_stats()

    # -- plumbing -------------------------------------------------------------

    @property
    def epoch(self) -> int:
        return self._epoch

    def _backend(self) -> str:
        if self._backend_override is not None:
            return self._backend_override
        if self._ctx is None:
            return "tpu"
        try:
            return str(self._ctx.conf.get("crush_backend"))
        except KeyError:
            return "tpu"

    def _fused_enabled(self) -> bool:
        if self._fused_override is not None:
            return bool(self._fused_override)
        if self._ctx is None:
            return True
        try:
            return bool(self._ctx.conf.get("osdmap_mapping_fused"))
        except KeyError:
            return True

    def _engine(self):
        if self._ctx is None or self._backend() == "scalar":
            return None
        return self._ctx.dispatch_engine()

    def _mesh(self):
        """The mesh for the on-device epoch diff — EXACTLY the mesh
        the engine places this service's remap batches over (its
        process-local submesh under jax.distributed; the diff tables
        are process-local host data, so placing onto non-addressable
        devices would raise).  Delegates to the engine so the
        multi-controller placement rule lives in one place."""
        eng = self._engine()
        if eng is None:
            return None
        try:
            return eng.placement_mesh()
        except Exception:
            return None

    def _ensure_mapping(self) -> OSDMapMapping:
        if self._mapping is None:
            self._mapping = OSDMapMapping(backend=self._backend(),
                                          fused=self._fused_enabled())
        else:
            # the knobs follow the live config (an operator flipping
            # crush_backend to scalar mid-flight — wedged device —
            # must take effect on the next update)
            self._mapping.backend = self._backend()
            self._mapping.fused = self._fused_enabled()
        if self._ctx is not None:
            try:
                self._mapping.min_device_pgs = int(
                    self._ctx.conf.get("osdmap_mapping_min_pgs"))
            except KeyError:
                pass
        return self._mapping

    # -- epoch advance --------------------------------------------------------

    def update_to(self, osdmap: OSDMap,
                  from_epoch: int | None = None) -> MapUpdate:
        """Bring the cache to (at least) osdmap's epoch and return the
        delta since ``from_epoch`` (default: the service's previous
        epoch).  Concurrent callers advancing the same epoch share one
        computation; a burst queues and only the newest target is
        computed."""
        with self._cv:
            if from_epoch is None:
                from_epoch = self.epoch
            target = osdmap.epoch
            if target > self.epoch:
                # queue with only the newest target kept; skipped
                # intermediates are counted ONCE, by the jump
                # arithmetic of whichever update actually runs
                if (self._pending is None
                        or target > self._pending.epoch):
                    self._pending = osdmap
            while True:
                if self.epoch >= target:
                    return self._delta_since(from_epoch, target)
                if self._updating:
                    self._cv.wait()
                    continue
                work = self._pending
                self._pending = None
                if work is None or work.epoch <= self.epoch:
                    # the queued target was consumed by an update that
                    # FAILED (or was superseded): re-queue our own map
                    # so this loop makes progress instead of spinning
                    if (self._pending is None
                            or target > self._pending.epoch):
                        self._pending = osdmap
                    continue
                self._updating = True
                chain_valid = self._chain_valid
                mapping = self._ensure_mapping()
                break
        t0 = time.perf_counter()
        delta_s = host_tail_s = 0.0
        try:
            info = mapping.update(work, engine=self._engine())
            device_s = time.perf_counter() - t0
            if chain_valid:
                changed, full, delta_s, host_tail_s = \
                    self._compute_delta(info)
            else:
                # prev tables came from a warm() outside the online
                # sequence: a delta against them would be discarded
                # below anyway — skip the whole candidate pass
                changed, full = None, True
        except BaseException:
            with self._cv:
                self._updating = False
                self._cv.notify_all()
            raise
        dt = time.perf_counter() - t0
        cached_pgs = sum(int(r.shape[0]) for r in mapping._raw.values())
        with self._cv:
            prev = info.prev
            newt = _Tables(work, mapping._raw, mapping._pps,
                           mapping._sigs, work.epoch,
                           fused=mapping._fused,
                           fused_w=mapping._fused_w,
                           tail_sigs=mapping._tail_sigs)
            self._tables = ({prev.epoch: prev, work.epoch: newt}
                            if prev.epoch >= 0 else {work.epoch: newt})
            if full or not self._chain_valid:
                # chain break (first map, or the prev tables came from
                # a warm() outside the online sequence): a delta
                # against them must never be served to online readers
                self._deltas.clear()
            else:
                self._deltas.append((prev.epoch, work.epoch,
                                     tuple(changed)))
            self._chain_valid = True
            skipped = (work.epoch - prev.epoch - 1
                       if prev.epoch >= 0 else 0)
            self._epoch = max(self._epoch, work.epoch)
            self._updating = False
            self._cv.notify_all()
        if skipped > 0:
            self.stats.record_skip(skipped)
        self.stats.record_update(
            seconds=dt, recomputed=len(info.recomputed),
            reused=len(info.reused),
            changed=(len(changed) if not full else cached_pgs),
            cached_pgs=cached_pgs, cached_pools=len(mapping._raw))
        self.stats.record_fused_epoch(mapping.fused_complete())
        # where did this epoch go: device remap vs candidate
        # extraction vs the host pipeline tail (ROADMAP item 2's
        # bottleneck question, readable via dump_mapping_stats)
        self.stats.record_phases(device_s=device_s, delta_s=delta_s,
                                 host_tail_s=host_tail_s)
        with self._cv:
            # work.epoch >= target and _epoch is monotonic, so the
            # cache is guaranteed at/past the caller's map now; the
            # delta is clamped to the CALLER's epoch, not the head
            return self._delta_since(from_epoch, target)

    def warm(self, osdmap: OSDMap) -> None:
        """Make the cache serve THIS map object — the offline-consumer
        entry (balancer, osdmaptool, what-if runs) whose maps sit at a
        fixed epoch, are rebuilt per run, or may not even belong to
        the online cluster.  A map already served (same object, or a
        content-equal copy of a cached epoch) binds for the cost of a
        signature hash; anything else rebuilds DETACHED from the
        online epoch sequence: tables install for reads, but the
        incremental delta chain is invalidated (never extended with a
        diff against offline tables), the published epoch never
        regresses, and the next online update serves one full rescan.
        On a context shared with online consumers a warm therefore
        costs them cache hits, never correctness — the deployed
        topology gives daemons their own contexts."""
        if self._tables_for(osdmap) is not None:
            with self._cv:
                self._epoch = max(self._epoch, osdmap.epoch)
            return
        with self._cv:
            while self._updating:
                self._cv.wait()
            self._updating = True
            mapping = self._ensure_mapping()
        t0 = time.perf_counter()
        try:
            info = mapping.update(osdmap, engine=self._engine())
        except BaseException:
            with self._cv:
                self._updating = False
                self._cv.notify_all()
            raise
        cached_pgs = sum(int(r.shape[0]) for r in mapping._raw.values())
        with self._cv:
            self._tables = {osdmap.epoch: _Tables(
                osdmap, mapping._raw, mapping._pps, mapping._sigs,
                osdmap.epoch, fused=mapping._fused,
                fused_w=mapping._fused_w,
                tail_sigs=mapping._tail_sigs)}
            self._deltas.clear()
            self._chain_valid = False
            self._epoch = max(self._epoch, osdmap.epoch)
            self._updating = False
            self._cv.notify_all()
        self.stats.record_update(
            seconds=time.perf_counter() - t0,
            recomputed=len(info.recomputed), reused=len(info.reused),
            changed=0, cached_pgs=cached_pgs,
            cached_pools=len(mapping._raw))
        self.stats.record_fused_epoch(mapping.fused_complete())

    def _delta_since(self, from_epoch: int,
                     to_epoch: int | None = None) -> MapUpdate:
        """Union of logged deltas covering EXACTLY (from_epoch,
        to_epoch] — clamped to the caller's own map epoch, never the
        (possibly newer) cache head: a PG that changed at the caller's
        epoch but reverted by the head would be invisible in the
        head-spanning union, yet the caller's map DOES see it.
        Called under the lock."""
        tgt = self.epoch if to_epoch is None else min(to_epoch,
                                                     self.epoch)
        if from_epoch >= tgt:
            return MapUpdate(from_epoch, tgt, (), False)
        changed: set = set()
        e = tgt
        for frm, to, delta in reversed(self._deltas):
            if to > e:
                if frm < e:
                    break    # tgt sits inside a skipped jump
                continue     # entry entirely newer than the caller
            if to != e:
                break
            changed.update(delta)
            e = frm
            if e <= from_epoch:
                break
        if e != from_epoch:
            # chain gap (first map, log overflow, a reader epoch inside
            # a skipped jump, or a warm() broke the chain): full
            # rescan, still served from cache where possible
            self.stats.record_full_rescan()
            return MapUpdate(from_epoch, tgt, None, True)
        return MapUpdate(from_epoch, tgt, sorted(changed), False)

    # -- delta derivation -----------------------------------------------------

    def _fused_delta(self, old: _Tables, mapping: OSDMapMapping):
        """Exact changed-PG set by diffing both epochs' PACKED fused
        tables on device: rows encode the full oracle tuple with
        deterministic padding, so row inequality IS tuple inequality —
        no candidate extraction, no per-candidate host tail.  Returns
        None when either epoch lacks complete fused coverage (the host
        candidate path below stays the exactness fallback)."""
        m_new = mapping.osdmap
        m_old = old.osdmap
        mesh = self._mesh()
        changed: list[tuple[int, int]] = []
        for pool_id, pool in m_new.pools.items():
            newp = mapping._fused.get(pool_id)
            if newp is None:
                return None
            old_pool = m_old.pools.get(pool_id)
            if old_pool is None:
                changed.extend((pool_id, pg)
                               for pg in range(pool.pg_num))
                continue
            oldp = old.fused.get(pool_id)
            if oldp is None:
                return None
            wn = mapping._fused_w[pool_id]
            wo = old.fused_w[pool_id]
            if wn == wo and oldp.shape == newp.shape:
                if oldp.size <= self.FUSED_DIFF_HOST_MAX:
                    # toy tables: one vectorized host compare beats a
                    # device round trip by ~30x on this class of host;
                    # production pool sizes take the device diff below
                    mask = np.flatnonzero((oldp != newp).any(axis=1))
                    changed.extend((pool_id, int(pg)) for pg in mask)
                    continue
                for pg in _changed_rows(oldp, newp, mesh=mesh):
                    changed.append((pool_id, int(pg)))
                continue
            # shared width or pg_num moved (override growth, pool
            # resize): normalize to a common layout and compare the
            # overlapping rows host-side — rare, and still exact
            from ceph_tpu.ops.placement_kernel import normalize_packed
            w = max(wo, wn)
            a = normalize_packed(oldp, wo, w)
            b = normalize_packed(newp, wn, w)
            k = min(a.shape[0], b.shape[0])
            if k:
                for pg in np.flatnonzero((a[:k] != b[:k]).any(axis=1)):
                    changed.append((pool_id, int(pg)))
            changed.extend((pool_id, pg)
                           for pg in range(k, newp.shape[0]))
        return sorted(changed)

    def _compute_delta(self, info: _UpdateInfo):
        """Exact changed-PG set for one epoch transition.  With
        complete fused tables on both sides the delta is a pure
        device diff of the packed outputs (_fused_delta) and the host
        tail contributes NOTHING; otherwise candidates come
        from (a) the on-device raw-table diff of recomputed pools,
        (b) PGs whose raw rows reference OSDs with changed up/exists
        state or primary affinity, and (c) override-keyed PGs whose
        entries moved (or any override key when osd visibility/weights
        moved — upmap validity reads them); then each candidate's full
        (up, up_primary, acting, acting_primary) is compared old-vs-new
        through the cached tables.  O(changed + overrides) host work.

        Returns (changed, full, delta_s, host_tail_s): the epoch's
        phase split — candidate extraction (incl. the on-device raw
        diff) vs the per-candidate host pipeline tail."""
        t0 = time.perf_counter()
        old = info.prev
        mapping = self._mapping
        m_new = mapping.osdmap
        if old.osdmap is None or old.epoch < 0:
            return None, True, 0.0, 0.0
        fused = self._fused_delta(old, mapping)
        if fused is not None:
            return fused, False, time.perf_counter() - t0, 0.0
        m_old = old.osdmap
        no = max(m_old.max_osd, m_new.max_osd, 1)
        st = (_vec(m_old.osd_state, no) != _vec(m_new.osd_state, no))
        af = (_vec(m_old.osd_primary_affinity, no, MAX_AFFINITY)
              != _vec(m_new.osd_primary_affinity, no, MAX_AFFINITY))
        changed_osds = np.flatnonzero(st | af)
        weights_moved = bool((_vec(m_old.osd_weight, no)
                              != _vec(m_new.osd_weight, no)).any())
        cand: set[tuple[int, int]] = set()
        recomputed = set(info.recomputed)
        mesh = self._mesh()     # once per epoch, not per pool
        for pool_id, pool in m_new.pools.items():
            new_raw = mapping._raw.get(pool_id)
            if new_raw is None:
                continue
            old_pool = m_old.pools.get(pool_id)
            old_raw = old.raw.get(pool_id)
            if (old_pool is None or old_raw is None
                    or old_pool.pg_num != pool.pg_num
                    or old_pool.type != pool.type
                    or old_raw.shape != new_raw.shape):
                cand.update((pool_id, pg) for pg in range(pool.pg_num))
                continue
            if pool_id in recomputed:
                for pg in _changed_rows(old_raw, new_raw, mesh=mesh):
                    cand.add((pool_id, int(pg)))
                if old_pool.pgp_num != pool.pgp_num:
                    # pps is the affinity seed: it can move a primary
                    # even where the raw row happens to coincide
                    po = old.pps.get(pool_id)
                    pn = mapping._pps.get(pool_id)
                    if po is None or pn is None:
                        cand.update((pool_id, pg)
                                    for pg in range(pool.pg_num))
                    else:
                        for pg in np.flatnonzero(po != pn):
                            cand.add((pool_id, int(pg)))
            if changed_osds.size and new_raw.size:
                mask = np.isin(new_raw, changed_osds).any(axis=1)
                if old_raw is not new_raw:   # reused pools alias
                    mask |= np.isin(old_raw, changed_osds).any(axis=1)
                for pg in np.flatnonzero(mask):
                    cand.add((pool_id, int(pg)))
        ov_keys: set[tuple[int, int]] = set()
        for attr in ("pg_temp", "primary_temp", "pg_upmap",
                     "pg_upmap_items"):
            do = getattr(m_old, attr)
            dn = getattr(m_new, attr)
            for k in set(do) | set(dn):
                if do.get(k) != dn.get(k):
                    ov_keys.add(k)
            if changed_osds.size or weights_moved:
                ov_keys.update(do)
                ov_keys.update(dn)
        for pool_id, pg in ov_keys:
            pool = m_new.pools.get(pool_id)
            if pool is not None and 0 <= pg < pool.pg_num:
                cand.add((pool_id, pg))
        t_cand = time.perf_counter()
        changed = []
        for pool_id, pg in cand:
            pool_n = m_new.pools[pool_id]
            new_t = _finish_from(m_new, pool_n, pool_id, pg,
                                 mapping._raw, mapping._pps)
            pool_o = m_old.pools.get(pool_id)
            old_t = None
            if (pool_o is not None and pg < pool_o.pg_num
                    and pool_id in old.raw
                    and pg < old.raw[pool_id].shape[0]):
                old_t = _finish_from(m_old, pool_o, pool_id, pg,
                                     old.raw, old.pps)
            if new_t != old_t:
                changed.append((pool_id, pg))
        return (sorted(changed), False, t_cand - t0,
                time.perf_counter() - t_cand)

    # -- reads ----------------------------------------------------------------

    def _tables_for(self, osdmap: OSDMap) -> _Tables | None:
        with self._cv:
            t = self._tables.get(osdmap.epoch)
            if t is None:
                return None
            # identity first: the module contract is that maps are
            # immutable once published, so the object the tables were
            # built from IS the epoch's content
            if t.osdmap is osdmap or t._has(t.bound, osdmap):
                return t
            if t._has(t.rejected, osdmap):
                return None
        # a DIFFERENT object at the same epoch — usually another
        # daemon's decode of the same published map.  Equal placement
        # signatures mean bit-identical raw tables (the pipeline tail
        # always reads the CALLER's map), so content-check once and
        # bind; a mismatch (foreign cluster sharing a context) is
        # memoized too so every later read is a cheap oracle fallback
        try:
            _csig, sigs = pool_signatures(osdmap)
        except Exception:
            return None
        tail_ok = False
        with self._cv:
            t2 = self._tables.get(osdmap.epoch)
        if t2 is not None and sigs == t2.sigs and t2.fused:
            # the raw signature deliberately excludes tail inputs:
            # verify them once (outside the lock — pure content
            # compare) so this copy may read the FUSED rows too;
            # a tail-divergent copy still binds, but reads go through
            # the host tail against its own map
            try:
                tail_ok = _tail_equal(t2.osdmap, osdmap)
            except Exception:
                tail_ok = False
        with self._cv:
            t3 = self._tables.get(osdmap.epoch)
            if t3 is None:
                return None
            if sigs == t3.sigs:
                t3._memo(t3.bound, osdmap)
                # tail_ok was verified against t2's map: only valid if
                # the published tables were not swapped meanwhile (a
                # racing warm() replacing the epoch)
                if tail_ok and t3 is t2:
                    t3._memo(t3.tail_bound, osdmap)
                return t3
            t3._memo(t3.rejected, osdmap)
            return None

    def lookup(self, osdmap: OSDMap, pool_id: int, pgid: int
               ) -> tuple[list[int], int, list[int], int]:
        """pg_to_up_acting_osds served from the cache — a packed-row
        read when the fused ladder published this pool (and the caller
        holds the service's map object or a tail-verified copy), the
        host pipeline tail over the cached raw row otherwise;
        scalar-oracle fallback on any epoch/object/pool mismatch."""
        pool = osdmap.pools[pool_id]
        t = self._tables_for(osdmap)
        if t is not None:
            if t.fused and (t.osdmap is osdmap
                            or t._has(t.tail_bound, osdmap)):
                fr = t.fused.get(pool_id)
                if fr is not None and 0 <= pgid < fr.shape[0]:
                    self.stats.record_lookup(True, fused=True)
                    from ceph_tpu.ops.placement_kernel import unpack_row
                    return unpack_row(fr[pgid], t.fused_w[pool_id])
            row = t.raw.get(pool_id)
            if row is not None and 0 <= pgid < row.shape[0]:
                self.stats.record_lookup(True)
                return _finish_from(osdmap, pool, pool_id, pgid,
                                    t.raw, t.pps)
        self.stats.record_lookup(False)
        return osdmap.pg_to_up_acting_osds(pool_id, pgid)

    def raw_row(self, osdmap: OSDMap, pool_id: int,
                pg: int) -> list[int] | None:
        """Cached _pg_to_raw_osds row (balancer's what-if input), or
        None when the cache cannot serve this map/pool."""
        t = self._tables_for(osdmap)
        if t is None:
            return None
        r = t.raw.get(pool_id)
        if r is None or not (0 <= pg < r.shape[0]):
            return None
        row = [int(o) for o in r[pg]]
        if not osdmap.pools[pool_id].is_erasure():
            row = [o for o in row if o != CRUSH_ITEM_NONE]
        return row

    def what_if_up(self, osdmap: OSDMap, pool_id: int,
                   candidates: list[tuple[int, list]]
                   ) -> list[list[int]] | None:
        """Batched what-if scoring for the balancer: the ``up`` set
        each candidate ``(pg, upmap_items_pairs)`` would produce —
        raw row + pair rewrites + state filtering, NO full-upmap/temp
        overrides, exactly the host ``up_of`` the balancer used to run
        per candidate — evaluated for ALL candidates in one fused
        ladder call.  None when the cache cannot serve this map or the
        fused ladder is unavailable (caller falls back to the host
        pipeline)."""
        if not candidates:
            return []
        mapping = self._mapping
        if (mapping is None or not getattr(mapping, "fused", False)
                or mapping.backend == "scalar"):
            return None
        t = self._tables_for(osdmap)
        if t is None:
            return None
        raw = t.raw.get(pool_id)
        pps = t.pps.get(pool_id)
        pool = osdmap.pools.get(pool_id)
        if raw is None or pps is None or pool is None:
            return None
        pgs = [pg for pg, _prs in candidates]
        if any(not (0 <= pg < raw.shape[0]) for pg in pgs):
            return None
        from ceph_tpu.ops import placement_kernel as pk
        b = len(candidates)
        pairs = max(max((len(prs) for _pg, prs in candidates),
                        default=1), 1)
        width = max(int(pool.size), raw.shape[1], 1)
        state, weight, affinity = osdmap.dense_osd_vectors()
        idx = np.asarray(pgs, dtype=np.int64)
        items = np.full((b, pairs, 2), -1, dtype=np.int32)
        for i, (_pg, prs) in enumerate(candidates):
            for j, (frm, to) in enumerate(prs[:pairs]):
                items[i, j, 0] = frm
                items[i, j, 1] = to
        ops_ = pk.LadderOperands(
            raw=pk.pad_raw(raw[idx], width),
            pps=np.asarray(pps)[idx].astype(np.uint32),
            raw_len=np.full(b, raw.shape[1], dtype=np.int32),
            up_rows=np.full((b, width), CRUSH_ITEM_NONE,
                            dtype=np.int32),
            up_len=np.zeros(b, dtype=np.int32),
            items=items,
            temp_rows=np.full((b, width), -1, dtype=np.int32),
            temp_len=np.zeros(b, dtype=np.int32),
            ptemp=np.full(b, -1, dtype=np.int32),
            state=state, weight=weight, affinity=affinity,
            erasure=pool.is_erasure(), width=width)
        try:
            engine = self._engine()
            if engine is not None:
                from ceph_tpu.ops.dispatch import (
                    BACKGROUND_BEST_EFFORT, submit_finish_ladder)
                packed = np.asarray(submit_finish_ladder(
                    engine, ops_,
                    cost_tag=("system", BACKGROUND_BEST_EFFORT),
                ).result(timeout=120.0))
            else:
                packed = pk.run_ladder(ops_)
        except Exception:
            return None
        return [pk.unpack_row(packed[i], width)[0] for i in range(b)]

    def pg_counts(self, osdmap: OSDMap, pool_id: int) -> np.ndarray:
        """Per-OSD PG count histogram for a pool (osdmaptool input);
        requires the cache to be at this map (update_to it first)."""
        t = self._tables_for(osdmap)
        if t is None:
            raise KeyError(f"mapping cache not at epoch {osdmap.epoch}")
        raw = t.raw[pool_id]
        valid = raw[(raw != CRUSH_ITEM_NONE) & (raw >= 0)]
        return np.bincount(valid, minlength=osdmap.max_osd)

    def place(self, crush: CrushMap, ruleno: int, xs, numrep: int,
              reweight) -> np.ndarray:
        """Bulk rule evaluation for offline tools (psim/crushtool):
        the production path — cached mapper, dispatch-engine
        submission — without needing an OSDMap."""
        xs = np.asarray(xs, dtype=np.uint32)
        reweight = np.asarray(reweight, dtype=np.int64)
        mapping = self._ensure_mapping()
        if mapping.backend == "scalar":
            return scalar_rows(crush, ruleno, xs, numrep, reweight)
        bm = mapping.mapper_for(crush)
        engine = self._engine()
        if engine is not None:
            from ceph_tpu.ops.dispatch import (
                BACKGROUND_BEST_EFFORT, submit_do_rule)
            return np.asarray(submit_do_rule(
                engine, bm, ruleno, xs, numrep, reweight,
                cost_tag=("system", BACKGROUND_BEST_EFFORT),
            ).result(timeout=120.0))
        return np.asarray(bm.do_rule(ruleno, xs, numrep, reweight))
