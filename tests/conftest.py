"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding (pjit/shard_map over a
jax.sharding.Mesh) is exercised without TPU hardware — the same mechanism the driver's
dryrun uses.  This must be configured before jax initializes its backends.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# config.update, not the env var: the environment exports JAX_PLATFORMS=axon (the
# real TPU tunnel) and the plugin outranks an env override, but tests need the
# virtual 8-device CPU mesh
jax.config.update("jax_platforms", os.environ.get("CEPH_TPU_TEST_PLATFORM", "cpu"))

import ceph_tpu  # noqa: E402,F401  (enables x64 before tests create arrays)
