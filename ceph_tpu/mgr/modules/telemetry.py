"""Telemetry module (src/pybind/mgr/telemetry analog): anonymized
cluster-shape report — no object names, no addresses; counts, sizes,
states, pool shapes only, like the reference's opt-in payload."""

from __future__ import annotations

import json

from ceph_tpu.mgr.module import MgrModule


class Module(MgrModule):
    NAME = "telemetry"
    COMMANDS = [{"prefix": "telemetry show",
                 "help": "the anonymized report payload"}]

    def report(self) -> dict:
        m = self.get_osdmap()
        pools = [{"pool": pid, "pg_num": p.pg_num,
                  "type": ("erasure" if p.is_erasure()
                           else "replicated"),
                  "size": getattr(p, "size", 0),
                  "cache_tier": p.tier_of >= 0}
                 for pid, p in m.pools.items()]
        df = self.get("df")
        return {
            "report_version": 1,
            "osd": {"count": sum(1 for o in range(m.max_osd)
                                 if m.exists(o)),
                    "up": sum(1 for o in range(m.max_osd)
                              if m.is_up(o))},
            "osdmap_epoch": m.epoch,
            "pools": pools,
            "pg_states": self.get("pg_summary"),
            "usage": {"total_objects": df["total_objects"],
                      "total_bytes_used": df["total_bytes_used"]},
            "health": self.get("health")["status"],
        }

    def handle_command(self, cmd: dict) -> tuple[str, int]:
        return json.dumps(self.report()), 0
