"""Bit-exactness pins, forever (VERDICT round-1 item 6).

tests/golden/ec_corpus/*.npz archives the encoded chunks of every
plugin x technique x (k, m) configuration (non-regression corpus,
ceph_erasure_code_non_regression.cc analog); crush_golden.npz pins the
full 16-bit crush_ln domain, the frozen ln tables (verified bit-identical
to src/crush/crush_ln_table.h), and rjenkins hash vectors.  CI fails if
any kernel's bytes ever change.
"""

import os

import numpy as np

from ceph_tpu.tools.ec_non_regression import CONFIGS, DEFAULT_DIR, check

GOLDEN = os.path.join(os.path.dirname(__file__), "golden")


def test_ec_corpus_bit_identical(capsys):
    assert check(DEFAULT_DIR) == 0, capsys.readouterr().out


def test_corpus_covers_every_plugin():
    plugins = {plugin for _name, plugin, _p in CONFIGS}
    assert plugins == {"jerasure", "isa", "shec", "lrc", "clay"}


def test_crush_ln_full_domain():
    from ceph_tpu.crush.mapper_ref import crush_ln
    g = np.load(os.path.join(GOLDEN, "crush_golden.npz"))
    want = g["ln_all"]
    # spot lattice + boundary values scalar-side (fast)...
    for u in [0, 1, 2, 255, 256, 0x7FFF, 0x8000, 0xFFFE, 0xFFFF]:
        assert crush_ln(u) == want[u], u
    # ...and the whole domain through the batched kernel
    import jax.numpy as jnp
    from ceph_tpu.ops.crush_kernel import crush_ln as crush_ln_jax
    got = np.asarray(crush_ln_jax(jnp.arange(65536, dtype=jnp.uint32)))
    assert (got == want).all()


def test_ln_tables_frozen():
    from ceph_tpu.crush.ln_table import lh_table, ll_table, rh_table
    g = np.load(os.path.join(GOLDEN, "crush_golden.npz"))
    assert (np.asarray(rh_table(), dtype=np.int64) == g["rh"]).all()
    assert (np.asarray(lh_table(), dtype=np.int64) == g["lh"]).all()
    assert (np.asarray(ll_table(), dtype=np.int64) == g["ll"]).all()


def test_rjenkins_hash_vectors():
    from ceph_tpu.crush.hashfn import crush_hash32_2, crush_hash32_3
    g = np.load(os.path.join(GOLDEN, "crush_golden.npz"))
    a, b, c = g["hash_a"], g["hash_b"], g["hash_c"]
    for i in range(0, len(a), 64):   # scalar spot checks
        assert crush_hash32_3(int(a[i]), int(b[i]), int(c[i])) \
            == int(g["hash3"][i])
        assert crush_hash32_2(int(a[i]), int(b[i])) == int(g["hash2"][i])
    # batched kernel over the whole vector set
    import jax.numpy as jnp
    from ceph_tpu.ops.crush_kernel import hash32_2, hash32_3
    got3 = np.asarray(hash32_3(jnp.asarray(a), jnp.asarray(b),
                               jnp.asarray(c)))
    got2 = np.asarray(hash32_2(jnp.asarray(a), jnp.asarray(b)))
    assert (got3 == g["hash3"]).all()
    assert (got2 == g["hash2"]).all()
