"""FSMap in the mon + MDS beacons + standby promotion + client
failover with cap reassert (mon/MDSMonitor.cc + MMDSBeacon + client
reconnect analogs)."""

from __future__ import annotations

import time

import pytest

from ceph_tpu.cephfs import CephFS
from ceph_tpu.mds.caps import BUFFER
from ceph_tpu.tools.vstart import MiniCluster


@pytest.fixture
def fs_cluster():
    c = MiniCluster(n_osds=3, ms_type="loopback").start()
    try:
        c.wait_for_osd_count(3)
        client = c.client(timeout=20.0)
        meta = c.create_pool(client, pg_num=4, size=2)
        data = c.create_pool(client, pg_num=8, size=2)
        rc, out = client.mon_command({"prefix": "fs new",
                                      "fs_name": "cephfs",
                                      "metadata": meta, "data": data})
        assert rc == 0, out
        yield c, client
    finally:
        c.stop()


def _wait_rank0(client, timeout=15.0, not_gid=None):
    deadline = time.time() + timeout
    while time.time() < deadline:
        fs = client.osdmap.fs_db
        ent = (fs or {}).get("ranks", {}).get("0")
        if ent and (not_gid is None or ent["gid"] != not_gid):
            return ent
        time.sleep(0.1)
    raise AssertionError("rank 0 never (re)filled")


def test_fs_new_and_rank_assignment(fs_cluster):
    c, client = fs_cluster
    mds, standby = c.run_fs_mds(2)
    ent = _wait_rank0(client)
    # one daemon got rank 0, the other parked as standby
    active = mds if ent["gid"] == mds.gid else standby
    other = standby if active is mds else mds
    deadline = time.time() + 20
    while active.state != "active" and time.time() < deadline:
        time.sleep(0.05)
    assert active.rank == 0 and active.state == "active"
    assert other.rank is None and other.state == "standby"
    rc, out = client.mon_command({"prefix": "fs status"})
    assert rc == 0 and "ranks" in out


def test_failover_promotes_standby_and_client_survives(fs_cluster):
    c, client = fs_cluster
    c.run_fs_mds(2)
    ent0 = _wait_rank0(client)

    fs = CephFS(c.mon_host, ms_type="loopback", client_id=301)
    fs.mount()                       # auto-resolves rank 0 from FSMap
    try:
        fs.mkdir("/surv")
        f = fs.open("/surv/file", "w")
        f.write(b"pre-failover data")
        f.close()                    # flushed + journaled on rank 0
        f2 = fs.open("/surv/file", "w")
        assert f2.state.caps & BUFFER
        f2.write(b"POST", )          # buffered under held caps

        # SIGKILL the active MDS: no flush, no goodbye
        active = next(d for d in c.fs_mds if d.gid == ent0["gid"])
        c.crash_fs_mds(active)
        ent1 = _wait_rank0(client, timeout=20.0, not_gid=ent0["gid"])
        assert ent1["gid"] != ent0["gid"]

        # the client's next MDS op fails over, reasserts caps, and the
        # replayed journal preserves everything acked: the open-"w"
        # truncate to 0 was journaled, and the buffered 4-byte write
        # rides the reassert
        st = fs.stat("/surv/file")
        assert st["size"] == 4
        f2.write(b"-and-more")       # caps still usable post-reassert
        f2.close()
        assert fs.stat("/surv/file")["size"] == 13
        got = fs.open("/surv/file").read()
        assert got == b"POST-and-more"
        assert fs.listdir("/")       # namespace intact
    finally:
        fs.unmount()


def test_standby_keeps_beaconing_and_refills(fs_cluster):
    """After a failover consumes the standby, a NEW daemon joining
    becomes the next standby; a second failover promotes it too."""
    c, client = fs_cluster
    c.run_fs_mds(2)
    ent0 = _wait_rank0(client)
    active0 = next(d for d in c.fs_mds if d.gid == ent0["gid"])
    c.crash_fs_mds(active0)
    ent1 = _wait_rank0(client, timeout=20.0, not_gid=ent0["gid"])

    c.run_fs_mds(1)                  # late joiner becomes standby
    active1 = next(d for d in c.fs_mds if d.gid == ent1["gid"])
    c.crash_fs_mds(active1)
    ent2 = _wait_rank0(client, timeout=20.0, not_gid=ent1["gid"])
    assert ent2["gid"] not in (ent0["gid"], ent1["gid"])


def test_fsmap_with_three_mons():
    """Beacons must reach the leader wherever it is (they fan out to
    every mon in the comma-separated mon host list)."""
    c = MiniCluster(n_osds=2, ms_type="loopback", n_mons=3).start()
    try:
        c.wait_for_osd_count(2)
        client = c.client(timeout=20.0)
        meta = c.create_pool(client, pg_num=4, size=2)
        data = c.create_pool(client, pg_num=4, size=2)
        rc, out = client.mon_command({"prefix": "fs new",
                                      "fs_name": "cephfs",
                                      "metadata": meta, "data": data})
        assert rc == 0, out
        c.run_fs_mds(1)
        ent = _wait_rank0(client)
        assert ent["gid"] == c.fs_mds[0].gid
    finally:
        c.stop()
