"""Experiment 4: careful re-measurement of the top Pallas configs."""
from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from ceph_tpu.ops.gf_kernel import ec_encode_ref
from ceph_tpu.gf.matrix import gen_cauchy1_matrix
from exp_gf import bit_matrix, K, M, CHUNK, STRIPES
from exp_gf3 import enc_pallas


def measure(step_fn, carry, n_lo=4, n_hi=20, reps=5):
    @functools.partial(jax.jit, static_argnames="n")
    def loop(c, n):
        c, _ = jax.lax.scan(lambda c, _: (step_fn(c), ()), c, None, length=n)
        return jax.tree_util.tree_leaves(c)[0].ravel()[0]

    jax.device_get(loop(carry, n_lo))
    jax.device_get(loop(carry, n_hi))
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.device_get(loop(carry, n_lo))
        t_lo = time.perf_counter() - t0
        t0 = time.perf_counter()
        jax.device_get(loop(carry, n_hi))
        t_hi = time.perf_counter() - t0
        ts.append(max(t_hi - t_lo, 1e-9) / (n_hi - n_lo))
    return ts


def main():
    gen = gen_cauchy1_matrix(K, M)
    coding = gen[K:]
    rng = np.random.default_rng(0)
    data_np = rng.integers(0, 256, (STRIPES, K, CHUNK), dtype=np.uint8)
    data = jnp.asarray(data_np)
    data_bytes = STRIPES * K * CHUNK
    ref = ec_encode_ref(coding, data_np)
    wb = bit_matrix(coding)

    def wblk_of(g):
        w = np.zeros((g * K * 8, g * M * 8), dtype=np.int8)
        for i in range(g):
            w[i * K * 8:(i + 1) * K * 8, i * M * 8:(i + 1) * M * 8] = wb
        return jnp.asarray(w)

    for g, sb in [(4, 4), (4, 8), (2, 4), (1, 4), (2, 8)]:
        w = wblk_of(g)
        fn = lambda d, g=g, sb=sb, w=w: enc_pallas(w, d, k=K, m=M, g=g, sb=sb, dot="int8")
        out = np.asarray(fn(data))
        ok = np.array_equal(out, ref)

        def step(d, fn=fn):
            p = fn(d)
            return d.at[0, 0, 0].set(p[0, 0, 0] ^ jnp.uint8(1))

        ts = measure(step, data)
        rates = sorted(data_bytes / t / 1e9 for t in ts)
        med = rates[len(rates) // 2]
        print(f"g{g}_sb{sb}: {'OK ' if ok else 'BAD'} med {med:7.2f} GB/s  "
              f"[{rates[0]:.1f} .. {rates[-1]:.1f}]")


if __name__ == "__main__":
    main()
