"""Fused Pallas TPU kernels for the CRUSH straw2 column draws.

The XLA path (ops.straw2_u32 driven by crush.fastpath) is bit-exact but
this backend leaves long u32 elementwise chains unfused: a single
(65536, 256) draw column costs ~25 ms against a ~0.5 ms roofline, with
hundreds of materialized (N, S) intermediates.  These kernels fuse one
whole column — rjenkins hash, crush_ln limb pipeline, magic division,
first-min winner select, and the is_out verdict — into one VMEM-resident
Pallas program per (r, block) grid step:

  root kernel:  xs block -> winner position/id per r  (+ is_out for flat
                rules, whose first level already lands on devices)
  leaf kernel:  root winner position -> the winning host's device row
                (fetched with an exact f32 one-hot MXU dot — a vectorized
                row gather the VPU cannot do) -> device winner + is_out

Bit-exactness contract: identical output to ops.straw2_u32 (itself
validated exhaustively against the s64 kernel and the scalar C-semantics
oracle).  tests/test_pallas_straw2.py compares both, exhaustively over
the 16-bit hash domain for the ln/divide pipeline and end-to-end on
random maps, in interpret mode on CPU and compiled on TPU.

Table lookups ride the MXU as exact one-hot matmuls (8-bit limbs in
bf16, one-hot 0/1 exact; f32 accumulator sums < 2^15).  The count-
leading-zeros of the ln normalization uses the f32 exponent field
(exact: inputs < 2^17 convert exactly).  All element math is u32/i32 —
no 64-bit emulation anywhere.
"""

from __future__ import annotations

import functools
import sys

# the unrolled R-column kernels build deep expression trees; default
# CPython recursion limits trip inside jax lowering
if sys.getrecursionlimit() < 20000:
    sys.setrecursionlimit(20000)

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ceph_tpu.ops.crush_kernel import (
    _ln_limb_operands_np, hash32_2, hash32_3)

_U32 = jnp.uint32
_I32 = jnp.int32

#: rows per grid step (TPU blocks need a 128-divisible last dim; VMEM
#: stays small because table lookups are group-accumulated — see _lookup)
BLOCK = 128


def _bitlen_f32(v):
    """bit length of v (uint32, v < 2^17) via the f32 exponent field —
    Mosaic-safe replacement for lax.clz; exact because the convert is."""
    # Mosaic has no u32->f32 cast; go through i32 (values < 2^17, safe)
    f = (v | _U32(1)).astype(_I32).astype(jnp.float32)
    e = (jax.lax.bitcast_convert_type(f, _U32) >> 23) - _U32(127)
    return e + _U32(1)


def _row_lookup(idx, row):
    """Per-lane table lookup: idx (B, S) i32 with values < S; row (S,)
    i32 holding the table in its leading lanes.  Lowers to Mosaic's
    tpu.dynamic_gather (take_along_axis on same-shaped 2-D operands) —
    a lane shuffle, with none of the one-hot matmul's VMEM or reshape
    trouble."""
    x = jnp.broadcast_to(row[None, :], idx.shape)
    # raw lax.gather with i32 indices: jnp.take_along_axis promotes its
    # indices to i64 under x64, which Mosaic cannot lower.  These
    # dimension numbers are exactly the per-lane tpu.dynamic_gather
    # pattern Mosaic's gather rule recognizes.
    dnums = jax.lax.GatherDimensionNumbers(
        offset_dims=(), collapsed_slice_dims=(1,), start_index_map=(1,),
        operand_batching_dims=(0,), start_indices_batching_dims=(0,))
    return jax.lax.gather(
        x, idx[..., None], dnums, slice_sizes=(1, 1),
        mode=jax.lax.GatherScatterMode.PROMISE_IN_BOUNDS)


def _ln_p48_pl(u, rhlh_ref, ll_lo_ref, ll_hi_ref, rh128):
    """P = 2^48 - crush_ln(u) as (p_hi17, p_lo32) u32 — the Pallas twin
    of straw2_u32._crush_ln_p48.

    rhlh_ref (13, S): limb j's table for k in [0, 127]; rh128 is the
    k == 128 row as python constants (tables must fit the S-lane gather
    width, and the leaf kernel runs at S = 128).  ll_lo/ll_hi (6, S):
    the 256-entry LL table split at row 128 the same way.
    """
    x = u.astype(_U32) + _U32(1)
    low17 = x & _U32(0x1FFFF)
    bits = _U32(16) - _bitlen_f32(low17)
    needs_norm = (x & _U32(0x18000)) == 0
    xnorm = jnp.where(needs_norm, x << bits, x).astype(_I32)
    iexpon = jnp.where(needs_norm, _U32(15) - bits, _U32(15)).astype(_I32)
    idx1 = (xnorm.astype(_U32) >> 8) << 1
    k = ((idx1 - _U32(256)) >> 1).astype(_I32)
    k_cap = jnp.minimum(k, _I32(127))
    is128 = k == _I32(128)
    rhlh = [jnp.where(is128, _I32(rh128[j]),
                      _row_lookup(k_cap, rhlh_ref[j, :]))
            for j in range(13)]
    acc = jnp.zeros_like(xnorm)
    for j in range(7):
        acc = (acc >> 8) + xnorm * rhlh[j]
    idx2 = acc & _I32(0xFF)
    lo7 = idx2 & _I32(127)
    hi_half = idx2 >= _I32(128)
    ll = [jnp.where(hi_half, _row_lookup(lo7, ll_hi_ref[j, :]),
                    _row_lookup(lo7, ll_lo_ref[j, :]))
          for j in range(6)]
    bj = []
    carry = jnp.zeros_like(xnorm)
    for j in range(6):
        t = rhlh[7 + j] + ll[j] + carry
        bj.append(t & _I32(0xFF))
        carry = t >> 8
    bj.append(carry)
    v = [((bj[j] >> 4) | ((bj[j + 1] & _I32(0xF)) << 4)) for j in range(6)]
    v[5] = v[5] + ((iexpon & _I32(0xF)) << 4)
    ln_lo = (v[0] | (v[1] << 8) | (v[2] << 16)).astype(_U32) \
        | (v[3].astype(_U32) << 24)
    ln_hi = (v[4] | (v[5] << 8)).astype(_U32)
    is_zero = (ln_lo == 0) & (ln_hi == 0)
    p_lo = (~ln_lo) + _U32(1)
    carry_in = jnp.where(ln_lo == 0, _U32(1), _U32(0))
    p_hi = (((~ln_hi) & _U32(0xFFFF)) + carry_in) & _U32(0x1FFFF)
    p_lo = jnp.where(is_zero, _U32(0), p_lo)
    p_hi = jnp.where(is_zero, _U32(0x10000), p_hi)
    return p_hi, p_lo


def _magic_div_pl(p_hi, p_lo, magic, off):
    """floor(P/w): the shared magic-multiply (straw2_u32) with magic as
    a list of 5 (B, S) limb planes — one implementation for both the
    XLA path and these kernels (pure jnp, Mosaic-safe)."""
    from ceph_tpu.ops.straw2_u32 import magic_divide_planes
    return magic_divide_planes(p_hi, p_lo, magic, off)


def _umin(v, axis, keepdims):
    """u32 min via the order-preserving signed bias (Mosaic has no
    unsigned reductions)."""
    s = (v ^ _U32(0x80000000)).astype(_I32)
    m = jnp.min(s, axis=axis, keepdims=keepdims)
    return m.astype(_U32) ^ _U32(0x80000000)


def _ult(a, b):
    """unsigned < via the sign bias (Mosaic lacks unsigned compares)."""
    return ((a ^ _U32(0x80000000)).astype(_I32)
            < (b ^ _U32(0x80000000)).astype(_I32))


def _first_min(q_hi, q_lo, ids):
    """Lexicographic first minimum along axis 1: winner q pair, position,
    id, and the winner one-hot mask (for gathering sibling values)."""
    b, s = q_hi.shape
    min_hi = _umin(q_hi, 1, True)
    on_h = q_hi == min_hi
    lo_m = jnp.where(on_h, q_lo, _U32(0xFFFFFFFF))
    min_lo = _umin(lo_m, 1, True)
    on = on_h & (lo_m == min_lo)
    # "first index wins": the smallest position among the tied minima
    # (no cumsum in Mosaic — a masked min over iota does the same)
    iota = jax.lax.broadcasted_iota(_I32, (b, s), 1)
    pos_m = jnp.where(on, iota, _I32(2 ** 31 - 1))
    minpos = jnp.min(pos_m, axis=1, keepdims=True)
    first = on & (iota == minpos)
    pos = minpos[:, 0]
    # dtype pinned: with x64 enabled jnp.sum promotes i32 -> i64,
    # which Mosaic cannot lower
    wid = jnp.sum(jnp.where(first, ids, _I32(0)), axis=1, dtype=_I32)
    return min_hi[:, 0], min_lo[:, 0], pos, wid, first


def _is_out_scalar(rw, item, x):
    """is_out (mapper.c:424-438) for already-gathered reweight values;
    all (B,) vectors."""
    keep_full = rw >= _I32(0x10000)
    zero = rw == 0
    h = hash32_2(x, item.astype(_U32)) & _U32(0xFFFF)
    keep_prob = h.astype(_I32) < rw
    return ~(keep_full | ((~zero) & keep_prob))


def _draw_slab(x, ids, wz, magic_planes, off, tabs, r):
    """One 128-lane slab of a straw2 column: (B,) x, (B, 128) item
    operands -> winner (q_hi, q_lo, pos, wid, first).  Slabs are 128 wide
    because tpu.dynamic_gather shuffles within a single vreg."""
    rhlh_ref, ll_lo_ref, ll_hi_ref, rh128 = tabs
    u = hash32_3(x[:, None], ids, r) & _U32(0xFFFF)
    p_hi, p_lo = _ln_p48_pl(u, rhlh_ref, ll_lo_ref, ll_hi_ref, rh128)
    q_hi, q_lo = _magic_div_pl(p_hi, p_lo, magic_planes, off)
    bad = wz != 0
    q_hi = jnp.where(bad, _U32(0xFFFFFFFF), q_hi)
    q_lo = jnp.where(bad, _U32(0xFFFFFFFF), q_lo)
    return _first_min(q_hi, q_lo, ids)


def _merge_slabs(best, new):
    """Merge a later slab's winner into the running best: strictly
    smaller (q_hi, q_lo) wins — ties stay with the earlier slab, whose
    positions are lower (the first-index rule)."""
    if best is None:
        return new
    bqh, bql, bpos, bwid, brw = best
    nqh, nql, npos, nwid, nrw = new
    better = _ult(nqh, bqh) | ((nqh == bqh) & _ult(nql, bql))
    return (jnp.where(better, nqh, bqh), jnp.where(better, nql, bql),
            jnp.where(better, npos, bpos), jnp.where(better, nwid, bwid),
            jnp.where(better, nrw, brw))


def _column_over_slabs(x, S, tabs, r, slab_operands, rw_of_slab):
    """Full-bucket column: iterate 128-wide slabs, merge winners.
    slab_operands(slab) -> (ids, wz, magic[5], off) as (B, 128) values;
    rw_of_slab(slab, first) -> (B,) winner reweight (or zeros)."""
    best = None
    for slab in range(S // 128):
        ids, wz, magic, off = slab_operands(slab)
        qh, ql, pos, wid, first = _draw_slab(x, ids, wz, magic, off,
                                             tabs, r)
        rwv = rw_of_slab(slab, first)
        pos = pos + _I32(slab * 128)
        best = _merge_slabs(best, (qh, ql, pos, wid, rwv))
    return best


def _store_row(ref, r, value):
    """Write one (B,) row at dynamic sublane index r of an (R, B) ref."""
    ref[pl.dslice(r, 1), :] = value[None, :]


def _root_kernel(xs_ref, ids_ref, wz_ref, magic_ref, off_ref, rw_ref,
                 rhlh_ref, ll_lo_ref, ll_hi_ref,
                 pos_ref, id_ref, bad_ref, *, flat, S, rh128):
    """Grid (n//B, R): one (block, r) column per step — r rides the grid
    so the kernel stays small enough for Mosaic to compile quickly."""
    r = pl.program_id(1)
    x = xs_ref[0, :]
    tabs = (rhlh_ref, ll_lo_ref, ll_hi_ref, rh128)

    def operands(slab):
        sl = slice(slab * 128, (slab + 1) * 128)
        return (ids_ref[0, sl][None, :], wz_ref[0, sl][None, :],
                [magic_ref[j, sl][None, :].astype(_U32) for j in range(5)],
                off_ref[0, sl][None, :])

    def rw_of(slab, first):
        if not flat:
            return jnp.zeros((x.shape[0],), dtype=_I32)
        sl = slice(slab * 128, (slab + 1) * 128)
        return jnp.sum(jnp.where(first, rw_ref[0, sl][None, :], _I32(0)),
                       axis=1, dtype=_I32)

    _qh, _ql, pos, wid, rwv = _column_over_slabs(
        x, S, tabs, r.astype(_U32), operands, rw_of)
    _store_row(pos_ref, r, pos)
    _store_row(id_ref, r, wid)
    if flat:
        _store_row(bad_ref, r, _is_out_scalar(rwv, wid, x).astype(_I32))
    else:
        _store_row(bad_ref, r, jnp.zeros_like(pos))


def _leaf_kernel(xs_ref, pos_ref, static_ref, rw_ref,
                 rhlh_ref, ll_lo_ref, ll_hi_ref,
                 id_ref, bad_ref, *, H, S, vary_r, rh128):
    r = pl.program_id(1)
    if vary_r:
        r_leaf = (r >> (vary_r - 1)).astype(_U32)
    else:
        r_leaf = _U32(0)
    x = xs_ref[0, :]
    iota = jax.lax.broadcasted_iota(_I32, (1, H), 1)
    tabs = (rhlh_ref, ll_lo_ref, ll_hi_ref, rh128)
    pos = pos_ref[pl.dslice(r, 1), :][0, :]   # this r's root winners
    # exact f32 one-hot row gather of the winning host's packed
    # fields: [ids | wz | off | magic0..magic4] (each S wide) + the
    # reweight row (dynamic) — a vectorized row gather on the MXU
    oh = jnp.where(pos[:, None] == iota, jnp.float32(1.0),
                   jnp.float32(0.0))
    # HIGHEST precision: the default TPU matmul truncates f32 operands
    # to bf16, mangling ids and 16-bit magic limbs
    rows = jnp.dot(oh, static_ref[...],
                   preferred_element_type=jnp.float32,
                   precision=jax.lax.Precision.HIGHEST)   # (B, 8*S)
    rwrow = jnp.dot(oh, rw_ref[...],
                    preferred_element_type=jnp.float32,
                    precision=jax.lax.Precision.HIGHEST)  # (B, S)

    def operands(slab):
        sl = slice(slab * 128, (slab + 1) * 128)
        # f32 -> u32 is an unhandled Mosaic cast; go via i32 (limb
        # values < 2^16, so fptosi is exact)
        return (rows[:, sl].astype(_I32),
                rows[:, S + slab * 128:S + (slab + 1) * 128]
                .astype(_I32),
                [rows[:, (3 + j) * S + slab * 128:
                      (3 + j) * S + (slab + 1) * 128]
                 .astype(_I32).astype(_U32) for j in range(5)],
                rows[:, 2 * S + slab * 128:2 * S + (slab + 1) * 128]
                .astype(_I32))

    def rw_of(slab, first):
        sl = slice(slab * 128, (slab + 1) * 128)
        return jnp.sum(
            jnp.where(first, rwrow[:, sl].astype(_I32), _I32(0)),
            axis=1, dtype=_I32)

    _qh, _ql, _pos_l, wid, rwv = _column_over_slabs(
        x, S, tabs, r_leaf, operands, rw_of)
    _store_row(id_ref, r, wid)
    _store_row(bad_ref, r, _is_out_scalar(rwv, wid, x).astype(_I32))


def _pad_lanes(n: int) -> int:
    return max(128, -(-n // 128) * 128)


@functools.lru_cache(maxsize=None)
def _ln_tables_rows():
    """Gather-layout ln tables, one vreg (128 lanes) wide: rhlh rows
    (13, 128) for k in [0,127] + the k==128 row as python constants; the
    256-entry LL table split at row 128 into (6, 128) halves."""
    rhlh, ll = _ln_limb_operands_np()          # (129, 13), (256, 6) bytes
    rhlh = rhlh.astype(np.int32)
    ll = ll.astype(np.int32)
    rh_rows = np.ascontiguousarray(rhlh[:128].T)
    rh128 = tuple(int(v) for v in rhlh[128])
    ll_lo = np.ascontiguousarray(ll[:128].T)
    ll_hi = np.ascontiguousarray(ll[128:].T)
    return rh_rows, rh128, ll_lo, ll_hi


class PallasColumns:
    """Compiled winner-precompute for one FastRule on the TPU backend.

    Produces (host_win_ids, host_pos, leaf_win, leaf_bad) arrays shaped
    (R, N) for r in [0, R): drop-in data for fastpath._consume.
    """

    def __init__(self, fr, interpret: bool = False):
        from ceph_tpu.ops.straw2_u32 import magic_tables
        self.fr = fr
        self.interpret = interpret
        S = _pad_lanes(len(fr.root_ids))
        self.S_root = S
        ids = np.zeros(S, dtype=np.int32)
        ids[:len(fr.root_ids)] = fr.root_ids
        w = np.zeros(S, dtype=np.int64)
        w[:len(fr.root_w)] = fr.root_w
        limbs, off = magic_tables(w)
        self.root_ids = jnp.asarray(ids[None, :])
        self.root_wz = jnp.asarray((w <= 0).astype(np.int32)[None, :])
        self.root_magic = jnp.asarray(
            np.ascontiguousarray(limbs.T))            # (5, S)
        self.root_off = jnp.asarray(off.astype(np.int32)[None, :])
        rh, self.rh128, ll_lo, ll_hi = _ln_tables_rows()
        self.tabs = (jnp.asarray(rh), jnp.asarray(ll_lo),
                     jnp.asarray(ll_hi))

        if fr.leaf_ids is not None:
            H, S_l = fr.leaf_ids.shape
            Sp = _pad_lanes(S_l)
            Hp = _pad_lanes(H)      # the one-hot dot wants 128-multiples
            self.H = Hp
            self.S_leaf = Sp
            lids = np.zeros((Hp, Sp), dtype=np.int64)
            lids[:H, :S_l] = fr.leaf_ids
            lw = np.zeros((Hp, Sp), dtype=np.int64)
            lw[:H, :S_l] = fr.leaf_w
            l_limbs, l_off = magic_tables(lw)
            # packed static per-host fields, all exact in f32
            packed = np.concatenate([
                lids.astype(np.float32),
                (lw <= 0).astype(np.float32),
                l_off.astype(np.float32),
            ] + [l_limbs[..., j].astype(np.float32) for j in range(5)],
                axis=1)                                # (Hp, 8*Sp)
            self.leaf_static = jnp.asarray(packed)
            self.leaf_ids_np = lids                    # for reweight rows

    @staticmethod
    def _fullspec(shape):
        return pl.BlockSpec(shape,
                            lambda i, r: (jnp.int32(0), jnp.int32(0)),
                            memory_space=pltpu.VMEM)

    def root_columns(self, xs, reweight, R: int):
        """xs (N,) uint32 -> (pos, ids, bad) each (R, N) int32.
        bad is meaningful only for flat rules (devices at level one)."""
        n = xs.shape[0]
        S = self.S_root
        flat = self.fr.kind == "choose_flat"
        if flat:
            rw = jnp.asarray(reweight).astype(jnp.int32)[
                jnp.clip(self.root_ids[0], 0, len(reweight) - 1)][None, :]
        else:
            rw = jnp.zeros((1, S), dtype=jnp.int32)
        B = BLOCK
        grid = (n // B, R)     # r innermost: output blocks revisited
        outs = [jax.ShapeDtypeStruct((R, n), jnp.int32) for _ in range(3)]
        out_specs = [pl.BlockSpec((R, B), lambda i, r: (jnp.int32(0), i))
                     for _ in range(3)]
        fs = self._fullspec
        rh, ll_lo, ll_hi = self.tabs
        pos, ids, bad = pl.pallas_call(
            functools.partial(_root_kernel, flat=flat, S=S,
                              rh128=self.rh128),
            grid=grid,
            out_shape=outs,
            in_specs=[pl.BlockSpec((1, B), lambda i, r: (jnp.int32(0), i)),
                      fs((1, S)), fs((1, S)), fs((5, S)), fs((1, S)),
                      fs((1, S)), fs(rh.shape), fs(ll_lo.shape),
                      fs(ll_hi.shape)],
            out_specs=out_specs,
            interpret=self.interpret,
        )(xs[None, :], self.root_ids, self.root_wz, self.root_magic,
          self.root_off, rw, rh, ll_lo, ll_hi)
        return pos, ids, bad

    def leaf_columns(self, xs, root_pos, reweight, R: int):
        """root winner positions -> (leaf_id, leaf_bad) each (R, N)."""
        n = xs.shape[0]
        # reweight row per (host, slot): dynamic, built by XLA per call
        # (zero-padded slots never win the draw — wz masks them — so
        # their reweight value is irrelevant)
        rw_rows = jnp.asarray(reweight).astype(jnp.int32)[
            jnp.clip(jnp.asarray(self.leaf_ids_np), 0,
                     len(reweight) - 1)].astype(jnp.float32)
        B = BLOCK
        grid = (n // B, R)
        outs = [jax.ShapeDtypeStruct((R, n), jnp.int32) for _ in range(2)]
        out_specs = [pl.BlockSpec((R, B), lambda i, r: (jnp.int32(0), i))
                     for _ in range(2)]
        fs = self._fullspec
        rh, ll_lo, ll_hi = self.tabs
        lid, lbad = pl.pallas_call(
            functools.partial(_leaf_kernel, H=self.H, S=self.S_leaf,
                              vary_r=self.fr.vary_r,
                              rh128=self.rh128),
            grid=grid,
            out_shape=outs,
            in_specs=[pl.BlockSpec((1, B), lambda i, r: (jnp.int32(0), i)),
                      pl.BlockSpec((R, B), lambda i, r: (jnp.int32(0), i)),
                      fs(self.leaf_static.shape), fs(rw_rows.shape),
                      fs(rh.shape), fs(ll_lo.shape), fs(ll_hi.shape)],
            out_specs=out_specs,
            interpret=self.interpret,
        )(xs[None, :], root_pos, self.leaf_static, rw_rows,
          rh, ll_lo, ll_hi)
        return lid, lbad
