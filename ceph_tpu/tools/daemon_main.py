"""Daemon process entry point — the ceph-osd / ceph-mon `main()` analog.

Each daemon runs as its own OS process over the TCP messenger stack
(`python -m ceph_tpu.tools.daemon_main --role osd --id 2 ...`), the
reference's deployment model (src/ceph_osd.cc, src/ceph_mon.cc; spawned
by vstart.sh / qa/standalone/ceph-helpers.sh run_mon:437 run_osd:596).
The process stays up until SIGTERM/SIGINT; SIGKILL models crash-death
(the thrasher's kill mode) with the store surviving on disk.

The mon's listen address must be pre-agreed (it IS the cluster's
bootstrap identity), so `--addr` takes an explicit host:port; OSDs bind
an ephemeral port and advertise it through MOSDBoot as usual.
"""

from __future__ import annotations

import argparse
import signal
import sys
import os
import threading


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ceph-tpu-daemon")
    p.add_argument("--role", required=True,
                   choices=["mon", "osd", "mgr", "mds"])
    p.add_argument("--id", type=int, default=0)
    p.add_argument("--addr", default="127.0.0.1:0",
                   help="bind address (mons need an agreed host:port)")
    p.add_argument("--mon-host", default="",
                   help="comma-separated mon addresses")
    p.add_argument("--monmap", default="",
                   help="mon only: comma-separated monmap (all mons)")
    p.add_argument("--ms-type", default="async",
                   help="messenger stack; 'ici' selects the cross-"
                        "process ici-wire stack (TCP control plane + "
                        "device transfer data plane)")
    p.add_argument("--jax-cpu-devices", type=int, default=0,
                   help="force the cpu platform with N local devices "
                        "BEFORE jax initializes (the virtual-mesh test "
                        "tier; production uses the real backend)")
    p.add_argument("--store-type", default="filestore")
    p.add_argument("--store-path", default="")
    p.add_argument("--auth-key", default="")
    p.add_argument("--heartbeats", action="store_true")
    p.add_argument("--metadata-pool", type=int, default=1)
    p.add_argument("--data-pool", type=int, default=2)
    args = p.parse_args(argv)
    auth_key = args.auth_key.encode() if args.auth_key else None
    if args.jax_cpu_devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count="
            f"{args.jax_cpu_devices}").strip()
        import jax
        jax.config.update("jax_platforms", "cpu")
    ms_type = "ici-wire" if args.ms_type == "ici" else args.ms_type

    if args.role == "mon":
        from ceph_tpu.mon import Monitor
        d = Monitor(mon_id=args.id, ms_type="async", addr=args.addr,
                    store_path=args.store_path or None, auth_key=auth_key)
        d.init(monmap=[])
        monmap = (args.monmap or args.addr).split(",")
        if args.id >= len(monmap):
            print(f"error: --id {args.id} outside the {len(monmap)}-entry "
                  "monmap (pass --monmap with every mon's address)",
                  file=sys.stderr)
            return 2
        # substitute my own resolved addr (port 0 binds resolve late)
        monmap[args.id] = d.addr
        d.set_monmap(monmap)
    elif args.role == "osd":
        from ceph_tpu.osd.daemon import OSDDaemon
        d = OSDDaemon(args.id, args.mon_host, store_type=args.store_type,
                      store_path=args.store_path, ms_type=ms_type,
                      addr=args.addr, heartbeats=args.heartbeats,
                      auth_key=auth_key)
        d.init()
    elif args.role == "mgr":
        from ceph_tpu.mgr import MgrDaemon
        d = MgrDaemon(args.mon_host, ms_type="async", addr=args.addr,
                      auth_key=auth_key)
        d.init()
    else:
        from ceph_tpu.mds import MDSDaemon
        d = MDSDaemon(args.mon_host, args.metadata_pool, args.data_pool,
                      ms_type="async", addr=args.addr, auth_key=auth_key)
        d.init()

    stop = threading.Event()

    def on_signal(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)
    # readiness marker for the spawning harness
    sys.stdout.write(f"ready {args.role}.{args.id}\n")
    sys.stdout.flush()
    stop.wait()
    d.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
