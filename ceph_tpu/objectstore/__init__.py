"""Local persistence (reference layer 3: src/os/ ObjectStore + src/kv/).

ObjectStore is the OSD's storage engine contract: collections (one per PG)
hold objects with byte data and omap key/value attributes; all mutations ride
atomic compound Transactions (os/ObjectStore.h:306) applied via
queue_transactions (os/ObjectStore.h:1460).

Backends: MemStore (the unit-test fake, src/os/memstore/) and FileStore
(directory tree + write-ahead journal with crc'd frames and mount-time replay,
src/os/filestore/ structure).  KeyValueDB (src/kv/KeyValueDB.h) backs the mon
store, with MemDB and a compacting file-backed LogDB.
"""

from .transaction import Transaction
from .objectstore import ObjectStore, create as create_objectstore
from .kv import KeyValueDB, MemDB, LogDB

__all__ = ["Transaction", "ObjectStore", "create_objectstore",
           "KeyValueDB", "MemDB", "LogDB"]
