"""Wire feature bits (include/ceph_features.h + msg/Policy.h analog).

Every connection handshake exchanges (supported, required) 64-bit
vectors right after the transport names.  A peer that lacks bits I
REQUIRE — or that requires bits I lack — is rejected cleanly at
handshake with a reason, before any message flows: the rolling-upgrade
contract.  Optional capabilities degrade instead: both sides compute
``common = mine & theirs`` and consult it per capability (wire
compression is the first consumer — offered zlib degrades to none
against a peer without FEATURE_WIRE_COMPRESSION, like msgr2's
compression negotiation falling back).

Bits are append-only, never recycled (the reference retired bits by
parking them on CEPH_FEATURE_RESERVED rather than reuse).
"""

from __future__ import annotations

import struct

FEATURE_BASE = 1 << 0               # the v1 framing itself
FEATURE_WIRE_COMPRESSION = 1 << 1   # negotiated zlib frames
FEATURE_CEPHX_TICKETS = 1 << 2      # ticket-based cephx handshakes
FEATURE_INCREMENTAL_MAPS = 1 << 3   # MOSDMapMsg incremental payloads
FEATURE_PG_STATS_V2 = 1 << 4        # MMgrReport v2 per-PG records
FEATURE_EC_RMW_PIPELINE = 1 << 5    # pipelined EC overlapping writes
FEATURE_TRACE = 1 << 6              # frame-header trace extension
#: advertised ONLY by ici-wire messengers (not in SUPPORTED_FEATURES):
#: the peer can redeem staged-buffer tokens for bulk payloads
FEATURE_ICI_TOKENS = 1 << 7
FEATURE_TRACE_SPANS = 1 << 8        # v2 (trace_id, parent_span_id) ext
#: MOSDOp v4 / MOSDOpReply v2 dmclock QoS extension (tenant id +
#: (delta, rho) tags out, phase-served echo back).  The extension is
#: payload-versioned — old peers skip the trailing fields via the
#: length-prefixed section and simply schedule the op untagged — so
#: the bit advertises the capability rather than gating framing
FEATURE_QOS_TAGS = 1 << 9

#: everything this build speaks
SUPPORTED_FEATURES = (FEATURE_BASE | FEATURE_WIRE_COMPRESSION
                      | FEATURE_CEPHX_TICKETS | FEATURE_INCREMENTAL_MAPS
                      | FEATURE_PG_STATS_V2 | FEATURE_EC_RMW_PIPELINE
                      | FEATURE_TRACE | FEATURE_TRACE_SPANS
                      | FEATURE_QOS_TAGS)

#: handshake frame: (supported u64, required u64) — ONE definition
#: shared by both TCP stacks; they must parse each other byte-exact
FEAT_FRAME = struct.Struct("<QQ")

#: the floor every peer must speak (Policy::features_required baseline)
REQUIRED_DEFAULT = FEATURE_BASE

_NAMES = {
    FEATURE_BASE: "base",
    FEATURE_WIRE_COMPRESSION: "wire-compression",
    FEATURE_CEPHX_TICKETS: "cephx-tickets",
    FEATURE_INCREMENTAL_MAPS: "incremental-maps",
    FEATURE_PG_STATS_V2: "pg-stats-v2",
    FEATURE_EC_RMW_PIPELINE: "ec-rmw-pipeline",
    FEATURE_TRACE_SPANS: "trace-spans",
    FEATURE_QOS_TAGS: "qos-tags",
}


def feature_names(bits: int) -> str:
    """Human-readable bit list for handshake reject messages."""
    out = [name for bit, name in sorted(_NAMES.items()) if bits & bit]
    extra = bits & ~sum(_NAMES)
    if extra:
        out.append(f"unknown({extra:#x})")
    return ",".join(out) or "none"


def check_compat(peer: str, mine: int, my_required: int,
                 peer_supported: int, peer_required: int) -> int:
    """Validate mutual feature requirements; returns the common feature
    set or raises ConnectionError with the missing bits named."""
    missing = my_required & ~peer_supported
    if missing:
        raise ConnectionError(
            f"peer {peer} lacks required features "
            f"[{feature_names(missing)}]")
    lacking = peer_required & ~mine
    if lacking:
        raise ConnectionError(
            f"peer {peer} requires features I lack "
            f"[{feature_names(lacking)}]")
    return mine & peer_supported
