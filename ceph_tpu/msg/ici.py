"""ICI messenger stack — the device mesh as a transport behind the
Messenger API (SURVEY §5's mapping: the reference's pluggable
NetworkStack family {posix, rdma, dpdk} becomes {tcp, loopback, ICI},
with the entity-addressed Messenger surface unchanged).

Control frames (op headers, acks, maps, peering) ride the in-process
queue exactly like the loopback stack.  BULK PAYLOADS — EC shard chunks
in MOSDECSubOpWrite / MOSDECSubOpReadReply — are split out of the frame
and moved through the jax device mesh instead: the sender places the
chunk on a device and the frame carries only a token the receiver
redeems.  The OSD daemons are completely unaware: the stack IS the
abstraction, so the EC data path and the mesh data path are one code
path.

Two deployment shapes, one token protocol:

* IN-PROCESS (``IciMessenger``, loopback control plane): the sender
  places the chunk directly on the RECEIVER's device (jax.device_put —
  an ICI hop on real multi-chip hardware) and the receiver redeems from
  the shared registry.
* CROSS-PROCESS (``IciWireMessenger``, TCP control plane): each process
  runs a ``jax.experimental.transfer`` server over its local backend
  (the DCN/ICI point-to-point engine).  The sender stages the chunk on
  its OWN device and registers it for pull; the token carries the
  sender's transfer-server address, and the receiving process pulls the
  buffer device-to-device — the RDMA-READ shape of the reference's
  RDMAStack (src/msg/async/rdma/RDMAStack.h), with the transfer server
  standing where the RDMA verbs stack stands.  Peers that did not
  negotiate FEATURE_ICI_TOKENS get plain inline frames (TCP fallback).

Device assignment: osd.N <-> local_devices[N % n] — each OSD "owns" a
mesh position, so a k+m shard fan-out lands one chunk per device,
exactly the sharded-encode layout of parallel/sharded.py.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ceph_tpu.common import lockdep

from .loopback import LoopbackConnection, LoopbackMessenger
from .message import Message
from .messenger import EntityName

_MARKER = b"\x00ICI\x00"
#: cross-process token: marker + u64 token + u64 nbytes + u16 addr-len
#: + transfer-server address (the sender's pull endpoint)
_MARKER_X = b"\x00ICX\x00"


class IciTransport:
    """Process-wide staged-buffer registry (the 'wire' is device HBM).

    Lifecycle hardening: every staged buffer carries a deadline.  A
    buffer nobody redeems (its frame was dropped with a dying daemon)
    reaps after TTL seconds — device memory cannot leak to lost
    messages.  A REDEEMED buffer lingers for GRACE seconds before
    reaping, so a stateful connection resending its backlog (frames
    already delivered once) can redeem the same token again instead of
    erroring; after the grace the resent frame is dropped like any
    transport loss and the op-level retry repairs it."""

    _instance = None
    _lock = lockdep.make_lock("IciTransport::instance")

    #: seconds an unredeemed staged buffer survives (message lost)
    TTL = 30.0
    #: seconds a redeemed buffer stays redeemable (resend window)
    GRACE = 10.0

    def __init__(self):
        import jax
        self.jax = jax
        self.devices = jax.devices()
        self._bufs: dict[int, dict] = {}
        self._seq = 0
        self._reg_lock = lockdep.make_lock("IciTransport::registry")
        self.bytes_staged = 0      # cumulative
        self.transfers = 0         # cumulative
        #: cross-process pull endpoint (enable_wire)
        self._server = None
        self.server_addr = ""
        self._peer_conns: dict[str, object] = {}
        self.pulls = 0             # cumulative cross-process redeems
        #: (addr, token) -> pull time: a remote registration is ONE-
        #: shot, so a resent frame must fail fast as transport loss —
        #: re-pulling a consumed uuid could block the dispatch thread
        self._pulled: dict[tuple[str, int], float] = {}
        #: wire-mode pinned ledger: bytes of every await_pull
        #: registration this process ever posted on the CURRENT
        #: transfer server.  Registrations are one-shot and cannot be
        #: cancelled, so the ledger only resets when the server itself
        #: is recycled — unlike the _bufs registry, whose TTL reap
        #: drops lost frames out of the outstanding() gauge while
        #: their buffers stay pinned.  Registration times are monotone,
        #: so "any registration still inside TTL" (the recycle
        #: precondition) is just the NEWEST timestamp — a scalar, so
        #: the per-stage quiet-window check never scans anything.
        self._wire_newest_reg = 0.0
        self.wire_pinned_bytes = 0  # gauge: sum of the ledger
        self.wire_recycles = 0      # cumulative server recreations
        #: bumped on every server swap: a stage that registered on the
        #: old server sees the bump and re-registers instead of sending
        #: a token that died with it (stage never takes _wire_lock)
        self._wire_gen = 0
    # gauge: currently staged, unredeemed

    def outstanding(self) -> tuple[int, int]:
        """(buffers, bytes) staged and not yet redeemed (after a reap)."""
        now = time.monotonic()
        with self._reg_lock:
            self._reap_locked(now)
            live = [e for e in self._bufs.values()
                    if e["redeemed_at"] is None]
            return len(live), sum(e["nbytes"] for e in live)

    def _reap_locked(self, now: float) -> None:
        dead = [t for t, e in self._bufs.items()
                if (e["redeemed_at"] is not None
                    and now - e["redeemed_at"] > self.GRACE)
                or (e["redeemed_at"] is None
                    and now - e["staged_at"] > self.TTL)]
        for t in dead:
            del self._bufs[t]

    @classmethod
    def instance(cls) -> "IciTransport":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def device_for(self, name: EntityName):
        idx = name.id if name.type == "osd" else 0
        return self.devices[idx % len(self.devices)]

    # -- cross-process pull endpoint (RDMAStack analog) -----------------------

    _wire_lock = lockdep.make_lock("IciTransport::wire")

    def _start_server(self):
        """Bind a fresh transfer server (factored so tests and the
        recycle path share one construction)."""
        from jax.experimental import transfer
        dev = self.jax.local_devices()[0]
        # explicit socket transport addresses: the default local
        # bulk transport only moves bytes within one process —
        # peers in OTHER processes need the TCP bulk path
        return transfer.start_transfer_server(
            dev.client, "127.0.0.1:0",
            transport_addresses=["127.0.0.1:0"])

    def enable_wire(self) -> str:
        """Start this process's jax transfer server (idempotent).
        Raises on backends without the transfer engine — callers fall
        back to plain TCP frames then ("fall back to TCP only when no
        shared mesh exists")."""
        with self._wire_lock:   # created UNDER the lock: a concurrent
            # caller must never leak a second bound server
            if self._server is not None:
                return self.server_addr
            server = self._start_server()
            self._server = server
            self.server_addr = server.address()
            return self.server_addr

    @property
    def wire_enabled(self) -> bool:
        return self._server is not None

    #: wire mode: the transfer server's one-shot pull registrations
    #: cannot be cancelled, and a successful remote pull is invisible
    #: to the sender, so two limits govern staging.  WIRE_STAGE_CAP
    #: bounds the RECENT window (the TTL-reaped registry gauge: bytes
    #: staged and unredeemed in the last 30 s) — flow control that
    #: healthy traffic recovers from on its own.  The pinned LEDGER
    #: counts every registration since the server last started (lost
    #: frames stay pinned until the server dies, pulled ones the
    #: engine releases — the sender cannot tell which is which): past
    #: half of WIRE_STAGE_CAP the transport opportunistically recycles
    #: the server in any TTL-quiet window, and past WIRE_PIN_HARD_CAP
    #: it refuses outright until a recycle succeeds, so worst-case
    #: pinned memory under sustained frame loss is hard-bounded while
    #: loss-free traffic never stalls before the hard cap
    WIRE_STAGE_CAP = 256 << 20
    WIRE_PIN_HARD_CAP = 4 * WIRE_STAGE_CAP

    def can_stage(self, nbytes: int) -> bool:
        if self._server is None:
            return True      # in-process buffers reap on TTL
        _n, recent = self.outstanding()     # takes _reg_lock itself
        with self._reg_lock:
            recent_ok = recent + nbytes <= self.WIRE_STAGE_CAP
            ledger_ok = (self.wire_pinned_bytes + nbytes
                         <= self.WIRE_PIN_HARD_CAP)
            if (recent_ok and ledger_ok
                    and self.wire_pinned_bytes
                    <= self.WIRE_STAGE_CAP // 2):
                return True
        if self._recycle_wire_server(nbytes):
            return True
        return recent_ok and ledger_ok

    @staticmethod
    def _close_server(server) -> None:
        """Best-effort explicit teardown of a transfer server being
        discarded.  Dropping the Python reference is the documented
        release mechanism, but if the wrapper exposes an explicit
        shutdown, call it — relying on GC alone would let a retained
        reference keep the old server (and every pinned one-shot
        registration) alive while the ledger reports zero."""
        for m in ("shutdown", "close", "stop"):
            fn = getattr(server, m, None)
            if callable(fn):
                try:
                    fn()
                except Exception:
                    pass
                return

    def _recycle_wire_server(self, nbytes: int) -> bool:
        """The pinned ledger is past its threshold.  If every
        registration is past TTL — no in-flight frame can legitimately
        still redeem — replace the transfer server: dropping it
        releases EVERY orphaned one-shot registration in one stroke
        (the only release mechanism the transfer engine offers).
        Tokens still in the wild die as transport loss; op-level
        retries resend, exactly like any reaped in-process buffer.
        Returns whether staging may proceed."""
        now = time.monotonic()
        # cheap pre-check outside _wire_lock: while traffic is flowing
        # (no TTL-quiet window) a recycle cannot succeed, and every
        # sender past the opportunistic threshold would otherwise
        # serialize on _wire_lock here just to learn that
        with self._reg_lock:
            if (self._server is not None
                    and now - self._wire_newest_reg < self.TTL
                    and self.wire_pinned_bytes + nbytes
                    > self.WIRE_STAGE_CAP // 2):
                return False
        with self._wire_lock:
            with self._reg_lock:
                if self._server is None:
                    return True
                if (self.wire_pinned_bytes + nbytes
                        <= self.WIRE_STAGE_CAP // 2):
                    return True     # raced another recycler
                if now - self._wire_newest_reg < self.TTL:
                    return False    # a recent frame may still redeem
            try:
                server = self._start_server()
            except Exception:
                return False
            with self._reg_lock:
                if time.monotonic() - self._wire_newest_reg < self.TTL:
                    # a stage committed a registration while the new
                    # server was binding: its frame is in the wild on
                    # the CURRENT server, so the swap would lose it.
                    # Drop the fresh (registration-free) server instead
                    self._close_server(server)
                    return False
                old = self._server
                self._server = server
                self.server_addr = server.address()
                self._wire_gen += 1
                self._wire_newest_reg = 0.0
                self.wire_pinned_bytes = 0
                self.wire_recycles += 1
                # local registry entries pointed at the old server's
                # registrations: their buffers are released with it
                self._bufs.clear()
                # cached pull connections were created FROM the old
                # server (server.connect) and die with it — keeping
                # them would both break every later redemption from
                # those peers and keep the old server alive, defeating
                # the release this recycle exists for
                self._peer_conns.clear()
            self._close_server(old)
        from ceph_tpu.common.logging import dout
        dout("ms", 1, "ici: recycled transfer server (pinned ledger "
             "at cap, all registrations past TTL)")
        return True

    def stage(self, chunk: bytes, peer: EntityName) -> bytes:
        """Place the payload on a device; returns the token the frame
        carries instead of the bytes.

        In-process: the chunk lands on the PEER's device (the ICI hop
        happens at stage time).  Wire mode: it lands on a LOCAL device
        and is registered for pull — the hop happens when the receiving
        process redeems (RDMA READ)."""
        import jax.numpy as jnp
        arr = jnp.asarray(np.frombuffer(chunk, dtype=np.uint8))
        if self._server is not None:
            dev = self.jax.local_devices()[0]
        else:
            dev = self.device_for(peer)
        buf = self.jax.device_put(arr, dev)
        now = time.monotonic()
        with self._reg_lock:
            self._reap_locked(now)
            self._seq += 1
            token = self._seq
            entry = self._bufs[token] = {"buf": buf, "nbytes": len(chunk),
                                         "staged_at": now,
                                         "redeemed_at": None}
            self.bytes_staged += len(chunk)
            self.transfers += 1
        # wire mode: await_pull runs OUTSIDE the locks (senders never
        # serialize on each other, nor behind a recycle's server bind);
        # the ledger commit then re-checks the server generation — a
        # recycle that swapped the server in between killed the
        # registration just made, so re-register on the live server.
        # The recycle side re-checks the quiet window at swap time, so
        # a COMMITTED registration can never die in a swap.
        while True:
            with self._reg_lock:
                server, gen = self._server, self._wire_gen
            if server is None:
                return _MARKER + token.to_bytes(8, "little")
            try:
                server.await_pull(token, [buf])
            except Exception:
                # the snapshotted server may have been recycled (and
                # explicitly closed) under us — retry on the live one;
                # a failure on the CURRENT server is genuine
                with self._reg_lock:
                    if self._wire_gen != gen:
                        continue
                raise
            with self._reg_lock:
                if self._wire_gen != gen:
                    continue
                # a recycle between the registry insert above and the
                # gen snapshot wiped _bufs: re-assert the entry (same-
                # process redemption reads it) before publishing the
                # token.  Idempotent when no recycle intervened.
                self._bufs[token] = entry
                self._wire_newest_reg = time.monotonic()
                self.wire_pinned_bytes += len(chunk)
                addr = self.server_addr.encode()
            return (_MARKER_X + token.to_bytes(8, "little")
                    + len(chunk).to_bytes(8, "little")
                    + len(addr).to_bytes(2, "little") + addr)

    def redeem(self, blob: bytes) -> bytes:
        if blob.startswith(_MARKER_X):
            off = len(_MARKER_X)
            token = int.from_bytes(blob[off:off + 8], "little")
            nbytes = int.from_bytes(blob[off + 8:off + 16], "little")
            alen = int.from_bytes(blob[off + 16:off + 18], "little")
            addr = blob[off + 18:off + 18 + alen].decode()
            if addr != self.server_addr:
                return self._pull(addr, token, nbytes)
            # our own process staged it: the registry is authoritative
            # (and survives the one-shot pull registration)
        token = int.from_bytes(blob[len(_MARKER):len(_MARKER) + 8],
                               "little")
        now = time.monotonic()
        with self._reg_lock:
            self._reap_locked(now)
            entry = self._bufs.get(token)
            if entry is not None and entry["redeemed_at"] is None:
                entry["redeemed_at"] = now
            buf = entry["buf"] if entry is not None else None
        if buf is None:
            raise KeyError(f"ici token {token} expired or unknown")
        return np.asarray(buf).tobytes()

    def _pull(self, addr: str, token: int, nbytes: int) -> bytes:
        """Cross-process redemption: a device-to-device pull from the
        staging process's transfer server (one-shot, like an RDMA READ
        of a posted buffer; a resend that re-pulls is transport loss
        and the op-level retry repairs it)."""
        if self._server is None:
            raise KeyError(
                f"ici token from {addr}: no local transfer server")
        from jax.sharding import SingleDeviceSharding
        now = time.monotonic()
        with self._reg_lock:
            for k in [k for k, t in self._pulled.items()
                      if now - t > self.GRACE]:
                del self._pulled[k]
            if (addr, token) in self._pulled:
                raise KeyError(
                    f"ici token {token} from {addr} already pulled "
                    "(one-shot): resend is transport loss")
            self._pulled[(addr, token)] = now
            conn = self._peer_conns.get(addr)
        if conn is None:
            conn = self._server.connect(addr)
            with self._reg_lock:
                self._peer_conns.setdefault(addr, conn)
                conn = self._peer_conns[addr]
        spec = self.jax.ShapeDtypeStruct(
            (nbytes,), np.uint8,
            sharding=SingleDeviceSharding(self.jax.local_devices()[0]))
        try:
            out = conn.pull(token, [spec])
            data = np.asarray(out[0]).tobytes()
        except Exception as e:
            raise KeyError(f"ici pull {token} from {addr}: {e}")
        with self._reg_lock:
            self.pulls += 1
        return data

    @staticmethod
    def is_token(blob: bytes) -> bool:
        return blob.startswith(_MARKER) or blob.startswith(_MARKER_X)


def _bulk_field(msg: Message):
    """The bulk-payload attribute of data-plane messages, if any."""
    from ceph_tpu.messages.osd_msgs import (
        MOSDECSubOpReadReply, MOSDECSubOpWrite)
    from ceph_tpu.osd.daemon import MOSDPGPush
    if isinstance(msg, (MOSDECSubOpWrite, MOSDECSubOpReadReply)):
        return "chunk"
    if isinstance(msg, MOSDPGPush):
        return "data"
    return None


#: payloads below this stay in the control frame
BULK_THRESHOLD = 512


def maybe_stage(msg: Message, peer_name) -> None:
    """Replace a bulk payload with a staged-buffer token (idempotent;
    shared by the in-process and wire stacks)."""
    field = _bulk_field(msg)
    if field is None or peer_name is None:
        return
    payload = getattr(msg, field)
    if (len(payload) >= BULK_THRESHOLD
            and not IciTransport.is_token(payload)):
        t = IciTransport.instance()
        if t.can_stage(len(payload)):
            setattr(msg, field, t.stage(payload, peer_name))
        # else: past the wire staging cap — the payload rides the
        # frame inline (TCP fallback), bounding the unreapable
        # one-shot registrations a lossy peer can pin


def maybe_redeem(msg: Message) -> bool:
    """Swap a token back for its bytes before dispatch; False = the
    staged buffer is gone (transport loss — caller drops the frame and
    the op-level retry resends fresh bytes)."""
    field = _bulk_field(msg)
    if field is None:
        return True
    payload = getattr(msg, field)
    if not IciTransport.is_token(payload):
        return True
    try:
        setattr(msg, field, IciTransport.instance().redeem(payload))
        return True
    except KeyError:
        from ceph_tpu.common.logging import dout
        dout("ms", 5, "ici: dropping frame with expired token")
        return False


class IciConnection(LoopbackConnection):
    def send_message(self, msg: Message) -> None:
        maybe_stage(msg, self.peer_name)
        super().send_message(msg)


class IciMessenger(LoopbackMessenger):
    """Loopback control plane + device-mesh data plane."""

    def _make_connection(self, addr: str, peer_name):
        return IciConnection(self, addr, peer_name)

    def deliver(self, msg: Message) -> bool:
        if not maybe_redeem(msg):
            return True
        return super().deliver(msg)


def make_wire_messenger(name, **kw):
    """TCP control plane + transfer-server data plane: the CROSS-PROCESS
    ici stack (the reference's RDMAStack role — a real inter-node bulk
    transport behind the same Messenger API).  Reached via
    Messenger.create("ici-wire"); raises when the jax backend has no
    transfer engine, so the operator falls back to plain TCP explicitly
    rather than silently losing the data plane.

    A thin subclass of the event-driven TCP messenger: bulk payloads
    tokenize at the frame point for peers that negotiated
    FEATURE_ICI_TOKENS (event_tcp._frame), and tokens are redeemed —
    possibly a cross-process device pull — before dispatch."""
    from ceph_tpu.msg.event_tcp import EventMessenger
    from ceph_tpu.msg.features import FEATURE_ICI_TOKENS

    class IciWireMessenger(EventMessenger):
        ici_wire = True

        def deliver(self, msg: Message) -> bool:
            if not maybe_redeem(msg):
                return True
            return EventMessenger.deliver(self, msg)

    IciTransport.instance().enable_wire()   # raises if unsupported
    m = IciWireMessenger(name, **kw)
    m.local_features |= FEATURE_ICI_TOKENS
    return m
