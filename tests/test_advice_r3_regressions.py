"""Regression tests for the round-3 advisor findings: aborted-batch WAL
index corruption in BlueStore, unjournaled rbd snap_rollback diverging
mirrors, auth key material riding the broadcast OSDMap, SigV4 replay
freshness, and per-client intake backpressure head-of-line blocking.
"""

from __future__ import annotations

import hashlib
import http.client
import time
import urllib.parse

import pytest

from ceph_tpu.objectstore import Transaction, create_objectstore
from ceph_tpu.osd.map_codec import decode_osdmap, encode_osdmap
from ceph_tpu.osd.op_queue import ClassInfo, ShardedOpQueue
from ceph_tpu.osd.osdmap import OSDMap


# -- bluestore: aborted batch must not lose committed deferred writes -------

def test_bluestore_aborted_batch_keeps_committed_wal(tmp_path):
    path = str(tmp_path / "bs")
    st = create_objectstore("bluestore", path)
    st.mkfs_if_needed()
    st.mount()
    try:
        st.apply_transaction(Transaction().create_collection("c.0"))
        st.apply_transaction(
            Transaction().write("c.0", "o", 0, b"\xa5" * 8192))
        # sub-block overwrite -> committed deferred (WAL) entry
        st.apply_transaction(Transaction().write("c.0", "o", 64, b"wal!"))
        assert st.read("c.0", "o", 64, 4) == b"wal!"
        # a batch that first REMOVES the object (purging its WAL from the
        # in-memory index) and then fails on a later op: nothing commits,
        # so the committed deferred write must remain visible
        with pytest.raises(KeyError):
            st.apply_transaction(
                Transaction().remove("c.0", "o")
                .write("no-such-collection", "x", 0, b"y"))
        assert st.read("c.0", "o", 64, 4) == b"wal!"
        # a later clean write (which folds the WAL) must fold the real
        # entries, not an empty index — and survive remount
        st.apply_transaction(
            Transaction().write("c.0", "o", 0, b"\xbb" * 8192))
        assert st.read("c.0", "o", 0, 4) == b"\xbb" * 4
        st.umount()
        st2 = create_objectstore("bluestore", path)
        st2.mount()
        try:
            assert st2.read("c.0", "o", 0, 8192) == b"\xbb" * 8192
        finally:
            st2.umount()
            st = None
    finally:
        if st is not None:
            st.umount()


def test_bluestore_aborted_overwrite_batch_wal_survives(tmp_path):
    """Same invariant through the CLONE-overwrite purge path."""
    st = create_objectstore("bluestore", str(tmp_path / "bs"))
    st.mkfs_if_needed()
    st.mount()
    try:
        st.apply_transaction(Transaction().create_collection("c.0"))
        st.apply_transaction(
            Transaction().write("c.0", "src", 0, b"\x11" * 4096))
        st.apply_transaction(
            Transaction().write("c.0", "dst", 0, b"\x22" * 8192))
        st.apply_transaction(
            Transaction().write("c.0", "dst", 100, b"deferred-bytes"))
        with pytest.raises(KeyError):
            st.apply_transaction(
                Transaction().clone("c.0", "src", "dst")
                .write("no-such-collection", "x", 0, b"y"))
        # the aborted clone purged dst's WAL index entries; they must be
        # restored so dst still reads its deferred patch
        assert st.read("c.0", "dst", 100, 14) == b"deferred-bytes"
    finally:
        st.umount()


# -- map codec: auth keys never ride the broadcast map ----------------------

def test_osdmap_encode_strips_auth_by_default():
    m = OSDMap(epoch=3)
    m.auth_db = {"client.admin": "c2VjcmV0", "osd.0": "a2V5"}
    public = decode_osdmap(encode_osdmap(m))
    assert public.auth_db == {}
    internal = decode_osdmap(encode_osdmap(m, with_auth=True))
    assert internal.auth_db == m.auth_db


def test_cluster_client_map_carries_no_auth_keys():
    from ceph_tpu.tools.vstart import MiniCluster
    c = MiniCluster(n_osds=2).start()
    try:
        c.wait_for_osd_count(2)
        client = c.client()
        rc, out = client.mon_command(
            {"prefix": "auth get-or-create", "entity": "client.leak"})
        assert rc == 0
        # provisioned key is servable via auth get ...
        rc, out = client.mon_command(
            {"prefix": "auth print-key", "entity": "client.leak"})
        assert rc == 0 and out
        # ... but the subscriber-facing map must not carry the table
        deadline = time.time() + 10
        while client.osdmap.epoch == 0 and time.time() < deadline:
            time.sleep(0.05)
        assert client.osdmap.epoch > 0
        assert client.osdmap.auth_db == {}
    finally:
        c.stop()


# -- rgw: SigV4 freshness window -------------------------------------------

def _signed_request(server, method, path, amzdate, access, secret):
    from ceph_tpu.rgw_rest import sign_request
    host = server.addr
    payload_sha = hashlib.sha256(b"").hexdigest()
    headers = {"Host": host, "x-amz-date": amzdate,
               "x-amz-content-sha256": payload_sha}
    parsed = urllib.parse.urlsplit(path)
    auth = sign_request(method, parsed.path, parsed.query,
                        {"host": host, "x-amz-date": amzdate,
                         "x-amz-content-sha256": payload_sha},
                        payload_sha, access, secret)
    headers["Authorization"] = auth
    h, p = host.rsplit(":", 1)
    conn = http.client.HTTPConnection(h, int(p), timeout=10)
    conn.request(method, path, b"", headers)
    resp = conn.getresponse()
    body = resp.read()
    conn.close()
    return resp.status, body


@pytest.fixture()
def rgw_cluster():
    from ceph_tpu.rgw_rest import RgwRestServer
    from ceph_tpu.tools.vstart import MiniCluster
    c = MiniCluster(n_osds=2).start()
    try:
        c.wait_for_osd_count(2)
        client = c.client()
        pool = c.create_pool(client, pg_num=8, size=2)
        io = client.open_ioctx(pool)
        srv = RgwRestServer(io).start()
        try:
            yield srv
        finally:
            srv.shutdown()
    finally:
        c.stop()


def test_sigv4_stale_date_rejected(rgw_cluster):
    srv = rgw_cluster
    srv.add_key("AKTEST", "sekrit")
    fresh = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    status, _ = _signed_request(srv, "PUT", "/tb", fresh,
                                "AKTEST", "sekrit")
    assert status == 200
    stale = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime(time.time() - 3600))
    status, body = _signed_request(srv, "GET", "/tb", stale,
                                   "AKTEST", "sekrit")
    assert status == 403
    assert b"RequestTimeTooSkewed" in body
    # injectable clock: the same stale request passes on a server whose
    # clock sits inside the window (proves the check uses srv.clock)
    srv.clock = lambda: time.time() - 3600
    status, _ = _signed_request(srv, "GET", "/tb", stale,
                                "AKTEST", "sekrit")
    assert status == 200
    srv.clock = time.time


# -- op queue: per-client cap must not block other clients ------------------

def test_client_backlog_cap_is_per_client():
    import threading
    release = threading.Event()

    def handler(klass, item):
        release.wait(timeout=10)

    q = ShardedOpQueue(handler, n_shards=1,
                       client_template=ClassInfo(weight=10.0),
                       max_client_backlog=4)
    try:
        # client.1 saturates its cap (1 in-flight in the worker + queue)
        for i in range(8):
            q.enqueue("pg0", "client.1", f"a{i}")
        assert q.enqueue("pg0", "client.1", "overflow") is False
        # a DIFFERENT client must still get through
        assert q.enqueue("pg0", "client.2", "b0") is True
        # untagged aggregate intake still enforces the aggregate cap
        assert q.enqueue("pg0", "client", "c0") is False
    finally:
        release.set()
        q.shutdown()
