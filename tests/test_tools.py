"""Tools tier: rados bench (obj_bencher), dencoder round-trip + corpus,
objectstore-tool PG export/import, kvstore-tool, monstore-tool —
src/tools/ analogs driven end-to-end."""

import json
import os
import time

import pytest

from ceph_tpu.tools.vstart import MiniCluster


@pytest.fixture
def cluster():
    c = MiniCluster(n_osds=3, ms_type="loopback").start()
    c.wait_for_osd_count(3)
    yield c
    c.stop()


# -- rados bench -------------------------------------------------------------

def test_rados_bench_write_seq_rand(cluster):
    from ceph_tpu.tools.rados_bench import ObjBencher
    client = cluster.client(timeout=20.0)
    pool = cluster.create_pool(client, pg_num=8, size=2)
    io = client.open_ioctx(pool)
    b = ObjBencher(io, obj_size=4096, concurrent=4, run_name="tbench")
    w = b.write_bench(1.0)
    assert w["mode"] == "write"
    assert w["errors"] == 0
    n = w["total_writes_or_reads"]
    assert n > 0 and w["bandwidth_mb_s"] > 0
    s = b.seq_read_bench(0.5, n)
    assert s["errors"] == 0 and s["total_writes_or_reads"] > 0
    r = b.rand_read_bench(0.5, n)
    assert r["errors"] == 0 and r["total_writes_or_reads"] > 0


def test_aio_completions(cluster):
    client = cluster.client(timeout=20.0)
    pool = cluster.create_pool(client, pg_num=8, size=2)
    io = client.open_ioctx(pool)
    cs = [io.aio_write_full(f"aio{i}", f"payload-{i}".encode())
          for i in range(8)]
    for c in cs:
        assert c.wait_for_complete(10.0)
        assert c.get_return_value() == 0
    rs = [io.aio_read(f"aio{i}") for i in range(8)]
    for i, c in enumerate(rs):
        assert c.wait_for_complete(10.0)
        assert c.data == f"payload-{i}".encode()


# -- dencoder ----------------------------------------------------------------

def test_dencoder_roundtrip_all():
    from ceph_tpu.tools import dencoder
    n = dencoder.roundtrip_all()
    assert n >= 25  # the catalog is substantial
    assert dencoder.struct_checks() == ["OSDMap", "Transaction"]


def test_dencoder_corpus(tmp_path):
    from ceph_tpu.tools import dencoder
    d = str(tmp_path / "corpus")
    n = dencoder.create_corpus(d)
    assert n >= 25
    assert dencoder.check_corpus(d) == []
    # corrupt one archived blob: the check must name it
    meta = json.load(open(os.path.join(d, "corpus.json")))
    victim = sorted(meta)[0]
    with open(os.path.join(d, f"{victim}.bin"), "r+b") as f:
        # first payload byte (after the 20-byte header) — covered by the
        # crc, so the archived blob must stop decoding
        f.seek(20)
        b = f.read(1)
        f.seek(20)
        f.write(bytes([b[0] ^ 0xFF]))
    failures = dencoder.check_corpus(d)
    assert failures and victim in failures[0]


def test_dencoder_committed_corpus():
    """The committed corpus pins the wire format across rounds."""
    from ceph_tpu.tools import dencoder
    d = os.path.join(os.path.dirname(__file__), "golden", "dencoder")
    assert os.path.isdir(d), "committed dencoder corpus missing"
    assert dencoder.check_corpus(d) == []


# -- objectstore tool --------------------------------------------------------

def test_objectstore_tool_export_import(tmp_path):
    from ceph_tpu.tools import objectstore_tool as ot
    c = MiniCluster(n_osds=2, ms_type="loopback", store_type="filestore",
                    base_path=str(tmp_path)).start()
    try:
        c.wait_for_osd_count(2)
        client = c.client(timeout=20.0)
        pool = c.create_pool(client, pg_num=2, size=2)
        io = client.open_ioctx(pool)
        for i in range(6):
            io.write_full(f"x{i}", f"surgery-{i}".encode() * 10)
            io.set_omap(f"x{i}", {"k": f"v{i}".encode()})
        time.sleep(0.3)
    finally:
        c.stop()

    # offline: open osd.0's store
    from ceph_tpu.objectstore import create_objectstore
    store = create_objectstore("filestore", str(tmp_path / "osd.0"))
    store.mount()
    try:
        listing = ot.op_list(store)
        pg_cids = [cid for cid in listing if "." in cid
                   and any(o for o in listing[cid]
                           if not o.startswith("_pgmeta"))]
        assert pg_cids, f"no populated pg collections in {list(listing)}"
        cid = pg_cids[0]
        p, s = cid.split(".")
        pgid = (int(p), int(s))
        info = ot.op_info(store, pgid)
        assert info["pgid"] == [pgid[0], pgid[1]]
        log = ot.op_log(store, pgid)
        assert log, "pg log empty"
        exp = str(tmp_path / "pg.export")
        res = ot.op_export(store, pgid, exp)
        assert res["bytes"] > 0
    finally:
        store.umount()

    # import into a brand-new store and verify object payloads survive
    dest = create_objectstore("filestore", str(tmp_path / "rebuilt"))
    dest.mkfs_if_needed()
    dest.mount()
    try:
        res = ot.op_import(dest, exp)
        assert res["pgid"] == cid
        objs = [o for o in dest.list_objects(cid)
                if not o.startswith("_pgmeta")]
        assert sorted(objs) == sorted(
            o for o in ot.op_list(dest)[cid] if not o.startswith("_pgmeta"))
        for o in objs:
            base = o.split(":", 1)[0]
            i = int(base[1:])
            assert dest.read(cid, o) == f"surgery-{i}".encode() * 10
        # double import refuses
        with pytest.raises(ValueError):
            ot.op_import(dest, exp)
    finally:
        dest.umount()


# -- kvstore / monstore tools ------------------------------------------------

def test_kvstore_tool_roundtrip(tmp_path, capsys):
    from ceph_tpu.tools import kvstore_tool
    path = str(tmp_path / "kv.log")
    assert kvstore_tool.main([path, "set", "p", "k1", b"hello".hex()]) == 0
    assert kvstore_tool.main([path, "get", "p", "k1"]) == 0
    out = capsys.readouterr().out.strip().splitlines()[-1]
    assert bytes.fromhex(out) == b"hello"
    assert kvstore_tool.main([path, "list", "p"]) == 0
    rows = json.loads(capsys.readouterr().out)
    assert rows == [{"prefix": "p", "key": "k1", "size": 5}]
    assert kvstore_tool.main([path, "compact"]) == 0
    assert kvstore_tool.main([path, "rm", "p", "k1"]) == 0
    assert kvstore_tool.main([path, "get", "p", "k1"]) == 1


def test_monstore_tool_dump_and_osdmap(tmp_path):
    from ceph_tpu.tools import monstore_tool
    from ceph_tpu.objectstore.kv import LogDB
    # build a real mon store by running a disk-backed mon
    c = MiniCluster(n_osds=2, ms_type="loopback",
                    base_path=str(tmp_path)).start()
    try:
        c.wait_for_osd_count(2)
        client = c.client(timeout=20.0)
        c.create_pool(client, pg_num=4, size=2)
    finally:
        c.stop()
    db = LogDB(str(tmp_path / "mon.0"))
    db.open()
    try:
        d = monstore_tool.dump(db)
        assert d["last_committed"] >= 3
        m = monstore_tool.get_osdmap(db)
        assert m["epoch"] == d["last_committed"] + 0 or m["epoch"] > 0
        assert m["up_osds"] == [0, 1]
        assert m["pools"], "pool creation not in committed map"
        # disaster recovery: truncate one version
        r = monstore_tool.rewrite_last_committed(db, d["last_committed"] - 1)
        assert r["dropped"] == 1
        assert monstore_tool.dump(db)["last_committed"] == \
            d["last_committed"] - 1
    finally:
        db.close()


def test_objectstore_bench(tmp_path):
    """fio-ObjectStore-engine analog: all phases run clean on every
    backend."""
    from ceph_tpu.tools.objectstore_bench import run
    from ceph_tpu.objectstore import create_objectstore
    for st_type in ("memstore", "bluestore"):
        store = create_objectstore(st_type, str(tmp_path / st_type))
        store.mkfs_if_needed()
        store.mount()
        try:
            res = run(store, n_objects=64, obj_size=4096, n_threads=2)
            for phase in ("write", "read", "overwrite", "delete"):
                assert res[phase]["errors"] == 0, (st_type, phase)
                assert res[phase]["iops"] > 0
        finally:
            store.umount()
