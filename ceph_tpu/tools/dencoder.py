"""ceph-dencoder analog: encode/decode round-trip and corpus checking
for every registered wire type.

The reference's ceph-dencoder (src/tools/ceph-dencoder/) lists each
encodable type, round-trips sample instances, and verifies archived
encodings from older versions still decode — the guard that keeps the
wire format compatible forever.  Here:

  * `list_types()`   — every registered Message type + core structs
  * `roundtrip(t)`   — encode(sample) -> decode -> re-encode, bytes equal
  * `create_corpus(dir)` / `check_corpus(dir)` — archive sample
    encodings with the head version at creation time; a check decodes
    every archived blob with current code (must succeed even across
    version bumps) and byte-compares the re-encode only when the type's
    head version is unchanged.

Usage: python -m ceph_tpu.tools.dencoder list|roundtrip|create|check [dir]
"""

from __future__ import annotations

import json
import os

from ceph_tpu.msg.encoding import Decoder, Encoder
from ceph_tpu.msg.message import _REGISTRY, Message


def _import_catalog() -> None:
    """Messages register on import; pull in every module that defines
    wire types (the dlopen-all analog)."""
    import ceph_tpu.messages  # noqa: F401
    import ceph_tpu.messages.peering_msgs  # noqa: F401
    import ceph_tpu.mon.monitor  # noqa: F401
    import ceph_tpu.mon.elector  # noqa: F401
    import ceph_tpu.mon.paxos  # noqa: F401
    import ceph_tpu.osd.daemon  # noqa: F401
    import ceph_tpu.mgr  # noqa: F401


def _sample(cls) -> Message:
    """Default-constructed sample instance (the reference generates
    samples via each type's generate_test_instances)."""
    return cls()


def list_types() -> list[dict]:
    _import_catalog()
    out = []
    for t, cls in sorted(_REGISTRY.items()):
        out.append({"type": t, "name": cls.__name__,
                    "head_version": cls.HEAD_VERSION,
                    "compat_version": cls.COMPAT_VERSION})
    return out


def roundtrip(cls) -> None:
    """encode -> decode -> re-encode must reproduce identical bytes."""
    msg = _sample(cls)
    wire = msg.encode()
    back = Message.decode(wire)
    wire2 = back.encode()
    if wire != wire2:
        raise AssertionError(
            f"{cls.__name__}: re-encode differs "
            f"({len(wire)} vs {len(wire2)} bytes)")


def roundtrip_all() -> int:
    _import_catalog()
    for _t, cls in sorted(_REGISTRY.items()):
        roundtrip(cls)
    return len(_REGISTRY)


# -- struct (non-message) round trips ----------------------------------------

def struct_checks() -> list[str]:
    """Core struct codecs: OSDMap/CrushMap survive encode/decode with
    identical bytes (map_codec), like dencoder's non-message types."""
    from ceph_tpu.crush import build_two_level_map
    from ceph_tpu.osd.map_codec import decode_osdmap, encode_osdmap
    from ceph_tpu.osd.osdmap import OSDMap, PGPool

    checked = []
    m = OSDMap()
    m.set_max_osd(4)
    for o in range(4):
        m.mark_up(o)
    crush, _root, rid = build_two_level_map(2, 2)
    m.crush = crush
    m.pools[1] = PGPool(pool_id=1, pg_num=8, crush_rule=rid)
    blob = encode_osdmap(m)
    blob2 = encode_osdmap(decode_osdmap(blob))
    assert blob == blob2, "OSDMap re-encode differs"
    checked.append("OSDMap")

    from ceph_tpu.objectstore.transaction import Transaction
    t = (Transaction().create_collection("1.0")
         .write("1.0", "o", 0, b"x" * 32).setattr("1.0", "o", "_v", b"1"))
    tb = t.encode()
    tb2 = Transaction.decode(tb).encode()
    assert tb == tb2, "Transaction re-encode differs"
    checked.append("Transaction")
    return checked


# -- corpus ------------------------------------------------------------------

def create_corpus(path: str) -> int:
    _import_catalog()
    os.makedirs(path, exist_ok=True)
    meta = {}
    for t, cls in sorted(_REGISTRY.items()):
        wire = _sample(cls).encode()
        with open(os.path.join(path, f"{cls.__name__}.bin"), "wb") as f:
            f.write(wire)
        meta[cls.__name__] = {"type": t, "head_version": cls.HEAD_VERSION}
    with open(os.path.join(path, "corpus.json"), "w") as f:
        json.dump(meta, f, indent=1, sort_keys=True)
    return len(meta)


def check_corpus(path: str) -> list[str]:
    """Every archived blob must decode with current code; byte-stable
    re-encode is enforced only while the head version is unchanged."""
    _import_catalog()
    with open(os.path.join(path, "corpus.json")) as f:
        meta = json.load(f)
    failures = []
    by_name = {cls.__name__: cls for cls in _REGISTRY.values()}
    for name, info in sorted(meta.items()):
        cls = by_name.get(name)
        if cls is None:
            failures.append(f"{name}: type no longer registered")
            continue
        with open(os.path.join(path, f"{name}.bin"), "rb") as f:
            wire = f.read()
        try:
            back = Message.decode(wire)
        except Exception as e:
            failures.append(f"{name}: archived encoding no longer "
                            f"decodes: {e}")
            continue
        if (cls.HEAD_VERSION == info["head_version"]
                and back.encode() != wire):
            failures.append(f"{name}: re-encode of archived bytes differs "
                            f"at unchanged head version")
    return failures


def main(argv=None) -> int:
    import sys
    argv = sys.argv[1:] if argv is None else argv
    cmd = argv[0] if argv else "list"
    if cmd == "list":
        for row in list_types():
            print("{type:4d} {name:28s} v{head_version}/"
                  "c{compat_version}".format(**row))
        return 0
    if cmd == "roundtrip":
        n = roundtrip_all()
        checked = struct_checks()
        print(f"{n} message types + {len(checked)} structs round-trip OK")
        return 0
    if cmd == "create":
        n = create_corpus(argv[1])
        print(f"archived {n} sample encodings")
        return 0
    if cmd == "check":
        failures = check_corpus(argv[1])
        for f in failures:
            print(f"FAIL {f}")
        print(f"{'FAILED' if failures else 'OK'}")
        return 1 if failures else 0
    print(__doc__)
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
