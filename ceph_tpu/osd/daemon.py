"""The OSD daemon (src/osd/OSD.{h,cc} + PrimaryLogPG + backends, condensed).

Structure mirrors the reference data path (SURVEY.md §3.1/§3.3):

  client MOSDOp -> primary:  replicated: local txn + MOSDRepOp fan-out, ack on
                             all commits (ReplicatedBackend::submit_transaction)
                             erasure: batched GF(2^8) encode -> per-shard
                             MOSDECSubOpWrite fan-out (ECBackend::start_rmw ->
                             ECUtil::encode; here the encode is one device call)
  reads:                     replicated: local; erasure: shard fan-in
                             (MOSDECSubOpRead) + recovery decode
  heartbeats:                periodic MOSDPing to up peers; missed grace ->
                             MOSDFailure to the mon (OSD::heartbeat_check)
  map handling:              MOSDMapMsg -> activate PGs (collections), simple
                             pull-based recovery for replicated objects

Erasure objects store one chunk per shard-OSD as "<oid>:<shard>" with the
stripe geometry in attrs; any k chunks reconstruct via the recovery-matrix
kernel, exactly the ECBackend read path.
"""

from __future__ import annotations

import threading
import time

from ceph_tpu.common.context import CephTpuContext
from ceph_tpu.common.logging import dout
from ceph_tpu.common.perf_counters import PerfCountersBuilder
from ceph_tpu.ec import registry_instance
from ceph_tpu.messages import (
    MOSDECSubOpRead, MOSDECSubOpReadReply, MOSDECSubOpWrite,
    MOSDECSubOpWriteReply, MOSDFailure, MOSDMapMsg, MOSDOp, MOSDOpReply,
    MOSDPing, MOSDRepOp, MOSDRepOpReply)
from ceph_tpu.messages.osd_msgs import (
    OP_DELETE, OP_OMAP_GET, OP_OMAP_SET, OP_READ, OP_STAT, OP_WRITE,
    OP_WRITEFULL, OSDOpField)
from ceph_tpu.mon.monitor import MMonSubscribe, MOSDBoot
from ceph_tpu.msg.encoding import Decoder, Encoder
from ceph_tpu.msg.message import Message, register_message
from ceph_tpu.msg.messenger import (
    ConnectionPolicy, Dispatcher, EntityName, Messenger)
from ceph_tpu.objectstore import Transaction, create_objectstore
from ceph_tpu.osd.map_codec import decode_osdmap
from ceph_tpu.osd.osdmap import CEPH_NOSD, OSDMap, pg_to_pgid

import numpy as np


@register_message
class MOSDPGScan(Message):
    """primary -> replica: list your objects for this PG (recovery scan)."""

    TYPE = 114

    def __init__(self, pgid: tuple[int, int] = (0, 0), from_osd: int = 0):
        super().__init__()
        self.pgid = pgid
        self.from_osd = from_osd

    def encode_payload(self, enc: Encoder):
        enc.versioned(1, 1, lambda e: (e.s64(self.pgid[0]),
                                       e.u32(self.pgid[1]),
                                       e.s32(self.from_osd)))

    def decode_payload(self, dec: Decoder, version):
        def body(d, v):
            self.pgid = (d.s64(), d.u32())
            self.from_osd = d.s32()
        dec.versioned(1, body)


@register_message
class MOSDPGScanReply(Message):
    TYPE = 115

    def __init__(self, pgid: tuple[int, int] = (0, 0), from_osd: int = 0,
                 objects: list[str] | None = None):
        super().__init__()
        self.pgid = pgid
        self.from_osd = from_osd
        self.objects = objects or []

    def encode_payload(self, enc: Encoder):
        enc.versioned(1, 1, lambda e: (
            e.s64(self.pgid[0]), e.u32(self.pgid[1]), e.s32(self.from_osd),
            e.list(self.objects, lambda e2, o: e2.str(o))))

    def decode_payload(self, dec: Decoder, version):
        def body(d, v):
            self.pgid = (d.s64(), d.u32())
            self.from_osd = d.s32()
            self.objects = d.list(lambda d2: d2.str())
        dec.versioned(1, body)


@register_message
class MOSDPGPull(Message):
    """primary -> holder: send me this object (recovery pull)."""

    TYPE = 116

    def __init__(self, pgid: tuple[int, int] = (0, 0), oid: str = "",
                 from_osd: int = 0):
        super().__init__()
        self.pgid = pgid
        self.oid = oid
        self.from_osd = from_osd

    def encode_payload(self, enc: Encoder):
        enc.versioned(1, 1, lambda e: (e.s64(self.pgid[0]),
                                       e.u32(self.pgid[1]),
                                       e.str(self.oid), e.s32(self.from_osd)))

    def decode_payload(self, dec: Decoder, version):
        def body(d, v):
            self.pgid = (d.s64(), d.u32())
            self.oid = d.str()
            self.from_osd = d.s32()
        dec.versioned(1, body)


@register_message
class MOSDPGPush(Message):
    """holder -> primary: object payload (recovery push; MOSDPGPush analog)."""

    TYPE = 117

    def __init__(self, pgid: tuple[int, int] = (0, 0), oid: str = "",
                 data: bytes = b"", omap: dict | None = None,
                 attrs: dict | None = None):
        super().__init__()
        self.pgid = pgid
        self.oid = oid
        self.data = data
        self.omap = omap or {}
        self.attrs = attrs or {}

    def encode_payload(self, enc: Encoder):
        enc.versioned(1, 1, lambda e: (
            e.s64(self.pgid[0]), e.u32(self.pgid[1]), e.str(self.oid),
            e.bytes(self.data),
            e.map(self.omap, lambda e2, k: e2.str(k),
                  lambda e2, v: e2.bytes(v)),
            e.map(self.attrs, lambda e2, k: e2.str(k),
                  lambda e2, v: e2.bytes(v))))

    def decode_payload(self, dec: Decoder, version):
        def body(d, v):
            self.pgid = (d.s64(), d.u32())
            self.oid = d.str()
            self.data = d.bytes()
            self.omap = d.map(lambda d2: d2.str(), lambda d2: d2.bytes())
            self.attrs = d.map(lambda d2: d2.str(), lambda d2: d2.bytes())
        dec.versioned(1, body)


class _InFlight:
    """One client op waiting on replica/shard acks (in-flight repop)."""

    def __init__(self, msg: MOSDOp, waiting: set[int], reply: MOSDOpReply):
        self.msg = msg
        self.waiting = waiting
        self.reply = reply


class OSDDaemon(Dispatcher):
    def __init__(self, osd_id: int, mon_addr: str,
                 ctx: CephTpuContext | None = None,
                 store_type: str = "memstore", store_path: str = "",
                 ms_type: str = "async", addr: str = "127.0.0.1:0",
                 heartbeats: bool = True):
        self.osd_id = osd_id
        self.whoami = EntityName("osd", osd_id)
        self.ctx = ctx or CephTpuContext(f"osd.{osd_id}")
        self.mon_addr = mon_addr
        self.store = create_objectstore(store_type, store_path)
        self.osdmap = OSDMap()
        self._lock = threading.RLock()
        self._in_flight: dict[tuple[int, int], _InFlight] = {}
        #: reqid -> {"shards": {shard: bytes}, "need": int, ...} EC reads
        self._ec_reads: dict[tuple[int, int], dict] = {}
        self._codecs: dict[int, object] = {}
        self._osd_addr_cache: dict[int, str] = {}
        self._hb_last: dict[int, float] = {}
        self._hb_timer: threading.Timer | None = None
        self._heartbeats = heartbeats
        self._stop = False

        self.msgr = Messenger.create(self.whoami, ms_type)
        self.msgr.set_policy("client", ConnectionPolicy.lossy_client())
        self.msgr.set_policy("osd", ConnectionPolicy.stateful_peer())
        self.msgr.set_policy("mon", ConnectionPolicy.stateful_peer())
        self.msgr.add_dispatcher_tail(self)
        self._addr = addr

        self.perf = (PerfCountersBuilder(f"osd.{osd_id}")
                     .add_u64("op_w").add_u64("op_r").add_u64("op_rep")
                     .add_u64("ec_encode_stripes").add_u64("recovery_pulls")
                     .add_time_avg("op_w_latency")
                     .create_perf_counters())
        self.ctx.perf.add(self.perf)
        self.ctx.admin.register_command(
            "dump_ops_in_flight",
            lambda **kw: {"num": len(self._in_flight)}, "in-flight ops")
        self.ctx.admin.register_command(
            "osd map epoch", lambda **kw: {"epoch": self.osdmap.epoch},
            "current map epoch")

    # -- lifecycle (OSD::init, ceph_osd.cc main) ------------------------------

    def init(self) -> None:
        self.store.mkfs_if_needed()
        self.store.mount()
        self.msgr.bind(self._addr)
        self.msgr.start()
        mon = self.msgr.connect_to(self.mon_addr, EntityName("mon", 0))
        mon.send_message(MMonSubscribe(name=str(self.whoami),
                                       addr=self.msgr.my_addr))
        mon.send_message(MOSDBoot(osd_id=self.osd_id,
                                  addr=self.msgr.my_addr))
        if self._heartbeats:
            self._schedule_heartbeat()

    def shutdown(self) -> None:
        self._stop = True
        if self._hb_timer:
            self._hb_timer.cancel()
        self.msgr.shutdown()
        self.store.umount()

    # -- map handling ---------------------------------------------------------

    def _handle_map(self, msg: MOSDMapMsg) -> None:
        newmap = decode_osdmap(msg.map_blob)
        with self._lock:
            if newmap.epoch <= self.osdmap.epoch:
                return
            oldmap = self.osdmap
            self.osdmap = newmap
            self._codecs.clear()
        del oldmap
        dout("osd", 5, "osd.%d got map epoch %d", self.osd_id, newmap.epoch)
        my_pgs = self._my_pgs()
        self._activate_pgs(my_pgs)
        self._maybe_recover(my_pgs)

    def _my_pgs(self) -> list[tuple[int, int, list[int], int]]:
        """(pool, pg, up, primary) for PGs whose up set includes me."""
        out = []
        m = self.osdmap
        for pool_id, pool in m.pools.items():
            for pg in range(pool.pg_num):
                up, primary, _a, _ap = m.pg_to_up_acting_osds(pool_id, pg)
                if self.osd_id in up:
                    out.append((pool_id, pg, up, primary))
        return out

    def _activate_pgs(self, my_pgs) -> None:
        t = Transaction()
        existing = set(self.store.list_collections())
        for pool_id, pg, _up, _p in my_pgs:
            cid = f"{pool_id}.{pg}"
            if cid not in existing:
                t.create_collection(cid)
        if len(t):
            self.store.apply_transaction(t)

    # -- recovery (pull-based backfill-lite) ----------------------------------

    def _maybe_recover(self, my_pgs) -> None:
        """Where I'm now primary, scan peers and pull objects I miss."""
        for pool_id, pg, up, primary in my_pgs:
            if primary != self.osd_id:
                continue
            peers = [o for o in up if o != self.osd_id and o != CEPH_NOSD]
            for peer in peers:
                con = self._osd_con(peer)
                if con:
                    con.send_message(MOSDPGScan(pgid=(pool_id, pg),
                                                from_osd=self.osd_id))

    def _handle_scan(self, msg: MOSDPGScan) -> None:
        cid = f"{msg.pgid[0]}.{msg.pgid[1]}"
        try:
            objs = self.store.list_objects(cid)
        except KeyError:
            objs = []
        con = self._osd_con(msg.from_osd)
        if con:
            con.send_message(MOSDPGScanReply(
                pgid=msg.pgid, from_osd=self.osd_id, objects=objs))

    def _handle_scan_reply(self, msg: MOSDPGScanReply) -> None:
        cid = f"{msg.pgid[0]}.{msg.pgid[1]}"
        try:
            mine = set(self.store.list_objects(cid))
        except KeyError:
            mine = set()
        missing = [o for o in msg.objects if o not in mine]
        con = self._osd_con(msg.from_osd)
        if con is None:
            return
        for oid in missing:
            self.perf.inc("recovery_pulls")
            con.send_message(MOSDPGPull(pgid=msg.pgid, oid=oid,
                                        from_osd=self.osd_id))

    def _handle_pull(self, msg: MOSDPGPull) -> None:
        cid = f"{msg.pgid[0]}.{msg.pgid[1]}"
        try:
            data = self.store.read(cid, msg.oid)
            omap = self.store.omap_get(cid, msg.oid)
        except KeyError:
            return
        con = self._osd_con(msg.from_osd)
        if con:
            con.send_message(MOSDPGPush(pgid=msg.pgid, oid=msg.oid,
                                        data=data, omap=omap))

    def _handle_push(self, msg: MOSDPGPush) -> None:
        cid = f"{msg.pgid[0]}.{msg.pgid[1]}"
        t = Transaction()
        existing = set(self.store.list_collections())
        if cid not in existing:
            t.create_collection(cid)
        t.write(cid, msg.oid, 0, msg.data)
        if msg.omap:
            t.omap_setkeys(cid, msg.oid, msg.omap)
        self.store.apply_transaction(t)

    # -- heartbeats (OSD::heartbeat, osd/OSD.cc:4879) -------------------------

    def _schedule_heartbeat(self) -> None:
        if self._stop:
            return
        interval = float(self.ctx.conf.get("osd_heartbeat_interval"))
        self._hb_timer = threading.Timer(interval, self._heartbeat_tick)
        self._hb_timer.daemon = True
        self._hb_timer.start()

    def _heartbeat_tick(self) -> None:
        try:
            now = time.time()
            grace = float(self.ctx.conf.get("osd_heartbeat_grace"))
            m = self.osdmap
            peers = [o for o in range(m.max_osd)
                     if o != self.osd_id and m.is_up(o)]
            for peer in peers:
                con = self._osd_con(peer)
                if con:
                    con.send_message(MOSDPing(
                        from_osd=self.osd_id, op=MOSDPing.PING, stamp=now,
                        epoch=m.epoch))
                # first contact starts the grace clock; a peer that never
                # answers is as failed as one that stopped answering
                last = self._hb_last.setdefault(peer, now)
                if now - last > grace:
                    mon = self.msgr.connect_to(self.mon_addr,
                                               EntityName("mon", 0))
                    mon.send_message(MOSDFailure(
                        reporter=self.osd_id, failed_osd=peer,
                        failed_for=now - last, epoch=m.epoch))
        finally:
            self._schedule_heartbeat()

    # -- dispatch -------------------------------------------------------------

    def ms_dispatch(self, msg) -> bool:
        if isinstance(msg, MOSDMapMsg):
            self._handle_map(msg)
            return True
        if isinstance(msg, MOSDOp):
            self._handle_op(msg)
            return True
        if isinstance(msg, MOSDRepOp):
            self._handle_rep_op(msg)
            return True
        if isinstance(msg, MOSDRepOpReply):
            self._handle_rep_reply(msg)
            return True
        if isinstance(msg, MOSDECSubOpWrite):
            self._handle_ec_write(msg)
            return True
        if isinstance(msg, MOSDECSubOpWriteReply):
            self._handle_ec_write_reply(msg)
            return True
        if isinstance(msg, MOSDECSubOpRead):
            self._handle_ec_read(msg)
            return True
        if isinstance(msg, MOSDECSubOpReadReply):
            self._handle_ec_read_reply(msg)
            return True
        if isinstance(msg, MOSDPing):
            self._handle_ping(msg)
            return True
        if isinstance(msg, MOSDPGScan):
            self._handle_scan(msg)
            return True
        if isinstance(msg, MOSDPGScanReply):
            self._handle_scan_reply(msg)
            return True
        if isinstance(msg, MOSDPGPull):
            self._handle_pull(msg)
            return True
        if isinstance(msg, MOSDPGPush):
            self._handle_push(msg)
            return True
        return False

    def _handle_ping(self, msg: MOSDPing) -> None:
        self._hb_last[msg.from_osd] = time.time()
        if msg.op == MOSDPing.PING and msg.connection is not None:
            msg.connection.send_message(MOSDPing(
                from_osd=self.osd_id, op=MOSDPing.PING_REPLY,
                stamp=msg.stamp, epoch=self.osdmap.epoch))

    # -- op execution (PrimaryLogPG::do_op analog) ----------------------------

    def _pg_members(self, pgid) -> tuple[list[int], int]:
        """(up, acting_primary) — ops are accepted by the acting primary,
        matching the client's _calc_target (osdc/Objecter.cc:2795)."""
        up, _up_primary, _acting, acting_primary = \
            self.osdmap.pg_to_up_acting_osds(pgid[0], pgid[1])
        return up, acting_primary

    def _handle_op(self, msg: MOSDOp) -> None:
        pool = self.osdmap.pools.get(msg.pgid[0])
        if pool is None:
            self._reply_err(msg, -2)
            return
        up, primary = self._pg_members(msg.pgid)
        if primary != self.osd_id:
            # not my op in this epoch; client resends on map update
            dout("osd", 10, "osd.%d not primary for %s", self.osd_id,
                 msg.pgid)
            return
        if pool.is_erasure():
            self._do_ec_op(msg, pool, up)
        else:
            self._do_replicated_op(msg, pool, up)

    def _reply_err(self, msg: MOSDOp, code: int) -> None:
        msg.connection.send_message(
            MOSDOpReply(tid=msg.tid, result=code, epoch=self.osdmap.epoch))

    # replicated pools ---------------------------------------------------------

    def _do_replicated_op(self, msg: MOSDOp, pool, up) -> None:
        cid = f"{msg.pgid[0]}.{msg.pgid[1]}"
        t = Transaction()
        reply_ops: list[OSDOpField] = []
        result = 0
        is_write = False
        for op in msg.ops:
            if op.op in (OP_WRITE, OP_WRITEFULL):
                is_write = True
                if op.op == OP_WRITEFULL:
                    t.truncate(cid, msg.oid, 0)
                t.write(cid, msg.oid, op.offset, op.data)
            elif op.op == OP_DELETE:
                is_write = True
                t.remove(cid, msg.oid)
            elif op.op == OP_OMAP_SET:
                is_write = True
                keys = _decode_omap(op.data)
                t.touch(cid, msg.oid)
                t.omap_setkeys(cid, msg.oid, keys)
            elif op.op == OP_READ:
                try:
                    data = self.store.read(
                        cid, msg.oid, op.offset,
                        op.length if op.length else None)
                    reply_ops.append(OSDOpField(OP_READ, op.offset,
                                                len(data), data))
                    self.perf.inc("op_r")
                except KeyError:
                    result = -2
            elif op.op == OP_STAT:
                try:
                    st = self.store.stat(cid, msg.oid)
                    reply_ops.append(OSDOpField(
                        OP_STAT, 0, st["size"], b""))
                except KeyError:
                    result = -2
            elif op.op == OP_OMAP_GET:
                try:
                    omap = self.store.omap_get(cid, msg.oid)
                    reply_ops.append(OSDOpField(
                        OP_OMAP_GET, 0, 0, _encode_omap(omap)))
                except KeyError:
                    result = -2
            else:
                result = -22
        if not is_write or result != 0:
            msg.connection.send_message(MOSDOpReply(
                tid=msg.tid, result=result, epoch=self.osdmap.epoch,
                ops=reply_ops))
            return
        # write path: local commit + replica fan-out (issue_repop)
        self.perf.inc("op_w")
        t0 = time.time()
        self.store.apply_transaction(t)
        replicas = [o for o in up if o != self.osd_id and o != CEPH_NOSD]
        reply = MOSDOpReply(tid=msg.tid, result=0, epoch=self.osdmap.epoch)
        if not replicas:
            self.perf.tinc("op_w_latency", time.time() - t0)
            msg.connection.send_message(reply)
            return
        reqid = (msg.client_id, msg.tid)
        with self._lock:
            self._in_flight[reqid] = _InFlight(msg, set(replicas), reply)
        blob = t.encode()
        for rep in replicas:
            con = self._osd_con(rep)
            if con is None:
                # address unknown this epoch: count it as an instant nack so
                # the op does not hang; the client retries on the next map
                self._ack_shard(reqid, rep, -107)
                continue
            con.send_message(MOSDRepOp(reqid=reqid, pgid=msg.pgid,
                                       oid=msg.oid, txn=blob))
        self.perf.tinc("op_w_latency", time.time() - t0)

    def _handle_rep_op(self, msg: MOSDRepOp) -> None:
        self.perf.inc("op_rep")
        t = Transaction.decode(msg.txn)
        cid = f"{msg.pgid[0]}.{msg.pgid[1]}"
        if cid not in self.store.list_collections():
            pre = Transaction().create_collection(cid)
            self.store.apply_transaction(pre)
        self.store.apply_transaction(t)
        msg.connection.send_message(MOSDRepOpReply(
            reqid=msg.reqid, pgid=msg.pgid, from_osd=self.osd_id, result=0))

    def _handle_rep_reply(self, msg: MOSDRepOpReply) -> None:
        self._ack_shard(msg.reqid, msg.from_osd, msg.result)

    def _ack_shard(self, reqid, from_osd: int, result: int) -> None:
        with self._lock:
            inf = self._in_flight.get(reqid)
            if inf is None:
                return
            inf.waiting.discard(from_osd)
            if result != 0:
                inf.reply.result = result
            if inf.waiting:
                return
            del self._in_flight[reqid]
        inf.msg.connection.send_message(inf.reply)

    # erasure pools ------------------------------------------------------------

    def _codec(self, pool):
        with self._lock:
            c = self._codecs.get(pool.pool_id)
            if c is None:
                profile = dict(pool.ec_profile)
                plugin = profile.pop("plugin", "jerasure")
                profile.setdefault(
                    "runtime", self.ctx.conf.get("erasure_code_runtime"))
                c = registry_instance().factory(plugin, profile)
                self._codecs[pool.pool_id] = c
            return c

    def _do_ec_op(self, msg: MOSDOp, pool, up) -> None:
        codec = self._codec(pool)
        k = codec.get_data_chunk_count()
        n = codec.get_chunk_count()
        cid = f"{msg.pgid[0]}.{msg.pgid[1]}"
        for op in msg.ops:
            if op.op == OP_WRITEFULL:
                self.perf.inc("op_w")
                reqid = (msg.client_id, msg.tid)
                shard_osds = {s: up[s] for s in range(min(n, len(up)))
                              if up[s] != CEPH_NOSD}
                if len(shard_osds) < max(k, pool.min_size):
                    # below min_size the write could never be re-read;
                    # block it (PrimaryLogPG checks acting >= min_size)
                    self._reply_err(msg, -11)
                    return
                chunks = codec.encode(set(range(n)), op.data)
                self.perf.inc("ec_encode_stripes")
                reply = MOSDOpReply(tid=msg.tid, result=0,
                                    epoch=self.osdmap.epoch)
                waiting = set()
                size_attr = str(len(op.data)).encode()
                for shard, osd in shard_osds.items():
                    if osd == self.osd_id:
                        t = (Transaction()
                             .truncate(cid, f"{msg.oid}:{shard}", 0)
                             .write(cid, f"{msg.oid}:{shard}", 0,
                                    chunks[shard])
                             .setattr(cid, f"{msg.oid}:{shard}", "size",
                                      size_attr))
                        self.store.apply_transaction(t)
                    else:
                        waiting.add(osd)
                with self._lock:
                    if waiting:
                        self._in_flight[reqid] = _InFlight(
                            msg, set(waiting), reply)
                for shard, osd in shard_osds.items():
                    if osd == self.osd_id:
                        continue
                    con = self._osd_con(osd)
                    if con is None:
                        self._ack_shard(reqid, osd, -107)
                        continue
                    con.send_message(MOSDECSubOpWrite(
                        reqid=reqid, pgid=msg.pgid,
                        oid=f"{msg.oid}:{shard}",
                        shard=shard, chunk=chunks[shard],
                        epoch=self.osdmap.epoch,
                        obj_size=len(op.data)))
                if not waiting:
                    msg.connection.send_message(reply)
            elif op.op == OP_READ:
                self.perf.inc("op_r")
                self._start_ec_read(msg, pool, up, cid)
            else:
                self._reply_err(msg, -22)
                return

    def _handle_ec_write(self, msg: MOSDECSubOpWrite) -> None:
        oid = msg.oid
        cid = f"{msg.pgid[0]}.{msg.pgid[1]}"
        if cid not in self.store.list_collections():
            self.store.apply_transaction(Transaction().create_collection(cid))
        t = (Transaction().truncate(cid, oid, 0)
             .write(cid, oid, 0, msg.chunk)
             .setattr(cid, oid, "size", str(msg.obj_size).encode()))
        self.store.apply_transaction(t)
        msg.connection.send_message(MOSDECSubOpWriteReply(
            reqid=msg.reqid, shard=msg.shard, from_osd=self.osd_id,
            result=0))

    def _handle_ec_write_reply(self, msg: MOSDECSubOpWriteReply) -> None:
        self._ack_shard(msg.reqid, msg.from_osd, msg.result)

    def _start_ec_read(self, msg: MOSDOp, pool, up, cid: str) -> None:
        """objects_read_and_reconstruct analog: gather k shards, decode."""
        codec = self._codec(pool)
        k = codec.get_data_chunk_count()
        n = codec.get_chunk_count()
        reqid = (msg.client_id, msg.tid)
        avail = {s: up[s] for s in range(min(n, len(up)))
                 if up[s] != CEPH_NOSD}
        if len(avail) < k:
            # fewer than k shards mapped to live osds: unreadable this epoch
            self._reply_err(msg, -5)
            return
        want = dict(list(avail.items()))
        state = {"msg": msg, "pool": pool, "shards": {}, "k": k,
                 "asked": set(), "failed": set()}
        with self._lock:
            self._ec_reads[reqid] = state
        # ask k shards (prefer data shards: minimum_to_decode semantics)
        chosen = sorted(want)[:k]
        for s in chosen:
            osd = want[s]
            state["asked"].add(s)
            if osd == self.osd_id:
                self._ec_read_local(reqid, msg, cid, s)
            else:
                con = self._osd_con(osd)
                if con is None:
                    self._ec_read_failed(reqid, s)
                    continue
                con.send_message(MOSDECSubOpRead(
                    reqid=reqid, pgid=msg.pgid, oid=msg.oid, shard=s))

    def _ec_read_local(self, reqid, msg, cid, shard) -> None:
        try:
            chunk = self.store.read(cid, f"{msg.oid}:{shard}")
            size = int(self.store.getattr(cid, f"{msg.oid}:{shard}", "size"))
        except (KeyError, TypeError):
            self._ec_read_failed(reqid, shard)
            return
        self._ec_read_done(reqid, shard, chunk, size)

    def _handle_ec_read(self, msg: MOSDECSubOpRead) -> None:
        cid = f"{msg.pgid[0]}.{msg.pgid[1]}"
        try:
            chunk = self.store.read(cid, f"{msg.oid}:{msg.shard}")
            size = int(self.store.getattr(cid, f"{msg.oid}:{msg.shard}",
                                          "size"))
            result = 0
        except (KeyError, TypeError):
            chunk, size, result = b"", 0, -2
        msg.connection.send_message(MOSDECSubOpReadReply(
            reqid=msg.reqid, shard=msg.shard, from_osd=self.osd_id,
            result=result, chunk=chunk + size.to_bytes(8, "little")
            if result == 0 else b""))

    def _handle_ec_read_reply(self, msg: MOSDECSubOpReadReply) -> None:
        if msg.result != 0:
            self._ec_read_failed(msg.reqid, msg.shard)
            return
        chunk, size = msg.chunk[:-8], int.from_bytes(msg.chunk[-8:],
                                                     "little")
        self._ec_read_done(msg.reqid, msg.shard, chunk, size)

    def _ec_read_failed(self, reqid, shard: int) -> None:
        with self._lock:
            state = self._ec_reads.get(reqid)
            if state is None:
                return
            state["failed"].add(shard)
            msg = state["msg"]
            pool = state["pool"]
        # ask a replacement shard not yet asked (min_to_decode retry)
        up, _primary = self._pg_members(msg.pgid)
        codec = self._codec(pool)
        n = codec.get_chunk_count()
        cid = f"{msg.pgid[0]}.{msg.pgid[1]}"
        with self._lock:
            candidates = [s for s in range(min(n, len(up)))
                          if up[s] != CEPH_NOSD and s not in state["asked"]]
            if not candidates:
                del self._ec_reads[reqid]
                self._reply_err(msg, -5)
                return
            s = candidates[0]
            state["asked"].add(s)
            osd = up[s]
        if osd == self.osd_id:
            self._ec_read_local(reqid, msg, cid, s)
        else:
            con = self._osd_con(osd)
            if con is None:
                self._ec_read_failed(reqid, s)
            else:
                con.send_message(MOSDECSubOpRead(
                    reqid=reqid, pgid=msg.pgid, oid=msg.oid, shard=s))

    def _ec_read_done(self, reqid, shard: int, chunk: bytes,
                      size: int) -> None:
        with self._lock:
            state = self._ec_reads.get(reqid)
            if state is None:
                return
            state["shards"][shard] = chunk
            state["size"] = size
            if len(state["shards"]) < state["k"]:
                return
            del self._ec_reads[reqid]
        msg = state["msg"]
        codec = self._codec(state["pool"])
        k = state["k"]
        have = dict(sorted(state["shards"].items())[:k])
        chunks = {s: c for s, c in have.items()}
        decoded = codec.decode(set(range(k)), chunks)
        data = b"".join(decoded[i] for i in range(k))[:state["size"]]
        msg.connection.send_message(MOSDOpReply(
            tid=msg.tid, result=0, epoch=self.osdmap.epoch,
            ops=[OSDOpField(OP_READ, 0, len(data), data)]))

    # -- peers ----------------------------------------------------------------

    def set_osd_addr(self, osd: int, addr: str) -> None:
        self._osd_addr_cache[osd] = addr

    def _osd_con(self, osd: int):
        addr = None
        if 0 <= osd < len(self.osdmap.osd_addrs):
            addr = self.osdmap.osd_addrs[osd] or None
        if addr is None:
            addr = self._osd_addr_cache.get(osd)
        if addr is None:
            return None
        return self.msgr.connect_to(addr, EntityName("osd", osd))


def _encode_omap(d: dict) -> bytes:
    e = Encoder()
    e.map(d, lambda e2, k2: e2.str(k2), lambda e2, v: e2.bytes(v))
    return e.tobytes()


def _decode_omap(data: bytes) -> dict:
    return Decoder(data).map(lambda d: d.str(), lambda d: d.bytes())
