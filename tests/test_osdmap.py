"""OSDMap placement-pipeline tests: scalar oracle semantics and batched
full-map equality (OSDMap.cc / OSDMapMapping.h analogs)."""

import numpy as np
import pytest

from ceph_tpu.crush import build_two_level_map
from ceph_tpu.crush.types import CRUSH_ITEM_NONE
from ceph_tpu.osd import OSDMap, OSDMapMapping, PGPool, ceph_stable_mod
from ceph_tpu.osd.osdmap import (
    CEPH_NOSD, POOL_TYPE_ERASURE, POOL_TYPE_REPLICATED)


def make_cluster(n_hosts=6, osds_per_host=4):
    crush, _root, rule = build_two_level_map(n_hosts, osds_per_host)
    m = OSDMap(crush=crush)
    n = n_hosts * osds_per_host
    m.set_max_osd(n)
    for o in range(n):
        m.mark_up(o)
    m.pools[1] = PGPool(pool_id=1, type=POOL_TYPE_REPLICATED, size=3,
                        crush_rule=rule, pg_num=64)
    return m


def test_stable_mod_matches_reference_property():
    # ceph_stable_mod(x, b, bmask) == x % b when b is a power of two
    for b in (1, 2, 4, 8, 64):
        bmask = b - 1
        for x in range(200):
            assert ceph_stable_mod(x, b, bmask) == x % b
    # growth stability: half the pgs keep their mapping when pg_num doubles
    moved = sum(ceph_stable_mod(x, 12, 15) != ceph_stable_mod(x, 8, 7)
                for x in range(1024))
    assert 0 < moved < 1024


def test_pg_to_up_acting_basic():
    m = make_cluster()
    ups = set()
    for pg in range(64):
        up, upp, acting, actp = m.pg_to_up_acting_osds(1, pg)
        assert len(up) == 3
        assert len(set(up)) == 3
        assert upp == up[0]
        assert acting == up and actp == upp
        ups.update(up)
    assert len(ups) > 12  # spread across the cluster


def test_down_osd_leaves_up_set():
    m = make_cluster()
    up0, *_ = m.pg_to_up_acting_osds(1, 0)
    victim = up0[0]
    m.mark_down(victim)
    up1, upp, _, _ = m.pg_to_up_acting_osds(1, 0)
    assert victim not in up1
    assert upp != victim


def test_out_osd_remapped_by_crush():
    m = make_cluster()
    up0, *_ = m.pg_to_up_acting_osds(1, 0)
    victim = up0[0]
    m.mark_out(victim)  # weight 0: CRUSH rejects it, set stays full
    up1, *_ = m.pg_to_up_acting_osds(1, 0)
    assert victim not in up1
    assert len(up1) == 3


def test_erasure_pool_keeps_positions():
    m = make_cluster()
    m.pools[2] = PGPool(pool_id=2, type=POOL_TYPE_ERASURE, size=4,
                        crush_rule=0, pg_num=32)
    up, upp, _, _ = m.pg_to_up_acting_osds(2, 3)
    assert len(up) == 4
    victim = up[1]
    m.mark_down(victim)
    up2, *_ = m.pg_to_up_acting_osds(2, 3)
    assert len(up2) == 4
    assert up2[1] == CEPH_NOSD      # positional hole, not compaction
    assert [o for i, o in enumerate(up2) if i != 1] == \
           [o for i, o in enumerate(up) if i != 1]


def test_pg_upmap_items_override():
    m = make_cluster()
    up0, *_ = m.pg_to_up_acting_osds(1, 5)
    frm = up0[1]
    to = next(o for o in range(m.max_osd) if o not in up0)
    m.pg_upmap_items[(1, 5)] = [(frm, to)]
    up1, *_ = m.pg_to_up_acting_osds(1, 5)
    assert to in up1 and frm not in up1


def test_pg_upmap_full_override():
    m = make_cluster()
    m.pg_upmap[(1, 7)] = [0, 4, 8]
    up, upp, _, _ = m.pg_to_up_acting_osds(1, 7)
    assert up == [0, 4, 8] and upp == 0


def test_pg_temp_and_primary_temp():
    m = make_cluster()
    m.pg_temp[(1, 9)] = [1, 2, 3]
    m.primary_temp[(1, 9)] = 3
    up, upp, acting, actp = m.pg_to_up_acting_osds(1, 9)
    assert acting == [1, 2, 3] and actp == 3
    assert up != acting  # up still CRUSH-computed


def test_primary_affinity_zero_shifts_primary():
    m = make_cluster()
    up0, upp0, _, _ = m.pg_to_up_acting_osds(1, 11)
    m.osd_primary_affinity[upp0] = 0  # never primary
    up1, upp1, _, _ = m.pg_to_up_acting_osds(1, 11)
    assert up1 == up0           # membership unchanged
    assert upp1 != upp0         # leadership moved


def test_batched_mapping_matches_scalar():
    m = make_cluster(n_hosts=8, osds_per_host=4)
    m.pools[3] = PGPool(pool_id=3, type=POOL_TYPE_ERASURE, size=4,
                        crush_rule=0, pg_num=128)
    m.mark_down(5)
    m.mark_out(9)
    m.osd_primary_affinity[2] = 0x8000
    m.pg_upmap_items[(1, 3)] = [(m.pg_to_up_acting_osds(1, 3)[0][0], 30)]
    mapping = OSDMapMapping(m)
    mapping.update()
    for pool_id, pool in m.pools.items():
        for pg in range(pool.pg_num):
            assert mapping.get(pool_id, pg) == \
                m.pg_to_up_acting_osds(pool_id, pg), (pool_id, pg)


def test_pg_counts_histogram():
    m = make_cluster()
    mapping = OSDMapMapping(m)
    mapping.update()
    counts = mapping.pg_counts(1)
    assert counts.sum() == 64 * 3
    assert (counts > 0).sum() > 12
