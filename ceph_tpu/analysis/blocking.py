"""Completion-thread blocking lint (check family ``blocking``).

The dispatch engines deliver every future on ONE completion thread
(ops/dispatch.py's delivery-order contract).  A continuation that
blocks — waiting on another dispatch future with ``.result()``, a
blocking bare ``acquire()``, ``time.sleep`` — stalls every later
completion in the pipeline, and waiting on a future of the SAME
engine is a guaranteed self-deadlock (the wait can only be satisfied
by the thread doing the waiting).  Host-sync calls (``np.asarray`` /
``block_until_ready`` on device values) serialize the double-buffered
pipeline the same way.

Roots: every function/lambda registered via ``add_done_callback``.
The lint flags blocking patterns in any function reachable from a
root through the best-effort call graph.  ``with lock:`` critical
sections are NOT flagged — bounded exclusion is how continuations are
meant to synchronize; parking the thread is not.
"""

from __future__ import annotations

import ast

from ceph_tpu.analysis import Finding
from ceph_tpu.analysis.core import TreeIndex, name_chain

#: reachability bound: a callback calling this many functions deep is
#: beyond useful precision (and beyond plausible completion-path code)
MAX_DEPTH = 6


def _roots(index: TreeIndex):
    """Functions registered as dispatch-future callbacks, with the
    call site that registered them (for the report)."""
    roots = []
    for fi in index.all_functions():
        for cs in fi.call_sites:
            node = cs.node
            if not isinstance(node, ast.Call):
                continue
            chain = name_chain(node.func)
            if not chain or chain[-1] != "add_done_callback":
                continue
            for arg in node.args:
                target = None
                ach = name_chain(arg)
                if isinstance(arg, ast.Lambda):
                    target = fi.nested.get(
                        f"<lambda@{arg.lineno}:{arg.col_offset}>")
                elif ach:
                    spec = None
                    if len(ach) == 1:
                        spec = ("name", ach[0])
                    elif ach[0] in ("self", "cls") and len(ach) == 2:
                        spec = ("self", ach[1])
                    if spec:
                        target = index.resolve_call(fi, spec)
                if target is not None:
                    roots.append((target, fi, cs.line))
    return roots


def _reachable(index: TreeIndex, roots):
    """fn -> (root, depth, via) for every function reachable from a
    callback root."""
    out = {}
    frontier = [(fn, fn, 0) for fn, _src, _ln in roots]
    for fn, root, _d in frontier:
        out.setdefault(fn, (root, 0, None))
    while frontier:
        nxt = []
        for fn, root, d in frontier:
            if d >= MAX_DEPTH:
                continue
            for cs in fn.call_sites:
                g = index.resolve_call(fn, cs.spec)
                if g is not None and g not in out:
                    out[g] = (root, d + 1, fn)
                    nxt.append((g, root, d + 1))
        frontier = nxt
    return out


def _params(fi) -> set:
    args = getattr(fi.node, "args", None)
    if args is None:
        return set()
    out = {a.arg for a in (list(args.posonlyargs) + list(args.args)
                           + list(args.kwonlyargs))}
    if args.vararg:
        out.add(args.vararg.arg)
    if args.kwarg:
        out.add(args.kwarg.arg)
    return out


def _blocking_sites(index: TreeIndex, fi):
    """(line, code, detail) blocking patterns directly inside fi."""
    sites = []
    params = _params(fi)
    for node in ast.walk(fi.node):
        if not isinstance(node, ast.Call):
            continue
        chain = name_chain(node.func)
        if not chain:
            continue
        tail = chain[-1]
        if tail == "result" and len(chain) > 1:
            # .result() DIRECTLY on a future the callback received as
            # a parameter (incl. ones threaded down a continuation
            # chain) is the standard already-complete read, not a
            # wait.  Only the two-element form qualifies: a future
            # reached THROUGH a parameter (self._w.result()) is
            # attribute-stored state, exactly the create-then-wait
            # self-deadlock this check exists for.
            if len(chain) == 2 and chain[0] in params:
                continue
            sites.append((node.lineno, "future-wait",
                          f"{'.'.join(chain)}() blocks on another "
                          f"completion"))
        elif chain == ("time", "sleep"):
            sites.append((node.lineno, "sleep", "time.sleep parks the "
                          "completion thread"))
        elif tail == "block_until_ready":
            sites.append((node.lineno, "host-sync",
                          "block_until_ready fences the device "
                          "pipeline"))
        elif tail == "asarray" and len(chain) == 2 and \
                chain[0] in ("np", "numpy"):
            # jnp.asarray stays device-side/async — only a HOST
            # asarray materializes and stalls the pipeline
            sites.append((node.lineno, "host-sync",
                          f"{chain[0]}.asarray on a device value "
                          f"synchronizes the pipeline"))
        elif tail == "acquire" and len(chain) > 1:
            # blocking bare acquire (with-statements are exempt):
            # acquire(False) / acquire(timeout=..) are bounded
            def bounded_timeout(v) -> bool:
                # timeout=-1 (or any negative constant) is the
                # documented block-forever spelling; a non-constant
                # timeout is assumed bounded.  Negative literals parse
                # as UnaryOp(USub, Constant), not negative Constants.
                if isinstance(v, ast.UnaryOp) and \
                        isinstance(v.op, ast.USub) and \
                        isinstance(v.operand, ast.Constant) and \
                        isinstance(v.operand.value, (int, float)):
                    return False
                return not (isinstance(v, ast.Constant)
                            and isinstance(v.value, (int, float))
                            and v.value < 0)
            blocking = True
            if node.args and isinstance(node.args[0], ast.Constant):
                blocking = bool(node.args[0].value)
            for kw in node.keywords:
                if kw.arg in ("blocking",) and isinstance(
                        kw.value, ast.Constant):
                    blocking = bool(kw.value.value)
                if kw.arg == "timeout" and bounded_timeout(kw.value):
                    blocking = False
            if len(node.args) >= 2 and bounded_timeout(node.args[1]):
                blocking = False
            if blocking:
                sites.append((node.lineno, "acquire",
                              f"unbounded {'.'.join(chain)}()"))
    return sites


def check(index: TreeIndex):
    roots = _roots(index)
    reach = _reachable(index, roots)
    findings = []
    seen = set()
    for fn in sorted(reach, key=lambda f: f.qualname):
        root, depth, via = reach[fn]
        for line, code, detail in _blocking_sites(index, fn):
            key = (fn.module.relpath, line, code)
            if key in seen:
                continue
            seen.add(key)
            how = "a completion callback" if depth == 0 else (
                f"reachable from completion callback "
                f"{root.qualname} (depth {depth})")
            findings.append(Finding(
                "blocking", fn.module.relpath, line, code,
                f"{detail}; {fn.qualname} is {how}"))
    return findings
