"""Compiled-TPU vs XLA cross-validation of the CRUSH fast path.

Interpret-mode tests cannot catch Mosaic *compiled-path* divergence: in
round 3 the in-kernel is_out (hash32_2 fed from the winner gather/sum
pipeline) miscompiled for ~0.03% of lanes on TPU while interpret mode was
bit-exact.  This suite re-runs the full bulk placement on the real device
against the XLA fast path (itself oracle-validated in test_mapper_jax).

It runs whenever a TPU backend is REACHABLE — the conftest exposes it
alongside the cpu test platform automatically, so a plain `pytest tests/`
on a TPU host exercises this gate (no opt-in env var needed); only hosts
with no TPU at all skip it.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ceph_tpu.crush import build_flat_map, build_two_level_map
from ceph_tpu.crush.fastpath import FastMapper, detect


def _tpu_device():
    for plat in ("axon", "tpu"):
        try:
            return jax.devices(plat)[0]
        except RuntimeError:
            continue
    return None

_TPU = _tpu_device()

pytestmark = pytest.mark.skipif(
    _TPU is None, reason="no TPU backend reachable on this host")


@pytest.fixture(autouse=True)
def _on_tpu():
    """Every computation in this module runs on the real chip even
    though the suite's default backend is the virtual CPU mesh."""
    with jax.default_device(_TPU):
        yield


def _skewed_bench_map():
    crush_map, _root, rid = build_two_level_map(250, 40)
    wrng = np.random.default_rng(42)
    for b in crush_map.buckets:
        if b is not None and b.type == 1:
            b.item_weights = [int(w) for w in
                              wrng.integers(0x8000, 0x20000, b.size)]
            b.weight = sum(b.item_weights)
    root = crush_map.bucket(-1)
    root.item_weights = [crush_map.bucket(h).weight for h in root.items]
    root.weight = sum(root.item_weights)
    return crush_map, rid


def test_two_stage_pallas_matches_xla_bulk():
    crush_map, rid = _skewed_bench_map()
    fr = detect(crush_map, rid)
    n_osds = 10000
    reweight = np.full(n_osds, 0x10000, dtype=np.int64)
    idx = np.random.default_rng(42).permutation(n_osds)
    reweight[idx[:1000]] = 0x8000
    reweight[idx[1000:1200]] = 0
    rw = jnp.asarray(reweight)
    xs = jnp.asarray(np.random.default_rng(0).integers(
        0, 2 ** 32, (65536,), dtype=np.uint32))
    fm = FastMapper(fr)
    assert fm._pallas is not None
    res_pl = np.asarray(fm.run(xs, rw, 3))
    fm_xla = FastMapper(fr)
    fm_xla._pallas = None
    res_xla = np.asarray(fm_xla.run(xs, rw, 3))
    np.testing.assert_array_equal(res_pl, res_xla)


def test_flat_rule_pallas_matches_xla():
    fmap, _r, frid = build_flat_map(300)
    fr = detect(fmap, frid)
    rw = jnp.asarray(np.where(np.arange(300) % 37 == 0, 0x8000,
                              0x10000).astype(np.int64))
    xs = jnp.asarray(np.random.default_rng(1).integers(
        0, 2 ** 32, (8192,), dtype=np.uint32))
    fm = FastMapper(fr)
    res_pl = np.asarray(fm.run(xs, rw, 3))
    fm_xla = FastMapper(fr)
    fm_xla._pallas = None
    res_xla = np.asarray(fm_xla.run(xs, rw, 3))
    np.testing.assert_array_equal(res_pl, res_xla)
