"""The OSD daemon (src/osd/OSD.{h,cc} + PrimaryLogPG + backends, condensed).

Structure mirrors the reference data path (SURVEY.md §3.1/§3.3), now with the
PG consistency backbone (src/osd/PGLog.h, src/osd/PG.h peering):

  client MOSDOp -> primary:  dedup against the pg log (reqid), allocate an
                             (epoch, seq) version, append a log entry, then
                             replicated: local txn + MOSDRepOp fan-out
                             erasure: batched GF(2^8) encode -> per-shard
                             MOSDECSubOpWrite fan-out (the whole-stripe encode
                             is one device call, ECUtil::encode's batch point)
  map change:                every PG re-peers: GetInfo (MOSDPGQuery/Notify)
                             -> GetLog from the peer with the longest history
                             (MOSDPGLog) -> merge_log (divergent-entry
                             rollback) -> recover missing objects ->
                             Activate (authoritative log to every replica)
  recovery:                  log-based, not scan-based: each OSD computes its
                             own missing set from the authoritative log and
                             pulls exactly those objects (MOSDPGPull/Push);
                             EC shards are reconstructed from k live shards
                             at the needed version and pushed per-shard
  heartbeats:                periodic MOSDPing to up peers; missed grace ->
                             MOSDFailure to the mon (OSD::heartbeat_check)

Erasure objects store one chunk per shard-OSD as "<oid>:<shard>" with the
stripe geometry in attrs; any k chunks reconstruct via the recovery-matrix
kernel, exactly the ECBackend read path.  Every object carries a "_v"
version attr so recovery can tell stale copies from current ones.

Durability: the pg log and pg info ride in the *same* ObjectStore
transaction as the data mutation (omap of the per-PG "_pgmeta_" object),
so replay after restart reconstructs exactly the logged history
(OSD::load_pgs, osd/OSD.cc:4061).
"""

from __future__ import annotations

import threading
import time

from ceph_tpu.common.context import CephTpuContext
from ceph_tpu.common.logging import dout
from ceph_tpu.common.perf_counters import PerfCountersBuilder
from ceph_tpu.common.throttle import Throttle
from ceph_tpu.ec import registry_instance
from ceph_tpu.messages import (
    MPGStats,
    MOSDECSubOpRead, MOSDECSubOpReadReply, MOSDECSubOpWrite,
    MOSDECSubOpWriteReply, MOSDFailure, MOSDMapMsg, MOSDOp, MOSDOpReply,
    MOSDPing, MOSDRepOp, MOSDRepOpReply)
from ceph_tpu.messages.osd_msgs import (
    OP_CALL, OP_DELETE, OP_NOTIFY, OP_OMAP_GET, OP_OMAP_RMKEYS, OP_PGLS,
    OP_OMAP_SET, OP_READ,
    OP_STAT, OP_UNWATCH, OP_WATCH, OP_WRITE, OP_WRITEFULL, MOSDScrub,
    MOSDScrubReply, MWatchNotify, MWatchNotifyAck, OSDOpField)
from ceph_tpu.messages.peering_msgs import MOSDPGLog, MOSDPGNotify, MOSDPGQuery
from ceph_tpu.mon.monitor import MMonSubscribe, MOSDBoot
from ceph_tpu.msg.encoding import Decoder, Encoder
from ceph_tpu.msg.message import Message, register_message
from ceph_tpu.msg.messenger import (
    ConnectionPolicy, Dispatcher, EntityName, Messenger)
from ceph_tpu.objectstore import Transaction, create_objectstore
from ceph_tpu.osd.map_codec import advance_map, encode_osdmap
from ceph_tpu.osd.osdmap import CEPH_NOSD, OSDMap, pg_to_pgid
from ceph_tpu.qos.dmclock import (
    BACKGROUND_BEST_EFFORT, PHASE_LIMIT, PHASE_NAMES, PHASE_NONE,
    PHASE_RESERVATION, PHASE_WEIGHT)
from ceph_tpu.client.rados import ceph_str_hash_rjenkins
from ceph_tpu.osd.pg import (
    EVERSION_ZERO, LOG_DELETE, LOG_MODIFY, PG, LogEntry, MissingItem,
    PeerState, PGInfo, STATE_ACTIVE, STATE_GETINFO, STATE_GETLOG,
    STATE_INACTIVE, STATE_RECOVERING, STATE_REPLICA)

import numpy as np


@register_message
class MOSDPGPull(Message):
    """recovering OSD -> source: send me this object (recovery pull).

    For EC PGs the oid is "<logical>:<shard>": the source reconstructs
    that shard's chunk from k live shards and pushes it back.
    """

    TYPE = 116

    def __init__(self, pgid: tuple[int, int] = (0, 0), oid: str = "",
                 from_osd: int = 0):
        super().__init__()
        self.pgid = pgid
        self.oid = oid
        self.from_osd = from_osd

    def encode_payload(self, enc: Encoder):
        enc.versioned(1, 1, lambda e: (e.s64(self.pgid[0]),
                                       e.u32(self.pgid[1]),
                                       e.str(self.oid), e.s32(self.from_osd)))

    def decode_payload(self, dec: Decoder, version):
        def body(d, v):
            self.pgid = (d.s64(), d.u32())
            self.oid = d.str()
            self.from_osd = d.s32()
        dec.versioned(1, body)


@register_message
class MOSDPGPush(Message):
    """source -> recovering OSD: object payload (MOSDPGPush analog).
    attrs carries the per-object metadata including the "_v" version."""

    TYPE = 117

    def __init__(self, pgid: tuple[int, int] = (0, 0), oid: str = "",
                 data: bytes = b"", omap: dict | None = None,
                 attrs: dict | None = None):
        super().__init__()
        self.pgid = pgid
        self.oid = oid
        self.data = data
        self.omap = omap or {}
        self.attrs = attrs or {}

    def encode_payload(self, enc: Encoder):
        enc.versioned(1, 1, lambda e: (
            e.s64(self.pgid[0]), e.u32(self.pgid[1]), e.str(self.oid),
            e.bytes(self.data),
            e.map(self.omap, lambda e2, k: e2.str(k),
                  lambda e2, v: e2.bytes(v)),
            e.map(self.attrs, lambda e2, k: e2.str(k),
                  lambda e2, v: e2.bytes(v))))

    def decode_payload(self, dec: Decoder, version):
        def body(d, v):
            self.pgid = (d.s64(), d.u32())
            self.oid = d.str()
            self.data = d.bytes()
            self.omap = d.map(lambda d2: d2.str(), lambda d2: d2.bytes())
            self.attrs = d.map(lambda d2: d2.str(), lambda d2: d2.bytes())
        dec.versioned(1, body)


#: scrub-map sentinel for a copy whose read failed checksum
#: verification; shaped like the (size, data_crc, omap_crc) triple so it
#: rides MOSDScrubReply's fixed wire format
SCRUB_CORRUPT = (2 ** 64 - 1, 0, 0)


def enc_version(v: tuple[int, int]) -> bytes:
    return f"{v[0]}.{v[1]}".encode()


def dec_version(blob: bytes | None) -> tuple[int, int] | None:
    if not blob:
        return None
    try:
        e, s = blob.decode().split(".")
        return (int(e), int(s))
    except ValueError:
        return None


class _InFlight:
    """One client op waiting on replica/shard acks (in-flight repop)."""

    def __init__(self, msg: MOSDOp, waiting: set[int], reply: MOSDOpReply):
        self.msg = msg
        self.waiting = waiting
        self.reply = reply


#: client_id used by internal EC recovery reads (cannot collide with real
#: clients, whose ids are small monotonically assigned ints)
#: store-name suffix for snapshot clones: head + CLONE_SEP + snap_seq.
#: The GROUP SEPARATOR control char keeps internal clone names out of
#: the client oid namespace — a client oid may contain "@" freely (rgw
#: versioned data objects do), but control characters are rejected at
#: the librados layer, so the suffix can never be ambiguous.  (The
#: reference separates oid and snap structurally in hobject_t,
#: src/common/hobject.h; this is the flattened-string equivalent.)
CLONE_SEP = "\x1d@"

RECOVERY_CLIENT = 0xFFFFFFFF00000000

#: reqid client for the tier agent's guarded evict deletes
TIER_AGENT_CLIENT = 0xFFFFFFFF00000001


class _ScrubChunk:
    """Queue item for one background deep-scrub chunk (one PG's
    scrub): shaped like a message for the opwq handler's getattr
    probes (trace/qos tags), so a sweep's chunks ride the sharded
    mClock queue in the background_best_effort class like any op."""

    __slots__ = ("pgid", "trace_id", "parent_span_id", "_qos_phase",
                 "qos_delta", "qos_rho")

    def __init__(self, pgid: tuple[int, int], cost: int = 1):
        self.pgid = pgid
        self.trace_id = 0
        self.parent_span_id = 0
        #: stamped by the opwq handler with the dmclock phase served
        self._qos_phase = PHASE_NONE
        #: dmclock cost scaling (osd_scrub_cost): a scrub map build is
        #: many small-op service times, so its weight tag advances by
        #: that many units per op — without this the per-op scheduler
        #: would hand the background class cost-times its weight's
        #: worth of worker-seconds
        self.qos_delta = max(1, int(cost))
        self.qos_rho = 0


class OSDDaemon(Dispatcher):
    def __init__(self, osd_id: int, mon_addr: str,
                 ctx: CephTpuContext | None = None,
                 store_type: str = "memstore", store_path: str = "",
                 ms_type: str = "async", addr: str = "127.0.0.1:0",
                 heartbeats: bool = True, auth_key=None,
                 mgr_addr: str | None = None,
                 cephx: tuple[str, str] | None = None,
                 conf: dict | None = None):
        self.osd_id = osd_id
        self.whoami = EntityName("osd", osd_id)
        self.ctx = ctx or CephTpuContext(f"osd.{osd_id}")
        # startup config overrides (vstart.sh -o analog): applied at the
        # CLI layer BEFORE any subsystem reads its options, so knobs
        # consumed at construction (osd_op_queue, shard count, ...) see
        # them — the central config-db only lands with the first map
        for k, v in (conf or {}).items():
            self.ctx.conf.set(k, v, source="cli")
        #: True when the context (and so its dispatch engine) is ours
        #: to tear down in shutdown(); a caller-supplied ctx may be
        #: shared with other daemons
        self._own_ctx = ctx is None
        #: comma-separated monitor addresses (mon_host); boot/failure
        #: reports go to every mon — the leader executes, peons ignore
        self.mon_addr = mon_addr
        self.mon_addrs = [a for a in mon_addr.split(",") if a]
        self.mgr_addr = mgr_addr
        self.store = create_objectstore(store_type, store_path,
                                        ctx=self.ctx)
        self.osdmap = OSDMap()
        from ceph_tpu.common.lockdep import make_lock
        self._lock = make_lock(f"OSD::osd_lock({osd_id})")
        self.pgs: dict[tuple[int, int], PG] = {}
        self._in_flight: dict[tuple[int, int], _InFlight] = {}
        #: ops from clients ahead of our map; flushed on map advance
        self._waiting_for_map: list[MOSDOp] = []
        #: inter-OSD ops parked until our map/splits catch up:
        #: (handler, msg) pairs replayed after the next map applies
        self._waiting_subops: list = []
        #: reqid -> EC read/recovery state
        self._ec_reads: dict[tuple[int, int], dict] = {}
        self._recover_tid = 0
        self._codecs: dict[int, object] = {}
        self._osd_addr_cache: dict[int, str] = {}
        self._hb_last: dict[int, float] = {}
        #: peers I currently have failure reports filed against; a ping
        #: from one triggers an alive-cancellation to the mons
        self._failure_reported: set[int] = set()
        self._last_sub_renew = 0.0
        #: (pgid, oid) -> {client_id: connection} (watch/notify; session
        #: scope — the reference persists watchers in object_info)
        self._watchers: dict[tuple, dict[int, object]] = {}
        #: notify_id -> pending notify state
        self._notifies: dict[int, dict] = {}
        self._notify_seq = 0
        #: scrub_id -> gathered scrub maps
        self._scrubs: dict[int, dict] = {}
        self._scrub_seq = 0
        self._hb_timer: threading.Timer | None = None
        self._tick_timer: threading.Timer | None = None
        self._heartbeats = heartbeats
        self._stop = False
        #: fault injection (reference: OSD.h debug_heartbeat_drops_remaining)
        self.debug_drop_rep_ops = 0
        #: async EC write dispatch: the encode is SUBMITTED through the
        #: context's coalescing engine and the transaction-build + shard
        #: fan-out runs in the completion continuation, so concurrent
        #: client writes share one device call.  Hot-togglable.
        self._ec_async = bool(self.ctx.conf.get("osd_ec_dispatch_async"))
        self.ctx.conf.add_observer(
            "osd_ec_dispatch_async",
            lambda _n, v: setattr(self, "_ec_async", bool(v)))
        #: async EC decode dispatch: degraded reads, recovery pulls and
        #: rmw gathers SUBMIT the decode through the context's decode
        #: engine (heterogeneous-matrix batched kernel — mixed erasure
        #: patterns share one device call) and finish reply/push/
        #: overlay in the completion continuation.  Hot-togglable.
        self._ec_decode_async = bool(
            self.ctx.conf.get("osd_ec_decode_async"))
        self.ctx.conf.add_observer(
            "osd_ec_decode_async",
            lambda _n, v: setattr(self, "_ec_decode_async", bool(v)))
        #: shared epoch-keyed mapping cache: map consumption rides the
        #: context's SharedPGMappingService — _scan_pgs walks only the
        #: changed-PG delta + locally-held PGs, and per-PG reads are
        #: cached-raw pipeline tails instead of scalar CRUSH.
        #: Hot-togglable (off = seed's full scalar scan).
        self._map_shared = bool(
            self.ctx.conf.get("osdmap_mapping_shared"))
        self.ctx.conf.add_observer(
            "osdmap_mapping_shared",
            lambda _n, v: setattr(self, "_map_shared", bool(v)))

        self._auth_key = auth_key
        self._cephx = cephx
        self.msgr = Messenger.create(self.whoami, ms_type)
        self.msgr.set_auth(auth_key)
        from ceph_tpu.common.moncmd import MonCommander, mon_targets
        #: the daemon's own admin RPC path (rotating keys, tickets)
        self.mon_cmd = MonCommander(self.msgr, self.mon_addrs,
                                    osdmap_fn=lambda: self.osdmap)
        from ceph_tpu.common.clog import ClusterLogClient
        #: central cluster log handle (LogClient): operator-significant
        #: events (boot, pg recovered) batch to every mon
        self.clog = ClusterLogClient(
            self.msgr,
            lambda: mon_targets(self.osdmap, self.mon_addrs),
            f"osd.{osd_id}")
        if cephx is not None:
            from ceph_tpu.auth.cephx import TicketKeyring
            from ceph_tpu.auth.handshake import CephxConfig
            #: gen -> service key; validates peer/client tickets
            self._rotating: dict[int, str] = {}
            self._rotating_at = 0.0
            self.msgr.set_auth_cephx(CephxConfig(
                entity=cephx[0], key=cephx[1],
                keyring=TicketKeyring(self.mon_cmd.fetch_ticket),
                service="osd", rotating=lambda: self._rotating))
        self.msgr.set_policy("client", ConnectionPolicy.lossy_client())
        self.msgr.set_policy("osd", ConnectionPolicy.stateful_peer())
        self.msgr.set_policy("mon", ConnectionPolicy.stateful_peer())
        self.msgr.add_dispatcher_tail(self)
        self._addr = addr

        self.perf = (PerfCountersBuilder(f"osd.{osd_id}")
                     .add_u64("op_w").add_u64("op_r").add_u64("op_rep")
                     .add_u64("ec_encode_stripes").add_u64("recovery_pulls")
                     .add_u64("peering_rounds").add_u64("log_entries")
                     .add_u64("pg_splits")
                     .add_u64("ec_rmw_gather").add_u64("ec_rmw_pipelined")
                     .add_u64("ec_dispatch_submits")
                     .add_u64("ec_dispatch_commits")
                     .add_u64("ec_decode_submits")
                     .add_u64("recovery_decode_stripes")
                     .add_u64("map_epochs")
                     .add_u64("map_pgs_scanned")
                     .add_u64("map_pgs_changed")
                     .add_u64("qos_reservation_served")
                     .add_u64("qos_weight_served")
                     .add_u64("qos_limit_served")
                     .add_u64("scrub_objects")
                     .add_u64("scrub_inconsistent")
                     .add_u64("scrub_repaired")
                     .add_u64("scrub_repair_unverified")
                     .add_u64("scrub_digest_batches")
                     .add_u64("scrub_missing_peers")
                     .add_time_avg("op_w_latency")
                     .add_time_avg("map_scan_latency")
                     .add_time_avg("qos_wait")
                     .add_time_avg("scrub_chunk_latency")
                     .create_perf_counters())
        self.ctx.perf.add(self.perf)
        # the messenger's and store's own counter sets live in the same
        # collection: `perf dump` and the mgr report carry all of them
        self.ctx.perf.add(self.msgr.perf)
        if hasattr(self.store, "perf"):
            self.ctx.perf.add(self.store.perf)
        from ceph_tpu.common.op_tracker import OpTracker
        self.op_tracker = OpTracker(
            complaint_time=float(
                self.ctx.conf.get("osd_op_complaint_time")),
            daemon=f"osd.{osd_id}")
        self.ctx.admin.register_command(
            "dump_ops_in_flight",
            lambda **kw: self.op_tracker.dump_ops_in_flight(),
            "in-flight client ops with event timelines")
        self.ctx.admin.register_command(
            "dump_historic_ops",
            lambda **kw: self.op_tracker.dump_historic_ops(),
            "recently completed + slowest ops")
        self.ctx.admin.register_command(
            "osd map epoch", lambda **kw: {"epoch": self.osdmap.epoch},
            "current map epoch")
        self.ctx.admin.register_command(
            "pg dump", lambda **kw: self._pg_dump(), "pg states")

        # sharded op queue with mClock/dmClock QoS (osd/OSD.h ShardedOpWQ
        # over osd/mClock* + src/dmclock): ops shard by pgid, classes
        # arbitrate by reservation/weight/limit with distributed
        # (delta, rho) increments from the MOSDOp wire tags.  One worker
        # per shard keeps per-PG FIFO order.  "direct" executes on
        # dispatch threads (legacy/seed FIFO).
        from ceph_tpu.osd.op_queue import (
            DEFAULT_CLASSES, ClassInfo, ShardedOpQueue)
        self._use_opwq = str(self.ctx.conf.get("osd_op_queue")) == "mclock"
        # deep-scrub chunks and replica scrub-map ops schedule in the
        # background_best_effort class (the reference's mClockScheduler
        # class of the same name): weight/limit from the osd_scrub_*
        # knobs, never a reservation — background integrity runs in the
        # excess so tenant floors hold under a full-cluster scrub storm
        opwq_classes = {n: ClassInfo(c.reservation, c.weight, c.limit)
                        for n, c in DEFAULT_CLASSES.items()}
        opwq_classes[BACKGROUND_BEST_EFFORT] = ClassInfo(
            reservation=0.0,
            weight=float(self.ctx.conf.get(
                "osd_scrub_background_weight")),
            limit=float(self.ctx.conf.get(
                "osd_scrub_background_limit")))
        self._mclock_per_client = bool(int(
            self.ctx.conf.get("osd_mclock_per_client")))
        #: tenant lanes (osd_qos_tenant_lanes): client ops carrying an
        #: authenticated tenant tag schedule as client.<tenant> with
        #: the OSDMap qos_db's profile for that tenant
        self._qos_tenant_lanes = bool(
            self.ctx.conf.get("osd_qos_tenant_lanes"))
        self.ctx.conf.add_observer(
            "osd_qos_tenant_lanes",
            lambda _n, v: setattr(self, "_qos_tenant_lanes", bool(v)))
        self.opwq = (ShardedOpQueue(
            self._opwq_handle,
            n_shards=int(self.ctx.conf.get("osd_op_num_shards")),
            classes=opwq_classes,
            name=f"osd.{osd_id}",
            client_template=ClassInfo(
                reservation=float(self.ctx.conf.get(
                    "osd_mclock_client_reservation")),
                weight=float(self.ctx.conf.get(
                    "osd_mclock_client_weight")),
                limit=float(self.ctx.conf.get(
                    "osd_mclock_client_limit"))),
            max_client_backlog=int(self.ctx.conf.get(
                "osd_op_queue_max_client_backlog")),
            idle_timeout=float(self.ctx.conf.get(
                "osd_qos_idle_client_timeout")))
            if self._use_opwq else None)
        if self.opwq is not None:
            self.ctx.conf.add_observer(
                "osd_qos_idle_client_timeout",
                lambda _n, v: self.opwq.set_idle_timeout(float(v)))
        #: the qos_db snapshot currently folded into the scheduler
        self._qos_profiles_applied: dict = {}
        #: pool_id -> (mode, alg) last pushed to the objectstore
        self._pool_comp_applied: dict = {}
        self.ctx.admin.register_command(
            "dump_qos_stats", lambda **kw: self._dump_qos_stats(),
            "per-tenant dmclock accounting: backlog, phase-served "
            "counts, queue-wait totals, applied profiles")
        from ceph_tpu.ops import telemetry
        self.ctx.admin.register_command(
            "dump_tenant_usage",
            lambda **kw: telemetry.tenant_dump(),
            "tenant device-time ledger: per-tenant x engine x channel "
            "device-seconds apportioned from coalesced dispatch "
            "batches by stripe share, batch/request/stripe counts, "
            "queue-wait histograms, and share-of-device gauges "
            "(untagged work lands in the _untagged bucket)")
        self.ctx.admin.register_command(
            "dump_bluestore_stats",
            lambda **kw: telemetry.bluestore_dump(),
            "device-resident objectstore accounting: bluestore_data "
            "checksum batches vs scalar blocks, batched read "
            "verification, block-compression outcomes, and the KV "
            "journal truncation ledger")

        #: background-integrity accounting (dump_scrub_stats / the
        #: MMgrReport scrub tail / ceph_scrub_* prometheus families)
        self._scrub_lock = make_lock(f"OSD::scrub_stats({osd_id})")
        self._scrub_stats: dict = {
            "sweeps": 0, "pgs_scrubbed": 0, "objects_scrubbed": 0,
            "digest_batches": 0, "digest_objects": 0,
            "scalar_fallbacks": 0, "inconsistent": 0, "repaired": 0,
            "repair_unverified": 0, "missing_peer_scrubs": 0,
            "missing_peer_retries": 0, "last_sweep": {}}
        self._scrub_sweeping = False
        self._scrub_auto_last = time.time()
        self.ctx.admin.register_command(
            "dump_scrub_stats", lambda **kw: self._dump_scrub_stats(),
            "background-integrity accounting: sweep/PG/object counts, "
            "batched-digest vs scalar-fallback split, inconsistencies "
            "found / repairs verified / repairs unverified, "
            "missing-peer rounds, the last sweep's report, and the "
            "background_best_effort dmclock lane this daemon's scrub "
            "ops ride")

        # recovery reservations (AsyncReserver / osd_max_backfills): a PG
        # needs a slot before pulling; pulls run in a bounded window
        from ceph_tpu.osd.reserver import AsyncReserver
        self.local_reserver = AsyncReserver(
            int(self.ctx.conf.get("osd_max_backfills")),
            name=f"osd.{osd_id}")
        #: bytes queued in the op queue (osd_client_message_size_cap)
        self._op_throttle = Throttle(
            f"osd.{osd_id}-op-bytes",
            int(self.ctx.conf.get("osd_client_message_size_cap")))

        # cache-tier agent (PrimaryLogPG promote_object + TierAgent):
        # promotions and flush/evict run on their own thread — they
        # issue internal client ops that may land back on this OSD's own
        # shard workers, so they must never run ON a shard worker
        import queue as _queue
        self._ms_type = ms_type
        self._promoting: dict[tuple, list] = {}
        self._agent_tid = 0
        self._agent_q: "_queue.Queue" = _queue.Queue()
        self._internal_client = None
        self._agent_thread = threading.Thread(
            target=self._agent_loop, name=f"osd.{osd_id}-tier-agent",
            daemon=True)
        self._agent_thread.start()
        self.ctx.admin.register_command(
            "dump_reservations", lambda **kw: self.local_reserver.dump(),
            "recovery reservation slots")

    def _opwq_handle(self, klass: str, item, served=None) -> None:
        """Shard worker: run the dispatch handler bound at enqueue.
        The worker JOINS the op's trace (the dispatch thread's
        thread-local died at the queue boundary; the id lives on the
        message).  ``served`` is the dmclock (phase, queue-wait) pair:
        the phase is stamped onto the message for the reply's echo
        (client rho accounting) and counted in the qos perf set, and a
        traced op gets a ``qos_wait`` event so ``tracing show``
        explains a throttled op."""
        handler, msg, cost = item
        from ceph_tpu.common import tracing
        # parent under the rx dispatch span deliver() stored on the msg
        prev = tracing.set_current(getattr(msg, "trace_id", 0),
                                   getattr(msg, "parent_span_id", 0))
        try:
            if served is not None:
                phase, wait = served
                msg._qos_phase = phase
                if phase == PHASE_RESERVATION:
                    self.perf.inc("qos_reservation_served")
                elif phase == PHASE_WEIGHT:
                    self.perf.inc("qos_weight_served")
                elif phase == PHASE_LIMIT:
                    self.perf.inc("qos_limit_served")
                self.perf.tinc("qos_wait", wait)
                if tracing.current():   # untraced majority skips the
                    tracing.record(     # event formatting entirely
                        f"osd.{self.osd_id}",
                        f"qos_wait {wait * 1000.0:.2f}ms class={klass} "
                        f"phase={PHASE_NAMES.get(phase, phase)}")
            handler(msg)
        finally:
            tracing.set_current(prev)
            self._op_throttle.put(cost)

    def _client_class(self, msg) -> str:
        """dmclock class for a client op: the authenticated TENANT lane
        when the op carries one and osd_qos_tenant_lanes is on (the
        MOSDOp v4 qos_tenant tag the RGW front stamps — its profile
        comes from the OSDMap qos_db), else per-client tag streams when
        osd_mclock_per_client is on (mClockClientQueue), else one
        aggregate class (mClockOpClassQueue).

        Trust boundary: the tenant tag is client-asserted, like this
        reduction's client_id/epoch — the gateway (which authenticates
        the S3 principal) is the trusted stamper, and a direct rados
        client claiming another tenant's lane is equivalent to the
        pre-existing client_id spoof.  Binding tenants to cephx
        entity caps (the reference's osd cap profile machinery) is the
        hardening step when untrusted direct clients matter; operators
        running such clients today should leave per-client lanes on
        and keep osd_qos_tenant_lanes for gateway-fronted pools."""
        if self._qos_tenant_lanes:
            tenant = getattr(msg, "qos_tenant", "")
            if tenant:
                return f"client.{tenant}"
        if self._mclock_per_client:
            return f"client.{getattr(msg, 'client_id', 0)}"
        return "client"

    def _dump_qos_stats(self) -> dict:
        """Admin `dump_qos_stats`: the merged per-lane dmclock
        accounting plus the qos_db snapshot this daemon scheduled
        from."""
        if self.opwq is None:
            return {"queue": "direct", "classes": {},
                    "profiles": dict(self._qos_profiles_applied)}
        out = self.opwq.dump_qos()
        out["queue"] = "mclock"
        out["tenant_lanes"] = self._qos_tenant_lanes
        out["profiles"] = dict(self._qos_profiles_applied)
        return out

    def _qos_digest(self) -> dict:
        """Per-lane accounting digest for the MMgrReport v4 tail (the
        mgr qos_feed -> ceph_qos_* prometheus families): client lanes
        + the aggregate evicted rollup, totals only."""
        if self.opwq is None:
            return {}
        d = self.opwq.dump_qos()
        lanes = {}
        for name, row in d["classes"].items():
            lanes[name] = {"backlog": row["backlog"],
                           "served": row["served"],
                           "wait_sum_s": row["wait_sum_s"],
                           # cumulative LATENCY_BOUNDS buckets: the mgr
                           # slo module diffs these across report
                           # intervals for a windowed p99 per lane
                           "wait_buckets": row["wait_buckets"]}
        return {"lanes": lanes, "evicted": d["evicted"]}

    @staticmethod
    def _op_cost(msg) -> int:
        """Approximate queued-payload bytes (the data dominates)."""
        cost = 256
        for attr in ("data", "shard_data"):
            v = getattr(msg, attr, None)
            if v is not None:
                cost += len(v)
        for op in getattr(msg, "ops", ()) or ():
            cost += len(getattr(op, "data", b"") or b"")
        return cost

    def _enqueue_op(self, klass: str, shard_key, handler, msg) -> None:
        """Route through the sharded mClock queue (enqueue_op →
        op_shardedwq → dequeue_op), or run inline when disabled.

        Queued payload bytes ride a throttle (osd_client_message_size_cap
        semantics): the messenger's dispatch throttle releases the moment
        we enqueue, so without this a stuck shard would buffer peer
        pushes/writes without bound.  get() blocks the dispatch thread —
        exactly the backpressure the reference applies at the front door."""
        if self.opwq is not None:
            cost = min(self._op_cost(msg), self._op_throttle.max_amount)
            self._op_throttle.get(cost)
            if not self.opwq.enqueue(shard_key, klass,
                                     (handler, msg, cost),
                                     delta=getattr(msg, "qos_delta", 1),
                                     rho=getattr(msg, "qos_rho", 1)):
                # client backlog cap: refuse (no reply) — the client's
                # timeout resend retries once the shard drains
                self._op_throttle.put(cost)
                trk = getattr(msg, "_trk", None)
                if trk is not None:
                    trk.mark_event("refused: client backlog at cap")
                    trk.finish()
        else:
            handler(msg)

    def _pg_dump(self) -> dict:
        with self._lock:
            return {f"{p[0]}.{p[1]}": {
                "state": pg.state, "last_update": list(pg.info.last_update),
                "log_len": len(pg.log), "missing": len(pg.missing),
                "up": pg.up, "primary": pg.primary}
                for p, pg in self.pgs.items()}

    # -- lifecycle (OSD::init, ceph_osd.cc main) ------------------------------

    def init(self) -> None:
        self.store.mkfs_if_needed()
        self.store.mount()
        self._load_pgs()
        self.msgr.bind(self._addr)
        self.msgr.start()
        if self._cephx is not None:
            # validation material BEFORE peers/clients connect
            self._refresh_rotating()
        self._maybe_reboot()
        if self._heartbeats:
            self._schedule_heartbeat()
        self._schedule_tick()

    def shutdown(self) -> None:
        self._stop = True
        if self._hb_timer:
            self._hb_timer.cancel()
        if self._tick_timer:
            self._tick_timer.cancel()
        if self.opwq is not None:
            self.opwq.shutdown()
        self._agent_q.put(None)
        if self._internal_client is not None:
            self._internal_client.shutdown()
        # drain in-flight async EC commits while the messenger and
        # store are still up (continuations fan out shards and reply),
        # then stop the engine's threads.  Only when the ctx is ours:
        # a caller-supplied context may serve other daemons.  Stragglers
        # submitting after stop() run inline, so nothing can hang.
        # decode first: its continuations (recovery re-encode, rmw
        # drain) submit into the encode engine, which must still be
        # live to take them; encode-side stragglers after its own stop
        # run inline, so nothing can hang either way
        engines = ([("decode", self.ctx._decode_dispatch),
                    ("dispatch", self.ctx._dispatch)]
                   if self._own_ctx else [])
        for ename, eng in engines:
            if eng is None:
                continue
            try:
                drained = eng.flush(timeout=5.0)
            except Exception as e:
                # a WEDGED engine raises (its waiters were already
                # failed loudly with EngineWedgedError): shutdown
                # proceeds — there is nothing left to drain
                dout("osd", 0, "osd.%d shutdown: %s engine wedged: "
                     "%r", self.osd_id, ename, e)
                drained = True
            if not drained:
                dout("osd", 0, "osd.%d shutdown: %s engine did "
                     "not drain in 5s — in-flight EC completions may "
                     "land on the unmounted store and be dropped",
                     self.osd_id, ename)
            if not eng.stop():
                dout("osd", 0, "osd.%d shutdown: %s engine "
                     "thread(s) still live past join timeout",
                     self.osd_id, ename)
        self.msgr.shutdown()
        # store LAST: a bluestore commit during the drain window above
        # runs its bluestore_data digest inline on a stopped engine
        # (or scalar on failure), so umount never races a pending batch
        self.store.umount()

    # -- tick (OSD::tick analog: watchdog for stuck peering/recovery) ---------

    TICK_INTERVAL = 0.5
    STUCK_AFTER = 2.0

    def _schedule_tick(self) -> None:
        if self._stop:
            return
        self._tick_timer = threading.Timer(self.TICK_INTERVAL, self._tick)
        self._tick_timer.daemon = True
        self._tick_timer.start()

    def _mgr_report(self) -> None:
        # the map's active-mgr record (MgrMap) wins; the static
        # constructor address is the pre-mgr_db fallback
        mgr_db = self.osdmap.mgr_db or {}
        mgr_addr = mgr_db.get("addr") or self.mgr_addr
        if not mgr_addr:
            return
        mgr_name = mgr_db.get("active_name", "mgr.0")
        try:
            mgr_rank = int(mgr_name.split(".")[1])
        except (IndexError, ValueError):
            mgr_rank = 0
        from ceph_tpu.mgr import MMgrReport
        states: dict[str, int] = {}
        n_obj = n_bytes = 0
        with self._lock:
            for pg in self.pgs.values():
                states[pg.state] = states.get(pg.state, 0) + 1
        per_cid: dict[str, tuple[int, int]] = {}
        for cid in self.store.list_collections():
            c_obj = c_bytes = 0
            try:
                for oid in self.store.list_objects(cid):
                    if oid.startswith(PG.PGMETA):
                        continue
                    c_obj += 1
                    c_bytes += self.store.stat(cid, oid)["size"]
            except KeyError:
                continue
            per_cid[cid] = (c_obj, c_bytes)
            n_obj += c_obj
            n_bytes += c_bytes
        # per-PG stat records for the PGs this osd leads (pg_stat_t
        # reduced): state, acting set, store usage, log bounds — the
        # mgr's `pg dump` / `pg ls` truth
        pg_stats: dict[str, dict] = {}
        with self._lock:
            pgids = list(self.pgs)
        for pgid in pgids:
            pool = self.osdmap.pools.get(pgid[0])
            if pool is None or not (0 <= pgid[1] < pool.pg_num):
                continue
            _up, primary = self._pg_members(pgid)
            if primary != self.osd_id:
                continue
            with self._lock:
                pg = self.pgs.get(pgid)
                if pg is None:
                    continue
                c_obj, c_bytes = per_cid.get(self._pg_cid(pgid), (0, 0))
                tail = (pg.log.entries[0].version if pg.log.entries
                        else pg.log.head)
                pg_stats[f"{pgid[0]}.{pgid[1]}"] = {
                    "state": pg.state, "up": list(pg.up),
                    "num_objects": c_obj, "bytes": c_bytes,
                    "missing": len(pg.missing),
                    "log_size": len(pg.log.entries),
                    "log_head": pg.log.head, "log_tail": tail}
        counters = dict(self.perf._u64)
        # v4 tail: completed slow traces (tail-sampled span trees),
        # historic slow-op digests, and the pipeline-profile phase
        # digest — the mgr insights module's feed
        from ceph_tpu.common import tracing
        from ceph_tpu.ops import telemetry
        con = self.msgr.connect_to(mgr_addr, EntityName("mgr", mgr_rank))
        con.send_message(MMgrReport(
            osd_id=self.osd_id, counters=counters, pg_states=states,
            num_objects=n_obj, bytes_used=n_bytes, pg_stats=pg_stats,
            perf=self.ctx.perf.dump(),
            slow_traces=tracing.slow_trace_digests(),
            slow_ops=self.op_tracker.slow_digests(),
            profile=telemetry.pipeline_profile_digest(),
            qos=self._qos_digest(),
            faults=self.ctx.fault_digest(),
            scrub=self._scrub_digest_report(),
            tenant_usage=telemetry.tenant_usage_digest()))

    ROTATING_REFRESH = 60.0

    def _refresh_rotating(self) -> None:
        keys = self.mon_cmd.fetch_rotating("osd")
        if keys is not None:
            self._rotating = keys
            self._rotating_at = time.time()

    def _tick(self) -> None:
        try:
            now = time.time()
            self._maybe_reboot()
            if self._cephx is not None \
                    and now - self._rotating_at > self.ROTATING_REFRESH:
                self._rotating_at = now     # before: no retry storm
                try:
                    self._refresh_rotating()
                except (OSError, TimeoutError):
                    pass
            self._renew_map_subscription(now)
            self._agent_scan(now)
            self._maybe_auto_scrub(now)
            self._mgr_report()
            self.clog.flush()
            # PG state summary to the mons (MPGStats flow): feeds the
            # PG_DEGRADED health check
            states, degraded = self._pg_stats_summary()
            self._send_to_mons(lambda: MPGStats(
                osd_id=self.osd_id, states=states,
                degraded_objects=degraded, stamp=now))
            for warn in self.op_tracker.check_ops_in_flight():
                dout("osd", 1, "osd.%d %s", self.osd_id, warn)
            with self._lock:
                pgs = list(self.pgs.values())
                # rmw gathers have no client resend to rescue them: a
                # lost shard-read reply would wedge the object behind
                # pg.rmw forever — time them out here
                stuck_rmw = [
                    (gid, st) for gid, st in self._ec_reads.items()
                    if st["kind"] == "rmw"
                    and now - st.get("started", now) > 8.0]
                for gid, st in stuck_rmw:
                    self._ec_reads.pop(gid, None)
                    # fail atomically under this lock (see _rmw_fail):
                    # releasing first would let a new write reclaim the
                    # gate ahead of the queued older writes
                    self._rmw_fail(st)
                # a pending-write gate whose commits all landed but
                # whose release was lost (a continuation died mid-
                # commit) would wedge the object's readers forever:
                # reap it defensively.  Gates with commits still in
                # flight are left alone — the engine always resolves
                # its futures, so the last continuation releases them
                wpend_waiting: list = []
                for gid, st in [
                        (g, s) for g, s in self._ec_reads.items()
                        if s.get("kind") == "wpend"
                        and not s.get("pending")
                        and now - s.get("started", now) > 8.0]:
                    self._ec_reads.pop(gid, None)
                    wpg = self.pgs.get(st["pgid"])
                    if wpg is not None:
                        if wpg.rmw.get(st["oid"]) == gid:
                            wpg.rmw.pop(st["oid"], None)
                        # parked pipelined writes re-dispatch before the
                        # waiting readers — they arrived first, and the
                        # release path (_ec_write_committed) keeps that
                        # per-object order too
                        wpend_waiting.extend(
                            m for m, _op in st.get("queue") or [])
                        wpend_waiting.extend(
                            wpg.waiting_for_missing.pop(st["oid"], []))
                # a dead watcher never acks: expire its notifies so the
                # notifier gets its reply instead of a client timeout
                stale_notifies = [
                    nid for nid, st in self._notifies.items()
                    if now - st.get("started", now) > 5.0]
                expired = [self._notifies.pop(nid)
                           for nid in stale_notifies]
            for st in expired:
                m = st["msg"]
                self._op_send_reply(m, MOSDOpReply(
                    tid=m.tid, result=0, epoch=self.osdmap.epoch))
            for m in wpend_waiting:
                self._handle_op(m)
            for pg in pgs:
                self._tick_pg(pg, now)
        finally:
            self._schedule_tick()

    def _send_to_mons(self, make_msg) -> None:
        """Send make_msg() to every monitor (reports are idempotent; the
        leader executes, peons ignore).  Targets follow the COMMITTED
        monmap when one exists, so runtime `mon add/rm` re-points the
        daemon without a restart."""
        from ceph_tpu.common.moncmd import mon_targets
        for rank, addr in mon_targets(self.osdmap, self.mon_addrs):
            mon = self.msgr.connect_to(addr, EntityName("mon", rank))
            mon.send_message(make_msg())

    def _renew_map_subscription(self, now: float,
                                force: bool = False) -> None:
        """Periodically re-subscribe to the mon map stream (the
        reference's MonClient renews subscriptions on an interval).  The
        subscription carries our epoch, so a renewal from a current osd
        costs the mon nothing; a stale osd — one that missed a commit
        push in a connection hiccup — gets the map and converges instead
        of monitoring peers against a stale view forever.  Forced
        renewals (epoch gossip hits) keep a small floor so a ping storm
        from many peers collapses into one subscribe."""
        interval = float(self.ctx.conf.get("osd_map_renew_interval"))
        floor = min(0.25, interval) if force else interval
        if now - self._last_sub_renew < floor:
            return
        self._last_sub_renew = now
        self._send_to_mons(lambda: MMonSubscribe(
            name=str(self.whoami), addr=self.msgr.my_addr,
            epoch=self.osdmap.epoch))

    def _maybe_reboot(self) -> None:
        """Re-send MOSDBoot until the map shows us up at our address —
        the first boot can race the monitor election/bootstrap
        (OSD::start_boot retry semantics)."""
        m = self.osdmap
        booted = (m.epoch > 0 and m.is_up(self.osd_id)
                  and self.osd_id < len(m.osd_addrs)
                  and m.osd_addrs[self.osd_id] == self.msgr.my_addr)
        if booted:
            return
        self._renew_map_subscription(time.time(), force=True)
        self._send_to_mons(lambda: MOSDBoot(osd_id=self.osd_id,
                                            addr=self.msgr.my_addr))

    def _tick_pg(self, pg: PG, now: float) -> None:
        restart = False
        repulls: list[str] = []
        flush: list = []
        with self._lock:
            # defensive: re-dispatch waiters whose block condition cleared
            if pg.state == STATE_ACTIVE:
                for oid in list(pg.waiting_for_missing):
                    if not self._blocked_on_recovery(pg, oid, True, True):
                        flush.extend(pg.waiting_for_missing.pop(oid))
                if pg.waiting_for_active:
                    flush.extend(pg.waiting_for_active)
                    pg.waiting_for_active = []
        for m in flush:
            self._handle_op(m)
        with self._lock:
            if (pg.primary == self.osd_id
                    and pg.state in (STATE_GETINFO, STATE_GETLOG)
                    and now - pg.peering_started > self.STUCK_AFTER):
                restart = True   # a query/notify was lost; re-run the round
            elif (pg.primary == self.osd_id
                    and pg.state == STATE_INACTIVE
                    and (pg.waiting_for_active or pg.waiting_for_missing)
                    and now - pg.peering_started > self.STUCK_AFTER):
                # ops parked on a primary that never started (or lost)
                # its peering round — e.g. an op racing a pg-split scan
                # under load: kick the round rather than strand them
                restart = True
            elif pg.state == STATE_RECOVERING:
                # drop stuck pulls; the window refill below re-issues them
                for oid, started in list(pg.recovering.items()):
                    if now - started > self.STUCK_AFTER:
                        del pg.recovering[oid]
                        repulls.append(oid)
        if restart:
            self._start_peering(pg, pg.up, pg.primary)
            return
        if pg.state == STATE_RECOVERING:
            if self.local_reserver.has(pg.pgid):
                if repulls or pg.missing:
                    self._start_recovery_ops(pg)
            else:
                # reservation lost (e.g. restored-from-disk state or a
                # cancelled slot): re-request it
                self.local_reserver.request(
                    pg.pgid, lambda: self._start_recovery_ops(pg))

    def _load_pgs(self) -> None:
        """Rebuild in-memory PG state from persisted pgmeta
        (OSD::load_pgs analog)."""
        for cid in self.store.list_collections():
            parts = cid.split(".")
            if len(parts) != 2:
                continue
            try:
                pgid = (int(parts[0]), int(parts[1]))
            except ValueError:
                continue
            try:
                meta = self.store.omap_get(cid, PG.PGMETA)
            except KeyError:
                continue
            pg = PG(pgid)
            info_blob = meta.get("info")
            if info_blob:
                pg.info = PG.decode_info(info_blob)
            entries = [PG.decode_entry(v) for k, v in sorted(meta.items())
                       if k.startswith("log.")]
            pg.log.copy_from(entries)
            missing_blob = meta.get("missing")
            if missing_blob:
                pg.decode_missing(missing_blob)
            pg.next_seq = pg.log.head[1]
            num_blob = meta.get("pg_num")
            pg.split_num = (int(num_blob.decode()) if num_blob else 0)
            self.pgs[pgid] = pg
            dout("osd", 10, "osd.%d loaded pg %s: %d entries, head %s",
                 self.osd_id, cid, len(entries), pg.log.head)

    # -- map handling ---------------------------------------------------------

    def _handle_map(self, msg: MOSDMapMsg) -> None:
        with self._lock:
            newmap, gapped = advance_map(self.osdmap, msg)
            if newmap is None and not gapped:
                return
            if newmap is not None:
                oldmap = self.osdmap
                self.osdmap = newmap
                self._codecs.clear()
        if gapped:
            # we were down across trimmed epochs: request a backfill
            # (OSD::handle_osd_map request_full analog)
            self._renew_map_subscription(time.time(), force=True)
            return
        dout("osd", 5, "osd.%d got map epoch %d", self.osd_id, newmap.epoch)
        self._apply_config_db(newmap)
        self._apply_qos_db(newmap)
        self._apply_pool_compression(newmap)
        self._split_pgs(newmap)
        upd = None
        if self._map_shared:
            # advance the shared cache (daemons on one context share a
            # single table build; a burst computes only the newest
            # epoch) and take the exact changed-PG delta from OUR old
            # epoch so the scan below is O(changed + local)
            try:
                upd = self.ctx.mapping_service().update_to(
                    newmap, from_epoch=oldmap.epoch)
            except Exception as e:   # cache is an optimization, never a wall
                dout("osd", 1, "osd.%d mapping service update failed, "
                     "falling back to scalar scan: %r", self.osd_id, e)
        del oldmap
        self.perf.inc("map_epochs")
        t_scan = time.time()
        self._scan_pgs(upd)
        self.perf.tinc("map_scan_latency", time.time() - t_scan)
        with self._lock:
            waiting = [m for m in self._waiting_for_map
                       if m.epoch <= newmap.epoch]
            self._waiting_for_map = [m for m in self._waiting_for_map
                                     if m.epoch > newmap.epoch]
            subops = self._waiting_subops
            self._waiting_subops = []
        for m in waiting:
            self._handle_op(m)
        for handler, m in subops:
            handler(m)

    def _apply_config_db(self, m: OSDMap) -> None:
        """Fold the map's central config-db into this daemon's config
        at the "mon" source layer (ConfigMonitor push -> md_config_t
        observers): global < osd < osd.N precedence, with retraction
        when a key leaves the db."""
        desired: dict[str, str] = {}
        for section in ("global", "osd", f"osd.{self.osd_id}"):
            desired.update(m.config_db.get(section, {}))
        applied = getattr(self, "_mon_config_applied", set())
        for name in applied - set(desired):
            try:
                self.ctx.conf.rm(name, "mon")
            except (KeyError, ValueError):
                pass
        for name, value in desired.items():
            try:
                self.ctx.conf.set(name, value, source="mon")
            except (KeyError, ValueError):
                dout("osd", 5, "osd.%d ignoring unknown config %s",
                     self.osd_id, name)
        self._mon_config_applied = set(desired)

    def _apply_qos_db(self, m: OSDMap) -> None:
        """Fold the map's per-tenant QoS profiles into the scheduler
        (`ceph qos set/rm` -> qos_db -> every OSD's mClock lanes): the
        dmclock class for tenant T is client.T, so a tenant's
        reservation/weight/limit apply the moment its map lands —
        including to lanes already backlogged."""
        if self.opwq is None or m.qos_db == self._qos_profiles_applied:
            return
        from ceph_tpu.osd.op_queue import ClassInfo
        from ceph_tpu.qos.dmclock import profiles_from_db
        profiles = {
            f"client.{tenant}": ClassInfo(reservation=p.reservation,
                                          weight=p.weight,
                                          limit=p.limit)
            for tenant, p in profiles_from_db(m.qos_db).items()}
        self.opwq.set_client_profiles(profiles)
        self._qos_profiles_applied = dict(m.qos_db)
        dout("osd", 5, "osd.%d applied qos_db (%d tenants)",
             self.osd_id, len(profiles))

    def _apply_pool_compression(self, m: OSDMap) -> None:
        """Push the map's per-pool compression opts (`osd pool set <p>
        compression_mode aggressive`) down to the objectstore; only
        bluestore exposes the hook."""
        setter = getattr(self.store, "set_pool_compression", None)
        if setter is None:
            return
        for pool_id, pool in m.pools.items():
            mode = getattr(pool, "compression_mode", "")
            alg = getattr(pool, "compression_algorithm", "")
            applied = self._pool_comp_applied.get(pool_id)
            if applied != (mode, alg):
                setter(pool_id, mode, alg)
                self._pool_comp_applied[pool_id] = (mode, alg)
        for pool_id in list(self._pool_comp_applied):
            if pool_id not in m.pools:
                setter(pool_id, "", "")
                del self._pool_comp_applied[pool_id]

    def _pg_stats_summary(self) -> tuple[dict, int]:
        """(state -> count over primary PGs, degraded object count).

        Primaries are judged against the CURRENT map, not the cached
        pg.primary: a PG remapped away leaves a stale local object in
        state "inactive" that must not count as degraded forever."""
        states: dict[str, int] = {}
        degraded = 0
        with self._lock:
            pgids = list(self.pgs)
        for pgid in pgids:
            pool = self.osdmap.pools.get(pgid[0])
            if pool is None or not (0 <= pgid[1] < pool.pg_num):
                continue
            _up, primary = self._pg_members(pgid)
            if primary != self.osd_id:
                continue
            with self._lock:
                pg = self.pgs.get(pgid)
                if pg is None:
                    continue
                states[pg.state] = states.get(pg.state, 0) + 1
                degraded += len(pg.missing)
                for ps in pg.peers.values():
                    degraded += len(ps.missing)
        return states, degraded

    def _pg_cid(self, pgid) -> str:
        return f"{pgid[0]}.{pgid[1]}"

    def _get_pg(self, pgid) -> PG:
        with self._lock:
            pg = self.pgs.get(pgid)
            if pg is None:
                pg = PG(pgid)
                pool = self.osdmap.pools.get(pgid[0])
                pg.split_num = pool.pg_num if pool else 0
                self.pgs[pgid] = pg
                cid = self._pg_cid(pgid)
                if cid not in self.store.list_collections():
                    self.store.apply_transaction(
                        Transaction().create_collection(cid)
                        .touch(cid, PG.PGMETA)
                        .omap_setkeys(cid, PG.PGMETA, {
                            "pg_num": str(pg.split_num).encode()}))
            return pg

    def _split_pending(self, pool_id: int) -> bool:
        """True while some local PG of the pool has not been split to the
        current pg_num — the window between installing a grown map and
        _split_pgs finishing.  Caller holds self._lock."""
        pool = self.osdmap.pools.get(pool_id)
        if pool is None:
            return False
        return any(pgid[0] == pool_id
                   and 0 < pg.split_num < pool.pg_num
                   for pgid, pg in self.pgs.items())

    def _park_subop(self, handler, msg, pool) -> bool:
        """Park an inter-OSD op that references a PG layout our map or
        local splits have not reached yet (require_same_or_newer_map
        analog): a child pgid beyond our pg_num means the sender runs a
        newer map; a pending split means applying now would target the
        pre-split collection.  Parked ops replay after the next map's
        split+scan completes."""
        with self._lock:
            if (msg.pgid[1] >= pool.pg_num
                    or self._split_pending(msg.pgid[0])):
                if len(self._waiting_subops) < 10000:
                    self._waiting_subops.append((handler, msg))
                return True
        return False

    def _pgls_field(self, cid: str, ec: bool) -> "OSDOpField":
        """One PG's client-visible object names (PrimaryLogPG do_pg_op
        PGNLS): store names reduce to the base (snap clones and EC
        shard suffixes stripped), LENGTH-PREFIX encoded — names may
        contain any byte, including newlines."""
        try:
            raw = self.store.list_objects(cid)
        except KeyError:
            raw = []
        names = sorted({self._base_oid(o, ec) for o in raw
                        if not o.startswith(PG.PGMETA)
                        and CLONE_SEP not in o})
        enc = Encoder()
        enc.list(names, lambda e, n: e.str(n))
        return OSDOpField(OP_PGLS, 0, len(names), enc.tobytes())

    @staticmethod
    def _base_oid(oid: str, ec: bool) -> str:
        """Logical object name of a store object: strips the CLONE_SEP
        snap-clone suffix and, on EC pools, the ":shard" suffix — the
        name the client hashed to place the object.  The shard strip is
        safe for client names containing ":" because the OSD appends
        exactly one suffix and rpartition takes the rightmost."""
        base = oid.split(CLONE_SEP, 1)[0]
        if ec and ":" in base:
            head, _, tail = base.rpartition(":")
            if tail.isdigit():
                return head
        return base

    def _split_pgs(self, newmap: OSDMap) -> None:
        """Split local PGs whose persisted pg_num watermark is behind the
        pool's (PG::split_into, src/osd/PG.cc:2575; collection split via
        the store-level collection_move primitive, os/ObjectStore.h
        split_collection).

        Driven by the per-PG "pg_num" watermark in pgmeta, NOT by a map
        diff: an OSD that was down across the pg_num change boots
        straight into the new map with no old map to compare, and its
        unsplit PGs (stale logs still interleaving the children's
        entries) would diverge from every peer's trimmed history.  The
        watermark also collapses multi-step growth seen at once
        (8->16->32 while down) into a single partition by the final
        pg_num.

        Children adopt the objects, log entries and missing-set items
        whose placement seed maps to them under the new pg_num; every
        replica computes the identical partition (it is a pure function
        of object names), so peering after the split converges exactly
        as before it.  With pgp_num unchanged, a child's placement seed
        stable_mod's back to its parent's, so children start colocated
        with their parents and data only moves when pgp_num is raised —
        the reference's two-step semantics."""
        for pool_id, pool in newmap.pools.items():
            with self._lock:
                # a pgmeta without the watermark predates the split
                # feature, when pg_num was immutable — such a store is by
                # definition already consistent with the pg_num it was
                # created under; adopt the current one (backfill, never
                # exempt: a zero watermark would skip every future split)
                legacy = [pgid for pgid in self.pgs
                          if pgid[0] == pool_id
                          and self.pgs[pgid].split_num == 0]
                for pgid in legacy:
                    self.pgs[pgid].split_num = pool.pg_num
                    self.store.apply_transaction(
                        Transaction().touch(self._pg_cid(pgid), PG.PGMETA)
                        .omap_setkeys(self._pg_cid(pgid), PG.PGMETA,
                                      {"pg_num":
                                       str(pool.pg_num).encode()}))
                stale = [(pgid, self.pgs[pgid].split_num)
                         for pgid in self.pgs
                         if pgid[0] == pool_id
                         and 0 < self.pgs[pgid].split_num < pool.pg_num
                         and pgid[1] < self.pgs[pgid].split_num]
            for pgid, old_num in sorted(stale):
                children = [c for c in range(old_num, pool.pg_num)
                            if pg_to_pgid(c, old_num) == pgid[1]]
                if children:
                    self._split_one(pgid, children, pool)
                else:
                    with self._lock:
                        pg = self.pgs.get(pgid)
                        if pg is not None:
                            pg.split_num = pool.pg_num
                            self.store.apply_transaction(
                                Transaction().omap_setkeys(
                                    self._pg_cid(pgid), PG.PGMETA,
                                    {"pg_num":
                                     str(pool.pg_num).encode()}))

    def _split_one(self, pgid, children: list[int], pool) -> None:
        pool_id, pnum = pgid
        ec = pool.is_erasure()
        new_num = pool.pg_num
        with self._lock:
            parent = self.pgs.get(pgid)
            if parent is None:
                return
            pcid = self._pg_cid(pgid)
            t = Transaction()
            child_cids = {}
            for c in children:
                ccid = self._pg_cid((pool_id, c))
                child_cids[c] = ccid
                if ccid not in self.store.list_collections():
                    t.create_collection(ccid)
                t.touch(ccid, PG.PGMETA)

            def target_of(oid: str) -> int:
                return pg_to_pgid(
                    ceph_str_hash_rjenkins(self._base_oid(oid, ec)),
                    new_num)

            # 1) objects: move every store object whose seed now maps to
            # a child (snap clones and EC shards travel with their base)
            moved = 0
            for oid in self.store.list_objects(pcid):
                if oid.startswith(PG.PGMETA):
                    continue
                tgt = target_of(oid)
                if tgt != pnum:
                    t.collection_move(pcid, oid, child_cids[tgt])
                    moved += 1

            # 2) log + missing: partition by the same function
            child_pgs: dict[int, PG] = {}
            for c in children:
                cpg = self.pgs.get((pool_id, c))
                if cpg is None:
                    cpg = PG((pool_id, c))
                    self.pgs[(pool_id, c)] = cpg
                child_pgs[c] = cpg
            keep_entries, moved_keys = [], []
            child_entries: dict[int, list] = {c: [] for c in children}
            for e in parent.log.entries:
                tgt = target_of(e.oid)
                if tgt == pnum:
                    keep_entries.append(e)
                else:
                    child_entries[tgt].append(e)
                    moved_keys.append(PG.log_key(e.version))
            parent.log.copy_from(keep_entries)
            for c, cpg in child_pgs.items():
                cpg.log.copy_from(child_entries[c])
                # both sides keep the parent's last_update (PG::split_into
                # copies info); new writes use the current (bumped) epoch,
                # so version monotonicity holds on both
                cpg.info.last_update = parent.info.last_update
                cpg.info.last_epoch_started = \
                    parent.info.last_epoch_started
                cpg.info.past_up = [list(iv)
                                    for iv in parent.info.past_up]
                cpg.missing = {o: m for o, m in parent.missing.items()
                               if target_of(o) == c}
                cpg.state = STATE_INACTIVE
            parent.missing = {o: m for o, m in parent.missing.items()
                              if target_of(o) == pnum}
            parent.info.last_complete = parent.complete_to()

            # 3) in-flight writes against the pre-split layout die here:
            # repops requeue their client op (post-split dispatch dedups
            # against the log), EC rmw gathers tear down with the gate
            # (the same on_change teardown _start_peering does)
            stale_infs = [rid for rid, inf in self._in_flight.items()
                          if inf.msg.pgid == pgid]
            for rid in stale_infs:
                inf = self._in_flight.pop(rid)
                trk = getattr(inf.msg, "_trk", None)
                if trk is not None:
                    trk.mark_event("repop torn down: pg split")
                parent.waiting_for_active.append(inf.msg)
            parent.rmw.clear()
            dead = [gid for gid, st in self._ec_reads.items()
                    if st["kind"] in ("rmw", "wpend")
                    and st["pgid"] == pgid]
            for gid in dead:
                self._requeue_rmw_state(self._ec_reads.pop(gid, None),
                                        parent)

            # queued ops whose object moved: requeue on the child (the
            # client also resends on the map change; the log dedups)
            for c, cpg in child_pgs.items():
                keep_waiting = []
                for m in parent.waiting_for_active:
                    (cpg.waiting_for_active
                     if target_of(m.oid) == c else keep_waiting).append(m)
                parent.waiting_for_active = keep_waiting
            for o in list(parent.waiting_for_missing):
                tgt = target_of(o)
                if tgt != pnum:
                    child_pgs[tgt].waiting_for_missing.setdefault(
                        o, []).extend(parent.waiting_for_missing.pop(o))

            # 4) persist the whole split atomically: child metadata, the
            # object moves, and the parent's trimmed log in ONE txn
            parent.split_num = new_num
            if moved_keys:
                t.omap_rmkeys(pcid, PG.PGMETA, moved_keys)
            t.omap_setkeys(pcid, PG.PGMETA, {
                "info": parent.encode_info(),
                "missing": parent.encode_missing(),
                "pg_num": str(new_num).encode()})
            for c, cpg in child_pgs.items():
                cpg.split_num = new_num
                ccid = child_cids[c]
                keys = {"info": cpg.encode_info(),
                        "missing": cpg.encode_missing(),
                        "pg_num": str(new_num).encode()}
                for e in cpg.log.entries:
                    keys[PG.log_key(e.version)] = PG.encode_entry(e)
                t.omap_setkeys(ccid, PG.PGMETA, keys)
            # the parent re-peers (cheap: same membership) so its
            # requeued ops flush at activation; children peer as new PGs
            parent.state = STATE_INACTIVE
            self.store.apply_transaction(t)
            self.perf.inc("pg_splits")
            dout("osd", 3, "osd.%d split pg %s into %d children "
                 "(%d objects moved)", self.osd_id, pgid, len(children),
                 moved)

    def _scan_pgs(self, upd=None) -> None:
        """On every new map: (re)start peering for PGs whose membership
        changed (the map-change edge of the peering statechart).

        With a MapUpdate delta from the shared mapping service, only
        the changed PGs plus every locally-held PG (current members AND
        strays — their notify/teardown edges depend on OUR state, not
        the map diff) are examined, and each read is a cached-raw
        pipeline tail — O(changed + local) host work instead of
        O(cluster PGs) scalar CRUSH.  Without a delta (shared cache
        off, first map, or a chain gap) every PG is walked as before."""
        m = self.osdmap
        if upd is not None and not upd.full:
            scan = set(upd.changed)
            scan.update(self.pgs.keys())
            pgids = sorted(scan)
            self.perf.inc("map_pgs_changed", len(upd.changed))
        else:
            pgids = [(pool_id, pgnum)
                     for pool_id, pool in m.pools.items()
                     for pgnum in range(pool.pg_num)]
        self.perf.inc("map_pgs_scanned", len(pgids))
        for pool_id, pgnum in pgids:
            pool = m.pools.get(pool_id)
            if pool is None or not (0 <= pgnum < pool.pg_num):
                continue   # locally-held PG of a deleted/shrunk pool
            up, _upp, _acting, primary = \
                self._pg_mapping(pool_id, pgnum)
            pgid = (pool_id, pgnum)
            if self.osd_id not in up:
                pg = self.pgs.get(pgid)
                if pg and pg.state != STATE_INACTIVE:
                    pg.state = STATE_INACTIVE
                    # no longer a member: a held/queued recovery slot
                    # must not leak (it would wedge every later PG)
                    self.local_reserver.cancel(pgid)
                # stray notify (PG stray semantics): we hold data for
                # a PG we are no longer (or never were) up for.  The
                # new primary may have NOTHING — a child remapped
                # onto fresh OSDs after pgp_num grew, or a wide
                # reshuffle — and only learns prior holders from
                # these notifies.
                if (pg is not None and primary != self.osd_id
                        and primary != CEPH_NOSD
                        and (pg.log.entries
                             or pg.info.last_update > EVERSION_ZERO)):
                    con = self._osd_con(primary)
                    if con:
                        con.send_message(MOSDPGNotify(
                            pgid=pgid,
                            info=self._advertised_info(pg),
                            epoch=m.epoch, from_osd=self.osd_id))
                continue
            pg = self._get_pg(pgid)
            if pg.up != up or pg.primary != primary \
                    or pg.state == STATE_INACTIVE:
                self._start_peering(pg, up, primary)

    def _pg_mapping(self, pool_id: int, pgnum: int
                    ) -> tuple[list[int], int, list[int], int]:
        """(up, up_primary, acting, acting_primary) for one PG — from
        the shared mapping cache when enabled (falls back to the
        scalar oracle on any epoch/object mismatch), else scalar."""
        if self._map_shared:
            return self.ctx.mapping_service().lookup(
                self.osdmap, pool_id, pgnum)
        return self.osdmap.pg_to_up_acting_osds(pool_id, pgnum)

    def _start_peering(self, pg: PG, up: list[int], primary: int) -> None:
        # interval change: the old interval's recovery slot is void
        self.local_reserver.cancel(pg.pgid)
        with self._lock:
            if pg.up and pg.up != up:
                self._merge_past_up(pg, [pg.up], new_up=up)
            pg.up = list(up)
            pg.primary = primary
            pg.peering_epoch = self.osdmap.epoch
            pg.peering_started = time.time()
            # drop strays the map says are gone: a dead stray with the
            # best last_update would otherwise be chosen as the GETLOG
            # target forever and wedge peering
            pg.strays = {o: i for o, i in pg.strays.items()
                         if self.osdmap.exists(o) and self.osdmap.is_up(o)}
            pg.peers = {o: PeerState(info=i)
                        for o, i in pg.strays.items() if o not in up}
            pg.recovering.clear()
            # interval change: in-flight rmw gathers die with the gate;
            # their client ops requeue (re-executed post-activation)
            pg.rmw.clear()
            dead = [gid for gid, st in self._ec_reads.items()
                    if st["kind"] in ("rmw", "wpend")
                    and st["pgid"] == pg.pgid]
            for gid in dead:
                self._requeue_rmw_state(
                    self._ec_reads.pop(gid, None), pg,
                    event="rmw gather torn down: interval change")
            # ops queued against the old interval: requeue for re-check
            # after this round settles (clients also resend on map change)
            for ops in pg.waiting_for_missing.values():
                pg.waiting_for_active.extend(ops)
            pg.waiting_for_missing.clear()
            # in-flight repops waiting on replicas from the OLD interval
            # would hang forever on a dead peer's ack; the entry is in
            # our log, peering converges the new replicas from it, so
            # requeue the client op — post-activation it dedups against
            # the log and acks (PrimaryLogPG on_change repop teardown)
            stale_infs = [rid for rid, inf in self._in_flight.items()
                          if inf.msg.pgid == pg.pgid]
            for rid in stale_infs:
                inf = self._in_flight.pop(rid)
                trk = getattr(inf.msg, "_trk", None)
                if trk is not None:
                    trk.mark_event("repop torn down: interval change")
                pg.waiting_for_active.append(inf.msg)
            if primary != self.osd_id:
                pg.state = STATE_REPLICA
                for m in pg.waiting_for_active:   # clients re-target
                    trk = getattr(m, "_trk", None)
                    if trk is not None:
                        trk.mark_event("discarded: no longer primary")
                        trk.finish()
                pg.waiting_for_active.clear()
                return
            self.perf.inc("peering_rounds")
            peers = [o for o in up
                     if o != self.osd_id and o != CEPH_NOSD]
            if not peers:
                self._pg_recover_or_activate(pg)
                return
            pg.state = STATE_GETINFO
        for o in peers:
            con = self._osd_con(o)
            if con:
                con.send_message(MOSDPGQuery(
                    pgid=pg.pgid, qtype=MOSDPGQuery.INFO,
                    epoch=pg.peering_epoch, from_osd=self.osd_id))

    # -- peering (primary side) ----------------------------------------------

    def _advertised_info(self, pg: PG) -> "PGInfo":
        """Info snapshot for peering replies.  Includes my current up set
        among the advertised intervals: if my map is older than the
        asker's, what I call "current" is a past interval to them — and
        it is where my shard chunks physically live."""
        info = PGInfo(pgid=pg.info.pgid, last_update=pg.info.last_update,
                      last_complete=pg.info.last_complete,
                      last_epoch_started=pg.info.last_epoch_started,
                      past_up=[list(iv) for iv in pg.info.past_up])
        if pg.up and pg.up not in info.past_up:
            info.past_up.append(list(pg.up))
        return info

    def _handle_pg_query(self, msg: MOSDPGQuery) -> None:
        pg = self._get_pg(msg.pgid)
        # reply over the incoming connection: a just-booted OSD may not
        # have the asker's address in its (older) map yet
        con = msg.connection or self._osd_con(msg.from_osd)
        if con is None:
            return
        if msg.qtype == MOSDPGQuery.INFO:
            con.send_message(MOSDPGNotify(
                pgid=msg.pgid, info=self._advertised_info(pg),
                epoch=msg.epoch, from_osd=self.osd_id))
        else:
            con.send_message(MOSDPGLog(
                pgid=msg.pgid, info=self._advertised_info(pg),
                entries=pg.log.entries, purpose=MOSDPGLog.REPLY,
                epoch=msg.epoch, from_osd=self.osd_id))

    def _handle_pg_notify(self, msg: MOSDPGNotify) -> None:
        restart = False
        with self._lock:
            pg = self.pgs.get(msg.pgid)
            if pg is None:
                return
            if msg.from_osd not in pg.up:
                # a stray holder announced itself: record as a peering
                # and recovery source
                pg.strays[msg.from_osd] = msg.info
                pg.peers.setdefault(msg.from_osd,
                                    PeerState()).info = msg.info
                self._merge_past_up(pg, msg.info.past_up)
                considered = getattr(pg, "strays_considered", {})
                if (pg.primary == self.osd_id
                        and pg.state in (STATE_ACTIVE, STATE_RECOVERING)
                        and msg.info.last_update > pg.info.last_update
                        and msg.info.last_update
                        > considered.get(msg.from_osd, EVERSION_ZERO)):
                    # the stray has history we activated without (its
                    # notify lost the race — possibly arriving mid-
                    # GETLOG, after the GETINFO snapshot): re-peer with
                    # it as a source.  Guarded on info a completed
                    # peering round has NOT already considered: a stray
                    # whose divergent tail the EC roll-forward trim
                    # rejected re-notifies the same info on every map
                    # epoch, and restarting for it each time would
                    # re-peer the PG forever
                    restart = True
                if pg.state != STATE_GETINFO:
                    pass_through = False
                else:
                    pass_through = True
            else:
                if (pg.state != STATE_GETINFO
                        or msg.epoch != pg.peering_epoch):
                    return
                pg.peers[msg.from_osd] = PeerState(info=msg.info)
                self._merge_past_up(pg, msg.info.past_up)
                pass_through = True
            target = None
            if pass_through and pg.state == STATE_GETINFO:
                expected = [o for o in pg.up
                            if o != self.osd_id and o != CEPH_NOSD]
                if not all(o in pg.peers for o in expected):
                    return
                # all infos in: pick the authoritative history among up
                # members AND strays (PG::find_best_info over the prior
                # set — longest last_update wins, self on ties)
                cands = {o: pg.peers[o].info for o in expected}
                for o, i in pg.strays.items():
                    cands.setdefault(o, i)
                # remember what this round evaluated: only genuinely
                # NEWER stray info may trigger a post-activation re-peer
                pg.strays_considered = {
                    o: i.last_update for o, i in cands.items()}
                # EC roll-forward bound (PGLog can_rollback_to collapsed
                # to entry granularity): an entry held by fewer than k
                # shard holders can neither be reconstructed nor have
                # been acked (the client ack waits for ALL shard
                # commits), so the authoritative history trims to the
                # k-th highest last_update among known holders.  Without
                # this, a torn write whose tail landed on one shard
                # poisons recovery forever (gather: need > every
                # reconstructable version).
                pool = self.osdmap.pools.get(pg.pgid[0])
                pg.ec_rollforward = None
                if pool is not None and pool.is_erasure():
                    lus = sorted(
                        [pg.info.last_update]
                        + [i.last_update for i in cands.values()],
                        reverse=True)
                    k = int(pool.ec_profile.get("k", 2))
                    if len(lus) >= k:
                        pg.ec_rollforward = lus[k - 1]
                best = (max(cands, key=lambda o: cands[o].last_update)
                        if cands else None)
                if (best is not None
                        and cands[best].last_update > pg.info.last_update):
                    pg.state = STATE_GETLOG
                    target = best
            elif not restart:
                return
        if restart:
            self._start_peering(pg, pg.up, pg.primary)
            return
        if target is None:
            self._ec_trim_log(pg)
            self._pg_recover_or_activate(pg)
            return
        con = self._osd_con(target)
        if con:
            con.send_message(MOSDPGQuery(
                pgid=pg.pgid, qtype=MOSDPGQuery.LOG, since=EVERSION_ZERO,
                epoch=pg.peering_epoch, from_osd=self.osd_id))

    def _handle_pg_log(self, msg: MOSDPGLog) -> None:
        with self._lock:
            pg = self.pgs.get(msg.pgid)
            if pg is None:
                return
            if msg.purpose == MOSDPGLog.REPLY:
                if (pg.state != STATE_GETLOG
                        or msg.epoch != pg.peering_epoch):
                    return
                self._merge_past_up(pg, msg.info.past_up)
                self._pg_merge(pg, msg.entries)
                self._ec_trim_log(pg)
                self._pg_recover_or_activate(pg)
                return
            # ACTIVATE: primary's authoritative history
            if msg.epoch < pg.peering_epoch or pg.primary == self.osd_id:
                return
            self._merge_past_up(pg, msg.info.past_up)
            self._pg_merge(pg, msg.entries)
            pg.info.last_epoch_started = msg.info.last_epoch_started
            degraded = bool(pg.missing)
            if degraded:
                pg.state = STATE_RECOVERING
            else:
                pg.state = STATE_ACTIVE
                self._persist_info(pg)
        if degraded:
            # replica recovers behind its own reservation slot: pull-based
            # recovery makes the puller the backfill target, so its local
            # reserver plays the remote-reservation role too
            self.local_reserver.request(
                pg.pgid, lambda: self._start_recovery_ops(pg))

    def _store_oid_fn(self, pg: PG):
        """Shard-decorated store name for this OSD's copy of an object
        (EC pools suffix the positional shard; one definition so merge,
        trim and recovery address the same on-disk objects)."""
        pool = self.osdmap.pools.get(pg.pgid[0])
        ec = pool is not None and pool.is_erasure()
        myshard = pg.up.index(self.osd_id) if ec \
            and self.osd_id in pg.up else None

        def store_oid(oid: str) -> str:
            return f"{oid}:{myshard}" if ec else oid
        return store_oid

    def _pg_merge(self, pg: PG, entries: list[LogEntry]) -> None:
        """merge_log + on-disk application of its consequences."""
        cid = self._pg_cid(pg.pgid)
        store_oid = self._store_oid_fn(pg)

        def local_has(oid: str):
            return dec_version(self._getattr_safe(cid, store_oid(oid), "_v"))

        old_keys = {PG.log_key(e.version) for e in pg.log.entries}
        to_remove, to_recover = pg.merge_log(entries, local_has)
        t = Transaction()
        for oid in to_remove:
            t.remove(cid, store_oid(oid))
        t.touch(cid, PG.PGMETA)
        # only touch the delta: rewriting the whole untrimmed log per
        # merge would make every map change O(full history)
        new_keys = {}
        cur_keys = set()
        for e in pg.log.entries:
            lk = PG.log_key(e.version)
            cur_keys.add(lk)
            if lk not in old_keys:
                new_keys[lk] = PG.encode_entry(e)
        stale = [k for k in old_keys if k not in cur_keys]
        if stale:
            t.omap_rmkeys(cid, PG.PGMETA, stale)
        new_keys["info"] = pg.encode_info()
        new_keys["missing"] = pg.encode_missing()
        t.omap_setkeys(cid, PG.PGMETA, new_keys)
        self.store.apply_transaction(t)
        pg.next_seq = pg.log.head[1]
        dout("osd", 10,
             "osd.%d pg %s merged log: head %s, %d missing, %d removed",
             self.osd_id, cid, pg.log.head, len(to_recover), len(to_remove))

    def _ec_trim_log(self, pg: PG) -> None:
        """Rewind an EC pg's authoritative log to the roll-forward bound
        computed during GETINFO (entries beyond it are unreconstructable
        AND unacked — see _handle_pg_notify).  Runs on the primary before
        activation, so replicas adopt the trimmed history uniformly and
        their own divergent tails roll back through the normal merge."""
        bound = getattr(pg, "ec_rollforward", None)
        if bound is None or pg.log.head <= bound:
            return
        cid = self._pg_cid(pg.pgid)
        store_oid = self._store_oid_fn(pg)
        divergent = pg.log.rewind(bound)
        t = Transaction().touch(cid, PG.PGMETA)
        t.omap_rmkeys(cid, PG.PGMETA,
                      [PG.log_key(e.version) for e in divergent])
        seen: set[str] = set()
        for e in reversed(divergent):
            if e.oid in seen:
                continue
            seen.add(e.oid)
            ae = pg.log.index.get(e.oid)
            if ae is None or ae.is_delete():
                pg.missing.pop(e.oid, None)
                t.remove(cid, store_oid(e.oid))
            else:
                have = dec_version(self._getattr_safe(
                    cid, store_oid(e.oid), "_v"))
                if have == ae.version:
                    pg.missing.pop(e.oid, None)
                else:
                    pg.missing[e.oid] = MissingItem(
                        need=ae.version, have=have or EVERSION_ZERO)
        pg.info.last_update = pg.log.head
        pg.info.last_complete = pg.complete_to()
        pg.next_seq = pg.log.head[1]
        t.omap_setkeys(cid, PG.PGMETA, {
            "info": pg.encode_info(),
            "missing": pg.encode_missing()})
        self.store.apply_transaction(t)
        dout("osd", 3, "osd.%d pg %s ec-trimmed log to %s "
             "(%d entries rolled back)", self.osd_id, cid, bound,
             len(divergent))

    def _getattr_safe(self, cid, oid, name):
        try:
            return self.store.getattr(cid, oid, name)
        except KeyError:
            return None

    def _persist_info(self, pg: PG) -> None:
        cid = self._pg_cid(pg.pgid)
        t = (Transaction().touch(cid, PG.PGMETA)
             .omap_setkeys(cid, PG.PGMETA, {
                 "info": pg.encode_info(),
                 "missing": pg.encode_missing()}))
        self.store.apply_transaction(t)

    def _pg_recover_or_activate(self, pg: PG) -> None:
        """Primary with the authoritative log: recover own missing objects
        first (behind a reservation slot), then activate replicas."""
        with self._lock:
            degraded = bool(pg.missing)
            if degraded:
                pg.state = STATE_RECOVERING
        if degraded:
            self.local_reserver.request(
                pg.pgid, lambda: self._start_recovery_ops(pg))
            return
        self._pg_activate(pg)

    def _start_recovery_ops(self, pg: PG) -> None:
        """Issue pulls up to the osd_recovery_max_active window
        (PrimaryLogPG::start_recovery_ops analog).  Runs on reservation
        grant and again as each object lands; recovery thus pipelines
        with client I/O instead of thundering in one burst."""
        pool = self.osdmap.pools.get(pg.pgid[0])
        ec = pool is not None and pool.is_erasure()
        window = int(self.ctx.conf.get("osd_recovery_max_active"))
        with self._lock:
            if pg.state != STATE_RECOVERING:
                self.local_reserver.cancel(pg.pgid)
                return
            room = window - len(pg.recovering)
            # capture need under the lock: a racing push can delete the
            # missing entry before the sends below run
            todo = [(oid, pg.missing[oid].need)
                    for oid in sorted(pg.missing)
                    if oid not in pg.recovering][:max(0, room)]
        for oid, need in todo:
            if pg.primary == self.osd_id:
                if ec:
                    self._recover_ec_object(pg, oid, dest_osd=self.osd_id)
                else:
                    source = self._pick_source(pg, need)
                    if source is not None:
                        self._pull_object(pg, oid, source)
            else:
                self._pull_object(pg, oid, pg.primary)

    def _pick_source(self, pg: PG, need) -> int | None:
        candidates = [o for o, ps in pg.peers.items()
                      if ps.info and ps.info.last_update >= need]
        if not candidates:
            return None
        return max(candidates,
                   key=lambda o: pg.peers[o].info.last_update)

    def _pg_activate(self, pg: PG) -> None:
        """Primary is complete: ship the authoritative log to every replica
        and open for business (PG::activate)."""
        with self._lock:
            pg.state = STATE_ACTIVE
            pg.info.last_epoch_started = pg.peering_epoch
            peers = [o for o in pg.up
                     if o != self.osd_id and o != CEPH_NOSD]
            for o in peers:
                ps = pg.peers.setdefault(o, PeerState())
                last = ps.info.last_update if ps.info else EVERSION_ZERO
                ps.missing = pg.peer_missing_from_log(last)
            waiting = pg.waiting_for_active
            pg.waiting_for_active = []
        self._persist_info(pg)
        for o in peers:
            con = self._osd_con(o)
            if con:
                con.send_message(MOSDPGLog(
                    pgid=pg.pgid, info=pg.info, entries=pg.log.entries,
                    purpose=MOSDPGLog.ACTIVATE, epoch=pg.peering_epoch,
                    from_osd=self.osd_id))
        dout("osd", 5, "osd.%d pg %s active, head %s (%d queued ops)",
             self.osd_id, self._pg_cid(pg.pgid), pg.log.head, len(waiting))
        for m in waiting:
            self._handle_op(m)

    # -- recovery -------------------------------------------------------------

    def _pull_object(self, pg: PG, oid: str, source: int,
                     con=None) -> None:
        pool = self.osdmap.pools.get(pg.pgid[0])
        ec = pool is not None and pool.is_erasure()
        with self._lock:
            if oid in pg.recovering:
                return
            pg.recovering[oid] = time.time()
        self.perf.inc("recovery_pulls")
        wire_oid = oid
        if ec:
            if self.osd_id not in pg.up:
                return
            myshard = pg.up.index(self.osd_id)
            wire_oid = f"{oid}:{myshard}"
        con = con or self._osd_con(source)
        if con:
            con.send_message(MOSDPGPull(pgid=pg.pgid, oid=wire_oid,
                                        from_osd=self.osd_id))

    def _handle_pull(self, msg: MOSDPGPull) -> None:
        pool = self.osdmap.pools.get(msg.pgid[0])
        if pool is not None and self._park_subop(
                self._handle_pull, msg, pool):
            return

        cid = f"{msg.pgid[0]}.{msg.pgid[1]}"
        pool = self.osdmap.pools.get(msg.pgid[0])
        pg = self.pgs.get(msg.pgid)
        if pool is not None and pool.is_erasure():
            logical, _, shard = msg.oid.rpartition(":")
            if pg is None:
                return
            self._recover_ec_object(pg, logical, dest_osd=msg.from_osd,
                                    dest_shard=int(shard))
            return
        try:
            data = self.store.read(cid, msg.oid)
            omap = self.store.omap_get(cid, msg.oid)
            attrs = {}
            v = self._getattr_safe(cid, msg.oid, "_v")
            if v:
                attrs["_v"] = v
        except KeyError:
            return
        con = msg.connection or self._osd_con(msg.from_osd)
        if con:
            con.send_message(MOSDPGPush(pgid=msg.pgid, oid=msg.oid,
                                        data=data, omap=omap, attrs=attrs))
        self._peer_recovered(pg, msg.from_osd, msg.oid)

    def _peer_recovered(self, pg: PG | None, peer: int, oid: str) -> None:
        """Primary bookkeeping: a peer now has `oid` (unblocks writes)."""
        if pg is None or pg.primary != self.osd_id:
            return
        logical = oid.rsplit(":", 1)[0] if ":" in oid else oid
        with self._lock:
            ps = pg.peers.get(peer)
            if ps:
                ps.missing.pop(logical, None)
            waiting = pg.waiting_for_missing.pop(logical, [])
        for m in waiting:
            self._handle_op(m)

    def _handle_push(self, msg: MOSDPGPush) -> None:
        cid = f"{msg.pgid[0]}.{msg.pgid[1]}"
        pg = self.pgs.get(msg.pgid)
        push_v = dec_version(msg.attrs.get("_v"))
        local_v = dec_version(self._getattr_safe(cid, msg.oid, "_v"))
        if local_v is not None and push_v is not None and local_v > push_v:
            return  # stale push; we already advanced past it
        t = Transaction()
        if cid not in self.store.list_collections():
            t.create_collection(cid)
        # replace wholesale: a divergent local copy's omap/attrs must not
        # survive union-merged into the authoritative state
        t.remove(cid, msg.oid)
        t.write(cid, msg.oid, 0, msg.data)
        if msg.omap:
            t.omap_setkeys(cid, msg.oid, msg.omap)
        for name, val in msg.attrs.items():
            t.setattr(cid, msg.oid, name, val)
        self.store.apply_transaction(t)
        if pg is None:
            return
        logical = msg.oid.rsplit(":", 1)[0] if ":" in msg.oid else msg.oid
        self._object_recovered(pg, logical, push_v)

    def _object_recovered(self, pg: PG, oid: str,
                          got_version) -> None:
        """My own missing object arrived; maybe finish recovery."""
        activate = False
        done = False
        with self._lock:
            item = pg.missing.get(oid)
            if item is not None and (got_version is None
                                     or got_version >= item.need):
                del pg.missing[oid]
            pg.recovering.pop(oid, None)
            if not pg.missing and pg.state == STATE_RECOVERING:
                done = True
                if pg.primary == self.osd_id:
                    activate = True
                else:
                    pg.state = STATE_ACTIVE
            pg.info.last_complete = pg.complete_to()
            waiting = pg.waiting_for_missing.pop(oid, [])
        self._persist_info(pg)
        if done:
            self.local_reserver.cancel(pg.pgid)  # release the slot
            self.clog.info("pg %d.%d recovered on osd.%d",
                           pg.pgid[0], pg.pgid[1], self.osd_id)
        elif (pg.state == STATE_RECOVERING
              and self.local_reserver.has(pg.pgid)):
            # refill the pull window — only while we still hold the
            # slot; a stale push after an interval change must not
            # bypass osd_max_backfills (the queued re-request's grant
            # restarts the window instead)
            self._start_recovery_ops(pg)
        if activate:
            self._pg_activate(pg)
        for m in waiting:
            self._handle_op(m)

    def _merge_past_up(self, pg: PG, intervals, new_up=None) -> None:
        """Adopt prior-interval up sets (own or learned from peer infos)."""
        cur = new_up if new_up is not None else pg.up
        for iv in intervals:
            iv = list(iv)
            if iv and iv != cur and iv not in pg.info.past_up:
                pg.info.past_up.append(iv)
        del pg.info.past_up[:-8]

    def _ec_shard_candidates(self, pg: PG, n: int) -> dict[int, list[int]]:
        """Per-shard holder candidates: current position first, then the
        holders from prior intervals (PastIntervals — after a remap the
        chunk still lives on its old positional holder)."""
        cand: dict[int, list[int]] = {}
        intervals = [pg.up] + list(reversed(pg.info.past_up))
        for s in range(n):
            seen: list[int] = []
            for iv in intervals:
                if s < len(iv) and iv[s] != CEPH_NOSD \
                        and iv[s] not in seen:
                    seen.append(iv[s])
            cand[s] = seen
        return cand

    def _recover_ec_object(self, pg: PG, oid: str, dest_osd: int,
                           dest_shard: int | None = None) -> None:
        """Reconstruct one EC object's shard at the logged version from k
        live shards, then store (self) or push (peer) the chunk
        (ECBackend recovery: objects_read_and_reconstruct)."""
        entry = pg.log.index.get(oid)
        if entry is None or entry.is_delete():
            return
        need = entry.version
        if dest_shard is None:
            if self.osd_id not in pg.up:
                return
            dest_shard = pg.up.index(self.osd_id)
        pool = self.osdmap.pools.get(pg.pgid[0])
        if pool is None:
            return
        with self._lock:
            if dest_osd == self.osd_id:
                if oid in pg.recovering:
                    return
                pg.recovering[oid] = time.time()
            self._recover_tid += 1
            reqid = (RECOVERY_CLIENT + self.osd_id, self._recover_tid)
        self.perf.inc("recovery_pulls")
        codec = self._codec(pool)
        k = codec.get_data_chunk_count()
        n = codec.get_chunk_count()
        state = {"kind": "recover", "pool": pool, "pgid": pg.pgid,
                 "oid": oid, "need": need, "dest_osd": dest_osd,
                 "dest_shard": dest_shard, "shards": {}, "k": k,
                 "active": set(), "cand": self._ec_shard_candidates(pg, n)}
        with self._lock:
            self._ec_reads[reqid] = state
        self._ec_gather(reqid, state)

    # -- heartbeats (OSD::heartbeat, osd/OSD.cc:4879) -------------------------

    def _schedule_heartbeat(self) -> None:
        if self._stop:
            return
        interval = float(self.ctx.conf.get("osd_heartbeat_interval"))
        self._hb_timer = threading.Timer(interval, self._heartbeat_tick)
        self._hb_timer.daemon = True
        self._hb_timer.start()

    def _heartbeat_tick(self) -> None:
        try:
            now = time.time()
            grace = float(self.ctx.conf.get("osd_heartbeat_grace"))
            m = self.osdmap
            peers = [o for o in range(m.max_osd)
                     if o != self.osd_id and m.is_up(o)]
            for peer in peers:
                con = self._osd_con(peer)
                if con:
                    con.send_message(MOSDPing(
                        from_osd=self.osd_id, op=MOSDPing.PING, stamp=now,
                        epoch=m.epoch))
                # first contact starts the grace clock; a peer that never
                # answers is as failed as one that stopped answering
                last = self._hb_last.setdefault(peer, now)
                if now - last > grace:
                    self._failure_reported.add(peer)
                    self._send_to_mons(lambda: MOSDFailure(
                        reporter=self.osd_id, failed_osd=peer,
                        failed_for=now - last, epoch=m.epoch))
            # forget peers the map marked down: a reported peer needs no
            # cancellation anymore, and its grace clock must restart from
            # scratch when it reboots — a stale _hb_last would instantly
            # re-report a healthy rebooted osd with a huge failed_for
            self._failure_reported = {p for p in self._failure_reported
                                      if m.is_up(p)}
            for p in [p for p in self._hb_last if not m.is_up(p)]:
                del self._hb_last[p]
        finally:
            self._schedule_heartbeat()

    # -- dispatch -------------------------------------------------------------

    def ms_dispatch(self, msg) -> bool:
        if self._stop:
            # a stopping daemon answers nothing (OSD::ms_dispatch
            # is_stopping): a zombie reply — e.g. a ping ack over a
            # connection accepted mid-shutdown — would keep peers'
            # liveness clocks fresh for a dead osd
            return True
        if isinstance(msg, MOSDMapMsg):
            self._handle_map(msg)
            return True
        from ceph_tpu.messages import MMonCommandAck
        if isinstance(msg, MMonCommandAck):
            self.mon_cmd.handle_ack(msg)
            return True
        # queued classes (enqueue_op → op_shardedwq → dequeue_op): work
        # items shard by pgid and ride the mClock scheduler; replies and
        # control-plane traffic dispatch inline (ms_fast_dispatch)
        if isinstance(msg, MOSDOp):
            self._enqueue_op(self._client_class(msg), msg.pgid,
                             self._handle_op, msg)
            return True
        if isinstance(msg, MOSDRepOp):
            self._enqueue_op("subop", msg.pgid, self._handle_rep_op, msg)
            return True
        if isinstance(msg, MOSDRepOpReply):
            self._handle_rep_reply(msg)
            return True
        if isinstance(msg, MOSDECSubOpWrite):
            self._enqueue_op("subop", msg.pgid, self._handle_ec_write, msg)
            return True
        if isinstance(msg, MOSDECSubOpWriteReply):
            self._handle_ec_write_reply(msg)
            return True
        if isinstance(msg, MOSDECSubOpRead):
            self._enqueue_op("subop", msg.pgid, self._handle_ec_read, msg)
            return True
        if isinstance(msg, MOSDECSubOpReadReply):
            self._handle_ec_read_reply(msg)
            return True
        if isinstance(msg, MOSDPing):
            self._handle_ping(msg)
            return True
        if isinstance(msg, MOSDPGQuery):
            self._handle_pg_query(msg)
            return True
        if isinstance(msg, MOSDPGNotify):
            self._handle_pg_notify(msg)
            return True
        if isinstance(msg, MOSDPGLog):
            self._handle_pg_log(msg)
            return True
        if isinstance(msg, MOSDPGPull):
            self._enqueue_op("recovery", msg.pgid, self._handle_pull, msg)
            return True
        if isinstance(msg, MOSDPGPush):
            self._enqueue_op("recovery", msg.pgid, self._handle_push, msg)
            return True
        if isinstance(msg, MWatchNotifyAck):
            self._handle_notify_ack(msg)
            return True
        if isinstance(msg, MOSDScrub):
            # replica scrub-map building is background work too: it
            # rides the same background_best_effort lane as the
            # primary's chunks — cost-scaled, a map build is many
            # small-op service times — so a scrub storm's replica half
            # is dmclock-arbitrated instead of competing as peer
            # traffic
            msg.qos_delta = max(1, int(self.ctx.conf.get(
                "osd_scrub_cost")))
            msg.qos_rho = 0
            self._enqueue_op(BACKGROUND_BEST_EFFORT, msg.pgid,
                             self._handle_scrub, msg)
            return True
        if isinstance(msg, MOSDScrubReply):
            self._handle_scrub_reply(msg)
            return True
        return False

    def _handle_ping(self, msg: MOSDPing) -> None:
        self._hb_last[msg.from_osd] = time.time()
        if msg.epoch > self.osdmap.epoch:
            # peer runs a newer map: catch up now (epoch gossip on the
            # heartbeat channel — OSD map-sharing semantics)
            self._renew_map_subscription(time.time(), force=True)
        if msg.from_osd in self._failure_reported:
            # the peer I reported as failed is talking again: retract
            # (OSD::send_still_alive / MOSDFailure FLAG_ALIVE)
            self._failure_reported.discard(msg.from_osd)
            self._send_to_mons(lambda: MOSDFailure(
                reporter=self.osd_id, failed_osd=msg.from_osd,
                epoch=self.osdmap.epoch, alive=True))
        if msg.op == MOSDPing.PING and msg.connection is not None:
            msg.connection.send_message(MOSDPing(
                from_osd=self.osd_id, op=MOSDPing.PING_REPLY,
                stamp=msg.stamp, epoch=self.osdmap.epoch))

    # -- cache-tier agent (promotion + flush/evict) ---------------------------

    def _is_internal(self, msg) -> bool:
        """Ops from the tier agent's embedded client must not re-enter
        the tier machinery (no promotion parking, no dirty stamp, no
        delete write-through) — they ARE the machinery."""
        c = self._internal_client
        return c is not None and msg.client_id == c.client_id

    def _internal_io(self, pool_id: int):
        """Lazy internal RadosClient (the reference uses OSD-to-OSD
        copy_from; an embedded client is the lite equivalent)."""
        from ceph_tpu.client.rados import RadosClient
        if self._internal_client is None:
            c = RadosClient(self.mon_addr, ms_type=self._ms_type,
                            timeout=8.0, auth_key=self._auth_key)
            c.connect()
            self._internal_client = c
        # direct=True: agent I/O must hit the pool it names — a flush
        # that followed the overlay would loop back into the cache
        return self._internal_client.open_ioctx(pool_id, direct=True)

    def _agent_loop(self) -> None:
        from ceph_tpu.common.logging import get_logger
        while not self._stop:
            try:
                job = self._agent_q.get(timeout=0.25)
            except Exception:
                continue
            if job is None:
                return
            try:
                if job[0] == "promote":
                    self._do_promote(job[1], job[2], job[3])
                elif job[0] == "base_delete":
                    try:
                        self._internal_io(job[2]).remove(job[1])
                    except OSError:
                        pass
                elif job[0] == "flush":
                    self._do_flush(job[1], job[2], job[3], job[4])
            except Exception:
                get_logger("osd").exception(
                    "osd.%d tier agent job %s failed", self.osd_id,
                    job[0])
                if job[0] == "promote":
                    self._promote_done(job[1], job[2], fail_rc=-11)

    def _do_promote(self, pgid, oid: str, base_pool: int) -> None:
        """Copy the object (or learn it is absent) from the base pool,
        install it CLEAN in the cache via the replicated write path,
        then re-dispatch the parked ops."""
        io = self._internal_io(base_pool)
        try:
            data = io.read(oid)
            omap = io.get_omap(oid)
        except OSError:
            # no base copy: the ops proceed against an absent object
            # (reads -> ENOENT, creates -> fresh object)
            self._promote_done(pgid, oid)
            return
        cache_io = self._internal_io(pgid[0])
        try:
            cache_io.write_full(oid, data)
            if omap:
                cache_io.set_omap(oid, omap)
        except OSError:
            # a half-installed promotion must not release parked ops:
            # a partial write would then create a truncated object that
            # the agent later flushes OVER the intact base copy
            self._promote_done(pgid, oid, fail_rc=-11)  # EAGAIN
            return
        self._promote_done(pgid, oid)

    def _promote_done(self, pgid, oid: str, fail_rc: int = 0) -> None:
        with self._lock:
            waiting = self._promoting.pop((pgid, oid), [])
        for m in waiting:
            if fail_rc:
                self._reply_err(m, fail_rc)
            else:
                m._tier_checked = True
                self._enqueue_op(self._client_class(m), m.pgid,
                                 self._handle_op, m)

    def _do_flush(self, pgid, oid: str, base_pool: int,
                  evict_only: bool) -> None:
        """Writeback: push the dirty object to the base pool, then evict
        it from the cache (the lite agent combines agent_maybe_flush +
        agent_maybe_evict; a re-read re-promotes).  A client write that
        races the flush keeps the object resident: the dirty stamp is
        re-read before the evicting remove, and a changed (or appeared)
        stamp aborts it — the next scan retries."""
        cid = self._pg_cid(pgid)
        stamp0 = self._getattr_safe(cid, oid, "_dirty")
        if not evict_only:
            if stamp0 is None:
                return   # already flushed or vanished
            try:
                data = self.store.read(cid, oid)
                omap = self.store.omap_get(cid, oid)
            except KeyError:
                return
            base_io = self._internal_io(base_pool)
            base_io.write_full(oid, data)
            if omap:
                base_io.set_omap(oid, omap)
        self._evict_object(pgid, oid, stamp0)

    def _evict_object(self, pgid, oid: str, stamp0) -> None:
        """Guarded replicated delete: the dirty-stamp check and the
        delete are ONE atomic step under the PG lock, so a client write
        racing the agent can never be destroyed — it changes the stamp
        and the evict aborts (the next scan retries)."""
        with self._lock:
            pg = self.pgs.get(pgid)
            if (pg is None or pg.state != STATE_ACTIVE
                    or pg.primary != self.osd_id):
                return
            cid = self._pg_cid(pgid)
            if self._getattr_safe(cid, oid, "_dirty") != stamp0:
                return   # raced a client write; keep the newer data
            if not self.store.exists(cid, oid):
                return
            self._agent_tid += 1
            reqid = (TIER_AGENT_CLIENT, self._agent_tid)
            t = Transaction().remove(cid, oid)
            entry = self._log_write(pg, t, oid, True, reqid)
            self.store.apply_transaction(t)
            up = pg.up
            replicas = [o for o in up
                        if o != self.osd_id and o != CEPH_NOSD]
            if replicas:
                fake = MOSDOp(client_id=TIER_AGENT_CLIENT,
                              tid=self._agent_tid, pgid=pgid, oid=oid,
                              ops=[OSDOpField(OP_DELETE)])
                fake.connection = None
                self._in_flight[reqid] = _InFlight(
                    fake, set(replicas),
                    MOSDOpReply(tid=self._agent_tid, result=0,
                                epoch=self.osdmap.epoch))
                blob = t.encode()
                entry_blob = PG.encode_entry(entry)
        for rep in replicas:
            con = self._osd_con(rep)
            if con is None:
                self._ack_shard(reqid, rep, -107)
                continue
            con.send_message(MOSDRepOp(reqid=reqid, pgid=pgid, oid=oid,
                                       txn=blob, pg_version=entry.version,
                                       entry=entry_blob))

    def _agent_scan(self, now: float) -> None:
        """Tick-side: queue flush/evict work for cache PGs I lead."""
        for pgid, pg in list(self.pgs.items()):
            pool = self.osdmap.pools.get(pgid[0])
            if (pool is None or pool.tier_of < 0
                    or pool.cache_mode != "writeback"
                    or pg.primary != self.osd_id
                    or pg.state != STATE_ACTIVE):
                continue
            cid = self._pg_cid(pgid)
            try:
                oids = [o for o in self.store.list_objects(cid)
                        if not o.startswith(PG.PGMETA)
                        and CLONE_SEP not in o]
            except KeyError:
                continue
            n_queued = 0
            for oid in oids:
                if n_queued >= 8:
                    break
                dirty = self._getattr_safe(cid, oid, "_dirty")
                if dirty is not None:
                    if now - float(dirty) >= pool.cache_min_flush_age:
                        self._agent_q.put(("flush", pgid, oid,
                                           pool.tier_of, False))
                        n_queued += 1
            if pool.target_max_objects \
                    and len(oids) > pool.target_max_objects:
                for oid in oids:
                    if n_queued >= 8:
                        break
                    if self._getattr_safe(cid, oid, "_dirty") is None:
                        self._agent_q.put(("flush", pgid, oid,
                                           pool.tier_of, True))
                        n_queued += 1

    # -- op execution (PrimaryLogPG::do_op analog) ----------------------------

    def _pg_members(self, pgid) -> tuple[list[int], int]:
        """(up, acting_primary) — ops are accepted by the acting primary,
        matching the client's _calc_target (osdc/Objecter.cc:2795)."""
        up, _up_primary, _acting, acting_primary = \
            self._pg_mapping(pgid[0], pgid[1])
        return up, acting_primary

    def _handle_op(self, msg: MOSDOp) -> None:
        # replayed ops (map-advance, recovery waiters, promote-done)
        # run on whatever thread flushed them: re-join the op's trace
        # from the message so the fan-out stays attributed
        tid = getattr(msg, "trace_id", 0)
        from ceph_tpu.common import tracing
        if tid and tracing.current() != tid:
            prev = tracing.set_current(
                tid, getattr(msg, "parent_span_id", 0))
            try:
                return self._handle_op(msg)
            finally:
                tracing.set_current(prev)
        if getattr(msg, "_trk", None) is None:
            kinds = ",".join(str(op.op) for op in msg.ops)
            msg._trk = self.op_tracker.create_request(
                f"osd_op(client.{msg.client_id}.{msg.tid} "
                f"{msg.pgid[0]}.{msg.pgid[1]} {msg.oid} ops=[{kinds}])")
        else:
            msg._trk.mark_event("requeued")
        if msg.epoch > self.osdmap.epoch:
            # client runs a newer map than us: park the op until our mon
            # subscription catches us up (OSD::wait_for_new_map), never
            # judge primaryship with a stale map
            with self._lock:
                if msg.epoch > self.osdmap.epoch:
                    msg._trk.mark_event("waiting for newer osdmap")
                    self._waiting_for_map.append(msg)
                    return
        pool = self.osdmap.pools.get(msg.pgid[0])
        if pool is None:
            self._reply_err(msg, -2)
            return
        # misdirected-op guard: after a PG split, a client on the old map
        # still targets the parent pgid; executing there would strand the
        # object in the wrong collection.  Drop and share our newer map —
        # the client recomputes and resends (OSD::handle_op misdirected
        # drop + maybe_share_map)
        is_pgls = any(op.op == OP_PGLS for op in msg.ops)
        if is_pgls:
            # pg-targeted op: the pg IS the address (no oid to rehash);
            # bounds-check against the pool's CURRENT pg_num
            expect = msg.pgid[1] if msg.pgid[1] < pool.pg_num else -1
        else:
            expect = pg_to_pgid(ceph_str_hash_rjenkins(msg.oid),
                                pool.pg_num)
        if expect != msg.pgid[1]:
            m = self.osdmap
            if msg.epoch < m.epoch and msg.connection is not None:
                msg.connection.send_message(MOSDMapMsg(
                    epoch=m.epoch, map_blob=encode_osdmap(m)))
            msg._trk.mark_event("dropped: misdirected (stale pg mapping)")
            msg._trk.finish()
            return
        up, primary = self._pg_members(msg.pgid)
        if primary != self.osd_id:
            # not my op in this epoch: share my newer map with the stale
            # sender so it re-targets (OSD maybe_share_map semantics);
            # without this a client whose map never changes again would
            # hang forever
            dout("osd", 10, "osd.%d not primary for %s", self.osd_id,
                 msg.pgid)
            m = self.osdmap
            if msg.epoch < m.epoch and msg.connection is not None:
                msg.connection.send_message(MOSDMapMsg(
                    epoch=m.epoch, map_blob=encode_osdmap(m)))
            msg._trk.mark_event("dropped: not primary")
            msg._trk.finish()
            return
        # check-and-enqueue must be atomic with the flush paths
        # (_pg_activate / _peer_recovered / _object_recovered), or an op can
        # slip into a waiting list just after its last flush ran
        with self._lock:
            pg = self.pgs.get(msg.pgid)
            if pg is None and self._split_pending(msg.pgid[0]):
                # between the new map installing and _split_pgs finishing:
                # creating the child now would let a write land in a PG
                # the imminent split is about to overwrite.  Park; the
                # end of _handle_map replays us after split+scan
                msg._trk.mark_event("waiting for pg split")
                self._waiting_for_map.append(msg)
                return
            if pg is None and 0 <= msg.pgid[1] < pool.pg_num:
                msg._trk.mark_event("creating pg (raced map advance)")
                # op raced ahead of _scan_pgs creating this PG on the
                # new map: create it, start its peering round now (the
                # scan may already be past this pgid), park the op;
                # activation flushes waiting_for_active
                pg = self._get_pg(msg.pgid)
                pg.waiting_for_active.append(msg)
                self._start_peering(pg, up, primary)
                return
            if pg is None or pg.state != STATE_ACTIVE:
                if pg is not None:
                    msg._trk.mark_event(
                        f"waiting for pg active (state={pg.state})")
                    pg.waiting_for_active.append(msg)
                else:
                    # pgid out of range for the pool: drop, close the op
                    msg._trk.mark_event("dropped: pgid out of range")
                    msg._trk.finish()
                return
            is_write = any(op.op in (OP_WRITE, OP_WRITEFULL, OP_DELETE,
                                     OP_OMAP_SET, OP_OMAP_RMKEYS)
                           for op in msg.ops)
            # pure EC writes ride the per-object write pipeline instead of
            # parking behind an in-flight rmw gather (ExtentCache analog,
            # src/osd/ExtentCache.h:1-491): _ec_write_op chains them onto
            # the gather's projected content
            ec_pipelinable = (pool.is_erasure() and bool(msg.ops)
                              and all(op.op in (OP_WRITE, OP_WRITEFULL)
                                      for op in msg.ops))
            if self._blocked_on_recovery(pg, msg.oid, is_write,
                                         pool.is_erasure(),
                                         rmw_ok=ec_pipelinable):
                msg._trk.mark_event("waiting for missing object")
                pg.waiting_for_missing.setdefault(msg.oid, []).append(msg)
                return
            # cache tier: an op for an object this (cache) pool does not
            # hold yet parks behind a promotion from the base pool
            # (PrimaryLogPG::maybe_promote / promote_object)
            if (pool.tier_of >= 0 and pool.cache_mode == "writeback"
                    and not getattr(msg, "_tier_checked", False)
                    and not self._is_internal(msg)
                    and not self.store.exists(self._pg_cid(msg.pgid),
                                              msg.oid)):
                msg._trk.mark_event("waiting for promotion")
                key = (msg.pgid, msg.oid)
                waiting = self._promoting.get(key)
                if waiting is not None:
                    waiting.append(msg)
                else:
                    self._promoting[key] = [msg]
                    self._agent_q.put(("promote", msg.pgid, msg.oid,
                                       pool.tier_of))
                return
            # execute under the lock: version allocation + log append +
            # store apply must be atomic against concurrent dispatch
            # threads (each connection has its own reader thread) and the
            # tick/activation requeue paths
            if pool.is_erasure():
                self._do_ec_op(msg, pool, pg)
            else:
                self._do_replicated_op(msg, pool, pg)
                if pool.tier_of >= 0 and is_write \
                        and not self._is_internal(msg) and any(
                        op.op == OP_DELETE for op in msg.ops):
                    # write-through for deletes: without it the base
                    # copy would resurrect on the next promotion
                    self._agent_q.put(("base_delete", msg.oid,
                                       pool.tier_of))

    def _blocked_on_recovery(self, pg: PG, oid: str, is_write: bool,
                             ec: bool, rmw_ok: bool = False) -> bool:
        """Block ops on objects still being recovered
        (PrimaryLogPG objects_blocked_on_recovery semantics).  rmw_ok
        lets pipelinable EC writes through an in-flight rmw gather —
        they join the gather's write queue instead of parking — but ONLY
        while nothing non-pipelinable is already parked on the object:
        jumping a parked read/delete would break per-object op order."""
        with self._lock:
            if oid in pg.missing or oid in pg.recovering:
                return True
            if oid in pg.rmw and not (rmw_ok
                                      and not pg.waiting_for_missing.get(oid)):
                return True
            if is_write or ec:
                return any(oid in ps.missing for ps in pg.peers.values())
        return False

    def _op_send_reply(self, msg: MOSDOp, reply: "MOSDOpReply") -> None:
        """Single client-reply chokepoint: closes the op's TrackedOp
        timeline (OpRequest lifecycle), echoes the dmclock phase that
        served the op (the client's ServiceTracker counts rho from
        it), and sends."""
        trk = getattr(msg, "_trk", None)
        if trk is not None:
            trk.mark_event(f"reply result={reply.result}")
            trk.finish()
        if not reply.qos_phase:
            reply.qos_phase = getattr(msg, "_qos_phase", 0)
        if msg.connection is not None:
            msg.connection.send_message(reply)

    def _reply_err(self, msg: MOSDOp, code: int) -> None:
        self._op_send_reply(
            msg, MOSDOpReply(tid=msg.tid, result=code,
                             epoch=self.osdmap.epoch))

    def _dedup_resend(self, pg: PG, reqid, msg: MOSDOp) -> bool:
        """Client resent an op already in the log.  If the original is
        still waiting on replica commits, attach the resend to it (reply
        when it completes) instead of acking an under-replicated write."""
        with self._lock:
            if not pg.log.has_reqid(reqid):
                return False
            inf = self._in_flight.get(reqid)
            if inf is not None:
                if inf.msg is not msg:   # tcp resends are fresh objects
                    trk = getattr(inf.msg, "_trk", None)
                    if trk is not None:
                        trk.mark_event("superseded by client resend")
                        trk.finish()
                inf.msg = msg      # reply goes to the latest connection
                return True
        self._op_send_reply(msg, MOSDOpReply(
            tid=msg.tid, result=0, epoch=self.osdmap.epoch))
        return True

    def _stale_retry(self, pg: PG, msg: MOSDOp) -> bool:
        """An op the client has ALREADY MOVED PAST: its tid is older
        than the object's newest logged op from the same client.  A
        timed-out-and-abandoned write can stay queued (peering,
        recovery gates) and land after a newer acked write — executing
        it would roll the object back under an acked state.  Drop it
        (Objecter per-object submission ordering, enforced OSD-side)."""
        last = pg.log.index.get(msg.oid)
        return (last is not None
                and last.reqid[0] == msg.client_id
                and msg.tid < last.reqid[1])

    def _log_write(self, pg: PG, t: Transaction, oid: str, is_delete: bool,
                   reqid) -> LogEntry:
        """Allocate a version, build the log entry, and fold the log append
        + info update into the data transaction (one atomic commit)."""
        cid = self._pg_cid(pg.pgid)
        version = pg.next_version(self.osdmap.epoch)
        prior = pg.log.index[oid].version if oid in pg.log.index \
            else EVERSION_ZERO
        entry = LogEntry(op=LOG_DELETE if is_delete else LOG_MODIFY,
                         oid=oid, version=version, prior_version=prior,
                         reqid=reqid)
        pg.record(entry)
        self.perf.inc("log_entries")
        t.touch(cid, PG.PGMETA)
        t.omap_setkeys(cid, PG.PGMETA, {
            PG.log_key(version): PG.encode_entry(entry),
            "info": pg.encode_info()})
        return entry

    # replicated pools ---------------------------------------------------------

    def _do_replicated_op(self, msg: MOSDOp, pool, pg: PG) -> None:
        up = pg.up
        cid = self._pg_cid(pg.pgid)
        reqid = (msg.client_id, msg.tid)
        t = Transaction()
        reply_ops: list[OSDOpField] = []
        result = 0
        is_write = False
        is_delete = False
        for op in msg.ops:
            if op.op in (OP_WRITE, OP_WRITEFULL):
                is_write = True
                is_delete = False
                if op.op == OP_WRITEFULL:
                    t.truncate(cid, msg.oid, 0)
                t.write(cid, msg.oid, op.offset, op.data)
            elif op.op == OP_DELETE:
                is_write = True
                is_delete = True
                t.remove(cid, msg.oid)
            elif op.op == OP_OMAP_SET:
                is_write = True
                is_delete = False
                keys = _decode_omap(op.data)
                t.touch(cid, msg.oid)
                t.omap_setkeys(cid, msg.oid, keys)
            elif op.op == OP_OMAP_RMKEYS:
                is_write = True
                is_delete = False
                t.omap_rmkeys(cid, msg.oid,
                              Decoder(op.data).list(lambda d: d.str()))
            elif op.op == OP_READ:
                try:
                    src_oid = msg.oid
                    if msg.snapid:
                        src_oid = self._resolve_snap(cid, msg.oid,
                                                     msg.snapid)
                    data = self.store.read(
                        cid, src_oid, op.offset,
                        op.length if op.length else None)
                    reply_ops.append(OSDOpField(OP_READ, op.offset,
                                                len(data), data))
                    self.perf.inc("op_r")
                except KeyError:
                    result = -2
            elif op.op == OP_STAT:
                try:
                    st = self.store.stat(cid, msg.oid)
                    reply_ops.append(OSDOpField(
                        OP_STAT, 0, st["size"], b""))
                except KeyError:
                    result = -2
            elif op.op == OP_PGLS:
                reply_ops.append(self._pgls_field(
                    cid, pool.is_erasure()))
            elif op.op == OP_OMAP_GET:
                try:
                    omap = self.store.omap_get(cid, msg.oid)
                    reply_ops.append(OSDOpField(
                        OP_OMAP_GET, 0, 0, _encode_omap(omap)))
                except KeyError:
                    result = -2
            elif op.op == OP_WATCH:
                with self._lock:
                    self._watchers.setdefault(
                        (msg.pgid, msg.oid), {})[msg.client_id] = \
                        msg.connection
                reply_ops.append(OSDOpField(OP_WATCH, 0, 0, b""))
            elif op.op == OP_UNWATCH:
                with self._lock:
                    self._watchers.get((msg.pgid, msg.oid), {}).pop(
                        msg.client_id, None)
            elif op.op == OP_NOTIFY:
                self._start_notify(msg, op)
                return   # replied when watchers ack (or timeout)
            elif op.op == OP_CALL:
                # in-OSD object class (ClassHandler::ClassMethod::exec)
                from ceph_tpu import cls as _cls
                try:
                    cname, method, inp = op.data.split(b"\0", 2)
                    handler = _cls.lookup(cname.decode(), method.decode())
                    if handler is None:
                        result = -95   # EOPNOTSUPP
                    else:
                        ctx = _cls.ClsContext(self.store, t, cid, msg.oid)
                        out = handler(ctx, inp)
                        if ctx.mutated:
                            is_write = True
                            is_delete = False
                        reply_ops.append(OSDOpField(OP_CALL, 0, 0,
                                                    out or b""))
                except PermissionError:
                    result = -13   # EACCES (e.g. cls_lock contention)
                except Exception:
                    result = -22
            else:
                result = -22
        if not is_write or result != 0:
            self._op_send_reply(msg, MOSDOpReply(
                tid=msg.tid, result=result, epoch=self.osdmap.epoch,
                ops=reply_ops))
            return
        # write path: dedup, log, local commit, replica fan-out (issue_repop)
        if self._dedup_resend(pg, reqid, msg):
            return
        if self._stale_retry(pg, msg):
            self._reply_err(msg, -125)   # ECANCELED: superseded op
            return
        self.perf.inc("op_w")
        t0 = time.time()
        # snapshot COW (PrimaryLogPG make_writeable): first write after
        # a pool snap clones the pre-write object to oid+CLONE_SEP+seq;
        # the clone's covered snap interval is (from_seq, snap_seq].
        # The effective seq is max(my map, the op's SnapContext): a
        # writer that learned of the snapshot before this OSD's map
        # caught up still triggers the clone (the reference orders this
        # through the per-op snapc, src/osd/PrimaryLogPG.cc
        # make_writeable)
        eff_seq = max(pool.snap_seq, getattr(msg, "write_snapc", 0))
        if eff_seq:
            obj_sc = int(self._getattr_safe(cid, msg.oid, "snapc")
                         or b"0")
            if obj_sc < eff_seq and self.store.exists(cid, msg.oid):
                clone = f"{msg.oid}{CLONE_SEP}{eff_seq}"
                pre = Transaction()
                pre.clone(cid, msg.oid, clone)
                pre.setattr(cid, clone, "from_seq", str(obj_sc).encode())
                pre.ops.extend(t.ops)
                t = pre
            if not is_delete:
                t.setattr(cid, msg.oid, "snapc",
                          str(eff_seq).encode())
        entry = self._log_write(pg, t, msg.oid, is_delete, reqid)
        if not is_delete:
            t.setattr(cid, msg.oid, "_v", enc_version(entry.version))
            if pool.tier_of >= 0 and not self._is_internal(msg):
                # cache tier: stamp dirtiness inside the SAME replicated
                # txn (the flush agent reads the stamp's age); promotion
                # installs (internal) stay clean
                t.setattr(cid, msg.oid, "_dirty",
                          str(time.time()).encode())
        self.store.apply_transaction(t)
        replicas = [o for o in up if o != self.osd_id and o != CEPH_NOSD]
        reply = MOSDOpReply(tid=msg.tid, result=0, epoch=self.osdmap.epoch,
                            ops=reply_ops)
        if not replicas:
            self.perf.tinc("op_w_latency", time.time() - t0)
            self._op_send_reply(msg, reply)
            return
        with self._lock:
            self._in_flight[reqid] = _InFlight(msg, set(replicas), reply)
        blob = t.encode()
        entry_blob = PG.encode_entry(entry)
        for rep in replicas:
            if self.debug_drop_rep_ops > 0:
                self.debug_drop_rep_ops -= 1
                continue
            con = self._osd_con(rep)
            if con is None:
                # address unknown this epoch: count it as an instant nack so
                # the op does not hang; the client retries on the next map
                self._ack_shard(reqid, rep, -107)
                continue
            con.send_message(MOSDRepOp(reqid=reqid, pgid=msg.pgid,
                                       oid=msg.oid, txn=blob,
                                       pg_version=entry.version,
                                       entry=entry_blob))
        self.perf.tinc("op_w_latency", time.time() - t0)

    def _handle_rep_op(self, msg: MOSDRepOp) -> None:
        self.perf.inc("op_rep")
        # a rep-op built before a PG split targets the parent; applying
        # its transaction here would strand the object in the parent
        # collection after this replica's own split.  Drop silently: the
        # primary's repop stalls, its own split tears it down and the
        # client's resend takes the post-split path
        pool = self.osdmap.pools.get(msg.pgid[0])
        if pool is not None:
            if self._park_subop(self._handle_rep_op, msg, pool):
                return
            base = self._base_oid(msg.oid, pool.is_erasure())
            if msg.oid and pg_to_pgid(ceph_str_hash_rjenkins(base),
                                      pool.pg_num) != msg.pgid[1]:
                return
        pg = self._get_pg(msg.pgid)
        entry = PG.decode_entry(msg.entry) if msg.entry else None
        # head-check, txn apply and log append must be one atomic step:
        # a concurrent peering merge advancing the head between them would
        # apply the data but trip record()'s ordering assert
        result = 0
        with self._lock:
            if entry is None or entry.version > pg.log.head:
                t = Transaction.decode(msg.txn)
                self.store.apply_transaction(t)
                if entry is not None:
                    pg.record(entry)
            elif not self._is_dup_entry(pg, entry):
                # an old interval's write racing a newer merged history:
                # the txn was NOT applied, and acking it would let a
                # deposed primary count a dropped write as committed
                result = -116  # ESTALE
        msg.connection.send_message(MOSDRepOpReply(
            reqid=msg.reqid, pgid=msg.pgid, from_osd=self.osd_id,
            result=result))

    @staticmethod
    def _is_dup_entry(pg: PG, entry: LogEntry) -> bool:
        """True if this exact entry is already in the log (primary
        resend), as opposed to a stale-interval write we discarded."""
        have = pg.log.reqids.get(entry.reqid) if entry.reqid != (0, 0) \
            else None
        return have == entry.version

    def _handle_rep_reply(self, msg: MOSDRepOpReply) -> None:
        self._ack_shard(msg.reqid, msg.from_osd, msg.result)

    def _ack_shard(self, reqid, from_osd: int, result: int) -> None:
        with self._lock:
            inf = self._in_flight.get(reqid)
            if inf is None:
                return
            inf.waiting.discard(from_osd)
            if result != 0:
                inf.reply.result = result
            if inf.waiting:
                return
            del self._in_flight[reqid]
        self._op_send_reply(inf.msg, inf.reply)

    # erasure pools ------------------------------------------------------------

    def _codec(self, pool):
        with self._lock:
            c = self._codecs.get(pool.pool_id)
            if c is None:
                profile = dict(pool.ec_profile)
                plugin = profile.pop("plugin", "jerasure")
                profile.setdefault(
                    "runtime", self.ctx.conf.get("erasure_code_runtime"))
                c = registry_instance().factory(plugin, profile)
                self._codecs[pool.pool_id] = c
            return c

    def _ec_stripe_info(self, codec, pool):
        """StripeInfo for MDS matrix codecs; None = whole-object layout
        (shec/lrc/clay encode through their own bespoke paths).  The
        stripe unit rounds up to the codec's per-chunk alignment quantum
        — bitmatrix techniques need chunk % w == 0."""
        if not getattr(codec, "supports_rmw_striping", False):
            return None
        from ceph_tpu.osd.ec_util import StripeInfo
        k = codec.get_data_chunk_count()
        su = int(pool.ec_profile.get("stripe_unit", 4096))
        quantum = max(1, codec.get_alignment() // k)
        su = -(-su // quantum) * quantum
        return StripeInfo(k, su)

    @staticmethod
    def _ec_live_shards(pg: PG, n: int) -> dict[int, int]:
        """{shard: osd} for the up-set slots currently holding a live
        OSD — every EC write path gates on this against min_size."""
        up = pg.up
        return {s: up[s] for s in range(min(n, len(up)))
                if up[s] != CEPH_NOSD}

    @staticmethod
    def _ec_shard_columns(si, stripes, parity, n: int) -> dict[int, bytes]:
        """Stack data+parity stripes, (S, n, su), and cut the per-shard
        columns the transactions and replica fan-out carry."""
        # analysis: allow[blocking] -- parity is the engine-delivered host array (completion thread materialized it)
        full = np.concatenate([stripes, np.asarray(parity)], axis=1)
        return {s: si.shard_column(full, s).tobytes() for s in range(n)}

    @staticmethod
    def _ec_encode_window(codec, si, data: bytes, s0: int,
                          s1: int) -> dict[int, bytes]:
        """Encode stripes [s0, s1) of `data` in one batched device call
        (the ECUtil::encode batch point): {shard: column bytes}."""
        n = codec.get_chunk_count()
        window = np.frombuffer(data[s0 * si.width:s1 * si.width],
                               dtype=np.uint8)
        stripes = si.split(window)
        return OSDDaemon._ec_shard_columns(
            si, stripes, codec.encode_chunks(stripes), n)

    def _ec_encode_object(self, codec, si, data: bytes) -> dict[int, bytes]:
        """Full object -> {shard: shard bytes}."""
        n = codec.get_chunk_count()
        if si is None:
            return codec.encode(set(range(n)), data)
        return self._ec_encode_window(codec, si, data, 0,
                                      si.object_stripes(len(data)))

    def _do_ec_op(self, msg: MOSDOp, pool, pg: PG) -> None:
        cid = self._pg_cid(pg.pgid)
        for op in msg.ops:
            if op.op in (OP_WRITE, OP_WRITEFULL):
                self._ec_write_op(msg, pool, pg, op)
                return
            if op.op == OP_READ:
                self.perf.inc("op_r")
                self._start_ec_read(msg, pool, pg.up, cid, op)
            elif op.op == OP_PGLS:
                # listing needs no shard gather: the primary's own
                # collection names every object (one shard each)
                self._op_send_reply(msg, MOSDOpReply(
                    tid=msg.tid, result=0, epoch=self.osdmap.epoch,
                    ops=[self._pgls_field(cid, True)]))
                return
            else:
                self._reply_err(msg, -22)
                return

    def _ec_write_op(self, msg: MOSDOp, pool, pg: PG, op) -> None:
        """ECBackend::submit_transaction -> start_rmw: full writes encode
        directly; partial writes first reconstruct the object (internal
        gather), overlay, then re-encode only the affected stripes."""
        codec = self._codec(pool)
        k = codec.get_data_chunk_count()
        n = codec.get_chunk_count()
        reqid = (msg.client_id, msg.tid)
        if self._dedup_resend(pg, reqid, msg):
            return
        if self._stale_retry(pg, msg):
            self._reply_err(msg, -125)   # ECANCELED: superseded op
            return
        shard_osds = self._ec_live_shards(pg, n)
        if len(shard_osds) < max(k, pool.min_size):
            # below min_size the write could never be re-read
            self._reply_err(msg, -11)
            return
        self.perf.inc("op_w")
        with self._lock:
            # ONE critical section from queue-join check through gate
            # install and state registration: a second writer must see
            # either no gate, or a fully-registered live gather — never
            # a gate whose state isn't in _ec_reads yet.  (Callers
            # already hold this RLock via _handle_op's dispatch block;
            # taking it here makes the invariant local.)
            #
            # Per-object write pipeline (ExtentCache reduced,
            # src/osd/ExtentCache.h:1-491): while an rmw gather is in
            # flight for this object, later writes — partial OR full —
            # join its queue in arrival order and will overlay onto the
            # gather's projected content with no second disk/shard read
            gid0 = pg.rmw.get(msg.oid)
            if gid0 is not None:
                st0 = self._ec_reads.get(gid0)
                if st0 is not None and st0.get("kind") == "rmw":
                    st0.setdefault("queue", []).append((msg, op))
                    self.perf.inc("ec_rmw_pipelined")
                    trk = getattr(msg, "_trk", None)
                    if trk is not None:
                        trk.mark_event("pipelined behind rmw gather")
                    return
                if st0 is not None and st0.get("kind") == "wpend":
                    # async commits in flight for this object, and the
                    # projected content is already known: chain directly
                    # onto it — no gather, and the new encode coalesces
                    # into the SAME device call as the pending one
                    if reqid in st0.get("reqids", ()):
                        # resend of a write whose commit is in flight:
                        # tcp resends are fresh objects (_dedup_resend's
                        # rule), so re-target the continuation's reply
                        # at the latest connection — the original may
                        # have arrived on one that is already dead
                        st0.setdefault("resends", {})[reqid] = msg
                        trk = getattr(msg, "_trk", None)
                        if trk is not None:
                            trk.mark_event(
                                "resend of in-flight async write")
                        return
                    last = st0.get("tids", {}).get(msg.client_id)
                    if last is not None and msg.tid < last:
                        # abandoned older op landing behind a newer
                        # in-flight write: executing it would roll the
                        # object back (same rule as _stale_retry)
                        self._reply_err(msg, -125)
                        return
                    if st0.get("failed"):
                        # poisoned gate: the projected base embeds a
                        # failed write's bytes — park until the gate
                        # releases, then re-execute against the last
                        # committed state
                        st0.setdefault("queue", []).append((msg, op))
                        return
                    self.perf.inc("ec_rmw_pipelined")
                    replace2 = op.op == OP_WRITEFULL
                    self._ec_apply_write(
                        msg, pool, pg, op,
                        old_data=b"" if replace2
                        else st0.get("base", b""),
                        replace=replace2)
                    return
                # stale gate from a torn-down gather: reclaim it
                pg.rmw.pop(msg.oid, None)
            existing = pg.log.index.get(msg.oid)
            fresh = existing is None or existing.is_delete()
            if op.op == OP_WRITEFULL or fresh:
                if op.op == OP_WRITEFULL or op.offset == 0:
                    self._ec_apply_write(msg, pool, pg, op, old_data=b"",
                                         replace=True)
                else:
                    # partial write to a fresh object: zero-fill base
                    self._ec_apply_write(msg, pool, pg, op, old_data=b"",
                                         replace=False)
                return
            # read-modify-write: gather the current object, then
            # continue.  The object is gated (pg.rmw); overlapping reads
            # park, further writes join this gather's pipeline queue
            self._recover_tid += 1
            gid = (RECOVERY_CLIENT + self.osd_id, self._recover_tid)
            pg.rmw[msg.oid] = gid
            si = self._ec_stripe_info(codec, pool)
            cand = self._ec_shard_candidates(pg, n)
            state = {"kind": "rmw", "msg": msg, "op": op, "pool": pool,
                     "pgid": msg.pgid, "oid": msg.oid, "si": si,
                     "shards": {}, "k": k, "active": set(), "cand": cand,
                     "need": existing.version, "started": time.time(),
                     "gid": gid, "queue": []}
            self._ec_reads[gid] = state
        self.perf.inc("ec_rmw_gather")
        self._ec_gather(gid, state)

    def _ec_rmw_ready(self, state: dict, old_data: bytes) -> None:
        """The rmw gather finished: overlay and apply.  Runs on a reply
        dispatch thread, so the apply (version allocation + log append +
        store commit) must retake the PG lock _handle_op holds on the
        direct path."""
        msg = state["msg"]
        pg = self.pgs.get(state["pgid"])
        if pg is None:
            # the PG left this OSD entirely (remap/removal): clients
            # resend on the map change, so no reply/requeue here
            with self._lock:
                self._ec_reads.pop(state.get("gid"), None)
            return
        with self._lock:
            if self._ec_reads.get(state.get("gid")) is not state:
                # the stuck-rmw watchdog or a teardown path claimed this
                # gather while the decode ran (popping it from _ec_reads
                # is the claim): it already replied/requeued — applying
                # here too would double-complete the op
                return
            if pg.rmw.get(msg.oid) != state.get("gid"):
                # an interval change orphaned this gather; a newer one
                # (or nobody) owns the gate now — applying pre-peering
                # old_data here would overlay a stale base.  Head and
                # pipelined writes requeue (never silently dropped);
                # post-activation dispatch dedups against the log
                self._ec_reads.pop(state.get("gid"), None)
                self._requeue_rmw_state(
                    state, pg, event="rmw gather orphaned: gate lost")
                return
            projected = self._ec_apply_write(msg, state["pool"], pg,
                                             state["op"],
                                             old_data=old_data,
                                             replace=False)
            base = old_data if projected is None else projected
            # drain the write pipeline: each queued write overlays onto
            # the previous write's projected content — ONE gather serves
            # the whole burst (the ExtentCache win).  New arrivals keep
            # appending under this same lock until the queue runs dry.
            q = state.get("queue") or []
            while q:
                m2, op2 = q.pop(0)
                # a map-change resend of an op already drained earlier in
                # this queue is in the log now: dedup it here exactly like
                # the direct path would, or it would apply twice.  With
                # async dispatch the earlier drain may still be
                # committing — its reqid sits in the state's pending set
                # rather than the log, so check both.  Don't just drop
                # it: the in-flight commit's reply must ride THIS (live)
                # connection, the original may be dead (same re-target
                # rule as the wpend branch and _dedup_resend's inf.msg)
                if (m2.client_id, m2.tid) in state.get("reqids", ()):
                    state.setdefault("resends", {})[
                        (m2.client_id, m2.tid)] = m2
                    continue
                if self._dedup_resend(pg, (m2.client_id, m2.tid), m2):
                    continue
                if self._stale_retry(pg, m2):
                    self._reply_err(m2, -125)
                    continue
                replace2 = op2.op == OP_WRITEFULL
                nxt = self._ec_apply_write(
                    m2, state["pool"], pg, op2,
                    old_data=b"" if replace2 else base,
                    replace=replace2)
                if nxt is not None:
                    base = nxt
            if state.get("pending"):
                # async encodes from this drain are still committing:
                # convert the gather gate into a pending-write gate and
                # let the LAST commit continuation release it — parked
                # readers must not see pre-commit shards
                state["kind"] = "wpend"
                state["started"] = time.time()
                waiting = []
            else:
                pg.rmw.pop(msg.oid, None)
                self._ec_reads.pop(state.get("gid"), None)
                waiting = pg.waiting_for_missing.pop(msg.oid, [])
        for m in waiting:
            self._handle_op(m)

    def _ec_apply_write(self, msg: MOSDOp, pool, pg: PG, op,
                        old_data: bytes, replace: bool) -> bytes | None:
        """Start one EC write: overlay, encode, commit, shard fan-out.
        With the dispatch engine on, the encode is SUBMITTED
        (submit-and-continue): this method returns after handing the
        affected stripes to the coalescing engine, and the
        transaction-build + fan-out runs in the completion continuation
        (_ec_write_committed) — the window in which a second client
        write lands its encode into the SAME device call.  Returns the
        full post-write object content — the projected base the rmw
        pipeline chains the next queued write onto — or None if the
        write was refused (reply already sent).  Caller holds
        self._lock."""
        codec = self._codec(pool)
        n = codec.get_chunk_count()
        k = codec.get_data_chunk_count()
        si = self._ec_stripe_info(codec, pool)
        shard_osds = self._ec_live_shards(pg, n)
        # the rmw gather is asynchronous: re-check the min_size gate
        # against the CURRENT up set before committing anything
        if len(shard_osds) < max(k, pool.min_size):
            self._reply_err(msg, -11)
            return None
        if replace:
            data = bytes(op.data)
        else:
            new_size = max(len(old_data), op.offset + len(op.data))
            buf = bytearray(new_size)
            buf[:len(old_data)] = old_data
            buf[op.offset:op.offset + len(op.data)] = op.data
            data = bytes(buf)
        self.perf.inc("ec_encode_stripes")
        t_kernel = time.perf_counter()
        if si is not None and not replace and old_data:
            # ranged: encode ONLY the affected stripes (the batched
            # device call covers [s0, s1)); only those columns travel
            # on growth s1 from stripe_range already equals
            # object_stripes(new_size): new_size = offset + len there
            s0, s1 = si.stripe_range(op.offset, len(op.data))
            shard_off = s0 * si.su
            shard_len = si.shard_len(len(data))
            truncate = False
        elif si is not None:
            s0, s1 = 0, si.object_stripes(len(data))
            shard_off, truncate = 0, True
            shard_len = si.shard_len(len(data))
        else:
            s0 = s1 = 0
            shard_off, truncate = 0, True
            shard_len = 0
        engine = (self.ctx.dispatch_engine()
                  if self._ec_async and si is not None else None)
        if engine is None and si is not None:
            # the async knob was toggled off with commits still in
            # flight for this object: a synchronous commit here would
            # log ahead of them and the object would roll back when
            # their continuations land — ride the engine's per-key
            # FIFO behind the pending writes instead
            gid0 = pg.rmw.get(msg.oid)
            st0 = (self._ec_reads.get(gid0)
                   if gid0 is not None else None)
            if (st0 is not None and st0.get("kind") == "wpend"
                    and st0.get("pending")):
                engine = self.ctx.dispatch_engine()
        if engine is None:
            # synchronous path: whole-object codecs (shec/lrc/clay
            # encode through their own bespoke layouts) and the async
            # knob off
            if si is None:
                sub = self._ec_encode_object(codec, si, data)
                shard_len = (len(next(iter(sub.values())))
                             if sub else 0)
            else:
                sub = self._ec_encode_window(codec, si, data, s0, s1)
            # device residency on the op's timeline (and, via the trace
            # id, in the cross-daemon span ring): a traced client op
            # shows where its TPU time went
            trk = getattr(msg, "_trk", None)
            if trk is not None:
                trk.mark_event(
                    "ec_encode kernel "
                    f"{(time.perf_counter() - t_kernel) * 1e3:.3f}ms")
            self._ec_write_commit(msg, pool, pg, sub, data, shard_osds,
                                  shard_off, shard_len, truncate)
            return data
        # submit-and-continue: gate the object (readers park, later
        # writes chain onto the projected base), stack the affected
        # stripes onto the engine's batch axis, return
        st = self._ec_wpend_state(pg, msg.oid)
        reqid = (msg.client_id, msg.tid)
        st.setdefault("reqids", set()).add(reqid)
        tids = st.setdefault("tids", {})
        if msg.tid >= tids.get(msg.client_id, 0):
            tids[msg.client_id] = msg.tid
        st["pending"] = st.get("pending", 0) + 1
        st["base"] = data
        window = np.frombuffer(data[s0 * si.width:s1 * si.width],
                               dtype=np.uint8)
        stripes = si.split(window)
        fut = codec.submit_chunks(
            engine, stripes,
            cost_tag=(getattr(msg, "qos_tenant", "") or "client",
                      "client"))
        self.perf.inc("ec_dispatch_submits")
        trk = getattr(msg, "_trk", None)
        if trk is not None:
            trk.mark_event(
                f"ec_encode submitted ({stripes.shape[0]} stripes)")
        cctx = {"msg": msg, "pool": pool, "pgid": pg.pgid,
                "oid": msg.oid, "gid": st["gid"], "state": st,
                "data": data, "stripes": stripes, "n": n, "k": k,
                "si": si, "shard_off": shard_off,
                "shard_len": shard_len, "truncate": truncate,
                "t0": t_kernel}
        fut.add_done_callback(
            lambda f, c=cctx: self._ec_write_committed(c, f))
        return data

    def _ec_wpend_state(self, pg: PG, oid: str) -> dict:
        """Find or create the pending-write gate for an object with
        async commits in flight (kind "wpend").  An in-flight rmw
        gather's state doubles as the gate until _ec_rmw_ready's drain
        converts it.  Caller holds self._lock."""
        gid = pg.rmw.get(oid)
        st = self._ec_reads.get(gid) if gid is not None else None
        if st is None or st.get("oid") != oid:
            self._recover_tid += 1
            gid = (RECOVERY_CLIENT + self.osd_id, self._recover_tid)
            st = {"kind": "wpend", "pgid": pg.pgid, "oid": oid,
                  "gid": gid, "queue": [], "started": time.time(),
                  "pending": 0, "reqids": set(), "tids": {},
                  "base": b""}
            pg.rmw[oid] = gid
            self._ec_reads[gid] = st
        return st

    def _ec_write_committed(self, c: dict, fut) -> None:
        """Completion continuation for a submitted EC write (runs on
        the engine's completion thread, in per-object submission order
        — the engine's delivery contract IS the log/commit ordering):
        build the transactions, apply locally, fan out, reply, and
        release the pending-write gate once the last in-flight commit
        for the object lands."""
        msg = c["msg"]
        # re-join the op's trace: this engine thread has no trace
        # context, but the commit's shard fan-out must carry the op's
        # trace id so replica dispatch spans stitch into one tree
        tid = getattr(msg, "trace_id", 0)
        from ceph_tpu.common import tracing
        if tid and tracing.current() != tid:
            prev = tracing.set_current(
                tid, getattr(msg, "parent_span_id", 0))
            try:
                return self._ec_write_committed(c, fut)
            finally:
                tracing.set_current(prev)
        st = c["state"]
        reqid = (msg.client_id, msg.tid)
        waiting: list = []
        requeue: list = []
        try:
            self._ec_write_committed_locked(c, fut, msg, st, reqid,
                                            waiting, requeue)
        finally:
            # OUTER finally: an exception escaping the commit (store or
            # send error) must not strand the ops the gate release just
            # popped out of every parking structure — nothing else
            # (tick reap, map change) would ever replay them
            for m in requeue:
                self._handle_op(m)
            for m in waiting:
                self._handle_op(m)

    def _ec_write_committed_locked(self, c: dict, fut, msg, st: dict,
                                   reqid, waiting: list,
                                   requeue: list) -> None:
        """Locked half of _ec_write_committed.  Ops to re-dispatch are
        EXTENDED into waiting/requeue (never rebound) so the caller's
        outer finally sees them even if the commit raises."""
        with self._lock:
            pg = self.pgs.get(c["pgid"])
            live = (pg is not None
                    and self._ec_reads.get(c["gid"]) is st
                    and pg.rmw.get(c["oid"]) == c["gid"])
            if not live:
                # the gate was torn down (interval change, split, PG
                # removal) before this commit landed: nothing was
                # logged or applied for this write yet, so drop it
                # whole — the map change that tore the gate down makes
                # the client resend and the write re-executes fresh
                trk = getattr(msg, "_trk", None)
                if trk is not None:
                    trk.mark_event(
                        "async commit dropped: gate torn down")
                return
            m2 = st.get("resends", {}).pop(reqid, None)
            if m2 is not None and m2 is not msg:
                # client resent while this commit was in flight: the
                # reply must ride the resend's (live) connection
                trk = getattr(msg, "_trk", None)
                if trk is not None:
                    trk.mark_event("superseded by client resend")
                    trk.finish()
                msg = c["msg"] = m2
            st["pending"] = st.get("pending", 1) - 1
            st.get("reqids", set()).discard(reqid)
            try:
                err = fut.exception()
                if err is not None or st.get("failed"):
                    # a failed commit poisons the gate: every later
                    # in-flight encode chained onto st["base"] embeds
                    # the failed write's bytes, and committing it
                    # would durably apply data whose client was told
                    # "error".  Fail the whole chain; retries re-
                    # execute against the last COMMITTED state once
                    # the gate releases
                    st["failed"] = True
                    if err is not None:
                        dout("osd", 1, "osd.%d async ec encode failed "
                             "for %s: %r", self.osd_id, c["oid"], err)
                    self._reply_err(msg, -5)
                else:
                    n, si, pool = c["n"], c["si"], c["pool"]
                    shard_osds = self._ec_live_shards(pg, n)
                    if len(shard_osds) < max(c["k"], pool.min_size):
                        st["failed"] = True
                        self._reply_err(msg, -11)
                    else:
                        sub = self._ec_shard_columns(
                            si, c["stripes"], fut.result(), n)
                        trk = getattr(msg, "_trk", None)
                        if trk is not None:
                            trk.mark_event(
                                "ec_encode kernel "
                                f"{(time.perf_counter() - c['t0']) * 1e3:.3f}"
                                "ms (async)")
                        self._ec_write_commit(
                            msg, pool, pg, sub, c["data"], shard_osds,
                            c["shard_off"], c["shard_len"],
                            c["truncate"])
                        self.perf.inc("ec_dispatch_commits")
            finally:
                if not st.get("pending") and st.get("kind") == "wpend":
                    pg.rmw.pop(c["oid"], None)
                    self._ec_reads.pop(c["gid"], None)
                    requeue.extend(
                        m for m, _op in st.get("queue") or [])
                    waiting.extend(
                        pg.waiting_for_missing.pop(c["oid"], []))

    def _ec_write_commit(self, msg: MOSDOp, pool, pg: PG, sub: dict,
                         data: bytes, shard_osds: dict, shard_off: int,
                         shard_len: int, truncate: bool) -> None:
        """Commit one encoded EC write: version allocation + log append
        + local shard transactions + replica fan-out + client reply.
        Caller holds self._lock (the direct path holds it across the
        encode; the async continuation retakes it)."""
        cid = self._pg_cid(pg.pgid)
        reqid = (msg.client_id, msg.tid)
        reply = MOSDOpReply(tid=msg.tid, result=0, epoch=self.osdmap.epoch)
        meta_t = Transaction()
        entry = self._log_write(pg, meta_t, msg.oid, is_delete=False,
                                reqid=reqid)
        entry_blob = PG.encode_entry(entry)
        v_attr = enc_version(entry.version)
        size_attr = str(len(data)).encode()
        from ceph_tpu.osd.ec_util import HashInfo
        waiting = set()
        for shard, osd in shard_osds.items():
            if osd != self.osd_id:
                waiting.add(osd)
                continue
            soid = f"{msg.oid}:{shard}"
            new_shard, base_ok = self._patched_shard(
                pg.pgid, msg.oid, shard, sub[shard], shard_off,
                shard_len, truncate,
                expected_prior=entry.prior_version)
            t = Transaction()
            if base_ok:
                (t.truncate(cid, soid, 0)
                 .write(cid, soid, 0, new_shard)
                 .setattr(cid, soid, "size", size_attr)
                 .setattr(cid, soid, "_v", v_attr)
                 .setattr(cid, soid, "hinfo",
                          HashInfo.compute(new_shard)))
            # unusable base: the shard stays untouched with its stale
            # version/hash (detected-bad everywhere) until the scheduled
            # repair rewrites it; only the log entry lands now
            t.ops.extend(meta_t.ops)
            self.store.apply_transaction(t)
        with self._lock:
            if waiting:
                self._in_flight[reqid] = _InFlight(msg, set(waiting),
                                                   reply)
        for shard, osd in shard_osds.items():
            if osd == self.osd_id:
                continue
            con = self._osd_con(osd)
            if con is None:
                self._ack_shard(reqid, osd, -107)
                continue
            con.send_message(MOSDECSubOpWrite(
                reqid=reqid, pgid=msg.pgid, oid=f"{msg.oid}:{shard}",
                shard=shard, chunk=sub[shard], epoch=self.osdmap.epoch,
                obj_size=len(data), entry=entry_blob,
                offset=shard_off, shard_len=shard_len,
                truncate=truncate))
        if not waiting:
            self._op_send_reply(msg, reply)

    def _patched_shard(self, pgid, oid: str, shard: int, chunk: bytes,
                       offset: int, shard_len: int, truncate: bool,
                       expected_prior=None) -> tuple[bytes, bool]:
        """(full post-write shard bytes, base_ok).  Whole replacements
        are the chunk itself; ranged writes patch the existing shard in
        memory — but ONLY onto a trustworthy base: the old bytes must
        pass their checksum AND sit at the write's prior_version (a
        shard that silently missed an intermediate write must not be
        patched into mixed-version content with a fresh valid hash).
        A bad base is left untouched — its stale version/hash keep it
        detected-bad in every gather — and a repair is scheduled."""
        from ceph_tpu.osd.ec_util import HashInfo
        if truncate:
            return chunk, True
        cid = f"{pgid[0]}.{pgid[1]}"
        soid = f"{oid}:{shard}"
        try:
            old = self.store.read(cid, soid)
        except KeyError:
            old = b""
        base_ok = HashInfo.matches(old, self._getattr_safe(cid, soid,
                                                           "hinfo"))
        if base_ok and expected_prior is not None:
            have = dec_version(self._getattr_safe(cid, soid, "_v"))
            base_ok = have == expected_prior
        if not base_ok:
            dout("osd", 1, "osd.%d shard %s/%s base unusable for ranged "
                 "write (corrupt or missed a prior write); scheduling "
                 "repair", self.osd_id, cid, soid)
            pg = self.pgs.get(pgid)
            if pg is not None:
                self._recover_ec_object(pg, oid, dest_osd=self.osd_id,
                                        dest_shard=shard)
            return old, False
        buf = bytearray(max(shard_len, len(old)))
        buf[:len(old)] = old
        buf[offset:offset + len(chunk)] = chunk
        out = bytes(buf[:shard_len]) if shard_len else bytes(buf)
        return out, True

    def _handle_ec_write(self, msg: MOSDECSubOpWrite) -> None:
        pool = self.osdmap.pools.get(msg.pgid[0])
        if pool is not None:
            if self._park_subop(self._handle_ec_write, msg, pool):
                return
            base = self._base_oid(msg.oid, True)
            if msg.oid and pg_to_pgid(ceph_str_hash_rjenkins(base),
                                      pool.pg_num) != msg.pgid[1]:
                return   # pre-split shard write: see _handle_rep_op

        oid = msg.oid
        cid = f"{msg.pgid[0]}.{msg.pgid[1]}"
        pg = self._get_pg(msg.pgid)
        entry = PG.decode_entry(msg.entry) if msg.entry else None
        from ceph_tpu.osd.ec_util import HashInfo
        # atomic head-check + apply + append (see _handle_rep_op)
        result = 0
        logical, _, shard_s = oid.rpartition(":")
        with self._lock:
            if entry is None or entry.version > pg.log.head:
                new_shard, base_ok = self._patched_shard(
                    msg.pgid, logical, int(shard_s), msg.chunk,
                    msg.offset, msg.shard_len, msg.truncate,
                    expected_prior=(entry.prior_version
                                    if entry is not None else None))
                t = Transaction()
                if base_ok:
                    (t.truncate(cid, oid, 0)
                     .write(cid, oid, 0, new_shard)
                     .setattr(cid, oid, "size",
                              str(msg.obj_size).encode())
                     .setattr(cid, oid, "hinfo",
                              HashInfo.compute(new_shard)))
                    if entry is not None:
                        t.setattr(cid, oid, "_v",
                                  enc_version(entry.version))
                if entry is not None:
                    t.touch(cid, PG.PGMETA)
                    pg.record(entry)
                    t.omap_setkeys(cid, PG.PGMETA, {
                        PG.log_key(entry.version): PG.encode_entry(entry),
                        "info": pg.encode_info()})
                self.store.apply_transaction(t)
            elif not self._is_dup_entry(pg, entry):
                result = -116  # ESTALE: stale-interval shard write dropped
        msg.connection.send_message(MOSDECSubOpWriteReply(
            reqid=msg.reqid, shard=msg.shard, from_osd=self.osd_id,
            result=result))

    def _handle_ec_write_reply(self, msg: MOSDECSubOpWriteReply) -> None:
        self._ack_shard(msg.reqid, msg.from_osd, msg.result)

    def _start_ec_read(self, msg: MOSDOp, pool, up, cid: str,
                       op=None) -> None:
        """objects_read_and_reconstruct analog: gather k shards, decode.
        op carries the byte range; today full shards travel and the
        whole object decodes before slicing (ranged shard reads over
        the wire are a known optimization, not yet done)."""
        codec = self._codec(pool)
        k = codec.get_data_chunk_count()
        n = codec.get_chunk_count()
        reqid = (msg.client_id, msg.tid)
        pg = self.pgs.get(msg.pgid)
        cand = (self._ec_shard_candidates(pg, n) if pg is not None
                else {s: [up[s]] for s in range(min(n, len(up)))
                      if up[s] != CEPH_NOSD})
        if sum(1 for c in cand.values() if c) < k:
            # fewer than k shards locatable: unreadable this epoch
            self._reply_err(msg, -5)
            return
        entry = pg.log.index.get(msg.oid) if pg is not None else None
        state = {"kind": "client", "msg": msg, "pool": pool,
                 "pgid": msg.pgid, "oid": msg.oid,
                 "off": op.offset if op is not None else 0,
                 "len": op.length if op is not None else 0,
                 # the logged version pins the stripe: past-interval
                 # holders may serve stale chunks that must not be mixed
                 # into the decode
                 "need": entry.version if entry is not None
                 and not entry.is_delete() else None,
                 "shards": {}, "k": k, "active": set(), "cand": cand}
        with self._lock:
            self._ec_reads[reqid] = state
        self._ec_gather(reqid, state)

    def _ec_gather(self, reqid, state: dict) -> None:
        """Keep enough shard reads in flight to reach k results
        (get_min_avail_to_read_shards + the retry ladder, unified)."""
        while True:
            with self._lock:
                if reqid not in self._ec_reads:
                    return
                have = len(state["shards"]) + len(state["active"])
                if have >= state["k"]:
                    return
                # lowest-index shard with a candidate left, not already
                # satisfied or in flight (prefer data shards)
                pick = None
                for s in sorted(state["cand"]):
                    if (s not in state["shards"]
                            and s not in state["active"]
                            and state["cand"][s]):
                        pick = s
                        break
                if pick is None:
                    del self._ec_reads[reqid]
                    give_up = True
                    if state["kind"] == "rmw":
                        # fail while still holding the lock (_rmw_fail
                        # contract: no gate-reclaim window)
                        self._rmw_fail(state)
                        return
                else:
                    give_up = False
                    osd = state["cand"][pick].pop(0)
                    state["active"].add(pick)
            if give_up:
                self._ec_read_give_up(state)
                return
            self._ec_ask(reqid, state, pick, osd)

    def _ec_ask(self, reqid, state: dict, shard: int, osd: int) -> None:
        pgid = state["pgid"]
        oid = state["oid"]
        if osd == self.osd_id:
            self._ec_read_local(reqid, oid, f"{pgid[0]}.{pgid[1]}", shard)
            return
        con = self._osd_con(osd)
        if con is None:
            self._ec_read_failed(reqid, shard)
            return
        con.send_message(MOSDECSubOpRead(
            reqid=reqid, pgid=pgid, oid=oid, shard=shard))

    def _read_shard_verified(self, pgid, oid: str, shard):
        """(chunk, size, ver) of a local shard, or None on absence OR a
        HashInfo checksum mismatch — a corrupt shard is as good as
        missing, and a repair reconstruct is scheduled (ECUtil HashInfo
        semantics)."""
        from ceph_tpu.osd.ec_util import HashInfo
        cid = f"{pgid[0]}.{pgid[1]}"
        soid = f"{oid}:{shard}"
        try:
            chunk = self.store.read(cid, soid)
            size = int(self.store.getattr(cid, soid, "size"))
        except (KeyError, TypeError):
            return None
        hinfo = self._getattr_safe(cid, soid, "hinfo")
        if not HashInfo.matches(chunk, hinfo):
            dout("osd", 1, "osd.%d shard %s/%s failed checksum; "
                 "scheduling repair", self.osd_id, cid, soid)
            pg = self.pgs.get(pgid)
            if pg is not None:
                self._recover_ec_object(pg, oid, dest_osd=self.osd_id,
                                        dest_shard=shard)
            return None
        ver = dec_version(self._getattr_safe(cid, soid, "_v")) \
            or EVERSION_ZERO
        return chunk, size, ver

    def _ec_read_local(self, reqid, oid: str, cid: str, shard) -> None:
        state = self._ec_reads.get(reqid)
        pgid = state["pgid"] if state else tuple(
            int(x) for x in cid.split("."))
        got = self._read_shard_verified(pgid, oid, shard)
        if got is None:
            self._ec_read_failed(reqid, shard)
            return
        self._ec_read_done(reqid, shard, *got)

    def _handle_ec_read(self, msg: MOSDECSubOpRead) -> None:
        pool = self.osdmap.pools.get(msg.pgid[0])
        if pool is not None and self._park_subop(
                self._handle_ec_read, msg, pool):
            return

        got = self._read_shard_verified(msg.pgid, msg.oid, msg.shard)
        if got is None:
            msg.connection.send_message(MOSDECSubOpReadReply(
                reqid=msg.reqid, shard=msg.shard, from_osd=self.osd_id,
                result=-2, chunk=b""))
            return
        chunk, size, ver = got
        msg.connection.send_message(MOSDECSubOpReadReply(
            reqid=msg.reqid, shard=msg.shard, from_osd=self.osd_id,
            result=0, ver=ver,
            chunk=chunk + size.to_bytes(8, "little")))

    def _handle_ec_read_reply(self, msg: MOSDECSubOpReadReply) -> None:
        if msg.result != 0:
            self._ec_read_failed(msg.reqid, msg.shard)
            return
        chunk, size = msg.chunk[:-8], int.from_bytes(msg.chunk[-8:],
                                                     "little")
        self._ec_read_done(msg.reqid, msg.shard, chunk, size, msg.ver)

    def _ec_read_failed(self, reqid, shard: int) -> None:
        with self._lock:
            state = self._ec_reads.get(reqid)
            if state is None:
                return
            state["active"].discard(shard)
        self._ec_gather(reqid, state)

    def _ec_read_give_up(self, state: dict) -> None:
        """Terminal gather failure for client reads and recovery pulls.
        rmw gathers go through _rmw_fail instead (atomically, under the
        lock that popped them)."""
        if state["kind"] == "client":
            self._reply_err(state["msg"], -5)
            return
        pg = self.pgs.get(state["pgid"])
        if pg is not None:
            with self._lock:
                pg.recovering.pop(state["oid"], None)

    def _rmw_fail(self, state: dict) -> None:
        """Fail an rmw gather whose state the CALLER just popped from
        _ec_reads, while STILL HOLDING self._lock: the gate release, the
        head's error reply, and the re-dispatch of pipelined writes all
        land before any new write can observe the stale gate — a new
        write slipping in between would reclaim the gate and apply ahead
        of the older queued writes (per-object order inversion)."""
        pg = self.pgs.get(state["pgid"])
        if pg is not None and pg.rmw.get(state["oid"]) == state.get("gid"):
            pg.rmw.pop(state["oid"], None)
        self._reply_err(state["msg"], -5)
        # pipelined writes re-dispatch in order: the first starts a fresh
        # gather and the rest join its queue, all under this lock
        for m2, _op2 in state.get("queue") or []:
            self._handle_op(m2)

    def _requeue_rmw_state(self, st: dict | None, dest_pg: PG,
                           event: str | None = None) -> None:
        """Requeue a torn-down rmw gather's client op and its pipelined
        queue onto dest_pg.waiting_for_active (caller holds the lock;
        split and interval-change teardown share this)."""
        if st is None:
            return
        m = st.get("msg")
        if m is not None:
            if event:
                trk = getattr(m, "_trk", None)
                if trk is not None:
                    trk.mark_event(event)
            dest_pg.waiting_for_active.append(m)
        for m2, _op2 in st.get("queue") or []:
            dest_pg.waiting_for_active.append(m2)

    def _ec_read_done(self, reqid, shard: int, chunk: bytes,
                      size: int, ver) -> None:
        with self._lock:
            state = self._ec_reads.get(reqid)
            if state is None:
                return
            state["active"].discard(shard)
            need = state.get("need")
            stale = need is not None and ver != need
            if not stale:
                state["shards"][shard] = chunk
                state["size"] = size
                if len(state["shards"]) < state["k"]:
                    return
        if stale:
            self._ec_gather(reqid, state)
            return
        if self._ec_submit_decode(reqid, state):
            # submit-and-continue: the decode rides the decode engine
            # (coalescing with every other in-flight gather's decode —
            # even under DIFFERENT erasure patterns) and the completion
            # continuation finishes the read
            return
        try:
            data = self._ec_decode_state(state)
        except (ValueError, IOError):
            # non-MDS codecs cannot decode from every k-subset: widen
            # the gather by one shard and keep going.  IOError is the
            # bitmatrix/shec spelling; a plain matrix codec whose
            # chosen rows are singular raises ValueError from
            # recovery_matrix (unreachable for the bundled MDS codecs,
            # but a third-party generator must widen, not wedge)
            with self._lock:
                state["k"] = len(state["shards"]) + 1
            self._ec_gather(reqid, state)
            return
        self._ec_read_finish(reqid, state, data)

    def _ec_submit_decode(self, reqid, state: dict) -> bool:
        """Submit the gather's reconstruction through the decode
        dispatch engine: True when the completion continuation now owns
        the rest of the read.  False falls back to the synchronous
        path — whole-object codecs (si None), packet-level bitmatrix
        codecs, the knob off, a widened (non-MDS) gather, no missing
        data rows, or a singular chosen set (the widen ladder handles
        that one just like the sync decode's IOError)."""
        if not self._ec_decode_async:
            return False
        pool = state["pool"]
        codec = self._codec(pool)
        if not getattr(codec, "supports_submit_decode", False):
            return False
        si = self._ec_stripe_info(codec, pool)
        if si is None:
            return False
        k = codec.get_data_chunk_count()
        if state["k"] != k:
            return False
        # cheap pre-check BEFORE any array assembly: a healthy read
        # (all k data shards gathered) needs no device call, and the
        # sync fallback would otherwise redo the whole assembly
        if all(s < k for s in sorted(state["shards"])[:k]):
            return False
        size = state["size"]
        chosen, arr, targets, stripes = self._ec_gathered_stripes(
            si, k, state["shards"], size)
        # targets cannot be empty here: the pre-check above bailed on
        # the all-data-shards case, so at least one parity shard is in
        # `chosen` and at least one data row is missing
        engine = self.ctx.decode_dispatch_engine()
        if state["kind"] == "recover":
            tag = ("recovery", "recovery")
        else:
            tag = (getattr(state.get("msg"), "qos_tenant", "")
                   or "client", "client")
        try:
            fut = codec.submit_decode_chunks(engine, chosen, arr,
                                             targets, cost_tag=tag)
        except (ValueError, IOError):
            return False
        self.perf.inc("ec_decode_submits")
        if state["kind"] == "recover":
            self.perf.inc("recovery_decode_stripes", int(arr.shape[0]))
        trk = getattr(state.get("msg"), "_trk", None)
        if trk is not None:
            trk.mark_event(
                f"ec_decode submitted ({arr.shape[0]} stripes, "
                f"{len(targets)} targets)")
        cctx = (reqid, state, si, stripes, targets, size)
        fut.add_done_callback(
            lambda f, c=cctx: self._ec_decode_done(*c, f))
        return True

    def _ec_decode_done(self, reqid, state: dict, si, stripes, targets,
                        size: int, fut) -> None:
        """Decode-engine completion continuation (runs on the decode
        engine's completion thread): overlay the rebuilt rows and
        finish the gather — client reply, rmw overlay-and-drain, or
        recovery store/push."""
        err = fut.exception()
        if err is not None:
            # device-side failure: re-enter the retry ladder exactly
            # like the synchronous decode's IOError widen
            dout("osd", 1, "osd.%d async ec decode failed for %s: %r",
                 self.osd_id, state.get("oid"), err)
            with self._lock:
                if self._ec_reads.get(reqid) is not state:
                    return
                state["k"] = len(state["shards"]) + 1
            self._ec_gather(reqid, state)
            return
        # analysis: allow[blocking] -- fut already delivered: engine futures carry host numpy
        rec = np.asarray(fut.result())
        for idx, d in enumerate(targets):
            stripes[:, d, :] = rec[:, idx, :]
        data = si.join(stripes).tobytes()[:size]
        # re-join the op's trace: the completion thread has no trace
        # context, but the reply / shard fan-out must stitch into the
        # op's span tree (same rule as _ec_write_committed)
        msg = state.get("msg")
        tid = getattr(msg, "trace_id", 0) if msg is not None else 0
        from ceph_tpu.common import tracing
        if tid and tracing.current() != tid:
            prev = tracing.set_current(
                tid, getattr(msg, "parent_span_id", 0))
            try:
                self._ec_read_finish(reqid, state, data)
            finally:
                tracing.set_current(prev)
            return
        self._ec_read_finish(reqid, state, data)

    def _ec_read_finish(self, reqid, state: dict, data: bytes) -> None:
        """Reconstructed object bytes in hand (synchronous decode or
        decode-engine continuation): complete the gather by kind."""
        if state["kind"] == "rmw":
            # the rmw state stays registered in _ec_reads until the
            # pipeline drain completes: a write arriving in this window
            # must find it live and join its queue, not mistake the gate
            # for a torn-down gather and usurp it (_ec_rmw_ready pops;
            # it also detects a gate lost to an interval change while
            # an async decode was in flight and requeues instead)
            self._ec_rmw_ready(state, data)
            return
        with self._lock:
            if self._ec_reads.get(reqid) is not state:
                # superseded while the decode was in flight (a client
                # resend re-registered this reqid with a fresh gather,
                # or a teardown claimed the state): the live owner
                # replies — a completion here would double-reply or
                # double-push
                return
            self._ec_reads.pop(reqid, None)
        if state["kind"] == "client":
            msg = state["msg"]
            off = state.get("off", 0)
            length = state.get("len", 0)
            data = data[off:off + length] if length else data[off:]
            self._op_send_reply(msg, MOSDOpReply(
                tid=msg.tid, result=0, epoch=self.osdmap.epoch,
                ops=[OSDOpField(OP_READ, off, len(data), data)]))
            return
        self._ec_recover_done(state, data)

    @staticmethod
    def _ec_gathered_stripes(si, k: int, shards: dict, size: int):
        """Shared shard-to-array assembly for the sync and async decode
        paths (they MUST reconstruct identically whatever the
        osd_ec_decode_async setting): (chosen, arr (S, k_chosen, su) of
        gathered columns, missing data-row targets, stripes buffer
        with the surviving data rows scattered in)."""
        shard_len = si.shard_len(size)
        chosen = sorted(shards)[:k]
        cols = []
        for s in chosen:
            b = shards[s]
            if len(b) < shard_len:    # short shard: zero-extend
                b = b + bytes(shard_len - len(b))
            cols.append(np.frombuffer(b[:shard_len], dtype=np.uint8)
                        .reshape(-1, si.su))
        arr = np.stack(cols, axis=1)             # (S, k, su)
        targets = [d for d in range(k) if d not in set(chosen)]
        stripes = np.zeros((arr.shape[0], k, si.su), dtype=np.uint8)
        for i, s in enumerate(chosen):
            if s < k:
                stripes[:, s, :] = arr[:, i, :]
        return chosen, arr, targets, stripes

    def _ec_decode_state(self, state: dict) -> bytes:
        """Gathered shards -> full object bytes.  Striped pools decode
        all stripes in one batched device call; whole-object pools go
        through the codec's own decode."""
        pool = state["pool"]
        codec = self._codec(pool)
        k = codec.get_data_chunk_count()
        si = self._ec_stripe_info(codec, pool)
        size = state["size"]
        shards = state["shards"]
        if si is None:
            decoded = codec.decode(set(range(k)), dict(shards))
            return b"".join(decoded[i] for i in range(k))[:size]
        chosen, arr, targets, stripes = self._ec_gathered_stripes(
            si, k, shards, size)
        if targets:
            # analysis: allow[blocking] -- synchronous scalar fallback path: decode_chunks returns host numpy
            rec = np.asarray(codec.decode_chunks(chosen, arr, targets))
            for idx, d in enumerate(targets):
                stripes[:, d, :] = rec[:, idx, :]
        return si.join(stripes).tobytes()[:size]

    def _ec_recover_done(self, state: dict, data: bytes) -> None:
        """Reconstructed the full object: re-encode and deliver the
        destination shard's chunk.  With async dispatch on, the
        re-encode SUBMITS through the encode engine — the reservation
        window's concurrent in-flight pulls coalesce their re-encodes
        into one device call — and the store/push runs in the
        continuation."""
        pool = state["pool"]
        codec = self._codec(pool)
        si = self._ec_stripe_info(codec, pool)
        if self._ec_async and si is not None:
            stripes = si.split(np.frombuffer(data, dtype=np.uint8))
            n = codec.get_chunk_count()
            fut = codec.submit_chunks(self.ctx.dispatch_engine(),
                                      stripes,
                                      cost_tag=("recovery", "recovery"))
            self.perf.inc("ec_dispatch_submits")
            fut.add_done_callback(
                lambda f, c=(state, data, si, stripes, n):
                self._ec_recover_encoded(*c, f))
            return
        chunks = self._ec_encode_object(codec, si, data)
        self._ec_recover_store(state, data, chunks)

    def _ec_recover_encoded(self, state: dict, data: bytes, si,
                            stripes, n: int, fut) -> None:
        """Encode-engine continuation for a recovery re-encode."""
        err = fut.exception()
        if err is not None:
            # the pull itself succeeded; a failed re-encode just
            # releases the recovering gate so the recovery window can
            # retry the object (it is still missing)
            dout("osd", 1, "osd.%d recovery re-encode failed for "
                 "%s: %r", self.osd_id, state.get("oid"), err)
            pg = self.pgs.get(state["pgid"])
            if pg is not None:
                with self._lock:
                    pg.recovering.pop(state["oid"], None)
            return
        chunks = self._ec_shard_columns(si, stripes, fut.result(), n)
        # keep the submit/commit pair convergent: operators read
        # in-flight encodes as submits - commits
        self.perf.inc("ec_dispatch_commits")
        self._ec_recover_store(state, data, chunks)

    def _ec_recover_store(self, state: dict, data: bytes,
                          chunks: dict) -> None:
        """Store (self) or push (peer) the recovered shard."""
        pgid = state["pgid"]
        oid = state["oid"]
        need = state["need"]
        dest_shard = state["dest_shard"]
        cid = f"{pgid[0]}.{pgid[1]}"
        shard_oid = f"{oid}:{dest_shard}"
        from ceph_tpu.osd.ec_util import HashInfo
        attrs = {"size": str(len(data)).encode(), "_v": enc_version(need),
                 "hinfo": HashInfo.compute(chunks[dest_shard])}
        pg = self.pgs.get(pgid)
        if state["dest_osd"] == self.osd_id:
            t = (Transaction().truncate(cid, shard_oid, 0)
                 .write(cid, shard_oid, 0, chunks[dest_shard]))
            for name, val in attrs.items():
                t.setattr(cid, shard_oid, name, val)
            self.store.apply_transaction(t)
            if pg is not None:
                self._object_recovered(pg, oid, need)
            return
        con = self._osd_con(state["dest_osd"])
        if con:
            con.send_message(MOSDPGPush(
                pgid=pgid, oid=shard_oid, data=chunks[dest_shard],
                attrs=attrs))
        self._peer_recovered(pg, state["dest_osd"], shard_oid)

    # -- snapshots (PrimaryLogPG snap resolution) -----------------------------

    def _resolve_snap(self, cid: str, oid: str, snapid: int) -> str:
        """Object name serving a read as-of pool snapshot `snapid`: the
        head if unchanged since, else the oldest clone whose covered
        interval (from_seq, clone_seq] contains snapid."""
        head_sc = self._getattr_safe(cid, oid, "snapc")
        # "snapc" records the pool snap_seq at the last write: the head
        # is the snap-s state only if last written BEFORE snap s existed
        if self.store.exists(cid, oid) and int(head_sc or b"0") < snapid:
            return oid
        clones = []
        for o in self.store.list_objects(cid):
            if o.startswith(oid + CLONE_SEP):
                try:
                    clones.append((int(o.rsplit(CLONE_SEP, 1)[1]), o))
                except ValueError:
                    continue
        for seq, name in sorted(clones):
            if seq >= snapid:
                frm = int(self._getattr_safe(cid, name, "from_seq")
                          or b"0")
                if frm < snapid:
                    return name
                break   # object did not exist at that snap
        raise KeyError(f"{oid} has no state at snap {snapid}")

    # -- watch / notify (PrimaryLogPG watch paths) ----------------------------

    def _start_notify(self, msg: MOSDOp, op) -> None:
        with self._lock:
            watchers = dict(self._watchers.get((msg.pgid, msg.oid), {}))
            watchers.pop(msg.client_id, None)   # not the notifier itself
            if not watchers:
                pass
            else:
                self._notify_seq += 1
                nid = self._notify_seq
                self._notifies[nid] = {
                    "msg": msg, "waiting": set(watchers),
                    "started": time.time()}
        if not watchers:
            self._op_send_reply(msg, MOSDOpReply(
                tid=msg.tid, result=0, epoch=self.osdmap.epoch))
            return
        note = MWatchNotify(pool=msg.pgid[0], oid=msg.oid,
                            notify_id=nid, payload=op.data)
        for cid_, con in watchers.items():
            con.send_message(note)

    def _handle_notify_ack(self, msg: MWatchNotifyAck) -> None:
        done = None
        with self._lock:
            st = self._notifies.get(msg.notify_id)
            if st is None:
                return
            # the ack connection's peer is the watcher; match by any —
            # acks are per notify_id, one per watcher
            if st["waiting"]:
                st["waiting"].pop()
            if not st["waiting"]:
                done = self._notifies.pop(msg.notify_id)
        if done is not None:
            m = done["msg"]
            self._op_send_reply(m, MOSDOpReply(
                tid=m.tid, result=0, epoch=self.osdmap.epoch))

    # -- scrub (PG::scrub / chunky_scrub: batched digests, verified ----------
    # repair, background QoS lane) --------------------------------------------

    #: wait budget for one coalesced digest batch (covers the engine's
    #: whole retry/fallback ladder; the scalar loop backstops a miss)
    SCRUB_DIGEST_TIMEOUT = 30.0

    #: _scrub_stats key -> per-daemon perf counter
    _SCRUB_PERF = {"objects_scrubbed": "scrub_objects",
                   "inconsistent": "scrub_inconsistent",
                   "repaired": "scrub_repaired",
                   "repair_unverified": "scrub_repair_unverified",
                   "digest_batches": "scrub_digest_batches",
                   "missing_peer_scrubs": "scrub_missing_peers"}

    def _scrub_note(self, **counts) -> None:
        """Fold counts into this daemon's scrub accounting, the
        process-global telemetry sink (the thrasher's cluster-wide
        scrub-storm gate), and the registered perf counters."""
        from ceph_tpu.ops import telemetry
        sink = telemetry.scrub_stats()
        with self._scrub_lock:
            for k, v in counts.items():
                if v:
                    self._scrub_stats[k] = self._scrub_stats.get(k, 0) + v
        for k, v in counts.items():
            if not v:
                continue
            sink.inc(k, int(v))
            c = self._SCRUB_PERF.get(k)
            if c:
                self.perf.inc(c, int(v))

    def _scrub_digest_rows(self, blobs: list) -> "np.ndarray | None":
        """(len(blobs), 2) uint32 digests via ONE coalesced device
        batch on the scrub_digest channel, or None — the caller runs
        the bit-exact scalar loop (knob off, empty batch, rows wider
        than the kernel cap, or a permanent engine error; transient
        device faults never reach here — the engine's retry ladder and
        host oracle absorb them)."""
        if not blobs or not bool(self.ctx.conf.get("osd_scrub_batched")):
            return None
        from ceph_tpu.ops import checksum_kernel as ck
        if max(len(b) for b in blobs) > ck.MAX_WIDTH:
            return None
        try:
            from ceph_tpu.ops.dispatch import submit_scrub_digest
            fut = submit_scrub_digest(self.ctx.decode_dispatch_engine(),
                                      blobs)
            # analysis: allow[blocking] -- scrub chunks are background ops; the future carries host numpy once delivered
            digs = np.asarray(fut.result(
                timeout=self.SCRUB_DIGEST_TIMEOUT))
        except Exception as e:
            dout("osd", 1, "osd.%d scrub digest batch failed, scalar "
                 "fallback: %r", self.osd_id, e)
            self._scrub_note(scalar_fallbacks=1)
            return None
        self._scrub_note(digest_batches=1, digest_objects=len(blobs))
        return digs

    def _scrub_read_rows(self, cid: str, oids: list | None = None,
                         names: list | None = None) -> tuple:
        """Bulk-read one scrub chunk's objects: returns (sentinels,
        rows) where sentinels maps oids whose store read failed
        checksum to SCRUB_CORRUPT (bluestore verifies every block on
        read; the sentinel is wire-compatible with the triple and
        diverges from every healthy map entry, so the compare pass
        repairs this copy from a clean peer), rows are
        (oid, data, omap_blob, hinfo) awaiting digests, and the third
        dict maps every seen oid to its raw "_v" blob (the
        version-skew guard the compare pass needs)."""
        out: dict = {}
        rows: list = []
        vers: dict = {}
        if names is None:
            # callers that already listed the collection (the chunk
            # chain) pass their slice straight in — re-listing the
            # whole collection per 16-name chunk is O(N^2/step)
            try:
                names = self.store.list_objects(cid)
            except KeyError:
                return out, rows, vers
            if oids is not None:
                sel = set(oids)
                names = [o for o in names if o in sel]
        pool = None
        try:
            pool = self.osdmap.pools.get(int(cid.split(".", 1)[0]))
        except ValueError:
            pass
        ec = pool is not None and pool.is_erasure()
        for oid in names:
            if oid.startswith(PG.PGMETA):
                continue
            try:
                data = self.store.read(cid, oid)
                omap = self.store.omap_get(cid, oid)
            except KeyError:
                continue
            except IOError:
                out[oid] = SCRUB_CORRUPT
                vers[oid] = self._getattr_safe(cid, oid, "_v") or b""
                continue
            oblob = repr(sorted(omap.items())).encode()
            hinfo = (self._getattr_safe(cid, oid, "hinfo")
                     if ec and ":" in oid else None)
            vers[oid] = self._getattr_safe(cid, oid, "_v") or b""
            rows.append((oid, data, oblob, hinfo))
        return out, rows, vers

    @staticmethod
    def _scrub_fill(out: dict, rows: list, digs) -> dict:
        """Fill the (size, data_crc, omap_crc) triples from a digest
        matrix (crc32 column; None = the seed's scalar shard_crc
        loop, bit-exact either way) and apply the EC hinfo sweep: a
        shard whose bytes diverge from their write-time checksum is
        this copy's SCRUB_CORRUPT — the detector the primary's shard
        sweep repairs from."""
        from ceph_tpu.osd.ec_util import shard_crc
        n = len(rows)
        for i, (oid, data, oblob, hinfo) in enumerate(rows):
            if digs is not None:
                dcrc, ocrc = int(digs[i, 0]), int(digs[n + i, 0])
            else:
                dcrc, ocrc = shard_crc(data), shard_crc(oblob)
            if hinfo and dcrc.to_bytes(4, "little") != hinfo:
                out[oid] = SCRUB_CORRUPT
                continue
            out[oid] = (len(data), dcrc, ocrc)
        return out

    def _scrub_map(self, cid: str,
                   oids: list | None = None) -> tuple[dict, dict]:
        """({oid: (size, data_crc, omap_crc)}, {oid: "_v" blob}) for
        every object in the collection (pgmeta excluded), or just
        ``oids`` (repair verification).

        Every object payload and omap blob stacks into ONE coalesced
        digest batch (the scrub_digest dispatch channel) instead of
        the seed's per-object host loop; the scalar ``shard_crc``
        loop remains the bit-exact fallback.  This is the synchronous
        build (direct callers, opwq off); the lane path uses the
        submit-and-continue variant (_scrub_digest_async) so shard
        workers never park on device latency."""
        out, rows, vers = self._scrub_read_rows(cid, oids=oids)
        digs = self._scrub_digest_rows(
            [r[1] for r in rows] + [r[2] for r in rows])
        return self._scrub_fill(out, rows, digs), vers

    def _scrub_digest_async(self, rows: list, finish) -> None:
        """Submit one chunk's digest batch and continue in the
        engine's completion callback — the shard worker returns as
        soon as the batch is queued, so scrub's worker quantum is
        reads + submit, never device turnaround (the
        submit-and-continue rule every async channel here follows).
        ``finish(digs_or_None)`` runs on the engine's completion
        thread (None = take the scalar loop)."""
        blobs = [r[1] for r in rows] + [r[2] for r in rows]
        if not blobs or not bool(
                self.ctx.conf.get("osd_scrub_batched")):
            finish(None)
            return
        from ceph_tpu.ops import checksum_kernel as ck
        if max(len(b) for b in blobs) > ck.MAX_WIDTH:
            finish(None)
            return
        try:
            from ceph_tpu.ops.dispatch import submit_scrub_digest
            fut = submit_scrub_digest(
                self.ctx.decode_dispatch_engine(), blobs)
        except Exception as e:
            dout("osd", 1, "osd.%d scrub digest submit failed, "
                 "scalar fallback: %r", self.osd_id, e)
            self._scrub_note(scalar_fallbacks=1)
            finish(None)
            return

        def cb(f) -> None:
            if f.exception() is not None:
                self._scrub_note(scalar_fallbacks=1)
                finish(None)
                return
            self._scrub_note(digest_batches=1,
                             digest_objects=len(blobs))
            # analysis: allow[blocking] -- delivered engine futures carry host numpy; asarray here is a view, not d2h
            finish(np.asarray(f.result()))

        fut.add_done_callback(cb)

    def _scrub_map_lane(self, cid: str, pgid, done,
                        oids: list | None = None,
                        cancelled=None) -> None:
        """Build a scrub map through the background dmclock lane in
        CHUNKS of osd_scrub_chunk_objects store objects per op (the
        reference's chunky scrub): each lane op is a small-op-sized
        service quantum, so excess-capacity scrub service never parks
        a shard worker behind a whole-PG bulk read + digest while a
        tenant op waits.  Every chunk carries the cost-scaled
        background tag (osd_scrub_cost).  ``done(map)`` fires on a
        shard worker after the last chunk; with the op queue off the
        map builds synchronously."""
        if self.opwq is None:
            done(self._scrub_map(cid, oids=oids))
            return
        try:
            names = [o for o in self.store.list_objects(cid)
                     if not o.startswith(PG.PGMETA)]
        except KeyError:
            names = []
        if oids is not None:
            sel = set(oids)
            names = [o for o in names if o in sel]
        if not names:
            done(({}, {}))
            return
        step = max(1, int(self.ctx.conf.get("osd_scrub_chunk_objects")))
        cost = int(self.ctx.conf.get("osd_scrub_cost"))
        acc: dict = {}
        acc_vers: dict = {}
        state = {"i": 0}

        def chunk(_msg) -> None:
            # worker quantum: bulk reads + digest submit only; the
            # digest completes (and the chain advances) on the
            # engine's completion thread
            if cancelled is not None and cancelled():
                return     # caller gave up (jam fallback): stop here
            i = state["i"]
            state["i"] = i + step
            out, rows, vers = self._scrub_read_rows(
                cid, names=names[i:i + step])
            acc_vers.update(vers)

            def finish(digs) -> None:
                try:
                    acc.update(self._scrub_fill(out, rows, digs))
                except Exception as e:  # never strand the sweep
                    dout("osd", 1, "osd.%d scrub chunk fill failed: "
                         "%r", self.osd_id, e)
                if cancelled is not None and cancelled():
                    return
                if state["i"] >= len(names) or self._stop:
                    # shutdown mid-chain: deliver what we have — the
                    # stopped op queue would never serve another
                    # chunk, and the waiter must not park out its
                    # whole timeout against a dead daemon
                    done((acc, acc_vers))
                    return
                # osd_scrub_sleep as a DELAYED REQUEUE (the mclock-era
                # reference's scrub_requeue_callback): the chain
                # advances from a timer thread even unpaced, because
                # _enqueue_op can block on the op-byte throttle and
                # pacing must park neither a shard worker nor the
                # engine completion thread this runs on
                t = threading.Timer(
                    max(0.0, float(self.ctx.conf.get(
                        "osd_scrub_sleep"))),
                    lambda: self._enqueue_op(
                        BACKGROUND_BEST_EFFORT, pgid, chunk,
                        _ScrubChunk(pgid, cost=cost)))
                t.daemon = True
                t.start()

            self._scrub_digest_async(rows, finish)

        self._enqueue_op(BACKGROUND_BEST_EFFORT, pgid, chunk,
                         _ScrubChunk(pgid, cost=cost))

    def _handle_scrub(self, msg: MOSDScrub) -> None:
        """Replica scrub-map request: the map builds through THIS
        daemon's background lane in chunks, and the reply goes out
        when the last chunk lands — a scrub storm's replica half is
        arbitrated, cost-tagged background work end to end."""
        cid = f"{msg.pgid[0]}.{msg.pgid[1]}"
        con = msg.connection or self._osd_con(msg.from_osd)
        if con is None:
            return

        def reply(mv) -> None:
            m, vers = mv
            con.send_message(MOSDScrubReply(
                pgid=msg.pgid, scrub_id=msg.scrub_id,
                from_osd=self.osd_id, scrub_map=m, versions=vers))

        self._scrub_map_lane(cid, msg.pgid, reply,
                             oids=getattr(msg, "oids", None))

    def _handle_scrub_reply(self, msg: MOSDScrubReply) -> None:
        with self._lock:
            st = self._scrubs.get(msg.scrub_id)
            if st is None:
                return
            st["maps"][msg.from_osd] = msg.scrub_map
            st["vers"][msg.from_osd] = getattr(msg, "versions", {})
            if set(st["maps"]) >= st["expect"]:
                st["event"].set()

    def _scrub_gather(self, pgid, peers: list, timeout: float,
                      oids: list | None = None) -> tuple[dict, dict]:
        """One replica scrub-map gather round: ask ``peers``, wait up
        to ``timeout``, return ({osd: map}, {osd: versions}) for
        whatever arrived (the caller owns retry and missing-peer
        accounting)."""
        if not peers:
            return {}, {}
        with self._lock:
            self._scrub_seq += 1
            sid = self._scrub_seq
            st = {"maps": {}, "vers": {}, "expect": set(peers),
                  "event": threading.Event()}
            self._scrubs[sid] = st
        for o in peers:
            con = self._osd_con(o)
            if con:
                con.send_message(MOSDScrub(pgid=pgid, scrub_id=sid,
                                           from_osd=self.osd_id,
                                           oids=oids))
        st["event"].wait(timeout)
        with self._lock:
            self._scrubs.pop(sid, None)
            return dict(st["maps"]), dict(st["vers"])

    def scrub_pg(self, pgid: tuple[int, int],
                 timeout: float | None = None) -> dict:
        """Primary-driven deep scrub: gather per-replica object maps
        (each built as one batched digest call), compare the packed
        triples vectorized, repair divergent copies (authority = the
        most common healthy triple, the primary pushing when it
        agrees and repulling when it is the outlier; EC shards rebuild
        through the batched decode path), and VERIFY every repair by
        re-fetching the repaired copy's digest before counting it.

        Report keys: ``checked``, ``inconsistent``, ``repaired``
        (verified only), ``repair_unverified``, ``missing_peers``
        (replicas that never answered — recorded, never silently
        compared as absent), ``clean`` (no inconsistency AND every
        peer reported; a PG with a missing peer map is never clean)."""
        pg = self.pgs.get(pgid)
        if pg is None or pg.primary != self.osd_id:
            raise ValueError(f"not primary for {pgid}")
        if timeout is None:
            timeout = float(self.ctx.conf.get("osd_scrub_chunk_timeout"))
        t0 = time.monotonic()
        cid = self._pg_cid(pgid)
        pool = self.osdmap.pools.get(pgid[0])
        peers = [o for o in pg.up
                 if o != self.osd_id and o != CEPH_NOSD]
        # peers the map already marks down go straight to
        # missing_peers instead of being waited out
        live = [o for o in peers if self.osdmap.is_up(o)]
        # start the primary's own chunked lane build FIRST (it only
        # enqueues), then gather — the replicas build their maps
        # concurrently with ours instead of serializing the two
        # slowest phases and eating into their own gather timeout
        own_box: dict = {"dead": False}
        own_ev = threading.Event()

        def _own_done(mv) -> None:
            own_box["map"] = mv
            own_ev.set()

        self._scrub_map_lane(cid, pgid, _own_done,
                             cancelled=lambda: own_box["dead"])
        got, gvers = self._scrub_gather(pgid, live, timeout)
        if own_ev.wait(4.0 * float(self.ctx.conf.get(
                "osd_scrub_chunk_timeout"))) and "map" in own_box:
            own_map, own_vers = own_box["map"]
        else:
            # lane jammed: cancel the chain and build directly rather
            # than wedge the sweep
            own_box["dead"] = True
            own_map, own_vers = self._scrub_map(cid)
        maps = {self.osd_id: own_map}
        vers = {self.osd_id: own_vers}
        maps.update(got)
        vers.update(gvers)
        missing = set(peers) - set(maps)
        retry = sorted(missing & set(live))
        if retry:
            # a silent replica is retried ONCE with backoff — the seed
            # dropped it from maps and compared its objects as if the
            # copy never existed
            self._scrub_note(missing_peer_retries=1)
            time.sleep(float(self.ctx.conf.get(
                "osd_scrub_retry_backoff_ms")) / 1e3)
            got, gvers = self._scrub_gather(pgid, retry, timeout)
            maps.update(got)
            vers.update(gvers)
            missing = set(peers) - set(maps)
        report = {"checked": 0, "inconsistent": [], "repaired": [],
                  "repair_unverified": [],
                  "missing_peers": sorted(missing), "clean": False}
        if pool is not None and pool.is_erasure():
            pending = self._scrub_compare_ec(pg, pgid, maps, vers,
                                             report)
        else:
            pending = self._scrub_compare_replicated(
                pg, pgid, cid, maps, vers, report)
        self._scrub_verify_repairs(pgid, cid, pending, report)
        # never report a PG clean when a peer map is missing
        report["clean"] = (not report["inconsistent"] and not missing
                           and not report["repair_unverified"])
        self._scrub_note(
            pgs_scrubbed=1, objects_scrubbed=report["checked"],
            inconsistent=len(report["inconsistent"]),
            repaired=len(report["repaired"]),
            repair_unverified=len(report["repair_unverified"]),
            missing_peer_scrubs=1 if missing else 0)
        self.perf.tinc("scrub_chunk_latency", time.monotonic() - t0)
        return report

    def _scrub_compare_replicated(self, pg: PG, pgid, cid: str,
                                  maps: dict, vers: dict,
                                  report: dict) -> list:
        """Replicated compare, vectorized: the per-osd maps pack into
        (oid x responder) size/crc/presence tables and one numpy pass
        finds the divergent rows — the seed walked a python dict per
        oid.  Authority semantics unchanged: the most common HEALTHY
        triple wins (a checksum-failed copy can never be
        authoritative, even as a majority); the primary pushes its
        copy when it agrees, repulls from a healthy peer when it is
        the outlier.  Returns the tentative repairs [(oid, osd, want)]
        for the verification pass."""
        all_oids = sorted({o for m in maps.values() for o in m})
        report["checked"] += len(all_oids)
        if not all_oids:
            return []
        osds = sorted(maps)
        rows, n = len(all_oids), len(osds)
        sizes = np.zeros((rows, n), dtype=np.uint64)
        dcrc = np.zeros((rows, n), dtype=np.uint64)
        ocrc = np.zeros((rows, n), dtype=np.uint64)
        present = np.zeros((rows, n), dtype=bool)
        idx = {oid: i for i, oid in enumerate(all_oids)}
        for j, osd in enumerate(osds):
            for oid, val in maps[osd].items():
                i = idx[oid]
                present[i, j] = True
                sizes[i, j], dcrc[i, j], ocrc[i, j] = val
        p = osds.index(self.osd_id)
        same = (present == present[:, p:p + 1]) & (
            ~present | ((sizes == sizes[:, p:p + 1])
                        & (dcrc == dcrc[:, p:p + 1])
                        & (ocrc == ocrc[:, p:p + 1])))
        pending = []
        for i in np.nonzero(~same.all(axis=1))[0]:
            oid = all_oids[int(i)]
            if not self._scrub_settled(pg, oid, maps, vers, osds):
                # version-skewed divergence: an in-flight write,
                # delete, or recovery — the replication machinery owns
                # it, and a scrub "repair" here would push a STALE
                # copy over an acked newer write (or mark the
                # primary's own newer copy missing).  Only
                # SAME-version divergence is corruption.
                continue
            report["inconsistent"].append(oid)
            vals = {osd: maps[osd].get(oid) for osd in osds}
            want = vals.get(self.osd_id)
            healthy = {osd: val for osd, val in vals.items()
                       if val is not None and val != SCRUB_CORRUPT}
            hcounts: dict = {}
            for val in healthy.values():
                hcounts[val] = hcounts.get(val, 0) + 1
            hmaj = max(hcounts,
                       key=lambda v: (hcounts[v], v == want)) \
                if hcounts else None
            if want == hmaj and want is not None:
                # the primary agrees with the healthy majority: push
                # its copy over every divergent (or corrupt) replica
                try:
                    data = self.store.read(cid, oid)
                    omap = self.store.omap_get(cid, oid)
                except (KeyError, IOError):
                    continue
                attrs = {}
                for name in ("_v", "snapc", "from_seq"):
                    v = self._getattr_safe(cid, oid, name)
                    if v:
                        attrs[name] = v
                for osd, val in vals.items():
                    if osd == self.osd_id or val == want:
                        continue
                    con = self._osd_con(osd)
                    if con:
                        con.send_message(MOSDPGPush(
                            pgid=pgid, oid=oid, data=data, omap=omap,
                            attrs=attrs))
                        pending.append((oid, osd, want))
            else:
                # the primary is the outlier (divergent or corrupt):
                # repull from a healthy peer holding the
                # healthy-majority value
                good = next((osd for osd, val in healthy.items()
                             if val == hmaj and osd != self.osd_id),
                            None)
                ent = pg.log.index.get(oid)
                if good is not None and ent is not None:
                    with self._lock:
                        pg.missing[oid] = MissingItem(need=ent.version)
                        pg.state = STATE_RECOVERING
                    self._pull_object(pg, oid, good)
                    pending.append((oid, self.osd_id, hmaj))
        return pending

    def _scrub_settled(self, pg: PG, oid: str, maps: dict,
                       vers: dict, osds) -> bool:
        """True when every PRESENT copy of ``oid`` reports the version
        the pg log currently heads for it (legacy copies without a
        "_v" blob count as settled — there is nothing to judge), and
        the object is live in the log.  Scrub maps are gathered
        seconds apart under load: only same-version divergence is
        corruption; version skew means a write/delete/recovery is in
        flight and the next sweep will see it converged."""
        ent = pg.log.index.get(oid)
        if ent is not None and ent.is_delete():
            return False        # delete in flight
        if ent is None:
            # trimmed history: no logged head to compare against —
            # settled iff every present copy agrees on ITS version
            # (same-version divergence on a cold object is exactly
            # the corruption scrub exists for)
            vs = {(vers.get(osd) or {}).get(oid) for osd in osds
                  if maps[osd].get(oid) is not None}
            vs.discard(None)
            vs.discard(b"")
            return len(vs) <= 1
        want = enc_version(ent.version)
        for osd in osds:
            if maps[osd].get(oid) is None:
                continue        # absence is handled by the repair path
            v = (vers.get(osd) or {}).get(oid)
            if v and v != want:
                return False
        return True

    def _scrub_compare_ec(self, pg: PG, pgid, maps: dict, vers: dict,
                          report: dict) -> list:
        """EC PGs: shards differ by construction, so cross-copy
        compare is meaningless — integrity is (a) each owner's hinfo
        sweep, which surfaces a shard whose bytes diverge from their
        write-time checksum as SCRUB_CORRUPT in that owner's own map,
        and (b) an existence sweep (a shard absent from its responding
        owner while the object lives in the pg log).  Bad shards
        rebuild through the batched decode path (_recover_ec_object ->
        submit_decode_chunks) and verify like every repair — the
        seed's EC branch only reported, never repaired."""
        up = list(pg.up)
        logicals = sorted({soid.rsplit(":", 1)[0]
                           for m in maps.values() for soid in m
                           if ":" in soid})
        pending = []
        for logical in logicals:
            report["checked"] += 1
            ent = pg.log.index.get(logical)
            live = ent is not None and not ent.is_delete()
            if live:
                # version-skew guard (see _scrub_settled): any present
                # shard off the logged head means the write/recovery
                # is still propagating — not corruption
                want = enc_version(ent.version)
                skewed = False
                for owner in up:
                    if owner == CEPH_NOSD or owner not in maps:
                        continue
                    for sh in range(len(up)):
                        v = (vers.get(owner) or {}).get(
                            f"{logical}:{sh}")
                        if v and v != want:
                            skewed = True
                if skewed:
                    continue
            for s, owner in enumerate(up):
                if owner == CEPH_NOSD or owner not in maps:
                    continue   # down/silent peer: missing_peers owns it
                soid = f"{logical}:{s}"
                val = maps[owner].get(soid)
                if not (val == SCRUB_CORRUPT or (val is None and live)):
                    continue
                report["inconsistent"].append(soid)
                if live:
                    self._recover_ec_object(pg, logical,
                                            dest_osd=owner,
                                            dest_shard=s)
                    # want=None: verified by ANY healthy follow-up
                    # triple — the rebuilt chunk's digest is not
                    # knowable on the primary
                    pending.append((soid, owner, None))
        return pending

    def _scrub_verify_repairs(self, pgid, cid: str, pending: list,
                              report: dict) -> None:
        """The fire-and-forget fix: a repair only counts once the
        repaired copy's digest is re-fetched (one follow-up scrub of
        JUST the repaired oids) and matches the authority triple
        (``want``; None accepts any healthy value — EC shard
        rebuilds).  Pushes and recovery pulls apply asynchronously, so
        this polls until osd_scrub_verify_timeout; what never verifies
        lands in repair_unverified, never silently in repaired."""
        if not pending:
            return
        if not bool(self.ctx.conf.get("osd_scrub_verify_repairs")):
            report["repaired"].extend(
                (oid, osd) for oid, osd, _ in pending)
            return
        left = {(oid, osd): want for oid, osd, want in pending}
        deadline = time.monotonic() + float(
            self.ctx.conf.get("osd_scrub_verify_timeout"))
        while left:
            by_osd: dict[int, list] = {}
            for (oid, osd) in left:
                by_osd.setdefault(osd, []).append(oid)
            gto = max(0.5, min(
                float(self.ctx.conf.get("osd_scrub_chunk_timeout")),
                deadline - time.monotonic()))
            for osd, oids in sorted(by_osd.items()):
                if osd == self.osd_id:
                    m, _v = self._scrub_map(cid, oids=sorted(oids))
                else:
                    m = self._scrub_gather(
                        pgid, [osd], timeout=gto,
                        oids=sorted(oids))[0].get(osd, {})
                for oid in sorted(oids):
                    want = left[(oid, osd)]
                    got = m.get(oid)
                    if (got is not None and got != SCRUB_CORRUPT
                            and (want is None or got == want)):
                        report["repaired"].append((oid, osd))
                        del left[(oid, osd)]
            if not left or time.monotonic() >= deadline:
                break
            time.sleep(0.2)
        report["repair_unverified"].extend(sorted(left))

    def scrub_all_pgs(self, timeout: float = 300.0) -> dict:
        """One full deep-scrub sweep of every PG this OSD leads, run
        on the CALLING thread (the continuous driver's own thread).
        Every piece of scrub WORK — the primary's map build and each
        replica's — is an op served through the background_best_effort
        dmclock lane (visible in dump_qos_stats), so a continuous
        full-cluster deep scrub competes only for the excess and
        cannot starve tenant reservations; the network waits (replica
        gathers, repair verification) park here and never hold a
        shard worker.  Returns the aggregate report."""
        with self._lock:
            pgids = [pgid for pgid, pg in self.pgs.items()
                     if pg.primary == self.osd_id]
        agg = {"pgs": 0, "checked": 0, "inconsistent": [],
               "repaired": [], "repair_unverified": [],
               "missing_peers": [], "clean": True}
        t0 = time.monotonic()
        deadline = t0 + timeout
        sleep = float(self.ctx.conf.get("osd_scrub_sleep"))
        for i, pgid in enumerate(pgids):
            if time.monotonic() >= deadline or self._stop:
                break
            if i and sleep > 0:
                # osd_scrub_sleep between PGs too: a sweep's fixed
                # per-PG cost (gather messages, digest dispatch,
                # compare) is python-side work the serving threads
                # contend with — pacing it is what makes "continuous"
                # scrub background in CPU terms, not just queue terms
                time.sleep(sleep)
            try:
                rep = self.scrub_pg(pgid)
            except (ValueError, KeyError):
                continue    # primaryship moved mid-sweep (map churn)
            except Exception as e:
                dout("osd", 1, "osd.%d scrub chunk %s failed: %r",
                     self.osd_id, pgid, e)
                continue
            agg["pgs"] += 1
            agg["checked"] += rep["checked"]
            for k in ("inconsistent", "repaired", "repair_unverified",
                      "missing_peers"):
                agg[k].extend(rep[k])
            agg["clean"] = agg["clean"] and rep["clean"]
        summary = {
            "pgs": agg["pgs"], "checked": agg["checked"],
            "inconsistent": len(agg["inconsistent"]),
            "repaired": len(agg["repaired"]),
            "repair_unverified": len(agg["repair_unverified"]),
            "missing_peers": sorted(set(agg["missing_peers"])),
            "clean": agg["clean"],
            "seconds": round(time.monotonic() - t0, 3)}
        with self._scrub_lock:
            self._scrub_stats["sweeps"] += 1
            self._scrub_stats["last_sweep"] = summary
        from ceph_tpu.ops import telemetry
        telemetry.scrub_stats().inc("sweeps", 1)
        return agg

    def _maybe_auto_scrub(self, now: float) -> None:
        """The continuous background-integrity driver: every
        osd_scrub_auto_interval seconds one full scrub_all_pgs sweep
        of the PGs this osd leads, on its own thread (a sweep blocks
        on replica maps; the tick timer must not)."""
        iv = float(self.ctx.conf.get("osd_scrub_auto_interval"))
        if (iv <= 0 or self._scrub_sweeping or self._stop
                or now - self._scrub_auto_last < iv):
            return
        self._scrub_sweeping = True
        threading.Thread(target=self._scrub_auto_sweep,
                         name=f"osd.{self.osd_id}-scrub",
                         daemon=True).start()

    def _scrub_auto_sweep(self) -> None:
        try:
            self.scrub_all_pgs()
        except Exception as e:
            dout("osd", 1, "osd.%d auto scrub sweep failed: %r",
                 self.osd_id, e)
        finally:
            self._scrub_auto_last = time.time()
            self._scrub_sweeping = False

    def _dump_scrub_stats(self) -> dict:
        """Admin ``dump_scrub_stats``: the daemon's background-
        integrity accounting plus the dmclock lane its scrub ops
        ride."""
        with self._scrub_lock:
            out = dict(self._scrub_stats)
            out["last_sweep"] = dict(self._scrub_stats["last_sweep"])
        out["qos_class"] = BACKGROUND_BEST_EFFORT
        out["batched"] = bool(self.ctx.conf.get("osd_scrub_batched"))
        out["auto_interval"] = float(
            self.ctx.conf.get("osd_scrub_auto_interval"))
        if self.opwq is not None:
            out["background_lane"] = self.opwq.dump_qos()[
                "classes"].get(BACKGROUND_BEST_EFFORT)
        return out

    def _scrub_digest_report(self) -> dict:
        """Compact per-daemon scrub counters for the MMgrReport tail
        (mgr scrub_feed -> ceph_scrub_* prometheus families)."""
        with self._scrub_lock:
            return {k: v for k, v in self._scrub_stats.items()
                    if k != "last_sweep"}

    # -- peers ----------------------------------------------------------------

    def set_osd_addr(self, osd: int, addr: str) -> None:
        self._osd_addr_cache[osd] = addr

    def _osd_con(self, osd: int):
        addr = None
        if 0 <= osd < len(self.osdmap.osd_addrs):
            addr = self.osdmap.osd_addrs[osd] or None
        if addr is None:
            addr = self._osd_addr_cache.get(osd)
        if addr is None:
            return None
        return self.msgr.connect_to(addr, EntityName("osd", osd))


def _encode_omap(d: dict) -> bytes:
    e = Encoder()
    e.map(d, lambda e2, k2: e2.str(k2), lambda e2, v: e2.bytes(v))
    return e.tobytes()


def _decode_omap(data: bytes) -> dict:
    return Decoder(data).map(lambda d: d.str(), lambda d: d.bytes())
